"""Graceful drain (SIGTERM preemption contract) and cut-level retention GC.

The graceful-preemption contract (tpumetrics/runtime/drain.py): once a drain
begins, intake refuses typed, every already-submitted batch reaches the
state, ONE final cut covers exactly that position, and a restore from the
drain cut is bit-identical — a polite preemption loses nothing.  Retention
(tpumetrics/resilience/elastic.py::gc_cuts): last K complete cuts survive,
superseded partial cuts and stale rank dirs are collected, in-progress
writes never are.
"""

from __future__ import annotations

import os
import signal
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from tpumetrics.classification import MulticlassAccuracy
from tpumetrics.resilience.elastic import (
    DistributedSnapshotManager,
    cut_digest,
    gc_cuts,
    scan_cuts,
)
from tpumetrics.runtime import (
    DrainingError,
    EvaluationService,
    StreamingEvaluator,
    install_preemption_handler,
)
from tpumetrics.runtime.drain import PreemptionInterrupt


def _acc():
    return MulticlassAccuracy(num_classes=5, average="micro", validate_args=False)


def _stream(rng, n, rows=6):
    out = []
    for _ in range(n):
        out.append(
            (
                jnp.asarray(rng.standard_normal((rows, 5)).astype(np.float32)),
                jnp.asarray(rng.integers(0, 5, rows).astype(np.int32)),
            )
        )
    return out


# ------------------------------------------------------------ graceful drain


class TestGracefulDrain:
    def test_request_drain_refuses_submit_typed(self, tmp_path):
        ev = StreamingEvaluator(_acc(), buckets=8, snapshot_dir=str(tmp_path))
        batches = _stream(np.random.default_rng(0), 3)
        for b in batches:
            ev.submit(*b)
        ev.request_drain()
        assert ev.draining
        with pytest.raises(DrainingError, match="draining"):
            ev.submit(*batches[0])
        # already-submitted batches still apply
        report = ev.drain()
        assert report.batches == 3

    def test_drain_final_cut_restore_bit_identical(self, tmp_path):
        rng = np.random.default_rng(1)
        batches = _stream(rng, 7)
        ev = StreamingEvaluator(_acc(), buckets=8, snapshot_dir=str(tmp_path))
        for b in batches:
            ev.submit(*b)
        report = ev.drain()
        assert report.batches == 7 and report.cut_step == 7
        assert report.cut_path and os.path.isfile(report.cut_path)

        # the drain cut covers EVERYTHING submitted: a restored evaluator
        # computes bit-identically to an uninterrupted one
        ref = _acc()
        for b in batches:
            ref.update(*b)
        ev2 = StreamingEvaluator(_acc(), buckets=8, snapshot_dir=str(tmp_path))
        assert ev2.restore_latest() == 7
        assert float(ev2.compute()) == float(ref.compute())
        ev2.close()

    def test_drain_is_idempotent(self, tmp_path):
        ev = StreamingEvaluator(_acc(), buckets=8, snapshot_dir=str(tmp_path))
        ev.submit(*_stream(np.random.default_rng(2), 1)[0])
        first = ev.drain()
        assert ev.drain() is first

    def test_sigterm_notify_mode(self, tmp_path):
        ev = StreamingEvaluator(_acc(), buckets=8, snapshot_dir=str(tmp_path))
        for b in _stream(np.random.default_rng(3), 4):
            ev.submit(*b)
        guard = install_preemption_handler(ev, mode="notify")
        try:
            assert not guard.requested
            os.kill(os.getpid(), signal.SIGTERM)
            assert guard.wait(timeout=5.0)
            assert guard.signum == signal.SIGTERM
            # the notice closed intake immediately, before any drain() call
            with pytest.raises(DrainingError):
                ev.submit(jnp.zeros((2, 5)), jnp.zeros((2,), jnp.int32))
            reports = guard.drain_now()
            assert reports[0].batches == 4 and reports[0].cut_step == 4
            assert guard.drain_now() is reports  # idempotent
        finally:
            guard.uninstall()

    def test_sigterm_raise_mode_interrupts_main_thread(self, tmp_path):
        ev = StreamingEvaluator(_acc(), buckets=8, snapshot_dir=str(tmp_path))
        ev.submit(*_stream(np.random.default_rng(4), 1)[0])
        guard = install_preemption_handler(ev, mode="raise")
        try:
            with pytest.raises(PreemptionInterrupt) as err:
                os.kill(os.getpid(), signal.SIGTERM)
                time.sleep(1.0)  # the handler interrupts this wait
            assert err.value.signum == signal.SIGTERM
            # PreemptionInterrupt is a BaseException: except Exception paths
            # cannot swallow the notice
            assert not isinstance(err.value, Exception)
            reports = guard.drain_now()
            assert reports[0].batches == 1
        finally:
            guard.uninstall()

    def test_repeated_sigterm_does_not_abort_the_drain(self, tmp_path):
        """A fleet re-sending SIGTERM during the grace window must not
        interrupt the drain the first signal started: only the FIRST notice
        raises in mode='raise' (regression: the old handler re-raised
        unconditionally, aborting drain_now mid-cut)."""
        ev = StreamingEvaluator(_acc(), buckets=8, snapshot_dir=str(tmp_path))
        ev.submit(*_stream(np.random.default_rng(10), 2)[0])
        guard = install_preemption_handler(ev, mode="raise")
        try:
            with pytest.raises(PreemptionInterrupt):
                os.kill(os.getpid(), signal.SIGTERM)
                time.sleep(1.0)
            # the second signal is swallowed (the first notice is in flight)
            os.kill(os.getpid(), signal.SIGTERM)
            time.sleep(0.2)  # would raise PreemptionInterrupt here if broken
            reports = guard.drain_now()
            assert reports[0].batches == 1 and reports[0].cut_step == 1
        finally:
            guard.uninstall()

    def test_concurrent_drains_serialize_to_one_report(self, tmp_path):
        """drain() is check-then-act on the cached report: two racing
        callers (preemption guard vs app shutdown) must produce ONE drain
        and ONE final cut, not a duplicate barrier entry."""
        from tpumetrics.runtime.snapshot import list_snapshots

        ev = StreamingEvaluator(_acc(), buckets=8, snapshot_dir=str(tmp_path))
        for b in _stream(np.random.default_rng(11), 4):
            ev.submit(*b)
        results = []
        threads = [
            threading.Thread(target=lambda: results.append(ev.drain()))
            for _ in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)
        assert len(results) == 4
        assert all(r is results[0] for r in results)  # one report object
        assert len(list_snapshots(str(tmp_path))) == 1  # one final cut

    def test_drain_latency_survives_close(self, tmp_path):
        """close() releases the per-stream histogram series, so the durable
        drain latency lives in the report and the drain_complete ledger
        event (regression: the histogram observation alone was erased
        before anyone could read it)."""
        from tpumetrics import telemetry

        ev = StreamingEvaluator(_acc(), buckets=8, snapshot_dir=str(tmp_path))
        ev.submit(*_stream(np.random.default_rng(12), 1)[0])
        with telemetry.capture() as led:
            report = ev.drain()
        assert report.drain_ms is not None and report.drain_ms > 0
        assert report.to_dict()["drain_ms"] == report.drain_ms
        events = [r for r in led.records if r.kind == "drain_complete"]
        assert events and events[0].extra["drain_ms"] > 0

    def test_handler_uninstall_restores_previous(self, tmp_path):
        seen = []
        previous = signal.signal(signal.SIGTERM, lambda s, f: seen.append(s))
        try:
            ev = StreamingEvaluator(_acc(), buckets=8)
            guard = install_preemption_handler(ev, mode="notify", final_cut=False)
            guard.uninstall()
            os.kill(os.getpid(), signal.SIGTERM)
            time.sleep(0.1)
            assert seen == [signal.SIGTERM]  # the pre-install handler is back
            assert not guard.requested
            ev.close()
        finally:
            signal.signal(signal.SIGTERM, previous)

    def test_drain_without_snapshots_reports_position_only(self):
        ev = StreamingEvaluator(_acc(), buckets=8)
        for b in _stream(np.random.default_rng(5), 2):
            ev.submit(*b)
        report = ev.drain()
        assert report.batches == 2 and report.cut_path is None


class TestServiceDrain:
    def test_service_drain_final_cut_per_tenant(self, tmp_path):
        rng = np.random.default_rng(6)
        svc = EvaluationService()
        a = svc.register("a", _acc(), buckets=8, snapshot_dir=str(tmp_path / "a"))
        b = svc.register("b", _acc(), buckets=8, snapshot_dir=str(tmp_path / "b"))
        sa, sb = _stream(rng, 3), _stream(rng, 5)
        for batch in sa:
            a.submit(*batch)
        for batch in sb:
            b.submit(*batch)
        svc.request_drain()
        with pytest.raises(DrainingError, match="draining"):
            a.submit(*sa[0])
        with pytest.raises(DrainingError):
            svc.register("late", _acc(), buckets=8)
        report = svc.drain()
        assert report.tenants["a"].batches == 3 and report.tenants["a"].cut_step == 3
        assert report.tenants["b"].batches == 5 and report.tenants["b"].cut_step == 5
        assert report.batches == 8
        assert svc.drain() is report  # idempotent

        # restore tenant b from its drain cut: bit-identical
        ref = _acc()
        for batch in sb:
            ref.update(*batch)
        ev = StreamingEvaluator(_acc(), buckets=8, snapshot_dir=str(tmp_path / "b"))
        assert ev.restore_latest() == 5
        assert float(ev.compute()) == float(ref.compute())
        ev.close()

    def test_service_handler_via_preemption_guard(self, tmp_path):
        svc = EvaluationService()
        t = svc.register("t", _acc(), buckets=8, snapshot_dir=str(tmp_path))
        t.submit(*_stream(np.random.default_rng(7), 1)[0])
        guard = install_preemption_handler(svc, mode="notify")
        try:
            os.kill(os.getpid(), signal.SIGTERM)
            assert guard.wait(timeout=5.0)
            reports = guard.drain_now()
            assert reports[0].tenants["t"].batches == 1
        finally:
            guard.uninstall()

    def test_blocked_submitter_woken_by_drain(self):
        svc = EvaluationService()
        handle = svc.register("t", _acc(), buckets=8, max_queue=1, backpressure="block")
        batch = _stream(np.random.default_rng(8), 1)[0]
        # fill the queue while the worker is busy enough that a second
        # submit blocks on space at least sometimes; the drain must wake it
        # with a typed error rather than leave it waiting forever
        errors = []

        def pump():
            try:
                for _ in range(50):
                    handle.submit(*batch)
            except DrainingError as err:
                errors.append(err)

        th = threading.Thread(target=pump)
        th.start()
        time.sleep(0.05)
        svc.request_drain()
        th.join(timeout=10.0)
        assert not th.is_alive()
        svc.drain()


# ------------------------------------------------------------- retention GC


def _write_cut(root, world, step, ranks=None, keep_cuts=None, config="cfg"):
    digest = cut_digest(step, world, config)
    for r in ranks if ranks is not None else range(world):
        # keep=None isolates CUT-level retention from the per-rank window
        mgr = DistributedSnapshotManager(root, r, world, keep=None, keep_cuts=keep_cuts)
        mgr.save(
            step,
            {"v": jnp.ones(2) * step},
            meta={
                "batches": step, "items": step, "mode": "bucketed",
                "degraded": False, "base_batches": 0, "base_items": 0,
                "elastic": mgr.elastic_meta(step, digest, config),
            },
        )


class TestCutRetention:
    def test_last_k_complete_cuts_survive(self, tmp_path):
        root = str(tmp_path)
        for step in range(1, 6):
            _write_cut(root, 3, step)
        removed = gc_cuts(root, keep_cuts=2)
        steps = sorted(c.step for c in scan_cuts(root))
        assert steps == [4, 5]
        assert len(removed) == 9  # 3 ranks x 3 superseded cuts

    def test_superseded_partial_cut_collected(self, tmp_path):
        root = str(tmp_path)
        _write_cut(root, 3, 1, ranks=[0, 2])  # partial (preemption orphan)
        _write_cut(root, 3, 2)
        _write_cut(root, 3, 3)
        gc_cuts(root, keep_cuts=2)
        cuts = scan_cuts(root)
        assert sorted(c.step for c in cuts) == [2, 3]
        assert all(not c.missing for c in cuts)

    def test_in_progress_cut_never_collected(self, tmp_path):
        root = str(tmp_path)
        for step in range(1, 4):
            _write_cut(root, 3, step)
        # step 4 is mid-write: only rank 1 has landed its member yet
        _write_cut(root, 3, 4, ranks=[1])
        gc_cuts(root, keep_cuts=1)
        steps = sorted(c.step for c in scan_cuts(root))
        # watermark = newest complete (3); the in-progress 4 MUST survive
        assert steps == [3, 4]

    def test_no_complete_cut_is_a_noop(self, tmp_path):
        root = str(tmp_path)
        _write_cut(root, 3, 1, ranks=[0])
        _write_cut(root, 3, 2, ranks=[1, 2])
        assert gc_cuts(root, keep_cuts=1) == []
        assert len(scan_cuts(root)) == 2  # evidence, not garbage

    def test_stale_rank_dirs_removed_after_shrink(self, tmp_path):
        root = str(tmp_path)
        _write_cut(root, 3, 1)
        _write_cut(root, 3, 2)
        for step in (3, 4):  # the world shrank to 2
            _write_cut(root, 2, step)
        gc_cuts(root, keep_cuts=2)
        assert sorted(c.step for c in scan_cuts(root)) == [3, 4]
        assert not os.path.isdir(os.path.join(root, "rank-00002"))  # stale

    def test_stale_tmp_debris_collected(self, tmp_path):
        root = str(tmp_path)
        _write_cut(root, 2, 1)
        debris = os.path.join(root, "rank-00000", ".snapshot-dead.tmp")
        with open(debris, "w") as fh:
            fh.write("torn")
        old = time.time() - 3600
        os.utime(debris, (old, old))
        fresh = os.path.join(root, "rank-00001", ".snapshot-live.tmp")
        with open(fresh, "w") as fh:
            fh.write("writing")
        gc_cuts(root, keep_cuts=1)
        assert not os.path.exists(debris)  # older than the grace window
        assert os.path.exists(fresh)  # an in-flight write is untouchable

    def test_manager_auto_gc_after_save(self, tmp_path):
        root = str(tmp_path)
        for step in range(1, 6):
            _write_cut(root, 2, step, keep_cuts=3)
        steps = sorted(c.step for c in scan_cuts(root))
        # auto-GC runs on RANK 0's save only (one scan per cut, not one per
        # rank — O(world) not O(world^2) metadata reads), so retention
        # trails by at most one save: rank 0 saved step 5 while cut 5 was
        # still partial, keeping complete cuts {2,3,4} plus the in-progress 5
        assert steps == [2, 3, 4, 5]
        mgr0 = DistributedSnapshotManager(root, 0, 2, keep=None, keep_cuts=3)
        mgr0.gc()  # explicit GC once cut 5 completed converges to the window
        assert sorted(c.step for c in scan_cuts(root)) == [3, 4, 5]

    def test_keep_cuts_validation(self, tmp_path):
        with pytest.raises(ValueError, match="keep_cuts"):
            DistributedSnapshotManager(str(tmp_path), 0, 2, keep_cuts=0)
        with pytest.raises(ValueError, match="keep_cuts"):
            gc_cuts(str(tmp_path), keep_cuts=0)

    def test_evaluator_keep_cuts_requires_elastic(self, tmp_path):
        with pytest.raises(ValueError, match="keep_cuts"):
            StreamingEvaluator(
                _acc(), buckets=8, snapshot_dir=str(tmp_path), keep_cuts=2
            )

    def test_evaluator_elastic_keep_cuts_bounds_disk(self, tmp_path):
        """World-1 elastic evaluator with keep_cuts: a long run of cuts
        keeps the snapshot root O(keep_cuts)."""
        ev = StreamingEvaluator(
            _acc(), buckets=8, snapshot_dir=str(tmp_path),
            snapshot_rank=0, snapshot_world_size=1, keep_cuts=2,
        )
        stream = _stream(np.random.default_rng(9), 6)
        for i, b in enumerate(stream):
            ev.submit(*b)
            ev.snapshot()
        cuts = scan_cuts(str(tmp_path))
        assert len(cuts) == 2  # O(keep_cuts), not O(history)
        ev.close()
