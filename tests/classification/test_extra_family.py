"""Calibration/kappa/MCC/jaccard/hinge/dice/ranking/fairness validated against
sklearn or manual numpy references (counterpart of reference
tests/unittests/classification/test_{calibration_error,cohen_kappa,
matthews_corrcoef,jaccard,hinge,dice,ranking,group_fairness}.py)."""

import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import (
    cohen_kappa_score as sk_cohen_kappa,
    coverage_error as sk_coverage_error,
    f1_score as sk_f1,
    jaccard_score as sk_jaccard,
    label_ranking_average_precision_score as sk_lrap,
    label_ranking_loss as sk_ranking_loss,
    matthews_corrcoef as sk_mcc,
)

import tpumetrics.classification as tmc
import tpumetrics.functional.classification as tmf
from tests.classification import inputs
from tests.conftest import NUM_CLASSES
from tests.helpers.testers import MetricTester


def _labels(p):
    p = np.asarray(p)
    if p.dtype.kind == "f":
        if p.ndim >= 2 and p.shape[-1] == NUM_CLASSES:
            return p.argmax(-1)
        return (p >= 0.5).astype(int)
    return p


class TestCohenKappa(MetricTester):
    atol = 1e-6

    @pytest.mark.parametrize("weights", [None, "linear", "quadratic"])
    @pytest.mark.parametrize("ddp", [False, True])
    def test_binary_vs_sklearn(self, weights, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=[jnp.asarray(p) for p in inputs.binary_probs_preds],
            target=[jnp.asarray(t) for t in inputs.binary_target],
            metric_class=tmc.BinaryCohenKappa,
            reference_metric=lambda p, t: sk_cohen_kappa(t.ravel(), _labels(p).ravel(), weights=weights),
            metric_args={"weights": weights},
            check_batch=False,
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_multiclass_vs_sklearn(self, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=[jnp.asarray(p) for p in inputs.multiclass_label_preds],
            target=[jnp.asarray(t) for t in inputs.multiclass_target],
            metric_class=tmc.MulticlassCohenKappa,
            reference_metric=lambda p, t: sk_cohen_kappa(t.ravel(), p.ravel()),
            metric_args={"num_classes": NUM_CLASSES},
            check_batch=False,
        )


class TestMatthewsCorrCoef(MetricTester):
    atol = 1e-6

    @pytest.mark.parametrize("ddp", [False, True])
    def test_binary_vs_sklearn(self, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=[jnp.asarray(p) for p in inputs.binary_probs_preds],
            target=[jnp.asarray(t) for t in inputs.binary_target],
            metric_class=tmc.BinaryMatthewsCorrCoef,
            reference_metric=lambda p, t: sk_mcc(t.ravel(), _labels(p).ravel()),
            check_batch=False,
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_multiclass_vs_sklearn(self, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=[jnp.asarray(p) for p in inputs.multiclass_label_preds],
            target=[jnp.asarray(t) for t in inputs.multiclass_target],
            metric_class=tmc.MulticlassMatthewsCorrCoef,
            reference_metric=lambda p, t: sk_mcc(t.ravel(), p.ravel()),
            metric_args={"num_classes": NUM_CLASSES},
            check_batch=False,
        )


class TestJaccard(MetricTester):
    atol = 1e-6

    @pytest.mark.parametrize("ddp", [False, True])
    def test_binary_vs_sklearn(self, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=[jnp.asarray(p) for p in inputs.binary_probs_preds],
            target=[jnp.asarray(t) for t in inputs.binary_target],
            metric_class=tmc.BinaryJaccardIndex,
            reference_metric=lambda p, t: sk_jaccard(t.ravel(), _labels(p).ravel()),
            check_batch=False,
        )

    @pytest.mark.parametrize("average", ["micro", "macro", "weighted"])
    @pytest.mark.parametrize("ddp", [False, True])
    def test_multiclass_vs_sklearn(self, average, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=[jnp.asarray(p) for p in inputs.multiclass_label_preds],
            target=[jnp.asarray(t) for t in inputs.multiclass_target],
            metric_class=tmc.MulticlassJaccardIndex,
            reference_metric=lambda p, t: sk_jaccard(t.ravel(), p.ravel(), average=average),
            metric_args={"num_classes": NUM_CLASSES, "average": average},
            check_batch=False,
        )

    @pytest.mark.parametrize("average", ["micro", "macro"])
    def test_multilabel_vs_sklearn(self, average):
        p = np.concatenate(inputs.multilabel_label_preds)
        t = np.concatenate(inputs.multilabel_target)
        res = tmf.multilabel_jaccard_index(jnp.asarray(p), jnp.asarray(t), NUM_CLASSES, average=average)
        ref = sk_jaccard(t, p, average=average)
        assert abs(float(res) - ref) < 1e-6


class TestCalibrationError(MetricTester):
    atol = 1e-6

    @staticmethod
    def _manual_ece(conf, acc, n_bins, norm):
        edges = np.linspace(0, 1, n_bins + 1)
        idx = np.clip(np.searchsorted(edges[1:-1], conf, side="right"), 0, n_bins - 1)
        errs, props = [], []
        for b in range(n_bins):
            m = idx == b
            if m.sum() == 0:
                continue
            errs.append(abs(acc[m].mean() - conf[m].mean()))
            props.append(m.mean())
        errs, props = np.asarray(errs), np.asarray(props)
        if norm == "l1":
            return float((errs * props).sum())
        if norm == "max":
            return float(errs.max())
        return float(np.sqrt((errs**2 * props).sum()))

    @pytest.mark.parametrize("norm", ["l1", "l2", "max"])
    @pytest.mark.parametrize("ddp", [False, True])
    def test_binary(self, norm, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=[jnp.asarray(p) for p in inputs.binary_probs_preds],
            target=[jnp.asarray(t) for t in inputs.binary_target],
            metric_class=tmc.BinaryCalibrationError,
            reference_metric=lambda p, t: self._manual_ece(p.ravel(), t.ravel(), 15, norm),
            metric_args={"n_bins": 15, "norm": norm},
            check_batch=False,
            shard_map_mode=False,
        )

    def test_multiclass(self):
        p = np.concatenate(inputs.multiclass_logits_preds)
        e = np.exp(p - p.max(-1, keepdims=True))
        probs = e / e.sum(-1, keepdims=True)
        t = np.concatenate(inputs.multiclass_target)
        res = tmf.multiclass_calibration_error(jnp.asarray(probs), jnp.asarray(t), NUM_CLASSES)
        conf = probs.max(-1)
        acc = (probs.argmax(-1) == t).astype(float)
        ref = self._manual_ece(conf, acc, 15, "l1")
        assert abs(float(res) - ref) < 1e-6


class TestHinge(MetricTester):
    @pytest.mark.parametrize("squared", [False, True])
    def test_binary_manual(self, squared):
        p = np.concatenate(inputs.binary_probs_preds)
        t = np.concatenate(inputs.binary_target)
        res = tmf.binary_hinge_loss(jnp.asarray(p), jnp.asarray(t), squared=squared)
        margin = np.where(t == 1, p, -p)
        measures = np.maximum(1 - margin, 0)
        ref = (measures**2 if squared else measures).mean()
        assert abs(float(res) - ref) < 1e-5

    def test_multiclass_crammer_singer_manual(self):
        logits = np.concatenate(inputs.multiclass_logits_preds)
        e = np.exp(logits - logits.max(-1, keepdims=True))
        probs = e / e.sum(-1, keepdims=True)
        t = np.concatenate(inputs.multiclass_target)
        res = tmf.multiclass_hinge_loss(jnp.asarray(probs), jnp.asarray(t), NUM_CLASSES)
        n = len(t)
        pred_t = probs[np.arange(n), t]
        masked = probs.copy()
        masked[np.arange(n), t] = -np.inf
        margin = pred_t - masked.max(-1)
        ref = np.maximum(1 - margin, 0).mean()
        assert abs(float(res) - ref) < 1e-5


class TestDice(MetricTester):
    def test_micro_equals_sklearn_f1_micro(self):
        p = np.concatenate(inputs.multiclass_label_preds)
        t = np.concatenate(inputs.multiclass_target)
        res = tmf.dice(jnp.asarray(p), jnp.asarray(t), average="micro", num_classes=NUM_CLASSES)
        ref = sk_f1(t, p, average="micro")
        assert abs(float(res) - ref) < 1e-6

    def test_macro_equals_sklearn_f1_macro(self):
        p = np.concatenate(inputs.multiclass_label_preds)
        t = np.concatenate(inputs.multiclass_target)
        res = tmf.dice(jnp.asarray(p), jnp.asarray(t), average="macro", num_classes=NUM_CLASSES)
        ref = sk_f1(t, p, average="macro")
        assert abs(float(res) - ref) < 1e-6

    def test_class_accumulates(self):
        m = tmc.Dice(average="micro")
        for i in range(4):
            m.update(jnp.asarray(inputs.multiclass_label_preds[i]), jnp.asarray(inputs.multiclass_target[i]))
        p = np.concatenate(inputs.multiclass_label_preds[:4])
        t = np.concatenate(inputs.multiclass_target[:4])
        assert abs(float(m.compute()) - sk_f1(t, p, average="micro")) < 1e-6


class TestRanking(MetricTester):
    atol = 1e-5

    @pytest.mark.parametrize(
        ("metric_class", "functional", "sk_fn"),
        [
            (tmc.MultilabelCoverageError, tmf.multilabel_coverage_error, sk_coverage_error),
            (tmc.MultilabelRankingAveragePrecision, tmf.multilabel_ranking_average_precision, sk_lrap),
            (tmc.MultilabelRankingLoss, tmf.multilabel_ranking_loss, sk_ranking_loss),
        ],
    )
    @pytest.mark.parametrize("ddp", [False, True])
    def test_vs_sklearn(self, metric_class, functional, sk_fn, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=[jnp.asarray(p) for p in inputs.multilabel_probs_preds],
            target=[jnp.asarray(t) for t in inputs.multilabel_target],
            metric_class=metric_class,
            reference_metric=lambda p, t: sk_fn(t, p),
            metric_args={"num_labels": NUM_CLASSES},
            check_batch=False,
        )


class TestGroupFairness(MetricTester):
    def test_group_stat_rates_manual(self):
        rng = np.random.default_rng(5)
        p = rng.integers(0, 2, 200)
        t = rng.integers(0, 2, 200)
        g = rng.integers(0, 3, 200)
        res = tmf.binary_groups_stat_rates(jnp.asarray(p), jnp.asarray(t), jnp.asarray(g), 3)
        for gi in range(3):
            m = g == gi
            tp = ((p == 1) & (t == 1) & m).sum()
            fp = ((p == 1) & (t == 0) & m).sum()
            tn = ((p == 0) & (t == 0) & m).sum()
            fn = ((p == 0) & (t == 1) & m).sum()
            total = tp + fp + tn + fn
            np.testing.assert_allclose(
                np.asarray(res[f"group_{gi}"]), np.array([tp, fp, tn, fn]) / total, atol=1e-6
            )

    def test_fairness_metrics(self):
        rng = np.random.default_rng(6)
        p = rng.random(500).astype(np.float32)
        t = rng.integers(0, 2, 500)
        g = rng.integers(0, 2, 500)
        res = tmf.binary_fairness(jnp.asarray(p), jnp.asarray(t), jnp.asarray(g), task="all")
        hard = (p >= 0.5).astype(int)
        pos_rates = np.array([hard[g == i].mean() for i in range(2)])
        tprs = np.array([hard[(g == i) & (t == 1)].mean() for i in range(2)])
        dp_key = [k for k in res if k.startswith("DP")][0]
        eo_key = [k for k in res if k.startswith("EO")][0]
        assert abs(float(res[dp_key]) - pos_rates.min() / pos_rates.max()) < 1e-6
        assert abs(float(res[eo_key]) - tprs.min() / tprs.max()) < 1e-6

    def test_modular_fairness(self):
        rng = np.random.default_rng(7)
        m = tmc.BinaryFairness(num_groups=2)
        for _ in range(3):
            m.update(
                jnp.asarray(rng.random(64).astype(np.float32)),
                jnp.asarray(rng.integers(0, 2, 64)),
                jnp.asarray(rng.integers(0, 2, 64)),
            )
        out = m.compute()
        assert any(k.startswith("DP") for k in out) and any(k.startswith("EO") for k in out)


class TestFairnessShardMap(MetricTester):
    """BinaryFairness with per-rank `groups` kwargs under shard_map — the
    kwarg-threading path of the SPMD tester (VERDICT r2 weak #7)."""

    atol = 1e-6

    def test_binary_fairness_groups_shard_map(self):
        rng = np.random.default_rng(5)
        nb, bs = 4, 64
        preds = [jnp.asarray(rng.random(bs).astype(np.float32)) for _ in range(nb)]
        target = [jnp.asarray(rng.integers(0, 2, bs)) for _ in range(nb)]
        groups = [jnp.asarray(rng.integers(0, 2, bs)) for _ in range(nb)]

        def reference(p, t, groups):
            hard = (p >= 0.5).astype(int)
            pos_rates = np.array([hard[groups == i].mean() for i in range(2)])
            tprs = np.array([hard[(groups == i) & (t == 1)].mean() for i in range(2)])
            dp = pos_rates.min() / pos_rates.max()
            eo = tprs.min() / tprs.max()
            # eager paths name the argmin/argmax groups; the jit path can't
            # (static keys) and uses the _min_max suffix — provide both
            return {
                f"DP_{pos_rates.argmin()}_{pos_rates.argmax()}": dp,
                f"EO_{tprs.argmin()}_{tprs.argmax()}": eo,
                "DP_min_max": dp,
                "EO_min_max": eo,
            }

        self.run_class_metric_test(
            ddp=True,
            preds=preds,
            target=target,
            metric_class=tmc.BinaryFairness,
            reference_metric=reference,
            metric_args={"num_groups": 2},
            groups=groups,
        )
