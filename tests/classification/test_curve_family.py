"""Curve family (PR-curve/ROC/AUROC/AveragePrecision + fixed-point metrics)
validated against sklearn (counterpart of reference
tests/unittests/classification/test_{precision_recall_curve,roc,auroc,
average_precision,recall_fixed_precision,specificity_sensitivity}.py)."""

import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import (
    average_precision_score as sk_average_precision,
    precision_recall_curve as sk_precision_recall_curve,
    roc_auc_score as sk_roc_auc,
    roc_curve as sk_roc_curve,
)

import tpumetrics.classification as tmc
import tpumetrics.functional.classification as tmf
from tests.classification import inputs
from tests.conftest import NUM_CLASSES
from tests.helpers.testers import MetricTester


def _softmax(x):
    e = np.exp(x - x.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


class TestBinaryCurves(MetricTester):
    atol = 1e-5

    @pytest.mark.parametrize("ddp", [False, True])
    def test_auroc_exact_vs_sklearn(self, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=[jnp.asarray(p) for p in inputs.binary_probs_preds],
            target=[jnp.asarray(t) for t in inputs.binary_target],
            metric_class=tmc.BinaryAUROC,
            reference_metric=lambda p, t: sk_roc_auc(t.ravel(), p.ravel()),
            check_batch=False,
            shard_map_mode=False,  # exact path computes eagerly (dynamic shapes)
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_auroc_binned_vs_sklearn(self, ddp):
        # dense threshold grid: binned result is within grid resolution of exact
        self.run_class_metric_test(
            ddp=ddp,
            preds=[jnp.asarray(p) for p in inputs.binary_probs_preds],
            target=[jnp.asarray(t) for t in inputs.binary_target],
            metric_class=tmc.BinaryAUROC,
            reference_metric=lambda p, t: sk_roc_auc(t.ravel(), p.ravel()),
            metric_args={"thresholds": 2000},
            check_batch=False,
        )
        # functional parity
        p, t = inputs.binary_probs_preds[0], inputs.binary_target[0]
        exact = float(tmf.binary_auroc(jnp.asarray(p), jnp.asarray(t)))
        binned = float(tmf.binary_auroc(jnp.asarray(p), jnp.asarray(t), thresholds=2000))
        assert abs(exact - binned) < 5e-3

    @pytest.mark.parametrize("ddp", [False, True])
    def test_average_precision_vs_sklearn(self, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=[jnp.asarray(p) for p in inputs.binary_probs_preds],
            target=[jnp.asarray(t) for t in inputs.binary_target],
            metric_class=tmc.BinaryAveragePrecision,
            reference_metric=lambda p, t: sk_average_precision(t.ravel(), p.ravel()),
            check_batch=False,
            shard_map_mode=False,
        )

    def test_pr_curve_exact_vs_sklearn(self):
        p = np.concatenate(inputs.binary_probs_preds)
        t = np.concatenate(inputs.binary_target)
        precision, recall, thresholds = tmf.binary_precision_recall_curve(jnp.asarray(p), jnp.asarray(t))
        sp, sr, st = sk_precision_recall_curve(t, p)
        np.testing.assert_allclose(np.asarray(precision), sp, atol=1e-6)
        np.testing.assert_allclose(np.asarray(recall), sr, atol=1e-6)
        np.testing.assert_allclose(np.asarray(thresholds), st, atol=1e-6)

    def test_roc_exact_vs_sklearn(self):
        p = np.concatenate(inputs.binary_probs_preds)
        t = np.concatenate(inputs.binary_target)
        fpr, tpr, _ = tmf.binary_roc(jnp.asarray(p), jnp.asarray(t))
        sf, st_, _ = sk_roc_curve(t, p, drop_intermediate=False)
        np.testing.assert_allclose(np.asarray(fpr), sf, atol=1e-6)
        np.testing.assert_allclose(np.asarray(tpr), st_, atol=1e-6)

    def test_pr_curve_class_binned_state_is_jittable(self):
        import jax

        metric = tmc.BinaryPrecisionRecallCurve(thresholds=50, validate_args=False)

        @jax.jit
        def step(state, p, t):
            return metric.functional_update(state, p, t)

        state = metric.init_state()
        for i in range(2):
            state = step(state, jnp.asarray(inputs.binary_probs_preds[i]), jnp.asarray(inputs.binary_target[i]))
        precision, recall, thresholds = metric.functional_compute(state)
        assert precision.shape == (51,)

    def test_recall_at_fixed_precision(self):
        p = np.concatenate(inputs.binary_probs_preds)
        t = np.concatenate(inputs.binary_target)
        for min_precision in (0.2, 0.5, 0.8):
            r, thr = tmf.binary_recall_at_fixed_precision(jnp.asarray(p), jnp.asarray(t), min_precision)
            # brute-force reference over the sklearn PR curve
            sp, sr, st = sk_precision_recall_curve(t, p)
            valid = sp[:-1] >= min_precision
            best = sr[:-1][valid].max() if valid.any() else 0.0
            assert abs(float(r) - best) < 1e-6

    def test_precision_at_fixed_recall(self):
        p = np.concatenate(inputs.binary_probs_preds)
        t = np.concatenate(inputs.binary_target)
        for min_recall in (0.2, 0.5, 0.8):
            pr, thr = tmf.binary_precision_at_fixed_recall(jnp.asarray(p), jnp.asarray(t), min_recall)
            sp, sr, st = sk_precision_recall_curve(t, p)
            valid = sr[:-1] >= min_recall
            best = sp[:-1][valid].max() if valid.any() else 0.0
            assert abs(float(pr) - best) < 1e-6

    def test_specificity_at_sensitivity(self):
        p = np.concatenate(inputs.binary_probs_preds)
        t = np.concatenate(inputs.binary_target)
        spec, thr = tmf.binary_specificity_at_sensitivity(jnp.asarray(p), jnp.asarray(t), 0.5)
        fpr, tpr, _ = sk_roc_curve(t, p, drop_intermediate=False)
        best = (1 - fpr)[tpr >= 0.5].max()
        assert abs(float(spec) - best) < 1e-6


class TestMulticlassCurves(MetricTester):
    atol = 1e-5

    @pytest.mark.parametrize("average", ["macro", "weighted"])
    @pytest.mark.parametrize("ddp", [False, True])
    def test_auroc_vs_sklearn(self, average, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=[jnp.asarray(_softmax(p)) for p in inputs.multiclass_logits_preds],
            target=[jnp.asarray(t) for t in inputs.multiclass_target],
            metric_class=tmc.MulticlassAUROC,
            reference_metric=lambda p, t: sk_roc_auc(t, p, multi_class="ovr", average=average),
            metric_args={"num_classes": NUM_CLASSES, "average": average},
            check_batch=False,
            shard_map_mode=False,
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_average_precision_vs_sklearn(self, ddp):
        def ref(p, t):
            onehot = np.eye(NUM_CLASSES)[t.astype(int)]
            return sk_average_precision(onehot, p, average="macro")

        self.run_class_metric_test(
            ddp=ddp,
            preds=[jnp.asarray(_softmax(p)) for p in inputs.multiclass_logits_preds],
            target=[jnp.asarray(t) for t in inputs.multiclass_target],
            metric_class=tmc.MulticlassAveragePrecision,
            reference_metric=ref,
            metric_args={"num_classes": NUM_CLASSES},
            check_batch=False,
            shard_map_mode=False,
        )

    def test_binned_auroc_close_to_exact(self):
        p = _softmax(np.concatenate(inputs.multiclass_logits_preds))
        t = np.concatenate(inputs.multiclass_target)
        exact = float(tmf.multiclass_auroc(jnp.asarray(p), jnp.asarray(t), NUM_CLASSES))
        binned = float(tmf.multiclass_auroc(jnp.asarray(p), jnp.asarray(t), NUM_CLASSES, thresholds=2000))
        assert abs(exact - binned) < 5e-3

    def test_roc_curves_match_sklearn_per_class(self):
        p = _softmax(np.concatenate(inputs.multiclass_logits_preds))
        t = np.concatenate(inputs.multiclass_target)
        fprs, tprs, _ = tmf.multiclass_roc(jnp.asarray(p), jnp.asarray(t), NUM_CLASSES)
        for i in range(NUM_CLASSES):
            sf, st_, _ = sk_roc_curve((t == i).astype(int), p[:, i], drop_intermediate=False)
            np.testing.assert_allclose(np.asarray(fprs[i]), sf, atol=1e-6)
            np.testing.assert_allclose(np.asarray(tprs[i]), st_, atol=1e-6)


class TestMultilabelCurves(MetricTester):
    atol = 1e-5

    @pytest.mark.parametrize("average", ["macro", "micro"])
    @pytest.mark.parametrize("ddp", [False, True])
    def test_auroc_vs_sklearn(self, average, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=[jnp.asarray(p) for p in inputs.multilabel_probs_preds],
            target=[jnp.asarray(t) for t in inputs.multilabel_target],
            metric_class=tmc.MultilabelAUROC,
            reference_metric=lambda p, t: sk_roc_auc(t, p, average=average),
            metric_args={"num_labels": NUM_CLASSES, "average": average},
            check_batch=False,
            shard_map_mode=False,
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_average_precision_vs_sklearn(self, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=[jnp.asarray(p) for p in inputs.multilabel_probs_preds],
            target=[jnp.asarray(t) for t in inputs.multilabel_target],
            metric_class=tmc.MultilabelAveragePrecision,
            reference_metric=lambda p, t: sk_average_precision(t, p, average="macro"),
            metric_args={"num_labels": NUM_CLASSES},
            check_batch=False,
            shard_map_mode=False,
        )


def test_binned_class_ddp_shard_map():
    """Binned AUROC state syncs inside shard_map (the TPU pod path)."""
    from tests.helpers.testers import _class_test_shard_map

    _class_test_shard_map(
        preds=[jnp.asarray(p) for p in inputs.binary_probs_preds],
        target=[jnp.asarray(t) for t in inputs.binary_target],
        metric_class=tmc.BinaryAUROC,
        reference_metric=lambda p, t: sk_roc_auc(t.ravel(), p.ravel()),
        metric_args={"thresholds": 2000, "validate_args": False},
        atol=5e-3,
    )


def test_task_wrappers_dispatch():
    assert isinstance(tmc.AUROC(task="binary"), tmc.BinaryAUROC)
    assert isinstance(tmc.ROC(task="multiclass", num_classes=3), tmc.MulticlassROC)
    assert isinstance(tmc.PrecisionRecallCurve(task="multilabel", num_labels=3), tmc.MultilabelPrecisionRecallCurve)
    assert isinstance(tmc.AveragePrecision(task="binary"), tmc.BinaryAveragePrecision)
    assert isinstance(
        tmc.RecallAtFixedPrecision(task="binary", min_precision=0.5), tmc.BinaryRecallAtFixedPrecision
    )
    assert isinstance(
        tmc.PrecisionAtFixedRecall(task="binary", min_recall=0.5), tmc.BinaryPrecisionAtFixedRecall
    )
    assert isinstance(
        tmc.SpecificityAtSensitivity(task="binary", min_sensitivity=0.5), tmc.BinarySpecificityAtSensitivity
    )


class TestBinnedConfusionTensor:
    """Regression tests for the scatter-free binned confusion redesign:
    the MXU-contraction path and the histogram fallback must match a direct
    per-threshold comparison exactly — including unsorted threshold lists,
    predictions tied exactly at a threshold, and ignore_index masking."""

    @staticmethod
    def _brute(preds2d, bits2d, valid2d, thr):
        out = np.zeros((len(thr), preds2d.shape[1], 2, 2), np.int64)
        for ti, th in enumerate(np.asarray(thr)):
            pr = (preds2d >= th).astype(int)
            for y in (0, 1):
                for pp in (0, 1):
                    out[ti, :, y, pp] = np.sum((bits2d == y) & (pr == pp) & valid2d, axis=0)
        return out

    @pytest.mark.parametrize("sorted_thr", [True, False])
    @pytest.mark.parametrize("ignore_index", [None, -1])
    def test_binary_matches_bruteforce(self, sorted_thr, ignore_index):
        from tpumetrics.functional.classification.precision_recall_curve import (
            _binary_precision_recall_curve_update,
        )

        rng = np.random.default_rng(7)
        n_t = 13
        thr_np = np.sort(rng.random(n_t).astype(np.float32))
        if not sorted_thr:
            thr_np = rng.permutation(thr_np)
        thr = jnp.asarray(thr_np)
        preds = jnp.asarray(rng.random(199, dtype=np.float32))
        preds = preds.at[:n_t].set(thr)  # exact ties at every threshold
        target = jnp.asarray(rng.integers(0, 2, 199), dtype=jnp.int32)
        if ignore_index is not None:
            target = target.at[::7].set(ignore_index)
        got = np.asarray(_binary_precision_recall_curve_update(preds, target, thr, ignore_index))
        valid = np.ones((199, 1), bool) if ignore_index is None else (np.asarray(target) != ignore_index)[:, None]
        bits = np.where(valid[:, 0], np.asarray(target), 0)[:, None]
        expected = self._brute(np.asarray(preds)[:, None], bits, valid, thr_np)[:, 0]
        assert np.array_equal(got, expected)

    @pytest.mark.parametrize("ignore_index", [None, -1])
    def test_multilabel_contract_matches_hist(self, ignore_index):
        from tpumetrics.functional.classification.precision_recall_curve import (
            _binned_confusion_contract,
            _binned_confusion_hist,
        )

        rng = np.random.default_rng(11)
        n, c, n_t = 157, 4, 9
        thr = jnp.asarray(rng.permutation(rng.random(n_t).astype(np.float32)))
        preds = jnp.asarray(rng.random((n, c), dtype=np.float32))
        target = jnp.asarray(rng.integers(0, 2, (n, c)), dtype=jnp.int32)
        invalid = None
        if ignore_index is not None:
            invalid = jnp.asarray(rng.integers(0, 2, (n, c)).astype(bool))
        a = _binned_confusion_contract(preds, target, thr, invalid)
        b = _binned_confusion_hist(preds, target, thr, invalid)
        assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_multiclass_matches_bruteforce_with_ignore(self):
        from tpumetrics.functional.classification.precision_recall_curve import (
            _multiclass_precision_recall_curve_update,
        )

        rng = np.random.default_rng(3)
        n, c, n_t = 211, NUM_CLASSES, 11
        thr_np = np.sort(rng.random(n_t).astype(np.float32))
        thr = jnp.asarray(thr_np)
        preds = jnp.asarray(rng.random((n, c), dtype=np.float32))
        target = jnp.asarray(rng.integers(0, c, n), dtype=jnp.int32).at[::5].set(-1)
        got = np.asarray(_multiclass_precision_recall_curve_update(preds, target, c, thr, None, -1))
        valid = np.broadcast_to((np.asarray(target) != -1)[:, None], (n, c))
        onehot = np.eye(c, dtype=int)[np.where(np.asarray(target) == -1, 0, np.asarray(target))]
        expected = self._brute(np.asarray(preds), onehot, valid, thr_np)
        assert np.array_equal(got, expected)

    def test_contract_and_hist_agree_on_nan_preds(self):
        from tpumetrics.functional.classification.precision_recall_curve import (
            _binned_confusion_contract,
            _binned_confusion_hist,
        )

        preds = jnp.asarray([[0.2], [jnp.nan], [0.8]], dtype=jnp.float32)
        target = jnp.asarray([[1], [1], [0]], dtype=jnp.int32)
        thr = jnp.asarray([0.5], dtype=jnp.float32)
        a = _binned_confusion_contract(preds, target, thr, None)
        b = _binned_confusion_hist(preds, target, thr, None)
        assert np.array_equal(np.asarray(a), np.asarray(b))
        # NaN >= thr is False -> the NaN positive sample is a false negative:
        # 0.2/y=1 -> fn, NaN/y=1 -> fn, 0.8/y=0 -> fp
        assert np.array_equal(np.asarray(a[0, 0]), [[0, 1], [2, 0]])
