"""Curve family (PR-curve/ROC/AUROC/AveragePrecision + fixed-point metrics)
validated against sklearn (counterpart of reference
tests/unittests/classification/test_{precision_recall_curve,roc,auroc,
average_precision,recall_fixed_precision,specificity_sensitivity}.py)."""

import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import (
    average_precision_score as sk_average_precision,
    precision_recall_curve as sk_precision_recall_curve,
    roc_auc_score as sk_roc_auc,
    roc_curve as sk_roc_curve,
)

import tpumetrics.classification as tmc
import tpumetrics.functional.classification as tmf
from tests.classification import inputs
from tests.conftest import NUM_CLASSES
from tests.helpers.testers import MetricTester


def _softmax(x):
    e = np.exp(x - x.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


class TestBinaryCurves(MetricTester):
    atol = 1e-5

    @pytest.mark.parametrize("ddp", [False, True])
    def test_auroc_exact_vs_sklearn(self, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=[jnp.asarray(p) for p in inputs.binary_probs_preds],
            target=[jnp.asarray(t) for t in inputs.binary_target],
            metric_class=tmc.BinaryAUROC,
            reference_metric=lambda p, t: sk_roc_auc(t.ravel(), p.ravel()),
            check_batch=False,
            shard_map_mode=False,  # exact path computes eagerly (dynamic shapes)
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_auroc_binned_vs_sklearn(self, ddp):
        # dense threshold grid: binned result is within grid resolution of exact
        self.run_class_metric_test(
            ddp=ddp,
            preds=[jnp.asarray(p) for p in inputs.binary_probs_preds],
            target=[jnp.asarray(t) for t in inputs.binary_target],
            metric_class=tmc.BinaryAUROC,
            reference_metric=lambda p, t: sk_roc_auc(t.ravel(), p.ravel()),
            metric_args={"thresholds": 2000},
            check_batch=False,
        )
        # functional parity
        p, t = inputs.binary_probs_preds[0], inputs.binary_target[0]
        exact = float(tmf.binary_auroc(jnp.asarray(p), jnp.asarray(t)))
        binned = float(tmf.binary_auroc(jnp.asarray(p), jnp.asarray(t), thresholds=2000))
        assert abs(exact - binned) < 5e-3

    @pytest.mark.parametrize("ddp", [False, True])
    def test_average_precision_vs_sklearn(self, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=[jnp.asarray(p) for p in inputs.binary_probs_preds],
            target=[jnp.asarray(t) for t in inputs.binary_target],
            metric_class=tmc.BinaryAveragePrecision,
            reference_metric=lambda p, t: sk_average_precision(t.ravel(), p.ravel()),
            check_batch=False,
            shard_map_mode=False,
        )

    def test_pr_curve_exact_vs_sklearn(self):
        p = np.concatenate(inputs.binary_probs_preds)
        t = np.concatenate(inputs.binary_target)
        precision, recall, thresholds = tmf.binary_precision_recall_curve(jnp.asarray(p), jnp.asarray(t))
        sp, sr, st = sk_precision_recall_curve(t, p)
        np.testing.assert_allclose(np.asarray(precision), sp, atol=1e-6)
        np.testing.assert_allclose(np.asarray(recall), sr, atol=1e-6)
        np.testing.assert_allclose(np.asarray(thresholds), st, atol=1e-6)

    def test_roc_exact_vs_sklearn(self):
        p = np.concatenate(inputs.binary_probs_preds)
        t = np.concatenate(inputs.binary_target)
        fpr, tpr, _ = tmf.binary_roc(jnp.asarray(p), jnp.asarray(t))
        sf, st_, _ = sk_roc_curve(t, p, drop_intermediate=False)
        np.testing.assert_allclose(np.asarray(fpr), sf, atol=1e-6)
        np.testing.assert_allclose(np.asarray(tpr), st_, atol=1e-6)

    def test_pr_curve_class_binned_state_is_jittable(self):
        import jax

        metric = tmc.BinaryPrecisionRecallCurve(thresholds=50, validate_args=False)

        @jax.jit
        def step(state, p, t):
            return metric.functional_update(state, p, t)

        state = metric.init_state()
        for i in range(2):
            state = step(state, jnp.asarray(inputs.binary_probs_preds[i]), jnp.asarray(inputs.binary_target[i]))
        precision, recall, thresholds = metric.functional_compute(state)
        assert precision.shape == (51,)

    def test_recall_at_fixed_precision(self):
        p = np.concatenate(inputs.binary_probs_preds)
        t = np.concatenate(inputs.binary_target)
        for min_precision in (0.2, 0.5, 0.8):
            r, thr = tmf.binary_recall_at_fixed_precision(jnp.asarray(p), jnp.asarray(t), min_precision)
            # brute-force reference over the sklearn PR curve
            sp, sr, st = sk_precision_recall_curve(t, p)
            valid = sp[:-1] >= min_precision
            best = sr[:-1][valid].max() if valid.any() else 0.0
            assert abs(float(r) - best) < 1e-6

    def test_precision_at_fixed_recall(self):
        p = np.concatenate(inputs.binary_probs_preds)
        t = np.concatenate(inputs.binary_target)
        for min_recall in (0.2, 0.5, 0.8):
            pr, thr = tmf.binary_precision_at_fixed_recall(jnp.asarray(p), jnp.asarray(t), min_recall)
            sp, sr, st = sk_precision_recall_curve(t, p)
            valid = sr[:-1] >= min_recall
            best = sp[:-1][valid].max() if valid.any() else 0.0
            assert abs(float(pr) - best) < 1e-6

    def test_specificity_at_sensitivity(self):
        p = np.concatenate(inputs.binary_probs_preds)
        t = np.concatenate(inputs.binary_target)
        spec, thr = tmf.binary_specificity_at_sensitivity(jnp.asarray(p), jnp.asarray(t), 0.5)
        fpr, tpr, _ = sk_roc_curve(t, p, drop_intermediate=False)
        best = (1 - fpr)[tpr >= 0.5].max()
        assert abs(float(spec) - best) < 1e-6


class TestMulticlassCurves(MetricTester):
    atol = 1e-5

    @pytest.mark.parametrize("average", ["macro", "weighted"])
    @pytest.mark.parametrize("ddp", [False, True])
    def test_auroc_vs_sklearn(self, average, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=[jnp.asarray(_softmax(p)) for p in inputs.multiclass_logits_preds],
            target=[jnp.asarray(t) for t in inputs.multiclass_target],
            metric_class=tmc.MulticlassAUROC,
            reference_metric=lambda p, t: sk_roc_auc(t, p, multi_class="ovr", average=average),
            metric_args={"num_classes": NUM_CLASSES, "average": average},
            check_batch=False,
            shard_map_mode=False,
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_average_precision_vs_sklearn(self, ddp):
        def ref(p, t):
            onehot = np.eye(NUM_CLASSES)[t.astype(int)]
            return sk_average_precision(onehot, p, average="macro")

        self.run_class_metric_test(
            ddp=ddp,
            preds=[jnp.asarray(_softmax(p)) for p in inputs.multiclass_logits_preds],
            target=[jnp.asarray(t) for t in inputs.multiclass_target],
            metric_class=tmc.MulticlassAveragePrecision,
            reference_metric=ref,
            metric_args={"num_classes": NUM_CLASSES},
            check_batch=False,
            shard_map_mode=False,
        )

    def test_binned_auroc_close_to_exact(self):
        p = _softmax(np.concatenate(inputs.multiclass_logits_preds))
        t = np.concatenate(inputs.multiclass_target)
        exact = float(tmf.multiclass_auroc(jnp.asarray(p), jnp.asarray(t), NUM_CLASSES))
        binned = float(tmf.multiclass_auroc(jnp.asarray(p), jnp.asarray(t), NUM_CLASSES, thresholds=2000))
        assert abs(exact - binned) < 5e-3

    def test_roc_curves_match_sklearn_per_class(self):
        p = _softmax(np.concatenate(inputs.multiclass_logits_preds))
        t = np.concatenate(inputs.multiclass_target)
        fprs, tprs, _ = tmf.multiclass_roc(jnp.asarray(p), jnp.asarray(t), NUM_CLASSES)
        for i in range(NUM_CLASSES):
            sf, st_, _ = sk_roc_curve((t == i).astype(int), p[:, i], drop_intermediate=False)
            np.testing.assert_allclose(np.asarray(fprs[i]), sf, atol=1e-6)
            np.testing.assert_allclose(np.asarray(tprs[i]), st_, atol=1e-6)


class TestMultilabelCurves(MetricTester):
    atol = 1e-5

    @pytest.mark.parametrize("average", ["macro", "micro"])
    @pytest.mark.parametrize("ddp", [False, True])
    def test_auroc_vs_sklearn(self, average, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=[jnp.asarray(p) for p in inputs.multilabel_probs_preds],
            target=[jnp.asarray(t) for t in inputs.multilabel_target],
            metric_class=tmc.MultilabelAUROC,
            reference_metric=lambda p, t: sk_roc_auc(t, p, average=average),
            metric_args={"num_labels": NUM_CLASSES, "average": average},
            check_batch=False,
            shard_map_mode=False,
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_average_precision_vs_sklearn(self, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=[jnp.asarray(p) for p in inputs.multilabel_probs_preds],
            target=[jnp.asarray(t) for t in inputs.multilabel_target],
            metric_class=tmc.MultilabelAveragePrecision,
            reference_metric=lambda p, t: sk_average_precision(t, p, average="macro"),
            metric_args={"num_labels": NUM_CLASSES},
            check_batch=False,
            shard_map_mode=False,
        )


def test_binned_class_ddp_shard_map():
    """Binned AUROC state syncs inside shard_map (the TPU pod path)."""
    from tests.helpers.testers import _class_test_shard_map

    _class_test_shard_map(
        preds=[jnp.asarray(p) for p in inputs.binary_probs_preds],
        target=[jnp.asarray(t) for t in inputs.binary_target],
        metric_class=tmc.BinaryAUROC,
        reference_metric=lambda p, t: sk_roc_auc(t.ravel(), p.ravel()),
        metric_args={"thresholds": 2000, "validate_args": False},
        atol=5e-3,
    )


def test_task_wrappers_dispatch():
    assert isinstance(tmc.AUROC(task="binary"), tmc.BinaryAUROC)
    assert isinstance(tmc.ROC(task="multiclass", num_classes=3), tmc.MulticlassROC)
    assert isinstance(tmc.PrecisionRecallCurve(task="multilabel", num_labels=3), tmc.MultilabelPrecisionRecallCurve)
    assert isinstance(tmc.AveragePrecision(task="binary"), tmc.BinaryAveragePrecision)
    assert isinstance(
        tmc.RecallAtFixedPrecision(task="binary", min_precision=0.5), tmc.BinaryRecallAtFixedPrecision
    )
    assert isinstance(
        tmc.PrecisionAtFixedRecall(task="binary", min_recall=0.5), tmc.BinaryPrecisionAtFixedRecall
    )
    assert isinstance(
        tmc.SpecificityAtSensitivity(task="binary", min_sensitivity=0.5), tmc.BinarySpecificityAtSensitivity
    )
