"""Shared random input fixtures for classification tests
(counterpart of reference tests/unittests/classification/inputs.py)."""

import numpy as np

from tests.conftest import BATCH_SIZE, NUM_BATCHES, NUM_CLASSES

_rng = np.random.default_rng(42)

# binary: probabilities and hard labels
binary_probs_preds = _rng.random((NUM_BATCHES, BATCH_SIZE)).astype(np.float32)
binary_label_preds = _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE))
binary_target = _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE))

# multiclass: logits and hard labels
multiclass_logits_preds = _rng.normal(size=(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)).astype(np.float32)
multiclass_label_preds = _rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE))
multiclass_target = _rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE))

# multilabel: probabilities and hard labels
multilabel_probs_preds = _rng.random((NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)).astype(np.float32)
multilabel_label_preds = _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES))
multilabel_target = _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES))

# ------- widened input matrix (reference inputs.py logit/multidim variants)
from tests.conftest import EXTRA_DIM  # noqa: E402

# binary: raw logits (pre-sigmoid, unbounded)
binary_logits_preds = _rng.normal(size=(NUM_BATCHES, BATCH_SIZE)).astype(np.float32)

# binary multidim: (B, E1, E2) per batch
binary_md_probs_preds = _rng.random((NUM_BATCHES, BATCH_SIZE, EXTRA_DIM, 2)).astype(np.float32)
binary_md_target = _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, EXTRA_DIM, 2))

# multiclass multidim: preds (B, C, E), target (B, E)
multiclass_md_logits_preds = _rng.normal(
    size=(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES, EXTRA_DIM)
).astype(np.float32)
multiclass_md_target = _rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE, EXTRA_DIM))

# multilabel multidim: preds (B, L, E), target (B, L, E)
multilabel_md_probs_preds = _rng.random(
    (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES, EXTRA_DIM)
).astype(np.float32)
multilabel_md_target = _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES, EXTRA_DIM))
