"""Stat-scores family (accuracy/precision/recall/F1/specificity/stat-scores/
confusion-matrix/hamming/exact-match) validated against sklearn
(counterpart of reference tests/unittests/classification/test_{accuracy,
precision_recall,f_beta,specificity,stat_scores,confusion_matrix,hamming,
exact_match}.py)."""

import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import (
    accuracy_score as sk_accuracy,
    confusion_matrix as sk_confusion_matrix,
    f1_score as sk_f1,
    hamming_loss as sk_hamming_loss,
    multilabel_confusion_matrix as sk_multilabel_confusion_matrix,
    precision_score as sk_precision,
    recall_score as sk_recall,
)

import tpumetrics.classification as tmc
import tpumetrics.functional.classification as tmf
from tests.classification import inputs
from tests.conftest import NUM_CLASSES
from tests.helpers.testers import MetricTester


def _sk_binary(preds, target, fn, **kw):
    preds = (preds >= 0.5).astype(int) if preds.dtype.kind == "f" else preds
    return fn(target.ravel(), preds.ravel(), **kw)


def _to_labels(preds):
    """sklearn-compatible hard labels from logits (argmax over class dim) or pass-through."""
    preds = np.asarray(preds)
    if preds.dtype.kind == "f" and preds.ndim >= 2 and preds.shape[-1] == NUM_CLASSES:
        return preds.argmax(-1)
    return preds


class TestBinaryStatFamily(MetricTester):
    atol = 1e-6

    @pytest.mark.parametrize(
        ("metric_class", "metric_fn", "sk_fn"),
        [
            (tmc.BinaryAccuracy, tmf.binary_accuracy, sk_accuracy),
            (tmc.BinaryPrecision, tmf.binary_precision, sk_precision),
            (tmc.BinaryRecall, tmf.binary_recall, sk_recall),
            (tmc.BinaryF1Score, tmf.binary_f1_score, sk_f1),
        ],
    )
    @pytest.mark.parametrize("use_probs", [True, False])
    @pytest.mark.parametrize("ddp", [False, True])
    def test_vs_sklearn(self, metric_class, metric_fn, sk_fn, use_probs, ddp):
        preds = inputs.binary_probs_preds if use_probs else inputs.binary_label_preds
        target = inputs.binary_target
        ref = lambda p, t: _sk_binary(p, t, sk_fn)  # noqa: E731
        self.run_class_metric_test(
            ddp=ddp,
            preds=[jnp.asarray(p) for p in preds],
            target=[jnp.asarray(t) for t in target],
            metric_class=metric_class,
            reference_metric=lambda p, t: ref(p, t),
        )
        if not ddp:
            self.run_functional_metric_test(
                [jnp.asarray(p) for p in preds],
                [jnp.asarray(t) for t in target],
                metric_fn,
                lambda p, t: ref(p, t),
            )

    def test_specificity(self):
        preds, target = inputs.binary_label_preds, inputs.binary_target
        p, t = preds.ravel(), target.ravel()
        tn = ((p == 0) & (t == 0)).sum()
        fp = ((p == 1) & (t == 0)).sum()
        expected = tn / (tn + fp)
        self.run_class_metric_test(
            ddp=False,
            preds=[jnp.asarray(x) for x in preds],
            target=[jnp.asarray(x) for x in target],
            metric_class=tmc.BinarySpecificity,
            reference_metric=lambda p_, t_: _sk_spec_binary(p_, t_),
            check_batch=False,
        )
        got = tmf.binary_specificity(jnp.asarray(p), jnp.asarray(t))
        assert np.allclose(float(got), expected)

    def test_confusion_matrix(self):
        preds, target = inputs.binary_label_preds, inputs.binary_target
        got = tmf.binary_confusion_matrix(jnp.asarray(preds.ravel()), jnp.asarray(target.ravel()))
        expected = sk_confusion_matrix(target.ravel(), preds.ravel())
        assert np.allclose(np.asarray(got), expected)

    def test_hamming(self):
        preds, target = inputs.binary_label_preds, inputs.binary_target
        got = tmf.binary_hamming_distance(jnp.asarray(preds.ravel()), jnp.asarray(target.ravel()))
        expected = sk_hamming_loss(target.ravel(), preds.ravel())
        assert np.allclose(float(got), expected)

    def test_stat_scores(self):
        preds, target = inputs.binary_label_preds, inputs.binary_target
        got = np.asarray(tmf.binary_stat_scores(jnp.asarray(preds.ravel()), jnp.asarray(target.ravel())))
        cm = sk_confusion_matrix(target.ravel(), preds.ravel())
        tn, fp, fn, tp = cm.ravel()
        assert got.tolist() == [tp, fp, tn, fn, tp + fn]

    def test_ignore_index(self):
        target = inputs.binary_target.copy().ravel()
        preds = inputs.binary_label_preds.ravel()
        target[::5] = -1
        got = tmf.binary_accuracy(jnp.asarray(preds), jnp.asarray(target), ignore_index=-1)
        keep = target != -1
        expected = sk_accuracy(target[keep], preds[keep])
        assert np.allclose(float(got), expected)


def _sk_spec_binary(preds, target):
    preds = (preds >= 0.5).astype(int) if preds.dtype.kind == "f" else preds
    p, t = preds.ravel(), target.ravel()
    tn = ((p == 0) & (t == 0)).sum()
    fp = ((p == 1) & (t == 0)).sum()
    return tn / (tn + fp) if tn + fp else 0.0


class TestMulticlassStatFamily(MetricTester):
    atol = 1e-6

    @pytest.mark.parametrize(
        ("metric_class", "metric_fn", "sk_fn", "average"),
        [
            (tmc.MulticlassAccuracy, tmf.multiclass_accuracy, None, "micro"),
            (tmc.MulticlassAccuracy, tmf.multiclass_accuracy, sk_recall, "macro"),
            (tmc.MulticlassPrecision, tmf.multiclass_precision, sk_precision, "macro"),
            (tmc.MulticlassPrecision, tmf.multiclass_precision, sk_precision, "micro"),
            (tmc.MulticlassPrecision, tmf.multiclass_precision, sk_precision, "weighted"),
            (tmc.MulticlassPrecision, tmf.multiclass_precision, sk_precision, None),
            (tmc.MulticlassRecall, tmf.multiclass_recall, sk_recall, "macro"),
            (tmc.MulticlassF1Score, tmf.multiclass_f1_score, sk_f1, "macro"),
            (tmc.MulticlassF1Score, tmf.multiclass_f1_score, sk_f1, "weighted"),
        ],
    )
    @pytest.mark.parametrize("use_logits", [True, False])
    @pytest.mark.parametrize("ddp", [False, True])
    def test_vs_sklearn(self, metric_class, metric_fn, sk_fn, average, use_logits, ddp):
        preds = inputs.multiclass_logits_preds if use_logits else inputs.multiclass_label_preds
        target = inputs.multiclass_target
        labels = list(range(NUM_CLASSES))

        if sk_fn is None:  # micro accuracy

            def ref(p, t):
                return sk_accuracy(t.ravel(), _to_labels(np.asarray(p)).ravel())

        else:

            def ref(p, t):
                return sk_fn(
                    t.ravel(),
                    _to_labels(np.asarray(p)).ravel(),
                    average=average,
                    labels=labels,
                    zero_division=0,
                )

        self.run_class_metric_test(
            ddp=ddp,
            preds=[jnp.asarray(p) for p in preds],
            target=[jnp.asarray(t) for t in target],
            metric_class=metric_class,
            reference_metric=ref,
            metric_args={"num_classes": NUM_CLASSES, "average": average},
        )
        if not ddp:
            self.run_functional_metric_test(
                [jnp.asarray(p) for p in preds],
                [jnp.asarray(t) for t in target],
                metric_fn,
                ref,
                metric_args={"num_classes": NUM_CLASSES, "average": average},
            )

    def test_confusion_matrix(self):
        preds, target = inputs.multiclass_label_preds, inputs.multiclass_target
        got = tmf.multiclass_confusion_matrix(
            jnp.asarray(preds.ravel()), jnp.asarray(target.ravel()), num_classes=NUM_CLASSES
        )
        expected = sk_confusion_matrix(target.ravel(), preds.ravel(), labels=list(range(NUM_CLASSES)))
        assert np.allclose(np.asarray(got), expected)

    @pytest.mark.parametrize("normalize", ["true", "pred", "all", None])
    def test_confusion_matrix_normalize(self, normalize):
        preds, target = inputs.multiclass_label_preds, inputs.multiclass_target
        got = tmf.multiclass_confusion_matrix(
            jnp.asarray(preds.ravel()),
            jnp.asarray(target.ravel()),
            num_classes=NUM_CLASSES,
            normalize=normalize,
        )
        expected = sk_confusion_matrix(
            target.ravel(), preds.ravel(), labels=list(range(NUM_CLASSES)), normalize=normalize
        )
        assert np.allclose(np.asarray(got), expected)

    def test_top_k(self):
        preds, target = inputs.multiclass_logits_preds, inputs.multiclass_target
        p, t = preds.reshape(-1, NUM_CLASSES), target.ravel()
        got = tmf.multiclass_accuracy(
            jnp.asarray(p), jnp.asarray(t), num_classes=NUM_CLASSES, top_k=2, average="micro"
        )
        topk = np.argsort(-p, axis=1)[:, :2]
        expected = np.mean([t[i] in topk[i] for i in range(len(t))])
        assert np.allclose(float(got), expected)

    def test_ignore_index(self):
        preds = inputs.multiclass_label_preds.ravel()
        target = inputs.multiclass_target.copy().ravel()
        target[::7] = NUM_CLASSES  # use an extra id as ignore
        got = tmf.multiclass_accuracy(
            jnp.asarray(preds), jnp.asarray(target), num_classes=NUM_CLASSES,
            average="micro", ignore_index=NUM_CLASSES,
        )
        keep = target != NUM_CLASSES
        expected = sk_accuracy(target[keep], preds[keep])
        assert np.allclose(float(got), expected)

    def test_exact_match(self):
        preds, target = inputs.multiclass_label_preds, inputs.multiclass_target
        got = tmf.multiclass_exact_match(jnp.asarray(preds), jnp.asarray(target), num_classes=NUM_CLASSES)
        expected = np.mean([(p == t).all() for p, t in zip(preds, target)])
        assert np.allclose(float(got), expected)


class TestMultilabelStatFamily(MetricTester):
    atol = 1e-6

    @pytest.mark.parametrize(
        ("metric_class", "metric_fn", "sk_fn", "average"),
        [
            (tmc.MultilabelPrecision, tmf.multilabel_precision, sk_precision, "macro"),
            (tmc.MultilabelPrecision, tmf.multilabel_precision, sk_precision, "micro"),
            (tmc.MultilabelRecall, tmf.multilabel_recall, sk_recall, "macro"),
            (tmc.MultilabelF1Score, tmf.multilabel_f1_score, sk_f1, "macro"),
            (tmc.MultilabelF1Score, tmf.multilabel_f1_score, sk_f1, "weighted"),
        ],
    )
    @pytest.mark.parametrize("ddp", [False, True])
    def test_vs_sklearn(self, metric_class, metric_fn, sk_fn, average, ddp):
        preds, target = inputs.multilabel_label_preds, inputs.multilabel_target

        def ref(p, t):
            p = p.reshape(-1, NUM_CLASSES)
            t = t.reshape(-1, NUM_CLASSES)
            return sk_fn(t, p, average=average, zero_division=0)

        self.run_class_metric_test(
            ddp=ddp,
            preds=[jnp.asarray(p) for p in preds],
            target=[jnp.asarray(t) for t in target],
            metric_class=metric_class,
            reference_metric=ref,
            metric_args={"num_labels": NUM_CLASSES, "average": average},
        )
        if not ddp:
            self.run_functional_metric_test(
                [jnp.asarray(p) for p in preds],
                [jnp.asarray(t) for t in target],
                metric_fn,
                ref,
                metric_args={"num_labels": NUM_CLASSES, "average": average},
            )

    def test_confusion_matrix(self):
        preds, target = inputs.multilabel_label_preds, inputs.multilabel_target
        got = tmf.multilabel_confusion_matrix(
            jnp.asarray(preds.reshape(-1, NUM_CLASSES)),
            jnp.asarray(target.reshape(-1, NUM_CLASSES)),
            num_labels=NUM_CLASSES,
        )
        expected = sk_multilabel_confusion_matrix(
            target.reshape(-1, NUM_CLASSES), preds.reshape(-1, NUM_CLASSES)
        )
        assert np.allclose(np.asarray(got), expected)

    def test_hamming(self):
        preds, target = inputs.multilabel_label_preds, inputs.multilabel_target
        got = tmf.multilabel_hamming_distance(
            jnp.asarray(preds.reshape(-1, NUM_CLASSES)),
            jnp.asarray(target.reshape(-1, NUM_CLASSES)),
            num_labels=NUM_CLASSES,
            average="micro",
        )
        expected = sk_hamming_loss(target.reshape(-1, NUM_CLASSES), preds.reshape(-1, NUM_CLASSES))
        assert np.allclose(float(got), expected)

    def test_exact_match(self):
        preds, target = inputs.multilabel_label_preds, inputs.multilabel_target
        p = preds.reshape(-1, NUM_CLASSES)
        t = target.reshape(-1, NUM_CLASSES)
        got = tmf.multilabel_exact_match(jnp.asarray(p), jnp.asarray(t), num_labels=NUM_CLASSES)
        expected = np.mean([(pi == ti).all() for pi, ti in zip(p, t)])
        assert np.allclose(float(got), expected)


class TestTaskWrappers:
    def test_accuracy_dispatch(self):
        m = tmc.Accuracy(task="multiclass", num_classes=4)
        assert isinstance(m, tmc.MulticlassAccuracy)
        m = tmc.Accuracy(task="binary")
        assert isinstance(m, tmc.BinaryAccuracy)
        m = tmc.Accuracy(task="multilabel", num_labels=3)
        assert isinstance(m, tmc.MultilabelAccuracy)

    def test_wrapper_raises_on_bad_task(self):
        with pytest.raises(ValueError, match="Invalid Classification"):
            tmc.Accuracy(task="not_a_task")

    def test_wrapper_requires_num_classes(self):
        with pytest.raises(ValueError, match="num_classes"):
            tmc.F1Score(task="multiclass")

    def test_samplewise_multidim(self):
        rng = np.random.default_rng(3)
        preds = rng.integers(0, 3, (4, 10))
        target = rng.integers(0, 3, (4, 10))
        got = tmf.multiclass_accuracy(
            jnp.asarray(preds), jnp.asarray(target), num_classes=3,
            average="micro", multidim_average="samplewise",
        )
        expected = (preds == target).mean(axis=1)
        assert np.allclose(np.asarray(got), expected)
