"""Widened input matrix (VERDICT r1 weak #8): logit and multidim variants
per task, the way the reference parametrizes its per-metric input list
(reference tests/unittests/classification/inputs.py)."""

import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import accuracy_score as sk_accuracy, f1_score as sk_f1

import tpumetrics.classification as tmc
from tests.classification import inputs
from tests.conftest import NUM_CLASSES
from tests.helpers.testers import MetricTester


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


class TestBinaryVariants(MetricTester):
    atol = 1e-6

    @pytest.mark.parametrize("ddp", [False, True])
    def test_logit_preds(self, ddp):
        """Unbounded preds are sigmoided before thresholding."""
        preds, target = inputs.binary_logits_preds, inputs.binary_target
        self.run_class_metric_test(
            ddp=ddp,
            preds=[jnp.asarray(p) for p in preds],
            target=[jnp.asarray(t) for t in target],
            metric_class=tmc.BinaryAccuracy,
            reference_metric=lambda p, t: sk_accuracy(
                np.asarray(t).ravel(), (_sigmoid(np.asarray(p)) >= 0.5).astype(int).ravel()
            ),
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_multidim_preds(self, ddp):
        """(B, E1, E2) inputs flatten into the sample dimension."""
        preds, target = inputs.binary_md_probs_preds, inputs.binary_md_target
        self.run_class_metric_test(
            ddp=ddp,
            preds=[jnp.asarray(p) for p in preds],
            target=[jnp.asarray(t) for t in target],
            metric_class=tmc.BinaryF1Score,
            reference_metric=lambda p, t: sk_f1(
                np.asarray(t).ravel(), (np.asarray(p) >= 0.5).astype(int).ravel()
            ),
        )


class TestMulticlassVariants(MetricTester):
    atol = 1e-6

    @pytest.mark.parametrize("average", ["micro", "macro"])
    @pytest.mark.parametrize("ddp", [False, True])
    def test_multidim_logits(self, average, ddp):
        """preds (B, C, E) with target (B, E): class dim is axis 1."""
        preds, target = inputs.multiclass_md_logits_preds, inputs.multiclass_md_target

        def ref(p, t):
            labels = np.asarray(p).argmax(1).ravel()
            t = np.asarray(t).ravel()
            if average == "micro":
                return sk_accuracy(t, labels)
            return sk_f1(t, labels, average=None, labels=range(NUM_CLASSES), zero_division=0)

        metric_cls = tmc.MulticlassAccuracy if average == "micro" else tmc.MulticlassF1Score
        reference = ref if average == "micro" else (
            lambda p, t: np.mean(ref(p, t))
        )
        self.run_class_metric_test(
            ddp=ddp,
            preds=[jnp.asarray(p) for p in preds],
            target=[jnp.asarray(t) for t in target],
            metric_class=metric_cls,
            metric_args={"num_classes": NUM_CLASSES, "average": average},
            reference_metric=reference,
        )


class TestMultilabelVariants(MetricTester):
    atol = 1e-6

    @pytest.mark.parametrize("ddp", [False, True])
    def test_multidim_probs(self, ddp):
        """preds/target (B, L, E): label dim is axis 1, extras flatten."""
        preds, target = inputs.multilabel_md_probs_preds, inputs.multilabel_md_target

        def ref(p, t):
            pp = (np.asarray(p) >= 0.5).astype(int).transpose(0, 2, 1).reshape(-1, NUM_CLASSES)
            tt = np.asarray(t).transpose(0, 2, 1).reshape(-1, NUM_CLASSES)
            return sk_f1(tt, pp, average="macro", zero_division=0)

        self.run_class_metric_test(
            ddp=ddp,
            preds=[jnp.asarray(p) for p in preds],
            target=[jnp.asarray(t) for t in target],
            metric_class=tmc.MultilabelF1Score,
            metric_args={"num_labels": NUM_CLASSES, "average": "macro"},
            reference_metric=ref,
        )
