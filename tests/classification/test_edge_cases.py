"""Classification degenerate inputs, pinned against the mounted reference's
conventions: single-class targets (undefined-metric cases), perfect
all-negative predictions."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from tpumetrics.functional.classification import (
    binary_accuracy,
    binary_auroc,
    binary_average_precision,
    binary_f1_score,
    multiclass_accuracy,
)

PREDS = jnp.asarray([0.2, 0.7, 0.4, 0.9])
ALL_POS = jnp.ones(4, jnp.int32)
ALL_NEG = jnp.zeros(4, jnp.int32)


def test_single_class_targets_auroc_and_ap():
    """No class boundary to rank across — verified equal to the reference:
    AUROC degenerates to 0.0 for BOTH single-class directions (its
    zero-area trapezoid), AP is 1.0 when everything is positive and NaN
    when nothing is."""
    assert float(binary_auroc(PREDS, ALL_POS)) == pytest.approx(0.0)
    assert float(binary_auroc(PREDS, ALL_NEG)) == pytest.approx(0.0)
    assert float(binary_average_precision(PREDS, ALL_POS)) == pytest.approx(1.0)
    assert np.isnan(float(binary_average_precision(PREDS, ALL_NEG)))


def test_perfect_all_negative_f1_is_zero():
    """No positives anywhere: precision/recall are 0/0 and F1 resolves to
    0 — the reference's zero_division default, even for a perfect
    classifier."""
    assert float(binary_f1_score(jnp.zeros(4), ALL_NEG)) == pytest.approx(0.0)
    # accuracy has no such degeneracy
    assert float(binary_accuracy(jnp.zeros(4), ALL_NEG)) == pytest.approx(1.0)


def test_absent_classes_macro_average():
    """Macro averaging over declared-but-absent classes follows the
    reference: absent classes are excluded from the mean, not counted as
    zeros."""
    preds = jnp.asarray([0, 1, 0, 1], jnp.int32)
    target = jnp.asarray([0, 1, 0, 1], jnp.int32)
    # num_classes=4 but only classes {0, 1} appear, predicted perfectly
    val = float(multiclass_accuracy(preds, target, num_classes=4, average="macro"))
    assert val == pytest.approx(1.0)
