"""Wrapper metrics (counterpart of reference ``tests/unittests/wrappers/``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import accuracy_score, r2_score as sk_r2

from tpumetrics import MetricCollection
from tpumetrics.classification import BinaryAccuracy, MulticlassAccuracy, MulticlassRecall
from tpumetrics.regression import MeanAbsoluteError, MeanSquaredError, R2Score
from tpumetrics.utils.exceptions import TPUMetricsUserError
from tpumetrics.wrappers import (
    BootStrapper,
    ClasswiseWrapper,
    MetricTracker,
    MinMaxMetric,
    MultioutputWrapper,
    MultitaskWrapper,
)

_rng = np.random.default_rng(17)


# ------------------------------------------------------------ BootStrapper


def test_bootstrapper_statistics_converge():
    """Bootstrap mean approaches the plain metric; std is small for large n."""
    n = 2000
    preds = jnp.asarray(_rng.integers(0, 5, n))
    target = jnp.asarray(np.where(_rng.random(n) < 0.7, np.asarray(preds), _rng.integers(0, 5, n)))
    boot = BootStrapper(MulticlassAccuracy(num_classes=5), num_bootstraps=20, quantile=0.5, raw=True, seed=0)
    boot.update(preds, target)
    out = boot.compute()
    plain = accuracy_score(np.asarray(target), np.asarray(preds))
    assert abs(float(out["mean"]) - plain) < 0.03
    assert float(out["std"]) < 0.05
    assert out["raw"].shape == (20,)
    assert abs(float(out["quantile"]) - plain) < 0.03


def test_bootstrapper_multinomial_and_reset():
    boot = BootStrapper(BinaryAccuracy(), num_bootstraps=5, sampling_strategy="multinomial", seed=1)
    boot.update(jnp.asarray([1, 0, 1, 1]), jnp.asarray([1, 0, 1, 0]))
    out1 = boot.compute()
    boot.reset()
    boot.update(jnp.asarray([1, 1, 1, 1]), jnp.asarray([1, 1, 1, 1]))
    out2 = boot.compute()
    assert float(out2["mean"]) == 1.0
    assert set(out1.keys()) == {"mean", "std"}
    with pytest.raises(ValueError, match="sampling_strategy"):
        BootStrapper(BinaryAccuracy(), sampling_strategy="bad")
    with pytest.raises(ValueError, match="base metric"):
        BootStrapper(lambda x: x)


# -------------------------------------------------------- ClasswiseWrapper


def test_classwise_wrapper():
    preds = jnp.asarray([0, 1, 2, 1, 0, 2])
    target = jnp.asarray([0, 1, 1, 1, 0, 0])
    metric = ClasswiseWrapper(MulticlassAccuracy(num_classes=3, average=None))
    out = metric(preds, target)
    assert set(out.keys()) == {"multiclassaccuracy_0", "multiclassaccuracy_1", "multiclassaccuracy_2"}

    labeled = ClasswiseWrapper(
        MulticlassAccuracy(num_classes=3, average=None), labels=["horse", "fish", "dog"], prefix="acc-"
    )
    labeled.update(preds, target)
    out = labeled.compute()
    assert set(out.keys()) == {"acc-horse", "acc-fish", "acc-dog"}
    per_class = np.asarray(MulticlassAccuracy(num_classes=3, average=None)(preds, target))
    assert np.isclose(float(out["acc-horse"]), per_class[0])

    postfixed = ClasswiseWrapper(MulticlassAccuracy(num_classes=3, average=None), postfix="-acc")
    postfixed.update(preds, target)
    assert set(postfixed.compute().keys()) == {"0-acc", "1-acc", "2-acc"}


def test_classwise_in_collection():
    preds = jnp.asarray([0, 1, 2, 1, 0, 2])
    target = jnp.asarray([0, 1, 1, 1, 0, 0])
    collection = MetricCollection(
        {
            "accuracy": ClasswiseWrapper(MulticlassAccuracy(num_classes=3, average=None), ["a", "b", "c"]),
            "recall": ClasswiseWrapper(MulticlassRecall(num_classes=3, average=None), ["a", "b", "c"]),
        }
    )
    collection.update(preds, target)
    out = collection.compute()
    assert "multiclassaccuracy_a" in out and "multiclassrecall_c" in out


# ------------------------------------------------------------ MinMaxMetric


def test_minmax_metric():
    metric = MinMaxMetric(BinaryAccuracy())
    metric.update(jnp.asarray([1, 0, 1, 1]), jnp.asarray([1, 0, 1, 1]))
    out = metric.compute()
    assert float(out["raw"]) == 1.0 and float(out["max"]) == 1.0 and float(out["min"]) == 1.0
    metric.update(jnp.asarray([0, 0, 0, 0]), jnp.asarray([1, 1, 1, 1]))
    out = metric.compute()
    assert float(out["min"]) == 0.5 and float(out["max"]) == 1.0
    metric.reset()
    metric.update(jnp.asarray([1, 0]), jnp.asarray([1, 1]))
    out = metric.compute()
    assert float(out["min"]) == 0.5 and float(out["max"]) == 0.5


# ------------------------------------------------------ MultioutputWrapper


def test_multioutput_wrapper_r2():
    target = jnp.asarray([[0.5, 1.0], [-1.0, 1.0], [7.0, -6.0]])
    preds = jnp.asarray([[0.25, 0.5], [-1.0, 1.0], [8.0, -5.0]])
    r2 = MultioutputWrapper(R2Score(), num_outputs=2)
    r2.update(preds, target)
    got = np.asarray(r2.compute())
    ref = sk_r2(np.asarray(target), np.asarray(preds), multioutput="raw_values")
    assert np.allclose(got, ref, atol=1e-4)


def test_multioutput_wrapper_remove_nans():
    target = jnp.asarray([[0.5, jnp.nan], [-1.0, 1.0], [7.0, -6.0]])
    preds = jnp.asarray([[0.25, 0.5], [-1.0, 1.0], [8.0, -5.0]])
    mse = MultioutputWrapper(MeanSquaredError(), num_outputs=2)
    mse.update(preds, target)
    got = np.asarray(mse.compute())
    # column 1 drops its NaN row
    ref0 = np.mean((np.asarray(preds)[:, 0] - np.asarray(target)[:, 0]) ** 2)
    ref1 = np.mean((np.asarray(preds)[1:, 1] - np.asarray(target)[1:, 1]) ** 2)
    assert np.allclose(got, [ref0, ref1], atol=1e-5)


# -------------------------------------------------------- MultitaskWrapper


def test_multitask_wrapper():
    metrics = MultitaskWrapper({"cls": BinaryAccuracy(), "reg": MeanSquaredError()})
    preds = {"cls": jnp.asarray([0, 1, 1]), "reg": jnp.asarray([2.0, 3.0, 4.0])}
    target = {"cls": jnp.asarray([0, 1, 0]), "reg": jnp.asarray([1.0, 3.0, 4.0])}
    metrics.update(preds, target)
    out = metrics.compute()
    assert np.isclose(float(out["cls"]), 2 / 3, atol=1e-5)
    assert np.isclose(float(out["reg"]), 1 / 3, atol=1e-5)

    fwd = metrics(preds, target)
    assert set(fwd.keys()) == {"cls", "reg"}
    metrics.reset()

    with pytest.raises(ValueError, match="same keys"):
        metrics.update({"cls": preds["cls"]}, target)
    with pytest.raises(TypeError, match="to be a dict"):
        MultitaskWrapper([BinaryAccuracy()])
    with pytest.raises(TypeError, match="Metric or a MetricCollection"):
        MultitaskWrapper({"a": lambda x: x})


def test_multitask_with_collections():
    metrics = MultitaskWrapper(
        {
            "cls": MetricCollection([BinaryAccuracy()]),
            "reg": MetricCollection([MeanSquaredError(), MeanAbsoluteError()]),
        }
    )
    preds = {"cls": jnp.asarray([0, 1, 1]), "reg": jnp.asarray([2.0, 3.0, 4.0])}
    target = {"cls": jnp.asarray([0, 1, 0]), "reg": jnp.asarray([1.0, 3.0, 4.0])}
    metrics.update(preds, target)
    out = metrics.compute()
    assert "MeanSquaredError" in out["reg"] and "MeanAbsoluteError" in out["reg"]


# ------------------------------------------------------------ MetricTracker


def test_tracker_single_metric():
    tracker = MetricTracker(MulticlassAccuracy(num_classes=10))
    values = []
    for step in range(5):
        tracker.increment()
        preds = jnp.asarray(_rng.integers(0, 10, 100))
        target = jnp.asarray(_rng.integers(0, 10, 100))
        tracker.update(preds, target)
        values.append(float(tracker.compute()))
    assert tracker.n_steps == 5
    all_vals = np.asarray(tracker.compute_all())
    assert np.allclose(all_vals, values, atol=1e-6)
    best, step = tracker.best_metric(return_step=True)
    assert np.isclose(best, max(values), atol=1e-6)
    assert step == int(np.argmax(values))


def test_tracker_collection_and_minimize():
    tracker = MetricTracker(
        MetricCollection([MeanSquaredError(), MeanAbsoluteError()]), maximize=[False, False]
    )
    for _ in range(3):
        tracker.increment()
        tracker.update(jnp.asarray(_rng.random(50)), jnp.asarray(_rng.random(50)))
    res = tracker.compute_all()
    assert res["MeanSquaredError"].shape == (3,)
    best, steps = tracker.best_metric(return_step=True)
    assert set(best.keys()) == {"MeanSquaredError", "MeanAbsoluteError"}
    assert np.isclose(
        best["MeanSquaredError"], float(res["MeanSquaredError"].min()), atol=1e-6
    )


def test_tracker_guards():
    tracker = MetricTracker(BinaryAccuracy())
    with pytest.raises(TPUMetricsUserError, match="increment"):
        tracker.update(jnp.asarray([1]), jnp.asarray([1]))
    with pytest.raises(TypeError, match="Metric"):
        MetricTracker(lambda x: x)


def test_minmax_forward_accumulates():
    """forward must not destroy the base metric's accumulation."""
    metric = MinMaxMetric(BinaryAccuracy())
    metric(jnp.asarray([1, 0, 1, 1]), jnp.asarray([1, 0, 1, 1]))  # acc 1.0
    out = metric(jnp.asarray([0, 0, 0, 0]), jnp.asarray([1, 1, 0, 0]))  # batch acc 0.5
    # accumulated accuracy over both batches = 6/8
    assert np.isclose(float(out["raw"]), 0.75, atol=1e-6)
    assert float(out["max"]) == 1.0
    # min/max are registered states: present in sync machinery
    assert "min_val" in metric._defaults and "max_val" in metric._defaults


def test_tracker_maximize_validation():
    with pytest.raises(ValueError, match="single bool"):
        MetricTracker(BinaryAccuracy(), maximize=[False])
    with pytest.raises(ValueError, match="len of argument"):
        MetricTracker(MetricCollection([BinaryAccuracy(), MeanSquaredError()]), maximize=[True])
    # minimize on a single metric
    tracker = MetricTracker(MeanSquaredError(), maximize=False)
    for err in (1.0, 5.0):
        tracker.increment()
        tracker.update(jnp.asarray([err]), jnp.asarray([0.0]))
    best, step = tracker.best_metric(return_step=True)
    assert np.isclose(best, 1.0) and step == 0
