"""tpumetrics.resilience.elastic: coordinated snapshots + elastic restore.

The acceptance surface of the elastic subsystem: world-N evaluation folded
through a consistent snapshot cut and resharded onto world M (shrink AND
grow) must compute exactly what the uninterrupted single-host run computes —
bit-exact for integer/sum/list states, within 1e-6 for mean-weighted float
states — and a partial snapshot set must either raise a typed error or
degrade EXPLICITLY (flag + ledger event) under a quorum policy, never return
a silently wrong answer.  Everything runs on one CPU host at emulated world
1..4, with the ``"preempt"`` fault kind producing the partial sets.
"""

from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np
import pytest

from tpumetrics import MetricCollection
from tpumetrics.aggregation import MeanMetric
from tpumetrics.classification import (
    MulticlassAccuracy,
    MulticlassF1Score,
    MulticlassStatScores,
)
from tpumetrics.metric import Metric
from tpumetrics.parallel.backend import DistributedBackend, NoOpBackend
from tpumetrics.parallel.merge import reshard_metric_states
from tpumetrics.regression import MeanSquaredError
from tpumetrics.resilience import (
    DistributedSnapshotManager,
    ElasticRestoreError,
    Fault,
    FaultInjectionBackend,
    InconsistentCutError,
    InjectedPreemption,
    QuorumPolicy,
    config_digest,
    load_latest_cut,
    scan_cuts,
    snapshot_barrier,
)
from tpumetrics.resilience import elastic as elastic_mod
from tpumetrics.runtime import StreamingEvaluator
from tpumetrics.text import BLEUScore
from tpumetrics.utils.data import dim_zero_cat
from tpumetrics.utils.exceptions import TPUMetricsUserError


def _blocks(items, n):
    """Contiguous block sharding (preserves global order under rank-major
    concatenation — the sharding the elastic cat placement assumes)."""
    split = np.array_split(np.arange(len(items)), n)
    return [[items[int(i)] for i in idx] for idx in split]


def _class_stream(rng, n_batches, num_classes=5, max_rows=12):
    out = []
    for _ in range(n_batches):
        n = int(rng.integers(1, max_rows))
        out.append(
            (
                jnp.asarray(rng.standard_normal((n, num_classes), dtype=np.float32)),
                jnp.asarray(rng.integers(0, num_classes, n).astype(np.int32)),
            )
        )
    return out


def _roundtrip(make, stream, n, m, cat_placement="rank0"):
    """world-n evaluate → coordinated payloads → fold → reshard to world-m →
    finish the stream → fold again.  Returns (single-host value, elastic
    value)."""
    ref = make()
    for b in stream:
        ref.update(*b)
    want = ref.compute()

    k = (2 * len(stream)) // 3
    proto = make()
    ranks = [make() for _ in range(n)]
    for r, block in enumerate(_blocks(stream[:k], n)):
        for b in block:
            ranks[r].update(*b)
    folded = proto.fold_snapshot_states([mm.snapshot_state() for mm in ranks])

    news = [make() for _ in range(m)]
    for j, mm in enumerate(news):
        mm.load_snapshot_state(
            proto.reshard_snapshot_state(folded, j, m, cat_placement=cat_placement)
        )
    for j, block in enumerate(_blocks(stream[k:], m)):
        for b in block:
            news[j].update(*b)
    final = make()
    final.load_snapshot_state(proto.fold_snapshot_states([mm.snapshot_state() for mm in news]))
    return want, final.compute()


# ------------------------------------------------- fold/reshard family sweep


WORLDS = [(3, 2), (2, 4)]  # shrink and grow, emulated at world <= 4


class TestElasticRoundtripFamilies:
    """The satellite sweep: >= 6 metric families, world-N → snapshot →
    restore at world-M == single-host reference."""

    @pytest.mark.parametrize("n,m", WORLDS)
    def test_statscores_integer_states_bit_exact(self, n, m):
        rng = np.random.default_rng(0)
        stream = _class_stream(rng, 12)
        want, got = _roundtrip(
            lambda: MulticlassStatScores(num_classes=5, average="micro", validate_args=False),
            stream, n, m,
        )
        assert np.array_equal(np.asarray(want), np.asarray(got))

    @pytest.mark.parametrize("n,m", WORLDS)
    def test_mse_sum_states(self, n, m):
        rng = np.random.default_rng(1)
        stream = [
            (
                jnp.asarray(rng.standard_normal(int(sz)).astype(np.float32)),
                jnp.asarray(rng.standard_normal(int(sz)).astype(np.float32)),
            )
            for sz in rng.integers(1, 9, size=12)
        ]
        want, got = _roundtrip(MeanSquaredError, stream, n, m)
        assert float(got) == pytest.approx(float(want), rel=1e-6)

    @pytest.mark.parametrize("n,m", WORLDS)
    def test_aggregation_mean_weighted(self, n, m):
        rng = np.random.default_rng(2)
        stream = [
            (jnp.asarray(rng.standard_normal(int(sz)).astype(np.float32)),)
            for sz in rng.integers(1, 7, size=12)
        ]
        want, got = _roundtrip(MeanMetric, stream, n, m)
        assert float(got) == pytest.approx(float(want), rel=1e-6)

    @pytest.mark.parametrize("n,m", WORLDS)
    def test_text_bleu(self, n, m):
        rng = np.random.default_rng(3)
        vocab = ["the", "cat", "sat", "on", "a", "mat", "dog", "ran", "far", "away"]

        def sentence():
            return " ".join(rng.choice(vocab, size=int(rng.integers(3, 9))))

        stream = [([sentence()], [[sentence(), sentence()]]) for _ in range(12)]
        want, got = _roundtrip(lambda: BLEUScore(n_gram=2), stream, n, m)
        assert float(got) == pytest.approx(float(want), rel=1e-6)

    @pytest.mark.parametrize("n,m", WORLDS)
    def test_samplewise_list_states_order_exact(self, n, m):
        # ALL states are eager cat lists; compute is per-sample, so global
        # row ORDER must survive the resize (rank0 placement + block shards)
        rng = np.random.default_rng(4)
        stream = _class_stream(rng, 12, num_classes=3)
        want, got = _roundtrip(
            lambda: MulticlassF1Score(
                num_classes=3, average="macro", multidim_average="samplewise",
                validate_args=False,
            ),
            stream, n, m,
        )
        assert np.array_equal(np.asarray(want), np.asarray(got))

    @pytest.mark.parametrize("n,m", WORLDS)
    def test_collection_with_compute_groups(self, n, m):
        rng = np.random.default_rng(5)
        stream = _class_stream(rng, 12, num_classes=4)

        def make():
            return MetricCollection(
                {
                    "acc": MulticlassAccuracy(num_classes=4, average="micro", validate_args=False),
                    "f1": MulticlassF1Score(num_classes=4, average="macro", validate_args=False),
                }
            )

        want, got = _roundtrip(make, stream, n, m)
        for key, val in want.items():
            assert np.array_equal(np.asarray(val), np.asarray(got[key])), key

    @pytest.mark.parametrize("n,m", WORLDS)
    def test_masked_buffer_functional_states(self, n, m):
        """The bucketed-runtime shape: functional state pytrees with
        MaskedBuffer leaves fold and reshard through fold_state_dicts /
        reshard_state_dict, preserving row order and exact contents."""

        class BufferCat(Metric):
            full_state_update = False

            def __init__(self, capacity=64, **kwargs):
                super().__init__(**kwargs)
                self.add_state("value", default=[], dist_reduce_fx="cat", capacity=capacity)

            def update(self, x):
                self._append_state("value", x)

            def compute(self):
                return dim_zero_cat(self.value)

        rng = np.random.default_rng(6)
        stream = [
            jnp.asarray(rng.standard_normal(int(sz)).astype(np.float32))
            for sz in rng.integers(1, 6, size=12)
        ]
        want = np.concatenate([np.asarray(b) for b in stream])

        proto = BufferCat()
        k = 8
        states = [BufferCat().init_state() for _ in range(n)]
        for r, block in enumerate(_blocks(stream[:k], n)):
            for b in block:
                states[r] = proto.functional_update(states[r], b)
        folded = proto.fold_state_dicts(states)
        new_states = [
            proto.reshard_state_dict(folded, j, m, cat_placement="balanced") for j in range(m)
        ]
        for j, block in enumerate(_blocks(stream[k:], m)):
            for b in block:
                new_states[j] = proto.functional_update(new_states[j], b)
        final = proto.fold_state_dicts(new_states)
        from tpumetrics.buffers import materialize

        got = np.asarray(materialize(final["value"]))
        # balanced placement splits restored rows contiguously across the new
        # ranks, so the re-fold interleaves restored blocks with new data —
        # contents are exact, global order is only guaranteed by "rank0"
        assert sorted(got.tolist()) == sorted(want.tolist())
        # rank0 placement preserves exact global order end-to-end
        rank0_states = [proto.reshard_state_dict(folded, j, m) for j in range(m)]
        for j, block in enumerate(_blocks(stream[k:], m)):
            for b in block:
                rank0_states[j] = proto.functional_update(rank0_states[j], b)
        ordered = np.asarray(materialize(proto.fold_state_dicts(rank0_states)["value"]))
        assert np.array_equal(ordered, want)


class TestReshardSemantics:
    def test_update_count_folds_and_splits_additively(self):
        rng = np.random.default_rng(7)
        stream = _class_stream(rng, 6)
        make = lambda: MulticlassAccuracy(num_classes=5, average="micro", validate_args=False)  # noqa: E731
        ranks = [make() for _ in range(2)]
        for r, block in enumerate(_blocks(stream, 2)):
            for b in block:
                ranks[r].update(*b)
        proto = make()
        folded = proto.fold_snapshot_states([mm.snapshot_state() for mm in ranks])
        assert folded["update_count"] == 6
        shares = [proto.reshard_snapshot_state(folded, j, 3) for j in range(3)]
        # near-even additive split: folds back to the total, and every rank
        # reads as updated (no spurious compute-before-update warnings)
        assert [s["update_count"] for s in shares] == [2, 2, 2]
        uneven = [proto.reshard_snapshot_state(folded, j, 4) for j in range(4)]
        assert [s["update_count"] for s in uneven] == [2, 2, 1, 1]

    def test_unsupported_state_kinds_raise_typed(self):
        class CustomReduce(Metric):
            def __init__(self, **kwargs):
                super().__init__(**kwargs)
                self.add_state("v", jnp.zeros(()), dist_reduce_fx=lambda x: x.sum(0))

            def update(self, x):
                self.v = self.v + x

            def compute(self):
                return self.v

        m = CustomReduce()
        m.update(jnp.asarray(1.0))
        with pytest.raises(TPUMetricsUserError, match="custom reduce"):
            reshard_metric_states({"v": m.v}, m._reductions, 0, 2)

        class GatherArray(Metric):
            def __init__(self, **kwargs):
                super().__init__(**kwargs)
                self.add_state("v", jnp.zeros((2,)), dist_reduce_fx=None)

            def update(self, x):
                self.v = x

            def compute(self):
                return self.v

        g = GatherArray()
        with pytest.raises(TPUMetricsUserError, match="resharded"):
            reshard_metric_states({"v": g.v}, g._reductions, 0, 2)

    def test_buffer_overflow_on_rank0_placement_raises(self):
        from tpumetrics.buffers import buffer_append, create_buffer

        folded = buffer_append(create_buffer(8), jnp.arange(7.0))
        template = create_buffer(4)
        from tpumetrics.utils.data import dim_zero_cat as _cat  # reductions map

        reductions = {"value": _cat}
        with pytest.raises(TPUMetricsUserError, match="capacity"):
            reshard_metric_states(
                {"value": folded}, reductions, 0, 2, templates={"value": template}
            )
        # balanced placement spreads 7 rows over 2 ranks of capacity 4: fits
        shares = [
            reshard_metric_states(
                {"value": folded}, reductions, j, 2,
                templates={"value": template}, cat_placement="balanced",
            )
            for j in range(2)
        ]
        assert [int(s["value"].count) for s in shares] == [4, 3]

    def test_fold_rejects_mismatched_configs(self):
        a = MulticlassAccuracy(num_classes=4, average="micro", validate_args=False)
        b = MulticlassAccuracy(num_classes=6, average="micro", validate_args=False)
        with pytest.raises(TPUMetricsUserError, match="incompatible"):
            a.fold_snapshot_states([a.snapshot_state(), b.snapshot_state()])


# ------------------------------------------------------------------- barrier


class _Cohort(DistributedBackend):
    """Emulated eager cohort: this rank's object gather returns its own
    payload plus precomputed peer stamps (rank 0 is us, the rest are given —
    the test_telemetry idiom)."""

    has_object_channel = True

    def __init__(self, rank, world, peek):
        self._rank, self._world, self._peek = rank, world, peek

    def available(self):
        return True

    def world_size(self):
        return self._world

    def rank(self):
        return self._rank

    def all_gather_object(self, obj, group=None):
        return [obj if r == self._rank else self._peek(r) for r in range(self._world)]


class TestSnapshotBarrier:
    def test_agreement_is_max_proposal_and_digests_match(self):
        cfg = "c" * 40
        steps = {0: 5, 1: 7, 2: 6}
        results = [
            snapshot_barrier(
                _Cohort(r, 3, lambda p: elastic_mod.make_stamp(p, steps[p], cfg)),
                rank=r, world_size=3, step=steps[r], config=cfg,
            )
            for r in range(3)
        ]
        assert all(step == 7 for step, _ in results)
        assert len({digest for _, digest in results}) == 1

    def test_config_mismatch_names_diverging_rank(self):
        def peek(r):
            return elastic_mod.make_stamp(r, 3, "bad" if r == 2 else "good")

        with pytest.raises(InconsistentCutError, match=r"rank\(s\) \[2\]"):
            snapshot_barrier(
                _Cohort(0, 3, peek), rank=0, world_size=3, step=3, config="good"
            )

    def test_duplicate_rank_assignment_refused(self):
        # two processes misconfigured with the same snapshot_rank would
        # overwrite each other's files: the barrier must fail fast instead
        def peek(r):
            return elastic_mod.make_stamp(0 if r == 1 else r, 3, "cfg")  # rank 1 claims 0

        with pytest.raises(InconsistentCutError, match="share a snapshot_rank"):
            snapshot_barrier(
                _Cohort(0, 3, peek), rank=0, world_size=3, step=3, config="cfg"
            )

    def test_lost_stamp_refuses_cut(self):
        with pytest.raises(InconsistentCutError, match="lost the stamp"):
            snapshot_barrier(
                _Cohort(0, 2, lambda r: None), rank=0, world_size=2, step=1, config="x"
            )

    def test_world1_skips_exchange(self):
        step, digest = snapshot_barrier(
            None, rank=0, world_size=1, step=4, config="solo"
        )
        assert step == 4 and digest == elastic_mod.cut_digest(4, 1, "solo")

    def test_barrier_records_ledger_event(self):
        from tpumetrics import telemetry

        with telemetry.capture() as led:
            snapshot_barrier(None, rank=0, world_size=1, step=1, config="x")
        assert led.summary()["elastic_barriers"] == 1


class TestPreemptFault:
    def test_preempt_latches_dead(self):
        backend = FaultInjectionBackend(
            NoOpBackend(), faults=[Fault(kind="preempt", op="all_gather_object", call=1)]
        )
        assert backend.all_gather_object("a") == ["a"]  # call 0: alive
        with pytest.raises(InjectedPreemption, match="preempted"):
            backend.all_gather_object("b")  # call 1: reclaimed
        assert backend.preempted
        # LATCHED: every later collective on any op refuses too
        with pytest.raises(InjectedPreemption, match="latched"):
            backend.all_gather(jnp.zeros(2))
        with pytest.raises(InjectedPreemption):
            backend.all_reduce(jnp.zeros(2), "sum")
        assert ("all_gather_object", 1, "preempt") in backend.fired

    def test_preempt_is_deterministic_under_retries(self):
        from tpumetrics.resilience import SyncFailedError, SyncPolicy, run_guarded, sync_policy

        backend = FaultInjectionBackend(
            NoOpBackend(), faults=[Fault(kind="preempt", op="all_gather_object")]
        )
        with sync_policy(SyncPolicy(retries=2, backoff=0.001)):
            with pytest.raises(SyncFailedError, match="3 attempt"):
                run_guarded(
                    lambda: backend.all_gather_object("x"),
                    op="all_gather_object", backend=backend,
                )


# --------------------------------------------------------------- cut storage


def _write_cut(root, world, step, payload_fn, config="cfg", ranks=None, mode="eager", bases=None):
    """Write one coordinated cut by hand (what N processes would do)."""
    digest = elastic_mod.cut_digest(step, world, config)
    for r in ranks if ranks is not None else range(world):
        mgr = DistributedSnapshotManager(root, r, world, keep=None)
        meta = {
            "batches": step, "items": step, "mode": mode, "degraded": False,
            "base_batches": (bases or {}).get(r, 0), "base_items": 0,
            "elastic": mgr.elastic_meta(step, digest, config),
        }
        mgr.save(step, payload_fn(r), meta=meta)
    return digest


class TestCutDiscovery:
    def test_complete_cut_found_and_loaded(self, tmp_path):
        root = str(tmp_path)
        _write_cut(root, 3, 5, lambda r: {"v": jnp.full((2,), float(r))})
        cuts = scan_cuts(root)
        assert len(cuts) == 1 and cuts[0].missing == () and cuts[0].world_size == 3
        loaded = load_latest_cut(root, template={"v": jnp.zeros(2)})
        assert not loaded.degraded and sorted(loaded.payloads) == [0, 1, 2]
        assert float(loaded.payloads[2]["v"][0]) == 2.0

    def test_incomplete_latest_falls_back_to_older_complete(self, tmp_path):
        root = str(tmp_path)
        _write_cut(root, 2, 3, lambda r: {"v": jnp.zeros(1)})
        _write_cut(root, 2, 7, lambda r: {"v": jnp.ones(1)}, ranks=[0])  # rank 1 preempted
        loaded = load_latest_cut(root, template={"v": jnp.zeros(1)})
        assert loaded.step == 3 and not loaded.degraded

    def test_only_incomplete_raises_typed(self, tmp_path):
        root = str(tmp_path)
        _write_cut(root, 3, 4, lambda r: {"v": jnp.zeros(1)}, ranks=[0, 2])
        with pytest.raises(InconsistentCutError, match=r"missing rank\(s\) \[1\]"):
            load_latest_cut(root, template={"v": jnp.zeros(1)})

    def test_quorum_degrades_explicitly_with_ledger_event(self, tmp_path):
        from tpumetrics import telemetry

        root = str(tmp_path)
        _write_cut(root, 2, 3, lambda r: {"v": jnp.zeros(1)})
        _write_cut(root, 4, 9, lambda r: {"v": jnp.full((1,), float(r))}, ranks=[0, 1, 3])
        with telemetry.capture() as led:
            loaded = load_latest_cut(
                root, template={"v": jnp.zeros(1)}, quorum=QuorumPolicy(min_ranks=3)
            )
        assert loaded.step == 9 and loaded.degraded and loaded.missing == (2,)
        assert led.summary()["elastic_degraded_cuts"] == 1
        # a tighter quorum rejects the partial set -> older complete cut wins
        loaded2 = load_latest_cut(
            root, template={"v": jnp.zeros(1)}, quorum=QuorumPolicy(min_fraction=1.0)
        )
        assert loaded2.step == 3 and not loaded2.degraded

    def test_corrupt_member_counts_as_missing(self, tmp_path):
        root = str(tmp_path)
        _write_cut(root, 2, 3, lambda r: {"v": jnp.zeros(1)})
        _write_cut(root, 2, 6, lambda r: {"v": jnp.ones(1)})
        victim = os.path.join(root, "rank-00001", "snapshot-6.npz")
        with open(victim, "r+b") as fh:
            fh.truncate(os.path.getsize(victim) // 2)
        loaded = load_latest_cut(root, template={"v": jnp.zeros(1)})
        assert loaded.step == 3  # torn member invalidated the newest cut

    def test_same_step_different_worlds_stay_separate_cuts(self, tmp_path):
        # stale rank dirs from a BIGGER former world can hold a snapshot at
        # the same step as a current smaller-world cut; the cut digest keeps
        # the sets apart (per-rank step monotonicity guarantees overlapping
        # ranks never reuse a step, so only disjoint stale ranks can collide)
        root = str(tmp_path)
        _write_cut(root, 4, 5, lambda r: {"v": jnp.ones(1)}, ranks=[2, 3])  # stale, incomplete
        _write_cut(root, 2, 5, lambda r: {"v": jnp.zeros(1)})
        loaded = load_latest_cut(root, template={"v": jnp.zeros(1)})
        assert loaded.world_size == 2 and not loaded.degraded
        assert len(scan_cuts(root)) == 2


# --------------------------------------------------- evaluator restore_elastic


def _make_acc():
    return MulticlassAccuracy(num_classes=5, average="micro", validate_args=False)


def _elastic_evaluators(root, make, world, digest, buckets=8, backend_for=None):
    """One evaluator per emulated rank.  The cohort backend serves PEER
    stamps from the shared ``props`` dict, which :func:`_record_proposals`
    fills for every rank BEFORE any rank writes — mirroring a real
    concurrent barrier, where all proposals are gathered before any save
    can bump a rank's on-disk step."""
    props: dict = {}

    def peek(r):
        return elastic_mod.make_stamp(r, props[r], digest)

    evs = []
    for r in range(world):
        backend = backend_for(r, peek) if backend_for else _Cohort(r, world, peek)
        evs.append(
            StreamingEvaluator(
                make(), buckets=buckets, snapshot_dir=root,
                snapshot_rank=r, snapshot_world_size=world, barrier_backend=backend,
            )
        )
    return evs, props


def _record_proposals(evs, props):
    for ev in evs:
        ev.flush()
    for r, ev in enumerate(evs):
        props[r] = ev._barrier_proposal()


class TestStreamingEvaluatorElastic:
    def _feed_and_cut(self, evs, props, batches_per_rank):
        for ev, block in zip(evs, batches_per_rank):
            for b in block:
                ev.submit(*b)
        _record_proposals(evs, props)
        for ev in evs:
            ev.snapshot()

    @pytest.mark.parametrize("n,m", [(2, 3), (3, 1)])
    def test_resize_roundtrip_matches_uninterrupted(self, tmp_path, n, m):
        rng = np.random.default_rng(11)
        stream = _class_stream(rng, 12)
        ref = _make_acc()
        for b in stream:
            ref.update(*b)
        want = float(ref.compute())

        root = str(tmp_path)
        digest = config_digest(_make_acc())
        evs, props = _elastic_evaluators(root, _make_acc, n, digest)
        k = 8
        self._feed_and_cut(evs, props, _blocks(stream[:k], n))
        for ev in evs:
            ev.close(drain=False)  # preemption: the whole slice goes away

        news, _ = _elastic_evaluators(root, _make_acc, m, digest)
        infos = [ev.restore_elastic() for ev in news]
        assert all(info["batches"] == k and info["from_world"] == n for info in infos)
        assert all(not info["degraded"] for info in infos)
        for ev, block in zip(news, _blocks(stream[k:], m)):
            for b in block:
                ev.submit(*b)
        for ev in news:
            ev.flush()
        proto = _make_acc()
        folded = proto.fold_state_dicts([ev._state for ev in news])
        got = float(proto.functional_compute(folded))
        assert got == want  # bit-identical to the uninterrupted run
        for ev in news:
            ev.close(drain=False)

    def test_preempted_rank_partial_cut_falls_back_then_quorum_degrades(self, tmp_path):
        rng = np.random.default_rng(12)
        stream = _class_stream(rng, 12)
        root = str(tmp_path)
        digest = config_digest(_make_acc())

        def backend_for(r, peek):
            inner = _Cohort(r, 2, peek)
            if r == 1:  # rank 1 is reclaimed at its SECOND barrier
                return FaultInjectionBackend(
                    inner, faults=[Fault(kind="preempt", op="all_gather_object", call=1)]
                )
            return FaultInjectionBackend(inner)

        evs, props = _elastic_evaluators(
            root, _make_acc, 2, digest, backend_for=backend_for
        )
        self._feed_and_cut(evs, props, _blocks(stream[:6], 2))  # cut 1: complete
        for ev, block in zip(evs, _blocks(stream[6:10], 2)):
            for b in block:
                ev.submit(*b)
        _record_proposals(evs, props)
        evs[0].snapshot()  # cut 2: rank 0 writes...
        with pytest.raises(InjectedPreemption):
            evs[1].snapshot()  # ...rank 1 dies mid-barrier -> partial set
        for ev in evs:
            ev.close(drain=False)

        # no quorum: the partial cut 2 is skipped, complete cut 1 restores
        ev_new = StreamingEvaluator(
            _make_acc(), buckets=8, snapshot_dir=root,
            snapshot_rank=0, snapshot_world_size=1,
        )
        info = ev_new.restore_elastic()
        assert info["batches"] == 6 and not info["degraded"]
        for b in stream[6:]:
            ev_new.submit(*b)
        got = float(ev_new.compute())
        ref = _make_acc()
        for b in stream:
            ref.update(*b)
        assert got == float(ref.compute())
        ev_new.close()

        # with a quorum: the fresher partial cut restores, DEGRADED + event
        from tpumetrics import telemetry

        ev_q = StreamingEvaluator(
            _make_acc(), buckets=8, snapshot_dir=root,
            snapshot_rank=0, snapshot_world_size=1,
        )
        with telemetry.capture() as led:
            info_q = ev_q.restore_elastic(quorum=QuorumPolicy(min_ranks=1))
        assert info_q["degraded"] and info_q["missing_ranks"] == [1]
        # fresher than the complete cut (local step 5 > 3), but the adopted
        # position only counts the PRESENT rank's data — rank 1's batches
        # are absent from the fold, visibly, not silently
        assert info_q["step"] == 5 and info_q["batches"] == 5
        assert ev_q.stats()["degraded"]
        assert led.summary()["elastic_degraded_cuts"] == 1
        assert led.summary()["elastic_restores"] == 1
        ev_q.close()

    def test_eager_list_state_evaluator_resize_order_exact(self, tmp_path):
        def make():
            return MulticlassF1Score(
                num_classes=3, average="macro", multidim_average="samplewise",
                validate_args=False,
            )

        rng = np.random.default_rng(13)
        stream = _class_stream(rng, 9, num_classes=3)
        ref = make()
        for b in stream:
            ref.update(*b)
        want = np.asarray(ref.compute())

        root = str(tmp_path)
        digest = config_digest(make())
        evs, props = _elastic_evaluators(
            root, make, 3, digest,
            buckets=None,  # eager mode: list states cannot take padding
        )
        self._feed_and_cut(evs, props, _blocks(stream[:6], 3))
        for ev in evs:
            ev.close(drain=False)

        ev_new = StreamingEvaluator(
            make(), snapshot_dir=root, snapshot_rank=0, snapshot_world_size=1
        )
        info = ev_new.restore_elastic()
        assert info["from_world"] == 3 and info["batches"] == 6
        for b in stream[6:]:
            ev_new.submit(*b)
        got = np.asarray(ev_new.compute())
        assert np.array_equal(got, want)
        ev_new.close()

    def test_restore_elastic_guards(self, tmp_path):
        root = str(tmp_path)
        ev = StreamingEvaluator(_make_acc(), buckets=8, snapshot_dir=root)
        with pytest.raises(TPUMetricsUserError, match="snapshot_rank"):
            ev.restore_elastic()
        ev.close()

        ev2 = StreamingEvaluator(
            _make_acc(), buckets=8, snapshot_dir=root,
            snapshot_rank=0, snapshot_world_size=1,
        )
        assert ev2.restore_elastic() is None  # fresh root: nothing to adopt
        ev2.submit(*_class_stream(np.random.default_rng(0), 1)[0])
        ev2.flush()
        with pytest.raises(TPUMetricsUserError, match="double-count"):
            ev2.restore_elastic()
        ev2.close()

    def test_snapshot_every_with_multi_rank_elastic_refused(self, tmp_path):
        # the auto cadence triggers on LOCAL batch counts, which uneven
        # stream shards make non-lockstep: the unmatched barrier would hang
        with pytest.raises(ValueError, match="lockstep"):
            StreamingEvaluator(
                _make_acc(), buckets=8, snapshot_dir=str(tmp_path),
                snapshot_rank=0, snapshot_world_size=2, snapshot_every=10,
            )
        # world-1 elastic keeps the auto cadence (nobody to diverge from)
        ev = StreamingEvaluator(
            _make_acc(), buckets=8, snapshot_dir=str(tmp_path),
            snapshot_rank=0, snapshot_world_size=1, snapshot_every=10,
        )
        ev.close()

    def test_mixed_base_cut_raises_before_touching_state(self, tmp_path):
        """A cut whose members disagree on the elastic base is rejected
        BEFORE any state is adopted: catching the typed error must leave
        the evaluator fresh (no half-restored state to double-count on)."""
        root = str(tmp_path)
        donor = _make_acc()
        donor.update(*_class_stream(np.random.default_rng(3), 1)[0])
        cfg = config_digest(_make_acc())
        _write_cut(
            root, 2, 5, lambda r: donor.snapshot_state(), config=cfg,
            bases={0: 0, 1: 3},  # rank 1 was crash-restored from another base
        )
        ev = StreamingEvaluator(
            _make_acc(), snapshot_dir=root, snapshot_rank=0, snapshot_world_size=1
        )
        with pytest.raises(InconsistentCutError, match="different\\s+elastic bases"):
            ev.restore_elastic()
        assert ev.stats()["batches"] == 0
        assert ev._metric._update_count == 0  # state untouched
        ev.close()

    def test_restore_elastic_config_change_raises(self, tmp_path):
        root = str(tmp_path)
        ev = StreamingEvaluator(
            _make_acc(), buckets=8, snapshot_dir=root,
            snapshot_rank=0, snapshot_world_size=1,
        )
        ev.submit(*_class_stream(np.random.default_rng(1), 1)[0])
        ev.flush()
        ev.snapshot()
        ev.close(drain=False)
        other = StreamingEvaluator(
            MulticlassAccuracy(num_classes=7, average="micro", validate_args=False),
            buckets=8, snapshot_dir=root, snapshot_rank=0, snapshot_world_size=1,
        )
        with pytest.raises(ElasticRestoreError):
            other.restore_elastic()
        other.close()

    def test_second_resize_totals_do_not_double_count(self, tmp_path):
        """Two successive resizes: the elastic base bookkeeping must not
        re-count the pre-resize prefix once per rank at the second fold."""
        rng = np.random.default_rng(14)
        stream = _class_stream(rng, 12)
        root = str(tmp_path)
        digest = config_digest(_make_acc())

        ev0 = StreamingEvaluator(
            _make_acc(), buckets=8, snapshot_dir=root,
            snapshot_rank=0, snapshot_world_size=1,
        )
        for b in stream[:4]:
            ev0.submit(*b)
        ev0.flush()
        ev0.snapshot()
        ev0.close(drain=False)

        # resize 1 -> 2, continue, cut again
        evs, props = _elastic_evaluators(root, _make_acc, 2, digest)
        infos = [ev.restore_elastic() for ev in evs]
        assert all(i["batches"] == 4 for i in infos)
        for ev, block in zip(evs, _blocks(stream[4:8], 2)):
            for b in block:
                ev.submit(*b)
        _record_proposals(evs, props)
        for ev in evs:
            ev.snapshot()
        for ev in evs:
            ev.close(drain=False)

        # resize 2 -> 1: the adopted position must be 8, not 4 + 2*4
        ev_final = StreamingEvaluator(
            _make_acc(), buckets=8, snapshot_dir=root,
            snapshot_rank=0, snapshot_world_size=1,
        )
        info = ev_final.restore_elastic()
        assert info["batches"] == 8, info
        for b in stream[8:]:
            ev_final.submit(*b)
        got = float(ev_final.compute())
        ref = _make_acc()
        for b in stream:
            ref.update(*b)
        assert got == float(ref.compute())
        ev_final.close()

    def test_snapshot_after_degraded_restore_onto_stale_rank_dir(self, tmp_path):
        """Regression: a quorum-degraded restore can adopt a global position
        LOWER than a reused rank directory's last on-disk step (the lost
        rank carried most of the stream).  The barrier proposal is floored
        past the stale step, so coordinated snapshots keep working instead
        of failing the per-rank monotonic check forever."""
        rng = np.random.default_rng(15)
        stream = _class_stream(rng, 8)
        root = str(tmp_path)
        # world 2, rank 0 drains 6 batches, rank 1 drains 2 -> cut step 6
        digest = config_digest(_make_acc())
        evs, props = _elastic_evaluators(root, _make_acc, 2, digest)
        for b in stream[:6]:
            evs[0].submit(*b)
        for b in stream[6:8]:
            evs[1].submit(*b)
        _record_proposals(evs, props)
        for ev in evs:
            ev.snapshot()
        for ev in evs:
            ev.close(drain=False)
        # rank 0's snapshot (6 of the 8 batches) is lost with its host
        import shutil

        shutil.rmtree(os.path.join(root, "rank-00000"))
        ev_new = StreamingEvaluator(
            _make_acc(), buckets=8, snapshot_dir=root,
            snapshot_rank=0, snapshot_world_size=1,
        )
        info = ev_new.restore_elastic(quorum=QuorumPolicy(min_ranks=1))
        assert info["degraded"] and info["batches"] == 2  # only rank 1 folded
        ev_new.submit(*stream[0])
        ev_new.flush()
        ev_new.snapshot()  # must not raise SnapshotError (non-monotonic)
        ev_new.close()

    def test_new_world_cut_at_same_position_is_complete(self, tmp_path):
        """Regression: after a resize, the first coordinated snapshot can
        land at the same stream position as the pre-resize cut.  The save
        must still write THIS world's cut member (never reuse the old
        world's step-equal file), or the new cut is permanently missing the
        rank."""
        rng = np.random.default_rng(16)
        stream = _class_stream(rng, 4)
        root = str(tmp_path)
        ev0 = StreamingEvaluator(
            _make_acc(), buckets=8, snapshot_dir=root,
            snapshot_rank=0, snapshot_world_size=1,
        )
        for b in stream:
            ev0.submit(*b)
        ev0.flush()
        ev0.snapshot()
        ev0.close(drain=False)

        digest = config_digest(_make_acc())
        evs, props = _elastic_evaluators(root, _make_acc, 2, digest)
        for ev in evs:
            assert ev.restore_elastic()["batches"] == 4
        _record_proposals(evs, props)
        for ev in evs:
            ev.snapshot()  # establish a world-2 base WITHOUT new progress
        for ev in evs:
            ev.close(drain=False)
        complete_world2 = [
            c for c in scan_cuts(root) if c.world_size == 2 and not c.missing
        ]
        assert complete_world2, [
            (c.step, c.world_size, c.missing) for c in scan_cuts(root)
        ]
        # and the fresh world-2 cut restores at the same global position
        ev_check = StreamingEvaluator(
            _make_acc(), buckets=8, snapshot_dir=root,
            snapshot_rank=0, snapshot_world_size=1,
        )
        assert ev_check.restore_elastic()["batches"] == 4
        ev_check.close()

    def test_mode_mismatch_is_typed_not_corruption(self, tmp_path):
        """Regression: a bucketed cut has no reconstruction skeleton; an
        eager-mode restore must raise the typed mode-mismatch error, not
        misclassify every member as a torn file and fall back silently."""
        root = str(tmp_path)
        ev = StreamingEvaluator(
            _make_acc(), buckets=8, snapshot_dir=root,
            snapshot_rank=0, snapshot_world_size=1,
        )
        ev.submit(*_class_stream(np.random.default_rng(2), 1)[0])
        ev.flush()
        ev.snapshot()
        ev.close(drain=False)
        eager = StreamingEvaluator(
            _make_acc(), snapshot_dir=root, snapshot_rank=0, snapshot_world_size=1
        )
        with pytest.raises(ElasticRestoreError, match="bucketed"):
            eager.restore_elastic()
        eager.close()
