"""Fixed-capacity masked buffer machinery (jit-safe cat/ragged states).

The VERDICT r1 acceptance case lives here: ranks contributing **different
valid row counts inside shard_map** whose merged metric matches sklearn —
the static-shape replacement of the reference's pad-gather-trim
(reference utilities/distributed.py:135-147) and `all_gather_object` ragged
sync (reference detection/mean_ap.py:994-1024).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tests.helpers.testers import shard_map
from tpumetrics.buffers import (
    MaskedBuffer,
    buffer_all_gather,
    buffer_append,
    buffer_merge,
    buffer_overflowed,
    create_buffer,
    masked_values,
    materialize,
)
from tpumetrics.metric import Metric
from tpumetrics.parallel import AxisBackend
from tpumetrics.parallel.merge import merge_metric_states


from tests.conftest import cpu_mesh as _mesh  # noqa: E402 — shared virtual-device mesh


class MaskedCatAUROC(Metric):
    """Exact-AUROC metric over masked cat states (a metric-author example of
    the fixed-capacity machinery: masked appends, eager-exact compute)."""

    def __init__(self, capacity: int = 256, **kwargs):
        super().__init__(**kwargs)
        self.add_state("preds", default=[], dist_reduce_fx="cat", capacity=capacity)
        self.add_state(
            "target", default=[], dist_reduce_fx="cat", capacity=capacity, feature_dtype=jnp.int32
        )

    def update(self, preds, target, valid=None):
        self._append_state("preds", preds, valid=valid)
        self._append_state("target", target, valid=valid)

    def compute(self):
        from tpumetrics.functional.classification import binary_auroc

        from tpumetrics.utils.data import dim_zero_cat

        return binary_auroc(dim_zero_cat(self.preds), dim_zero_cat(self.target), thresholds=None)


def test_append_materialize_roundtrip():
    buf = create_buffer(10, (), jnp.float32)
    buf = buffer_append(buf, jnp.asarray([1.0, 2.0, 3.0]))
    buf = buffer_append(buf, jnp.asarray([4.0]))
    assert int(buf.count) == 4
    np.testing.assert_allclose(np.asarray(materialize(buf)), [1, 2, 3, 4])


def test_masked_append_drops_invalid_rows():
    buf = create_buffer(10)
    batch = jnp.asarray([1.0, 2.0, 3.0, 4.0])
    buf = buffer_append(buf, batch, valid=jnp.asarray([True, False, True, False]))
    np.testing.assert_allclose(np.asarray(materialize(buf)), [1, 3])
    # appends keep packing contiguously
    buf = buffer_append(buf, batch, valid=jnp.asarray([False, True, False, True]))
    np.testing.assert_allclose(np.asarray(materialize(buf)), [1, 3, 2, 4])


def test_overflow_drops_and_flags():
    buf = create_buffer(3)
    buf = buffer_append(buf, jnp.asarray([1.0, 2.0]))
    buf = buffer_append(buf, jnp.asarray([3.0, 4.0]))  # 4th row dropped
    assert int(buf.count) == 3
    assert bool(buffer_overflowed(buf))
    np.testing.assert_allclose(np.asarray(materialize(buf)), [1, 2, 3])


def test_append_under_jit_static_shapes():
    buf = create_buffer(8, (2,), jnp.float32)

    @jax.jit
    def step(b, x, valid):
        return buffer_append(b, x, valid=valid)

    x = jnp.arange(6.0).reshape(3, 2)
    buf = step(buf, x, jnp.asarray([True, True, False]))
    buf = step(buf, x + 10, jnp.asarray([False, True, True]))
    np.testing.assert_allclose(np.asarray(materialize(buf)), [[0, 1], [2, 3], [12, 13], [14, 15]])


def test_buffer_merge_eager_matches_union():
    b1 = buffer_append(create_buffer(5), jnp.asarray([1.0, 2.0]))
    b2 = buffer_append(create_buffer(5), jnp.asarray([3.0]))
    b3 = create_buffer(5)  # empty rank
    merged = buffer_merge([b1, b2, b3])
    np.testing.assert_allclose(np.asarray(materialize(merged)), [1, 2, 3])
    vals, mask = masked_values(merged)
    assert vals.shape == (15,) and int(mask.sum()) == 3


@pytest.mark.parametrize("world_size", [2, 4, 8])
def test_uneven_shard_sync_inside_shard_map_matches_sklearn(world_size):
    """Each rank contributes a DIFFERENT, data-dependent number of valid rows
    inside shard_map; the in-trace gather+mask sync must merge them exactly
    (VERDICT r1 'Done' criterion for task 2)."""
    from sklearn.metrics import roc_auc_score

    per_dev = 20  # >= 3 + 2*7 so every rank's request fits its shard
    cap = 64
    metric = MaskedCatAUROC(capacity=cap)
    mesh = _mesh(world_size)

    rng = np.random.default_rng(11)
    preds = jnp.asarray(rng.random((world_size * per_dev,)), dtype=jnp.float32)
    target = jnp.asarray(rng.integers(0, 2, (world_size * per_dev,)), dtype=jnp.int32)

    def run(p, t):
        r = jax.lax.axis_index("r")
        # rank r keeps 3 + 2r rows — uneven by construction
        valid = jnp.arange(per_dev) < (3 + 2 * r)
        state = metric.init_state()
        state = metric.functional_update(state, p, t, valid=valid)
        return metric.sync_state(state, AxisBackend("r"))

    synced = jax.jit(shard_map(run, mesh=mesh, in_specs=(P("r"), P("r")), out_specs=P()))(preds, target)

    assert isinstance(synced["preds"], MaskedBuffer)
    assert synced["preds"].values.shape == (world_size * cap,)
    assert int(synced["preds"].count) == sum(3 + 2 * r for r in range(world_size))

    result = metric.functional_compute(synced)

    keep = np.concatenate(
        [np.arange(r * per_dev, r * per_dev + 3 + 2 * r) for r in range(world_size)]
    )
    ref = roc_auc_score(np.asarray(target)[keep], np.asarray(preds)[keep])
    assert np.allclose(np.asarray(result), ref, atol=1e-6), (float(result), ref)


def test_uneven_emulated_rank_merge_matches_sklearn():
    """Same criterion on the eager (DCN/emulated-rank) path via merge_metric_states."""
    from sklearn.metrics import roc_auc_score

    rng = np.random.default_rng(5)
    metric = MaskedCatAUROC(capacity=32)
    replicas = [MaskedCatAUROC(capacity=32) for _ in range(3)]
    all_p, all_t = [], []
    states = []
    for r, m in enumerate(replicas):
        n = 4 + 3 * r
        p = jnp.asarray(rng.random((n,)), dtype=jnp.float32)
        t = jnp.asarray(rng.integers(0, 2, (n,)), dtype=jnp.int32)
        state = m.functional_update(m.init_state(), p, t)
        states.append(state)
        all_p.append(np.asarray(p))
        all_t.append(np.asarray(t))

    merged = merge_metric_states(states, metric._reductions)
    result = metric.functional_compute(merged)
    ref = roc_auc_score(np.concatenate(all_t), np.concatenate(all_p))
    assert np.allclose(np.asarray(result), ref, atol=1e-6)


def test_forward_reduce_merge_with_buffers():
    """forward-style merge of a batch state into a global buffer state."""
    metric = MaskedCatAUROC(capacity=16)
    g = metric.functional_update(metric.init_state(), jnp.asarray([0.9, 0.1]), jnp.asarray([1, 0]))
    b = metric.functional_update(metric.init_state(), jnp.asarray([0.8]), jnp.asarray([1]))
    from tpumetrics.buffers import buffer_extend

    merged = buffer_extend(g["preds"], b["preds"])
    np.testing.assert_allclose(np.asarray(materialize(merged)), [0.9, 0.1, 0.8], rtol=1e-6)
