"""Device-side observability (ISSUE 14): program profiles, in-trace state
health, HBM accounting, Perfetto export, and cross-rank straggler timelines.

The acceptance spine lives in ``TestServiceAcceptance``: a 2-tenant service
run with health probes armed exports (a) a Perfetto trace that validates
round-trip with both tenants' batch spans, compile marks, and device
(dispatch) slices present, and (b) after one tenant's stream is fed an
``inf``, a ``state_health`` ledger event + nonzero
``tpumetrics_state_nonfinite_total`` for that tenant BEFORE ``compute()``,
with the neighbor tenant bit-identical to an unprobed run.  The straggler
acceptance (a merged 2-rank timeline naming the deliberately-delayed rank)
runs over synthesized per-rank JSONL in ``TestTimeline`` — the same files a
soak writes, with a controlled delay.
"""

from __future__ import annotations

import collections
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpumetrics.aggregation import MeanMetric
from tpumetrics.classification import MulticlassAccuracy
from tpumetrics.parallel.fuse_update import FusedCollectionStep
from tpumetrics.runtime import EvaluationService, StreamingEvaluator
from tpumetrics.telemetry import device, export, health, instruments, ledger, spans, timeline, xla


@pytest.fixture(autouse=True)
def _device_observability_hygiene():
    """Every test starts and ends with the device layer OFF and empty (the
    test-local mirrors of the observability-suite hygiene): profiling
    disabled + registry cleared, spans off, attribution off, global ledger
    off."""
    yield
    device.disable_device_profiles()
    device.reset_device_profiles()
    spans.disable()
    spans.reset()
    xla.disable_compile_attribution()
    ledger.disable()
    export.disable_flight_recorder()


def _acc(classes=4):
    return MulticlassAccuracy(num_classes=classes, average="micro", validate_args=False)


def _batch(classes=4, seed=0, rows=5):
    rng = np.random.default_rng(seed)
    return (
        jnp.asarray(rng.standard_normal((rows, classes)), jnp.float32),
        jnp.asarray(rng.integers(0, classes, rows), jnp.int32),
    )


# ------------------------------------------------------------- health: units


class TestHealthProbeUnits:
    def test_float_nan_inf_saturation_counts(self):
        arr = jnp.asarray([1.0, jnp.nan, jnp.inf, -jnp.inf, 2.0], jnp.float32)
        vec = np.asarray(health.probe_tree(arr))
        assert vec.tolist() == [1, 2, 0]

    def test_float_saturation_near_dtype_max(self):
        top = float(np.finfo(np.float32).max)
        arr = jnp.asarray([top, -top, top * 0.5, 1.0], jnp.float32)
        vec = np.asarray(health.probe_tree(arr))
        assert vec.tolist() == [0, 0, 2]  # finite-but-at-the-edge only

    def test_int_saturation_at_dtype_bounds(self):
        ii = np.iinfo(np.int32)
        arr = jnp.asarray([0, ii.max, ii.min, 7], jnp.int32)
        vec = np.asarray(health.probe_tree(arr))
        assert vec.tolist() == [0, 0, 2]

    def test_bool_and_nonarray_probe_as_zero(self):
        tree = health.probe_tree({"flag": jnp.asarray([True, False]), "label": "x"})
        assert np.asarray(tree["flag"]).tolist() == [0, 0, 0]
        assert np.asarray(tree["label"]).tolist() == [0, 0, 0]

    def test_packed_matches_tree_and_paths(self):
        state = {
            "b": {"y": jnp.asarray([jnp.inf]), "x": jnp.asarray([1.0])},
            "a": jnp.asarray([jnp.nan, 2.0]),
        }
        packed = np.asarray(health.probe_packed(state))
        paths = health.state_paths(state)
        assert paths == ["a", "b/x", "b/y"]  # sorted recursion order
        flat = health.flatten(health.probe_tree(state))
        assert [p for p, _ in flat] == paths
        for i, (_path, vec) in enumerate(flat):
            assert packed[i].tolist() == np.asarray(vec).tolist()

    def test_summarize_packed_and_tree_agree(self):
        state = {"m": jnp.asarray([jnp.nan, jnp.inf, 1.0])}
        via_tree = health.summarize(health.probe_tree(state))
        via_packed = health.summarize(
            health.probe_packed(state), health.state_paths(state)
        )
        assert via_tree == via_packed
        assert via_tree["nonfinite_total"] == 2
        assert via_tree["per_state"]["m"] == {
            "nan": 1, "inf": 1, "saturated": 0, "nonfinite": 2,
        }

    def test_masked_buffer_state_probes_per_field(self):
        """NamedTuple state nodes (the MaskedBuffer kind backing
        capacity-declared list states — PR 12's packed detection rows) probe
        per FIELD with sharding-convention paths; the probed evaluator runs
        end to end on such a metric (regression: the generator-rebuild form
        crashed MaskedBuffer's positional constructor)."""
        from tpumetrics.aggregation import CatMetric

        m = CatMetric()
        m.set_state_capacity("value", 16)
        s = m.init_state()
        assert health.state_paths(s) == ["value/values", "value/count", "value/requested"]
        s = m.functional_update(s, jnp.asarray([1.0, np.inf]))
        summ = health.summarize(health.probe_packed(s), health.state_paths(s))
        assert summ["per_state"]["value/values"]["inf"] == 1

        # the probed COMPILED step over the buffer state (where the
        # generator-rebuild crash lived, at trace time inside _finish)
        m2 = CatMetric()
        m2.set_state_capacity("value", 16)
        step = FusedCollectionStep(m2, donate=False, health_probe=True)
        s2, h = step.update(m2.init_state(), jnp.asarray([2.0, 3.0]))
        summ2 = health.summarize(h, health.state_paths(s2))
        assert summ2["nonfinite_total"] == 0
        assert "value/values" in summ2["per_state"]

    def test_summarize_none_is_all_zero(self):
        assert health.summarize(None) == {
            "per_state": {}, "nonfinite_total": 0, "saturated_total": 0,
        }


# ----------------------------------------------------------- health: parity


class TestHealthProbeParity:
    def test_probed_step_state_is_bit_identical(self):
        """THE parity contract: arming the probe changes not one state bit,
        on both the update and the masked_update program."""
        preds, target = _batch(rows=12, seed=3)
        plain = FusedCollectionStep(_acc())
        probed = FusedCollectionStep(_acc(), health_probe=True)
        s_plain = plain.update(plain.init_state(), preds, target)
        s_probed, h = probed.update(probed.init_state(), preds, target)
        for key in s_plain:
            assert np.array_equal(np.asarray(s_plain[key]), np.asarray(s_probed[key])), key
        n_valid = jnp.asarray(12, jnp.int32)
        s_plain2 = plain.masked_update(s_plain, (preds, target), n_valid, 16)
        s_probed2, h2 = probed.masked_update(s_probed, (preds, target), n_valid, 16)
        for key in s_plain2:
            assert np.array_equal(np.asarray(s_plain2[key]), np.asarray(s_probed2[key])), key
        # the probe output is the packed (N, 3) counter array, all clean
        assert np.asarray(h2).shape == (len(health.state_paths(s_probed2)), 3)
        assert int(np.asarray(h2).sum()) == 0

    def test_probe_stays_on_device(self):
        """The probed step's health output is a device array — nothing in
        the dispatch path fetched it (zero extra device→host transfers)."""
        preds, target = _batch(rows=8, seed=1)
        probed = FusedCollectionStep(_acc(), health_probe=True)
        with jax.transfer_guard_device_to_host("disallow"):
            s, h = probed.update(probed.init_state(), preds, target)
            s, h = probed.update(s, preds, target)
        assert isinstance(h, jax.Array)

    def test_megabatch_refuses_probe(self):
        probed = FusedCollectionStep(_acc(), health_probe=True)
        s = probed.init_state()
        with pytest.raises(Exception, match="health_probe"):
            probed.megabatch_update([s], [(jnp.zeros((4, 4)),)], [4], 4)


# ------------------------------------------------------- evaluator integration


class TestEvaluatorHealth:
    def test_requires_buckets(self):
        with pytest.raises(ValueError, match="health_probe"):
            StreamingEvaluator(_acc(), health_probe=True)

    def test_clean_stream_reads_zero(self):
        ev = StreamingEvaluator(_acc(), buckets=[8], health_probe=True)
        with ev:
            ev.submit(*_batch(seed=0))
            ev.flush()
            st = ev.stats()
        h = st["device"]["health"]
        assert h is not None and h["nonfinite_total"] == 0
        assert set(h["per_state"]) == {"fn", "fp", "tn", "tp"}

    def test_poisoned_stream_pages_before_compute(self):
        """Feed an inf: stats() after flush — BEFORE any compute() — must
        surface a nonzero non-finite count, exactly ONE state_health ledger
        event, and the per-(stream, state) gauge series."""
        ledger.enable()
        ledger.reset()
        ev = StreamingEvaluator(MeanMetric(), buckets=[8], health_probe=True)
        try:
            ev.submit(jnp.asarray([1.0, 2.0]))
            ev.submit(jnp.asarray([np.inf, 3.0]))
            ev.flush()
            stream = ev._stream
            st = ev.stats()
            h = st["device"]["health"]
            assert h["nonfinite_total"] >= 1
            # the bucketed masked path's delta correction turns the inf into
            # nan (inf − inf); either way mean_value reads non-finite
            assert h["per_state"]["mean_value"]["nonfinite"] >= 1
            events = [r for r in ledger.get_ledger().records if r.kind == "state_health"]
            assert len(events) == 1
            assert events[0].extra["stream"] == stream
            assert events[0].extra["state"] == "mean_value"
            gauge = instruments.gauge(
                instruments.STATE_NONFINITE, labels=("stream", "state")
            )
            assert gauge.value(stream, "mean_value") >= 1
            # a second read latches: no duplicate event
            ev.stats()
            events = [r for r in ledger.get_ledger().records if r.kind == "state_health"]
            assert len(events) == 1
            # compute() still works (guard off) and the value is the inf
            assert not np.isfinite(float(ev.compute()))
        finally:
            ev.close()
        # close() released the minted series
        assert gauge.value(stream, "mean_value") == 0.0
        assert (stream, "mean_value") not in dict(gauge.collect())

    def test_saturation_only_corruption_pages_too(self):
        """A finite-but-saturated state is the EARLY warning the probe
        exists for — it must latch a state_health event without waiting for
        the value to actually overflow to inf."""
        ledger.enable()
        ledger.reset()
        from tpumetrics.aggregation import SumMetric

        # 0.995*max: past the 0.99 saturation fraction but still finite.
        # bucket=1 so pad replication cannot double the value into inf —
        # the point is a state that sits AT the edge without overflowing
        top = float(np.finfo(np.float32).max) * 0.995
        ev = StreamingEvaluator(SumMetric(), buckets=[1], health_probe=True)
        try:
            ev.submit(jnp.asarray([top]))  # sum_value sits at the f32 edge
            ev.flush()
            h = ev.stats()["device"]["health"]
            assert h["nonfinite_total"] == 0, h
            assert h["saturated_total"] >= 1, h
            events = [r for r in ledger.get_ledger().records if r.kind == "state_health"]
            assert len(events) == 1
            assert events[0].extra["saturated"] >= 1
        finally:
            ev.close()

    def test_probed_evaluator_bit_identical_to_unprobed(self):
        batches = [_batch(seed=s, rows=6) for s in range(4)]
        with StreamingEvaluator(_acc(), buckets=[8], health_probe=True) as probed, \
                StreamingEvaluator(_acc(), buckets=[8]) as plain:
            for b in batches:
                probed.submit(*b)
                plain.submit(*b)
            v_probed, v_plain = probed.compute(), plain.compute()
        assert float(v_probed) == float(v_plain)

    def test_stats_after_close_does_not_remint_series(self):
        """close() releases the device series; a later stats() read still
        answers (the section is computed from live objects) but must not
        re-mint the released gauge labels or re-page a past corruption."""
        ledger.enable()
        ledger.reset()
        ev = StreamingEvaluator(MeanMetric(), buckets=[8], health_probe=True)
        ev.submit(jnp.asarray([np.inf, 1.0]))
        ev.flush()
        stream = ev._stream
        ev.stats()  # pages: one state_health event + the gauge series
        ev.close()
        gauge = instruments.gauge(
            instruments.STATE_NONFINITE, labels=("stream", "state")
        )
        hbm_gauge = instruments.gauge(
            instruments.STATE_HBM_BYTES, labels=("stream",)
        )
        assert (stream, "mean_value") not in dict(gauge.collect())
        assert (stream,) not in dict(hbm_gauge.collect())
        st = ev.stats()  # still answers, mints nothing, pages nothing
        assert st["device"]["hbm"]["state_bytes"] > 0
        assert (stream, "mean_value") not in dict(gauge.collect())
        assert (stream,) not in dict(hbm_gauge.collect())
        events = [r for r in ledger.get_ledger().records if r.kind == "state_health"]
        assert len(events) == 1  # no re-page after close

    def test_mesh_probe_bit_identical(self, mesh8):
        """The probe composes with sharded execution mode: the counter
        reductions ride the ONE global SPMD program, and the probed mesh
        evaluator computes bit-identically to an unprobed single-device
        one."""
        rng = np.random.default_rng(0)
        batches = [
            (
                jnp.asarray(rng.standard_normal((16, 4)), jnp.float32),
                jnp.asarray(rng.integers(0, 4, 16)),
            )
            for _ in range(3)
        ]
        probed = StreamingEvaluator(_acc(), buckets=[16], mesh=mesh8, health_probe=True)
        plain = StreamingEvaluator(_acc(), buckets=[16])
        with probed, plain:
            for b in batches:
                probed.submit(*b)
                plain.submit(*b)
            probed.flush()
            h = probed.stats()["device"]["health"]
            assert h["nonfinite_total"] == 0
            assert float(probed.compute()) == float(plain.compute())

    def test_hbm_section_tracks_state_bytes(self):
        ev = StreamingEvaluator(_acc(), buckets=[8])
        with ev:
            ev.submit(*_batch())
            ev.flush()
            sec = ev.stats()["device"]["hbm"]
        # 4 int scalar states -> a small, nonzero, watermark >= current
        assert sec["state_bytes"] > 0
        assert sec["watermark_bytes"] >= sec["state_bytes"]

    def test_hbm_section_eager_metric_and_collection(self):
        """The EAGER path reads metric_state() — a method — per metric, and
        a MetricCollection contributes every member (regression: the bound
        method referenced as an attribute crashed collections and read 0
        for plain metrics)."""
        from tpumetrics.collections import MetricCollection

        ev = StreamingEvaluator(_acc())  # buckets=None: eager
        with ev:
            ev.submit(*_batch())
            ev.flush()
            assert ev.stats()["device"]["hbm"]["state_bytes"] > 0
        col = MetricCollection({"a": _acc(), "b": _acc()})
        ev2 = StreamingEvaluator(col)
        with ev2:
            ev2.submit(*_batch())
            ev2.flush()
            assert ev2.stats()["device"]["hbm"]["state_bytes"] > 0
        with EvaluationService() as svc:
            h = svc.register("eager-hbm-tenant", MeanMetric())
            h.submit(jnp.asarray([1.0, 2.0]))
            h.flush()
            assert h.stats()["device"]["hbm"]["state_bytes"] > 0


# ------------------------------------------------------------ program profiles


class TestDeviceProfileRegistry:
    def test_disabled_hook_registers_nothing(self):
        ev = StreamingEvaluator(_acc(), buckets=[8])
        with ev:
            ev.submit(*_batch())
            ev.flush()
        assert len(device.registry()) == 0

    def test_armed_registry_attributes_and_resolves(self):
        device.enable_device_profiles()
        ev = StreamingEvaluator(_acc(), buckets=[8])
        stream = ev._stream
        with ev:
            ev.submit(*_batch(rows=5))
            ev.submit(*_batch(rows=5, seed=1))  # same signature: ONE profile
            ev.flush()
            profs = device.profiles(tenant=stream)
            assert len(profs) == 1
            assert profs[0]["flops"] > 0
            assert profs[0]["label"].startswith("step:MulticlassAccuracy")
            summary = device.profile_summary(stream)
            assert summary["registered"] == 1 and summary["resolved"] == 1
            assert summary["flops_per_step"] == profs[0]["flops"]
            st = ev.stats()
            assert st["device"]["programs"]["registered"] == 1
            flops_gauge = instruments.gauge(
                instruments.PROGRAM_FLOPS, labels=("tenant",)
            )
            assert flops_gauge.value(stream) > 0
        # close() released the stream's profiles + gauge series
        assert device.profiles(tenant=stream) == []
        assert flops_gauge.value(stream) == 0.0

    def test_stats_never_resolves(self):
        """stats() must not pay an XLA compile: it reports the registered
        count but resolves nothing."""
        device.enable_device_profiles()
        ev = StreamingEvaluator(_acc(), buckets=[8])
        with ev:
            ev.submit(*_batch())
            ev.flush()
            sec = ev.stats()["device"]["programs"]
            assert sec["registered"] == 1
            assert sec["resolved"] == 0  # lazy until an explicit reader asks
            assert sec["flops_per_step"] == 0

    def test_distinct_signatures_register_separately(self):
        device.enable_device_profiles()
        ev = StreamingEvaluator(_acc(), buckets=[4, 8])
        with ev:
            ev.submit(*_batch(rows=3))
            ev.submit(*_batch(rows=7))  # different bucket -> different program
            ev.flush()
            assert len(device.registry()) == 2

    def test_registry_is_bounded(self):
        reg = device.ProfileRegistry(capacity=2)
        for i in range(5):
            reg.register(f"p{i}", object(), (jnp.zeros((i + 1,)),))
        assert len(reg) == 2
        assert reg.registered == 5 and reg.evictions == 3

    def test_newest_tracks_recency_of_dispatch(self):
        """newest() means most recently DISPATCHED, not first-seen — the
        last_cost_analysis semantics the matcher's bench read replaced
        (regression: an early-return on a known key froze recency, so
        A, B, A-again answered B)."""
        reg = device.ProfileRegistry(capacity=8)
        prog_a, prog_b = object(), object()
        reg.register("m", prog_a, (jnp.zeros((2,)),))
        reg.register("m", prog_b, (jnp.zeros((4,)),))
        assert reg.newest("m")._program is prog_b
        reg.register("m", prog_a, (jnp.zeros((2,)),))  # A dispatches again
        assert reg.newest("m")._program is prog_a

    def test_matcher_registers_under_shared_label(self):
        """The detection matcher feeds the SAME registry (no private
        last_cost_analysis variant): one small jitted evaluation registers
        a resolvable profile under its label."""
        from tpumetrics.detection import _coco_eval_jax
        from tpumetrics.detection.mean_ap import _torch_f32_linspace

        rng = np.random.default_rng(5)

        def boxes(n):
            xy = rng.uniform(0, 40, (n, 2))
            wh = rng.uniform(2, 20, (n, 2))
            return np.concatenate([xy, xy + wh], 1).astype(np.float64)

        dets = [
            (boxes(4), rng.random(4).astype(np.float32), rng.integers(0, 2, 4).astype(np.int64))
        ]
        gts = [
            (boxes(3), rng.integers(0, 2, 3).astype(np.int64),
             np.zeros(3, np.int64), np.zeros(3, np.float64))
        ]
        got = _coco_eval_jax.coco_evaluate_jit(
            dets, gts,
            _torch_f32_linspace(0.5, 0.95, 10), _torch_f32_linspace(0.0, 1.0, 101),
            [1, 10, 100], [0, 1],
        )
        assert got is not None
        prof = device.registry().newest(_coco_eval_jax.MATCHER_PROFILE_LABEL)
        assert prof is not None
        resolved = prof.resolve()
        assert resolved["flops"] > 0, resolved


# -------------------------------------------------------- perfetto round-trip


def _validate_perfetto(trace, span_dicts, record_dicts):
    """The round-trip validator: valid trace-event JSON, monotone ts, every
    span/ledger record represented exactly once, process metadata per pid."""
    parsed = json.loads(json.dumps(trace))  # valid JSON end to end
    events = parsed["traceEvents"]
    meta = [e for e in events if e.get("ph") == "M"]
    body = [e for e in events if e.get("ph") != "M"]
    # monotone timestamps
    ts = [e["ts"] for e in body]
    assert ts == sorted(ts)
    # one process_name per pid present in the body
    assert {e["pid"] for e in meta} == {e["pid"] for e in body}
    # every span exactly once (matched by its unique span id)
    span_events = [e for e in body if e.get("cat") == "span"]
    assert sorted(e["args"]["span"] for e in span_events) == sorted(
        s["span"] for s in span_dicts
    )
    # every ledger record exactly once (compile marks + slices + instants)
    ledger_events = [e for e in body if e.get("cat") in ("compile", "collective", "ledger")]
    assert len(ledger_events) == len(record_dicts)
    return parsed


class TestPerfettoRoundTrip:
    def test_spans_and_ledger_each_exactly_once(self):
        spans.enable()
        ledger.enable()
        ledger.reset()
        with spans.span("batch", stream="t0"):
            with spans.span("dispatch", bucket=8):
                pass
        ledger.record_event(None, "xla_compile", tenant="t0", seconds=0.25)
        ledger.record_collective(
            None, "all_reduce", "sum", (4, 4), "float32", 4, world_size=4
        )
        ledger.record_event(None, "drain_requested", stream="t0")
        span_dicts = [s.to_dict() for s in spans.spans()]
        record_dicts = [r.to_dict() for r in ledger.get_ledger().records]
        trace = export.perfetto_trace()
        parsed = _validate_perfetto(trace, span_dicts, record_dicts)
        body = [e for e in parsed["traceEvents"] if e.get("ph") != "M"]
        # the tenant track: both spans ride the root's stream label
        assert {e["tid"] for e in body if e.get("cat") == "span"} == {"t0"}
        # the compile mark is a real slice with the event's duration
        compile_marks = [e for e in body if e.get("cat") == "compile"]
        assert len(compile_marks) == 1 and compile_marks[0]["dur"] == 0.25 * 1e6
        # the collective is a visible device slice
        assert any(e["cat"] == "collective" for e in body)

    def test_file_target_writes_json(self, tmp_path):
        spans.enable()
        with spans.span("batch", stream="t0"):
            pass
        path = str(tmp_path / "trace.json")
        out = export.perfetto_trace(path, record_list=[])
        assert out == path
        with open(path) as fh:
            parsed = json.load(fh)
        assert any(e.get("cat") == "span" for e in parsed["traceEvents"])


# ----------------------------------------------------- cross-rank timelines


def _write_rank_stream(directory, rank, epoch, records):
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"epoch{epoch:03d}-rank{rank:05d}.jsonl")
    with open(path, "a") as fh:
        for rec in records:
            fh.write(json.dumps(rec) + "\n")
    return path


def _barrier_rec(step, mono_ns, wall_ns, **extra):
    return {
        "kind": "elastic_barrier", "op": "elastic_barrier", "dtype": "",
        "shape": [], "element_count": 0, "payload_bytes": 0, "wire_bytes": 0.0,
        "backend": "FileBarrierBackend", "tag": "", "world_size": 2,
        "in_trace": False, "source": "event", "extra": {"step": step, **extra},
        "mono_ns": mono_ns, "wall_ns": wall_ns,
    }


class TestTimeline:
    WALL0 = 1_700_000_000_000_000_000

    def _two_rank_dir(self, tmp_path, delay_ns=40_000_000):
        """Two ranks, one epoch: rank 1 deliberately enters every barrier
        ``delay_ns`` late, and its process has a DIFFERENT monotonic epoch
        (the cross-process alignment the wall anchor exists for)."""
        tel = str(tmp_path / "telemetry")
        for rank, (mono0, delay) in enumerate([(3_000_000_000, 0), (11_000_000_000, delay_ns)]):
            recs = [
                _barrier_rec(
                    step + 1,
                    mono0 + step * 500_000_000 + delay,
                    self.WALL0 + step * 500_000_000 + delay,
                    rank=rank,
                )
                for step in range(3)
            ]
            _write_rank_stream(tel, rank, 0, recs)
        return tel

    def test_merge_aligns_across_monotonic_epochs(self, tmp_path):
        tel = self._two_rank_dir(tmp_path)
        tl = timeline.merge_timelines(tel)
        assert tl.ranks == [0, 1]
        assert len(tl.events) == 6
        # despite wildly different mono bases, same-window events are close
        per_rank = tl.by_rank()
        gap = abs(per_rank[1][0]["t_global_ns"] - per_rank[0][0]["t_global_ns"])
        assert gap == 40_000_000

    def test_straggler_names_the_delayed_rank(self, tmp_path):
        """THE straggler acceptance: a 2-rank timeline with one
        deliberately-delayed rank names that rank in the report."""
        tel = self._two_rank_dir(tmp_path, delay_ns=40_000_000)
        tl = timeline.merge_timelines(tel)
        report = timeline.straggler_report(tl)
        assert report["straggler"] == 1
        assert report["n_windows"] == 3
        assert all(w["slowest_rank"] == 1 for w in report["windows"])
        assert 39.0 < report["max_skew_ms"] < 41.0
        text = timeline.render_report(tl, report)
        assert "straggler: rank 1" in text

    def test_occurrence_keyed_windows_without_step(self, tmp_path):
        tel = str(tmp_path / "telemetry")
        for rank, delay in ((0, 0), (1, 10_000_000)):
            recs = []
            for i in range(2):
                rec = _barrier_rec(0, 1_000_000_000 * (i + 1) + delay,
                                   self.WALL0 + 1_000_000_000 * (i + 1) + delay)
                rec["extra"] = {}  # no step: k-th occurrence matching
                recs.append(rec)
            _write_rank_stream(tel, rank, 0, recs)
        report = timeline.straggler_report(timeline.merge_timelines(tel))
        assert report["n_windows"] == 2 and report["straggler"] == 1

    def test_to_perfetto_one_process_per_rank(self, tmp_path):
        tel = self._two_rank_dir(tmp_path)
        tl = timeline.merge_timelines(tel)
        trace = timeline.to_perfetto(tl)
        body = [e for e in trace["traceEvents"] if e.get("ph") != "M"]
        meta = [e for e in trace["traceEvents"] if e.get("ph") == "M"]
        assert {e["pid"] for e in body} == {0, 1}
        assert {(e["pid"], e["args"]["name"]) for e in meta} == {
            (0, "rank 0"), (1, "rank 1"),
        }
        assert len(body) == 6  # every record exactly once
        ts = [e["ts"] for e in body]
        assert ts == sorted(ts)

    def test_cli_report_subcommand(self, tmp_path, capsys):
        from tpumetrics.soak.cli import main as cli_main

        self._two_rank_dir(tmp_path)
        trace_path = str(tmp_path / "soak.trace.json")
        rc = cli_main(["report", str(tmp_path), "--perfetto", trace_path])
        assert rc == 0
        out = capsys.readouterr().out
        assert "straggler: rank 1" in out
        with open(trace_path) as fh:
            parsed = json.load(fh)
        assert parsed["traceEvents"]
        # --json mode emits the machine-readable report
        rc = cli_main(["report", str(tmp_path), "--json"])
        assert rc == 0
        rep = json.loads(capsys.readouterr().out)
        assert rep["straggler"] == 1

    def test_cli_report_empty_dir_is_usage_error(self, tmp_path, capsys):
        from tpumetrics.soak.cli import main as cli_main

        rc = cli_main(["report", str(tmp_path / "nothing-here")])
        assert rc == 2

    def test_cli_report_io_error_is_usage_error(self, tmp_path, capsys):
        """An unwritable --perfetto target exits 2 with a clean error line,
        like generate/run do for the same failure class — never a
        traceback."""
        from tpumetrics.soak.cli import main as cli_main

        self._two_rank_dir(tmp_path)
        rc = cli_main([
            "report", str(tmp_path),
            "--perfetto", str(tmp_path / "no-such-dir" / "out.json"),
        ])
        assert rc == 2
        assert "error:" in capsys.readouterr().err


# -------------------------------------------------------------- clock pairs


class TestClockPairs:
    def test_ledger_records_carry_both_clocks(self):
        ledger.enable()
        ledger.reset()
        ledger.record_event(None, "drain_requested", stream="s")
        rec = ledger.get_ledger().records[-1]
        assert rec.mono_ns > 0 and rec.wall_ns > 0
        d = rec.to_dict()
        assert d["mono_ns"] == rec.mono_ns and d["wall_ns"] == rec.wall_ns

    def test_spans_carry_wall_anchor(self):
        spans.enable()
        with spans.span("x"):
            pass
        sp = spans.spans()[-1]
        assert sp.wall_ns > 0
        assert sp.to_dict()["wall_ns"] == sp.wall_ns
        # the pair is consistent: wall anchor ~ now (within a minute)
        import time as _time

        assert abs(sp.wall_ns - _time.time_ns()) < 60 * 1e9


# ------------------------------------------------------- acceptance: service


class TestServiceAcceptance:
    def test_two_tenant_probed_service_acceptance(self, tmp_path):
        """ISSUE 14 acceptance (a)+(b): 2 probed tenants; a Perfetto trace
        with both tenants' batch spans, compile marks, and device slices;
        the poisoned tenant pages BEFORE compute; the clean neighbor is
        bit-identical to an unprobed functional run."""
        spans.enable(capacity=8192)
        spans.reset()
        ledger.enable()
        ledger.reset()
        xla.enable_compile_attribution()
        xla.reset_compile_attribution()
        clean_batches = [_batch(seed=s, rows=6) for s in range(3)]
        with EvaluationService() as svc:
            ha = svc.register("acc-clean", _acc(), buckets=[8], health_probe=True)
            hb = svc.register("mean-poison", MeanMetric(), buckets=[8], health_probe=True)
            for b in clean_batches:
                ha.submit(*b)
            hb.submit(jnp.asarray([1.0, 2.0]))
            hb.submit(jnp.asarray([np.inf, 3.0]))
            ha.flush()
            hb.flush()

            # (b) the poisoned tenant pages BEFORE compute()
            st_b = hb.stats()
            assert st_b["device"]["health"]["nonfinite_total"] >= 1
            events = [
                r for r in ledger.get_ledger().records if r.kind == "state_health"
            ]
            assert len(events) == 1
            assert events[0].extra["stream"] == "mean-poison"
            gauge = instruments.gauge(
                instruments.STATE_NONFINITE, labels=("stream", "state")
            )
            assert gauge.value("mean-poison", "mean_value") >= 1
            # the clean neighbor reads clean
            assert ha.stats()["device"]["health"]["nonfinite_total"] == 0

            # neighbor bit-identity vs an UNPROBED functional run
            m = _acc()
            s = m.init_state()
            for p, t in clean_batches:
                s = m.functional_update(s, p, t)
            assert float(ha.compute()) == float(m.functional_compute(s))

            # (a) the Perfetto trace round-trips with both tenants' batch
            # spans, compile marks, and device (dispatch) slices
            span_dicts = [sp.to_dict() for sp in spans.spans()]
            record_dicts = [r.to_dict() for r in ledger.get_ledger().records]
            trace = export.perfetto_trace(
                span_list=spans.spans(),
                record_list=ledger.get_ledger().records,
            )
            parsed = _validate_perfetto(trace, span_dicts, record_dicts)
            body = [e for e in parsed["traceEvents"] if e.get("ph") != "M"]
            batch_tracks = {
                e["tid"] for e in body if e.get("cat") == "span" and e["name"] == "batch"
            }
            assert {"acc-clean", "mean-poison"} <= batch_tracks
            dispatch_tracks = {
                e["tid"] for e in body if e.get("cat") == "span" and e["name"] == "dispatch"
            }
            assert {"acc-clean", "mean-poison"} <= dispatch_tracks
            assert any(e.get("cat") == "compile" for e in body), (
                "no compile marks in the trace despite attributed compiles"
            )
