"""tpumetrics.monitoring: windows, decay, sketches, drift — the online-
monitoring workload class.

Acceptance surface (ISSUE 11): windowed/decayed aggregators are exact and
trace-safe under the bucketed/fused/megabatch runtime paths; the quantile
sketch is a *mergeable* state kind (bit-identical under any fold order,
resharded as sketch-on-rank0 + empties); a windowed stream killed mid-window
and resized elastically computes bit-identically to an uninterrupted
single-world run; drift monitors alert exactly once per threshold crossing,
into the ledger, the Prometheus export, and ``stats()["monitoring"]``.
"""

from __future__ import annotations

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpumetrics import MetricCollection
from tpumetrics.monitoring import (
    DecayedMean,
    KLDrift,
    KSDistance,
    PSI,
    SketchLayout,
    SketchQuantiles,
    WindowedMax,
    WindowedMean,
    WindowedMin,
    WindowedSum,
    monitoring_stats,
    stream_scope,
)
from tpumetrics.monitoring.sketch import sketch_merge
from tpumetrics.parallel.backend import DistributedBackend
from tpumetrics.parallel.fuse_update import FusedCollectionStep
from tpumetrics.parallel.merge import (
    AssociativeMerge,
    merge_metric_states,
    reshard_metric_states,
)
from tpumetrics.resilience import config_digest
from tpumetrics.resilience import elastic as elastic_mod
from tpumetrics.runtime import StreamingEvaluator
from tpumetrics.runtime.service import EvaluationService
from tpumetrics.runtime.snapshot import SnapshotSpecError
from tpumetrics.telemetry import ledger
from tpumetrics.telemetry.export import prometheus_text
from tpumetrics.utils.exceptions import TPUMetricsUserError


# ------------------------------------------------------------------ windowed


class TestWindowedAggregators:
    def test_windowed_mean_matches_naive_recompute(self):
        rng = np.random.default_rng(0)
        m = WindowedMean(window=5)
        history = []
        for _ in range(17):
            batch = rng.normal(0, 2, int(rng.integers(1, 9))).astype(np.float32)
            history.append(batch)
            m.update(jnp.asarray(batch))
            recent = np.concatenate(history[-5:])
            assert np.isclose(float(m.compute()), float(recent.mean()), rtol=1e-5)
            m._computed = None

    @pytest.mark.parametrize(
        "cls,fold",
        [(WindowedSum, np.sum), (WindowedMax, np.max), (WindowedMin, np.min)],
    )
    def test_windowed_extrema_and_sum_match_naive(self, cls, fold):
        rng = np.random.default_rng(1)
        m = cls(window=4)
        history = []
        for _ in range(11):
            batch = rng.normal(0, 3, int(rng.integers(1, 6))).astype(np.float32)
            history.append(batch)
            m.update(jnp.asarray(batch))
            want = float(fold(np.concatenate(history[-4:])))
            assert np.isclose(float(m.compute()), want, rtol=1e-5)
            m._computed = None

    def test_coarse_slots_pane_semantics(self):
        # window=4, slots=2 -> each slot covers 2 updates; after 5 updates
        # the live window is updates 3..5 (the current pane is half full)
        m = WindowedSum(window=4, slots=2)
        for x in (1.0, 2.0, 4.0, 8.0, 16.0):
            m.update(x)
            m._computed = None
        assert float(m.compute()) == 4.0 + 8.0 + 16.0

    def test_weighted_windowed_mean(self):
        m = WindowedMean(window=2)
        m.update(jnp.asarray([1.0, 3.0]), weight=jnp.asarray([1.0, 3.0]))
        m.update(2.0)
        # (1*1 + 3*3 + 2) / (1 + 3 + 1)
        assert float(m.compute()) == pytest.approx(12.0 / 5.0)

    def test_valid_mask_is_exact(self):
        m = WindowedMean(window=8)
        padded = jnp.asarray([5.0, 7.0, 999.0, 999.0])
        m.update(padded, valid=jnp.asarray([True, True, False, False]))
        assert float(m.compute()) == 6.0
        mx = WindowedMax(window=8)
        mx.update(padded, valid=jnp.asarray([True, True, False, False]))
        assert float(mx.compute()) == 7.0

    def test_nan_ignored_by_default(self):
        m = WindowedMean(window=4)
        m.update(jnp.asarray([1.0, jnp.nan, 3.0]))
        assert float(m.compute()) == 2.0

    def test_eviction_is_one_slot_write(self):
        # state shapes are (slots,) regardless of the data — eviction cannot
        # be O(window * rows)
        m = WindowedMean(window=1024, slots=8)
        assert m.slot_sum.shape == (8,)
        m.update(jnp.arange(16.0))
        assert m.slot_sum.shape == (8,)

    def test_window_must_be_static(self):
        with pytest.raises(TPUMetricsUserError, match="static python int"):
            WindowedMean(window=jnp.asarray(8))
        with pytest.raises(TPUMetricsUserError, match="evenly"):
            WindowedMean(window=6, slots=4)
        with pytest.raises(TPUMetricsUserError, match=">= 1"):
            WindowedSum(window=0)

    def test_no_retrace_across_window_positions(self):
        # the ring index is traced state: wrapping the window must not mint
        # new trace signatures (fixed shapes -> one compiled step per shape)
        m = WindowedMean(window=2)
        step = jax.jit(lambda s, v: m.functional_update(s, v))
        state = m.init_state()
        for i in range(7):
            state = step(state, jnp.full((4,), float(i)))
        assert step._cache_size() == 1
        assert float(m.functional_compute(state)) == pytest.approx((5.0 + 6.0) / 2)

    def test_decayed_mean_recurrence(self):
        m = DecayedMean(half_life=2)
        alpha = 2.0 ** (-1 / 2)
        s = w = 0.0
        for x in (1.0, 5.0, 2.0, 8.0):
            m.update(x)
            s = s * alpha + x
            w = w * alpha + 1.0
        assert float(m.compute()) == pytest.approx(s / w, rel=1e-6)

    def test_decayed_mean_half_life_semantics(self):
        # an observation half_life updates old carries half the weight
        m = DecayedMean(half_life=4)
        m.update(0.0)
        for _ in range(4):
            m.update(1.0)
        # weight of the first obs is 0.5 vs 1.0 for the latest
        w = 2.0 ** (-np.arange(5) / 4.0)[::-1]
        x = np.asarray([0.0, 1.0, 1.0, 1.0, 1.0])
        assert float(m.compute()) == pytest.approx(float((w * x).sum() / w.sum()), rel=1e-5)


# -------------------------------------------------------------------- sketch


class TestSketch:
    def test_merge_associative_commutative_bit_identical(self):
        """Random split orders of the same data fold to BIT-identical
        sketches — the contract that makes the sketch a dist_reduce_fx."""
        rng = np.random.default_rng(2)
        layout = SketchLayout(levels=16, capacity=32)
        parts = [rng.normal(0, 3, 200).astype(np.float32) for _ in range(7)]
        rows = []
        for p in parts:
            rows.append(
                layout.update_row(layout.empty(1)[0], jnp.asarray(p), jnp.ones(p.shape))
            )

        def fold(order):
            acc = rows[order[0]]
            for i in order[1:]:
                acc = layout.merge(jnp.stack([acc, rows[i]]))
            return np.asarray(acc)

        base = fold(list(range(7)))
        rnd = random.Random(7)
        for _ in range(12):
            order = list(range(7))
            rnd.shuffle(order)
            assert np.array_equal(fold(order), base), order
        # pairwise-tree fold too (associativity, not just permutations)
        left = layout.merge(jnp.stack([rows[0], rows[1]]))
        right = layout.merge(jnp.stack([rows[2], rows[3]]))
        tree = layout.merge(jnp.stack([np.asarray(left), np.asarray(right)]))
        flat = fold([0, 1, 2, 3])
        assert np.array_equal(np.asarray(tree), flat)

    @pytest.mark.parametrize(
        "corpus",
        [
            lambda rng: rng.normal(5.0, 2.0, 20000),
            lambda rng: rng.lognormal(0.0, 1.0, 20000),
            lambda rng: rng.uniform(-3.0, 3.0, 20000),
        ],
        ids=["normal", "lognormal", "uniform_signed"],
    )
    def test_quantile_error_bound_vs_numpy(self, corpus):
        rng = np.random.default_rng(3)
        data = corpus(rng).astype(np.float32)
        capacity = 128
        m = SketchQuantiles(
            quantiles=(0.01, 0.1, 0.25, 0.5, 0.75, 0.9, 0.99), capacity=capacity
        )
        m.update(jnp.asarray(data))
        got = np.asarray(m.compute())
        for q, est in zip(m.quantiles, got):
            true = float(np.quantile(data, q))
            # bucket midpoint: <= one bucket width (~2|x|/capacity in the
            # geometric range), plus sub-unit absolute slack
            tol = 3.0 * abs(true) / capacity + 2.0 * m.unit + 1e-3
            assert abs(est - true) <= tol, (q, est, true, tol)

    def test_min_max_are_exact_and_bound_the_estimates(self):
        m = SketchQuantiles(quantiles=(0.0, 1.0))
        m.update(jnp.asarray([3.25, -7.5, 0.125, 11.0]))
        lo, hi = np.asarray(m.compute())
        assert lo == -7.5 and hi == 11.0

    def test_empty_sketch_computes_nan(self):
        m = SketchQuantiles()
        m._update_count = 1  # silence the pre-update warning; state is empty
        assert np.isnan(np.asarray(m.compute())).all()

    def test_windowed_sketch_evicts(self):
        m = SketchQuantiles(quantiles=(0.5,), window=2, slots=2)
        m.update(jnp.full((64,), 1000.0))
        m.update(jnp.full((64,), 1.0))
        m.update(jnp.full((64,), 2.0))  # the 1000s slide out
        m._computed = None
        est = float(np.asarray(m.compute()))
        assert est <= 3.0

    def test_sketch_counts_weighted_by_valid_mask(self):
        m = SketchQuantiles(quantiles=(0.5,))
        m.update(
            jnp.asarray([2.0, 2.0, 900.0, 900.0]),
            valid=jnp.asarray([True, True, False, False]),
        )
        layout = m._sketch_layout
        assert float(layout.total(m.merged_row())) == 2.0
        assert float(np.asarray(m.compute())) == pytest.approx(2.0, rel=1.0 / 64)

    def test_geometry_must_be_static(self):
        with pytest.raises(TPUMetricsUserError, match="static python int"):
            SketchQuantiles(capacity=jnp.asarray(8))
        with pytest.raises(TPUMetricsUserError, match="evenly"):
            SketchQuantiles(window=5, slots=2)

    def test_inf_outliers_land_in_the_top_bucket(self):
        # floor(log2(inf)) cast to int32 saturates; the +1 must not wrap an
        # inf outlier into the near-zero bucket (documented top-bucket clip)
        layout = SketchLayout(levels=16, capacity=32)
        idx = np.asarray(layout.bucket_index(jnp.asarray([jnp.inf, -jnp.inf, 1.0])))
        assert idx[0] == layout.side - 1  # top positive bucket
        assert idx[1] == 2 * layout.side - 1  # top negative bucket
        m = SketchQuantiles(quantiles=(0.5,))
        m.update(jnp.asarray([jnp.inf] * 5 + [100.0]))
        est = float(np.asarray(m.compute()))
        assert est >= 2.0**22, est  # saturates at the range top, not near 0

    def test_non_integral_window_refused(self):
        with pytest.raises(TPUMetricsUserError, match="truncate"):
            WindowedMean(window=2.5)
        with pytest.raises(TPUMetricsUserError, match="truncate"):
            SketchQuantiles(window=8, slots=2.5)
        assert WindowedMean(window=4.0).window == 4  # integral float is fine

    def test_default_unit_anchors_the_range_top(self):
        # shrinking levels must coarsen precision near zero, NOT silently
        # clip real-world magnitudes: the covered top stays ~2^23 and a
        # small sketch still separates ordinary values
        small = SketchQuantiles(quantiles=(0.5,), levels=16, capacity=64)
        assert small.unit * 2 ** (small.levels - 1) == 2.0**23
        for i in range(6):
            small.update(jnp.full((32,), 100.0 * i))
        est = float(np.asarray(small.compute()))
        # nearest-rank median of 192 values is the 96th (= 200); one bucket
        # of slack for the midpoint representative
        assert abs(est - 200.0) <= small.unit / 64 + 1e-3
        assert SketchQuantiles().unit == 2.0**-20  # levels=44 default unchanged

    def test_default_slots_divide_any_window(self):
        # the default must be a divisor of the window, not a flat 8 — any
        # window length constructs without hand-picking slots
        assert SketchQuantiles(window=12).slots == 6
        assert SketchQuantiles(window=7).slots == 7
        assert SketchQuantiles(window=13).slots == 1  # prime > 8: cumulative panes
        assert SketchQuantiles(window=64).slots == 8


# ------------------------------------------------- merge state kind plumbing


class TestMergeStateKind:
    def _sketch_states(self, seed, n_ranks):
        rng = np.random.default_rng(seed)
        states, metrics = [], []
        for _ in range(n_ranks):
            m = SketchQuantiles(levels=12, capacity=16)
            m.update(jnp.asarray(rng.normal(0, 1, 50).astype(np.float32)))
            states.append(m.metric_state())
            metrics.append(m)
        return metrics[0], states

    def test_reshard_is_rank0_plus_empties_and_folds_back(self):
        proto, states = self._sketch_states(4, 3)
        folded = merge_metric_states(states, proto._reductions)
        shards = [
            reshard_metric_states(dict(folded), proto._reductions, r, 4)
            for r in range(4)
        ]
        layout = proto._sketch_layout
        for r in (1, 2, 3):
            counts = np.asarray(shards[r]["sketch"])[..., : layout.total_index + 1]
            assert counts.sum() == 0.0  # empties everywhere but rank 0
        refold = merge_metric_states(shards, proto._reductions)
        assert np.array_equal(np.asarray(refold["sketch"]), np.asarray(folded["sketch"]))

    def test_bare_callable_reduce_still_refuses_reshard(self):
        reductions = {"s": lambda stacked: stacked.sum(0)}
        with pytest.raises(TPUMetricsUserError, match="AssociativeMerge"):
            reshard_metric_states({"s": jnp.ones((4,))}, reductions, 0, 2)

    def test_state_spec_reports_merge_kind_with_params(self):
        m = SketchQuantiles(levels=12, capacity=16)
        spec = m.state_spec()["sketch"]
        assert spec["kind"] == "merge"
        assert spec["reduce"] == "merge:sketch"
        assert spec["params"]["levels"] == 12 and spec["params"]["capacity"] == 16

    def test_snapshot_spec_error_names_sketch_params(self, tmp_path):
        ev = StreamingEvaluator(
            SketchQuantiles(levels=12, capacity=32), buckets=16, snapshot_dir=str(tmp_path)
        )
        ev.submit(jnp.arange(8.0))
        ev.flush()
        ev.snapshot()
        ev.close()
        ev2 = StreamingEvaluator(
            SketchQuantiles(levels=12, capacity=16), buckets=16, snapshot_dir=str(tmp_path)
        )
        with pytest.raises(SnapshotSpecError, match=r"capacity=16.*levels=12|levels=12.*capacity=16"):
            ev2.restore_latest()
        ev2.close(drain=False)

    def test_oo_snapshot_mismatch_names_sketch_params(self):
        a = SketchQuantiles(levels=12, capacity=32)
        b = SketchQuantiles(levels=12, capacity=16)
        a.update(jnp.arange(4.0))
        snap = a.snapshot_state()
        with pytest.raises(TPUMetricsUserError, match="merge:sketch"):
            b.load_snapshot_state(snap)

    def test_collection_annotations_keep_same_named_sketches_apart(self):
        # two members both declare a state literally named 'sketch' with
        # DIFFERENT geometry: each spec-error annotation must carry its own
        # member's parameters (a bare-name key would let the last one win)
        from tpumetrics.runtime.snapshot import state_annotations

        col = MetricCollection(
            {
                "q": SketchQuantiles(levels=12, capacity=128),
                "psi": PSI(reference=np.arange(50.0), levels=12, capacity=16),
            }
        )
        ann = state_annotations(col)
        assert "capacity=128" in ann["['q']['sketch']"]
        assert "capacity=16" in ann["['psi']['sketch']"]

    def test_drift_monitor_clone_rebuilds_alert_lock(self):
        m = PSI(reference=np.arange(50.0), threshold=0.1)
        m.update(jnp.arange(200.0))
        c = m.clone()  # deepcopy: the lock must not travel, latches may
        c.update(jnp.arange(200.0))
        assert c._alert_lock is not m._alert_lock
        float(c.compute())

    def test_identity_contract(self):
        layout = SketchLayout(levels=8, capacity=8)
        fn = sketch_merge(layout)
        assert isinstance(fn, AssociativeMerge)
        row = layout.update_row(layout.empty(1)[0], jnp.asarray([1.0, 2.0]), jnp.ones(2))
        ring = jnp.stack([row])
        merged = fn(jnp.stack([ring, fn.identity_like(ring)]))
        assert np.array_equal(np.asarray(merged), np.asarray(ring))


# ------------------------------------------------------- runtime path parity


def _ref_values(seed=11):
    return np.random.default_rng(seed).normal(0, 1, 1500).astype(np.float32)


def _monitoring_collection(window=8):
    ref = _ref_values()
    return MetricCollection(
        {
            "wmean": WindowedMean(window=window, slots=4),
            "q": SketchQuantiles(quantiles=(0.5, 0.99), levels=20, capacity=64),
            "psi": PSI(
                reference=ref, threshold=0.25, hysteresis=0.05, levels=20, capacity=64
            ),
        }
    )


def _stream(seed, n, lo=1, hi=30, loc=2.0):
    rng = np.random.default_rng(seed)
    return [
        jnp.asarray(rng.normal(loc, 1.0, int(rng.integers(lo, hi))).astype(np.float32))
        for _ in range(n)
    ]


class TestRuntimeParity:
    def test_bucketed_evaluator_bit_identical_to_oo(self):
        batches = _stream(21, 7)
        col = _monitoring_collection()
        for b in batches:
            col.update(b)
        want = col.compute()

        ev = StreamingEvaluator(_monitoring_collection(), buckets=32)
        for b in batches:
            ev.submit(b)
        got = ev.compute()
        st = ev.stats()
        ev.close()
        for k in want:
            assert np.array_equal(
                np.asarray(want[k]), np.asarray(got[k]), equal_nan=True
            ), k
        assert st["monitoring"]["psi"]["alert_active"] is True

    def test_fused_oo_collection_parity(self):
        batches = [jnp.asarray([1.0, 2.0]), jnp.asarray([3.0]), jnp.asarray([4.0, 5.0])]
        plain = MetricCollection({"wm": WindowedMean(window=2), "dm": DecayedMean(half_life=3)})
        fused = MetricCollection(
            {"wm": WindowedMean(window=2), "dm": DecayedMean(half_life=3)},
            fused_update=True,
        )
        for b in batches:
            plain.update(b)
            fused.update(b)
        a, b_ = plain.compute(), fused.compute()
        for k in a:
            assert np.array_equal(np.asarray(a[k]), np.asarray(b_[k])), k

    def test_fused_step_masked_update_parity(self):
        col = _monitoring_collection()
        step = FusedCollectionStep(col, donate=False)
        state = step.init_state()
        raw = jnp.asarray([1.0, 2.0, 5.0])
        padded = jnp.concatenate([raw, jnp.broadcast_to(raw[0:1], (5,))])
        state = step.masked_update(state, (padded,), jnp.asarray(3, jnp.int32), 8)
        want = _monitoring_collection()
        want.update(raw)
        got = col.functional_compute(state)
        expect = want.compute()
        for k in expect:
            assert np.array_equal(
                np.asarray(expect[k]), np.asarray(got[k]), equal_nan=True
            ), k

    def test_megabatch_parity_windowed(self):
        streams = [_stream(31 + i, 5, lo=8, hi=9) for i in range(3)]  # same shapes
        with EvaluationService() as svc:
            handles = [
                svc.register(f"t{i}", _monitoring_collection(), buckets=[16])
                for i in range(3)
            ]
            for j in range(5):
                for i, h in enumerate(handles):
                    h.submit(streams[i][j])
            svc.flush()
            st = svc.stats()
            got = [h.compute() for h in handles]
            mon = [h.stats().get("monitoring") for h in handles]
        assert st["shared_steps"] == 1
        assert st["megabatch_steps"] > 0
        for i in range(3):
            want_col = _monitoring_collection()
            for b in streams[i]:
                want_col.update(b)
            want = want_col.compute()
            for k in want:
                assert np.array_equal(
                    np.asarray(want[k]), np.asarray(got[i][k]), equal_nan=True
                ), (i, k)
            assert mon[i]["psi"]["alert_active"] is True

    def test_scalar_submits_route_through_windows(self):
        ev = StreamingEvaluator(WindowedMean(window=2), buckets=8)
        for x in (1.0, 2.0, 9.0):
            ev.submit(x)
        assert float(ev.compute()) == pytest.approx((2.0 + 9.0) / 2)
        ev.close()


# --------------------------------------------------------------------- drift


class TestDriftMonitors:
    def test_alert_fires_exactly_once_per_crossing_with_hysteresis(self):
        ref = _ref_values(5)
        m = KSDistance(
            reference=ref, threshold=0.5, hysteresis=0.1, window=4, slots=4,
            compute_with_cache=False, levels=20, capacity=64,
        )
        rng = np.random.default_rng(6)
        shifted = lambda: jnp.asarray(rng.normal(8.0, 1.0, 200).astype(np.float32))
        matched = lambda: jnp.asarray(rng.normal(0.0, 1.0, 200).astype(np.float32))
        with ledger.capture() as cap:
            m.update(shifted())
            assert float(m.compute()) >= 0.5
            entry = m._runtime("")
            assert entry["alerts"] == 1 and entry["active"]
            # still above threshold: latched, no second alert
            m.update(shifted())
            m.compute()
            assert m._runtime("")["alerts"] == 1
            # window slides to matched data: score drops below re-arm point
            for _ in range(4):
                m.update(matched())
            assert float(m.compute()) < 0.4
            assert not m._runtime("")["active"]
            # second genuine crossing fires again
            for _ in range(4):
                m.update(shifted())
            m.compute()
            assert m._runtime("")["alerts"] == 2
        events = [r for r in cap.records if r.kind == "drift_alert"]
        assert len(events) == 2
        assert cap.summary()["drift_alerts"] == 2
        assert events[0].extra["monitor"] == "KSDistance"

    def test_kl_and_psi_detect_shift_and_stay_quiet_on_match(self):
        rng = np.random.default_rng(7)
        ref = rng.normal(0, 1, 4000).astype(np.float32)
        for cls in (PSI, KLDrift):
            same = cls(reference=ref, threshold=0.25)
            same.update(jnp.asarray(rng.normal(0, 1, 4000).astype(np.float32)))
            assert float(same.compute()) < 0.1, cls
            moved = cls(reference=ref, threshold=0.25)
            moved.update(jnp.asarray(rng.normal(1.5, 1, 4000).astype(np.float32)))
            assert float(moved.compute()) > 0.25, cls

    def test_per_stream_latches_are_independent(self):
        ref = _ref_values(8)
        m = PSI(reference=ref, threshold=0.1, compute_with_cache=False)
        m.update(jnp.asarray(_ref_values(9) + 3.0))
        with ledger.capture() as cap:
            with stream_scope("tenant-a"):
                m.compute()
            with stream_scope("tenant-b"):
                m.compute()
        assert m._runtime("tenant-a")["alerts"] == 1
        assert m._runtime("tenant-b")["alerts"] == 1
        assert m._runtime("")["alerts"] == 0
        streams = {r.extra["stream"] for r in cap.records if r.kind == "drift_alert"}
        assert streams == {"tenant-a", "tenant-b"}
        stats = monitoring_stats(m, "tenant-a")
        assert stats["PSI"]["alert_active"] is True

    def test_gauge_and_counter_in_prometheus_export(self):
        ref = _ref_values(10)
        m = PSI(reference=ref, threshold=0.1, name="psi_feature_x")
        m.update(jnp.asarray(ref + 5.0))
        with stream_scope("svc-tenant"):
            m.compute()
        text = prometheus_text()
        assert "tpumetrics_drift_score" in text
        assert 'stream="svc-tenant"' in text and 'monitor="psi_feature_x"' in text
        assert "tpumetrics_drift_alerts_total" in text
        from tpumetrics.monitoring import release_stream

        release_stream(m, "svc-tenant")
        assert 'monitor="psi_feature_x"' not in prometheus_text()

    def test_tenant_handle_stats_surface_monitoring(self):
        ref = _ref_values(12)
        with EvaluationService() as svc:
            h = svc.register(
                "tenant-m",
                MetricCollection({"psi": PSI(reference=ref, threshold=0.1)}),
                buckets=[16],
            )
            h.submit(jnp.asarray(ref[:16] + 4.0))
            h.compute()
            section = h.stats()["monitoring"]
        assert section["psi"]["monitor"] == "PSI"
        assert section["psi"]["alert_active"] is True
        assert section["psi"]["alerts"] == 1

    def test_reference_digest_guards_restore(self):
        ref_a = _ref_values(13)
        a = PSI(reference=ref_a, threshold=0.5)
        b = PSI(reference=ref_a * 3.0, threshold=0.5)
        a.update(jnp.asarray(ref_a))
        snap = a.snapshot_state()
        with pytest.raises(TPUMetricsUserError, match="reference_digest"):
            b.load_snapshot_state(snap)

    def test_evaluator_close_releases_drift_series(self):
        ref = _ref_values(14)
        ev = StreamingEvaluator(
            MetricCollection({"psi": PSI(reference=ref, threshold=0.05)}), buckets=[16]
        )
        ev.submit(jnp.asarray(ref[:12] + 4.0))
        ev.compute()
        stream = ev._stream
        assert f'stream="{stream}"' in prometheus_text()
        ev.close()
        assert f'stream="{stream}"' not in prometheus_text()


# ------------------------------------------------------------------ sharding


class TestShardedMonitoring:
    def test_for_metric_keeps_merge_states_replicated(self):
        rules = _monitoring_collection().state_partition_rules()
        # no rule may target the sketch/slot states: the merge IS the
        # collective, windows/sketches replicate like reduce-op states
        assert not any("sketch" in p or "slot" in p for p in rules.patterns)

    def test_sharded_evaluator_parity(self, mesh8):
        batches = [
            jnp.asarray(np.arange(float(8 * i), 8.0 * (i + 1), dtype=np.float32))
            for i in range(6)
        ]
        plain = _monitoring_collection()
        for b in batches:
            plain.update(b)
        want = plain.compute()
        ev = StreamingEvaluator(_monitoring_collection(), buckets=[8], mesh=mesh8)
        for b in batches:
            ev.submit(b)
        got = ev.compute()
        ev.close()
        for k in want:
            assert np.array_equal(
                np.asarray(want[k]), np.asarray(got[k]), equal_nan=True
            ), k


# ------------------------------------------------------------------- elastic


def _int_stream(seed, n, rows=(3, 9)):
    """Integer-valued float batches: cross-rank sums are exact in f32, so
    bit-identical claims survive any summation grouping."""
    rng = np.random.default_rng(seed)
    return [
        jnp.asarray(
            rng.integers(-20, 50, int(rng.integers(*rows))).astype(np.float32)
        )
        for _ in range(n)
    ]


def _shards(batch, world):
    return np.array_split(np.asarray(batch), world)


class TestElasticWindows:
    @pytest.mark.parametrize("n,m", [(2, 4), (3, 2)])
    def test_midwindow_shrink_grow_lockstep_bit_identical(self, n, m):
        """Lockstep data-parallel windows (every rank sees its shard of
        every batch) across a mid-window resize: fold -> reshard -> resume
        must equal the uninterrupted single-world run BIT-identically, with
        evictions crossing the resize boundary."""

        def make():
            return MetricCollection(
                {
                    "wm": WindowedMean(window=4, slots=2),
                    "dm": DecayedMean(half_life=2),
                    "q": SketchQuantiles(quantiles=(0.5, 0.9), levels=16, capacity=32),
                }
            )

        batches = _int_stream(40, 10, rows=(max(n, m) + 2, 16))
        proto = make()
        single = proto.init_state()
        for b in batches:
            single = proto.functional_update(single, b)
        want = proto.functional_compute(single)

        states = [proto.init_state() for _ in range(n)]
        cut_at = 6  # mid-window: slot ring has wrapped and is part-filled
        for b in batches[:cut_at]:
            for r, shard in enumerate(_shards(b, n)):
                states[r] = proto.functional_update(states[r], jnp.asarray(shard))
        folded = proto.fold_state_dicts(states)
        resharded = [proto.reshard_state_dict(folded, j, m) for j in range(m)]
        for b in batches[cut_at:]:
            for j, shard in enumerate(_shards(b, m)):
                resharded[j] = proto.functional_update(resharded[j], jnp.asarray(shard))
        refolded = proto.fold_state_dicts(resharded)
        got = proto.functional_compute(refolded)
        for k in want:
            assert np.array_equal(
                np.asarray(want[k]), np.asarray(got[k]), equal_nan=True
            ), k


class _Cohort(DistributedBackend):
    """Emulated eager cohort (the test_elastic idiom): this rank's object
    gather returns its own payload plus precomputed peer stamps."""

    has_object_channel = True

    def __init__(self, rank, world, peek):
        self._rank, self._world, self._peek = rank, world, peek

    def available(self):
        return True

    def world_size(self):
        return self._world

    def rank(self):
        return self._rank

    def all_gather_object(self, obj, group=None):
        return [obj if r == self._rank else self._peek(r) for r in range(self._world)]


def _blocks(items, n):
    split = np.array_split(np.arange(len(items)), n)
    return [[items[int(i)] for i in idx] for idx in split]


class TestAcceptance:
    def test_streaming_windowed_kill_restore_resize_bit_identical(self, tmp_path):
        """THE acceptance run: a StreamingEvaluator over a windowed
        collection (WindowedMean + sketch p50/p99 + PSI monitor) is killed
        mid-window, restored and resized 2 -> 4 via restore_elastic(), and
        its compute()/drift scores are bit-identical to an uninterrupted
        single-world run; the drift alert lands in the ledger AND the
        Prometheus export."""
        ref = np.asarray(_int_stream(50, 1, rows=(400, 401))[0])

        def make():
            return MetricCollection(
                {
                    "wmean": WindowedMean(window=32, slots=8),
                    "q": SketchQuantiles(quantiles=(0.5, 0.99), levels=16, capacity=64),
                    "psi": PSI(
                        reference=ref, threshold=0.25, hysteresis=0.05,
                        levels=16, capacity=64,
                    ),
                }
            )

        # 12 batches; shifted so PSI must alert.  Window (32) exceeds the
        # stream: the kill at batch 8 is genuinely MID-window.
        batches = [jnp.asarray(np.asarray(b) + 60.0) for b in _int_stream(51, 12)]
        single = make()
        with stream_scope("single"):
            for b in batches:
                single.update(b)
            want = single.compute()

        root = str(tmp_path)
        digest = config_digest(make())
        props: dict = {}

        def peek(r):
            return elastic_mod.make_stamp(r, props[r], digest)

        def cohort_evaluators(world):
            return [
                StreamingEvaluator(
                    make(), buckets=16, snapshot_dir=root,
                    snapshot_rank=r, snapshot_world_size=world,
                    barrier_backend=_Cohort(r, world, peek),
                )
                for r in range(world)
            ]

        evs = cohort_evaluators(2)
        k = 8
        for ev, block in zip(evs, _blocks(batches[:k], 2)):
            for b in block:
                ev.submit(b)
        for ev in evs:
            ev.flush()
        for r, ev in enumerate(evs):
            props[r] = ev._barrier_proposal()
        for ev in evs:
            ev.snapshot()
        for ev in evs:
            ev.close(drain=False)  # the kill: whole world preempted

        with ledger.capture() as cap:
            news = cohort_evaluators(4)
            infos = [ev.restore_elastic() for ev in news]
            assert all(i["batches"] == k and i["from_world"] == 2 for i in infos)
            for ev, block in zip(news, _blocks(batches[k:], 4)):
                for b in block:
                    ev.submit(b)
            for ev in news:
                ev.flush()
            proto = make()
            folded = proto.fold_state_dicts([ev._state for ev in news])
            with stream_scope("global"):
                got = proto.functional_compute(folded)
            news[0].compute()  # rank-local compute: fires this rank's alert
            stats0 = news[0].stats()
            prom = prometheus_text()
            for ev in news:
                ev.close(drain=False)

        # bit-identical values AND drift scores across kill + 2->4 resize
        for key in want:
            assert np.array_equal(
                np.asarray(want[key]), np.asarray(got[key]), equal_nan=True
            ), key
        # the drift alert is visible in stats, the ledger, and Prometheus
        assert stats0["monitoring"]["psi"]["alert_active"] is True
        assert cap.summary()["drift_alerts"] >= 1
        assert any(r.kind == "drift_alert" for r in cap.records)
        assert "tpumetrics_drift_score" in prom and 'monitor="PSI"' in prom
        assert any(r.kind == "elastic_restore" for r in cap.records)
