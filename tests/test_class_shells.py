"""Class-shell wiring sweep: every thin modular class must accumulate over
batches to exactly what its functional form computes on the concatenated
data (the reference exercises this pairing per metric file; here one
parametrized harness covers the classes that have no dedicated test)."""

import jax.numpy as jnp
import numpy as np
import pytest

import tpumetrics.classification as tmc
import tpumetrics.functional.classification as tmf
import tpumetrics.functional.regression as tmfr
import tpumetrics.regression as tmr
from tests.conftest import NUM_BATCHES
from tests.helpers.testers import _class_test

_rng = np.random.default_rng(99)
N_BATCH, B, C, L, E = NUM_BATCHES, 64, 5, 4, 6

bin_probs = [_rng.random(B).astype(np.float32) for _ in range(N_BATCH)]
bin_target = [_rng.integers(0, 2, B).astype(np.int32) for _ in range(N_BATCH)]
mc_logits = [_rng.standard_normal((B, C)).astype(np.float32) for _ in range(N_BATCH)]
mc_target = [_rng.integers(0, C, B).astype(np.int32) for _ in range(N_BATCH)]
mc_logits_md = [_rng.standard_normal((B, C, E)).astype(np.float32) for _ in range(N_BATCH)]
mc_target_md = [_rng.integers(0, C, (B, E)).astype(np.int32) for _ in range(N_BATCH)]
ml_probs = [_rng.random((B, L)).astype(np.float32) for _ in range(N_BATCH)]
ml_target = [_rng.integers(0, 2, (B, L)).astype(np.int32) for _ in range(N_BATCH)]
reg_preds = [_rng.standard_normal(B).astype(np.float32) for _ in range(N_BATCH)]
reg_target = [(p + 0.3 * _rng.standard_normal(B)).astype(np.float32) for p in reg_preds]
reg_pos_preds = [np.abs(p) + 0.1 for p in reg_preds]
reg_pos_target = [np.abs(t) + 0.1 for t in reg_target]

_INPUTS = {
    "binary": (bin_probs, bin_target),
    "multiclass": (mc_logits, mc_target),
    "multiclass_md": (mc_logits_md, mc_target_md),
    "multilabel": (ml_probs, ml_target),
    "regression": (reg_preds, reg_target),
    "regression_pos": (reg_pos_preds, reg_pos_target),
}

CASES = [
    # classification: binary
    (tmc.BinaryStatScores, {}, tmf.binary_stat_scores, {}, "binary"),
    (tmc.BinaryFBetaScore, {"beta": 0.5}, tmf.binary_fbeta_score, {"beta": 0.5}, "binary"),
    (tmc.BinaryHammingDistance, {}, tmf.binary_hamming_distance, {}, "binary"),
    (tmc.BinaryHingeLoss, {}, tmf.binary_hinge_loss, {}, "binary"),
    (tmc.BinaryConfusionMatrix, {}, tmf.binary_confusion_matrix, {}, "binary"),
    (tmc.BinaryROC, {"thresholds": 16}, tmf.binary_roc, {"thresholds": 16}, "binary"),
    # classification: multiclass
    (tmc.MulticlassStatScores, {"num_classes": C}, tmf.multiclass_stat_scores, {"num_classes": C}, "multiclass"),
    (
        tmc.MulticlassFBetaScore,
        {"num_classes": C, "beta": 2.0},
        tmf.multiclass_fbeta_score,
        {"num_classes": C, "beta": 2.0},
        "multiclass",
    ),
    (
        tmc.MulticlassHammingDistance,
        {"num_classes": C},
        tmf.multiclass_hamming_distance,
        {"num_classes": C},
        "multiclass",
    ),
    (tmc.MulticlassHingeLoss, {"num_classes": C}, tmf.multiclass_hinge_loss, {"num_classes": C}, "multiclass"),
    (
        tmc.MulticlassCalibrationError,
        {"num_classes": C, "n_bins": 10},
        tmf.multiclass_calibration_error,
        {"num_classes": C, "n_bins": 10},
        "multiclass",
    ),
    (
        tmc.MulticlassSpecificity,
        {"num_classes": C},
        tmf.multiclass_specificity,
        {"num_classes": C},
        "multiclass",
    ),
    (
        tmc.MulticlassExactMatch,
        {"num_classes": C},
        tmf.multiclass_exact_match,
        {"num_classes": C},
        "multiclass_md",
    ),
    (
        tmc.MulticlassPrecisionRecallCurve,
        {"num_classes": C, "thresholds": 16},
        tmf.multiclass_precision_recall_curve,
        {"num_classes": C, "thresholds": 16},
        "multiclass",
    ),
    (
        tmc.MulticlassPrecisionAtFixedRecall,
        {"num_classes": C, "min_recall": 0.5, "thresholds": 32},
        tmf.multiclass_precision_at_fixed_recall,
        {"num_classes": C, "min_recall": 0.5, "thresholds": 32},
        "multiclass",
    ),
    (
        tmc.MulticlassRecallAtFixedPrecision,
        {"num_classes": C, "min_precision": 0.5, "thresholds": 32},
        tmf.multiclass_recall_at_fixed_precision,
        {"num_classes": C, "min_precision": 0.5, "thresholds": 32},
        "multiclass",
    ),
    (
        tmc.MulticlassSpecificityAtSensitivity,
        {"num_classes": C, "min_sensitivity": 0.5, "thresholds": 32},
        tmf.multiclass_specificity_at_sensitivity,
        {"num_classes": C, "min_sensitivity": 0.5, "thresholds": 32},
        "multiclass",
    ),
    # classification: multilabel
    (tmc.MultilabelStatScores, {"num_labels": L}, tmf.multilabel_stat_scores, {"num_labels": L}, "multilabel"),
    (
        tmc.MultilabelFBetaScore,
        {"num_labels": L, "beta": 0.5},
        tmf.multilabel_fbeta_score,
        {"num_labels": L, "beta": 0.5},
        "multilabel",
    ),
    (
        tmc.MultilabelHammingDistance,
        {"num_labels": L},
        tmf.multilabel_hamming_distance,
        {"num_labels": L},
        "multilabel",
    ),
    (
        tmc.MultilabelConfusionMatrix,
        {"num_labels": L},
        tmf.multilabel_confusion_matrix,
        {"num_labels": L},
        "multilabel",
    ),
    (tmc.MultilabelROC, {"num_labels": L, "thresholds": 16}, tmf.multilabel_roc, {"num_labels": L, "thresholds": 16}, "multilabel"),
    (
        tmc.MultilabelJaccardIndex,
        {"num_labels": L},
        tmf.multilabel_jaccard_index,
        {"num_labels": L},
        "multilabel",
    ),
    (
        tmc.MultilabelMatthewsCorrCoef,
        {"num_labels": L},
        tmf.multilabel_matthews_corrcoef,
        {"num_labels": L},
        "multilabel",
    ),
    (
        tmc.MultilabelExactMatch,
        {"num_labels": L},
        tmf.multilabel_exact_match,
        {"num_labels": L},
        "multilabel",
    ),
    (
        tmc.MultilabelSpecificity,
        {"num_labels": L},
        tmf.multilabel_specificity,
        {"num_labels": L},
        "multilabel",
    ),
    (
        tmc.MultilabelPrecisionAtFixedRecall,
        {"num_labels": L, "min_recall": 0.5, "thresholds": 32},
        tmf.multilabel_precision_at_fixed_recall,
        {"num_labels": L, "min_recall": 0.5, "thresholds": 32},
        "multilabel",
    ),
    (
        tmc.MultilabelRecallAtFixedPrecision,
        {"num_labels": L, "min_precision": 0.5, "thresholds": 32},
        tmf.multilabel_recall_at_fixed_precision,
        {"num_labels": L, "min_precision": 0.5, "thresholds": 32},
        "multilabel",
    ),
    (
        tmc.MultilabelSpecificityAtSensitivity,
        {"num_labels": L, "min_sensitivity": 0.5, "thresholds": 32},
        tmf.multilabel_specificity_at_sensitivity,
        {"num_labels": L, "min_sensitivity": 0.5, "thresholds": 32},
        "multilabel",
    ),
    # regression
    (tmr.CosineSimilarity, {}, tmfr.cosine_similarity, {}, "regression"),
    (tmr.MinkowskiDistance, {"p": 3.0}, tmfr.minkowski_distance, {"p": 3.0}, "regression"),
    (tmr.RelativeSquaredError, {}, tmfr.relative_squared_error, {}, "regression"),
    (
        tmr.SymmetricMeanAbsolutePercentageError,
        {},
        tmfr.symmetric_mean_absolute_percentage_error,
        {},
        "regression_pos",
    ),
    (
        tmr.WeightedMeanAbsolutePercentageError,
        {},
        tmfr.weighted_mean_absolute_percentage_error,
        {},
        "regression_pos",
    ),
    (
        tmr.TweedieDevianceScore,
        {"power": 1.5},
        tmfr.tweedie_deviance_score,
        {"power": 1.5},
        "regression_pos",
    ),
]


@pytest.mark.parametrize(
    ("metric_class", "args", "fn", "fn_args", "kind"),
    CASES,
    ids=[c[0].__name__ for c in CASES],
)
def test_class_accumulates_to_functional(metric_class, args, fn, fn_args, kind):
    """Full protocol harness (const-attr guard, pickle, clone, forward-vs-
    update agreement, state_dict) with the functional form as the reference."""
    preds, target = _INPUTS[kind]
    _class_test(
        [jnp.asarray(p) for p in preds],
        [jnp.asarray(t) for t in target],
        metric_class,
        lambda p, t: fn(jnp.asarray(p), jnp.asarray(t), **fn_args),
        metric_args=args,
        atol=1e-5,
    )


def test_task_wrappers_dispatch_extra():
    assert isinstance(tmc.StatScores(task="binary"), tmc.BinaryStatScores)
    assert isinstance(tmc.FBetaScore(task="multiclass", num_classes=C, beta=0.5), tmc.MulticlassFBetaScore)
    assert isinstance(tmc.HammingDistance(task="multilabel", num_labels=L), tmc.MultilabelHammingDistance)
    assert isinstance(tmc.HingeLoss(task="binary"), tmc.BinaryHingeLoss)
    assert isinstance(tmc.ExactMatch(task="multiclass", num_classes=C), tmc.MulticlassExactMatch)
    assert isinstance(tmc.ConfusionMatrix(task="multilabel", num_labels=L), tmc.MultilabelConfusionMatrix)
