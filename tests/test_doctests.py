"""Run every docstring example in the package (VERDICT r1 weak #7: the
doctests passed but nothing executed them in CI). The persistent JAX
compilation cache configured in conftest makes warm runs cheap."""

from __future__ import annotations

import doctest
import importlib
import pkgutil

import pytest

import tpumetrics


def _iter_modules():
    for info in pkgutil.walk_packages(tpumetrics.__path__, prefix="tpumetrics."):
        yield info.name


@pytest.mark.parametrize("module_name", sorted(_iter_modules()))
def test_module_doctests(module_name):
    module = importlib.import_module(module_name)
    results = doctest.testmod(
        module,
        optionflags=doctest.ELLIPSIS | doctest.NORMALIZE_WHITESPACE,
        verbose=False,
    )
    assert results.failed == 0, f"{results.failed} doctest failure(s) in {module_name}"
