"""Run every docstring example in the package (VERDICT r1 weak #7: the
doctests passed but nothing executed them in CI). The persistent JAX
compilation cache configured in conftest makes warm runs cheap."""

from __future__ import annotations

import doctest
import importlib
import pkgutil

import pytest

import tpumetrics


def _iter_modules():
    for info in pkgutil.walk_packages(tpumetrics.__path__, prefix="tpumetrics."):
        yield info.name


@pytest.mark.parametrize("module_name", sorted(_iter_modules()))
def test_module_doctests(module_name):
    module = importlib.import_module(module_name)
    results = doctest.testmod(
        module,
        optionflags=doctest.ELLIPSIS | doctest.NORMALIZE_WHITESPACE,
        verbose=False,
    )
    assert results.failed == 0, f"{results.failed} doctest failure(s) in {module_name}"


def test_every_exported_metric_has_a_runnable_example():
    """CI mirror of docs/_gen_index.py's generation gate: the per-metric doc
    pages embed class docstrings, and the sweep above executes what's
    embedded — so every exported metric class must carry a doctest block."""
    import inspect

    from tpumetrics.metric import Metric

    missing = [
        n
        for n in tpumetrics.__all__
        if inspect.isclass(getattr(tpumetrics, n, None))
        and issubclass(getattr(tpumetrics, n), Metric)
        and getattr(tpumetrics, n) is not Metric
        and ">>>" not in (inspect.getdoc(getattr(tpumetrics, n)) or "")
    ]
    assert not missing, f"exported metric classes without a runnable docstring example: {sorted(missing)}"
