"""Distributed sync tests over the virtual 8-device CPU mesh.

Counterpart of reference tests/unittests/bases/test_ddp.py:33-274, exercised
through shard_map collectives (the ICI path) and the pure merge helper (the
DCN path).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tests.conftest import cpu_mesh as _mesh
from tests.test_metric import DummyListMetric, DummyMeanMetric, DummyMetric
from tpumetrics.parallel import AxisBackend
from tpumetrics.parallel.merge import merge_metric_states

from tests.helpers.testers import shard_map


@pytest.mark.parametrize("world_size", [2, 4, 8])
def test_sum_state_psum_inside_shard_map(world_size):
    metric = DummyMetric()

    def run(x):
        state = metric.init_state()
        state = metric.functional_update(state, x[0])
        return metric.functional_compute(state, axis_name="r")

    xs = jnp.arange(world_size, dtype=jnp.float32).reshape(world_size, 1)
    out = jax.jit(shard_map(run, mesh=_mesh(world_size), in_specs=P("r"), out_specs=P()))(xs)
    assert float(out) == sum(range(world_size))


@pytest.mark.parametrize("world_size", [2, 4])
def test_cat_state_all_gather_inside_shard_map(world_size):
    metric = DummyListMetric()

    def run(x):
        state = metric.init_state()
        state = metric.functional_update(state, x[0])
        return metric.functional_compute(state, axis_name="r")

    xs = jnp.arange(world_size * 3, dtype=jnp.float32).reshape(world_size, 3)
    out = jax.jit(shard_map(run, mesh=_mesh(world_size), in_specs=P("r"), out_specs=P()))(xs)
    assert out.tolist() == list(range(world_size * 3))


def test_mean_metric_distributed_equals_global():
    ws = 4
    metric = DummyMeanMetric()

    def run(x):
        state = metric.init_state()
        state = metric.functional_update(state, x[0])
        return metric.functional_compute(state, axis_name="r")

    rng = np.random.default_rng(1)
    data = rng.normal(size=(ws, 16)).astype(np.float32)
    out = jax.jit(shard_map(run, mesh=_mesh(ws), in_specs=P("r"), out_specs=P()))(jnp.asarray(data))
    assert np.allclose(float(out), data.mean(), atol=1e-6)


def test_merge_metric_states_sum_and_cat():
    m1, m2 = DummyMetric(), DummyMetric()
    m1.update(1.0)
    m2.update(2.0)
    merged = merge_metric_states([m1.metric_state(), m2.metric_state()], m1._reductions)
    assert float(merged["x"]) == 3.0

    l1, l2 = DummyListMetric(), DummyListMetric()
    l1.update(jnp.asarray([1.0, 2.0]))
    l2.update(jnp.asarray([3.0]))
    merged = merge_metric_states([l1.metric_state(), l2.metric_state()], l1._reductions)
    assert merged["x"][0].tolist() == [1.0, 2.0, 3.0]


def test_merge_empty_list_states():
    l1, l2 = DummyListMetric(), DummyListMetric()
    merged = merge_metric_states([l1.metric_state(), l2.metric_state()], l1._reductions)
    assert merged["x"] == []


def test_eager_sync_with_custom_dist_fn():
    """Emulate a 2-rank gather through the dist_sync_fn injection point
    (reference test_ddp.py:33-59)."""
    metric = DummyMetric(
        distributed_available_fn=lambda: True,
        dist_sync_fn=lambda x, group: [x, x],  # each rank contributes the same value
    )
    metric.update(3.0)
    assert float(metric.compute()) == 6.0
    # after compute, state is unsynced back to the local value
    assert float(metric.x) == 3.0


def test_eager_sync_cat_with_custom_dist_fn():
    metric = DummyListMetric(
        distributed_available_fn=lambda: True,
        dist_sync_fn=lambda x, group: [x, x + 10.0],
    )
    metric.update(jnp.asarray([1.0, 2.0]))
    out = metric.compute()
    assert out.tolist() == [1.0, 2.0, 11.0, 12.0]
    assert [v.tolist() for v in metric.x] == [[1.0, 2.0]]


def test_axis_backend_world_size_and_allreduce():
    ws = 4

    def run(x):
        backend = AxisBackend("r", axis_size=ws)
        return backend.all_reduce(x[0, 0], "max")

    xs = jnp.arange(ws, dtype=jnp.float32).reshape(ws, 1)
    out = jax.jit(shard_map(run, mesh=_mesh(ws), in_specs=P("r"), out_specs=P()))(xs)
    assert float(out) == ws - 1


@pytest.mark.parametrize("world_size", [2, 8])
def test_dist_sync_on_step_forward_over_mesh(world_size):
    """`functional_forward` with in-trace sync == reference on the union of the
    step's shards, while the local state keeps accumulating (the
    dist_sync_on_step=True BASELINE config)."""
    from sklearn.metrics import accuracy_score

    from tpumetrics.classification import MulticlassAccuracy

    num_classes = 4
    n_steps = 3
    per_dev = 8
    metric = MulticlassAccuracy(num_classes=num_classes, average="micro", validate_args=False)
    mesh = _mesh(world_size)

    rng = np.random.default_rng(7)
    preds = jnp.asarray(rng.standard_normal((n_steps, world_size * per_dev, num_classes)), dtype=jnp.float32)
    target = jnp.asarray(rng.integers(0, num_classes, (n_steps, world_size * per_dev)), dtype=jnp.int32)

    # carried state is per-device (each device accumulates its own shard), so
    # it must stay sharded over the axis: leading device dim + P("r") specs
    def step(state, p, t):
        local = jax.tree_util.tree_map(lambda x: x[0], state)
        new_state, val = metric.functional_forward(local, p, t, axis_name="r")
        return jax.tree_util.tree_map(lambda x: x[None], new_state), val

    fn = jax.jit(shard_map(step, mesh=mesh, in_specs=(P("r"), P("r"), P("r")), out_specs=(P("r"), P())))

    state = jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x, (world_size,) + x.shape), metric.init_state()
    )
    for i in range(n_steps):
        state, batch_val = fn(state, preds[i], target[i])
        ref = accuracy_score(np.asarray(target[i]), np.argmax(np.asarray(preds[i]), axis=1))
        assert np.allclose(np.asarray(batch_val), ref, atol=1e-6)

    final = jax.jit(
        shard_map(
            lambda s: metric.functional_compute(jax.tree_util.tree_map(lambda x: x[0], s), axis_name="r"),
            mesh=mesh,
            in_specs=(P("r"),),
            out_specs=P(),
        )
    )(state)
    all_t = np.asarray(target).reshape(-1)
    all_p = np.argmax(np.asarray(preds).reshape(-1, num_classes), axis=1)
    assert np.allclose(np.asarray(final), accuracy_score(all_t, all_p), atol=1e-6)


def test_collection_dist_sync_on_step_forward_over_mesh():
    """MetricCollection functional_forward over the mesh: per-step synced values
    for every member of the collection (BASELINE config row 2)."""
    from sklearn.metrics import accuracy_score, f1_score

    from tpumetrics.classification import MulticlassAccuracy, MulticlassF1Score
    from tpumetrics.collections import MetricCollection

    num_classes, world_size, per_dev = 4, 8, 8
    col = MetricCollection(
        {
            "acc": MulticlassAccuracy(num_classes=num_classes, average="micro", validate_args=False),
            "f1": MulticlassF1Score(num_classes=num_classes, average="macro", validate_args=False),
        }
    )
    mesh = _mesh(world_size)
    rng = np.random.default_rng(3)
    preds = jnp.asarray(rng.standard_normal((world_size * per_dev, num_classes)), dtype=jnp.float32)
    target = jnp.asarray(rng.integers(0, num_classes, (world_size * per_dev,)), dtype=jnp.int32)

    def step(state, p, t):
        local = jax.tree_util.tree_map(lambda x: x[0], state)
        new_state, vals = col.functional_forward(local, p, t, axis_name="r")
        return jax.tree_util.tree_map(lambda x: x[None], new_state), vals

    fn = jax.jit(shard_map(step, mesh=mesh, in_specs=(P("r"), P("r"), P("r")), out_specs=(P("r"), P())))
    state = jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x, (world_size,) + x.shape), col.init_state()
    )
    state, vals = fn(state, preds, target)

    t = np.asarray(target)
    p = np.argmax(np.asarray(preds), axis=1)
    assert np.allclose(np.asarray(vals["acc"]), accuracy_score(t, p), atol=1e-6)
    assert np.allclose(np.asarray(vals["f1"]), f1_score(t, p, average="macro"), atol=1e-6)


def test_gather_ragged_list_preserves_boundaries():
    """Reduce-None ragged list states gather item-by-item, preserving
    per-item (e.g. per-image) boundaries and uneven rank counts."""
    import jax.numpy as jnp

    from tpumetrics.metric import _gather_ragged_list

    local = [jnp.ones((2, 4)), 2 * jnp.ones((3, 5))]  # ragged in BOTH dims
    peer = [3 * jnp.ones((1, 7))]

    class _FakeTwoRankBackend:
        """Two collectives: the per-item shape matrix, then the flat data."""

        def __init__(self):
            self.step = 0

        def all_gather(self, v, group=None):
            self.step += 1
            if self.step == 1:
                return [v, jnp.asarray([(p.ndim,) + p.shape for p in peer], jnp.int32)]
            assert self.step == 2, "ragged gather must use exactly two collectives"
            return [v, jnp.concatenate([jnp.ravel(p) for p in peer])]

    merged = _gather_ragged_list(_FakeTwoRankBackend(), local, None, jnp.float32)
    assert len(merged) == 3
    assert merged[0].shape == (2, 4) and merged[1].shape == (3, 5) and merged[2].shape == (1, 7)
    assert abs(float(merged[2].mean()) - 3.0) < 1e-5


# --------------------------------------------- all_reduce per-rank weighting
#
# MultiHostBackend inherits the default gather+local-reduce all_reduce, and
# its all_gather pads/trims uneven dim-0 shapes.  These tests pin the reduce
# semantics at that intersection: "mean" weights each RANK equally (divide by
# world size — psum/pmean semantics), and uneven per-rank shapes must raise a
# clear error rather than zero-pad into a silently-corrupted mean/min.


class _FakePaddedGatherBackend:
    """Stands in for MultiHostBackend's wire: per-rank payloads set at
    construction, gathers replay the pad-gather-trim result (trimmed
    per-rank shapes, exactly what the real backend hands all_reduce)."""

    def __init__(self, per_rank):
        self.per_rank = [jnp.asarray(v) for v in per_rank]

    def available(self):
        return True

    def world_size(self):
        return len(self.per_rank)

    def all_gather(self, x, group=None):
        return list(self.per_rank)


def test_noop_all_reduce_mean_is_identity():
    """World size 1: NoOpBackend's mean must be the rank's own value with
    weight 1 — the degenerate case of equal per-rank weighting."""
    from tpumetrics.parallel import NoOpBackend

    be = NoOpBackend()
    x = jnp.asarray([1.0, 2.0, 3.0])
    np.testing.assert_array_equal(np.asarray(be.all_reduce(x, "mean")), np.asarray(x))
    np.testing.assert_array_equal(np.asarray(be.all_reduce(x, "sum")), np.asarray(x))


def test_default_all_reduce_mean_weights_ranks_equally():
    """The default gather+reduce path divides by WORLD SIZE, not by any
    row count: a rank's mean-state contribution has weight 1/N regardless
    of how much data produced it (matching psum/pmean semantics, which is
    what MeanMetric-style states assume when they carry their own weight
    state alongside)."""
    from tpumetrics.parallel.backend import DistributedBackend

    per_rank = [jnp.asarray([2.0, 4.0]), jnp.asarray([6.0, 8.0]), jnp.asarray([1.0, 3.0])]
    be = _FakePaddedGatherBackend(per_rank)
    got = DistributedBackend.all_reduce(be, per_rank[0], "mean")
    want = np.mean(np.stack([np.asarray(v) for v in per_rank]), axis=0)
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-6)
    got_sum = DistributedBackend.all_reduce(be, per_rank[0], "sum")
    np.testing.assert_allclose(np.asarray(got_sum), want * 3, atol=1e-6)


@pytest.mark.parametrize("op", ["sum", "mean", "max", "min"])
def test_default_all_reduce_uneven_dim0_raises(op):
    """Pad-gather-trim hands all_reduce ragged per-rank arrays when dim-0
    differs; reducing those is undefined (zero-padding would corrupt
    mean/min silently, stacking raggeds would crash deep in jnp): the
    default path must refuse with a clear typed error — TPUMetricsUserError,
    so the resilience retry loop treats it as deterministic, not transient."""
    from tpumetrics.parallel.backend import DistributedBackend
    from tpumetrics.utils.exceptions import TPUMetricsUserError

    be = _FakePaddedGatherBackend([jnp.ones((2, 3)), jnp.ones((4, 3))])
    with pytest.raises(TPUMetricsUserError, match="identical per-rank shapes"):
        DistributedBackend.all_reduce(be, jnp.ones((2, 3)), op)
