"""Tier-1 gate: the tpulint self-run over ``tpumetrics/`` stays clean.

The gate compares the analyzer's unsuppressed findings against the committed
zero-findings baseline (tests/analysis_baseline.json): any new violation —
a host sync sneaking into an update path, a one-branch collective, a shadow
state, a bad ``add_state`` default — fails tier-1 with the rule code in the
assertion message.  The seeded-hazard tests prove the gate actually bites:
each hazard class injected into a fixture metric trips exactly its code
through the SAME gate helper the package run uses.
"""

from __future__ import annotations

import functools
import json
import os
import re
import textwrap

import pytest

from tpumetrics.analysis import analyze_paths

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PACKAGE = os.path.join(_REPO, "tpumetrics")
_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)), "analysis_baseline.json")
_DOCS = os.path.join(_REPO, "docs", "analysis.md")

#: the number of justified inline suppressions the package self-run carries.
#: This pin may only go DOWN silently (a suppression was fixed for real);
#: raising it is a reviewed decision — every new suppression is a claim that
#: a finding was audited and is safe, and the justification must say why.
_SUPPRESSED_PIN = 17


@functools.lru_cache(maxsize=1)
def _package_findings():
    return tuple(analyze_paths([_PACKAGE]))


def _gate_violations(paths):
    """Unsuppressed findings as 'relpath:line:code — message' strings (the
    exact check the package gate and the seeded-hazard tests share)."""
    out = []
    for f in analyze_paths(paths):
        if f.suppressed:
            continue
        rel = os.path.relpath(f.path, _REPO) if f.path.startswith(_REPO) else f.path
        out.append(f"{rel}:{f.line}:{f.code} — {f.message}")
    return out


def _baseline_allowed():
    with open(_BASELINE) as fh:
        payload = json.load(fh)
    assert payload["version"] == 1
    return payload["allowed_unsuppressed"]


def test_package_self_run_matches_zero_findings_baseline():
    allowed = _baseline_allowed()
    assert allowed == [], "the baseline must stay empty: fix or inline-suppress instead"
    violations = []
    for f in _package_findings():
        if f.suppressed:
            continue
        rel = os.path.relpath(f.path, _REPO) if f.path.startswith(_REPO) else f.path
        violations.append(f"{rel}:{f.line}:{f.code} — {f.message}")
    assert violations == allowed, (
        "tpulint found new violations in tpumetrics/ — fix them or add an inline "
        "`# tpulint: disable=CODE -- why` suppression:\n" + "\n".join(violations)
    )


def test_package_suppressed_count_stays_pinned():
    """The suppression budget can only move deliberately.  Fewer suppressed
    findings than the pin means a suppression was genuinely fixed — lower
    the pin in the same change.  More means someone added a suppression:
    that is a reviewed decision, not drive-by lint hygiene, so the pin (and
    the new `-- why`) must move together in the diff."""
    suppressed = [f for f in _package_findings() if f.suppressed]
    assert len(suppressed) == _SUPPRESSED_PIN, (
        f"package self-run carries {len(suppressed)} suppressed findings, "
        f"pin says {_SUPPRESSED_PIN} — update _SUPPRESSED_PIN deliberately "
        "(down: a suppression was fixed; up: justify the new suppression):\n"
        + "\n".join(
            f"{os.path.relpath(f.path, _REPO)}:{f.line}:{f.code} -- {f.justification}"
            for f in suppressed
        )
    )
    # every suppression carries its written justification (TPL901 enforces
    # this for NEW ones; this asserts the invariant over the standing set)
    assert all(f.justification for f in suppressed)


def test_docs_rule_table_covers_catalog():
    """Docs drift gate: every CATALOG code must have a row in the
    docs/analysis.md rule table (| TPLxxx | name | ... |) — a rule shipped
    without its documented contract is invisible to the people the lint
    messages point at the docs."""
    from tpumetrics.analysis.rules import CATALOG

    with open(_DOCS, encoding="utf-8") as fh:
        text = fh.read()
    documented = set(re.findall(r"^\|\s*(TPL\d{3})\s*\|", text, flags=re.MULTILINE))
    missing = sorted(set(CATALOG) - documented)
    assert not missing, f"rules missing from the docs/analysis.md table: {missing}"
    stale = sorted(documented - set(CATALOG))
    assert not stale, f"docs/analysis.md documents codes no rule implements: {stale}"


_SEEDS = {
    "TPL101": """
        def update(self, preds, target):
            self.total = self.total + float(jnp.sum(preds))
    """,
    "TPL102": """
        def update(self, preds, target):
            if jnp.any(preds > 0):
                self.total = self.total + 1.0
    """,
    "TPL401": """
        def update(self, preds, target):
            self.hidden = jnp.sum(preds)
            self.total = self.total + self.hidden
    """,
}


@pytest.mark.parametrize("code", sorted(_SEEDS))
def test_seeded_hazard_trips_gate_with_its_code(tmp_path, code):
    src = textwrap.dedent(
        """
        import jax
        import jax.numpy as jnp
        from tpumetrics.metric import Metric

        class Seeded(Metric):
            def __init__(self, **kw):
                super().__init__(**kw)
                self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")
        {update}
            def compute(self):
                return self.total
        """
    ).format(update=textwrap.indent(textwrap.dedent(_SEEDS[code]), "    "))
    (tmp_path / "seeded.py").write_text(src)
    violations = _gate_violations([str(tmp_path)])
    assert violations, f"seeded {code} hazard must fail the gate"
    assert all(f":{code} " in v or f":{code} —" in v for v in violations), violations


def test_seeded_one_branch_collective_trips_gate(tmp_path):
    (tmp_path / "seeded.py").write_text(
        textwrap.dedent(
            """
            def flush(backend, values, rank):
                if rank == 0:
                    return backend.all_reduce(values)
                return values
            """
        )
    )
    violations = _gate_violations([str(tmp_path)])
    assert len(violations) == 1 and ":TPL201" in violations[0]


def test_seeded_bad_state_default_trips_gate(tmp_path):
    (tmp_path / "seeded.py").write_text(
        textwrap.dedent(
            """
            import jax.numpy as jnp
            from tpumetrics.metric import Metric

            class Seeded(Metric):
                def __init__(self, **kw):
                    super().__init__(**kw)
                    self.add_state("low", jnp.zeros(()), dist_reduce_fx="min")

                def update(self, x):
                    self.low = jnp.minimum(self.low, jnp.min(x))

                def compute(self):
                    return self.low
            """
        )
    )
    violations = _gate_violations([str(tmp_path)])
    assert len(violations) == 1 and ":TPL301" in violations[0]


def test_seeded_blocking_under_lock_trips_gate(tmp_path):
    """The concurrency plane bites through the same gate helper: a device
    fetch under a declared lock (the PR-15 stats() shape) fails tier-1."""
    (tmp_path / "seeded.py").write_text(
        textwrap.dedent(
            """
            import threading
            import jax

            class Evaluator:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._latest = None

                def stats(self):
                    with self._lock:
                        return jax.device_get(self._latest)
            """
        )
    )
    violations = _gate_violations([str(tmp_path)])
    assert len(violations) == 1 and ":TPL123" in violations[0]


def test_unjustified_suppression_trips_gate(tmp_path):
    """Suppressing without a `-- why` is itself a gate failure (TPL901):
    the self-run's clean state certifies every exception was justified."""
    (tmp_path / "seeded.py").write_text(
        textwrap.dedent(
            """
            import jax.numpy as jnp
            from tpumetrics.metric import Metric

            class Seeded(Metric):
                def __init__(self, **kw):
                    super().__init__(**kw)
                    self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

                def update(self, preds):
                    self.total = self.total + float(jnp.sum(preds))  # tpulint: disable=TPL101

                def compute(self):
                    return self.total
            """
        )
    )
    violations = _gate_violations([str(tmp_path)])
    assert len(violations) == 1 and ":TPL901" in violations[0]
