"""Tier-1 gate: the tpulint self-run over ``tpumetrics/`` stays clean.

The gate compares the analyzer's unsuppressed findings against the committed
zero-findings baseline (tests/analysis_baseline.json): any new violation —
a host sync sneaking into an update path, a one-branch collective, a shadow
state, a bad ``add_state`` default — fails tier-1 with the rule code in the
assertion message.  The seeded-hazard tests prove the gate actually bites:
each hazard class injected into a fixture metric trips exactly its code
through the SAME gate helper the package run uses.
"""

from __future__ import annotations

import json
import os
import textwrap

import pytest

from tpumetrics.analysis import analyze_paths

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PACKAGE = os.path.join(_REPO, "tpumetrics")
_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)), "analysis_baseline.json")


def _gate_violations(paths):
    """Unsuppressed findings as 'relpath:line:code — message' strings (the
    exact check the package gate and the seeded-hazard tests share)."""
    out = []
    for f in analyze_paths(paths):
        if f.suppressed:
            continue
        rel = os.path.relpath(f.path, _REPO) if f.path.startswith(_REPO) else f.path
        out.append(f"{rel}:{f.line}:{f.code} — {f.message}")
    return out


def _baseline_allowed():
    with open(_BASELINE) as fh:
        payload = json.load(fh)
    assert payload["version"] == 1
    return payload["allowed_unsuppressed"]


def test_package_self_run_matches_zero_findings_baseline():
    allowed = _baseline_allowed()
    assert allowed == [], "the baseline must stay empty: fix or inline-suppress instead"
    violations = _gate_violations([_PACKAGE])
    assert violations == allowed, (
        "tpulint found new violations in tpumetrics/ — fix them or add an inline "
        "`# tpulint: disable=CODE -- why` suppression:\n" + "\n".join(violations)
    )


_SEEDS = {
    "TPL101": """
        def update(self, preds, target):
            self.total = self.total + float(jnp.sum(preds))
    """,
    "TPL102": """
        def update(self, preds, target):
            if jnp.any(preds > 0):
                self.total = self.total + 1.0
    """,
    "TPL401": """
        def update(self, preds, target):
            self.hidden = jnp.sum(preds)
            self.total = self.total + self.hidden
    """,
}


@pytest.mark.parametrize("code", sorted(_SEEDS))
def test_seeded_hazard_trips_gate_with_its_code(tmp_path, code):
    src = textwrap.dedent(
        """
        import jax
        import jax.numpy as jnp
        from tpumetrics.metric import Metric

        class Seeded(Metric):
            def __init__(self, **kw):
                super().__init__(**kw)
                self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")
        {update}
            def compute(self):
                return self.total
        """
    ).format(update=textwrap.indent(textwrap.dedent(_SEEDS[code]), "    "))
    (tmp_path / "seeded.py").write_text(src)
    violations = _gate_violations([str(tmp_path)])
    assert violations, f"seeded {code} hazard must fail the gate"
    assert all(f":{code} " in v or f":{code} —" in v for v in violations), violations


def test_seeded_one_branch_collective_trips_gate(tmp_path):
    (tmp_path / "seeded.py").write_text(
        textwrap.dedent(
            """
            def flush(backend, values, rank):
                if rank == 0:
                    return backend.all_reduce(values)
                return values
            """
        )
    )
    violations = _gate_violations([str(tmp_path)])
    assert len(violations) == 1 and ":TPL201" in violations[0]


def test_seeded_bad_state_default_trips_gate(tmp_path):
    (tmp_path / "seeded.py").write_text(
        textwrap.dedent(
            """
            import jax.numpy as jnp
            from tpumetrics.metric import Metric

            class Seeded(Metric):
                def __init__(self, **kw):
                    super().__init__(**kw)
                    self.add_state("low", jnp.zeros(()), dist_reduce_fx="min")

                def update(self, x):
                    self.low = jnp.minimum(self.low, jnp.min(x))

                def compute(self):
                    return self.low
            """
        )
    )
    violations = _gate_violations([str(tmp_path)])
    assert len(violations) == 1 and ":TPL301" in violations[0]


def test_unjustified_suppression_trips_gate(tmp_path):
    """Suppressing without a `-- why` is itself a gate failure (TPL901):
    the self-run's clean state certifies every exception was justified."""
    (tmp_path / "seeded.py").write_text(
        textwrap.dedent(
            """
            import jax.numpy as jnp
            from tpumetrics.metric import Metric

            class Seeded(Metric):
                def __init__(self, **kw):
                    super().__init__(**kw)
                    self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

                def update(self, preds):
                    self.total = self.total + float(jnp.sum(preds))  # tpulint: disable=TPL101

                def compute(self):
                    return self.total
            """
        )
    )
    violations = _gate_violations([str(tmp_path)])
    assert len(violations) == 1 and ":TPL901" in violations[0]
