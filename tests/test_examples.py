"""Every shipped example must run green end-to-end (the reference keeps its
``examples/`` exercised through docs builds; here they run directly)."""

from __future__ import annotations

import os
import subprocess
import sys

import pytest

_EXAMPLES_DIR = os.path.join(os.path.dirname(__file__), "..", "examples")
_EXAMPLES = sorted(f for f in os.listdir(_EXAMPLES_DIR) if f.endswith(".py"))


@pytest.mark.parametrize("example", _EXAMPLES)
def test_example_runs(example):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # examples pick their own platform
    out = subprocess.run(
        [sys.executable, os.path.join(_EXAMPLES_DIR, example)],
        capture_output=True,
        text=True,
        timeout=420,
        env=env,
    )
    assert out.returncode == 0, f"{example} failed:\n{out.stdout[-1500:]}\n{out.stderr[-1500:]}"
    assert "OK" in out.stdout, f"{example} did not reach its final assertion:\n{out.stdout[-500:]}"
