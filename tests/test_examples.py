"""Every shipped example must run green end-to-end AND produce sane values.

The reference keeps its ``examples/`` exercised through docs builds; here
each example runs as a subprocess and its printed outputs are parsed and
asserted (value ranges, relationships, produced files) so example rot is
caught — a smoke "OK" alone would not notice a metric silently returning
garbage."""

from __future__ import annotations

import os
import re
import subprocess
import sys

import pytest

_EXAMPLES_DIR = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "examples"))
_EXAMPLES = sorted(f for f in os.listdir(_EXAMPLES_DIR) if f.endswith(".py"))


def _floats(pattern: str, text: str):
    return [float(v) for v in re.findall(pattern, text)]


def _check_bert_score(out: str) -> None:
    f1s = _floats(r"f1=(-?[0-9.]+)", out)
    assert f1s, "no per-pair f1 lines"
    assert all(0.0 <= v <= 1.0 + 1e-6 for v in f1s)
    streamed = re.search(r"streamed idf f1: \[([^\]]+)\]", out)
    assert streamed, "no streamed idf line"
    vals = [float(v) for v in streamed.group(1).split(",")]
    assert vals and all(-1.0 <= v <= 1.0 + 1e-6 for v in vals)


def _check_detection_map(out: str) -> None:
    m = {k: _floats(rf"{k}\s*= ([0-9.\-]+)", out) for k in ("mAP", "mAP@50", "mAP@75")}
    assert all(len(v) == 1 for v in m.values()), out
    # jittered-box corpus: real signal, ordered as COCO demands
    assert 0.0 < m["mAP"][0] <= m["mAP@50"][0] <= 1.0
    aps = _floats(r"class \d+: AP = ([0-9.\-]+)", out)
    assert aps and all(-1.0 <= v <= 1.0 for v in aps)


def _check_multihost(out: str) -> None:
    vals = {k: v for k, v in re.findall(r"^(acc|f1|auroc): ([0-9.]+)$", out, re.M)}
    assert set(vals) == {"acc", "f1", "auroc"}, out
    # random logits over 10 classes: accuracy must sit near chance, auroc near 0.5
    assert 0.02 <= float(vals["acc"]) <= 0.3
    assert 0.3 <= float(vals["auroc"]) <= 0.7


def _check_plotting(out: str) -> None:
    paths = re.findall(r"^wrote (.+)$", out, re.M)
    assert paths, "plotting example wrote no files"
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(_EXAMPLES_DIR, "..", p)
        assert os.path.isfile(full) and os.path.getsize(full) > 0, p


def _check_rouge(out: str) -> None:
    default = _floats(r"default tokenization\s+rouge1_fmeasure = ([0-9.]+)", out)
    custom = _floats(r"hyphens kept\s+rouge1_fmeasure = ([0-9.]+)", out)
    assert len(default) == 1 and len(custom) == 1
    assert 0.0 <= default[0] <= 1.0 and 0.0 <= custom[0] <= 1.0
    # the example's whole point: the custom tokenizer changes the score
    assert default[0] != custom[0]


def _check_segm_map(out: str) -> None:
    map50 = _floats(r"segm mAP@50: ([0-9.\-]+)", out)
    lpips = _floats(r"LPIPS mean over 8 pairs: ([0-9.\-]+)", out)
    assert len(map50) == 1 and 0.0 < map50[0] <= 1.0
    assert len(lpips) == 1 and lpips[0] >= 0.0


def _check_train_loop(out: str) -> None:
    epochs = re.findall(r"^epoch \d+: (.+)$", out, re.M)
    assert len(epochs) >= 2, out
    def parse(line):
        return {k: float(v) for k, v in re.findall(r"(\w+)=([0-9.\-]+)", line)}
    first, last = parse(epochs[0]), parse(epochs[-1])
    assert {"acc", "loss"} <= set(first), first
    # training on a learnable synthetic task must actually learn
    assert last["loss"] < first["loss"], (first, last)
    assert last["acc"] >= first["acc"] - 1e-6, (first, last)
    assert 0.0 <= last["acc"] <= 1.0


_CHECKS = {
    "bert_score-own_model.py": _check_bert_score,
    "detection_map.py": _check_detection_map,
    "multihost_eval.py": _check_multihost,
    "plotting.py": _check_plotting,
    "rouge_score-own_normalizer_and_tokenizer.py": _check_rouge,
    "segm_map_and_lpips.py": _check_segm_map,
    "train_loop_flax.py": _check_train_loop,
}


def test_every_example_has_a_value_check():
    assert set(_CHECKS) == set(_EXAMPLES), (
        "examples and value-checks out of sync: "
        f"missing={sorted(set(_EXAMPLES) - set(_CHECKS))} stale={sorted(set(_CHECKS) - set(_EXAMPLES))}"
    )


# Examples pick their own platform so a healthy local accelerator gets
# exercised end-to-end.  But a sick/contended accelerator boot (the tunneled
# PJRT plugin can block for MINUTES per subprocess while holding
# /tmp/libtpu_lockfile) must not eat the tier-1 budget 420s at a time, 8
# examples in a row — probe the boot ONCE with a hard bound and pin the
# examples to CPU for the session when it can't come up quickly.
_PLATFORM_PROBE: dict = {}


def _accelerator_boots_quickly(timeout: float = 90.0) -> bool:
    if "ok" not in _PLATFORM_PROBE:
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        try:
            out = subprocess.run(
                [sys.executable, "-c", "import jax; print(jax.devices()[0].platform)"],
                capture_output=True, text=True, timeout=timeout, env=env,
            )
            _PLATFORM_PROBE["ok"] = out.returncode == 0
        except subprocess.TimeoutExpired:
            _PLATFORM_PROBE["ok"] = False
    return _PLATFORM_PROBE["ok"]


def _example_env() -> dict:
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # examples pick their own platform...
    if not _accelerator_boots_quickly():
        env["JAX_PLATFORMS"] = "cpu"  # ...unless booting it is the bottleneck
    return env


@pytest.mark.parametrize("example", _EXAMPLES)
def test_example_runs(example, tmp_path):
    env = _example_env()
    # route plot outputs to the test's tmpdir: regenerating the checked-in
    # examples/_plots/*.png on every tier-1 run dirtied the working tree
    # (and had to be checked out before every commit)
    env["TPUMETRICS_PLOT_DIR"] = str(tmp_path / "plots")
    out = subprocess.run(
        [sys.executable, os.path.join(_EXAMPLES_DIR, example)],
        capture_output=True,
        text=True,
        timeout=420,
        env=env,
    )
    assert out.returncode == 0, f"{example} failed:\n{out.stdout[-1500:]}\n{out.stderr[-1500:]}"
    assert "OK" in out.stdout, f"{example} did not reach its final assertion:\n{out.stdout[-500:]}"
    check = _CHECKS.get(example)
    if check is not None:
        check(out.stdout)
