"""Pallas kernel semantics, pinned via the interpreter (CPU-safe).

The kernels in ``tpumetrics/ops`` are explicit alternatives to XLA paths;
these tests pin their exact semantics so the kernel code stays correct even
while it is not the default lowering (see the module docstrings)."""

import jax.numpy as jnp
import numpy as np
import pytest

from tpumetrics.ops import binned_confusion_fused


@pytest.mark.parametrize("n,c,t", [(257, 5, 13), (64, 1, 3), (130, 4, 129)])
def test_binned_confusion_fused_matches_bruteforce(n, c, t):
    rng = np.random.default_rng(42)
    preds = rng.random((n, c)).astype(np.float32)
    bits = rng.integers(0, 2, (n, c)).astype(np.float32)
    valid = rng.integers(0, 2, (n, c)).astype(np.float32)
    y = bits * valid
    thr = np.sort(rng.random(t).astype(np.float32))
    # exact ties at thresholds exercise the >= semantics
    preds[: min(n, t), 0] = thr[: min(n, t)]

    tp, pp = binned_confusion_fused(
        jnp.asarray(preds), jnp.asarray(y), jnp.asarray(valid), jnp.asarray(thr), interpret=True
    )
    pos = (preds[:, :, None] >= thr[None, None, :]).astype(np.float64)
    tp_ref = np.einsum("nct,nc->tc", pos, y)
    pp_ref = np.einsum("nct,nc->tc", pos, valid)
    assert np.array_equal(np.asarray(tp), tp_ref)
    assert np.array_equal(np.asarray(pp), pp_ref)


def test_binned_confusion_fused_nan_preds_below_all_thresholds():
    preds = jnp.asarray([[0.2], [float("nan")], [0.8]], dtype=jnp.float32)
    y = jnp.asarray([[1.0], [1.0], [0.0]])
    v = jnp.ones((3, 1), jnp.float32)
    thr = jnp.asarray([0.5], dtype=jnp.float32)
    tp, pp = binned_confusion_fused(preds, y, v, thr, interpret=True)
    # NaN >= thr is False: only the 0.8/y=0 sample is predicted positive
    assert float(tp[0, 0]) == 0.0
    assert float(pp[0, 0]) == 1.0
