"""Epoch-lifecycle integration tests (VERDICT r4 missing #2).

Counterpart of the reference's train-framework integration tier
(reference tests/integrations/test_lightning.py): a flax/optax classifier
under ``jit`` + ``shard_map`` with a ``MetricCollection``, exercising the
full epoch contract —

  forward-during-train → compute-at-epoch-end → reset → next epoch,
  with an orbax checkpoint mid-stream and a restore that continues to
  EXACTLY the uninterrupted run's numbers.

Metric state is carried with an EXPLICIT leading device axis
(``out_specs=P("dp")``), the pattern ``Metric.functional_forward``'s
docstring prescribes: a falsely-replicated ``P()`` carry happens to work
in-loop (buffers stay per-device) but would checkpoint only device 0's
partial state — these tests pin the checkpoint-correct pattern, and
``examples/train_loop_flax.py`` is built on it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from tests.helpers.testers import shard_map
from tpumetrics import MetricCollection
from tpumetrics.aggregation import MeanMetric
from tpumetrics.classification import MulticlassAccuracy, MulticlassF1Score

flax_nn = pytest.importorskip("flax.linen")
optax = pytest.importorskip("optax")

NUM_CLASSES = 5
FEATURES = 16
BATCH = 64  # global batch over the dp mesh
STEPS_PER_EPOCH = 4
EPOCHS = 3
N_DEV = 8


class _MLP(flax_nn.Module):
    @flax_nn.compact
    def __call__(self, x):
        x = flax_nn.Dense(32)(x)
        x = flax_nn.relu(x)
        return flax_nn.Dense(NUM_CLASSES)(x)


def _make_data(seed=0):
    rng = np.random.default_rng(seed)
    n = BATCH * STEPS_PER_EPOCH * EPOCHS
    x = rng.standard_normal((n, FEATURES), dtype=np.float32)
    w = rng.standard_normal((FEATURES, NUM_CLASSES), dtype=np.float32)
    y = np.argmax(x @ w + 0.3 * rng.standard_normal((n, NUM_CLASSES)), axis=-1).astype(np.int32)
    return jnp.asarray(x), jnp.asarray(y)


def _collection():
    return MetricCollection(
        {
            "acc": MulticlassAccuracy(num_classes=NUM_CLASSES, average="micro", validate_args=False),
            "f1": MulticlassF1Score(num_classes=NUM_CLASSES, average="macro", validate_args=False),
        }
    )


class _Loop:
    """The canonical jitted train loop: params/opt/metric-state threading,
    metric state carried with an explicit leading device axis."""

    def __init__(self, seed=0):
        self.mesh = Mesh(np.array(jax.devices()[:N_DEV]), ("dp",))
        self.model = _MLP()
        self.tx = optax.adam(1e-2)
        self.metrics = _collection()
        self.loss_metric = MeanMetric()
        key = jax.random.PRNGKey(seed)
        self.params = self.model.init(key, jnp.zeros((1, FEATURES)))
        self.opt_state = self.tx.init(self.params)

        model, tx, metrics, loss_metric = self.model, self.tx, self.metrics, self.loss_metric

        def train_step(params, opt_state, metric_state, x, y):
            def loss_fn(p):
                logits = model.apply(p, x)
                return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean(), logits

            (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            grads = jax.lax.pmean(grads, "dp")
            loss = jax.lax.pmean(loss, "dp")
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)

            cls_state, loss_state = jax.tree.map(lambda a: a[0], metric_state)
            cls_state, batch_vals = metrics.functional_forward(cls_state, logits, y, axis_name="dp")
            loss_state = loss_metric.functional_update(loss_state, loss)
            new_state = jax.tree.map(lambda a: a[None], (cls_state, loss_state))
            return params, opt_state, new_state, batch_vals

        # metric state rides with the device axis EXPLICIT: (n_dev, ...)
        self.step = jax.jit(
            shard_map(
                train_step,
                mesh=self.mesh,
                in_specs=(P(), P(), P("dp"), P("dp"), P("dp")),
                out_specs=(P(), P(), P("dp"), P()),
            )
        )

        def _compute(metric_state):
            cls_state, loss_state = jax.tree.map(lambda a: a[0], metric_state)
            vals = metrics.functional_compute(cls_state, axis_name="dp")
            vals["loss"] = loss_metric.functional_compute(loss_state, axis_name="dp")
            return vals

        self.epoch_compute = jax.jit(
            shard_map(_compute, mesh=self.mesh, in_specs=(P("dp"),), out_specs=P())
        )

    def init_metric_state(self):
        """Per-device zero states stacked on a leading device axis; reset ==
        reinit (the functional analogue of ``Metric.reset``)."""
        zero = (self.metrics.init_state(), self.loss_metric.init_state())
        return jax.tree.map(lambda a: jnp.broadcast_to(a[None], (N_DEV,) + a.shape), zero)

    def run_epoch(self, x_epoch, y_epoch, metric_state=None, start_step=0):
        """Advance params/opt through one epoch, returning the epoch's
        accumulated metric state."""
        if metric_state is None:
            metric_state = self.init_metric_state()
        for i in range(start_step, STEPS_PER_EPOCH):
            lo = i * BATCH
            self.params, self.opt_state, metric_state, _ = self.step(
                self.params, self.opt_state, metric_state, x_epoch[lo : lo + BATCH], y_epoch[lo : lo + BATCH]
            )
        return metric_state


def _epoch_slice(x, y, epoch):
    n = BATCH * STEPS_PER_EPOCH
    return x[epoch * n : (epoch + 1) * n], y[epoch * n : (epoch + 1) * n]


def test_epoch_lifecycle_matches_eager_metrics():
    """compute-at-epoch-end after in-jit accumulation equals an eager
    reference collection fed the same per-step logits (the parameter
    trajectory the compiled loop actually took) — across 3 epochs with
    reset-by-reinit between them."""
    loop = _Loop()
    x, y = _make_data()
    for epoch in range(EPOCHS):
        xe, ye = _epoch_slice(x, y, epoch)
        state = loop.init_metric_state()
        ref = _collection()
        ref_loss = []
        for i in range(STEPS_PER_EPOCH):
            lo = i * BATCH
            xb, yb = xe[lo : lo + BATCH], ye[lo : lo + BATCH]
            # the step updates metrics with logits from the INCOMING params —
            # replicate eagerly before the params advance
            logits = loop.model.apply(loop.params, xb)
            ref.update(logits, yb)
            ref_loss.append(
                float(optax.softmax_cross_entropy_with_integer_labels(logits, yb).mean())
            )
            loop.params, loop.opt_state, state, _ = loop.step(
                loop.params, loop.opt_state, state, xb, yb
            )
        vals = loop.epoch_compute(state)
        want = ref.compute()
        for k in ("acc", "f1"):
            np.testing.assert_allclose(
                float(vals[k]), float(want[k]), atol=1e-5, err_msg=f"epoch {epoch} {k}"
            )
        np.testing.assert_allclose(float(vals["loss"]), np.mean(ref_loss), atol=1e-5)


def test_forward_vs_update_equivalence_across_epochs():
    """``functional_forward``'s per-step batch value equals a fresh eager
    collection on exactly that batch, across an epoch boundary (state reinit
    between epochs does not disturb per-batch values)."""
    loop = _Loop(seed=1)
    x, y = _make_data(seed=1)

    for epoch in range(2):
        xe, ye = _epoch_slice(x, y, epoch)
        state = loop.init_metric_state()
        for i in range(STEPS_PER_EPOCH):
            lo = i * BATCH
            xb, yb = xe[lo : lo + BATCH], ye[lo : lo + BATCH]
            logits = loop.model.apply(loop.params, xb)
            loop.params, loop.opt_state, state, batch_vals = loop.step(
                loop.params, loop.opt_state, state, xb, yb
            )
            ref = _collection()
            ref.update(logits, yb)
            want = ref.compute()
            for k in ("acc", "f1"):
                np.testing.assert_allclose(
                    float(batch_vals[k]),
                    float(want[k]),
                    atol=1e-5,
                    err_msg=f"epoch {epoch} step {i} {k}",
                )


def test_checkpoint_restore_continues_identically(tmp_path):
    """orbax checkpoint MID-epoch (params + opt state + device-axis metric
    state); a fresh loop restores and continues; the interrupted epoch's
    metrics, the following epoch's metrics, and the final params all equal
    the uninterrupted run's."""
    orbax = pytest.importorskip("orbax.checkpoint")
    x, y = _make_data(seed=2)

    # uninterrupted run: 3 epochs
    base = _Loop(seed=2)
    per_epoch_vals = []
    for epoch in range(EPOCHS):
        xe, ye = _epoch_slice(x, y, epoch)
        state = base.run_epoch(xe, ye)
        per_epoch_vals.append({k: float(v) for k, v in base.epoch_compute(state).items()})
    want_params = jax.device_get(base.params)

    # interrupted run: epoch 0, then 2 of 4 steps into epoch 1 → checkpoint
    a = _Loop(seed=2)
    xe0, ye0 = _epoch_slice(x, y, 0)
    a.run_epoch(xe0, ye0)
    xe1, ye1 = _epoch_slice(x, y, 1)
    mid_state = a.init_metric_state()
    for i in range(2):
        lo = i * BATCH
        a.params, a.opt_state, mid_state, _ = a.step(
            a.params, a.opt_state, mid_state, xe1[lo : lo + BATCH], ye1[lo : lo + BATCH]
        )
    ckpt = orbax.PyTreeCheckpointer()
    path = tmp_path / "ckpt"
    ckpt.save(path, {"params": a.params, "opt_state": a.opt_state, "metric_state": mid_state})
    del a

    # fresh loop (different seed: EVERYTHING must come from the checkpoint)
    b = _Loop(seed=99)
    template = {
        "params": b.params,
        "opt_state": b.opt_state,
        "metric_state": b.init_metric_state(),
    }
    restored = ckpt.restore(path, item=template)
    b.params = restored["params"]
    b.opt_state = restored["opt_state"]
    state1 = b.run_epoch(xe1, ye1, metric_state=restored["metric_state"], start_step=2)
    vals_epoch1 = {k: float(v) for k, v in b.epoch_compute(state1).items()}
    for k, v in per_epoch_vals[1].items():
        np.testing.assert_allclose(vals_epoch1[k], v, atol=1e-6, err_msg=f"epoch 1 {k}")

    xe2, ye2 = _epoch_slice(x, y, 2)
    state2 = b.run_epoch(xe2, ye2)
    vals_epoch2 = {k: float(v) for k, v in b.epoch_compute(state2).items()}
    for k, v in per_epoch_vals[2].items():
        np.testing.assert_allclose(vals_epoch2[k], v, atol=1e-6, err_msg=f"epoch 2 {k}")
    for leaf_b, leaf_want in zip(
        jax.tree.leaves(jax.device_get(b.params)), jax.tree.leaves(want_params)
    ):
        np.testing.assert_allclose(leaf_b, leaf_want, atol=1e-6)


def test_reset_isolates_epochs():
    """Reinit between epochs fully clears accumulation: an epoch preceded by
    a discarded epoch of foreign data computes the same values as the same
    epoch run alone (identical param threading)."""
    loop = _Loop(seed=3)
    x, y = _make_data(seed=3)
    xe0, ye0 = _epoch_slice(x, y, 0)
    xe1, ye1 = _epoch_slice(x, y, 1)
    params0, opt0 = loop.params, loop.opt_state

    def run_epoch1(params, opt, state):
        for i in range(STEPS_PER_EPOCH):
            lo = i * BATCH
            params, opt, state, _ = loop.step(params, opt, state, xe1[lo : lo + BATCH], ye1[lo : lo + BATCH])
        return state

    vals_direct = loop.epoch_compute(run_epoch1(params0, opt0, loop.init_metric_state()))

    # pollute a state with epoch-0 data (params frozen), then reset
    st = loop.init_metric_state()
    for i in range(STEPS_PER_EPOCH):
        lo = i * BATCH
        _, _, st, _ = loop.step(params0, opt0, st, xe0[lo : lo + BATCH], ye0[lo : lo + BATCH])
    st = loop.init_metric_state()  # reset
    vals_after_reset = loop.epoch_compute(run_epoch1(params0, opt0, st))
    for k in ("acc", "f1", "loss"):
        np.testing.assert_allclose(
            float(vals_after_reset[k]), float(vals_direct[k]), atol=1e-6, err_msg=k
        )
