"""Clustering domain vs sklearn (counterpart of reference
``tests/unittests/clustering/``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn import metrics as sklearn_metrics

from tests.conftest import BATCH_SIZE, NUM_BATCHES
from tests.helpers.testers import MetricTester
from tpumetrics.clustering import (
    AdjustedMutualInfoScore,
    AdjustedRandScore,
    CalinskiHarabaszScore,
    CompletenessScore,
    DaviesBouldinScore,
    DunnIndex,
    FowlkesMallowsIndex,
    HomogeneityScore,
    MutualInfoScore,
    NormalizedMutualInfoScore,
    RandScore,
    VMeasureScore,
)
from tpumetrics.functional.clustering import (
    adjusted_mutual_info_score,
    adjusted_rand_score,
    calinski_harabasz_score,
    completeness_score,
    davies_bouldin_score,
    dunn_index,
    fowlkes_mallows_index,
    homogeneity_score,
    mutual_info_score,
    normalized_mutual_info_score,
    rand_score,
    v_measure_score,
)

_rng = np.random.default_rng(42)
NUM_CLUSTERS = 6
# extrinsic inputs: integer label pairs
PREDS = [jnp.asarray(_rng.integers(0, NUM_CLUSTERS, BATCH_SIZE)) for _ in range(NUM_BATCHES)]
TARGET = [jnp.asarray(_rng.integers(0, NUM_CLUSTERS - 1, BATCH_SIZE)) for _ in range(NUM_BATCHES)]
# intrinsic inputs: float data + labels
DATA = [jnp.asarray(_rng.standard_normal((BATCH_SIZE, 4)), dtype=jnp.float32) for _ in range(NUM_BATCHES)]
LABELS = [jnp.asarray(_rng.integers(0, 4, BATCH_SIZE)) for _ in range(NUM_BATCHES)]


def _sk(fn):
    """sklearn clustering metrics take (labels_true, labels_pred)."""
    return lambda preds, target: fn(target, preds)


EXTRINSIC_CASES = [
    (MutualInfoScore, mutual_info_score, {}, _sk(sklearn_metrics.mutual_info_score)),
    (
        NormalizedMutualInfoScore,
        normalized_mutual_info_score,
        {"average_method": "arithmetic"},
        _sk(lambda t, p: sklearn_metrics.normalized_mutual_info_score(t, p, average_method="arithmetic")),
    ),
    (
        NormalizedMutualInfoScore,
        normalized_mutual_info_score,
        {"average_method": "geometric"},
        _sk(lambda t, p: sklearn_metrics.normalized_mutual_info_score(t, p, average_method="geometric")),
    ),
    (
        AdjustedMutualInfoScore,
        adjusted_mutual_info_score,
        {"average_method": "arithmetic"},
        _sk(sklearn_metrics.adjusted_mutual_info_score),
    ),
    (
        AdjustedMutualInfoScore,
        adjusted_mutual_info_score,
        {"average_method": "min"},
        _sk(lambda t, p: sklearn_metrics.adjusted_mutual_info_score(t, p, average_method="min")),
    ),
    (RandScore, rand_score, {}, _sk(sklearn_metrics.rand_score)),
    (AdjustedRandScore, adjusted_rand_score, {}, _sk(sklearn_metrics.adjusted_rand_score)),
    (FowlkesMallowsIndex, fowlkes_mallows_index, {}, _sk(sklearn_metrics.fowlkes_mallows_score)),
    (HomogeneityScore, homogeneity_score, {}, _sk(sklearn_metrics.homogeneity_score)),
    (CompletenessScore, completeness_score, {}, _sk(sklearn_metrics.completeness_score)),
    (VMeasureScore, v_measure_score, {}, _sk(sklearn_metrics.v_measure_score)),
]
_IDS = [
    "mutual_info",
    "nmi_arithmetic",
    "nmi_geometric",
    "ami_arithmetic",
    "ami_min",
    "rand",
    "adjusted_rand",
    "fowlkes_mallows",
    "homogeneity",
    "completeness",
    "v_measure",
]


class TestExtrinsicClustering(MetricTester):
    atol = 1e-4

    @pytest.mark.parametrize("metric_class, metric_fn, args, sk_fn", EXTRINSIC_CASES, ids=_IDS)
    @pytest.mark.parametrize("ddp", [False, True])
    def test_class(self, metric_class, metric_fn, args, sk_fn, ddp):
        # static class space makes compute jit-safe inside shard_map
        margs = {**args, "num_classes_preds": NUM_CLUSTERS, "num_classes_target": NUM_CLUSTERS}
        self.run_class_metric_test(
            ddp=ddp,
            preds=PREDS,
            target=TARGET,
            metric_class=metric_class,
            reference_metric=sk_fn,
            metric_args=margs,
        )

    @pytest.mark.parametrize("metric_class, metric_fn, args, sk_fn", EXTRINSIC_CASES, ids=_IDS)
    def test_functional(self, metric_class, metric_fn, args, sk_fn):
        # eager path: observed-class contingency, like the reference
        fn_args = {k: v for k, v in args.items() if k != "average_method"}
        if "average_method" in args:
            fn = lambda p, t, am=args["average_method"]: metric_fn(p, t, am)  # noqa: E731
        else:
            fn = metric_fn
        self.run_functional_metric_test(
            preds=PREDS, target=TARGET, metric_functional=fn, reference_metric=sk_fn, metric_args=fn_args
        )


def _np_dunn(data, labels, p=2):
    """Independent numpy reference for the Dunn index."""
    ks = np.unique(labels)
    cents = np.stack([data[labels == k].mean(axis=0) for k in ks])
    inter = [
        np.linalg.norm(cents[i] - cents[j], ord=p)
        for i in range(len(ks))
        for j in range(i + 1, len(ks))
    ]
    intra = [np.linalg.norm(data[labels == k] - cents[i], ord=p, axis=1).max() for i, k in enumerate(ks)]
    return min(inter) / max(intra)


INTRINSIC_CASES = [
    (CalinskiHarabaszScore, calinski_harabasz_score, sklearn_metrics.calinski_harabasz_score),
    (DaviesBouldinScore, davies_bouldin_score, sklearn_metrics.davies_bouldin_score),
    (DunnIndex, dunn_index, _np_dunn),
]


class TestIntrinsicClustering(MetricTester):
    atol = 1e-3

    @pytest.mark.parametrize(
        "metric_class, metric_fn, sk_fn", INTRINSIC_CASES, ids=["calinski_harabasz", "davies_bouldin", "dunn"]
    )
    @pytest.mark.parametrize("ddp", [False, True])
    def test_class(self, metric_class, metric_fn, sk_fn, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=DATA,
            target=LABELS,
            metric_class=metric_class,
            reference_metric=sk_fn,
            metric_args={"num_labels": 4},
        )

    @pytest.mark.parametrize(
        "metric_class, metric_fn, sk_fn", INTRINSIC_CASES, ids=["calinski_harabasz", "davies_bouldin", "dunn"]
    )
    def test_functional(self, metric_class, metric_fn, sk_fn):
        self.run_functional_metric_test(
            preds=DATA, target=LABELS, metric_functional=metric_fn, reference_metric=sk_fn
        )


def test_contingency_matches_sklearn():
    from sklearn.metrics.cluster import contingency_matrix

    from tpumetrics.functional.clustering.utils import calculate_contingency_matrix

    p = np.asarray(PREDS[0])
    t = np.asarray(TARGET[0])
    got = np.asarray(calculate_contingency_matrix(jnp.asarray(p), jnp.asarray(t)))
    ref = contingency_matrix(t, p)
    assert np.array_equal(got, ref)


def test_static_class_space_matches_observed():
    """Padding the class space with empty clusters must not change any score."""
    p, t = PREDS[0], TARGET[0]
    for fn in (mutual_info_score, rand_score, adjusted_rand_score, v_measure_score, fowlkes_mallows_index):
        eager = float(fn(p, t))
        static = float(fn(p, t, num_classes_preds=NUM_CLUSTERS + 5, num_classes_target=NUM_CLUSTERS + 3))
        assert np.isclose(eager, static, atol=1e-5), fn.__name__


def test_jit_clustering_with_static_classes():
    fn = jax.jit(
        lambda p, t: adjusted_mutual_info_score(
            p, t, num_classes_preds=NUM_CLUSTERS, num_classes_target=NUM_CLUSTERS
        )
    )
    got = float(fn(PREDS[0], TARGET[0]))
    ref = float(sklearn_metrics.adjusted_mutual_info_score(np.asarray(TARGET[0]), np.asarray(PREDS[0])))
    assert np.isclose(got, ref, atol=1e-3)


def test_intrinsic_validation_errors():
    with pytest.raises(ValueError, match="Expected 2D data"):
        calinski_harabasz_score(jnp.zeros((8,)), jnp.zeros((8,), dtype=jnp.int32))
    with pytest.raises(ValueError, match="Number of detected clusters"):
        davies_bouldin_score(jnp.zeros((8, 2)), jnp.zeros((8,), dtype=jnp.int32))
    with pytest.raises(ValueError, match="Expected real, discrete values"):
        mutual_info_score(jnp.zeros((8,)), jnp.zeros((8,)))


def test_negative_labels_dropped_in_static_space():
    """DBSCAN-style noise labels (-1) must be dropped, not wrap around."""
    preds = jnp.asarray([-1, 0, 1, 1, 0, -1])
    target = jnp.asarray([0, 0, 1, 1, 0, 1])
    keep = np.asarray(preds) >= 0
    ref = float(sklearn_metrics.mutual_info_score(np.asarray(target)[keep], np.asarray(preds)[keep]))
    got = float(mutual_info_score(preds, target, num_classes_preds=2, num_classes_target=2))
    assert np.isclose(got, ref, atol=1e-6)


def test_buffered_compute_under_jit():
    """Fixed-capacity buffer states: the whole update+compute runs inside jit,
    including uneven per-batch valid counts, and matches sklearn on the valid rows."""
    cap = 160  # > total appended rows: the buffer keeps an invalid tail, exercising the mask path
    for cls, fn, kwargs in [
        (MutualInfoScore, sklearn_metrics.mutual_info_score, {}),
        (RandScore, sklearn_metrics.rand_score, {}),
        (VMeasureScore, sklearn_metrics.v_measure_score, {}),
        (AdjustedMutualInfoScore, sklearn_metrics.adjusted_mutual_info_score, {}),
    ]:
        m = cls(num_classes_preds=NUM_CLUSTERS, num_classes_target=NUM_CLUSTERS, **kwargs)
        m.set_state_capacity("preds", cap)
        m.set_state_capacity("target", cap)

        @jax.jit
        def run(preds_batches, target_batches):
            state = m.init_state()
            for i in range(preds_batches.shape[0]):
                state = m.functional_update(state, preds_batches[i], target_batches[i])
            return m.functional_compute(state)

        p = jnp.stack(PREDS)
        t = jnp.stack(TARGET)
        got = float(run(p, t))
        ref = float(fn(np.concatenate([np.asarray(x) for x in TARGET]), np.concatenate([np.asarray(x) for x in PREDS])))
        assert np.isclose(got, ref, atol=5e-3), (cls.__name__, got, ref)


def test_buffered_intrinsic_compute_under_jit():
    m = CalinskiHarabaszScore(num_labels=4)
    m.set_state_capacity("data", 200, feature_shape=(4,))  # > 128 rows: invalid tail exercises the mask
    m.set_state_capacity("labels", 200)

    @jax.jit
    def run(data_batches, label_batches):
        state = m.init_state()
        for i in range(data_batches.shape[0]):
            state = m.functional_update(state, data_batches[i], label_batches[i])
        return m.functional_compute(state)

    got = float(run(jnp.stack(DATA), jnp.stack(LABELS)))
    ref = float(
        sklearn_metrics.calinski_harabasz_score(
            np.concatenate([np.asarray(x) for x in DATA]), np.concatenate([np.asarray(x) for x in LABELS])
        )
    )
    assert np.isclose(got, ref, rtol=1e-3), (got, ref)


def test_nmi_homogeneity_consistent_with_dropped_rows():
    """Entropies must be computed on the same row set as the contingency
    table, so scores stay in [0, 1] when noise rows are dropped."""
    preds = jnp.asarray([-1, -1, 0, 1, 1, 0])
    target = jnp.asarray([1, 1, 0, 1, 1, 0])
    keep = np.asarray(preds) >= 0
    kp, kt = np.asarray(preds)[keep], np.asarray(target)[keep]
    for fn, sk in [
        (normalized_mutual_info_score, sklearn_metrics.normalized_mutual_info_score),
        (homogeneity_score, sklearn_metrics.homogeneity_score),
        (completeness_score, sklearn_metrics.completeness_score),
        (fowlkes_mallows_index, sklearn_metrics.fowlkes_mallows_score),
    ]:
        got = float(fn(preds, target, num_classes_preds=2, num_classes_target=2))
        ref = float(sk(kt, kp))
        assert np.isclose(got, ref, atol=1e-5), (fn.__name__, got, ref)


def test_intrinsic_with_declared_empty_clusters():
    """num_labels larger than observed clusters (dead k-means clusters) must
    not distort the scores via phantom origin centroids."""
    rng = np.random.default_rng(9)
    data = rng.standard_normal((60, 3)).astype(np.float32) + 5.0  # offset from origin
    labels = rng.integers(0, 3, 60)
    dj, lj = jnp.asarray(data), jnp.asarray(labels)
    assert np.isclose(
        float(calinski_harabasz_score(dj, lj, num_labels=5)),
        sklearn_metrics.calinski_harabasz_score(data, labels),
        rtol=1e-4,
    )
    assert np.isclose(
        float(davies_bouldin_score(dj, lj, num_labels=5)),
        sklearn_metrics.davies_bouldin_score(data, labels),
        rtol=1e-4,
    )
    assert np.isclose(
        float(dunn_index(dj, lj, num_labels=5)),
        float(_np_dunn(data, labels)),
        rtol=1e-4,
    )
