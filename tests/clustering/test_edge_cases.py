"""Clustering + nominal degenerate inputs, pinned against sklearn / the
mounted reference's conventions (single-cluster partitions, constant
variables, perfect association)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from tpumetrics.functional.clustering import (
    adjusted_rand_score,
    normalized_mutual_info_score,
    rand_score,
)
from tpumetrics.functional.nominal import cramers_v, pearsons_contingency_coefficient, theils_u

CONST = jnp.zeros(12, jnp.int32)
MIXED = jnp.asarray([0, 1, 2] * 4, jnp.int32)


def test_single_cluster_partitions():
    """Everything in one cluster: agreement with itself is perfect (ARS 1,
    Rand 1); against a real partition ARS collapses to 0 (chance level) and
    NMI to 0 (no information) — sklearn's exact conventions."""
    assert float(adjusted_rand_score(CONST, CONST)) == pytest.approx(1.0)
    assert float(rand_score(CONST, CONST)) == pytest.approx(1.0)
    assert float(adjusted_rand_score(CONST, MIXED)) == pytest.approx(0.0)
    assert float(normalized_mutual_info_score(CONST, MIXED)) == pytest.approx(0.0)


def test_perfect_partition_agreement():
    assert float(adjusted_rand_score(MIXED, MIXED)) == pytest.approx(1.0)
    assert float(normalized_mutual_info_score(MIXED, MIXED)) == pytest.approx(1.0)
    # label permutation is still a perfect partition match
    permuted = jnp.asarray([2, 0, 1] * 4, jnp.int32)
    assert float(adjusted_rand_score(MIXED, permuted)) == pytest.approx(1.0)


def test_nominal_constant_variable():
    """A constant variable has no association to measure: Cramer's V is NaN
    (the reference's convention — zero degrees of freedom), Theil's U is 0
    (no uncertainty reduction)."""
    assert np.isnan(float(cramers_v(CONST, MIXED)))
    assert float(theils_u(CONST, MIXED)) == pytest.approx(0.0)


def test_nominal_perfect_association():
    assert float(cramers_v(MIXED, MIXED)) == pytest.approx(1.0)
    assert float(theils_u(MIXED, MIXED)) == pytest.approx(1.0)
    # Pearson's C saturates at sqrt((k-1)/k), not 1 — the textbook ceiling
    assert float(pearsons_contingency_coefficient(MIXED, MIXED)) == pytest.approx(
        np.sqrt(2 / 3), abs=1e-6
    )
