"""Subprocess worker for the multi-process (DCN) test pool.

Launched N times by ``tests/test_multihost.py`` with a localhost
coordinator; each process initializes ``jax.distributed`` on the CPU
backend (Gloo collectives) and runs every scenario, writing its results to
``<out>/rank<r>.json``.  This is the process-level analogue of the
reference's session-global 2-process Gloo pool
(reference tests/unittests/conftest.py:28-63) — here it drives
``MultiHostBackend``'s shape/dtype negotiation, empty-rank adoption,
pad-gather-trim, and the host-object wire end-to-end.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _tolist(x):
    import numpy as np

    return np.asarray(x).tolist()


# ------------------------------------------------- tiny offline text stack
# (mirrors tests/multimodal/test_model_metrics.py; duplicated here because the
# worker runs outside pytest and must not import test modules)


class WordTokenizer:
    cls_token_id = 1
    sep_token_id = 2
    pad_token_id = 0
    mask_token_id = 3

    def __init__(self):
        self.vocab = {}

    def _id(self, word):
        if word not in self.vocab:
            self.vocab[word] = 4 + (len(self.vocab) % 96)
        return self.vocab[word]

    def __call__(self, sentences, **kwargs):
        import numpy as np

        rows = [
            [self.cls_token_id] + [self._id(w) for w in s.lower().split()] + [self.sep_token_id]
            for s in sentences
        ]
        max_len = max(len(r) for r in rows)
        input_ids = np.full((len(rows), max_len), self.pad_token_id, np.int32)
        attention = np.zeros((len(rows), max_len), np.int32)
        for i, r in enumerate(rows):
            input_ids[i, : len(r)] = r
            attention[i, : len(r)] = 1
        return {"input_ids": input_ids, "attention_mask": attention}


class ToyEmbedder:
    def __init__(self, dim=16, vocab=100, seed=0):
        import jax.numpy as jnp
        import numpy as np

        rng = np.random.default_rng(seed)
        self.table = jnp.asarray(rng.standard_normal((vocab, dim)), jnp.float32)

    def __call__(self, model, batch):
        import jax.numpy as jnp

        ids = jnp.asarray(batch["input_ids"])
        return self.table[ids]


class ToyMLM:
    """Deterministic masked LM with sequence-context mixing (the InfoLM
    driver; mirrors tests/multimodal/test_model_metrics.py)."""

    def __init__(self, vocab=100, seed=0):
        import jax.numpy as jnp
        import numpy as np

        rng = np.random.default_rng(seed)
        self.table = jnp.asarray(rng.standard_normal((vocab, vocab)), jnp.float32)

    def __call__(self, input_ids, attention_mask=None):
        import jax.numpy as jnp

        class _Out:
            pass

        ids = jnp.asarray(input_ids)
        token_logits = self.table[ids]
        context = token_logits.mean(axis=1, keepdims=True)
        out = _Out()
        out.logits = token_logits + 2.0 * context
        return out


# --------------------------------------------------------------- corpora
# deterministic and rank-strided so the parent can recompute the union


def classification_shard(rank, world, n=256, classes=7):
    import numpy as np

    rng = np.random.default_rng(0)
    logits = rng.standard_normal((n, classes)).astype(np.float32)
    labels = rng.integers(0, classes, n)
    return logits[rank::world], labels[rank::world]


def sentence_shard(rank, world):
    preds, target = sentence_corpus()
    return preds[rank::world], target[rank::world]


def sentence_corpus():
    preds = [
        "the cat sat on the mat",
        "a dog barked loudly",
        "hello there general kenobi",
        "one two three four five",
        "the quick brown fox jumps",
        "rain falls on the plain",
        "metrics are fun to build",
    ]
    target = [
        "the cat sat on a mat",
        "the dog barked",
        "hello there",
        "one two three four",
        "a quick brown fox leaps",
        "rain fell on a plain",
        "metrics are hard to build",
    ]
    return preds, target


def detection_corpus(n_images=12, seed=3):
    """Per-image random boxes; image i has (i % 4) detections and (i % 3 + 1) gts."""
    import numpy as np

    rng = np.random.default_rng(seed)
    preds, target = [], []
    for i in range(n_images):
        nd, ng = i % 4, i % 3 + 1
        db = rng.uniform(0, 50, (nd, 2))
        preds.append(
            {
                "boxes": np.concatenate([db, db + rng.uniform(5, 40, (nd, 2))], -1).astype(np.float32),
                "scores": rng.uniform(0.1, 1.0, nd).astype(np.float32),
                "labels": rng.integers(0, 3, nd),
            }
        )
        gb = rng.uniform(0, 50, (ng, 2))
        target.append(
            {
                "boxes": np.concatenate([gb, gb + rng.uniform(5, 40, (ng, 2))], -1).astype(np.float32),
                "labels": rng.integers(0, 3, ng),
            }
        )
    return preds, target


# -------------------------------------------------------------- scenarios


def run_scenarios(rank: int, world: int) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpumetrics import MetricCollection
    from tpumetrics.aggregation import CatMetric
    from tpumetrics.classification import MulticlassAccuracy, MulticlassAUROC, MulticlassF1Score
    from tpumetrics.detection import MeanAveragePrecision
    from tpumetrics.parallel.backend import MultiHostBackend, get_default_backend
    from tpumetrics.text import BERTScore

    backend = MultiHostBackend()
    results = {
        "init": {
            "rank": rank,
            "world": world,
            "process_count": jax.process_count(),
            "default_backend": type(get_default_backend()).__name__,
            "available": backend.available(),
            "world_size": backend.world_size(),
        }
    }

    # --- backend branch coverage -------------------------------------
    # equal shapes → _gather_equal fast path
    g = backend.all_gather(jnp.arange(4, dtype=jnp.int32) + 10 * rank)
    results["gather_equal"] = [_tolist(v) for v in g]

    # 0-d input → atleast_1d
    g = backend.all_gather(jnp.float32(rank + 0.5))
    results["gather_scalar"] = [_tolist(v) for v in g]

    # per-rank dim-0 sizes → pad-gather-trim
    x = jnp.arange((rank + 1) * 3, dtype=jnp.float32).reshape(rank + 1, 3) + 100 * rank
    g = backend.all_gather(x)
    results["gather_uneven"] = [{"shape": list(v.shape), "vals": _tolist(v)} for v in g]

    # rank 0 holds an empty f32 1-D placeholder, everyone else (rank+1, 2)
    # int32 → dtype adoption + ndim normalization + pad-gather-trim
    if rank == 0:
        x = jnp.zeros((0,), jnp.float32)
    else:
        x = jnp.arange((rank + 1) * 2, dtype=jnp.int32).reshape(rank + 1, 2) + 100 * rank
    g = backend.all_gather(x)
    results["gather_empty_rank"] = [
        {"shape": list(v.shape), "dtype": str(v.dtype), "vals": _tolist(v)} for v in g
    ]

    # every rank empty → equal-shape fast path with zero-size payloads
    g = backend.all_gather(jnp.zeros((0,), jnp.float32))
    results["gather_all_empty"] = [{"shape": list(v.shape), "dtype": str(v.dtype)} for v in g]

    # fused reductions
    x = jnp.asarray([rank + 1.0, rank * 2.0], jnp.float32)
    results["allreduce"] = {op: _tolist(backend.all_reduce(x, op)) for op in ("sum", "mean", "max", "min")}

    # host-object wire (ragged pickled payloads)
    obj = {"rank": rank, "words": [f"w{rank}_{i}" for i in range(rank + 1)]}
    results["gather_object"] = backend.all_gather_object(obj)

    # --- metric end-to-end over the ambient backend ------------------
    # sum-reduced states
    logits, labels = classification_shard(rank, world)
    acc = MulticlassAccuracy(num_classes=7, average="micro")
    acc.update(jnp.asarray(logits), jnp.asarray(labels))
    results["metric_acc"] = float(acc.compute())

    # uneven cat-state with an empty rank (rank 0 never updates)
    cat = CatMetric()
    for i in range(rank * 2):
        cat.update(jnp.float32(rank * 10 + i))
    results["metric_cat"] = _tolist(cat.compute())

    # MetricCollection (mixed state shapes incl. binned curve state)
    coll = MetricCollection(
        {
            "acc": MulticlassAccuracy(num_classes=7, average="micro"),
            "f1": MulticlassF1Score(num_classes=7, average="macro"),
            "auroc": MulticlassAUROC(num_classes=7, thresholds=64),
        }
    )
    coll.update(jnp.asarray(logits), jnp.asarray(labels))
    results["metric_collection"] = {k: float(v) for k, v in coll.compute().items()}

    # BERTScore sentence-state merge over the host-object wire
    preds, target = sentence_shard(rank, world)
    bs = BERTScore(model=ToyEmbedder(), user_tokenizer=WordTokenizer(), user_forward_fn=ToyEmbedder(), idf=True)
    if preds:
        bs.update(list(preds), list(target))
    out = bs.compute()
    results["metric_bertscore"] = {k: _tolist(out[k]) for k in ("precision", "recall", "f1")}
    # unsync must restore the local shard
    results["bertscore_local_after_compute"] = list(bs._preds)

    # InfoLM: the other raw-sentence host state riding the object wire
    from tpumetrics.text import InfoLM

    il = InfoLM(
        model=ToyMLM(),
        user_tokenizer=WordTokenizer(),
        information_measure="l1_distance",
        idf=True,
        verbose=False,
    )
    if preds:
        il.update(list(preds), list(target))
    results["metric_infolm"] = float(il.compute())

    # heterogeneous-shape collection through the FUSED eager sync: scalar
    # and (7,7)-matrix sum states of mixed dtypes flatten into the shared
    # flush's per-(op, dtype) buffers across real processes
    from tpumetrics.classification import MulticlassConfusionMatrix

    mixed = MetricCollection(
        {
            "acc2": MulticlassAccuracy(num_classes=7, average="micro"),
            "confmat": MulticlassConfusionMatrix(num_classes=7),
        }
    )
    mixed.update(jnp.asarray(logits), jnp.asarray(labels))
    mres = mixed.compute()
    results["metric_mixed_collection"] = {
        "acc2": float(mres["acc2"]),
        "confmat_sum": int(np.asarray(mres["confmat"]).sum()),
        "confmat_trace": int(np.asarray(mres["confmat"]).trace()),
    }

    # wrapper metrics: children own sync — each child self-syncs over the
    # ambient MultiHostBackend at compute (wrappers/abstract.py design)
    from tpumetrics.regression import MeanSquaredError
    from tpumetrics.wrappers import MultitaskWrapper

    mt = MultitaskWrapper(
        {
            "cls": MulticlassAccuracy(num_classes=7, average="micro"),
            "reg": MeanSquaredError(),
        }
    )
    mt.update(
        {"cls": jnp.asarray(logits), "reg": jnp.asarray(logits[:, 0])},
        {"cls": jnp.asarray(labels), "reg": jnp.asarray(logits[:, 1])},
    )
    results["metric_multitask"] = {k: float(v) for k, v in mt.compute().items()}

    # mAP: ragged per-image reduce-None list states via _gather_ragged_list
    dpreds, dtarget = detection_corpus()
    mp = MeanAveragePrecision(iou_type="bbox")
    mp.update(
        [{k: jnp.asarray(v) for k, v in p.items()} for p in dpreds[rank::world]],
        [{k: jnp.asarray(v) for k, v in t.items()} for t in dtarget[rank::world]],
    )
    mres = mp.compute()
    results["metric_map"] = {k: float(np.asarray(v).reshape(-1)[0]) for k, v in mres.items() if k != "classes"}

    # --- telemetry over real DCN: ledger accounting for a fused flush ------
    from tpumetrics import telemetry

    tel = MetricCollection(
        {
            "acc3": MulticlassAccuracy(num_classes=7, average="micro"),
            "f13": MulticlassF1Score(num_classes=7, average="macro"),
        }
    )
    tel.update(jnp.asarray(logits), jnp.asarray(labels))
    with telemetry.capture() as led:
        tel_res = tel.compute()
    s = led.summary()
    results["telemetry_ledger"] = {
        "collectives_issued": s["collectives_issued"],
        "flush_count": s["flush_count"],
        "lockstep_fingerprints": s["lockstep_fingerprints"],
        "wire_bytes_total": s["wire_bytes_total"],
        "backends": sorted({r.backend for r in led.records if r.source == "backend"}),
        "acc3": float(tel_res["acc3"]),
    }

    # --- induced rank-divergent schedule: the ADVICE r5 #3 deadlock --------
    # rank 0 enters the collection flush with one member's compute value
    # cached, so its candidate set differs from every other rank's; the
    # lockstep verifier must RAISE on every rank (naming the divergence)
    # instead of hanging the DCN flush
    from tpumetrics.classification import MulticlassConfusionMatrix as _ConfMat

    div = MetricCollection(
        {
            "acc4": MulticlassAccuracy(num_classes=7, average="micro"),
            "conf4": _ConfMat(num_classes=7),
        }
    )
    div.update(jnp.asarray(logits), jnp.asarray(labels))
    if rank == 0:
        div._modules["conf4"]._computed = jnp.zeros((7, 7))  # divergent flag
    try:
        div.compute()
        results["lockstep_violation"] = None
    except telemetry.LockstepViolation as err:
        results["lockstep_violation"] = str(err)

    # --- resilience over real DCN: armed SyncPolicy, no faults -------------
    # the watchdog engages for every eager collective (MultiHostBackend,
    # world > 1); with nothing stalling, values must match the unguarded
    # sync exactly and nothing may be marked degraded
    from tpumetrics import resilience as _res
    from tpumetrics.resilience import Fault, FaultInjectionBackend, SyncPolicy

    armed = MulticlassAccuracy(num_classes=7, average="micro")
    armed.update(jnp.asarray(logits), jnp.asarray(labels))
    with _res.sync_policy(SyncPolicy(timeout=120.0, retries=1)):
        armed_val = float(armed.compute())
    results["resilience_armed"] = {
        "value": armed_val,
        "degraded": bool(armed.degraded),
        "guard_applies": SyncPolicy(timeout=1.0).applies(backend),
    }

    # --- deterministic all-rank stall -> typed timeout -> degraded local ---
    # LAST scenario on purpose: every rank's fused flush stalls (30s) behind
    # a 0.5s deadline, so each rank gets SyncTimeoutError and serves its
    # local shard value.  The stalled watchdog threads are daemons sleeping
    # longer than the process lives, so no orphan collective is ever issued
    # to interleave with other traffic.
    stall = MulticlassAccuracy(num_classes=7, average="micro")
    stall.update(jnp.asarray(logits), jnp.asarray(labels))
    local_ref = MulticlassAccuracy(num_classes=7, average="micro", sync_on_compute=False)
    local_ref.update(jnp.asarray(logits), jnp.asarray(labels))
    stall.sync_backend = FaultInjectionBackend(
        backend, [Fault("stall", op="all_reduce", delay=30.0, count=99)], available=True
    )
    with _res.sync_policy(SyncPolicy(timeout=0.5, on_failure="local")):
        stalled_val = float(stall.compute())
    results["resilience_stall"] = {
        "degraded": bool(stall.degraded),
        "mode": stall.degraded_mode,
        "value": stalled_val,
        "local_expected": float(local_ref.compute()),
    }

    return results


# ------------------------------------------- elastic-over-real-DCN scenarios
# The single-host fault-injection story (tests/test_elastic.py) validated
# once over real process boundaries: a COORDINATED snapshot_barrier cut whose
# object exchange rides the real MultiHostBackend wire, one rank dying
# abruptly right after the cut, and restore_elastic() onto a SMALLER world
# that finishes the stream and cuts again on the new world.  Traffic and
# metric come from tpumetrics.soak.traffic so the parent can recompute the
# uninterrupted oracle bit-identically.


def run_elastic_write(rank: int, world: int, snap_root: str, stop: int) -> dict:
    """Phase 1: feed [0, stop) strided, coordinated cut, kill the top rank."""
    import jax.numpy as jnp

    from tpumetrics.parallel.backend import MultiHostBackend
    from tpumetrics.runtime import StreamingEvaluator
    from tpumetrics.soak.traffic import make_batch, make_metric

    ev = StreamingEvaluator(
        make_metric(5), buckets=8, snapshot_dir=snap_root,
        snapshot_rank=rank, snapshot_world_size=world,
        barrier_backend=MultiHostBackend(),
    )
    for i in range(rank, stop, world):
        preds, target = make_batch(1, i, num_classes=5, max_rows=8)
        ev.submit(jnp.asarray(preds), jnp.asarray(target))
    ev.flush()
    path = ev.snapshot()  # the barrier crosses real process boundaries here
    stats = ev.stats()
    if rank == world - 1:
        # "kill one rank": die abruptly AFTER the cut completed — no close,
        # no result file; everything it applied since the cut is lost, which
        # is exactly nothing (the cut just covered it)
        sys.stdout.flush()
        os._exit(0)
    ev.close(drain=False)
    return {"cut_path": path, "batches": stats["batches"], "items": stats["items"]}


def run_elastic_restore(
    rank: int, world: int, snap_root: str, start: int, stop: int
) -> dict:
    """Phase 2 (smaller world): restore the cut, finish the stream, cut again."""
    import jax.numpy as jnp

    from tpumetrics.parallel.backend import MultiHostBackend
    from tpumetrics.runtime import StreamingEvaluator
    from tpumetrics.soak.traffic import make_batch, make_metric

    ev = StreamingEvaluator(
        make_metric(5), buckets=8, snapshot_dir=snap_root,
        snapshot_rank=rank, snapshot_world_size=world,
        barrier_backend=MultiHostBackend(),
    )
    info = ev.restore_elastic()
    for i in range(start + rank, stop, world):
        preds, target = make_batch(1, i, num_classes=5, max_rows=8)
        ev.submit(jnp.asarray(preds), jnp.asarray(target))
    ev.flush()
    ev.snapshot()  # a coordinated cut on the NEW world
    stats = ev.stats()
    ev.close(drain=False)
    return {
        "restore": info,
        "batches": stats["batches"],
        "items": stats["items"],
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--world", type=int, required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--out", required=True)
    ap.add_argument(
        "--scenario", choices=("pool", "elastic-write", "elastic-restore"),
        default="pool",
    )
    ap.add_argument("--snap-root", default=None)
    ap.add_argument("--feed-start", type=int, default=0)
    ap.add_argument("--feed-stop", type=int, default=0)
    args = ap.parse_args()

    import jax

    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{args.port}",
        num_processes=args.world,
        process_id=args.rank,
    )

    if args.scenario == "pool":
        results = run_scenarios(args.rank, args.world)
    elif args.scenario == "elastic-write":
        results = run_elastic_write(args.rank, args.world, args.snap_root, args.feed_stop)
    else:
        results = run_elastic_restore(
            args.rank, args.world, args.snap_root, args.feed_start, args.feed_stop
        )

    path = os.path.join(args.out, f"rank{args.rank}.json")
    with open(path + ".tmp", "w") as fh:
        json.dump(results, fh)
    os.replace(path + ".tmp", path)
    print(f"worker rank {args.rank}/{args.world} OK", flush=True)


if __name__ == "__main__":
    sys.exit(main())
