"""Sharded-state execution mode: partition rules, one-program SPMD steps,
zero host round trips, and elastic re-placement across mesh shapes.

The GSPMD counterpart of test_fuse_update.py: metric state lives as
``NamedSharding``-ed ``jax.Array``s on the 8-virtual-device CPU mesh
(``tests/conftest.cpu_mesh`` — jaxlib CPU cannot run cross-process
collectives, so single-process SPMD is how this box tests the mesh path),
every collection step compiles to ONE global SPMD program, and
``dist_reduce_fx`` folds lower to in-trace collectives.  Parity is against
the plain eager path over the identical stream: integer states bit-exact,
float states allclose.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tests.conftest import cpu_mesh
from tpumetrics import MetricCollection, StreamingEvaluator, telemetry
from tpumetrics.buffers import materialize
from tpumetrics.classification import (
    MulticlassAccuracy,
    MulticlassF1Score,
    MulticlassPrecision,
    MulticlassRecall,
    MulticlassStatScores,
)
from tpumetrics.image import PeakSignalNoiseRatio
from tpumetrics.metric import Metric
from tpumetrics.parallel import (
    FusedCollectionStep,
    StatePartitionRules,
    make_mesh,
    place_states,
    state_paths,
)
from tpumetrics.regression import MeanSquaredError
from tpumetrics.utils.data import dim_zero_cat
from tpumetrics.utils.exceptions import TPUMetricsUserError


def _dp_mesh(n=8):
    return cpu_mesh(n, axis_name="dp")


class BufferRows(Metric):
    """Native-valid samplewise metric: every valid input row is recorded, in
    order, into a fixed-capacity MaskedBuffer (the list-state family)."""

    full_state_update = False

    def __init__(self, capacity=512, features=3, **kw):
        super().__init__(**kw)
        self.add_state(
            "rows", default=[], dist_reduce_fx="cat",
            capacity=capacity, feature_shape=(features,),
        )

    def update(self, x, valid=None):
        self._append_state("rows", x, valid=valid)

    def compute(self):
        return dim_zero_cat(self.rows)


# ------------------------------------------------------------ rules resolution


class TestStatePartitionRules:
    def test_scalars_always_replicate(self):
        rules = StatePartitionRules([(".*", P("dp"))], data_axis="dp")
        assert rules.spec_for("total", jnp.zeros(())) == P()
        assert rules.spec_for("total", jnp.zeros((1,))) == P()
        assert rules.spec_for("rows", jnp.zeros((16, 3))) == P("dp")

    def test_first_match_wins_and_default_applies(self):
        rules = StatePartitionRules(
            [("rows/values$", P("dp")), ("rows", P())], data_axis="dp"
        )
        assert rules.spec_for("rows/values", jnp.zeros((16, 3))) == P("dp")
        assert rules.spec_for("rows/other", jnp.zeros((16,))) == P()
        assert rules.spec_for("unmatched", jnp.zeros((16,))) == P()

    def test_invalid_regex_raises_typed(self):
        with pytest.raises(TPUMetricsUserError, match="regex"):
            StatePartitionRules([("((", P())])

    def test_unknown_mesh_axis_raises_typed(self, mesh8):
        rules = StatePartitionRules([("rows", P("model"))])
        with pytest.raises(TPUMetricsUserError, match="mesh axis"):
            rules.place(mesh8, {"rows": jnp.zeros((16, 3))})

    def test_non_divisible_dim_demotes_to_replicated(self, mesh8):
        rules = StatePartitionRules([("rows", P("dp"))], data_axis="dp")
        placed = rules.place(mesh8, {"rows": jnp.zeros((10, 3))})  # 10 % 8 != 0
        assert placed["rows"].sharding.spec == P()
        placed = rules.place(mesh8, {"rows": jnp.zeros((16, 3))})
        assert placed["rows"].sharding.spec == P("dp")

    def test_state_paths_cover_buffers_and_nesting(self):
        state = {"m": {"rows": BufferRows().init_state()["rows"], "total": jnp.zeros(())}}
        paths = dict(state_paths(state))
        assert set(paths) == {"m/rows/values", "m/rows/count", "m/rows/requested", "m/total"}

    def test_for_metric_defaults(self):
        rules = BufferRows().state_partition_rules(data_axis="dp")
        state = BufferRows().init_state()
        assert rules.spec_for("rows/values", state["rows"].values) == P("dp")
        assert rules.spec_for("rows/count", state["rows"].count) == P()

    def test_collection_rules_are_leader_agnostic(self):
        col = MetricCollection({"b": BufferRows(), "mse": MeanSquaredError()})
        rules = col.state_partition_rules(data_axis="dp")
        # suffix-matching: any leader prefix resolves the same spec
        assert rules.spec_for("b/rows/values", jnp.zeros((64, 3))) == P("dp")
        assert rules.spec_for("renamed/rows/values", jnp.zeros((64, 3))) == P("dp")
        assert rules.spec_for("mse/sum_squared_error", jnp.zeros((8,))) == P()

    def test_stale_rule_warns_on_place(self, mesh8):
        rules = StatePartitionRules([("long_gone/values", P("dp"))], data_axis="dp")
        with pytest.warns(UserWarning, match="long_gone"):
            rules.place(mesh8, {"rows": jnp.zeros((16, 3))})

    def test_place_without_mesh_materializes_device_copies(self):
        host = {"rows": np.ones((4, 3), np.float32)}
        placed = place_states(None, None, host)
        assert isinstance(placed["rows"], jax.Array)
        np.testing.assert_array_equal(np.asarray(placed["rows"]), host["rows"])


# ------------------------------------------------------- one-program parity


def _class_stream(rng, n, num_classes=5, rows=(8, 64)):
    out = []
    for _ in range(n):
        b = int(rng.integers(*rows))
        out.append(
            (
                jnp.asarray(rng.standard_normal((b, num_classes)).astype(np.float32)),
                jnp.asarray(rng.integers(0, num_classes, size=(b,)).astype(np.int32)),
            )
        )
    return out


def _sharded_vs_eager(make, stream, mesh, *, exact, buckets=(8, 64)):
    """Drive a sharded StreamingEvaluator and a plain eager twin over the
    identical stream; compare compute() and return both objects."""
    ev = StreamingEvaluator(make(), buckets=buckets, mesh=mesh)
    eager = make()
    for batch in stream:
        ev.submit(*batch)
        eager.update(*batch)
    got, want = ev.compute(), eager.compute()
    ev.close()
    if isinstance(want, dict):
        assert set(got) == set(want)
        pairs = [(got[k], want[k], k) for k in want]
    else:
        pairs = [(got, want, "value")]
    for g, w, key in pairs:
        if exact:
            assert np.array_equal(np.asarray(g), np.asarray(w)), key
        else:
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), rtol=1e-5, atol=1e-6, err_msg=key
            )
    return ev, eager


class TestShardedParityFamilies:
    def test_statscores_collection_int_states_bit_exact(self, mesh8):
        rng = np.random.default_rng(0)

        def make():
            return MetricCollection(
                {
                    "acc": MulticlassAccuracy(num_classes=4, average="micro", validate_args=False),
                    "prec": MulticlassPrecision(num_classes=4, average="macro", validate_args=False),
                    "rec": MulticlassRecall(num_classes=4, average="macro", validate_args=False),
                }
            )

        probe = _class_stream(rng, 1, num_classes=4)[0]
        stream = _class_stream(rng, 8, num_classes=4)
        ev_col, eager_col = None, None

        def make_established():
            col = make()
            col.establish_compute_groups(*probe)
            return col

        ev = StreamingEvaluator(make_established(), buckets=(8, 64), mesh=mesh8)
        eager = make_established()
        for batch in stream:
            ev.submit(*batch)
            eager.update(*batch)
        got, want = ev.compute(), eager.compute()
        for k in want:
            np.testing.assert_allclose(np.asarray(got[k]), np.asarray(want[k]), rtol=1e-6)
        # the statscores GROUP leader's integer states must be bit-exact:
        # integer sums are associativity-free, so sharding cannot perturb them
        leader = next(iter(ev._state))
        eager_leader = eager._modules[leader]
        for attr, leaf in ev._state[leader].items():
            assert leaf.dtype == eager_leader._defaults[attr].dtype
            assert np.array_equal(
                np.asarray(leaf), np.asarray(getattr(eager_leader, attr))
            ), attr
        # sharded mode reported in stats
        assert ev.stats()["mesh"] == {"dp": 8}
        ev.close()

    def test_statscores_samplewise_int_bit_exact_direct_step(self, mesh8):
        # samplewise statscores keeps per-class structure; direct (unbucketed)
        # sharded step on fixed-size batches, int bit-exactness
        rng = np.random.default_rng(1)
        m = MulticlassStatScores(num_classes=5, average=None, validate_args=False)
        step = FusedCollectionStep(m, mesh=mesh8)
        state = step.init_state()
        eager = MulticlassStatScores(num_classes=5, average=None, validate_args=False)
        for _ in range(4):
            preds = jnp.asarray(rng.standard_normal((64, 5)).astype(np.float32))
            target = jnp.asarray(rng.integers(0, 5, size=(64,)).astype(np.int32))
            state = step.update(state, preds, target)
            eager.update(preds, target)
        for attr in eager._defaults:
            assert np.array_equal(
                np.asarray(state[attr]), np.asarray(getattr(eager, attr))
            ), attr

    def test_regression_float(self, mesh8):
        rng = np.random.default_rng(2)
        stream = [
            (
                jnp.asarray(rng.standard_normal((int(n),)).astype(np.float32)),
                jnp.asarray(rng.standard_normal((int(n),)).astype(np.float32)),
            )
            for n in rng.integers(4, 50, size=8)
        ]
        _sharded_vs_eager(MeanSquaredError, stream, mesh8, exact=False)

    def test_image_float_min_max_states(self, mesh8):
        # PSNR with tracked data range: exercises min/max reduces under GSPMD
        rng = np.random.default_rng(3)
        stream = [
            (
                jnp.asarray(rng.uniform(0, 4, size=(8, 3, 6, 6)).astype(np.float32)),
                jnp.asarray(rng.uniform(0, 4, size=(8, 3, 6, 6)).astype(np.float32)),
            )
            for _ in range(5)
        ]
        _sharded_vs_eager(PeakSignalNoiseRatio, stream, mesh8, exact=False)

    def test_samplewise_buffer_rows_order_exact(self, mesh8):
        rng = np.random.default_rng(4)
        batches = [
            rng.standard_normal((int(n), 3)).astype(np.float32)
            for n in rng.integers(1, 40, size=12)
        ]
        ev = StreamingEvaluator(BufferRows(), buckets=(8, 64), mesh=mesh8)
        for b in batches:
            ev.submit(jnp.asarray(b))
        ev.flush()
        got = np.asarray(materialize(ev._state["rows"]))
        ev.close()
        # ORDER-exact, not just set-equal: buffer rows land at the same
        # logical offsets whether or not the capacity axis is distributed
        assert np.array_equal(got, np.concatenate(batches))

    def test_aggregation_scalar_submits(self, mesh8):
        from tpumetrics import MeanMetric

        rng = np.random.default_rng(5)
        values = [float(v) for v in rng.standard_normal(10)]
        ev = StreamingEvaluator(MeanMetric(), buckets=(8,), mesh=mesh8)
        eager = MeanMetric()
        for v in values:
            ev.submit(v)
            eager.update(v)
        np.testing.assert_allclose(
            np.asarray(ev.compute()), np.asarray(eager.compute()), rtol=1e-6
        )
        ev.close()

    def test_collection_with_groups_mixed_kwargs_routing(self, mesh8):
        rng = np.random.default_rng(6)

        def make():
            col = MetricCollection(
                {
                    "acc": MulticlassAccuracy(num_classes=4, average="micro", validate_args=False),
                    "f1": MulticlassF1Score(num_classes=4, average="macro", validate_args=False),
                    "stat": MulticlassStatScores(num_classes=4, average="macro", validate_args=False),
                }
            )
            probe = _class_stream(np.random.default_rng(99), 1, num_classes=4)[0]
            col.establish_compute_groups(*probe)
            return col

        stream = _class_stream(rng, 6, num_classes=4)
        ev, eager = _sharded_vs_eager(make, stream, mesh8, exact=False)
        # compute groups collapsed acc/f1/stat into one leader: the sharded
        # state carries exactly the leader set
        assert set(ev._state) == {cg[0] for cg in eager._groups.values()}


# --------------------------------------------------- zero host round trips


class TestZeroHostTransfers:
    def test_update_loop_is_transfer_free(self, mesh8):
        """Between update() and compute() nothing may touch the host: the
        whole sharded update loop runs under a device→host transfer guard
        (host→device input feeding is legitimate and stays allowed)."""
        rng = np.random.default_rng(0)
        col = MetricCollection(
            {
                "acc": MulticlassAccuracy(num_classes=5, average="micro", validate_args=False),
                "f1": MulticlassF1Score(num_classes=5, average="macro", validate_args=False),
            }
        )
        preds = jnp.asarray(rng.standard_normal((128, 5)).astype(np.float32))
        target = jnp.asarray(rng.integers(0, 5, size=(128,)).astype(np.int32))
        col.establish_compute_groups(preds[:8], target[:8])
        step = FusedCollectionStep(col, mesh=mesh8)
        state = step.init_state()
        state = step.update(state, preds, target)  # compile outside the guard
        with jax.transfer_guard_device_to_host("disallow"):
            for _ in range(5):
                state = step.update(state, preds, target)
            jax.block_until_ready(jax.tree_util.tree_leaves(state))
        # compute still sees everything (6 batches applied)
        out = col.functional_compute(state)
        assert np.isfinite(float(out["acc"]))

    def test_trace_time_ledger_records_static_collectives(self, mesh8):
        """GSPMD-inserted collectives report into the ledger at TRACE time
        (op/bytes/axis, static=True, source='spmd') and never again on
        steady-state steps — attribution with zero per-step host cost."""
        m = MulticlassAccuracy(num_classes=4, average="micro", validate_args=False)
        step = FusedCollectionStep(m, mesh=mesh8)
        state = step.init_state()
        preds = jnp.asarray(np.random.default_rng(0).standard_normal((64, 4)), jnp.float32)
        target = jnp.zeros((64,), jnp.int32)
        with telemetry.capture() as led:
            state = step.update(state, preds, target)  # traces -> records
        s = led.summary()
        assert s["spmd_collectives"] == len(m._defaults)  # one per reduce state
        assert s["collectives_issued"] == 0  # no eager wire op at all
        for rec in led.records:
            assert rec.source == "spmd"
            assert rec.in_trace is True
            assert rec.extra["static"] is True
            assert rec.extra["axis"] == "dp"
            assert rec.op == "sum"
            assert rec.world_size == 8
        with telemetry.capture() as led2:
            state = step.update(state, preds, target)  # cached: no re-trace
        assert led2.summary()["records"] == 0

    def test_sharded_program_contains_all_reduce(self, mesh8):
        """The ONE compiled program really holds the in-trace collective the
        partition rules imply (dist_reduce_fx='sum' → all-reduce over dp)."""
        m = MeanSquaredError()
        rules = m.state_partition_rules(data_axis="dp")
        state = place_states(mesh8, rules, m.init_state())
        preds = jnp.asarray(np.ones((64,), np.float32))
        dp = jax.sharding.NamedSharding(mesh8, P("dp"))

        def run(s, p, t):
            s = rules.constrain(mesh8, s)
            return rules.constrain(mesh8, m.functional_update(s, p, t))

        lowered = jax.jit(run).lower(
            state, jax.device_put(preds, dp), jax.device_put(preds * 0.5, dp)
        )
        assert "all-reduce" in lowered.compile().as_text()


# ------------------------------------------------- elastic: re-place on mesh


class TestElasticReplacement:
    def _run(self, tmp_path, write_mesh, read_mesh, buckets=(8, 64)):
        def make():
            return MulticlassAccuracy(num_classes=5, average="micro", validate_args=False)

        rng = np.random.default_rng(7)
        stream = _class_stream(rng, 8, num_classes=5, rows=(8, 33))
        root = str(tmp_path)

        ev = StreamingEvaluator(
            make(), buckets=buckets, mesh=write_mesh, snapshot_dir=root,
            snapshot_rank=0, snapshot_world_size=1,
        )
        for batch in stream[:4]:
            ev.submit(*batch)
        ev.snapshot()
        ev.close()

        ev2 = StreamingEvaluator(
            make(), buckets=buckets, mesh=read_mesh, snapshot_dir=root,
            snapshot_rank=0, snapshot_world_size=1,
        )
        info = ev2.restore_elastic()
        assert info is not None and info["batches"] == 4
        # every restored leaf was re-placed under the NEW mesh
        for _path, leaf in state_paths(ev2._state):
            assert leaf.sharding.mesh.shape == read_mesh.shape
        for batch in stream[4:]:
            ev2.submit(*batch)
        got = np.asarray(ev2.compute())
        ev2.close()

        ref = make()
        st = ref.init_state()
        for batch in stream:
            st = ref.functional_update(st, *batch)
        want = np.asarray(ref.functional_compute(st))
        assert np.array_equal(got, want)  # bit-identical across the resize

    def test_shrink_8_to_4(self, tmp_path):
        self._run(tmp_path, _dp_mesh(8), _dp_mesh(4))

    def test_grow_2_to_8(self, tmp_path):
        self._run(tmp_path, _dp_mesh(2), _dp_mesh(8))

    def test_buffer_state_replaced_and_order_kept(self, tmp_path, mesh8):
        root = str(tmp_path)
        rng = np.random.default_rng(8)
        batches = [
            rng.standard_normal((int(n), 3)).astype(np.float32)
            for n in rng.integers(1, 30, size=8)
        ]
        ev = StreamingEvaluator(
            BufferRows(), buckets=(8, 32), mesh=mesh8, snapshot_dir=root,
            snapshot_rank=0, snapshot_world_size=1,
        )
        for b in batches[:5]:
            ev.submit(jnp.asarray(b))
        ev.snapshot()
        ev.close()

        mesh4 = _dp_mesh(4)
        ev2 = StreamingEvaluator(
            BufferRows(), buckets=(8, 32), mesh=mesh4, snapshot_dir=root,
            snapshot_rank=0, snapshot_world_size=1,
        )
        assert ev2.restore_elastic() is not None
        assert ev2._state["rows"].values.sharding.spec == P("dp")
        assert ev2._state["rows"].values.sharding.mesh.shape == mesh4.shape
        for b in batches[5:]:
            ev2.submit(jnp.asarray(b))
        ev2.flush()
        got = np.asarray(materialize(ev2._state["rows"]))
        ev2.close()
        assert np.array_equal(got, np.concatenate(batches))


# ------------------------------------------------------------- construction


class TestConstruction:
    def test_mesh_requires_buckets(self, mesh8):
        with pytest.raises(ValueError, match="buckets"):
            StreamingEvaluator(MeanSquaredError(), mesh=mesh8)

    def test_rules_require_mesh(self):
        with pytest.raises(TPUMetricsUserError, match="mesh"):
            FusedCollectionStep(MeanSquaredError(), partition_rules=StatePartitionRules())

    def test_bad_data_axis_raises(self, mesh8):
        with pytest.raises(TPUMetricsUserError, match="data_axis"):
            FusedCollectionStep(MeanSquaredError(), mesh=mesh8, data_axis="model")

    def test_make_mesh_bounds(self):
        assert tuple(make_mesh(4, "dp").shape.items()) == (("dp", 4),)
        with pytest.raises(TPUMetricsUserError, match="available devices"):
            make_mesh(10**6)
