"""The telemetry subsystem: collective ledger, sinks, lockstep verification.

Pins the three tentpole guarantees:

- **Ledger accounting** — an in-trace fused collection sync on the 8-virtual-
  device CPU mesh records one all_reduce per (op, dtype) class whose summed
  wire bytes equal the analytic ring model EXACTLY (integer agreement with
  bench.py's hand computation), with attribution tags naming members.
- **Zero-overhead disabled path** — with telemetry off, nothing records and
  the report helpers return before touching any state.
- **Lockstep verification** — a rank-divergent schedule raises
  :class:`LockstepViolation` naming the diverging rank and the first
  differing entry; in-trace backends skip the exchange and only record.
  (The real multi-process divergence lives in tests/test_multihost.py.)
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tests.helpers.testers import shard_map
from tpumetrics import MetricCollection, telemetry
from tpumetrics.classification import (
    MulticlassAccuracy,
    MulticlassAUROC,
    MulticlassF1Score,
    MulticlassPrecision,
    MulticlassRecall,
)
from tpumetrics.parallel.fuse import FusedReducer
from tpumetrics.telemetry import JsonlSink, LockstepViolation, lockstep


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Every test starts and ends with global telemetry off and empty."""
    telemetry.disable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()
    telemetry.configure(lockstep_verification=True)


from tests.conftest import cpu_mesh as _mesh  # noqa: E402 — shared virtual-device mesh


def _bench_collection(C=16):
    """The collection_sync_8dev bench config's collection."""
    return MetricCollection(
        {
            "acc": MulticlassAccuracy(num_classes=C, average="micro", validate_args=False),
            "f1": MulticlassF1Score(num_classes=C, average="macro", validate_args=False),
            "auroc": MulticlassAUROC(num_classes=C, validate_args=False, thresholds=64),
        }
    )


def _data(C=16, B=64, seed=0):
    rng = np.random.default_rng(seed)
    preds = jnp.asarray(jax.nn.softmax(jnp.asarray(rng.standard_normal((B, C)), jnp.float32)))
    target = jnp.asarray(rng.integers(0, C, size=(B,)), jnp.int32)
    return preds, target


# ------------------------------------------------------------------- ledger


def test_ledger_matches_analytic_wire_bytes_8dev():
    """Capturing one traced step of the collection_sync_8dev config yields
    EXACT integer agreement between ledger wire bytes and the analytic
    2*(N-1)/N * payload ring model bench.py cross-checks against."""
    N = 8
    preds, target = _data()
    col = _bench_collection()
    col.establish_compute_groups(preds[:8], target[:8])

    state0 = col.init_state()
    payload = sum(
        int(np.prod(jnp.shape(leaf))) * jnp.asarray(leaf).dtype.itemsize
        for st in state0.values()
        for leaf in jax.tree.leaves(st)
    )
    analytic = 2 * (N - 1) / N * payload

    def run(p, t):
        st = col.functional_update(col.init_state(), p, t)
        return col.functional_compute(st, axis_name="r")

    step = jax.jit(shard_map(run, mesh=_mesh(), in_specs=(P("r"), P("r")), out_specs=P()))
    with telemetry.capture() as led:
        out = step(preds, target)  # first call traces -> records
        jax.block_until_ready(out)

    s = led.summary()
    assert s["wire_bytes_total"] == analytic  # exact agreement, not approx
    assert round(s["wire_bytes_total"]) == round(analytic)
    assert s["flush_count"] == 1  # ONE fused flush for the whole collection
    # in-trace: the exchange is skipped but the fingerprint IS recorded
    assert s["lockstep_fingerprints"] == 1

    backend_recs = [r for r in led.records if r.source == "backend"]
    assert backend_recs, "no backend collectives recorded"
    for r in backend_recs:
        assert r.backend == "AxisBackend"
        assert r.in_trace is True
        assert r.world_size == N
        assert r.op in ("sum", "mean", "max", "min")
    # one collective per (op, dtype) class, elements conserved
    classes = {(r.op, r.dtype) for r in backend_recs}
    assert s["collectives_issued"] == len(classes)
    total_elements = sum(
        int(np.prod(jnp.shape(leaf))) for st in state0.values() for leaf in jax.tree.leaves(st)
    )
    assert sum(r.element_count for r in backend_recs) == total_elements

    # attribution tags name the collection members that contributed
    reducer_recs = [r for r in led.records if r.source == "reducer"]
    assert reducer_recs
    tags = " ".join(r.tag for r in reducer_recs)
    assert "auroc" in tags and "acc" in tags


def test_disabled_telemetry_records_nothing():
    """With telemetry off the ledger stays empty across a full synced step
    (the <2% headline-overhead criterion rests on this fast path)."""
    assert not telemetry.recording()
    preds, target = _data(C=5, B=32, seed=1)
    col = MetricCollection(
        {"p": MulticlassPrecision(num_classes=5, average="macro", validate_args=False)}
    )
    col.establish_compute_groups(preds[:8], target[:8])

    def run(p, t):
        st = col.functional_update(col.init_state(), p, t)
        return col.functional_compute(st, axis_name="r")

    out = jax.jit(shard_map(run, mesh=_mesh(), in_specs=(P("r"), P("r")), out_specs=P()))(
        preds, target
    )
    jax.block_until_ready(out)
    led = telemetry.get_ledger()
    assert led.records == []
    assert led.summary()["collectives_issued"] == 0
    # report helpers bail out before touching any state
    telemetry.record_collective(None, "all_reduce", "sum", (4,), "float32", 4, 8)
    telemetry.record_flush(None, entries=3, classes=1)
    assert led.records == []


def test_enable_disable_global_ledger():
    telemetry.enable()
    assert telemetry.enabled() and telemetry.recording()
    telemetry.record_collective(object(), "all_reduce", "sum", (8,), "float32", 4, 4)
    telemetry.disable()
    telemetry.record_collective(object(), "all_reduce", "sum", (8,), "float32", 4, 4)
    s = telemetry.summary()
    assert s["collectives_issued"] == 1
    assert s["wire_bytes_total"] == 2 * 3 / 4 * 32
    assert s["bytes_by_op"] == {"sum": 2 * 3 / 4 * 32}


def test_capture_is_independent_of_global_flag():
    with telemetry.capture() as led:
        telemetry.record_collective(object(), "all_gather", "gather", (2, 3), "int32", 4, 2)
    assert led.summary()["collectives_issued"] == 1
    assert led.summary()["wire_bytes_total"] == 1 * 24  # (N-1) * payload
    assert telemetry.get_ledger().records == []  # global stayed off
    # scope exited: no further recording
    telemetry.record_collective(object(), "all_gather", "gather", (2, 3), "int32", 4, 2)
    assert len(led.records) == 1


# -------------------------------------------------------------------- sinks


class _FakeWorld1Backend:
    """Duck-typed world-1 backend (uninstrumented, like test backends)."""

    in_trace = False
    has_object_channel = False

    def world_size(self):
        return 1

    def all_reduce(self, x, op, group=None):
        return x


def test_fused_reducer_reports_classes_and_flush_to_jsonl(tmp_path):
    """Even under a custom (uninstrumented) backend the FusedReducer reports
    its logical per-(op, dtype) classes and the flush event — and the JSONL
    sink writes one well-formed object per record."""
    path = tmp_path / "collectives.jsonl"
    with telemetry.capture(sinks=[JsonlSink(str(path))]) as led:
        red = FusedReducer(_FakeWorld1Backend())
        with telemetry.attribution("acc"):
            red.add(jnp.ones((3,), jnp.float32), "sum")
        with telemetry.attribution("f1"):
            red.add(jnp.ones((2, 2), jnp.float32), "sum")
        red.add(jnp.asarray(5, jnp.int32), "max")
        red.flush()

    s = led.summary()
    assert s["flush_count"] == 1
    assert s["fused_entries"] == 3
    reducer_recs = [r for r in led.records if r.source == "reducer"]
    assert {(r.op, r.dtype) for r in reducer_recs} == {("sum", "float32"), ("max", "int32")}
    fused = next(r for r in reducer_recs if r.op == "sum")
    assert fused.element_count == 7  # 3 + 4 fused into one class
    assert fused.tag == "acc+f1"

    lines = [json.loads(line) for line in path.read_text().splitlines()]
    assert len(lines) == len(led.records)
    for obj in lines:
        assert {"kind", "op", "dtype", "shape", "element_count", "payload_bytes",
                "wire_bytes", "backend", "tag", "world_size", "in_trace", "source"} <= set(obj)
    assert any(obj["kind"] == "flush" for obj in lines)


def test_attribution_nesting():
    assert telemetry.current_tag() == ""
    with telemetry.attribution("col"):
        with telemetry.attribution("MulticlassAccuracy"):
            assert telemetry.current_tag() == "col/MulticlassAccuracy"
        assert telemetry.current_tag() == "col"
    assert telemetry.current_tag() == ""


# ----------------------------------------------------------------- lockstep


def _schedule(n=3, start=0):
    return [
        (f"m{i}", "sum", "float32", (4,)) for i in range(start, start + n)
    ]


class _FakeRanksObjectBackend:
    """Emulated N-rank object channel: rank 0 is us, the rest are given.

    Mirrors the verifier's two-phase protocol: a string payload is the
    digest exchange, a list payload is the schedule exchange (mismatch
    diagnosis only)."""

    in_trace = False
    has_object_channel = True

    def __init__(self, *other_entries):
        self._others = [lockstep.normalize_schedule(e) for e in other_entries]
        self.gathers = 0

    def world_size(self):
        return 1 + len(self._others)

    def all_gather_object(self, obj, group=None):
        self.gathers += 1
        if isinstance(obj, str):  # digest phase
            return [obj] + [lockstep.schedule_fingerprint(s) for s in self._others]
        return [obj] + self._others  # schedule phase


def test_lockstep_agreement_passes_with_one_small_gather():
    be = _FakeRanksObjectBackend(_schedule())
    digest = telemetry.verify_lockstep(be, _schedule(), context="test")
    assert digest == lockstep.schedule_fingerprint(_schedule())
    assert be.gathers == 1  # happy path ships the digest only


def test_lockstep_violation_two_ranks_is_symmetric():
    """With exactly two ranks there is no majority — neither rank can be
    blamed, so the report names both and the first differing entry."""
    ours = _schedule(3)
    theirs = list(ours)
    theirs[1] = ("m1", "sum", "int32", (4,))  # dtype diverges at entry 1
    be = _FakeRanksObjectBackend(theirs)
    with pytest.raises(LockstepViolation, match=r"ranks 0 and 1 disagree .* entry 1") as ei:
        telemetry.verify_lockstep(be, ours, context="unit")
    msg = str(ei.value)
    assert "float32" in msg and "int32" in msg and "unit" in msg
    assert be.gathers == 2  # digest phase + schedule phase


def test_lockstep_violation_majority_names_outlier():
    """With a strict majority the outlier rank is named — here WE (rank 0)
    are the diverger against two agreeing peers."""
    ours = _schedule(3)
    theirs = _schedule(2)
    be = _FakeRanksObjectBackend(theirs, theirs)  # world=3, peers agree
    with pytest.raises(LockstepViolation, match=r"rank 0 diverges from the majority") as ei:
        telemetry.verify_lockstep(be, ours)
    assert "entry 2" in str(ei.value)  # ours has one entry more


def test_lockstep_violation_on_missing_entry():
    ours = _schedule(3)
    be = _FakeRanksObjectBackend(ours[:2])  # rank 1 plans one collective fewer
    with pytest.raises(LockstepViolation, match=r"entry 2") as ei:
        telemetry.verify_lockstep(be, ours)
    assert "<no entry>" in str(ei.value)


def test_lockstep_gather_shapes_do_not_fingerprint():
    """Gather-style entries may differ in shape across ranks (pad-gather-trim
    handles uneven dim 0) — shape must not enter the digest for them."""
    a = [("m0", "gather", "float32", (3, 2))]
    b = [("m0", "gather", "float32", (7, 2))]
    assert lockstep.schedule_fingerprint(a) == lockstep.schedule_fingerprint(b)
    # ...but reduce-op shapes MUST match
    a = [("m0", "sum", "float32", (3,))]
    b = [("m0", "sum", "float32", (7,))]
    assert lockstep.schedule_fingerprint(a) != lockstep.schedule_fingerprint(b)


def test_lockstep_skips_in_trace_backend_and_records_fingerprint():
    class _InTrace:
        in_trace = True
        has_object_channel = False

        def all_gather_object(self, obj, group=None):  # pragma: no cover
            raise AssertionError("in-trace backend must not exchange")

    with telemetry.capture() as led:
        digest = telemetry.verify_lockstep(_InTrace(), _schedule())
    assert digest is not None
    marks = [r for r in led.records if r.kind == "lockstep"]
    assert len(marks) == 1
    assert marks[0].in_trace is True
    assert marks[0].extra["digest"] == digest


def test_lockstep_configure_disables_exchange():
    telemetry.configure(lockstep_verification=False)
    try:
        be = _FakeRanksObjectBackend(_schedule(1))  # would diverge from 3 entries
        digest = telemetry.verify_lockstep(be, _schedule(3))
        assert digest is not None  # no raise: exchange disabled
        assert be.gathers == 0
    finally:
        telemetry.configure(lockstep_verification=True)


def test_collection_eager_flush_preverifies_schedule():
    """MetricCollection's fused eager sync exchanges its candidate schedule
    over an eager object-capable backend before any collective — divergent
    candidate sets raise instead of hanging (ADVICE r5 #3)."""
    from tpumetrics.parallel.backend import set_default_backend

    class _DivergentBackend:
        """Rank-1 peer reports an EMPTY schedule (its metric had a cached
        ``_computed``) — the exact ADVICE #3 deadlock scenario."""

        in_trace = False
        has_object_channel = True

        def available(self):
            return True

        def world_size(self):
            return 2

        def all_gather_object(self, obj, group=None):
            if isinstance(obj, str):  # digest phase
                return [obj, lockstep.schedule_fingerprint([])]
            return [obj, []]  # schedule phase

        def all_reduce(self, x, op, group=None):  # pragma: no cover
            raise AssertionError("collective issued despite schedule divergence")

        def all_gather(self, x, group=None):  # pragma: no cover
            raise AssertionError("collective issued despite schedule divergence")

    col = MetricCollection(
        {
            "prec": MulticlassPrecision(num_classes=5, average="macro", validate_args=False),
            "rec": MulticlassRecall(num_classes=5, average="macro", validate_args=False),
        }
    )
    preds, target = _data(C=5, B=32, seed=3)
    col.update(preds, target)
    set_default_backend(_DivergentBackend())
    try:
        with pytest.raises(LockstepViolation, match="rank 1"):
            col.compute()
        # the abort left every member clean: flags restored, nothing synced
        for m in col.values():
            assert not m._is_synced and m._to_sync
    finally:
        set_default_backend(None)
