"""Tier-1 dots-regression guard.

DOTS_PASSED (the driver's tier-1 health number) can shrink SILENTLY: a
broken conftest probe, an import error under ``--continue-on-collection-
errors``, or an over-eager ``slow`` marker sweep all make the suite smaller
without failing anything.  This guard pins the COLLECTED non-slow test count
to a floor recorded in ``bench_floors.json`` (``tier1_collection_floor``),
so an accidental mass-skip fails loudly instead of quietly eroding coverage.

Engagement is decided from the INVOCATION, not the collection result: a run
pointed at the whole ``tests/`` tree (or the repo root) is a full-suite run
and the guard asserts — a module vanishing from such a run is exactly the
failure being guarded against, so it must FAIL the guard, never skip it.
Runs pointed at specific files/nodes, or filtered with ``-k``/non-tier-1
``-m``, skip.
"""

from __future__ import annotations

import json
import os

import pytest

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
_TESTS_DIR = os.path.dirname(os.path.abspath(__file__))


def test_tier1_collection_floor(request):
    keyword = getattr(request.config.option, "keyword", "") or ""
    markexpr = getattr(request.config.option, "markexpr", "") or ""
    if keyword or markexpr not in ("", "not slow"):
        pytest.skip(f"filtered run (-k {keyword!r} / -m {markexpr!r}): not tier-1 shaped")
    arg_paths = [
        os.path.abspath(str(a).split("::")[0]) for a in (request.config.args or [])
    ]
    if not any(p in (_TESTS_DIR, _REPO) for p in arg_paths):
        pytest.skip(f"targeted run ({arg_paths}): the floor only binds full-suite runs")

    # full-suite invocation: every top-level test module must have survived
    # collection — a vanished module IS the mass-skip being guarded against
    collected_files = {
        os.path.basename(item.location[0]) for item in request.session.items
    }
    all_modules = {
        name for name in os.listdir(_TESTS_DIR)
        if name.startswith("test_") and name.endswith(".py")
    }
    missing = sorted(all_modules - collected_files)
    assert not missing, (
        f"Full-suite run collected nothing from {missing}: a collection error or "
        "module-wide skip is silently dropping tests (check for import failures "
        "under --continue-on-collection-errors)."
    )
    with open(os.path.join(_REPO, "bench_floors.json")) as fh:
        floor = int(json.load(fh)["tier1_collection_floor"])
    n = len(request.session.items)
    assert n >= floor, (
        f"Tier-1 collected only {n} non-slow tests but the floor is {floor}: "
        "a collection error, a broken conftest probe, or an over-eager slow-marker "
        "sweep is silently shrinking the suite. If the shrink is intentional "
        "(tests moved/merged), lower tier1_collection_floor in bench_floors.json "
        "in the same change, with a note."
    )
