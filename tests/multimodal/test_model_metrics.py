"""Model-backed metrics (BERTScore/InfoLM/CLIPScore/CLIP-IQA) with tiny
randomly-initialized offline models (counterpart of reference
``tests/unittests/{text/test_bertscore,multimodal}/``)."""

from __future__ import annotations

import os

os.environ.setdefault("HF_HUB_OFFLINE", "1")

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpumetrics.functional.multimodal import clip_image_quality_assessment, clip_score
from tpumetrics.functional.text import bert_score, infolm
from tpumetrics.multimodal import CLIPImageQualityAssessment, CLIPScore
from tpumetrics.text import BERTScore, InfoLM


# ------------------------------------------------- tiny offline fixtures


class _WordTokenizer:
    """Whitespace tokenizer with a growing vocabulary and [CLS]/[SEP]."""

    cls_token_id = 1
    sep_token_id = 2
    pad_token_id = 0
    mask_token_id = 3

    def __init__(self):
        self.vocab = {}

    def _id(self, word):
        if word not in self.vocab:
            self.vocab[word] = 4 + (len(self.vocab) % 96)
        return self.vocab[word]

    def __call__(self, sentences, **kwargs):
        rows = [[self.cls_token_id] + [self._id(w) for w in s.lower().split()] + [self.sep_token_id] for s in sentences]
        max_len = max(len(r) for r in rows)
        input_ids = np.full((len(rows), max_len), self.pad_token_id, np.int32)
        attention = np.zeros((len(rows), max_len), np.int32)
        for i, r in enumerate(rows):
            input_ids[i, : len(r)] = r
            attention[i, : len(r)] = 1
        return {"input_ids": input_ids, "attention_mask": attention}


class _ToyEmbedder:
    """Deterministic embedding model: token-id embedding table."""

    def __init__(self, dim=16, vocab=100, seed=0):
        rng = np.random.default_rng(seed)
        self.table = jnp.asarray(rng.standard_normal((vocab, dim)), jnp.float32)

    def __call__(self, model, batch):
        ids = jnp.asarray(batch["input_ids"])
        return self.table[ids]


class _ToyMLM:
    """Deterministic masked LM whose per-position logits mix in sequence
    context (a context-free table would predict the same distribution at
    every masked slot, making InfoLM degenerate)."""

    def __init__(self, vocab=100, seed=0):
        rng = np.random.default_rng(seed)
        self.table = jnp.asarray(rng.standard_normal((vocab, vocab)), jnp.float32)

    def __call__(self, input_ids, attention_mask=None):
        class _Out:
            pass

        ids = jnp.asarray(input_ids)
        token_logits = self.table[ids]
        context = token_logits.mean(axis=1, keepdims=True)
        out = _Out()
        out.logits = token_logits + 2.0 * context
        return out


# -------------------------------------------------------------- BERTScore


def test_bert_score_perfect_match():
    tok = _WordTokenizer()
    emb = _ToyEmbedder()
    preds = ["hello there general kenobi", "the cat sat"]
    out = bert_score(preds, preds, model=emb, user_tokenizer=tok, user_forward_fn=emb)
    assert np.allclose(np.asarray(out["f1"]), 1.0, atol=1e-5)
    assert np.allclose(np.asarray(out["precision"]), 1.0, atol=1e-5)


def test_bert_score_orders_similarity():
    tok = _WordTokenizer()
    emb = _ToyEmbedder()
    target = ["the quick brown fox jumps"]
    close = ["the quick brown fox leaps"]
    far = ["completely unrelated words entirely different"]
    f_close = float(bert_score(close, target, model=emb, user_tokenizer=tok, user_forward_fn=emb)["f1"][0])
    f_far = float(bert_score(far, target, model=emb, user_tokenizer=tok, user_forward_fn=emb)["f1"][0])
    assert f_close > f_far


def test_bert_score_class_and_idf():
    tok = _WordTokenizer()
    emb = _ToyEmbedder()
    metric = BERTScore(model=emb, user_tokenizer=tok, user_forward_fn=emb, idf=True)
    metric.update(["the cat sat on the mat"], ["the cat sat on a mat"])
    metric.update(["a dog barked"], ["the dog barked"])
    out = metric.compute()
    assert np.asarray(out["f1"]).shape == (2,)
    assert (np.asarray(out["f1"]) > 0).all()
    metric.reset()
    assert metric._preds == []


def test_bert_score_gated_default():
    with pytest.raises(ModuleNotFoundError, match="Pass your own"):
        bert_score(["a"], ["a"], model_name_or_path="definitely-not-cached-model")


# ----------------------------------------------------------------- InfoLM


def test_infolm_identical_is_best():
    tok = _WordTokenizer()
    mlm = _ToyMLM()
    preds = ["the cat sat on the mat"]
    target_same = ["the cat sat on the mat"]
    target_diff = ["a dog runs fast outside today"]
    same = float(infolm(preds, target_same, model=mlm, user_tokenizer=tok, information_measure="l2_distance", idf=False))
    diff = float(infolm(preds, target_diff, model=mlm, user_tokenizer=tok, information_measure="l2_distance", idf=False))
    assert same < 1e-6
    assert diff > same


@pytest.mark.parametrize(
    "measure, kwargs",
    [
        ("kl_divergence", {}),
        ("alpha_divergence", {"alpha": 0.5}),
        ("beta_divergence", {"beta": 0.5}),
        ("ab_divergence", {"alpha": 0.5, "beta": 0.5}),
        ("renyi_divergence", {"alpha": 0.5}),
        ("l1_distance", {}),
        ("l_infinity_distance", {}),
        ("fisher_rao_distance", {}),
    ],
)
def test_infolm_measures(measure, kwargs):
    tok = _WordTokenizer()
    mlm = _ToyMLM()
    val = infolm(
        ["the cat sat"], ["a cat sits"], model=mlm, user_tokenizer=tok,
        information_measure=measure, idf=False, **kwargs,
    )
    assert np.isfinite(float(val))


def test_infolm_class_and_validation():
    tok = _WordTokenizer()
    mlm = _ToyMLM()
    m = InfoLM(model=mlm, user_tokenizer=tok, information_measure="l1_distance", idf=True)
    m.update(["the cat sat"], ["a cat sat"])
    assert np.isfinite(float(m.compute()))
    with pytest.raises(ValueError, match="information_measure"):
        InfoLM(information_measure="bad")
    with pytest.raises(ValueError, match="alpha"):
        InfoLM(information_measure="alpha_divergence", alpha=1.0)


# ------------------------------------------------------------- CLIP family


@pytest.fixture(scope="module")
def tiny_clip():
    from transformers import CLIPConfig, CLIPTextConfig, CLIPVisionConfig, FlaxCLIPModel

    tc = CLIPTextConfig(
        hidden_size=32, intermediate_size=64, num_attention_heads=2, num_hidden_layers=2,
        vocab_size=100, max_position_embeddings=64, projection_dim=32,
    )
    vc = CLIPVisionConfig(
        hidden_size=32, intermediate_size=64, num_attention_heads=2, num_hidden_layers=2,
        image_size=32, patch_size=8, projection_dim=32,
    )
    cfg = CLIPConfig(text_config=tc.to_dict(), vision_config=vc.to_dict(), projection_dim=32)
    model = FlaxCLIPModel(cfg)

    class _ClipProcessor(_WordTokenizer):
        def __call__(self, text=None, images=None, return_tensors="np", padding=True):
            out = {}
            if text is not None:
                out.update(super().__call__(text))
            if images is not None:
                pix = np.stack([np.asarray(i, np.float32) for i in images])
                if pix.shape[-1] == 3:  # HWC -> CHW
                    pix = pix.transpose(0, 3, 1, 2)
                out["pixel_values"] = pix
            return out

    return model, _ClipProcessor()


def test_clip_score(tiny_clip):
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.integers(0, 255, (2, 3, 32, 32)), jnp.float32)
    texts = ["a photo of a cat", "a photo of a dog"]
    score = clip_score(images, texts, model_name_or_path=tiny_clip)
    assert np.isfinite(float(score)) and float(score) >= 0

    metric = CLIPScore(model_name_or_path=tiny_clip)
    metric.update(images, texts)
    metric.update(images, texts)
    assert np.isclose(float(metric.compute()), max(float(score), 0.0), atol=1e-4)

    with pytest.raises(ValueError, match="same"):
        clip_score(images, ["just one"], model_name_or_path=tiny_clip)


def test_clip_iqa(tiny_clip):
    rng = np.random.default_rng(1)
    images = jnp.asarray(rng.random((2, 3, 32, 32)), jnp.float32)
    out = clip_image_quality_assessment(images, model_name_or_path=tiny_clip, prompts=("quality",))
    assert out.shape == (2,)
    assert ((np.asarray(out) >= 0) & (np.asarray(out) <= 1)).all()

    out = clip_image_quality_assessment(
        images, model_name_or_path=tiny_clip, prompts=("quality", ("Nice photo.", "Terrible photo."))
    )
    assert set(out.keys()) == {"quality", "user_defined_0"}

    metric = CLIPImageQualityAssessment(model_name_or_path=tiny_clip, prompts=("quality", "sharpness"))
    metric.update(images)
    res = metric.compute()
    assert set(res.keys()) == {"quality", "sharpness"}

    with pytest.raises(ValueError, match="prompts"):
        clip_image_quality_assessment(images, model_name_or_path=tiny_clip, prompts=("nonexistent-prompt",))


def test_clip_score_gated_default():
    with pytest.raises(ModuleNotFoundError, match="network"):
        CLIPScore(model_name_or_path="openai/clip-not-cached")


def test_bert_score_batched_forward_matches_single():
    """Chunked model forwards (batch_size) must not change scores."""
    from tpumetrics.functional.text import bert_score

    tok = _WordTokenizer()
    emb = _ToyEmbedder()
    preds = ["the cat sat", "a dog ran fast", "hello", "one two three four"]
    target = ["the cat sat down", "a dog ran", "hello there", "one two three"]
    big = bert_score(preds, target, model=emb, user_tokenizer=tok, user_forward_fn=emb, batch_size=64)
    tiny = bert_score(preds, target, model=emb, user_tokenizer=tok, user_forward_fn=emb, batch_size=1)
    for k in ("precision", "recall", "f1"):
        assert np.allclose(np.asarray(big[k]), np.asarray(tiny[k]), atol=1e-6), k


def _write_baseline_csv(path, rows):
    """bert-score rescale-baseline layout (reference bert.py:175-184):
    header line, then ``layer,P,R,F`` rows."""
    lines = ["LAYER,P,R,F"] + [",".join(str(v) for v in r) for r in rows]
    path.write_text("\n".join(lines) + "\n")


def test_bert_score_rescale_with_local_baseline(tmp_path):
    """`(x - b) / (1 - b)` against the last baseline row when num_layers is
    unset (reference bert.py:225-240 with num_layers=-1)."""
    tok = _WordTokenizer()
    emb = _ToyEmbedder()
    preds = ["the cat sat", "hello there"]
    target = ["the cat sat down", "hello there friend"]
    csv_path = tmp_path / "baseline.csv"
    _write_baseline_csv(csv_path, [[0, 0.9, 0.9, 0.9], [1, 0.3, 0.4, 0.5]])

    raw = bert_score(preds, target, model=emb, user_tokenizer=tok, user_forward_fn=emb)
    scaled = bert_score(
        preds, target, model=emb, user_tokenizer=tok, user_forward_fn=emb,
        rescale_with_baseline=True, baseline_path=str(csv_path),
    )
    for key, b in (("precision", 0.3), ("recall", 0.4), ("f1", 0.5)):
        expect = (np.asarray(raw[key]) - b) / (1 - b)
        assert np.allclose(np.asarray(scaled[key]), expect, atol=1e-6), key

    # the class path reaches the same numbers
    m = BERTScore(
        model=emb, user_tokenizer=tok, user_forward_fn=emb,
        rescale_with_baseline=True, baseline_path=str(csv_path),
    )
    m.update(preds, target)
    out = m.compute()
    assert np.allclose(np.asarray(out["f1"]), np.asarray(scaled["f1"]), atol=1e-6)


def test_bert_score_rescale_without_local_file_raises():
    tok = _WordTokenizer()
    emb = _ToyEmbedder()
    with pytest.raises(NotImplementedError, match="baseline_path"):
        bert_score(["a"], ["a"], model=emb, user_tokenizer=tok, user_forward_fn=emb,
                   rescale_with_baseline=True)
    with pytest.raises(NotImplementedError, match="baseline_path"):
        BERTScore(model=emb, user_tokenizer=tok, user_forward_fn=emb, rescale_with_baseline=True)


def test_bert_score_baseline_path_inert_without_flag(tmp_path):
    """Reference loads the baseline only when rescale_with_baseline=True
    (bert.py:394); a bare baseline_path leaves scores untouched."""
    tok = _WordTokenizer()
    emb = _ToyEmbedder()
    csv_path = tmp_path / "baseline.csv"
    _write_baseline_csv(csv_path, [[0, 0.5, 0.5, 0.5]])
    preds, target = ["the cat sat"], ["the cat sat down"]
    raw = bert_score(preds, target, model=emb, user_tokenizer=tok, user_forward_fn=emb)
    with_path = bert_score(
        preds, target, model=emb, user_tokenizer=tok, user_forward_fn=emb,
        baseline_path=str(csv_path),
    )
    assert np.allclose(np.asarray(raw["f1"]), np.asarray(with_path["f1"]))


def test_bert_score_scorer_signature_independent_of_corpus_size():
    """Corpora whose chunk counts round to the same power of two share ONE
    compiled _score_scan signature (padding happens outside the jit)."""
    from tpumetrics.functional.text.bert import _score_scan

    tok = _WordTokenizer()
    emb = _ToyEmbedder()
    # same max token length (jit signature includes seq); sizes 5 and 7 both
    # round to k=2 chunks of step=4
    corpus5 = [f"w{i} x y z" for i in range(5)]
    corpus7 = [f"w{i} x y z" for i in range(7)]
    before = _score_scan._cache_size()
    bert_score(corpus5, corpus5, model=emb, user_tokenizer=tok, user_forward_fn=emb, batch_size=4)
    after_first = _score_scan._cache_size()
    bert_score(corpus7, corpus7, model=emb, user_tokenizer=tok, user_forward_fn=emb, batch_size=4)
    assert _score_scan._cache_size() == after_first
    assert after_first >= before  # first call may have hit an existing entry


def test_text_model_metrics_string_state_sync_policy():
    """Sentence buffers are host strings: an in-trace (array-only) backend
    must raise rather than silently score one rank's shard; an eager backend
    with a host-object channel merges them (cross-process this is
    MultiHostBackend.all_gather_object — tests/test_multihost.py)."""
    from tpumetrics.metric import TPUMetricsUserError
    from tpumetrics.parallel.backend import AxisBackend
    from tpumetrics.text import BERTScore

    tok = _WordTokenizer()
    emb = _ToyEmbedder()
    m = BERTScore(model=emb, user_tokenizer=tok, user_forward_fn=emb, sync_backend=AxisBackend("ddp"))
    m.update(["a b"], ["a b"])
    with pytest.raises(TPUMetricsUserError):
        m._sync_dist()

    # escape hatch: user declares every rank holds the full corpus
    m2 = BERTScore(model=emb, user_tokenizer=tok, user_forward_fn=emb, sentences_replicated=True)
    m2.update(["a b"], ["a b"])
    m2._sync_dist()  # must not raise

    # eager single-process backend: object-gather is the identity, sync succeeds
    m3 = BERTScore(model=emb, user_tokenizer=tok, user_forward_fn=emb)
    m3.update(["a b"], ["a b"])
    m3._sync_dist()  # must not raise
    assert m3._preds == ["a b"]
    m3.reset()
    assert m3._sentence_cache is None

    # a custom dist_sync_fn only sees array states — it must not silently
    # merge arrays while keeping one rank's sentence shard
    m4 = BERTScore(model=emb, user_tokenizer=tok, user_forward_fn=emb)
    m4.update(["a b"], ["a b"])
    with pytest.raises(TPUMetricsUserError):
        m4._sync_dist(dist_sync_fn=lambda x, group: [x])

    # dist_sync_on_step would merge-but-never-restore the unregistered
    # sentence buffers through forward's per-step sync — must stay loud
    m5 = BERTScore(model=emb, user_tokenizer=tok, user_forward_fn=emb, dist_sync_on_step=True)
    m5.update(["a b"], ["a b"])
    with pytest.raises(TPUMetricsUserError):
        m5._sync_dist()


def test_bert_score_all_layers_output_contract():
    """all_layers (layer axis > 1) returns (num_layers, n) like the reference's
    transpose-and-squeeze (ref functional/text/bert.py:139-140); layer 0 of a
    stacked forward must equal the plain single-layer score."""
    tok = _WordTokenizer()
    base = _ToyEmbedder()

    class _ThreeLayer:
        def __call__(self, model, batch):
            h = base(model, batch)  # (b, s, d)
            return jnp.stack([h, 0.5 * h + 0.1, -h], axis=1)  # (b, 3, s, d)

    preds = ["the cat sat on the mat", "a dog barked", "hello there friend"]
    target = ["the cat sat on a mat", "the dog barked", "hello there"]
    out = bert_score(preds, target, model=object(), user_tokenizer=tok, user_forward_fn=_ThreeLayer())
    for key in ("precision", "recall", "f1"):
        assert np.asarray(out[key]).shape == (3, len(preds)), key
    single = bert_score(preds, target, model=object(), user_tokenizer=tok, user_forward_fn=base)
    # layer 0 is the unscaled embedding — identical to the single-layer run
    np.testing.assert_allclose(np.asarray(out["f1"][0]), np.asarray(single["f1"]), atol=1e-6)
    # small corpus (single chunk) and large corpus (scan path) agree
    big_preds, big_target = preds * 80, target * 80
    big = bert_score(big_preds, big_target, model=object(), user_tokenizer=tok,
                     user_forward_fn=_ThreeLayer(), batch_size=32)
    assert np.asarray(big["f1"]).shape == (3, len(big_preds))
    np.testing.assert_allclose(np.asarray(big["f1"])[:, : len(preds)], np.asarray(out["f1"]), atol=1e-5)
