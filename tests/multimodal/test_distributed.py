"""Distributed class tests for EVERY exported multimodal metric.

Counterpart of the reference funneling all metric tests through its
2-process pool (reference tests/unittests/conftest.py:28-63). Both CLIP
metrics tokenize/process on host before the Flax forward, so their
distributed surface is the reduce-op sum-state merge (emulated-DDP mode) —
the same wire the eager DCN backend drives. A coverage gate fails when a
new export lacks an entry.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

import tpumetrics.multimodal as mm_domain
from tests.helpers.testers import run_ddp_self_equivalence_test
from tests.multimodal.test_model_metrics import tiny_clip  # noqa: F401  (fixture)

_rng = np.random.default_rng(41)

_TEXTS = [
    "a photo of a cat",
    "a photo of a dog",
    "a red house on a hill",
    "two birds in the sky",
    "a small blue car",
    "an empty street at night",
]


def _image_text_batches(n_batches=4, per_batch=2):
    out = []
    for b in range(n_batches):
        images = jnp.asarray(_rng.integers(0, 255, (per_batch, 3, 32, 32)), jnp.float32)
        texts = [_TEXTS[(b * per_batch + i) % len(_TEXTS)] for i in range(per_batch)]
        out.append((images, texts))
    return out


def _image_batches(n_batches=4, per_batch=2):
    return [
        (jnp.asarray(_rng.random((per_batch, 3, 32, 32)), jnp.float32),)
        for _ in range(n_batches)
    ]


CASES = {
    "CLIPScore": ("image_text", ("emulated",)),
    "CLIPImageQualityAssessment": ("image", ("emulated",)),
}


def test_every_multimodal_class_has_a_distributed_case():
    assert set(CASES) == set(mm_domain.__all__)


def test_clip_score_distributed(tiny_clip):  # noqa: F811
    run_ddp_self_equivalence_test(
        lambda: mm_domain.CLIPScore(model_name_or_path=tiny_clip),
        _image_text_batches(),
        atol=1e-4,
    )


def test_clip_iqa_distributed(tiny_clip):  # noqa: F811
    run_ddp_self_equivalence_test(
        lambda: mm_domain.CLIPImageQualityAssessment(
            model_name_or_path=tiny_clip, prompts=("quality", "sharpness")
        ),
        _image_batches(),
        atol=1e-4,
    )
