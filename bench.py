"""Headline benchmark: metric update+compute latency per step (the hot loop).

Covers the BASELINE.md target configs:
- MulticlassAccuracy jitted update+compute (headline; vs reference on torch)
- MetricCollection(Accuracy, F1, AUROC) with dist_sync_on_step semantics,
  synced in-trace over an 8-device mesh (subprocess with 8 virtual CPU
  devices — the driver machine exposes one TPU chip)
- detection.MeanAveragePrecision update+compute (ragged-state cost)
- image.FrechetInceptionDistance streaming update (feature-state bandwidth)
- image.LPIPS streaming update with a conv backbone (feature distances)
- text.BERTScore under emulated 4-rank DDP: rank-strided updates, state
  merge, one batched embed+score (multi-host/DCN-scale stand-in)

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "details"}.
``vs_baseline`` = reference_us / ours_us (higher is better; >1 means faster
than the reference).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

BATCH = 8192
NUM_CLASSES = 128
STEPS = 50


def _bench_tpumetrics() -> float:
    import jax
    import jax.numpy as jnp

    from tpumetrics.classification import MulticlassAccuracy

    metric = MulticlassAccuracy(num_classes=NUM_CLASSES, average="micro", validate_args=False)

    def step(state, preds, target):
        new_state = metric.functional_update(state, preds, target)
        return new_state, metric.functional_compute(new_state)

    step = jax.jit(step, donate_argnums=(0,))

    rng = np.random.default_rng(0)
    preds = jnp.asarray(rng.standard_normal((BATCH, NUM_CLASSES), dtype=np.float32))
    target = jnp.asarray(rng.integers(0, NUM_CLASSES, size=(BATCH,)), dtype=jnp.int32)

    state = metric.init_state()
    state, val = step(state, preds, target)  # compile
    jax.block_until_ready(val)

    t0 = time.perf_counter()
    for _ in range(STEPS):
        state, val = step(state, preds, target)
    jax.block_until_ready(val)
    t1 = time.perf_counter()
    return (t1 - t0) / STEPS * 1e6  # us/step


def _bench_reference() -> float:
    """Time the reference TorchMetrics MulticlassAccuracy (torch CPU); falls
    back to an equivalent hand-written torch update+compute step when the
    reference's deps (lightning_utilities) are absent."""
    import torch

    rng = np.random.default_rng(0)
    preds = torch.from_numpy(rng.standard_normal((BATCH, NUM_CLASSES), dtype=np.float32))
    target = torch.from_numpy(rng.integers(0, NUM_CLASSES, size=(BATCH,)).astype(np.int64))

    try:
        sys.path.insert(0, "/root/reference/src")
        from torchmetrics.classification import MulticlassAccuracy as RefAccuracy

        metric = RefAccuracy(num_classes=NUM_CLASSES, average="micro", validate_args=False)
        metric.update(preds, target)  # warmup
        metric.compute()
        metric.reset()

        t0 = time.perf_counter()
        for _ in range(STEPS):
            metric.update(preds, target)
            metric._computed = None
            metric.compute()
        t1 = time.perf_counter()
        return (t1 - t0) / STEPS * 1e6  # us/step
    except Exception:
        pass

    # equivalent torch step: argmax -> bincount confusion counts -> micro acc
    def step(tp, total, preds, target):
        labels = preds.argmax(dim=1)
        counts = torch.bincount(target * NUM_CLASSES + labels, minlength=NUM_CLASSES * NUM_CLASSES)
        confmat = counts.reshape(NUM_CLASSES, NUM_CLASSES)
        tp = tp + confmat.diagonal().sum()
        total = total + target.numel()
        return tp, total, tp.float() / total.float()

    tp = torch.zeros((), dtype=torch.long)
    total = torch.zeros((), dtype=torch.long)
    step(tp, total, preds, target)  # warmup
    t0 = time.perf_counter()
    for _ in range(STEPS):
        tp, total, val = step(tp, total, preds, target)
    t1 = time.perf_counter()
    return (t1 - t0) / STEPS * 1e6  # us/step


_COLLECTION_SYNC_SCRIPT = r"""
import os, sys, time, json
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
sys.path.insert(0, {repo_dir!r})
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from tpumetrics import MetricCollection
from tpumetrics.classification import MulticlassAccuracy, MulticlassF1Score, MulticlassAUROC

C, B, STEPS = 16, 1024, 20
col = MetricCollection({
    "acc": MulticlassAccuracy(num_classes=C, average="micro", validate_args=False),
    "f1": MulticlassF1Score(num_classes=C, average="macro", validate_args=False),
    "auroc": MulticlassAUROC(num_classes=C, validate_args=False, thresholds=64),
})
mesh = Mesh(np.asarray(jax.devices()[:8]), ("dp",))

def sharded_step(state, preds, target):
    # dist_sync_on_step: accumulate locally, sync in-trace, return batch vals
    new_state, vals = col.functional_forward(state, preds, target, axis_name="dp")
    return new_state, vals

step = jax.jit(
    jax.shard_map(
        sharded_step, mesh=mesh,
        in_specs=(P(), P("dp"), P("dp")), out_specs=(P(), P()),
        check_vma=False,
    ),
    donate_argnums=(0,),
)
rng = np.random.default_rng(0)
preds = jnp.asarray(jax.nn.softmax(jnp.asarray(rng.standard_normal((B, C), dtype=np.float32))))
target = jnp.asarray(rng.integers(0, C, size=(B,)), dtype=jnp.int32)
state = col.init_state()
state, vals = step(state, preds, target)
jax.block_until_ready(vals)
t0 = time.perf_counter()
for _ in range(STEPS):
    state, vals = step(state, preds, target)
jax.block_until_ready(vals)
t1 = time.perf_counter()
print(json.dumps({"us_per_step": (t1 - t0) / STEPS * 1e6}))
"""


def _bench_collection_sync_8dev() -> float:
    """Per-step latency of MetricCollection(Accuracy, F1, AUROC) with
    in-trace cross-device sync (dist_sync_on_step) over an 8-device mesh.
    Runs in a subprocess because the parent owns the TPU backend."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    script = _COLLECTION_SYNC_SCRIPT.replace(
        "{repo_dir!r}", repr(os.path.dirname(os.path.abspath(__file__)))
    )
    out = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True, text=True, timeout=300, env=env,
    )
    if out.returncode != 0:
        raise RuntimeError(out.stderr[-2000:])
    return float(json.loads(out.stdout.strip().splitlines()[-1])["us_per_step"])


def _bench_map() -> float:
    """MeanAveragePrecision update+compute on synthetic detections — the
    ragged-state path (variable boxes per image)."""
    import jax.numpy as jnp

    from tpumetrics.detection import MeanAveragePrecision

    rng = np.random.default_rng(0)
    n_imgs, steps = 16, 5

    def boxes(n):
        xy = rng.uniform(0, 80, size=(n, 2))
        wh = rng.uniform(4, 20, size=(n, 2))
        return np.concatenate([xy, xy + wh], axis=1)

    preds, target = [], []
    for i in range(n_imgs):
        nd, ng = int(rng.integers(3, 12)), int(rng.integers(2, 8))
        preds.append({
            "boxes": jnp.asarray(boxes(nd), jnp.float32),
            "scores": jnp.asarray(rng.uniform(0.1, 1.0, nd), jnp.float32),
            "labels": jnp.asarray(rng.integers(0, 4, nd), jnp.int32),
        })
        target.append({
            "boxes": jnp.asarray(boxes(ng), jnp.float32),
            "labels": jnp.asarray(rng.integers(0, 4, ng), jnp.int32),
        })

    m = MeanAveragePrecision()
    m.update(preds, target)  # warmup (traces IoU kernels)
    m.compute()
    m.reset()
    t0 = time.perf_counter()
    for _ in range(steps):
        m.update(preds, target)
        m.compute()
        m.reset()  # fixed 16-image cost per step
    t1 = time.perf_counter()
    return (t1 - t0) / steps * 1e6


def _bench_fid() -> float:
    """FID streaming update throughput with a deterministic extractor —
    exercises the large feature-state accumulation path."""
    import jax
    import jax.numpy as jnp

    from tpumetrics.image import FrechetInceptionDistance

    dim, batch, steps = 256, 128, 20
    rng = np.random.default_rng(0)
    proj = jnp.asarray(rng.standard_normal((3 * 32 * 32, dim), dtype=np.float32))

    def extractor(imgs):
        flat = imgs.reshape(imgs.shape[0], -1).astype(jnp.float32)
        return jnp.tanh(flat @ proj)

    m = FrechetInceptionDistance(feature=extractor, num_features=dim)
    real = jnp.asarray(rng.integers(0, 255, size=(batch, 3, 32, 32)), jnp.uint8)
    fake = jnp.asarray(rng.integers(0, 255, size=(batch, 3, 32, 32)), jnp.uint8)
    m.update(real, real=True)  # warmup
    m.update(fake, real=False)
    jax.block_until_ready(m.real_features_sum)
    t0 = time.perf_counter()
    for _ in range(steps):
        m.update(real, real=True)
        m.update(fake, real=False)
    jax.block_until_ready(m.real_features_sum)
    t1 = time.perf_counter()
    return (t1 - t0) / steps * 1e6


def _bench_lpips() -> float:
    """LPIPS streaming update with a deterministic conv backbone — exercises
    the feature-distance accumulation path (BASELINE 'FID + LPIPS' config)."""
    import jax
    import jax.numpy as jnp

    from tpumetrics.image import LearnedPerceptualImagePatchSimilarity

    rng = np.random.default_rng(0)
    k1 = jnp.asarray(rng.standard_normal((16, 3, 3, 3), dtype=np.float32) * 0.1)
    k2 = jnp.asarray(rng.standard_normal((32, 16, 3, 3), dtype=np.float32) * 0.1)

    def backbone(x):
        h1 = jax.nn.relu(jax.lax.conv_general_dilated(x, k1, (2, 2), "SAME"))
        h2 = jax.nn.relu(jax.lax.conv_general_dilated(h1, k2, (2, 2), "SAME"))
        return [h1, h2]

    m = LearnedPerceptualImagePatchSimilarity(net_type=backbone)
    batch, steps = 64, 20
    img1 = jnp.asarray(rng.uniform(-1, 1, (batch, 3, 64, 64)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(-1, 1, (batch, 3, 64, 64)), jnp.float32)
    m.update(img1, img2)  # warmup
    jax.block_until_ready(m.sum_scores)
    t0 = time.perf_counter()
    for _ in range(steps):
        m.update(img1, img2)
    jax.block_until_ready(m.sum_scores)
    t1 = time.perf_counter()
    return (t1 - t0) / steps * 1e6


def _bench_bertscore_ddp() -> float:
    """BERTScore under emulated DDP: 4 rank-strided replicas with a
    deterministic embedder, states merged then computed once (BASELINE
    'BERTScore under DDP' config — multi-host merge + batched embed)."""
    import jax.numpy as jnp

    from tpumetrics.text import BERTScore

    rng = np.random.default_rng(0)
    vocab = ["alpha", "beta", "gamma", "delta", "epsilon", "zeta", "eta", "theta"]

    def sentences(n):
        return [" ".join(rng.choice(vocab, size=rng.integers(3, 9))) for _ in range(n)]

    word_ids = {w: i + 1 for i, w in enumerate(vocab)}  # deterministic ids

    def tokenizer(batch, max_length=16):
        ids = np.zeros((len(batch), max_length), np.int32)
        mask = np.zeros((len(batch), max_length), np.int32)
        for i, s in enumerate(batch):
            toks = [word_ids[w] for w in s.split()][:max_length]
            ids[i, : len(toks)] = toks
            mask[i, : len(toks)] = 1
        return {"input_ids": jnp.asarray(ids), "attention_mask": jnp.asarray(mask)}

    emb = jnp.asarray(rng.standard_normal((98, 32), dtype=np.float32))

    def forward_fn(model, batch):
        return emb[batch["input_ids"]]

    world, steps, per_rank = 4, 8, 32
    preds = [sentences(per_rank) for _ in range(world * steps)]
    target = [sentences(per_rank) for _ in range(world * steps)]

    def make():
        return BERTScore(model=object(), user_tokenizer=tokenizer, user_forward_fn=forward_fn)

    make().update(preds[0], target[0])  # warm tokenizer path
    t0 = time.perf_counter()
    replicas = [make() for _ in range(world)]
    for rank, m in enumerate(replicas):
        for i in range(rank, world * steps, world):
            m.update(preds[i], target[i])
    # sentence states are host-side Python lists (device sync is refused for
    # them, tpumetrics/text/_sentence_state.py) — the multi-host analogue is
    # an all_gather_object of the sentences, emulated here by concatenation,
    # followed by ONE batched embed+score over the union
    combined = make()
    for m in replicas:
        combined.update(m._preds, m._target)
    out = combined.compute()
    f1 = np.asarray(out["f1"])
    assert f1.shape[0] == world * steps * per_rank, f1.shape
    t1 = time.perf_counter()
    return (t1 - t0) * 1e6  # us for the full merged evaluation


def _enable_compilation_cache() -> None:
    """Persistent XLA compile cache: one-time eager/jit compiles (expensive on
    remote-attached accelerators) amortize across bench runs, as they do in
    any long-lived production process."""
    import jax

    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def main() -> None:
    _enable_compilation_cache()
    ours_us = _bench_tpumetrics()
    try:
        ref_us = _bench_reference()
        vs_baseline = round(ref_us / ours_us, 3)
    except Exception:
        vs_baseline = None  # baseline unavailable — not a measured tie

    details = {}
    for name, fn in (
        ("collection_sync_8dev_us", _bench_collection_sync_8dev),
        ("map_ragged_update_compute_us", _bench_map),
        ("fid_stream_update_us", _bench_fid),
        ("lpips_stream_update_us", _bench_lpips),
        ("bertscore_ddp_eval_us", _bench_bertscore_ddp),
    ):
        try:
            details[name] = round(fn(), 2)
        except Exception as err:  # sub-bench failure must not kill the headline
            details[name] = f"error: {type(err).__name__}"

    print(
        json.dumps(
            {
                "metric": "multiclass_accuracy_update_compute",
                "value": round(ours_us, 2),
                "unit": "us/step",
                "vs_baseline": vs_baseline,
                "details": details,
            }
        )
    )


if __name__ == "__main__":
    main()
