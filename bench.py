"""Headline benchmark: metric update+compute latency per step (the hot loop).

Covers the BASELINE.md target configs:
- MulticlassAccuracy jitted update+compute (headline; vs reference on torch)
- MetricCollection(Accuracy, F1, AUROC) with dist_sync_on_step semantics,
  synced in-trace over an 8-device mesh (subprocess with 8 virtual CPU
  devices — the driver machine exposes one TPU chip)
- detection.MeanAveragePrecision update+compute (ragged-state cost)
- image.FrechetInceptionDistance streaming update (feature-state bandwidth)
- image.LPIPS streaming update with a conv backbone (feature distances)
- text.BERTScore under emulated 4-rank DDP: rank-strided updates, state
  merge, one batched embed+score (multi-host/DCN-scale stand-in)

Methodology (VERDICT r2 weak #4): every config is measured as
**interleaved min-of-k** — ours and the torch-CPU reference alternate inside
one process, and the minimum over rounds is reported — so the tunneled chip's
~2x run-to-run variance and ambient host load cannot fake a regression or a
win.  (Exception: collection_sync_8dev's "ours" needs its own CPU-mesh
subprocess, so there ours and the reference each take an internal min-of-3
without alternation.)  The reference side runs the mounted reference
implementation where it can run offline (shimmed deps), and an equivalent
hand-written torch step where it cannot (noted per config).  A failing
reference side never discards the "ours" measurement — each ref setup is
exception-guarded to None.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "details"};
each details entry is {"us", "ref_us", "vs_baseline"}.  ``vs_baseline`` =
reference_us / ours_us (higher is better; >1 means faster than the
reference).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

BATCH = 8192
NUM_CLASSES = 128
STEPS = 50

_REPO = os.path.dirname(os.path.abspath(__file__))
_SHIMS = os.path.join(_REPO, "tests", "reference_parity", "_shims")
_REF_SRC = "/root/reference/src"


def _ensure_reference_importable() -> bool:
    if not os.path.isdir(_REF_SRC):
        return False
    for p in (_SHIMS, _REF_SRC):
        if p not in sys.path:
            sys.path.insert(0, p)
    return True


def _interleaved(ours_once, ref_once, rounds: int = 3):
    """Alternate ours/reference measurements in one process; min over rounds."""
    ours_times, ref_times = [], []
    for _ in range(rounds):
        ours_times.append(ours_once())
        if ref_once is not None:
            ref_times.append(ref_once())
    ours = min(ours_times)
    ref = min(ref_times) if ref_times else None
    return ours, ref


def _entry(ours_us, ref_us, accounting=None):
    out = {"us": round(ours_us, 2)}
    if ref_us is not None:
        out["ref_us"] = round(ref_us, 2)
        out["vs_baseline"] = round(ref_us / ours_us, 3)
    if accounting:
        accounting = dict(accounting)
        extras = accounting.pop("extras", None) or {}
        out.update(_accounting(ours_us, **accounting))
        out.update({k: (round(v, 2) if isinstance(v, float) else v)
                    for k, v in extras.items() if v is not None})
    return out


# -------------------------------------------------- MFU / bandwidth accounting
#
# Per VERDICT r4 weak #1: wall-clock ratios alone can't say whether a config
# is compute-bound (good — the chip is the limit) or host/protocol-bound
# (fixable).  Each config therefore reports the work it moves per step:
#   flops_per_step   — from XLA's compiled cost_analysis where the hot loop is
#                      one jitted program, else an analytic count (noted)
#   achieved_gflops  — flops_per_step / measured step time
#   mfu              — achieved / chip peak (bf16 MXU peak: the conservative
#                      denominator — f32 work can never reach 1.0 against it);
#                      omitted when the platform peak is unknown (CPU mesh)
#   wire_bytes_per_step / achieved_gbps — collective payload per step for the
#                      sync config (2*(N-1)/N * state bytes per all_reduce)

_PEAK_FLOPS_TABLE = (
    ("v5 lite", 197e12, "tpu-v5e bf16"),
    ("v5e", 197e12, "tpu-v5e bf16"),
    ("v5p", 459e12, "tpu-v5p bf16"),
    ("v4", 275e12, "tpu-v4 bf16"),
    ("v6", 918e12, "tpu-v6e bf16"),
    ("trillium", 918e12, "tpu-v6e bf16"),
)


def _peak_flops():
    """(peak_flops_per_s, label) of device 0, or (None, None) when unknown."""
    try:
        import jax

        d = jax.devices()[0]
        kind = (getattr(d, "device_kind", "") or "").lower()
        for key, peak, label in _PEAK_FLOPS_TABLE:
            if key in kind:
                return peak, label
        if d.platform == "tpu":
            return 197e12, "tpu (assumed v5e) bf16"
    except Exception:
        pass
    return None, None


def _compiled_flops(jitted, *args):
    """FLOPs of one call of a jitted function via XLA cost analysis."""
    try:
        ca = jitted.lower(*args).compile().cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        flops = float(ca.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception:
        return None


def _accounting(ours_us, flops_per_step=None, flops_source=None, wire_bytes_per_step=None,
                on_accelerator=True):
    out = {}
    if flops_per_step:
        out["flops_per_step"] = round(flops_per_step)
        out["flops_source"] = flops_source or "cost_analysis"
        achieved = flops_per_step / (ours_us * 1e-6)
        out["achieved_gflops"] = round(achieved / 1e9, 2)
        peak, label = _peak_flops() if on_accelerator else (None, None)
        if peak:
            out["mfu"] = round(achieved / peak, 5)
            out["mfu_peak"] = label
    if wire_bytes_per_step:
        out["wire_bytes_per_step"] = round(wire_bytes_per_step)
        out["achieved_gbps"] = round(wire_bytes_per_step / (ours_us * 1e-6) / 1e9, 3)
    return out


# ------------------------------------------------------------------ headline


def _make_ours_accuracy():
    import jax
    import jax.numpy as jnp

    from tpumetrics.classification import MulticlassAccuracy

    metric = MulticlassAccuracy(num_classes=NUM_CLASSES, average="micro", validate_args=False)

    def step(state, preds, target):
        new_state = metric.functional_update(state, preds, target)
        return new_state, metric.functional_compute(new_state)

    step = jax.jit(step)

    rng = np.random.default_rng(0)
    preds = jnp.asarray(rng.standard_normal((BATCH, NUM_CLASSES), dtype=np.float32))
    target = jnp.asarray(rng.integers(0, NUM_CLASSES, size=(BATCH,)), dtype=jnp.int32)
    state0 = metric.init_state()
    _, val = step(state0, preds, target)  # compile
    jax.block_until_ready(val)
    flops = _compiled_flops(step, state0, preds, target)

    def run_once():
        state = state0
        t0 = time.perf_counter()
        for _ in range(STEPS):
            state, val = step(state, preds, target)
        jax.block_until_ready(val)
        return (time.perf_counter() - t0) / STEPS * 1e6

    return run_once, flops


def _make_ref_accuracy():
    """The reference MulticlassAccuracy on torch CPU (same batch/classes)."""
    import torch

    rng = np.random.default_rng(0)
    preds = torch.from_numpy(rng.standard_normal((BATCH, NUM_CLASSES), dtype=np.float32))
    target = torch.from_numpy(rng.integers(0, NUM_CLASSES, size=(BATCH,)).astype(np.int64))

    if _ensure_reference_importable():
        try:
            from torchmetrics.classification import MulticlassAccuracy as RefAccuracy

            metric = RefAccuracy(num_classes=NUM_CLASSES, average="micro", validate_args=False)
            metric.update(preds, target)  # warmup
            metric.compute()

            def run_once():
                metric.reset()
                t0 = time.perf_counter()
                for _ in range(STEPS):
                    metric.update(preds, target)
                    metric._computed = None
                    metric.compute()
                return (time.perf_counter() - t0) / STEPS * 1e6

            return run_once
        except Exception:
            pass

    # equivalent torch step: argmax -> bincount confusion counts -> micro acc
    def step(tp, total):
        labels = preds.argmax(dim=1)
        counts = torch.bincount(target * NUM_CLASSES + labels, minlength=NUM_CLASSES * NUM_CLASSES)
        confmat = counts.reshape(NUM_CLASSES, NUM_CLASSES)
        tp = tp + confmat.diagonal().sum()
        total = total + target.numel()
        return tp, total, tp.float() / total.float()

    step(torch.zeros((), dtype=torch.long), torch.zeros((), dtype=torch.long))  # warmup

    def run_once():
        tp = torch.zeros((), dtype=torch.long)
        total = torch.zeros((), dtype=torch.long)
        t0 = time.perf_counter()
        for _ in range(STEPS):
            tp, total, val = step(tp, total)
        return (time.perf_counter() - t0) / STEPS * 1e6

    return run_once


# ------------------------------------------------- collection w/ 8-dev sync

_COLLECTION_SYNC_SCRIPT = r"""
import os, sys, time, json
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
sys.path.insert(0, {repo_dir!r})
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from tpumetrics import MetricCollection
from tpumetrics.classification import MulticlassAccuracy, MulticlassF1Score, MulticlassAUROC

C, B, STEPS, ROUNDS = 16, 1024, 20, 3
col = MetricCollection({
    "acc": MulticlassAccuracy(num_classes=C, average="micro", validate_args=False),
    "f1": MulticlassF1Score(num_classes=C, average="macro", validate_args=False),
    "auroc": MulticlassAUROC(num_classes=C, validate_args=False, thresholds=64),
})
mesh = Mesh(np.asarray(jax.devices()[:8]), ("dp",))

def sharded_step(state, preds, target):
    new_state, vals = col.functional_forward(state, preds, target, axis_name="dp")
    return new_state, vals

try:  # jax >= 0.5 exposes shard_map at top level
    _shard_map = lambda f, **kw: jax.shard_map(f, check_vma=False, **kw)
    jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map as _sm
    _shard_map = lambda f, **kw: _sm(f, check_rep=False, **kw)

rng = np.random.default_rng(0)
preds = jnp.asarray(jax.nn.softmax(jnp.asarray(rng.standard_normal((B, C), dtype=np.float32))))
target = jnp.asarray(rng.integers(0, C, size=(B,)), dtype=jnp.int32)
col.establish_compute_groups(preds[:8], target[:8])
step = jax.jit(
    _shard_map(
        sharded_step, mesh=mesh,
        in_specs=(P(), P("dp"), P("dp")), out_specs=(P(), P()),
    ),
)
state0 = col.init_state()
# the collective LEDGER sources wire_bytes_per_step: records are made at
# trace time (static metadata), so capturing the first call — the trace —
# accounts one steady-state step of the compiled program
from tpumetrics import telemetry
with telemetry.capture() as led:
    state, vals = step(state0, preds, target)
jax.block_until_ready(vals)
ledger_summary = led.summary()

# accounting: per-device FLOPs of one step (XLA cost analysis) and the
# collective payload the per-step batch-value sync moves per device —
# ring all_reduce moves ~2*(N-1)/N * payload bytes per device (kept as an
# analytic cross-check against the ledger)
flops = None
try:
    ca = step.lower(state0, preds, target).compile().cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    flops = float(ca.get("flops", 0.0)) or None
except Exception:
    pass
N = 8
payload = sum(
    int(np.prod(jnp.shape(leaf))) * jnp.asarray(leaf).dtype.itemsize
    for st in state0.values()
    for leaf in jax.tree.leaves(st)
)
wire_bytes_analytic = 2 * (N - 1) / N * payload

times = []
for _ in range(ROUNDS):
    state = state0
    t0 = time.perf_counter()
    for _ in range(STEPS):
        state, vals = step(state, preds, target)
    jax.block_until_ready(vals)
    times.append((time.perf_counter() - t0) / STEPS * 1e6)
print(json.dumps({
    "us_per_step": min(times),
    "flops_per_step": flops,
    "wire_bytes_per_step": ledger_summary["wire_bytes_total"],
    "wire_bytes_analytic": wire_bytes_analytic,
    "ledger_collectives": ledger_summary["collectives_issued"],
    "ledger_flushes": ledger_summary["flush_count"],
}))
"""


def _bench_collection_sync_8dev():
    """Ours: per-step MetricCollection forward with in-trace 8-device sync
    (subprocess owns a CPU mesh).  Reference: the same collection's eager
    ``forward`` on torch CPU over the same global batch — its per-step cost
    WITHOUT any cross-process sync (gloo can't run here), i.e. a lower bound
    for the reference."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    script = _COLLECTION_SYNC_SCRIPT.replace("{repo_dir!r}", repr(_REPO))
    out = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True, timeout=600, env=env
    )
    if out.returncode != 0:
        raise RuntimeError(out.stderr[-2000:])
    sub = json.loads(out.stdout.strip().splitlines()[-1])
    ours = float(sub["us_per_step"])
    accounting = {
        # CPU-mesh subprocess: no chip peak — report flops + wire bytes/s
        # only.  wire_bytes_per_step is LEDGER-sourced (telemetry.capture of
        # the traced step); the analytic ring-model value rides along as a
        # cross-check — the two must agree to the integer.
        "flops_per_step": sub.get("flops_per_step"),
        "wire_bytes_per_step": sub.get("wire_bytes_per_step"),
        "on_accelerator": False,
    }
    accounting["extras"] = {
        "wire_bytes_analytic": sub.get("wire_bytes_analytic"),
        "ledger_collectives": sub.get("ledger_collectives"),
        "ledger_flushes": sub.get("ledger_flushes"),
    }

    ref = None
    try:
        if not _ensure_reference_importable():
            raise ImportError("reference tree unavailable")
        import torch
        from torchmetrics import MetricCollection as RefCollection
        from torchmetrics.classification import (
            MulticlassAccuracy as RefAcc,
            MulticlassAUROC as RefAUROC,
            MulticlassF1Score as RefF1,
        )

        C, B, steps = 16, 1024, 20
        col = RefCollection(
            {
                "acc": RefAcc(num_classes=C, average="micro", validate_args=False),
                "f1": RefF1(num_classes=C, average="macro", validate_args=False),
                "auroc": RefAUROC(num_classes=C, validate_args=False, thresholds=64),
            }
        )
        rng = np.random.default_rng(0)
        preds = torch.softmax(torch.from_numpy(rng.standard_normal((B, C), dtype=np.float32)), dim=1)
        target = torch.from_numpy(rng.integers(0, C, size=(B,)).astype(np.int64))
        col.forward(preds, target)  # warmup + group discovery
        times = []
        for _ in range(3):
            col.reset()
            t0 = time.perf_counter()
            for _ in range(steps):
                col.forward(preds, target)
            times.append((time.perf_counter() - t0) / steps * 1e6)
        ref = min(times)
    except Exception:
        ref = None
    return ours, ref, accounting


# ------------------------------------------- sharded one-program collection

_SHARDED_COLLECTION_SCRIPT = r"""
import os, sys, time, json
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
sys.path.insert(0, {repo_dir!r})
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from tpumetrics import MetricCollection, telemetry
from tpumetrics.classification import MulticlassAccuracy, MulticlassAUROC, MulticlassF1Score
from tpumetrics.parallel import FusedCollectionStep, make_mesh

C, B, N, STEPS, ROUNDS = 16, 1024, 8, 20, 3

def make_col():
    return MetricCollection({
        "acc": MulticlassAccuracy(num_classes=C, average="micro", validate_args=False),
        "f1": MulticlassF1Score(num_classes=C, average="macro", validate_args=False),
        "auroc": MulticlassAUROC(num_classes=C, validate_args=False, thresholds=64),
    })

rng = np.random.default_rng(0)
preds = jnp.asarray(jax.nn.softmax(jnp.asarray(rng.standard_normal((B, C), dtype=np.float32))))
target = jnp.asarray(rng.integers(0, C, size=(B,)), dtype=jnp.int32)

# ---- sharded mode: ONE global SPMD program per collection step
col = make_col()
col.establish_compute_groups(preds[:8], target[:8])
mesh = make_mesh(N, "dp")
step = FusedCollectionStep(col, mesh=mesh)
state = step.init_state()
with telemetry.capture() as led_trace:
    state = step.update(state, preds, target)  # trace + compile
spmd_collectives = led_trace.summary()["spmd_collectives"]

sharded_times = []
with telemetry.capture() as led_steady:
    # the acceptance invariant: NOTHING touches the host between update()
    # and compute() — the whole timed loop runs under a device->host
    # transfer guard (a violation raises and fails the scenario loudly),
    # and the eager-collective count over the loop must stay 0
    with jax.transfer_guard_device_to_host("disallow"):
        for _ in range(ROUNDS):
            t0 = time.perf_counter()
            for _ in range(STEPS):
                state = step.update(state, preds, target)
            jax.block_until_ready(jax.tree_util.tree_leaves(state))
            sharded_times.append((time.perf_counter() - t0) / STEPS * 1e6)
eager_collectives = led_steady.summary()["collectives_issued"]
sharded_result = col.functional_compute(state)
sharded_state = state

# ---- baseline: the eager per-rank loop (the pre-sharding production path):
# N per-rank states advanced by N Python-dispatched donated programs per
# step over the per-rank shards, stitched back by an eager fold at compute
col2 = make_col()
col2.establish_compute_groups(preds[:8], target[:8])
step2 = FusedCollectionStep(col2)
shards_p = preds.reshape(N, B // N, C)
shards_t = target.reshape(N, B // N)
states = [step2.init_state() for _ in range(N)]
for r in range(N):
    states[r] = step2.update(states[r], shards_p[r], shards_t[r])  # compile
per_rank_times = []
for _ in range(ROUNDS):
    t0 = time.perf_counter()
    for _ in range(STEPS):
        for r in range(N):
            states[r] = step2.update(states[r], shards_p[r], shards_t[r])
    jax.block_until_ready(jax.tree_util.tree_leaves(states))
    per_rank_times.append((time.perf_counter() - t0) / STEPS * 1e6)
folded = col2.fold_state_dicts(states)
per_rank_result = col2.functional_compute(folded)

# ---- parity gates (in-scenario: a fast but wrong mode must fail loudly).
# Integer states bit-exact — int sums are associativity-free, so the mesh
# must not perturb them; float results allclose.
for leader, st in sharded_state.items():
    for attr, leaf in st.items():
        a, b = np.asarray(leaf), np.asarray(folded[leader][attr])
        if np.issubdtype(a.dtype, np.integer):
            assert np.array_equal(a, b), f"int state diverged: {leader}/{attr}"
        else:
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6, err_msg=f"{leader}/{attr}")
for key, val in per_rank_result.items():
    np.testing.assert_allclose(
        np.asarray(sharded_result[key]), np.asarray(val), rtol=1e-5, atol=1e-6, err_msg=key
    )
assert eager_collectives == 0, f"eager collectives inside the sharded loop: {eager_collectives}"
assert spmd_collectives > 0, "sharded trace recorded no in-trace collectives"

print(json.dumps({
    "sharded_us": min(sharded_times),
    "per_rank_us": min(per_rank_times),
    "spmd_collectives": spmd_collectives,
    "eager_collectives_during_update": eager_collectives,
}))
"""


def _bench_sharded_collection():
    """One-program sharded collection step (8-virtual-device GSPMD mesh,
    state as NamedSharding-ed arrays, in-trace psum) vs the eager per-rank
    loop it replaces (8 per-rank donated programs per step + eager fold at
    compute).  In-scenario asserts: zero device→host transfers and zero
    eager collectives across the timed sharded loop (jax.transfer_guard +
    ledger), integer states bit-exact against the per-rank fold."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    script = _SHARDED_COLLECTION_SCRIPT.replace("{repo_dir!r}", repr(_REPO))
    out = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True, timeout=600, env=env
    )
    if out.returncode != 0:
        raise RuntimeError(out.stderr[-2000:])
    sub = json.loads(out.stdout.strip().splitlines()[-1])
    ours = float(sub["sharded_us"])
    ref = float(sub["per_rank_us"])
    accounting = {
        "on_accelerator": False,
        "extras": {
            "spmd_collectives": sub.get("spmd_collectives"),
            "eager_collectives_during_update": sub.get("eager_collectives_during_update"),
        },
    }
    return ours, ref, accounting


# ------------------------------------------------------------------------ mAP


def _map_corpus():
    rng = np.random.default_rng(0)
    n_imgs = 64  # an eval-set-sized corpus; tiny corpora benchmark fixed costs

    def boxes(n):
        xy = rng.uniform(0, 80, size=(n, 2))
        wh = rng.uniform(4, 20, size=(n, 2))
        return np.concatenate([xy, xy + wh], axis=1).astype(np.float32)

    preds, target = [], []
    for _ in range(n_imgs):
        nd, ng = int(rng.integers(3, 20)), int(rng.integers(2, 10))
        preds.append(
            {
                "boxes": boxes(nd),
                "scores": rng.uniform(0.1, 1.0, nd).astype(np.float32),
                "labels": rng.integers(0, 4, nd).astype(np.int64),
            }
        )
        target.append({"boxes": boxes(ng), "labels": rng.integers(0, 4, ng).astype(np.int64)})
    return preds, target


def _bench_map():
    """MeanAveragePrecision update+compute (ragged-state path). Reference:
    the mounted reference's pure-torch ``_mean_ap`` on the same corpus (its
    pycocotools backend cannot run offline; ``_mean_ap`` is the reference's
    own all-torch implementation).

    The hot path under test is the JITTED dense-cell matcher
    (``detection/_coco_eval_jax``): ONE compiled XLA program for greedy
    matching + PR accumulation, compiled once per bucket shape.  In-scenario
    parity gate: the jitted result must be BIT-identical to the per-cell
    numpy reference path (``coco_evaluate_unfused``) on this exact corpus."""
    import jax.numpy as jnp

    from tpumetrics.detection import MeanAveragePrecision

    preds_np, target_np = _map_corpus()
    preds = [{k: jnp.asarray(v) for k, v in p.items()} for p in preds_np]
    target = [{k: jnp.asarray(v) for k, v in t.items()} for t in target_np]
    steps = 5

    m = MeanAveragePrecision()
    m.update(preds, target)  # warmup (traces IoU kernels + the matcher program)
    fused_vals = m.compute()

    # correctness gate: the jitted matcher must reproduce the per-cell
    # reference path bit-identically on this exact corpus
    from unittest import mock

    from tpumetrics.detection import _coco_eval, _coco_eval_jax, mean_ap as _mean_ap_mod
    from tpumetrics.telemetry import device as tele_device

    assert tele_device.registry().newest(_coco_eval_jax.MATCHER_PROFILE_LABEL) is not None, (
        "the jitted matcher did not engage on the bench corpus — the scenario "
        "would silently time the numpy fallback"
    )
    m._computed = None  # drop the cached result or the mocked compute is a no-op
    with mock.patch.object(_coco_eval_jax, "_ENABLED", False), mock.patch.object(
        _mean_ap_mod, "coco_evaluate", _coco_eval.coco_evaluate_unfused
    ):
        unfused_vals = m.compute()
    for key, val in fused_vals.items():
        ref_val = unfused_vals[key]
        assert np.array_equal(np.asarray(val), np.asarray(ref_val)), (
            f"jitted mAP != per-cell reference for {key}: {val} vs {ref_val}"
        )

    # device-resident state gate: the packed dense update path (flat row
    # buffers + segment ids) must land on the SAME bits as the list path
    from tpumetrics.detection import pack_detection_batch

    mp = MeanAveragePrecision()
    pd, td = pack_detection_batch(preds_np, target_np)
    mp.update({k: jnp.asarray(v) for k, v in pd.items()}, {k: jnp.asarray(v) for k, v in td.items()})
    packed_vals = mp.compute()
    for key, val in fused_vals.items():
        assert np.array_equal(np.asarray(val), np.asarray(packed_vals[key])), (
            f"packed mAP != list-state mAP for {key}"
        )

    def ours_once():
        m.reset()
        t0 = time.perf_counter()
        for _ in range(steps):
            m.update(preds, target)
            m.compute()
            m.reset()
        return (time.perf_counter() - t0) / steps * 1e6

    ref_once = None
    try:
        if not _ensure_reference_importable():
            raise ImportError("reference tree unavailable")
        import torch
        from torchmetrics.detection._mean_ap import MeanAveragePrecision as RefMAP

        tpreds = [{k: torch.from_numpy(v) for k, v in p.items()} for p in preds_np]
        ttarget = [{k: torch.from_numpy(v) for k, v in t.items()} for t in target_np]
        rm = RefMAP()
        rm.update(tpreds, ttarget)
        rm.compute()

        def ref_once():
            rm.reset()
            t0 = time.perf_counter()
            for _ in range(steps):
                rm.update(tpreds, ttarget)
                rm.compute()
                rm.reset()
            return (time.perf_counter() - t0) / steps * 1e6

    except Exception:
        ref_once = None

    ours, ref = _interleaved(ours_once, ref_once, rounds=2)
    # real compiled flops from the SHARED device-profile registry (the
    # matcher registers every program it dispatches; the registry resolves
    # XLA cost analysis lazily — one program execution per compute == per
    # step), so achieved_gflops/mfu stop reading as vacuously zero; the
    # analytic IoU count stays as fallback for a corpus the jitted path
    # declines
    prof = tele_device.registry().newest(_coco_eval_jax.MATCHER_PROFILE_LABEL)
    cost = prof.resolve() if prof is not None else None
    if cost and cost.get("flops", 0) > 0:
        return ours, ref, {"flops_per_step": float(cost["flops"]), "flops_source": "cost_analysis"}
    pair_flops = 16 * sum(len(p["scores"]) * len(t["labels"]) for p, t in zip(preds_np, target_np))
    return ours, ref, {"flops_per_step": float(pair_flops), "flops_source": "analytic-iou"}


# ------------------------------------------------------------------------ FID


def _bench_fid():
    """FID streaming update with a deterministic conv extractor on both sides
    (the reference accepts any ``nn.Module`` as ``feature``).  The extractor
    is conv-stack-shaped (the real workload is an InceptionV3 forward): a toy
    linear probe would benchmark host/tunnel latency instead of the config."""
    import jax
    import jax.numpy as jnp

    from tpumetrics.image import FrechetInceptionDistance

    dim, batch, steps = 256, 64, 10
    rng = np.random.default_rng(0)
    k1_np = (rng.standard_normal((64, 3, 3, 3)) * 0.1).astype(np.float32)
    k2_np = (rng.standard_normal((128, 64, 3, 3)) * 0.05).astype(np.float32)
    k3_np = (rng.standard_normal((256, 128, 3, 3)) * 0.05).astype(np.float32)
    proj_np = rng.standard_normal((256, dim)).astype(np.float32)
    jk = [jnp.asarray(k) for k in (k1_np, k2_np, k3_np)]
    proj = jnp.asarray(proj_np)

    def extractor(imgs):
        h = imgs.astype(jnp.float32) / 255.0
        for k in jk:
            # explicit (1,1) padding == torch conv2d(padding=1); XLA "SAME"
            # would pad (0,1) on even inputs and shift windows by one pixel
            h = jax.nn.relu(jax.lax.conv_general_dilated(h, k, (2, 2), ((1, 1), (1, 1))))
        return jnp.tanh(h.mean(axis=(2, 3)) @ proj)

    real_np = rng.integers(0, 255, size=(batch, 3, 96, 96)).astype(np.uint8)
    fake_np = rng.integers(0, 255, size=(batch, 3, 96, 96)).astype(np.uint8)

    m = FrechetInceptionDistance(feature=extractor, num_features=dim)
    real = jnp.asarray(real_np)
    fake = jnp.asarray(fake_np)
    m.update(real, real=True)  # warmup
    m.update(fake, real=False)
    jax.block_until_ready(m.fake_features_sum)
    # one measured step = a real + a fake update; the extractor forward is
    # the work (the moment accumulation is O(batch*dim))
    ex_flops = _compiled_flops(jax.jit(extractor), real)
    flops = 2 * ex_flops if ex_flops else None

    def ours_once():
        t0 = time.perf_counter()
        for _ in range(steps):
            m.update(real, real=True)
            m.update(fake, real=False)
        # the fake-side update is the LAST enqueued device work; blocking on
        # the real side would leave ~1/(2*steps) of the work untimed
        jax.block_until_ready(m.fake_features_sum)
        return (time.perf_counter() - t0) / steps * 1e6

    ref_once = None
    try:
        if not _ensure_reference_importable():
            raise ImportError("reference tree unavailable")
        import torch
        import torch.nn.functional as TF
        from torchmetrics.image.fid import FrechetInceptionDistance as RefFID

        class TorchExtractor(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.k = torch.nn.ParameterList(
                    torch.nn.Parameter(torch.from_numpy(k), requires_grad=False)
                    for k in (k1_np, k2_np, k3_np)
                )
                self.proj = torch.nn.Parameter(torch.from_numpy(proj_np), requires_grad=False)

            def forward(self, imgs):
                h = imgs.float() / 255.0
                for k in self.k:
                    h = TF.relu(TF.conv2d(h, k, stride=2, padding=1))
                return torch.tanh(h.mean(dim=(2, 3)) @ self.proj)

        rm = RefFID(feature=TorchExtractor())
        treal = torch.from_numpy(real_np)
        tfake = torch.from_numpy(fake_np)
        rm.update(treal, real=True)
        rm.update(tfake, real=False)

        def ref_once():
            t0 = time.perf_counter()
            for _ in range(steps):
                rm.update(treal, real=True)
                rm.update(tfake, real=False)
            return (time.perf_counter() - t0) / steps * 1e6

    except Exception:
        ref_once = None

    ours, ref = _interleaved(ours_once, ref_once, rounds=3)
    return ours, ref, {"flops_per_step": flops}


# ---------------------------------------------------------------------- LPIPS


def _bench_lpips():
    """LPIPS streaming update with the same deterministic conv backbone on
    both sides (pretrained torchvision backbones can't load offline, so the
    reference side is the equivalent hand-written torch LPIPS step: same
    convs, same unit-normalize/diff/spatial-average formula)."""
    import jax
    import jax.numpy as jnp

    from tpumetrics.image import LearnedPerceptualImagePatchSimilarity

    rng = np.random.default_rng(0)
    k1_np = (rng.standard_normal((16, 3, 3, 3)) * 0.1).astype(np.float32)
    k2_np = (rng.standard_normal((32, 16, 3, 3)) * 0.1).astype(np.float32)
    k1 = jnp.asarray(k1_np)
    k2 = jnp.asarray(k2_np)

    def backbone(x):
        h1 = jax.nn.relu(jax.lax.conv_general_dilated(x, k1, (2, 2), "SAME"))
        h2 = jax.nn.relu(jax.lax.conv_general_dilated(h1, k2, (2, 2), "SAME"))
        return [h1, h2]

    m = LearnedPerceptualImagePatchSimilarity(net_type=backbone)
    batch, steps = 64, 20
    img1_np = rng.uniform(-1, 1, (batch, 3, 64, 64)).astype(np.float32)
    img2_np = rng.uniform(-1, 1, (batch, 3, 64, 64)).astype(np.float32)
    img1 = jnp.asarray(img1_np)
    img2 = jnp.asarray(img2_np)
    m.update(img1, img2)  # warmup
    jax.block_until_ready(m.sum_scores)
    # one measured step = one update: functional_update is the same jitted
    # work (two backbone forwards + distance) the eager loop runs
    try:
        flops = _compiled_flops(
            jax.jit(lambda s, a, b: m.functional_update(s, a, b)), m.init_state(), img1, img2
        )
    except Exception:
        flops = None

    def ours_once():
        t0 = time.perf_counter()
        for _ in range(steps):
            m.update(img1, img2)
        jax.block_until_ready(m.sum_scores)
        return (time.perf_counter() - t0) / steps * 1e6

    import torch
    import torch.nn.functional as F

    tk1 = torch.from_numpy(k1_np)
    tk2 = torch.from_numpy(k2_np)
    ti1 = torch.from_numpy(img1_np)
    ti2 = torch.from_numpy(img2_np)
    shift = torch.tensor([-0.030, -0.088, -0.188]).view(1, 3, 1, 1)
    scale = torch.tensor([0.458, 0.448, 0.450]).view(1, 3, 1, 1)

    def t_backbone(x):
        h1 = F.relu(F.conv2d(x, tk1, stride=2, padding=1))
        h2 = F.relu(F.conv2d(h1, tk2, stride=2, padding=1))
        return [h1, h2]

    def t_lpips_sum(a, b):
        fa = t_backbone((a - shift) / scale)
        fb = t_backbone((b - shift) / scale)
        total = 0.0
        for x, y in zip(fa, fb):
            xn = x / torch.sqrt(1e-8 + (x**2).sum(dim=1, keepdim=True))
            yn = y / torch.sqrt(1e-8 + (y**2).sum(dim=1, keepdim=True))
            total = total + ((xn - yn) ** 2).mean(dim=1, keepdim=True).mean(dim=(2, 3)).sum()
        return total

    with torch.no_grad():
        t_lpips_sum(ti1, ti2)  # warmup

    def ref_once():
        acc = 0.0
        t0 = time.perf_counter()
        with torch.no_grad():
            for _ in range(steps):
                acc = acc + t_lpips_sum(ti1, ti2)
        return (time.perf_counter() - t0) / steps * 1e6

    ours, ref = _interleaved(ours_once, ref_once, rounds=3)
    return ours, ref, {"flops_per_step": flops}


# ----------------------------------------------------------- backbone runtime


def _bench_backbone_runtime():
    """N fresh LPIPS-alex tenants spinning up against the SHARED backbone
    runtime vs the same N tenants on private per-instance weight plumbing
    (the pre-registry behavior: each instance placed its own copy of the
    weight tree and jit-compiled its own identical forward).

    One measured round = spin up ``tenants`` instances + run ``steps`` eval
    batches each + release.  The shared side digest-dedupes every
    acquisition to ONE resident handle whose engine holds the only compiled
    program (per-tenant cost: a content hash + a dict hit); the private side
    pays a fresh weight placement AND a fresh XLA compile per tenant per
    round — exactly what a service sees when same-backbone tenants churn.
    The per-batch unit is (tenants * steps) forwards either way.

    In-scenario gates: the shared engine compiled exactly ONCE across every
    tenant (trace universe = one bucket signature) and the shared forward is
    BIT-identical to the private one (meshless placement is fp32-exact).
    MFU/flops come from the shared forward's ``backbones/<key>`` program
    profile (XLA cost_analysis), like the detection matcher's."""
    import jax
    import jax.numpy as jnp

    from tpumetrics.backbones.registry import get_backbone, registry_stats
    from tpumetrics.image._backbones import alexnet_features
    from tpumetrics.telemetry import device as tele_device

    rng = np.random.default_rng(0)
    shapes = [(64, 3, 11, 11), (192, 64, 5, 5), (384, 192, 3, 3), (256, 384, 3, 3), (256, 256, 3, 3)]
    params_np = [
        ((rng.standard_normal(s) * 0.05).astype(np.float32), np.zeros(s[0], np.float32))
        for s in shapes
    ]
    tenants, steps, batch = 3, 4, 8
    img_np = rng.uniform(-1, 1, (batch, 3, 64, 64)).astype(np.float32)
    img = jnp.asarray(img_np)

    # the long-lived service case: the resident handle outlives tenant churn
    # (it registers its program profile on the first eager dispatch)
    seed = get_backbone("lpips:alex", params_np)
    shared_out = seed(img)
    jax.block_until_ready(shared_out[-1])

    def ours_once():
        t0 = time.perf_counter()
        handles = [get_backbone("lpips:alex", params_np) for _ in range(tenants)]
        out = None
        for h in handles:
            for _ in range(steps):
                out = h(img)
        jax.block_until_ready(out[-1])
        for h in handles:
            h.close()
        return (time.perf_counter() - t0) / (tenants * steps) * 1e6

    def ref_once():
        t0 = time.perf_counter()
        out = None
        for _ in range(tenants):
            own = [(jnp.asarray(w), jnp.asarray(b)) for w, b in params_np]
            fwd = jax.jit(lambda p, x: alexnet_features(p)(x))  # noqa: B023
            for _ in range(steps):
                out = fwd(own, img)
        jax.block_until_ready(out[-1])
        return (time.perf_counter() - t0) / (tenants * steps) * 1e6

    ours, ref = _interleaved(ours_once, ref_once, rounds=3)

    # gates: one compile total across every tenant of every round, refcount
    # back to the resident seed only, and fp32 bit-parity with the private path
    stats = registry_stats()[seed.key]
    assert stats["compiles"] == 1, f"shared engine compiled {stats['compiles']}x, expected 1"
    assert stats["refs"] == 1, f"tenant churn leaked refs: {stats['refs']}"
    private_out = alexnet_features([(jnp.asarray(w), jnp.asarray(b)) for w, b in params_np])(img)
    for a, b in zip(shared_out, private_out):
        assert np.array_equal(np.asarray(a), np.asarray(b)), "shared forward != private forward"

    prof = tele_device.registry().newest(seed.label)
    cost = prof.resolve() if prof is not None else None
    extras = {
        "shared_compiles": stats["compiles"],
        "resident_bytes": stats["bytes"],
    }
    seed.close()
    if cost and cost.get("flops", 0) > 0:
        return ours, ref, {
            "flops_per_step": float(cost["flops"]),
            "flops_source": "cost_analysis",
            "extras": extras,
        }
    return ours, ref, {"extras": extras}


# ------------------------------------------------------------------ BERTScore


def _bertscore_fixture():
    """A transformer-scale embedder (token embedding + 4 dense layers,
    d=512): the BASELINE config is 'BERTScore under DDP', whose cost in the
    reference is the model forward — a toy lookup embedder would benchmark
    host/tunnel latency instead of the workload."""
    rng = np.random.default_rng(0)
    vocab = [f"tok{i}" for i in range(64)]

    def sentences(n):
        return [" ".join(rng.choice(vocab, size=rng.integers(6, 20))) for _ in range(n)]

    word_ids = {w: i + 1 for i, w in enumerate(vocab)}
    d = 512
    weights = {
        "emb": (rng.standard_normal((len(vocab) + 2, d)) * 0.1).astype(np.float32),
        "layers": [(rng.standard_normal((d, d)) * (1.0 / np.sqrt(d))).astype(np.float32) for _ in range(4)],
    }
    world, steps, per_rank = 4, 8, 64
    preds = [sentences(per_rank) for _ in range(world * steps)]
    target = [sentences(per_rank) for _ in range(world * steps)]
    return word_ids, weights, world, steps, per_rank, preds, target


def _bench_bertscore_ddp():
    """BERTScore under emulated DDP on both sides: 4 rank-strided replicas
    with the SAME deterministic embedder (the reference supports
    user_tokenizer/user_forward_fn), merged, one batched embed+score."""
    import jax.numpy as jnp

    from tpumetrics.text import BERTScore

    word_ids, weights, world, steps, per_rank, preds, target = _bertscore_fixture()
    emb = jnp.asarray(weights["emb"])
    layers = [jnp.asarray(w) for w in weights["layers"]]

    def tokenizer(batch, max_length=24):
        ids = np.zeros((len(batch), max_length), np.int32)
        mask = np.zeros((len(batch), max_length), np.int32)
        for i, s in enumerate(batch):
            toks = [word_ids[w] for w in s.split()][:max_length]
            ids[i, : len(toks)] = toks
            mask[i, : len(toks)] = 1
        return {"input_ids": ids, "attention_mask": mask}

    def forward_fn(model, batch):
        h = emb[jnp.asarray(batch["input_ids"])]
        for w in layers:
            h = jnp.tanh(h @ w)
        return h

    def make():
        return BERTScore(model=object(), user_tokenizer=tokenizer, user_forward_fn=forward_fn)

    make().update(preds[0], target[0])  # warm tokenizer path

    def ours_once():
        t0 = time.perf_counter()
        replicas = [make() for _ in range(world)]
        for rank, m in enumerate(replicas):
            for i in range(rank, world * steps, world):
                m.update(preds[i], target[i])
        # sentence states are host-side Python lists (device sync is refused
        # for them, tpumetrics/text/_sentence_state.py) — the multi-host
        # analogue is an all_gather_object of the sentences, emulated by
        # concatenation, followed by ONE batched embed+score over the union
        combined = make()
        for m in replicas:
            combined.update(*m.sentence_state)
        out = combined.compute()
        f1 = np.asarray(out["f1"])
        assert f1.shape[0] == world * steps * per_rank, f1.shape
        return (time.perf_counter() - t0) * 1e6

    ref_once = None
    if _ensure_reference_importable():
        import torch
        from torchmetrics.text.bert import BERTScore as RefBERTScore

        temb = torch.from_numpy(weights["emb"])
        tlayers = [torch.from_numpy(w) for w in weights["layers"]]

        def t_tokenizer(batch, max_length=24):
            ids = np.zeros((len(batch), max_length), np.int64)
            mask = np.zeros((len(batch), max_length), np.int64)
            for i, s in enumerate(batch):
                toks = [word_ids[w] for w in s.split()][:max_length]
                ids[i, : len(toks)] = toks
                mask[i, : len(toks)] = 1
            return {"input_ids": torch.from_numpy(ids), "attention_mask": torch.from_numpy(mask)}

        def t_forward_fn(model, batch):
            with torch.no_grad():
                h = temb[batch["input_ids"]]
                for w in tlayers:
                    h = torch.tanh(h @ w)
            return h

        def ref_make():
            return RefBERTScore(
                model=torch.nn.Identity(), user_tokenizer=t_tokenizer, user_forward_fn=t_forward_fn
            )

        try:
            ref_make().update(preds[0], target[0])

            def ref_once():
                t0 = time.perf_counter()
                replicas = [ref_make() for _ in range(world)]
                rank_texts = [([], []) for _ in range(world)]
                for rank, m in enumerate(replicas):
                    for i in range(rank, world * steps, world):
                        m.update(preds[i], target[i])
                        rank_texts[rank][0].extend(preds[i])
                        rank_texts[rank][1].extend(target[i])
                # the reference stores tokenized tensors; the multi-host merge
                # analogue is an object-gather of the raw sentences, emulated
                # by re-feeding each rank's text into one combined metric
                combined = ref_make()
                for ptexts, ttexts in rank_texts:
                    combined.update(ptexts, ttexts)
                out = combined.compute()
                f1 = out["f1"]
                n = len(f1) if not hasattr(f1, "numel") else f1.numel()
                assert n == world * steps * per_rank
                return (time.perf_counter() - t0) * 1e6

        except Exception:
            ref_once = None

    ours, ref = _interleaved(ours_once, ref_once, rounds=2)
    # analytic (the measured unit is one full ddp eval, not a step): both
    # corpora embed through 4 d*d dense layers over seq tokens, then the
    # greedy-matching einsum scores each pair (2*seq^2*d)
    n, seq, d, n_layers = world * steps * per_rank, 24, 512, 4
    embed_flops = 2 * n * seq * n_layers * 2 * d * d  # both sides
    score_flops = n * 2 * seq * seq * d
    return ours, ref, {
        "flops_per_step": float(embed_flops + score_flops),
        "flops_source": "analytic-embed+score",
    }


# ------------------------------------------------- fused collection update


def _bench_fused_collection_update():
    """Whole-collection fused step (ONE donated-state XLA program per step,
    tpumetrics.parallel.fuse_update) vs the sequential per-metric path (one
    jitted program per leader, dispatched in a Python loop) over an
    identical 12-metric collection and stream.

    ``vs_baseline`` = sequential_us / fused_us.  The batch is deliberately
    serving-shaped (256 rows): per-metric device work is small, so the
    sequential path's cost is dominated by 12 dispatch round trips the
    fused path collapses into one.  Correctness is asserted in-scenario:
    both paths' final states must compute identical values."""
    import jax
    import jax.numpy as jnp

    from tpumetrics import MetricCollection
    from tpumetrics.classification import (
        MulticlassAccuracy,
        MulticlassAUROC,
        MulticlassCalibrationError,
        MulticlassCohenKappa,
        MulticlassF1Score,
        MulticlassMatthewsCorrCoef,
        MulticlassPrecision,
        MulticlassRecall,
        MulticlassSpecificity,
        MulticlassStatScores,
    )
    from tpumetrics.parallel import FusedCollectionStep

    C, B, steps = 32, 256, 50
    mk = dict(num_classes=C, validate_args=False)
    col = MetricCollection(
        {
            "acc_micro": MulticlassAccuracy(average="micro", **mk),
            "acc_macro": MulticlassAccuracy(average="macro", **mk),
            "acc_weighted": MulticlassAccuracy(average="weighted", **mk),
            "prec": MulticlassPrecision(average="macro", **mk),
            "rec": MulticlassRecall(average="macro", **mk),
            "f1": MulticlassF1Score(average="macro", **mk),
            "spec": MulticlassSpecificity(average="macro", **mk),
            "stat": MulticlassStatScores(average="macro", **mk),
            "auroc": MulticlassAUROC(thresholds=32, **mk),
            "kappa": MulticlassCohenKappa(**mk),
            "mcc": MulticlassMatthewsCorrCoef(**mk),
            "cal": MulticlassCalibrationError(n_bins=15, **mk),
        },
        compute_groups=False,  # 12 leaders: the one-program-vs-12 comparison
    )
    rng = np.random.default_rng(0)
    preds = jnp.asarray(jax.nn.softmax(jnp.asarray(rng.standard_normal((B, C), dtype=np.float32))))
    target = jnp.asarray(rng.integers(0, C, (B,)), jnp.int32)

    fused = FusedCollectionStep(col, donate=True)
    state = fused.update(fused.init_state(), preds, target)  # compile
    jax.block_until_ready(jax.tree.leaves(state))
    flops = None
    try:
        program = next(iter(fused._programs.values()))
        flops = _compiled_flops(program, fused.init_state(), (preds, target))
    except Exception:
        pass

    leaders = [cg[0] for cg in col._groups.values()]
    seq_steps = {
        n: jax.jit(lambda s, p, t, m=col._modules[n]: m.functional_update(s, p, t))
        for n in leaders
    }
    seq = {n: seq_steps[n](col._modules[n].init_state(), preds, target) for n in leaders}
    jax.block_until_ready(jax.tree.leaves(seq))

    final_states = {}

    def fused_once():
        s = fused.update(fused.init_state(), preds, target)
        t0 = time.perf_counter()
        for _ in range(steps):
            s = fused.update(s, preds, target)
        jax.block_until_ready(jax.tree.leaves(s))
        final_states["fused"] = s
        return (time.perf_counter() - t0) / steps * 1e6

    def seq_once():
        ss = {n: seq_steps[n](col._modules[n].init_state(), preds, target) for n in leaders}
        t0 = time.perf_counter()
        for _ in range(steps):
            for n in leaders:
                ss[n] = seq_steps[n](ss[n], preds, target)
        jax.block_until_ready(jax.tree.leaves(ss))
        final_states["seq"] = ss
        return (time.perf_counter() - t0) / steps * 1e6

    # one discarded warm round: the first timed donated-loop pass runs cold
    # (allocator growth, CPU caches) and on a noisy 2-CPU box can read 5x
    # slow, which min-of-rounds alone does not always absorb
    fused_once()
    seq_once()
    ours, ref = _interleaved(fused_once, seq_once, rounds=5)

    # correctness gate: identical final values from both paths (same number
    # of applied steps), computed per leader
    fused_vals = col.functional_compute(final_states["fused"])
    seq_vals = col.functional_compute(final_states["seq"])
    for key, val in fused_vals.items():
        ok = np.allclose(np.asarray(val), np.asarray(seq_vals[key]), rtol=0, atol=0)
        assert ok, f"fused != sequential for {key}: {val} vs {seq_vals[key]}"

    extras = {
        "metrics_in_collection": len(col),
        "fused_programs": fused.program_count,
        "sequential_programs": len(leaders),
        "donated": True,
    }
    return ours, ref, {"flops_per_step": flops, "extras": extras}


# ----------------------------------------------- persistent compile cache

_COMPILE_CACHE_SCRIPT = r"""
import json, os, sys, time
sys.path.insert(0, {repo_dir!r})
mode, cache_dir, snap_dir = sys.argv[1], sys.argv[2], sys.argv[3]
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
from tpumetrics import MetricCollection
from tpumetrics.classification import (
    MulticlassAccuracy, MulticlassAUROC, MulticlassF1Score, MulticlassPrecision,
    MulticlassRecall, MulticlassSpecificity, MulticlassStatScores,
)
from tpumetrics.runtime import StreamingEvaluator, count_cache_hits

C = 16
mk = dict(num_classes=C, validate_args=False)
col = MetricCollection({
    "acc_micro": MulticlassAccuracy(average="micro", **mk),
    "acc_macro": MulticlassAccuracy(average="macro", **mk),
    "acc_weighted": MulticlassAccuracy(average="weighted", **mk),
    "prec": MulticlassPrecision(average="macro", **mk),
    "rec": MulticlassRecall(average="macro", **mk),
    "f1": MulticlassF1Score(average="macro", **mk),
    "spec": MulticlassSpecificity(average="macro", **mk),
    "stat": MulticlassStatScores(average="macro", **mk),
    "auroc": MulticlassAUROC(thresholds=32, **mk),
    "f1_micro": MulticlassF1Score(average="micro", **mk),
}, compute_groups=False)

# deterministic ragged stream; the second half touches the SAME bucket set
# as the first so both processes compile/load an identical program universe
sizes = [5, 12, 20, 3, 28, 17, 9, 26]
rng = np.random.default_rng(0)
stream = []
for n in sizes * 2:
    stream.append((
        jnp.asarray(jax.nn.softmax(jnp.asarray(rng.standard_normal((n, C), dtype=np.float32)))),
        jnp.asarray(rng.integers(0, C, n).astype(np.int32)),
    ))
half = len(sizes)

ev = StreamingEvaluator(
    col, buckets=32, compile_cache_dir=cache_dir,
    snapshot_dir=snap_dir, snapshot_rank=0, snapshot_world_size=1,
)
restore = None
with count_cache_hits() as hits:
    if mode == "warm":
        restore = ev.restore_elastic()  # the post-restart adoption path
        pos = restore["batches"]
    else:
        pos = 0
    t0 = time.perf_counter()
    with ev:
        if mode == "cold":
            for p, t in stream[:half]:
                ev.submit(p, t)
            ev.flush()
            elapsed = time.perf_counter() - t0
            ev.snapshot()
            for p, t in stream[half:]:
                ev.submit(p, t)
        else:
            for p, t in stream[pos:]:
                ev.submit(p, t)
            ev.flush()
            elapsed = time.perf_counter() - t0
        vals = {k: np.asarray(v).tolist() for k, v in ev.compute().items()}
print(json.dumps({
    "elapsed_s": elapsed,
    "compile_s": max(hits["backend_compile_secs"] - hits["cache_retrieval_secs"], 0.0),
    "vals": vals,
    "cache_hits": hits["hits"],
    "cache_misses": hits["misses"],
    "restored_from": None if restore is None else restore["batches"],
}))
"""


def _bench_compile_cache_cold_warm():
    """Cold-process vs warm-process compile cost with the persistent XLA
    compilation cache (tpumetrics.runtime.compile_cache) — the preemption /
    elastic-resize restart story as a measured scenario.

    Two subprocesses share one cache directory.  COLD starts with an empty
    cache: its timed window (stream half the batches through every bucket +
    flush) pays every XLA compile.  It then snapshots (elastic, world=1)
    and finishes the stream.  WARM is a fresh process on the populated
    cache: ``restore_elastic()`` adopts the snapshot, and its timed window
    replays the remaining batches — the identical program universe — hitting
    disk instead of the compiler.

    Gates: ``vs_baseline`` = cold_s / warm_s wall time (floor), and
    ``warm_cold_compile_ratio`` = warm_compile_s / cold_compile_s must stay
    under the ``compile_cache_ceilings`` ceiling (the acceptance bound:
    warm COMPILE time <= 0.5x cold).  Compile seconds sum JAX's
    backend-compile duration events minus cache-retrieval time (jax times
    compile-or-load as one event; the subtraction isolates actual XLA
    compilation) — wall time also contains tracing/dispatch, which no
    cache can remove.
    In-scenario asserts: the warm process's resumed result equals the cold
    process's full-stream result (bit-identical restore), and the warm run
    observed > 0 persistent-cache hits (it REUSED executables rather than
    re-compiling)."""
    import shutil
    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="tpum_ccache_")
    snap_dir = tempfile.mkdtemp(prefix="tpum_ccsnap_")
    script = _COMPILE_CACHE_SCRIPT.replace("{repo_dir!r}", repr(_REPO))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    # this process's cache (enabled in main()) must not leak into the
    # subprocesses: the scenario owns its directory end to end
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    env.pop("TPUMETRICS_COMPILE_CACHE", None)

    def run(mode):
        out = subprocess.run(
            [sys.executable, "-c", script, mode, cache_dir, snap_dir],
            capture_output=True, text=True, timeout=600, env=env,
        )
        if out.returncode != 0:
            raise RuntimeError(f"{mode}: {out.stderr[-2000:]}")
        return json.loads(out.stdout.strip().splitlines()[-1])

    try:
        cold = run("cold")
        warm = run("warm")
    finally:
        cache_entries = 0
        for _root, _dirs, files in os.walk(cache_dir):
            cache_entries += len(files)
        shutil.rmtree(cache_dir, ignore_errors=True)
        shutil.rmtree(snap_dir, ignore_errors=True)

    # the resumed warm result must equal the cold full-stream result
    for k, v in cold["vals"].items():
        assert v == warm["vals"][k], f"warm resume diverged on {k}: {warm['vals'][k]} != {v}"
    assert warm["cache_hits"] > 0, "warm process recompiled instead of reusing the cache"
    assert warm["restored_from"] == 8, warm["restored_from"]

    ours = warm["elapsed_s"] * 1e6  # us, like every other config
    ref = cold["elapsed_s"] * 1e6
    extras = {
        "cold_s": round(cold["elapsed_s"], 3),
        "warm_s": round(warm["elapsed_s"], 3),
        "cold_compile_s": round(cold["compile_s"], 3),
        "warm_compile_s": round(warm["compile_s"], 3),
        "warm_cold_compile_ratio": round(
            warm["compile_s"] / max(cold["compile_s"], 1e-9), 4
        ),
        "cache_entries": cache_entries,
        "warm_cache_hits": warm["cache_hits"],
        "cold_cache_misses": cold["cache_misses"],
        "restore_resumed_ok": True,
    }
    return ours, ref, {"extras": extras}


# -------------------------------------------------------- streaming runtime


def _ragged_stream(n_batches=60, num_classes=32, seed=0):
    """A serving-shaped stream: every batch a different leading dimension."""
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    # >= 50 DISTINCT ragged sizes (the acceptance scenario): a permutation of
    # 1..n_batches guarantees uniqueness; the naive jitted path compiles once
    # per size, the bucketed path once per bucket edge it touches
    sizes = rng.permutation(np.arange(1, n_batches + 1)).tolist()
    stream = []
    for n in sizes:
        stream.append(
            (
                jnp.asarray(rng.standard_normal((int(n), num_classes), dtype=np.float32)),
                jnp.asarray(rng.integers(0, num_classes, int(n)).astype(np.int32)),
            )
        )
    return stream


def _bench_streaming_throughput():
    """StreamingEvaluator (async + shape-bucketed, compile-per-bucket) vs the
    naive per-shape-jitted update loop over the same ragged stream.

    ``vs_baseline`` here is naive_time / streaming_time over an identical
    stream — the win is the bounded compile universe (the naive path pays one
    XLA compile per distinct batch shape).  Extras report both compile counts
    and verify the preemption contract: a kill-then-restore_latest() run must
    compute() bit-identically to the uninterrupted run.
    """
    import tempfile

    import jax
    import jax.numpy as jnp

    from tpumetrics.classification import MulticlassAccuracy
    from tpumetrics.runtime import StreamingEvaluator

    C = 32

    def make():
        return MulticlassAccuracy(num_classes=C, average="micro", validate_args=False)

    stream = _ragged_stream(num_classes=C)
    n_items = sum(int(p.shape[0]) for p, _ in stream)

    def streaming_once():
        ev = StreamingEvaluator(make(), buckets=64)
        t0 = time.perf_counter()
        with ev:
            for p, t in stream:
                ev.submit(p, t)
            val = ev.compute()
        jax.block_until_ready(val)
        return (time.perf_counter() - t0) * 1e6, float(val), ev.stats()["xla_compiles"]

    def naive_once():
        metric = make()
        step = jax.jit(lambda state, p, t: metric.functional_update(state, p, t))
        shapes = set()
        state = metric.init_state()
        t0 = time.perf_counter()
        for p, t in stream:
            shapes.add((p.shape, t.shape))
            state = step(state, p, t)
        val = metric.functional_compute(state)
        jax.block_until_ready(val)
        return (time.perf_counter() - t0) * 1e6, float(val), len(shapes)

    # interleaved min-of-k like every other config; the first streaming round
    # pays the per-bucket compiles, later rounds hit jit caches on both sides
    s_times, n_times = [], []
    s_val = n_val = None
    s_compiles = n_compiles = None
    for _ in range(3):
        us, s_val, s_compiles = streaming_once()
        s_times.append(us)
        us, n_val, n_compiles = naive_once()
        n_times.append(us)
    ours, ref = min(s_times), min(n_times)

    # preemption contract: kill mid-stream, restore, replay — bit-identical
    snap_dir = tempfile.mkdtemp(prefix="tpum_snap_")
    ev = StreamingEvaluator(make(), buckets=64, snapshot_dir=snap_dir, snapshot_every=20)
    for p, t in stream[:37]:
        ev.submit(p, t)
    ev.flush()
    ev.close(drain=False)  # "kill": no final snapshot past the last boundary
    ev2 = StreamingEvaluator(make(), buckets=64, snapshot_dir=snap_dir)
    pos = ev2.restore_latest()
    with ev2:
        for p, t in stream[pos:]:
            ev2.submit(p, t)
        restored_val = float(ev2.compute())

    assert s_val is not None and abs(s_val - n_val) < 1e-7, (s_val, n_val)
    # both acceptance invariants fail the scenario loudly, not quietly
    assert restored_val == s_val, f"restore not bit-identical: {restored_val} != {s_val}"
    assert s_compiles <= 7, f"bucketed path compiled {s_compiles} > len(buckets)=7 programs"
    extras = {
        "items_per_sec": n_items / (ours * 1e-6),
        "naive_items_per_sec": n_items / (ref * 1e-6),
        "distinct_shapes": n_compiles,
        "streaming_compiles": s_compiles,
        "naive_compiles": n_compiles,
        "restore_bit_identical": bool(restored_val == s_val),
        "restore_replay_from": pos,
    }
    return ours, ref, {"extras": extras}


def _bench_multitenant_scaling():
    """16 same-fingerprint tenants through ONE EvaluationService vs 16
    independent (sequentially-run) StreamingEvaluators over identical
    streams — the ISSUE 8 acceptance scenario.

    ``vs_baseline`` = sequential_wall / service_wall.  The service's wins
    are structural: ONE worker thread instead of 16, ONE fused-step trace
    universe instead of 16 (global signature dedupe — every evaluator
    re-traces its own step per bucket even when the persistent compile
    cache serves the XLA binary), and the megabatch fast path driving up to
    16 same-signature updates through one vmapped device program.

    In-scenario asserts (loud failures, not drifting numbers):

    - per-tenant parity: every tenant's compute() is BIT-IDENTICAL to its
      sequential-evaluator twin (integer statscores states);
    - signature dedupe: the service's distinct XLA compiles <= the
      16-evaluator total (the acceptance "<= 1x the distinct compiles");
    - the megabatch path actually engaged.

    Extras carry the 1000-stream soak: 1000 tenants over 4 distinct
    configurations registered on one service, submit-call latency read from
    the SHARED ``tpumetrics_submit_latency_ms`` instrument histogram the
    service populates (full p50/p90/p99/max distribution in the extras),
    with p99 gated by ``multitenant_ceilings.soak_p99_submit_ms`` (submit is
    an enqueue + a signature probe — it must stay off the device path no
    matter how many streams share the worker).
    """
    import numpy as np

    import jax.numpy as jnp

    from tpumetrics.classification import MulticlassAccuracy
    from tpumetrics.runtime import EvaluationService, StreamingEvaluator

    T, C, BATCHES = 16, 16, 8

    def make():
        return MulticlassAccuracy(num_classes=C, average="micro", validate_args=False)

    rng = np.random.default_rng(8)
    streams = [
        [
            (
                jnp.asarray(np.random.default_rng(100 * i + j).standard_normal((int(n), C), dtype=np.float32)),
                jnp.asarray(np.random.default_rng(100 * i + j).integers(0, C, int(n)).astype(np.int32)),
            )
            for j, n in enumerate(rng.integers(8, 33, BATCHES))
        ]
        for i in range(T)
    ]

    def service_once():
        svc = EvaluationService()
        handles = [svc.register(f"t{i}", make(), buckets=[32]) for i in range(T)]
        t0 = time.perf_counter()
        for j in range(BATCHES):
            for i in range(T):
                handles[i].submit(*streams[i][j])
        vals = [float(h.compute()) for h in handles]
        wall = (time.perf_counter() - t0) * 1e6
        stats = svc.stats()
        svc.close()
        return wall, vals, stats

    def sequential_once():
        t0 = time.perf_counter()
        vals, compiles = [], 0
        for i in range(T):
            ev = StreamingEvaluator(make(), buckets=[32])
            with ev:
                for p, t in streams[i]:
                    ev.submit(p, t)
                vals.append(float(ev.compute()))
            compiles += ev.stats()["xla_compiles"]
        wall = (time.perf_counter() - t0) * 1e6
        return wall, vals, compiles

    s_times, q_times = [], []
    svc_vals = seq_vals = None
    svc_stats = None
    seq_compiles = None
    for _ in range(3):
        wall, svc_vals, svc_stats = service_once()
        s_times.append(wall)
        wall, seq_vals, seq_compiles = sequential_once()
        q_times.append(wall)
    ours, ref = min(s_times), min(q_times)

    assert svc_vals == seq_vals, "multi-tenant parity broke: service != sequential"
    svc_compiles = svc_stats["xla_compiles"]
    # the acceptance bound: 16 tenants for <= 1x the baseline's compiles
    # (in practice ~6 megabatch-K programs vs 16 per-evaluator traces)
    assert svc_compiles <= seq_compiles, (
        f"signature dedupe regressed: service compiled {svc_compiles} distinct "
        f"signatures vs {seq_compiles} across 16 evaluators"
    )
    assert svc_stats["shared_steps"] == 1, "same-fingerprint tenants did not share a step"
    assert svc_stats["megabatch_steps"] > 0, "megabatch fast path never engaged"

    # ---- 1000-stream soak: p99 submit latency stays enqueue-shaped --------
    # Latency is sourced from the SHARED submit-latency histogram the service
    # itself populates (tpumetrics.telemetry.instruments) — the bench reads
    # the same instrument production scrapes, instead of hand-rolling its
    # own percentile math around the submit calls.
    from tpumetrics.telemetry import instruments as _instruments

    SOAK_T, SOAK_BATCHES = 1000, 2
    submit_hist = _instruments.histogram(
        _instruments.SUBMIT_LATENCY_MS, labels=("stream",)
    )
    submit_hist.clear()  # earlier scenarios' streams must not pollute the gate
    svc = EvaluationService()
    soak_handles = []
    for i in range(SOAK_T):
        classes = (8, 12, 16, 24)[i % 4]
        m = MulticlassAccuracy(num_classes=classes, average="micro", validate_args=False)
        soak_handles.append((svc.register(f"s{i}", m, buckets=[16]), classes))
    soak_batches = {
        classes: (
            jnp.asarray(np.random.default_rng(classes).standard_normal((16, classes), dtype=np.float32)),
            jnp.asarray(np.random.default_rng(classes).integers(0, classes, 16).astype(np.int32)),
        )
        for classes in (8, 12, 16, 24)
    }
    # freeze the 1000-tenant object graph out of the cyclic collector for
    # the timed window: a gen-2 sweep over it is a 10-90ms stop-the-world
    # pause that lands on whichever thread allocates next — measured here,
    # that is CPython's collector, not the submit path the ceiling gates
    # (standard practice for latency-sensitive serving; docs/observability.md)
    import gc

    gc.collect()
    gc.freeze()
    try:
        for _ in range(SOAK_BATCHES):
            for h, classes in soak_handles:
                p, t = soak_batches[classes]
                h.submit(p, t)
        svc.flush()
    finally:
        gc.unfreeze()
    soak_lat = submit_hist.summary()  # cross-tenant aggregate
    # the histogram really is the source: every soak submit was observed
    assert soak_lat["count"] == SOAK_T * SOAK_BATCHES, soak_lat
    soak_p99 = float(soak_lat["p99"])
    soak_stats = svc.stats()
    # spot-check correctness under the soak: every stream fully applied,
    # sampled tenants compute the same value as a direct functional run
    for h, classes in soak_handles[::250]:
        assert h.stats()["batches"] == SOAK_BATCHES, h.stats()
        m = MulticlassAccuracy(num_classes=classes, average="micro", validate_args=False)
        s = m.init_state()
        for _ in range(SOAK_BATCHES):
            s = m.functional_update(s, *soak_batches[classes])
        assert float(h.compute()) == float(m.functional_compute(s))
    svc.close()

    extras = {
        "tenants": T,
        "service_compiles": svc_compiles,
        "sequential_compiles": seq_compiles,
        "compile_ratio": round(svc_compiles / max(seq_compiles, 1), 3),
        "megabatch_steps": svc_stats["megabatch_steps"],
        "megabatch_tenants": svc_stats["megabatch_tenants"],
        "shared_steps": svc_stats["shared_steps"],
        "soak_streams": SOAK_T,
        # full distribution from the shared histogram (same ceiling key)
        "soak_p50_submit_ms": round(float(soak_lat["p50"]), 3),
        "soak_p90_submit_ms": round(float(soak_lat["p90"]), 3),
        "soak_p99_submit_ms": round(soak_p99, 3),
        "soak_max_submit_ms": round(float(soak_lat["max"]), 3),
        "soak_submit_count": soak_lat["count"],
        "soak_shared_steps": soak_stats["shared_steps"],
        "soak_compiles": soak_stats["xla_compiles"],
    }
    return ours, ref, {"extras": extras}


def _bench_tenant_lifecycle():
    """Tenant lifecycle at registration scale (ISSUE 17 acceptance): 100k
    registered tenants at ~99% idle on ONE budgeted EvaluationService.

    ``vs_baseline`` = baseline_wall / lifecycle_wall over the IDENTICAL
    hot-tenant submit+flush workload: the same 1k hot tenants driven through
    a plain 1k-tenant service (ref) vs through the 100k-registered budgeted
    service (ours).  O(active) scheduling is the claim under test — the 99k
    hibernated tenants leave the DRR ring and the instrument registry
    entirely, so the ratio must hold ~1 no matter how many tenants are
    registered.

    The registration wave itself exercises pristine-start: once the HBM
    budget saturates, ``register()`` creates tenants directly in the
    hibernated state (no device allocation, no scheduler entry, no spill
    file), which is what makes 100k registrations tractable at all.

    In-scenario asserts (loud failures, not drifting numbers):

    - pristine-start engaged: registrations past the budget went straight
      to hibernated;
    - the scheduler census is O(active): DRR membership stays at the
      resident count, never the registered count;
    - bit-identity: a hot tenant's compute() equals the functional oracle,
      and a revived tenant's compute() equals its oracle too;
    - the steady-state HBM watermark holds under the budget after the
      revival wave forced evictions.

    Extras carry the three gated series (``tenant_lifecycle_ceilings``):
    ``hbm_watermark_budget_ratio`` (max sampled resident bytes / budget,
    ceiling 1.0 — the budget is a contract, not a target),
    ``hot_p99_submit_ratio`` (hot-tenant p99 submit on the 100k service /
    the 1k baseline, from the shared submit-latency histogram), and
    ``revival_latency_p99_ms`` (the manager's revival histogram over a
    200-tenant revival wave).
    """
    import gc

    import jax.numpy as jnp

    from tpumetrics.aggregation import MeanMetric
    from tpumetrics.backbones.registry import resident_bytes
    from tpumetrics.runtime import EvaluationService
    from tpumetrics.telemetry import instruments as _instruments

    REG_T = int(os.environ.get("TPUMETRICS_BENCH_LIFECYCLE_TENANTS", 100_000))
    HOT = max(min(1000, REG_T // 100), 8)
    REVIVE = max(min(200, REG_T // 500), 4)
    BATCHES = 2

    batch = jnp.asarray(
        np.random.default_rng(17).standard_normal(16, dtype=np.float32)
    )

    def make():
        return MeanMetric()

    # one tenant's resident state size, measured — the budget then caps the
    # resident set at 1.5x the hot-tenant count
    probe = EvaluationService(hbm_budget_bytes=1 << 40)
    probe.register("probe", make(), buckets=[16])
    size = probe.stats()["lifecycle"]["resident_state_bytes"]
    probe.close()
    assert size > 0, "lifecycle accounting recorded no resident bytes"
    resident_cap = int(HOT * 1.5)
    budget = size * resident_cap

    submit_hist = _instruments.histogram(
        _instruments.SUBMIT_LATENCY_MS, labels=("stream",)
    )

    def hot_round(svc, handles):
        t0 = time.perf_counter()
        for _ in range(BATCHES):
            for h in handles:
                h.submit(batch)
        svc.flush()
        return (time.perf_counter() - t0) * 1e6

    # ---- ref: the same hot workload on a plain service of exactly HOT ----
    ref_svc = EvaluationService()
    ref_handles = [
        ref_svc.register(f"b{i}", make(), buckets=[16]) for i in range(HOT)
    ]
    submit_hist.clear()
    gc.collect()
    gc.freeze()
    try:
        ref_us = hot_round(ref_svc, ref_handles)
    finally:
        gc.unfreeze()
    base_p99 = float(submit_hist.summary()["p99"])
    ref_svc.close()

    # ---- ours: 100k registered, budget caps residency -------------------
    svc = EvaluationService(hbm_budget_bytes=budget)
    t0 = time.perf_counter()
    handles = [svc.register(f"t{i}", make(), buckets=[16]) for i in range(REG_T)]
    register_wall_s = time.perf_counter() - t0
    lc = svc.stats()["lifecycle"]
    assert lc["hibernated_tenants"] >= REG_T - resident_cap - 1, (
        f"pristine-start never engaged: {lc}"
    )
    assert lc["scheduled_tenants"] <= resident_cap, (
        f"DRR census is not O(active): {lc}"
    )
    hot_handles = handles[:HOT]  # registered first -> resident

    def watermark():
        s = svc.stats()["lifecycle"]
        return s["resident_state_bytes"] + resident_bytes()

    submit_hist.clear()
    gc.collect()
    gc.freeze()
    try:
        ours_us = hot_round(svc, hot_handles)
    finally:
        gc.unfreeze()
    hot_p99 = float(submit_hist.summary()["p99"])
    svc.lifecycle.enforce_budget()  # settle worker-side eviction first
    marks = [watermark()]

    # ---- revival wave: deep-hibernated tail comes back interactive ------
    revive_hist = _instruments.histogram(
        _instruments.REVIVAL_LATENCY_MS, labels=("service",), sketch=True
    )
    revive_ids = [f"t{i}" for i in range(REG_T - REVIVE, REG_T)]
    for tid in revive_ids:
        svc.submit(tid, batch)
    svc.flush()
    svc.lifecycle.enforce_budget()
    marks.append(watermark())
    rev = revive_hist.summary(svc._label)
    assert rev["count"] >= REVIVE, f"revival histogram missed revivals: {rev}"
    revival_p99 = float(rev["p99"])

    # bit-identity spot checks against the functional oracle
    oracle = make()
    s = oracle.init_state()
    for _ in range(BATCHES):
        s = oracle.functional_update(s, batch)
    assert float(hot_handles[0].compute()) == float(oracle.functional_compute(s))
    s1 = oracle.functional_update(oracle.init_state(), batch)
    assert float(svc.compute(revive_ids[0])) == float(oracle.functional_compute(s1))

    lc = svc.stats()["lifecycle"]
    watermark_ratio = max(marks) / budget
    assert watermark_ratio <= 1.0, (
        f"steady-state HBM watermark {max(marks)} over budget {budget}"
    )
    extras = {
        "registered_tenants": REG_T,
        "hot_tenants": HOT,
        "resident_cap": resident_cap,
        "hbm_budget_bytes": budget,
        "hbm_watermark_budget_ratio": round(watermark_ratio, 4),
        "baseline_p99_submit_ms": round(base_p99, 3),
        "hot_p99_submit_ms": round(hot_p99, 3),
        "hot_p99_submit_ratio": round(hot_p99 / max(base_p99, 1e-9), 3),
        "revived_tenants": REVIVE,
        "revival_latency_p99_ms": round(revival_p99, 3),
        "register_wall_s": round(register_wall_s, 3),
        "scheduled_tenants": lc["scheduled_tenants"],
        "hibernated_tenants": lc["hibernated_tenants"],
        "evictions": lc["evictions"],
        "revivals": lc["revivals"],
    }
    svc.close()
    return ours_us, ref_us, {"extras": extras}


def _bench_resilience_overhead():
    """Cost of the SyncPolicy guard when NO fault fires (tpumetrics.resilience).

    Two numbers, two gates:

    - ``vs_baseline`` = inert_sync_us / armed_sync_us over an identical eager
      fused sync loop (fault-injection backend with an EMPTY schedule, so the
      guard is engaged but nothing ever fires).  Armed mode pays one watchdog
      thread per guarded collective; the floor in bench_floors.json bounds
      how much that may cost relative to the unguarded sync.
    - ``inert_overhead_ns_per_call`` — the production default: with an inert
      policy the guard must collapse to a predicate check.  Measured as the
      per-call delta between ``run_guarded(fn)`` and ``fn()`` over a large
      loop; gated by a ceiling (resilience_overhead_ceilings).
    """
    from tpumetrics.classification import MulticlassStatScores
    from tpumetrics.parallel.backend import NoOpBackend
    from tpumetrics.resilience import FaultInjectionBackend, SyncPolicy, run_guarded, sync_policy

    backend = FaultInjectionBackend(NoOpBackend(), faults=())  # nothing ever fires
    metric = MulticlassStatScores(num_classes=64, average=None, validate_args=False)
    metric.sync_backend = backend
    metric.distributed_available_fn = lambda: True
    rng = np.random.default_rng(11)
    import jax.numpy as jnp

    preds = jnp.asarray(rng.standard_normal((256, 64)), jnp.float32)
    target = jnp.asarray(rng.integers(0, 64, (256,)), jnp.int32)
    metric.update(preds, target)

    K = 50

    def sync_loop_once():
        t0 = time.perf_counter()
        for _ in range(K):
            metric._computed = None
            metric.compute()  # eager fused sync through the guarded flush
        return (time.perf_counter() - t0) * 1e6 / K

    armed = SyncPolicy(timeout=30.0, retries=2)
    armed_times, inert_times = [], []
    for _ in range(3):
        with sync_policy(armed):
            armed_times.append(sync_loop_once())
        inert_times.append(sync_loop_once())
    ours, ref = min(armed_times), min(inert_times)
    assert backend.fired == [], f"no fault was scheduled, yet {backend.fired} fired"

    # inert fast path: run_guarded must be ~a predicate check per call
    N = 50_000
    fn = int  # cheapest stable callable
    t0 = time.perf_counter()
    for _ in range(N):
        fn()
    direct = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(N):
        run_guarded(fn, op="noop", backend=backend)
    guarded = time.perf_counter() - t0
    inert_overhead_ns = max(0.0, (guarded - direct) / N * 1e9)

    extras = {
        "armed_added_us_per_sync": round(ours - ref, 2),
        "inert_overhead_ns_per_call": round(inert_overhead_ns, 1),
        "guarded_collectives_per_sync": 1,  # 4 same-dtype sum states fuse to one class
    }
    return ours, ref, {"extras": extras}


def _bench_observability_overhead():
    """Cost of the observability layer at its two operating points
    (tpumetrics.telemetry.spans / instruments).

    - ``vs_baseline`` = inert_span_ns / armed_span_ns: how much cheaper the
      disabled span path is than full tracing.  No floor ambition here — the
      ratio just documents the gap (armed tracing allocates a span object
      and appends to a locked ring; disabled is a flag test returning a
      shared singleton).
    - ``observability_overhead_ceilings`` gate the production costs:
      ``inert_span_ns_per_call`` (a DISABLED ``span()`` — the default — must
      stay ~a flag test; the evaluator/service call it on every batch) and
      ``counter_ns_per_call`` (an ENABLED counter/histogram update — the
      default — sits on the 1000-stream submit path).

    In-scenario asserts: the disabled ``span()`` returns THE shared no-op
    singleton (nothing allocated per call), the span ring stays bounded
    under sustained armed tracing, and the flight-recorder ring never grows
    past its capacity.
    """
    from tpumetrics.telemetry import export as tele_export
    from tpumetrics.telemetry import instruments as tele_instruments
    from tpumetrics.telemetry import spans as tele_spans

    N = 100_000

    def per_call_ns(fn):
        t0 = time.perf_counter()
        for _ in range(N):
            fn()
        return (time.perf_counter() - t0) / N * 1e9

    def empty():
        pass

    was_enabled = tele_spans.enabled()
    try:
        tele_spans.disable()
        base = min(per_call_ns(empty) for _ in range(3))
        inert = min(per_call_ns(lambda: tele_spans.span("noop")) for _ in range(3))
        # the disabled path hands back one module-lifetime singleton
        assert tele_spans.span("a") is tele_spans.span("b")

        tele_spans.enable(capacity=1024)

        def armed():
            with tele_spans.span("noop"):
                pass

        armed_ns = min(per_call_ns(armed) for _ in range(3))
        tracer = tele_spans.get_tracer()
        assert len(tracer.spans()) <= tracer.capacity, "span ring exceeded its bound"
        assert tracer.evicted > 0, "3x capacity recorded, yet nothing evicted?"
    finally:
        tele_spans.disable()
        tele_spans.reset()
        if was_enabled:
            tele_spans.enable()

    c = tele_instruments.counter("bench_observability_total", labels=("who",))
    counter_ns = min(per_call_ns(lambda: c.inc(1, "bench")) for _ in range(3))
    h = tele_instruments.histogram("bench_observability_ms", labels=("who",))
    hist_ns = min(per_call_ns(lambda: h.observe(0.5, "bench")) for _ in range(3))

    # flight ring bound under sustained recording (no dump = no file I/O)
    rec = tele_export.FlightRecorder(directory=".", capacity=256)
    for i in range(1024):
        rec.note("tick", i=i)
    assert len(rec) == 256, "flight ring exceeded its bound"

    extras = {
        "inert_span_ns_per_call": round(max(0.0, inert - base), 1),
        "armed_span_ns_per_call": round(max(0.0, armed_ns - base), 1),
        "counter_ns_per_call": round(max(0.0, counter_ns - base), 1),
        "histogram_ns_per_call": round(max(0.0, hist_ns - base), 1),
    }
    return armed_ns / 1e3, inert / 1e3, {"extras": extras}


def _bench_device_observability():
    """Cost of the DEVICE-side observability layer at its two hot points
    (tpumetrics.telemetry.device / health).

    - ``vs_baseline`` = unprobed_us / probed_us over an identical fused
      masked-update loop: how much step time the in-trace health probe
      eats.  The probe appends pure-jnp NaN/inf/saturation reductions to
      the step program (same XLA dispatch, outputs stay on device), so the
      ratio should sit near 1.0; the floor catches a structural regression
      (a probe forcing a second dispatch or a host sync reads ~0.1).
    - ``device_observability_ceilings`` gate the production costs:
      ``health_probe_overhead_ratio`` (probed/unprobed step time — the
      ISSUE bound: the probe must cost <5% step time) and
      ``profile_lookup_ns_per_call`` (the armed profile registry's
      per-dispatch seen-signature check — the only work a steady-state
      dispatch pays once its program registered).

    In-scenario asserts: probed and unprobed steps produce BIT-identical
    metric state (the parity contract), the probe's health summary over a
    clean stream is all-zero, and the armed registry actually registered
    the step program (with a resolvable flops count).
    """
    import jax
    import jax.numpy as jnp

    from tpumetrics import MetricCollection
    from tpumetrics.classification import MulticlassAccuracy, MulticlassConfusionMatrix
    from tpumetrics.parallel.fuse_update import FusedCollectionStep
    from tpumetrics.telemetry import device as tele_device
    from tpumetrics.telemetry import health as tele_health

    # rows sized so the step is genuinely device-bound (~1ms on the 2-CPU
    # box): the probe's cost is a few fixed reductions + one extra output
    # handle, so against a too-small step the ratio would measure host
    # dispatch jitter, not the probe
    C, ROWS, STEPS = 64, 4096, 15

    rng = np.random.default_rng(11)
    preds = jnp.asarray(rng.standard_normal((ROWS, C)), jnp.float32)
    target = jnp.asarray(rng.integers(0, C, ROWS))
    jax.block_until_ready((preds, target))
    n_valid = jnp.asarray(ROWS, jnp.int32)

    def make():
        col = MetricCollection(
            {
                "acc": MulticlassAccuracy(num_classes=C, average="micro", validate_args=False),
                "confmat": MulticlassConfusionMatrix(num_classes=C, validate_args=False),
            }
        )
        col.update(preds, target)  # establishes compute groups
        col.reset()
        return col

    step_plain = FusedCollectionStep(make(), donate=True)
    step_probe = FusedCollectionStep(make(), donate=True, health_probe=True)

    def plain_once():
        s = step_plain.init_state()
        s = step_plain.masked_update(s, (preds, target), n_valid, ROWS)  # compile
        t0 = time.perf_counter()
        for _ in range(STEPS):
            s = step_plain.masked_update(s, (preds, target), n_valid, ROWS)
        jax.block_until_ready(jax.tree_util.tree_leaves(s))
        return (time.perf_counter() - t0) / STEPS * 1e6, s

    def probe_once():
        s = step_probe.init_state()
        s, h = step_probe.masked_update(s, (preds, target), n_valid, ROWS)  # compile
        t0 = time.perf_counter()
        for _ in range(STEPS):
            s, h = step_probe.masked_update(s, (preds, target), n_valid, ROWS)
        jax.block_until_ready(jax.tree_util.tree_leaves(s))
        return (time.perf_counter() - t0) / STEPS * 1e6, s, h

    plain_times, probe_times, ratios = [], [], []
    s_plain = s_probe = h_probe = None
    for _ in range(7):
        us_plain, s_plain = plain_once()
        plain_times.append(us_plain)
        us_probe, s_probe, h_probe = probe_once()
        probe_times.append(us_probe)
        # same-round pairwise ratio: plain and probed run back to back, so
        # ambient box load cancels — the min over rounds is the probe's
        # actual overhead, which is what the <5% ceiling bounds
        ratios.append(us_probe / us_plain)
    plain_us, probe_us = min(plain_times), min(probe_times)
    overhead_ratio = min(ratios)

    # parity: the probe must not change a single state bit
    flat_probe = jax.tree_util.tree_leaves(s_probe)
    for i, leaf in enumerate(jax.tree_util.tree_leaves(s_plain)):
        assert np.array_equal(np.asarray(leaf), np.asarray(flat_probe[i])), (
            "health probe changed the metric state — the parity contract broke"
        )
    summ = tele_health.summarize(h_probe, tele_health.state_paths(s_probe))
    assert summ["nonfinite_total"] == 0, f"clean stream read corrupt: {summ}"

    # armed profile registry: per-dispatch seen-signature check cost, plus
    # the registered program must resolve to a real flops count
    tele_device.reset_device_profiles()
    tele_device.enable_device_profiles()
    try:
        s = step_plain.init_state()
        s = step_plain.masked_update(s, (preds, target), n_valid, ROWS)
        registered = len(tele_device.registry())
        assert registered >= 1, "armed registry saw no dispatch"
        N = 20_000
        label = "step:bench:('masked', %d)" % ROWS
        note_args = (s, (preds, target), n_valid)
        tele_device.note_dispatch(label, step_plain, note_args)  # first = insert
        t0 = time.perf_counter()
        for _ in range(N):
            tele_device.note_dispatch(label, step_plain, note_args)
        lookup_ns = (time.perf_counter() - t0) / N * 1e9
        prof = tele_device.profiles()
        assert any(p.get("flops", 0) > 0 for p in prof), (
            f"no registered program resolved a flops count: {prof}"
        )
    finally:
        tele_device.disable_device_profiles()
        tele_device.reset_device_profiles()

    extras = {
        "rows_per_step": ROWS,
        "num_classes": C,
        "probed_us_per_step": probe_us,
        "unprobed_us_per_step": plain_us,
        "health_probe_overhead_ratio": round(overhead_ratio, 4),
        "profile_lookup_ns_per_call": round(lookup_ns, 1),
        "parity_ok": True,
    }
    return probe_us, plain_us, {"extras": extras}


def _bench_elastic_restore():
    """Cost of elastic coordination (tpumetrics.resilience.elastic).

    Two numbers, two gates, on a 50-metric collection:

    - ``vs_baseline`` = plain_snapshot_us / coordinated_snapshot_us over an
      identical save loop (emulated 8-rank barrier cohort): the barrier adds
      one guarded object exchange + cut stamping per step; the floor in
      bench_floors.json bounds how much of the snapshot step it may eat.
    - ``restore_8to4_ms`` — wall time for a FULL 8→4 elastic restore: each
      of the 4 new ranks discovers the cut, CRC-loads all 8 member payloads,
      folds them into the canonical global state and reshards its share.
      Gated by a ceiling (elastic_restore_ceilings); also asserts the folded
      world-4 result equals the world-8 fold (the correctness invariant —
      a fast but wrong restore must fail the scenario loudly).
    """
    import shutil
    import tempfile

    import jax.numpy as jnp

    from tpumetrics import MetricCollection
    from tpumetrics.classification import MulticlassAccuracy
    from tpumetrics.parallel.backend import DistributedBackend
    from tpumetrics.resilience import elastic as elastic_mod
    from tpumetrics.resilience.elastic import DistributedSnapshotManager, load_latest_cut

    N_METRICS, WORLD_FROM, WORLD_TO, C = 50, 8, 4, 8

    def make():
        return MetricCollection(
            {
                f"m{i:02d}": MulticlassAccuracy(num_classes=C, average="micro", validate_args=False)
                for i in range(N_METRICS)
            }
        )

    rng = np.random.default_rng(17)
    replicas = [make() for _ in range(WORLD_FROM)]
    for col in replicas:
        preds = jnp.asarray(rng.standard_normal((64, C)), jnp.float32)
        target = jnp.asarray(rng.integers(0, C, (64,)), jnp.int32)
        col.update(preds, target)
    payloads = [col.snapshot_state() for col in replicas]
    config = elastic_mod.config_digest(replicas[0])

    class _Cohort(DistributedBackend):
        has_object_channel = True

        def __init__(self, rank, step):
            self._rank, self._step = rank, step

        def available(self):
            return True

        def world_size(self):
            return WORLD_FROM

        def rank(self):
            return self._rank

        def all_gather_object(self, obj, group=None):
            return [
                obj if r == self._rank else elastic_mod.make_stamp(r, self._step, config)
                for r in range(WORLD_FROM)
            ]

    K = 5  # coordinated-vs-plain save rounds

    def coordinated_once(root):
        mgrs = [DistributedSnapshotManager(root, r, WORLD_FROM, keep=None) for r in range(WORLD_FROM)]
        t0 = time.perf_counter()
        for step in range(1, K + 1):
            for r in range(WORLD_FROM):
                agreed, digest = elastic_mod.snapshot_barrier(
                    _Cohort(r, step), rank=r, world_size=WORLD_FROM, step=step, config=config
                )
                meta = {
                    "batches": step, "items": step, "mode": "eager", "degraded": False,
                    "base_batches": 0, "base_items": 0,
                    "elastic": mgrs[r].elastic_meta(agreed, digest, config),
                }
                mgrs[r].save(agreed, payloads[r], meta=meta)
        return (time.perf_counter() - t0) * 1e6 / (K * WORLD_FROM)

    def plain_once(root):
        from tpumetrics.runtime.snapshot import SnapshotManager

        mgrs = [SnapshotManager(os.path.join(root, f"r{r}"), keep=None) for r in range(WORLD_FROM)]
        t0 = time.perf_counter()
        for step in range(1, K + 1):
            for r in range(WORLD_FROM):
                mgrs[r].save(step, payloads[r], meta={"batches": step, "items": step})
        return (time.perf_counter() - t0) * 1e6 / (K * WORLD_FROM)

    coord_times, plain_times = [], []
    coord_root = None
    for _ in range(3):
        root = tempfile.mkdtemp(prefix="tpum_elastic_")
        coord_times.append(coordinated_once(root))
        if coord_root is None:
            coord_root = root  # keep one populated root for the restore leg
        else:
            shutil.rmtree(root, ignore_errors=True)
        root2 = tempfile.mkdtemp(prefix="tpum_plain_")
        plain_times.append(plain_once(root2))
        shutil.rmtree(root2, ignore_errors=True)
    ours, ref = min(coord_times), min(plain_times)

    # ---- the 8 -> 4 restore leg (correctness-asserted, ceiling-gated)
    proto = make()
    ref_col = make()
    ref_col.load_snapshot_state(proto.fold_snapshot_states(payloads))
    want_vals = {k: float(v) for k, v in ref_col.compute().items()}

    t0 = time.perf_counter()
    new_cols = []
    for r in range(WORLD_TO):
        cut = load_latest_cut(coord_root)
        folded = proto.fold_snapshot_states([cut.payloads[i] for i in sorted(cut.payloads)])
        share = proto.reshard_snapshot_state(folded, r, WORLD_TO)
        col = make()
        col.load_snapshot_state(share)
        new_cols.append(col)
    restore_ms = (time.perf_counter() - t0) * 1e3
    shutil.rmtree(coord_root, ignore_errors=True)

    got = proto.fold_snapshot_states([c.snapshot_state() for c in new_cols])
    final = make()
    final.load_snapshot_state(got)
    got_vals = {k: float(v) for k, v in final.compute().items()}
    for k, v in want_vals.items():
        assert abs(got_vals[k] - v) < 1e-7, (k, got_vals[k], v)

    extras = {
        "barrier_added_us_per_step": round(ours - ref, 2),
        "restore_8to4_ms": round(restore_ms, 1),
        "metrics_in_collection": N_METRICS,
    }
    return ours, ref, {"extras": extras}


def _bench_analysis_runtime():
    """Wall time of the tpulint self-run over the whole package
    (tpumetrics.analysis) — the pass tier-1 gates on.

    No reference side (there is nothing to compare against), three ceilings
    (``analysis_runtime_ceilings``):

    - ``analysis_wall_ms`` — the warm-repeat floor (min of 3): the full
      two-pass analysis (index + rules over every package file) must stay
      cheap enough to run on every CI commit and inside tier-1; the ceiling
      catches algorithmic blowups (an accidentally quadratic reachability,
      taint, or lock-order fixed-point pass), not box noise.
    - ``tpulint_self_run_ms`` — the COLD first pass, which is what a
      single-shot CI invocation actually pays (source reads and index build
      included, no warm page cache).  Tracked separately so the rule set can
      grow (the concurrency plane added a thread-entry oracle, a lock-model
      census, and an interprocedural acquire-set closure) without the
      one-shot cost silently drifting past what tier-1 can absorb.
    - ``findings_unsuppressed`` — ceiling 0: the bench run re-asserts the
      self-run is clean, so a bench-gated pipeline cannot go green with a
      dirty package even if the pytest gate was skipped.
    """
    from tpumetrics.analysis import analyze_paths

    pkg = os.path.join(_REPO, "tpumetrics")
    times, findings = [], []
    for _ in range(3):
        t0 = time.perf_counter()
        findings = analyze_paths([pkg])
        times.append((time.perf_counter() - t0) * 1e6)
    ours = min(times)
    unsuppressed = [f for f in findings if not f.suppressed]
    assert not unsuppressed, (
        f"tpulint self-run must be clean, got {len(unsuppressed)}: "
        + "; ".join(f"{f.path}:{f.line}:{f.code}" for f in unsuppressed[:5])
    )
    n_files = sum(
        len([f for f in files if f.endswith(".py")])
        for root, dirs, files in os.walk(pkg)
        if "__pycache__" not in root
    )
    extras = {
        "analysis_wall_ms": round(ours / 1000.0, 1),
        "tpulint_self_run_ms": round(times[0] / 1000.0, 1),
        "files_analyzed": n_files,
        "findings_unsuppressed": len(unsuppressed),
        "findings_suppressed": len(findings) - len(unsuppressed),
    }
    return ours, None, {"extras": extras}


def _enable_compilation_cache() -> None:
    """Persistent XLA compile cache: one-time eager/jit compiles (expensive on
    remote-attached accelerators) amortize across bench runs, as they do in
    any long-lived production process."""
    import jax

    cache_dir = os.path.join(_REPO, ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def _bench_monitoring_window():
    """Windowed monitoring (ring-of-subwindow-states) vs the naive
    recompute-from-CatMetric baseline over an identical unbounded-style
    stream.

    The serving pattern: every step ingests one batch AND reads the current
    window aggregate.  Without windows the only exact option is a CatMetric
    history + recompute over the concatenated tail — O(window · rows) device
    work per step on state that never stops growing.  The windowed
    aggregator folds the batch into one ring slot (O(rows)) and computes
    from ``slots`` partials (O(slots)); ``vs_baseline`` = naive / windowed.

    In-scenario asserts: windowed reads match the naive tail recompute
    (parity), the windowed state stays fixed-shape, and the whole stream
    runs through ONE compiled step (no per-position retrace).  The ceiling
    ``monitoring_ceilings.sketch_update_ns_per_row`` separately pins the
    quantile sketch's scatter-add ingest cost (the drift/quantile hot path).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpumetrics.monitoring import SketchQuantiles, WindowedMean
    from tpumetrics.utils.data import dim_zero_cat

    W, ROWS, STEPS = 64, 128, 150
    rng = np.random.default_rng(17)
    stream = [
        jnp.asarray(rng.normal(2.0, 1.0, ROWS).astype(np.float32)) for _ in range(STEPS)
    ]
    jax.block_until_ready(stream[-1])

    metric = WindowedMean(window=W, slots=W)
    step = jax.jit(lambda s, v: metric.functional_update(s, v))
    read = jax.jit(
        lambda s: jnp.sum(s["slot_sum"]) / jnp.sum(s["slot_weight"])
    )

    def windowed_once():
        state = metric.init_state()
        t0 = time.perf_counter()
        vals = []
        for b in stream:
            state = step(state, b)
            vals.append(read(state))
        jax.block_until_ready(vals[-1])
        return (time.perf_counter() - t0) * 1e6, vals, state

    naive_read = jax.jit(lambda rows: jnp.mean(rows))

    def naive_once():
        history = []  # the CatMetric pattern: keep everything, slice the tail
        t0 = time.perf_counter()
        vals = []
        for b in stream:
            history.append(b)
            vals.append(naive_read(dim_zero_cat(history[-W:])))
        jax.block_until_ready(vals[-1])
        return (time.perf_counter() - t0) * 1e6, vals

    w_times, n_times = [], []
    w_vals = n_vals = None
    state = None
    for _ in range(3):
        us, w_vals, state = windowed_once()
        w_times.append(us)
        us, n_vals = naive_once()
        n_times.append(us)
    ours, ref = min(w_times), min(n_times)

    # parity: every windowed read equals the naive tail recompute
    w_arr = np.asarray(jax.device_get(jnp.stack(w_vals)))
    n_arr = np.asarray(jax.device_get(jnp.stack(n_vals)))
    assert np.allclose(w_arr, n_arr, rtol=1e-5), "windowed reads drifted from naive tail"
    assert state["slot_sum"].shape == (W,), "windowed state must stay fixed-shape"
    assert step._cache_size() == 1, f"windowed step retraced: {step._cache_size()} programs"

    # sketch ingest ceiling: ns/row through the jitted sketch update
    sk = SketchQuantiles(quantiles=(0.5, 0.99))
    sk_step = jax.jit(lambda s, v: sk.functional_update(s, v))
    sk_state = sk.init_state()
    sk_state = sk_step(sk_state, stream[0])  # compile
    jax.block_until_ready(jax.tree_util.tree_leaves(sk_state))
    t0 = time.perf_counter()
    for b in stream:
        sk_state = sk_step(sk_state, b)
    jax.block_until_ready(jax.tree_util.tree_leaves(sk_state))
    sketch_ns_per_row = (time.perf_counter() - t0) * 1e9 / (STEPS * ROWS)

    extras = {
        "window": W,
        "rows_per_step": ROWS,
        "windowed_us_per_step": ours / STEPS,
        "naive_us_per_step": ref / STEPS,
        "sketch_update_ns_per_row": sketch_ns_per_row,
        "windowed_compiles": step._cache_size(),
        "parity_ok": True,
    }
    return ours, ref, {"extras": extras}


def _bench_chaos_soak():
    """The resilience hot path as a STANDING bench gate (ISSUE 12): a real
    3-process pool under a deterministic chaos schedule — one SIGTERM
    graceful drain, one SIGKILL at an arbitrary stream point, one shrink,
    one grow — with every recovery bit-identity-verified against the
    uninterrupted oracle by the supervisor (a divergence errors the
    scenario, which trips the gate).

    Emitted series and gates (``chaos_soak_floors``/``chaos_soak_ceilings``):

    - ``restore_latency_p50_ms`` / ``restore_latency_p99_ms`` — max-over-
      ranks wall time of each recovery cycle's ``restore_elastic`` call
      (cut discovery + CRC loads + fold + reshard + place).  The p99
      ceiling catches algorithmic blowups in the restore path (a per-rank
      re-fold, an O(history) cut scan after retention broke), not box
      noise.
    - ``throughput_rows_per_s_min`` — the slowest leg's feed throughput
      (submit+flush+coordinated-cut cadence).  The floor is deliberately
      far below observed: it exists to catch structural stalls (a wedged
      barrier retrying every cut, a retrace per feed), not to benchmark
      row throughput (the legs are tiny by design).
    - ``unrecovered_incidents`` — ceiling 0 BY DESIGN: the bench cannot go
      green while any induced incident fails recovery or any standing gate
      (bit-identity, exactly-once adoption, ledger/flight continuity).
    """
    import shutil
    import tempfile

    from tpumetrics.soak.schedule import ChaosSchedule, Incident
    from tpumetrics.soak.supervisor import run_soak

    schedule = ChaosSchedule(
        seed=0, world=3, cut_every=3,
        incidents=(
            Incident(kind="sigterm", feed=6, world_after=3),
            Incident(kind="sigkill", feed=7, world_after=3, abrupt=True,
                     target_rank=1, tail=2),
            Incident(kind="shrink", feed=6, world_after=2),
            Incident(kind="grow", feed=6, world_after=3, abrupt=True,
                     target_rank=0, tail=1),
        ),
        restore_ceiling_s=60.0,
    )
    root = tempfile.mkdtemp(prefix="tpum_chaos_")
    t0 = time.perf_counter()
    try:
        report = run_soak(schedule, root)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    wall_us = (time.perf_counter() - t0) * 1e6
    assert report["unrecovered"] == 0, report  # every gate held, every cycle
    assert report["final"].get("ok") is True, report["final"]
    lat = report["restore_latency_s"]
    extras = {
        "restore_latency_p50_ms": round(lat["p50"] * 1e3, 1),
        "restore_latency_p99_ms": round(lat["p99"] * 1e3, 1),
        "restore_latency_max_ms": round(lat["max"] * 1e3, 1),
        "throughput_rows_per_s_min": report["throughput_rows_per_s"]["min"],
        "throughput_rows_per_s_mean": report["throughput_rows_per_s"]["mean"],
        "unrecovered_incidents": report["unrecovered"],
        "incidents": report["n_incidents"],
        "worlds": report["worlds"],
        "soak_wall_s": round(wall_us / 1e6, 1),
    }
    return wall_us, None, {"extras": extras}


def _bench_storage_faults():
    """The storage-fault path as a STANDING bench gate (ISSUE 19): a real
    2-process pool under a deterministic storage-incident schedule — one
    transient-EIO window (``io_flaky``), one bounded ENOSPC window
    (``disk_full``), one on-disk cut corruption (``corrupt_cut``) — plus
    one clean SIGTERM leg as the overhead baseline.  The supervisor
    bit-identity-verifies every recovery against the uninterrupted oracle
    and asserts the storage-specific gates (retries absorbed with the
    exactly-once anchor intact, durability degraded AND resumed, corrupt
    member quarantined with the fallback inside the retention window); any
    failure errors the scenario, which trips the gate.

    Emitted series and gates (``storage_fault_ceilings``):

    - ``io_retry_overhead_ratio`` — clean-leg feed throughput over the
      flaky leg's (both legs the same shape).  The ceiling catches a
      retry path gone pathological (unbounded backoff, a retry storm per
      write), not the bounded handful of deterministic retries the fault
      plan schedules.
    - ``heal_resume_ms_p99`` — wall time of the explicit heal cut that
      closes a durability-degraded window (fault cleared -> cut durable ->
      ``durability_resumed``).  Healing is one snapshot write; the ceiling
      catches it growing into a rebuild.
    - ``lost_updates`` — ceiling 0 BY DESIGN: storage faults never lose
      an update (retries absorb transients, degraded windows keep serving
      from HBM and re-cover on heal, corrupt cuts roll back and re-feed).
    """
    import shutil
    import tempfile

    from tpumetrics.soak.schedule import ChaosSchedule, Incident
    from tpumetrics.soak.supervisor import run_soak

    schedule = ChaosSchedule(
        seed=0, world=2, cut_every=3,
        incidents=(
            Incident(kind="io_flaky", feed=9, world_after=2),
            Incident(kind="disk_full", feed=9, world_after=2),
            Incident(kind="corrupt_cut", feed=9, world_after=2, abrupt=True,
                     target_rank=1),
            Incident(kind="sigterm", feed=9, world_after=2),
        ),
        restore_ceiling_s=60.0,
    )
    root = tempfile.mkdtemp(prefix="tpum_storage_")
    t0 = time.perf_counter()
    try:
        report = run_soak(schedule, root)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    wall_us = (time.perf_counter() - t0) * 1e6
    assert report["unrecovered"] == 0, report  # every storage gate held
    assert report["final"].get("ok") is True, report["final"]
    recs = {r["kind"]: r for r in report["incidents"]}
    clean_tp = recs["sigterm"]["throughput_rows_per_s"]
    flaky_tp = recs["io_flaky"]["throughput_rows_per_s"]
    heal_ms = [
        r["heal_cut_s"] * 1e3 for r in report["incidents"] if "heal_cut_s" in r
    ]
    extras = {
        "io_retry_overhead_ratio": round(clean_tp / max(flaky_tp, 1e-9), 3),
        "heal_resume_ms_p99": round(max(heal_ms), 1),
        "lost_updates": report["lost_batches"],
        "io_retry_events": recs["io_flaky"]["io_retry_events"],
        "degraded_windows": recs["disk_full"]["degraded_events"],
        "quarantined_events": recs["corrupt_cut"]["quarantined_events"],
        "fallback_depth_max": recs["corrupt_cut"]["fallback_depth_max"],
        "soak_wall_s": round(wall_us / 1e6, 1),
    }
    return wall_us, None, {"extras": extras}


def _bench_fleet_resize():
    """The self-scaling fleet loop as a STANDING bench gate (ISSUE 18): a
    hot-tenant wave saturates a 1-rank pool until the fast-burn SLO
    breaches, the autoscaler grows the pool, displaced tenants live-migrate
    to the new ranks, and the submit p99 must RECOVER — with zero lost or
    double-counted updates across every migration.

    Emitted series and gates (``fleet_ceilings``):

    - ``migration_latency_p99_ms`` — p99 wall of every zero-loss handoff
      (window → cut → adopt → commit) the resize performed.  The ceiling
      catches algorithmic blowups (an O(history) cut, a revival instead of
      a spill-file ship), not box noise.
    - ``lost_updates`` — ceiling 0 BY DESIGN: the confusion-matrix row
      total after every migration must equal the rows fed; one lost or
      double-counted row is a zero-loss contract violation, never raise it.
    - ``p99_recovery_ratio`` — recovered-wave p99 / hot-wave p99.  Under
      1.0 means the grown pool actually relieved the saturated rank; the
      ceiling catches a grow that re-routes nothing (rebalance broken) or
      migrations that wedge the new ranks.

    In-scenario asserts: the fast-burn breach fired, the pool grew, at
    least one tenant migrated, and every tenant's ``compute()`` is
    bit-identical to its unmigrated oracle."""
    import tempfile
    from collections import deque

    from tpumetrics.fleet import Autoscaler, AutoscalerPolicy, FleetController
    from tpumetrics.soak.traffic import make_batch, make_metric, oracle_value, values_equal
    from tpumetrics.telemetry.slo import SloEngine, SloRule

    tenants = [f"hot-{i}" for i in range(8)]
    recent = deque(maxlen=256)  # sliding submit-latency window the SLO reads

    def p99_signal():
        if not recent:
            return None
        ordered = sorted(recent)
        return ordered[min(len(ordered) - 1, int(0.99 * len(ordered)))]

    rule = SloRule(
        "submit_p99", p99_signal, objective=5.0, budget=0.01,
        fast_window_s=60.0, slow_window_s=600.0,
        description="fleet submit p99 <= 5ms",
    )
    engine = SloEngine([rule])  # never armed: the bench ticks it manually
    scaler = Autoscaler(
        engine,
        AutoscalerPolicy(min_ranks=1, max_ranks=3, grow_after=2,
                         shrink_after=10_000, cooldown_s=0.0),
    )
    fc = FleetController(
        lambda tid: make_metric(), ranks=1,
        register_kw={"max_queue": 4, "backpressure": "block", "megabatch": False},
        handoff_dir=tempfile.mkdtemp(prefix="tpum_fleet_"),
        autoscaler=scaler, slo=engine,
    )
    fed = {tid: 0 for tid in tenants}

    def wave(rounds):
        # one saturating wave: every tenant fed round-robin against tiny
        # block-policy queues — submit wall time IS the backpressure signal
        lat = []
        for _ in range(rounds):
            for tid in tenants:
                preds, target = make_batch(hash(tid) % 997, fed[tid])
                t0 = time.perf_counter()
                fc.submit(tid, preds, target)
                ms = (time.perf_counter() - t0) * 1e3
                lat.append(ms)
                recent.append(ms)
                fed[tid] += 1
        return lat

    t0 = time.perf_counter()
    reports = []
    try:
        for tid in tenants:
            fc.register(tid)
        wave(2)  # warm the compile caches off the measured waves
        hot = wave(6)
        # manual clock: one tick per 10 simulated seconds until the burn
        # windows fill, the breach latches, and the hysteresis grows the pool
        now, grew = 0.0, False
        for _ in range(8):
            decision, world, moved = fc.autoscale_tick(now)
            reports.extend(moved)
            if decision == "grow":
                grew = True
            if grew and fc.world > 1:
                break
            now += 10.0
        assert engine.violations("submit_p99") >= 1, "fast-burn SLO never breached"
        assert grew and fc.world > 1, f"pool never grew (world={fc.world})"
        assert reports, "grow rebalanced no tenants"
        fc.flush()
        post = wave(6)
        # ---- zero-loss across every migration: bit-identity per tenant
        lost = 0
        for tid in tenants:
            got = fc.compute(tid)
            want = oracle_value(hash(tid) % 997, range(fed[tid]))
            lost += abs(int(want["confmat"].sum()) - int(got["confmat"].sum()))
            assert values_equal(got, want), f"{tid} diverged from unmigrated oracle"
        wall_us = (time.perf_counter() - t0) * 1e6
        hot_p99 = sorted(hot)[int(0.99 * len(hot))]
        post_p99 = sorted(post)[int(0.99 * len(post))]
        lat_sorted = sorted(r.latency_ms for r in reports)
        extras = {
            "hot_p99_ms": round(hot_p99, 3),
            "recovered_p99_ms": round(post_p99, 3),
            "p99_recovery_ratio": round(post_p99 / hot_p99, 4) if hot_p99 else 0.0,
            "migration_latency_p99_ms": round(
                lat_sorted[int(0.99 * len(lat_sorted))], 1
            ),
            "migrations": len(reports),
            "lost_updates": lost,
            "world_after": fc.world,
            "routing_epoch": fc.ring.epoch,
            "grow_decisions": scaler.decisions["grow"],
        }
        return wall_us, None, {"extras": extras}
    finally:
        fc.close(drain=False)
        engine.close()


def _bench_admin_plane():
    """The embedded admin plane (ISSUE 15): scrape latency against a LOADED
    1000-tenant service, plus the inert-predicate discipline — the admin
    server is a pure reader, so "server off" must cost literally nothing on
    the dispatch path (there is no admin hook on submit/dispatch at all),
    and "server on + concurrent scraper" must add ~zero.

    Emitted series and gates (``admin_plane_ceilings``):

    - ``scrape_ms_p99`` — wall time of a real HTTP ``GET /metrics`` against
      the loaded service (1000 tenants × 4 configs, the multitenant soak's
      shape).  The ceiling catches a scrape that synchronizes with the
      device or holds the service lock through a dispatch, not box noise.
    - ``dispatch_overhead_ratio`` — min-over-rounds pairwise ratio of the
      submit+flush wall with a live 4-scrapes/s scraper thread vs without
      the server entirely.  ~1.0 by construction (plus 2-core CPU sharing
      with the renderer); the ceiling catches a scrape path acquiring
      locks the submit path needs.

    In-scenario asserts: every under-load scrape returned 200; at
    quiescence ``GET /metrics`` is byte-identical to ``prometheus_text()``
    (the exposition cannot drift from the library function a validator
    already pins); ``/healthz`` reports 200/ok; ``/statusz`` carries every
    tenant.
    """
    import threading
    import urllib.request

    import jax.numpy as jnp

    from tpumetrics.classification import MulticlassAccuracy
    from tpumetrics.runtime import EvaluationService
    from tpumetrics.telemetry.export import prometheus_text

    T, BATCHES, CONFIGS = 1000, 2, (8, 12, 16, 24)

    batches = {
        classes: (
            jnp.asarray(np.random.default_rng(classes).standard_normal((16, classes), dtype=np.float32)),
            jnp.asarray(np.random.default_rng(classes).integers(0, classes, 16).astype(np.int32)),
        )
        for classes in CONFIGS
    }

    def build(admin):
        svc = EvaluationService(admin_port=0 if admin else None)
        handles = []
        for i in range(T):
            classes = CONFIGS[i % 4]
            m = MulticlassAccuracy(num_classes=classes, average="micro", validate_args=False)
            handles.append((svc.register(f"a{i}", m, buckets=[16]), classes))
        return svc, handles

    def load(handles, svc):
        t0 = time.perf_counter()
        for _ in range(BATCHES):
            for h, classes in handles:
                h.submit(*batches[classes])
        svc.flush()
        return (time.perf_counter() - t0) * 1e6

    def get(url, path):
        with urllib.request.urlopen(url + path, timeout=30) as r:
            return r.status, r.read()

    ratios = []
    scrape_ms: list = []
    on_us = off_us = None
    for _ in range(3):
        # server OFF: the baseline submit+flush wall (no admin plane at all)
        svc, handles = build(admin=False)
        off_us = load(handles, svc)
        svc.close()
        # server ON + live scraper at a 4-scrapes/s cadence
        svc, handles = build(admin=True)
        url = svc.admin.url
        stop = threading.Event()
        statuses: list = []

        def scraper():
            # 4 scrapes/s — already ~60x a default Prometheus cadence; a
            # hotter loop would just measure 2-core CPU contention between
            # the renderer and the submit loop, not the admin plane
            while not stop.is_set():
                t0 = time.perf_counter()
                st, _ = get(url, "/metrics")
                statuses.append(st)
                scrape_ms.append((time.perf_counter() - t0) * 1e3)
                stop.wait(0.25)

        thread = threading.Thread(target=scraper, daemon=True)
        thread.start()
        try:
            on_us = load(handles, svc)
        finally:
            stop.set()
            thread.join(timeout=30)
        ratios.append(on_us / off_us)
        assert statuses and all(st == 200 for st in statuses), (
            f"a scrape failed under load: {statuses[:5]}"
        )
        # quiescent scrapes for the latency series + the identity pin
        for _ in range(10):
            t0 = time.perf_counter()
            st, body = get(url, "/metrics")
            scrape_ms.append((time.perf_counter() - t0) * 1e3)
        assert body.decode() == prometheus_text(), (
            "admin /metrics diverged from prometheus_text() at quiescence"
        )
        st, health = get(url, "/healthz")
        assert st == 200 and json.loads(health)["status"] == "ok", health
        t0 = time.perf_counter()
        st, statusz = get(url, "/statusz")
        statusz_ms = (time.perf_counter() - t0) * 1e3
        tenants = list(json.loads(statusz)["targets"].values())[0]["tenants"]
        assert len(tenants) == T, f"/statusz lost tenants: {len(tenants)}"
        svc.close()

    scrape_sorted = sorted(scrape_ms)

    def pct(p):
        return scrape_sorted[min(len(scrape_sorted) - 1, int(round(p * (len(scrape_sorted) - 1))))]

    overhead_ratio = min(ratios)
    extras = {
        "tenants": T,
        "scrapes": len(scrape_ms),
        "scrape_ms_p50": round(pct(0.50), 3),
        "scrape_ms_p99": round(pct(0.99), 3),
        "scrape_ms_max": round(scrape_sorted[-1], 3),
        "statusz_ms": round(statusz_ms, 3),
        "dispatch_overhead_ratio": round(overhead_ratio, 4),
        "submit_wall_server_on_us": round(on_us, 1),
        "submit_wall_server_off_us": round(off_us, 1),
    }
    return pct(0.99) * 1e3, None, {"extras": extras}


def _check_floors(headline_vs, details):
    """Regression gate (VERDICT r4 weak #4): per-config vs_baseline floors
    live in bench_floors.json; any measured ratio below its floor is a loud
    failure (exit 2) instead of a silently drifting BENCH_r*.json number.
    Configs whose reference side failed (no vs_baseline) are skipped.

    ``wire_bytes_ceilings`` gate the LEDGER-sourced collective payload the
    same way: a config moving more bytes per step than its ceiling (e.g. a
    regression re-registering compute-group members in the fused flush)
    fails loudly."""
    floor_path = os.path.join(_REPO, "bench_floors.json")
    if not os.path.isfile(floor_path):
        return []
    with open(floor_path) as fh:
        gate = json.load(fh)
    floors = gate["floors"]
    ceilings = gate.get("wire_bytes_ceilings", {})
    violations = []
    measured = {"headline": headline_vs}
    for name, entry in details.items():
        if isinstance(entry, dict):
            measured[name] = entry.get("vs_baseline")
    for name, floor in floors.items():
        got = measured.get(name)
        if got is not None and got < floor:
            violations.append(f"{name}: vs_baseline {got} < floor {floor}")
    def check_ceiling(config, key, ceiling, fail_on_error):
        """One ceiling check: details[config][key] must not exceed ceiling;
        an errored scenario entry optionally trips the gate too (its
        invariants never ran)."""
        entry = details.get(config)
        if isinstance(entry, dict):
            got = entry.get(key)
            if got is not None and got > ceiling:
                violations.append(f"{config}: {key} {got} > ceiling {ceiling}")
        elif entry is not None and fail_on_error:
            violations.append(f"{config}: scenario failed ({entry})")

    for name, ceiling in ceilings.items():
        check_ceiling(name, "wire_bytes_per_step", ceiling, fail_on_error=False)
    # resilience ceilings: the inert SyncPolicy guard must stay ~free on the
    # hot path (a predicate check per collective, not a thread or a lock)
    for key, ceiling in gate.get("resilience_overhead_ceilings", {}).items():
        check_ceiling("resilience_overhead", key, ceiling, fail_on_error=True)
    # compile ceilings: a bucketed config recompiling per shape is a regression
    for name, ceiling in gate.get("compile_ceilings", {}).items():
        check_ceiling(name, "streaming_compiles", ceiling, fail_on_error=True)
    # observability ceilings: the disabled span path (the default) must stay
    # ~a flag test, and the always-on instruments must stay cheap enough for
    # the 1000-stream submit path
    for key, ceiling in gate.get("observability_overhead_ceilings", {}).items():
        check_ceiling("observability_overhead", key, ceiling, fail_on_error=True)
    # device-observability ceilings: the in-trace health probe must stay
    # under 5% of step time (ISSUE 14 acceptance) and the armed profile
    # registry's per-dispatch seen check must stay dict-lookup-shaped (an
    # errored scenario also trips — its parity asserts never ran)
    for key, ceiling in gate.get("device_observability_ceilings", {}).items():
        check_ceiling("device_observability", key, ceiling, fail_on_error=True)
    # multi-tenant ceilings: the 1000-stream soak's p99 submit latency must
    # stay enqueue-shaped (an errored scenario also trips the gate — its
    # parity/dedupe asserts never ran)
    for key, ceiling in gate.get("multitenant_ceilings", {}).items():
        check_ceiling("multitenant_scaling", key, ceiling, fail_on_error=True)
    # tenant-lifecycle ceilings: the steady-state HBM watermark must hold
    # under the budget no matter how many tenants are registered, the hot-
    # tenant submit path must stay flat vs the 1k baseline (O(active)
    # scheduling), and revival must stay interactive (an errored scenario
    # also trips — its bit-identity/pristine-start asserts never ran)
    for key, ceiling in gate.get("tenant_lifecycle_ceilings", {}).items():
        check_ceiling("tenant_lifecycle", key, ceiling, fail_on_error=True)
    # admin-plane ceilings: a scrape of the loaded 1000-tenant service must
    # stay reader-cheap (never synchronizing with a dispatch) and a live
    # scraper must add ~zero submit-path overhead — the admin server has no
    # hook on the dispatch path, so "server off" costs nothing by
    # construction (an errored scenario also trips: its identity/health
    # asserts never ran)
    for key, ceiling in gate.get("admin_plane_ceilings", {}).items():
        check_ceiling("admin_plane", key, ceiling, fail_on_error=True)
    # elastic ceilings: the 8->4 fold+reshard restore must stay interactive
    # (a restore that takes minutes would eat the preemption grace window)
    for key, ceiling in gate.get("elastic_restore_ceilings", {}).items():
        check_ceiling("elastic_restore", key, ceiling, fail_on_error=True)
    # analysis ceilings: the static lint pass must stay cheap enough to gate
    # every commit on, and its self-run must stay clean (findings ceiling 0)
    for key, ceiling in gate.get("analysis_runtime_ceilings", {}).items():
        check_ceiling("analysis_runtime", key, ceiling, fail_on_error=True)
    # compile-cache ceilings: a warm (cache-populated) process must restart
    # meaningfully cheaper than a cold one — the preemption/resize payoff
    for key, ceiling in gate.get("compile_cache_ceilings", {}).items():
        check_ceiling("compile_cache_cold_warm", key, ceiling, fail_on_error=True)
    # sharded ceilings: the one-program SPMD step must issue ZERO eager
    # collectives between update() and compute() (the zero-host-round-trip
    # acceptance invariant; the transfer guard inside the scenario covers
    # device->host transfers the same way)
    for key, ceiling in gate.get("sharded_collection_ceilings", {}).items():
        check_ceiling("sharded_collection_8dev", key, ceiling, fail_on_error=True)
    # monitoring ceilings: the quantile sketch's scatter-add ingest must stay
    # cheap per row (the drift/quantile hot path; an errored scenario also
    # trips the gate — its parity/no-retrace asserts never ran)
    for key, ceiling in gate.get("monitoring_ceilings", {}).items():
        check_ceiling("monitoring_window", key, ceiling, fail_on_error=True)

    def check_floor_extra(config, key, floor, fail_on_error):
        """One extras-keyed floor: details[config][key] must not fall BELOW
        floor (the mirror of check_ceiling for scenarios whose headline is
        not a vs_baseline ratio)."""
        entry = details.get(config)
        if isinstance(entry, dict):
            got = entry.get(key)
            if got is not None and got < floor:
                violations.append(f"{config}: {key} {got} < floor {floor}")
        elif entry is not None and fail_on_error:
            violations.append(f"{config}: scenario failed ({entry})")

    # chaos-soak gates: zero unrecovered incidents (by design — an errored
    # scenario means a recovery gate raised mid-soak, which must also trip),
    # bounded per-cycle restore latency, and a structural-stall throughput
    # floor for the feed+cut cadence
    for key, ceiling in gate.get("chaos_soak_ceilings", {}).items():
        check_ceiling("chaos_soak", key, ceiling, fail_on_error=True)
    for key, floor in gate.get("chaos_soak_floors", {}).items():
        check_floor_extra("chaos_soak", key, floor, fail_on_error=True)
    # storage-fault ceilings: the retry path must stay a bounded handful of
    # deterministic backoffs (not a storm), healing a degraded-durability
    # window must stay one snapshot write, and storage faults must lose
    # ZERO updates (an errored scenario also trips — the quarantine/
    # fallback/exactly-once asserts never ran)
    for key, ceiling in gate.get("storage_fault_ceilings", {}).items():
        check_ceiling("storage_faults", key, ceiling, fail_on_error=True)
    # fleet gates: zero lost updates across every live migration (by design
    # — an errored scenario means a zero-loss or bit-identity assert raised
    # mid-resize, which must also trip), bounded handoff latency, and a
    # submit-p99 that actually recovers once the pool grows
    for key, ceiling in gate.get("fleet_ceilings", {}).items():
        check_ceiling("fleet_resize", key, ceiling, fail_on_error=True)
    return violations


def main() -> None:
    _enable_compilation_cache()

    # headline: interleaved min-of-5
    try:
        ref_run = _make_ref_accuracy()
    except Exception:
        ref_run = None
    ours_run, headline_flops = _make_ours_accuracy()
    ours_us, ref_us = _interleaved(ours_run, ref_run, rounds=5)
    vs_baseline = round(ref_us / ours_us, 3) if ref_us is not None else None

    details = {}
    for name, fn in (
        ("collection_sync_8dev", _bench_collection_sync_8dev),
        ("sharded_collection_8dev", _bench_sharded_collection),
        ("map_ragged_update_compute", _bench_map),
        ("fid_stream_update", _bench_fid),
        ("lpips_stream_update", _bench_lpips),
        ("backbone_runtime", _bench_backbone_runtime),
        ("bertscore_ddp_eval", _bench_bertscore_ddp),
        ("fused_collection_update", _bench_fused_collection_update),
        ("compile_cache_cold_warm", _bench_compile_cache_cold_warm),
        ("streaming_throughput", _bench_streaming_throughput),
        ("multitenant_scaling", _bench_multitenant_scaling),
        ("tenant_lifecycle", _bench_tenant_lifecycle),
        ("resilience_overhead", _bench_resilience_overhead),
        ("observability_overhead", _bench_observability_overhead),
        ("device_observability", _bench_device_observability),
        ("admin_plane", _bench_admin_plane),
        ("elastic_restore", _bench_elastic_restore),
        ("monitoring_window", _bench_monitoring_window),
        ("chaos_soak", _bench_chaos_soak),
        ("storage_faults", _bench_storage_faults),
        ("fleet_resize", _bench_fleet_resize),
        ("analysis_runtime", _bench_analysis_runtime),
    ):
        try:
            ours, ref, accounting = fn()
            details[name] = _entry(ours, ref, accounting)
        except Exception as err:  # sub-bench failure must not kill the headline
            details[name] = f"error: {type(err).__name__}: {err}"

    violations = _check_floors(vs_baseline, details)

    print(
        json.dumps(
            {
                "metric": "multiclass_accuracy_update_compute",
                "value": round(ours_us, 2),
                "unit": "us/step",
                "vs_baseline": vs_baseline,
                "details": details,
                "headline_accounting": _accounting(ours_us, flops_per_step=headline_flops),
                "floor_violations": violations,
            }
        )
    )
    if violations:
        for v in violations:
            print(f"FLOOR REGRESSION: {v}", file=sys.stderr)
        sys.exit(2)


if __name__ == "__main__":
    main()
