"""Headline benchmark: metric update+compute latency per step (the hot loop).

Measures the jitted fused update+compute step of ``MulticlassAccuracy`` on a
large batch (BASELINE.md north star: "metric update+sync us/step"), and
compares against the reference TorchMetrics implementation running on torch
(CPU build in this image; the reference has no TPU path at all).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` = reference_us / ours_us (higher is better; >1 means faster
than the reference).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

BATCH = 8192
NUM_CLASSES = 128
STEPS = 50


def _bench_tpumetrics() -> float:
    import jax
    import jax.numpy as jnp

    from tpumetrics.classification import MulticlassAccuracy

    metric = MulticlassAccuracy(num_classes=NUM_CLASSES, average="micro", validate_args=False)

    def step(state, preds, target):
        new_state = metric.functional_update(state, preds, target)
        return new_state, metric.functional_compute(new_state)

    step = jax.jit(step, donate_argnums=(0,))

    rng = np.random.default_rng(0)
    preds = jnp.asarray(rng.standard_normal((BATCH, NUM_CLASSES), dtype=np.float32))
    target = jnp.asarray(rng.integers(0, NUM_CLASSES, size=(BATCH,)), dtype=jnp.int32)

    state = metric.init_state()
    state, val = step(state, preds, target)  # compile
    jax.block_until_ready(val)

    t0 = time.perf_counter()
    for _ in range(STEPS):
        state, val = step(state, preds, target)
    jax.block_until_ready(val)
    t1 = time.perf_counter()
    return (t1 - t0) / STEPS * 1e6  # us/step


def _bench_reference() -> float:
    """Time the reference TorchMetrics MulticlassAccuracy (torch CPU); falls
    back to an equivalent hand-written torch update+compute step when the
    reference's deps (lightning_utilities) are absent."""
    import torch

    rng = np.random.default_rng(0)
    preds = torch.from_numpy(rng.standard_normal((BATCH, NUM_CLASSES), dtype=np.float32))
    target = torch.from_numpy(rng.integers(0, NUM_CLASSES, size=(BATCH,)).astype(np.int64))

    try:
        sys.path.insert(0, "/root/reference/src")
        from torchmetrics.classification import MulticlassAccuracy as RefAccuracy

        metric = RefAccuracy(num_classes=NUM_CLASSES, average="micro", validate_args=False)
        metric.update(preds, target)  # warmup
        metric.compute()
        metric.reset()

        t0 = time.perf_counter()
        for _ in range(STEPS):
            metric.update(preds, target)
            metric._computed = None
            metric.compute()
        t1 = time.perf_counter()
        return (t1 - t0) / STEPS * 1e6  # us/step
    except Exception:
        pass

    # equivalent torch step: argmax -> bincount confusion counts -> micro acc
    def step(tp, total, preds, target):
        labels = preds.argmax(dim=1)
        counts = torch.bincount(target * NUM_CLASSES + labels, minlength=NUM_CLASSES * NUM_CLASSES)
        confmat = counts.reshape(NUM_CLASSES, NUM_CLASSES)
        tp = tp + confmat.diagonal().sum()
        total = total + target.numel()
        return tp, total, tp.float() / total.float()

    tp = torch.zeros((), dtype=torch.long)
    total = torch.zeros((), dtype=torch.long)
    step(tp, total, preds, target)  # warmup
    t0 = time.perf_counter()
    for _ in range(STEPS):
        tp, total, val = step(tp, total, preds, target)
    t1 = time.perf_counter()
    return (t1 - t0) / STEPS * 1e6  # us/step


def main() -> None:
    ours_us = _bench_tpumetrics()
    try:
        ref_us = _bench_reference()
        vs_baseline = round(ref_us / ours_us, 3)
    except Exception:
        vs_baseline = None  # baseline unavailable — not a measured tie
    print(
        json.dumps(
            {
                "metric": "multiclass_accuracy_update_compute",
                "value": round(ours_us, 2),
                "unit": "us/step",
                "vs_baseline": vs_baseline,
            }
        )
    )


if __name__ == "__main__":
    main()
