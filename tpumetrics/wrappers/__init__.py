"""Wrapper metrics (counterpart of reference ``torchmetrics/wrappers``)."""

from tpumetrics.wrappers.abstract import WrapperMetric
from tpumetrics.wrappers.bootstrapping import BootStrapper
from tpumetrics.wrappers.classwise import ClasswiseWrapper
from tpumetrics.wrappers.minmax import MinMaxMetric
from tpumetrics.wrappers.multioutput import MultioutputWrapper
from tpumetrics.wrappers.multitask import MultitaskWrapper
from tpumetrics.wrappers.running import Running
from tpumetrics.wrappers.tracker import MetricTracker

__all__ = [
    "BootStrapper",
    "ClasswiseWrapper",
    "MetricTracker",
    "MinMaxMetric",
    "MultioutputWrapper",
    "MultitaskWrapper",
    "Running",
    "WrapperMetric",
]
