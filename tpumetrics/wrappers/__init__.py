"""Wrapper metrics (counterpart of reference ``torchmetrics/wrappers``)."""

from tpumetrics.wrappers.abstract import WrapperMetric
from tpumetrics.wrappers.running import Running

__all__ = [
    "Running",
    "WrapperMetric",
]
