"""MinMaxMetric (counterpart of reference ``wrappers/minmax.py:29``)."""

from __future__ import annotations

from typing import Any, Dict, Optional, Union

import jax
import jax.numpy as jnp

from tpumetrics.metric import Metric
from tpumetrics.wrappers.abstract import WrapperMetric

Array = jax.Array


class MinMaxMetric(WrapperMetric):
    """Track the running min/max of a metric's compute value.

    The extrema are registered states (``min``/``max`` reduce), so they sync
    across devices and persist through ``state_dict`` — unlike the
    reference's plain attributes (reference minmax.py:51-52). ``forward``
    accumulates into the base metric and returns the refreshed statistics
    (the reference's double-compute forward would silently reset the base
    metric's accumulation, since the wrapper itself holds no batch states).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.wrappers import MinMaxMetric
        >>> from tpumetrics.classification import BinaryAccuracy
        >>> metric = MinMaxMetric(BinaryAccuracy())
        >>> _ = metric(jnp.asarray([1, 0, 1, 1]), jnp.asarray([1, 0, 1, 1]))
        >>> {k: float(v) for k, v in metric.compute().items()}
        {'raw': 1.0, 'max': 1.0, 'min': 1.0}
    """

    full_state_update: Optional[bool] = True

    min_val: Array
    max_val: Array

    def __init__(self, base_metric: Metric, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected base metric to be an instance of `tpumetrics.Metric` but received {base_metric}"
            )
        self._base_metric = base_metric
        self.add_state("min_val", default=jnp.asarray(jnp.inf), dist_reduce_fx="min", persistent=True)
        self.add_state("max_val", default=jnp.asarray(-jnp.inf), dist_reduce_fx="max", persistent=True)

    def update(self, *args: Any, **kwargs: Any) -> None:
        self._base_metric.update(*args, **kwargs)

    def compute(self) -> Dict[str, Array]:
        """{raw, max, min}; the extrema refresh on every compute (reference minmax.py:92-103)."""
        val = self._base_metric.compute()
        if not self._is_suitable_val(val):
            raise RuntimeError(f"Returned value from base metric should be a float or scalar tensor, but got {val}.")
        val = jnp.asarray(val)
        self.max_val = jnp.maximum(self.max_val, val)
        self.min_val = jnp.minimum(self.min_val, val)
        return {"raw": val, "max": self.max_val, "min": self.min_val}

    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, Array]:
        """Accumulate the batch into the base metric and return the
        refreshed running statistics."""
        self.update(*args, **kwargs)
        return self.compute()

    def reset(self) -> None:
        super().reset()
        self._base_metric.reset()

    @staticmethod
    def _is_suitable_val(val: Union[float, Array]) -> bool:
        if isinstance(val, (int, float)):
            return True
        if isinstance(val, (jax.Array, jnp.ndarray)):
            return val.size == 1
        return False

    # ------------------------------------------------------ functional bridge
    # state = {"base": <wrapped state>, "min_val", "max_val"}. The extrema
    # refresh when a value is OBSERVED: ``functional_forward`` returns the
    # refreshed state (the jit-loop analogue of the eager forward), while
    # ``functional_compute`` is a pure read — it reports extrema as-of the
    # current value without persisting them (persist by carrying the state
    # that ``functional_forward`` returns).

    def init_state(self) -> Dict[str, Any]:
        return {
            "base": self._base_metric.init_state(),
            "min_val": jnp.asarray(jnp.inf),
            "max_val": jnp.asarray(-jnp.inf),
        }

    def functional_update(self, state: Dict[str, Any], *args: Any, **kwargs: Any) -> Dict[str, Any]:
        return {**state, "base": self._base_metric.functional_update(state["base"], *args, **kwargs)}

    def functional_compute(self, state: Dict[str, Any], axis_name: Any = None, backend: Any = None) -> Dict[str, Array]:
        val = jnp.asarray(
            self._base_metric.functional_compute(state["base"], axis_name=axis_name, backend=backend)
        )
        return {
            "raw": val,
            "max": jnp.maximum(state["max_val"], val),
            "min": jnp.minimum(state["min_val"], val),
        }

    def functional_forward(
        self, state: Dict[str, Any], *args: Any, axis_name: Any = None, backend: Any = None, **kwargs: Any
    ) -> tuple:
        new_state = self.functional_update(state, *args, **kwargs)
        stats = self.functional_compute(new_state, axis_name=axis_name, backend=backend)
        new_state = {**new_state, "min_val": stats["min"], "max_val": stats["max"]}
        return new_state, stats

    def _sync_state_collect(self, state: Dict[str, Any], backend: Any, reducer: Any, group: Any = None) -> Any:
        h_min = reducer.add(state["min_val"], "min")
        h_max = reducer.add(state["max_val"], "max")
        base_fin = self._base_metric._sync_state_collect(state["base"], backend, reducer, group)
        return lambda: {
            "base": base_fin(),
            "min_val": reducer.result(h_min),
            "max_val": reducer.result(h_max),
        }

    sync_state = Metric.sync_state
