"""MetricTracker (counterpart of reference ``wrappers/tracker.py:31``)."""

from __future__ import annotations

from copy import deepcopy
from typing import Any, List, Union

import jax
import jax.numpy as jnp

from tpumetrics.collections import MetricCollection
from tpumetrics.metric import Metric
from tpumetrics.utils.exceptions import TPUMetricsUserError
from tpumetrics.utils.prints import rank_zero_warn

Array = jax.Array


class MetricTracker:
    """Track a metric (or collection) over a sequence of steps — one clone
    per ``increment()``; ``compute_all``/``best_metric`` summarize history.

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.wrappers import MetricTracker
        >>> from tpumetrics.classification import BinaryAccuracy
        >>> tracker = MetricTracker(BinaryAccuracy())
        >>> for step in range(3):
        ...     tracker.increment()
        ...     tracker.update(jnp.asarray([1, 0, 1, int(step > 0)]), jnp.asarray([1, 0, 1, 1]))
        >>> float(tracker.best_metric())
        1.0
        >>> tracker.n_steps
        3
    """

    def __init__(self, metric: Union[Metric, MetricCollection], maximize: Union[bool, List[bool]] = True) -> None:
        if not isinstance(metric, (Metric, MetricCollection)):
            raise TypeError(
                "Metric arg need to be an instance of a tpumetrics `Metric` or `MetricCollection`"
                f" but got {metric}"
            )
        self._base_metric = metric
        if not isinstance(maximize, (bool, list)):
            raise ValueError("Argument `maximize` should either be a single bool or list of bool")
        if isinstance(maximize, list):
            if not all(isinstance(m, bool) for m in maximize):
                raise ValueError("Argument `maximize` should either be a single bool or list of bool")
            if isinstance(metric, Metric):
                raise ValueError(
                    "Argument `maximize` should be a single bool when `metric` is a single Metric"
                )
            if len(maximize) != len(metric):
                raise ValueError(
                    "The len of argument `maximize` should match the length of the metric collection"
                )
        self.maximize = maximize

        self._steps: List[Union[Metric, MetricCollection]] = []
        self._increment_called = False

    @property
    def n_steps(self) -> int:
        """Number of steps tracked so far."""
        return len(self._steps)

    def increment(self) -> None:
        """Start a fresh tracked step (a new clone of the base metric)."""
        self._increment_called = True
        self._steps.append(deepcopy(self._base_metric))
        self._steps[-1].reset()

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Update the currently tracked step."""
        self._check_for_increment("update")
        self._steps[-1].update(*args, **kwargs)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Forward on the currently tracked step."""
        self._check_for_increment("forward")
        return self._steps[-1](*args, **kwargs)

    __call__ = forward

    def compute(self) -> Any:
        """Compute of the currently tracked step."""
        self._check_for_increment("compute")
        return self._steps[-1].compute()

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        """Plot the tracked history (``compute_all()`` by default; reference
        wrappers/tracker.py:273-311)."""
        from tpumetrics.utils.plot import plot_single_or_multi_val

        val = val if val is not None else self.compute_all()
        return plot_single_or_multi_val(val, ax=ax, name=type(self).__name__)

    def compute_all(self) -> Any:
        """Stacked per-step values (dict of stacks for a collection)."""
        self._check_for_increment("compute_all")
        res = [step.compute() for step in self._steps]
        if isinstance(res[0], dict):
            keys = res[0].keys()
            return {k: jnp.stack([r[k] for r in res]) for k in keys}
        if isinstance(res[0], list):
            return [jnp.stack([r2[i] for r2 in res], 0) for i in range(len(res[0]))]
        return jnp.stack(res, axis=0)

    def reset(self) -> None:
        """Reset the currently tracked step."""
        if self._steps:
            self._steps[-1].reset()

    def reset_all(self) -> None:
        """Reset all tracked steps."""
        for step in self._steps:
            step.reset()

    def best_metric(
        self, return_step: bool = False
    ) -> Any:
        """Best value over all steps (and optionally the step index);
        per-key dicts for a collection (reference tracker.py:186-268)."""
        res = self.compute_all()
        if isinstance(res, list):
            rank_zero_warn(
                "Encountered nested structure. You are probably using a metric collection inside a metric collection,"
                " or a metric wrapper inside a metric collection, which is not supported by `.best_metric()` method."
                " Returning `None` instead."
            )
            return (None, None) if return_step else None

        if isinstance(self._base_metric, Metric):
            fn = jnp.argmax if self.maximize else jnp.argmin
            try:
                idx = int(fn(res, 0))
                value = res[idx]
                if return_step:
                    return float(value), idx
                return float(value)
            except (ValueError, TypeError) as error:
                rank_zero_warn(
                    f"Encountered the following error when trying to get the best metric: {error}"
                    " this is probably due to the 'best' not being defined for this metric."
                    " Returning `None` instead.",
                )
                return (None, None) if return_step else None

        maximize = self.maximize if isinstance(self.maximize, list) else len(res) * [self.maximize]
        value, idx = {}, {}
        for i, (k, v) in enumerate(res.items()):
            try:
                fn = jnp.argmax if maximize[i] else jnp.argmin
                out = int(fn(v, 0))
                value[k], idx[k] = float(v[out]), out
            except (ValueError, TypeError) as error:
                rank_zero_warn(
                    f"Encountered the following error when trying to get the best metric for metric {k}:"
                    f" {error} this is probably due to the 'best' not being defined for this metric."
                    " Returning `None` instead.",
                )
                value[k], idx[k] = None, None
        if return_step:
            return value, idx
        return value

    def _check_for_increment(self, method: str) -> None:
        if not self._increment_called:
            raise TPUMetricsUserError(f"`{method}` cannot be called before `.increment()` has been called.")
