"""MultitaskWrapper (counterpart of reference ``wrappers/multitask.py:29``)."""

from __future__ import annotations

from typing import Any, Dict, Union

import jax

from tpumetrics.collections import MetricCollection
from tpumetrics.metric import Metric
from tpumetrics.wrappers.abstract import WrapperMetric

Array = jax.Array


class MultitaskWrapper(WrapperMetric):
    """Route per-task predictions/targets to per-task metrics.

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.wrappers import MultitaskWrapper
        >>> from tpumetrics.classification import BinaryAccuracy
        >>> from tpumetrics.regression import MeanSquaredError
        >>> metrics = MultitaskWrapper({"Classification": BinaryAccuracy(), "Regression": MeanSquaredError()})
        >>> preds = {"Classification": jnp.asarray([0, 1, 1]), "Regression": jnp.asarray([127.5, 87.1, 25.6])}
        >>> target = {"Classification": jnp.asarray([0, 1, 0]), "Regression": jnp.asarray([120.0, 85.0, 30.0])}
        >>> metrics.update(preds, target)
        >>> {k: round(float(v), 4) for k, v in metrics.compute().items()}
        {'Classification': 0.6667, 'Regression': 26.6733}
    """

    is_differentiable = False

    def __init__(self, task_metrics: Dict[str, Union[Metric, MetricCollection]]) -> None:
        self._check_task_metrics_type(task_metrics)
        super().__init__()
        self.task_metrics = dict(task_metrics)

    @staticmethod
    def _check_task_metrics_type(task_metrics: Dict[str, Union[Metric, MetricCollection]]) -> None:
        if not isinstance(task_metrics, dict):
            raise TypeError(f"Expected argument `task_metrics` to be a dict. Found task_metrics = {task_metrics}")
        for metric in task_metrics.values():
            if not isinstance(metric, (Metric, MetricCollection)):
                raise TypeError(
                    "Expected each task's metric to be a Metric or a MetricCollection. "
                    f"Found a metric of type {type(metric)}"
                )

    def update(self, task_preds: Dict[str, Array], task_targets: Dict[str, Array]) -> None:
        """Route each task's batch to its metric."""
        if not self.task_metrics.keys() == task_preds.keys() == task_targets.keys():
            raise ValueError(
                "Expected arguments `task_preds` and `task_targets` to have the same keys as the wrapped"
                f" `task_metrics`. Found task_preds.keys() = {task_preds.keys()},"
                f" task_targets.keys() = {task_targets.keys()}"
                f" and self.task_metrics.keys() = {self.task_metrics.keys()}"
            )
        for task_name, metric in self.task_metrics.items():
            metric.update(task_preds[task_name], task_targets[task_name])

    def compute(self) -> Dict[str, Any]:
        return {task_name: metric.compute() for task_name, metric in self.task_metrics.items()}

    def forward(self, task_preds: Dict[str, Array], task_targets: Dict[str, Array]) -> Dict[str, Any]:
        """Per-task forwards; each inner metric accumulates itself."""
        return {
            task_name: metric(task_preds[task_name], task_targets[task_name])
            for task_name, metric in self.task_metrics.items()
        }

    def reset(self) -> None:
        for metric in self.task_metrics.values():
            metric.reset()
        super().reset()

    # ------------------------------------------------------ functional bridge
    # per-task child states as one pytree, so the whole wrapper rides
    # jit/shard_map like any Metric (children's own functional bridges do
    # the work; a task mapped to a MetricCollection nests its state dict)

    def init_state(self) -> Dict[str, Any]:
        return {name: m.init_state() for name, m in self.task_metrics.items()}

    def functional_update(
        self, state: Dict[str, Any], task_preds: Dict[str, Array], task_targets: Dict[str, Array]
    ) -> Dict[str, Any]:
        if not self.task_metrics.keys() == task_preds.keys() == task_targets.keys():
            raise ValueError(
                "Expected arguments `task_preds` and `task_targets` to have the same keys as the"
                f" wrapped `task_metrics`. Found task_preds.keys() = {task_preds.keys()},"
                f" task_targets.keys() = {task_targets.keys()}"
                f" and self.task_metrics.keys() = {self.task_metrics.keys()}"
            )
        return {
            name: m.functional_update(state[name], task_preds[name], task_targets[name])
            for name, m in self.task_metrics.items()
        }

    def functional_compute(self, state: Dict[str, Any], axis_name: Any = None, backend: Any = None) -> Dict[str, Any]:
        out = {}
        for name, m in self.task_metrics.items():
            if isinstance(m, Metric):
                out[name] = m.functional_compute(state[name], axis_name=axis_name, backend=backend)
            else:  # MetricCollection's bridge takes axis_name only; an
                # explicit backend syncs its whole state first — unless
                # axis_name is also given, where axis wins (mirroring
                # Metric.functional_compute, which replaces `backend` with
                # AxisBackend(axis_name)); syncing with both would merge the
                # collection task's states twice, inflating sum states by
                # world_size while Metric tasks sync once (ADVICE r5 #1)
                task_state = (
                    m.sync_states(state[name], backend)
                    if backend is not None and axis_name is None
                    else state[name]
                )
                out[name] = m.functional_compute(task_state, axis_name=axis_name)
        return out

    def _sync_state_collect(self, state: Dict[str, Any], backend: Any, reducer: Any, group: Any = None) -> Any:
        from tpumetrics.telemetry import ledger as _telemetry

        finalizers = {}
        for name, m in self.task_metrics.items():
            with _telemetry.attribution(name):
                finalizers[name] = m._sync_state_collect(state[name], backend, reducer, group)
        return lambda: {name: fin() for name, fin in finalizers.items()}

    sync_state = Metric.sync_state

    def functional_forward(
        self,
        state: Dict[str, Any],
        task_preds: Dict[str, Array],
        task_targets: Dict[str, Array],
        axis_name: Any = None,
        backend: Any = None,
    ) -> tuple:
        new_state = self.functional_update(state, task_preds, task_targets)
        batch_state = self.functional_update(self.init_state(), task_preds, task_targets)
        return new_state, self.functional_compute(batch_state, axis_name=axis_name, backend=backend)
