"""Sliding-window wrapper metric.

Counterpart of reference ``wrappers/running.py:27-135``: keeps ``window``
copies of the wrapped metric's state (one per recent step) and computes the
metric over their merge. Requires ``full_state_update=False`` on the wrapped
metric.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import jax

from tpumetrics.metric import Metric
from tpumetrics.wrappers.abstract import WrapperMetric

Array = jax.Array


class Running(WrapperMetric):
    """Compute a metric over a running window of the last ``window`` updates.

    ``forward`` still returns the current-batch value; ``compute`` returns the
    windowed value. Memory grows linearly with ``window`` (one state clone per
    slot — reference running.py:103-107).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.wrappers import Running
        >>> from tpumetrics.aggregation import SumMetric
        >>> metric = Running(SumMetric(), window=3)
        >>> for i in range(6):
        ...     _ = metric.update(jnp.asarray([float(i)]))
        >>> float(metric.compute())  # 3 + 4 + 5
        12.0
    """

    def __init__(self, base_metric: Metric, window: int = 5) -> None:
        super().__init__()
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected argument `metric` to be an instance of `tpumetrics.Metric` but got {base_metric}"
            )
        if not (isinstance(window, int) and window > 0):
            raise ValueError(f"Expected argument `window` to be a positive integer but got {window}")
        self.base_metric = base_metric
        self.window = window
        if base_metric.full_state_update is not False:
            raise ValueError(
                f"Expected attribute `full_state_update` set to `False` but got {base_metric.full_state_update}"
            )
        self._num_vals_seen = 0

        for key in base_metric._defaults:
            for i in range(window):
                self.add_state(
                    name=f"{key}_{i}",
                    default=base_metric._defaults[key],
                    dist_reduce_fx=base_metric._reductions[key],
                )

    def _store_slot(self) -> None:
        slot = self._num_vals_seen % self.window
        for key in self.base_metric._defaults:
            setattr(self, f"{key}_{slot}", getattr(self.base_metric, key))

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Update the wrapped metric, snapshot its state into the current slot, reset it."""
        self.base_metric.update(*args, **kwargs)
        self._store_slot()
        self.base_metric.reset()
        self._num_vals_seen += 1

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Forward to the wrapped metric (batch value) and snapshot state."""
        res = self.base_metric.forward(*args, **kwargs)
        self._store_slot()
        self.base_metric.reset()
        self._num_vals_seen += 1
        self._computed = None
        return res

    def compute(self) -> Any:
        """Merge all window slots into the wrapped metric and compute."""
        for i in range(self.window):
            self.base_metric._reduce_states(
                {key: getattr(self, f"{key}_{i}") for key in self.base_metric._defaults}
            )
        # make sure the inner compute does not warn about a missing update
        self.base_metric._update_count = max(self._num_vals_seen, 1)
        val = self.base_metric.compute()
        self.base_metric.reset()
        return val

    def reset(self) -> None:
        super().reset()
        self.base_metric.reset()
        self._num_vals_seen = 0

    def plot(self, val: Optional[Union[Array, Sequence[Array]]] = None, ax: Any = None) -> Any:
        return self._plot(val, ax)
