"""Abstract base for wrapper metrics.

Counterpart of reference ``wrappers/abstract.py:19`` — wrapper metrics
forward all calls to the wrapped metric, which owns sync/counter logic, so
the default update/compute wrapping is disabled here.
"""

from __future__ import annotations

from typing import Any, Callable

from tpumetrics.metric import Metric


class WrapperMetric(Metric):
    """Base class for metrics that wrap other metrics.

    The wrapped metric handles synchronization and bookkeeping; this base
    disables the outer wrapping so it doesn't run twice.
    """

    def _wrap_update(self, update: Callable) -> Callable:
        return update

    def _wrap_compute(self, compute: Callable) -> Callable:
        return compute

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Each wrapper defines its own forward protocol."""
        raise NotImplementedError

    # ------------------------------------------------------ functional bridge
    # Wrapper state lives in the wrapped children, not in registered states,
    # so the base Metric bridge (which borrows registered states only) would
    # silently mutate children while returning an empty pytree. Wrappers
    # with coherent pure semantics (Classwise/Multioutput/Multitask/MinMax,
    # CompositionalMetric) override the whole bridge; the rest — resampling
    # (BootStrapper), windowing (Running), compute-call bookkeeping
    # (MetricTracker) — are order/RNG-dependent by design and fail loudly.

    def _no_functional_bridge(self) -> None:
        from tpumetrics.metric import TPUMetricsUserError

        raise TPUMetricsUserError(
            f"{type(self).__name__} does not support the functional/jit bridge: its state"
            " lives in wrapped child metrics with order- or sampling-dependent update"
            " semantics. Use the eager API (update/compute), or wrap with a bridge-capable"
            " wrapper (ClasswiseWrapper, MultioutputWrapper, MultitaskWrapper, MinMaxMetric)."
        )

    def init_state(self) -> Any:
        self._no_functional_bridge()

    def functional_update(self, state: Any, *args: Any, **kwargs: Any) -> Any:
        self._no_functional_bridge()

    def functional_compute(self, state: Any, axis_name: Any = None, backend: Any = None) -> Any:
        self._no_functional_bridge()

    def functional_forward(self, state: Any, *args: Any, **kwargs: Any) -> Any:
        self._no_functional_bridge()

    def sync_state(self, state: Any, backend: Any) -> Any:
        self._no_functional_bridge()

    def _sync_state_collect(self, state: Any, backend: Any, reducer: Any, group: Any = None) -> Any:
        self._no_functional_bridge()
