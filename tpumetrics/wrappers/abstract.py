"""Abstract base for wrapper metrics.

Counterpart of reference ``wrappers/abstract.py:19`` — wrapper metrics
forward all calls to the wrapped metric, which owns sync/counter logic, so
the default update/compute wrapping is disabled here.
"""

from __future__ import annotations

from typing import Any, Callable

from tpumetrics.metric import Metric


class WrapperMetric(Metric):
    """Base class for metrics that wrap other metrics.

    The wrapped metric handles synchronization and bookkeeping; this base
    disables the outer wrapping so it doesn't run twice.
    """

    def _wrap_update(self, update: Callable) -> Callable:
        return update

    def _wrap_compute(self, compute: Callable) -> Callable:
        return compute

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Each wrapper defines its own forward protocol."""
        raise NotImplementedError
