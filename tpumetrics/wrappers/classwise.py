"""ClasswiseWrapper (counterpart of reference ``wrappers/classwise.py:27``)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax

from tpumetrics.metric import Metric
from tpumetrics.wrappers.abstract import WrapperMetric

Array = jax.Array


class ClasswiseWrapper(WrapperMetric):
    """Explode a per-class metric output into a labeled dict.

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.wrappers import ClasswiseWrapper
        >>> from tpumetrics.classification import MulticlassAccuracy
        >>> metric = ClasswiseWrapper(MulticlassAccuracy(num_classes=3, average=None), labels=["horse", "fish", "dog"])
        >>> preds = jnp.asarray([0, 1, 2, 1, 0, 2])
        >>> target = jnp.asarray([0, 1, 1, 1, 0, 0])
        >>> out = metric(preds, target)
        >>> sorted(out.keys())
        ['multiclassaccuracy_dog', 'multiclassaccuracy_fish', 'multiclassaccuracy_horse']
    """

    def __init__(
        self,
        metric: Metric,
        labels: Optional[List[str]] = None,
        prefix: Optional[str] = None,
        postfix: Optional[str] = None,
    ) -> None:
        super().__init__()
        if not isinstance(metric, Metric):
            raise ValueError(f"Expected argument `metric` to be an instance of `tpumetrics.Metric` but got {metric}")
        self.metric = metric
        if labels is not None and not (isinstance(labels, list) and all(isinstance(lab, str) for lab in labels)):
            raise ValueError(f"Expected argument `labels` to either be `None` or a list of strings but got {labels}")
        self.labels = labels
        if prefix is not None and not isinstance(prefix, str):
            raise ValueError(f"Expected argument `prefix` to either be `None` or a string but got {prefix}")
        self._prefix = prefix
        if postfix is not None and not isinstance(postfix, str):
            raise ValueError(f"Expected argument `postfix` to either be `None` or a string but got {postfix}")
        self._postfix = postfix
        self._update_count = 1

    def _convert(self, x: Array) -> Dict[str, Array]:
        """Split a per-class vector into a labeled dict (reference classwise.py:109-120)."""
        if not self._prefix and not self._postfix:
            prefix = f"{self.metric.__class__.__name__.lower()}_"
            postfix = ""
        else:
            prefix = self._prefix or ""
            postfix = self._postfix or ""
        if self.labels is None:
            return {f"{prefix}{i}{postfix}": val for i, val in enumerate(x)}
        if len(self.labels) != len(x):
            raise ValueError(
                f"Expected argument `labels` to have {len(x)} entries (one per class in the wrapped"
                f" metric's output), but got {len(self.labels)}"
            )
        return {f"{prefix}{lab}{postfix}": val for lab, val in zip(self.labels, x)}

    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, Array]:
        return self._convert(self.metric(*args, **kwargs))

    def update(self, *args: Any, **kwargs: Any) -> None:
        self.metric.update(*args, **kwargs)

    def compute(self) -> Dict[str, Array]:
        return self._convert(self.metric.compute())

    def reset(self) -> None:
        self.metric.reset()

    def _filter_kwargs(self, **kwargs: Any) -> Dict[str, Any]:
        return self.metric._filter_kwargs(**kwargs)

    # ------------------------------------------------------ functional bridge
    # pure delegation: the wrapper's state IS the wrapped metric's state;
    # only the compute output gains the labeled-dict conversion

    def init_state(self) -> Dict[str, Any]:
        return self.metric.init_state()

    def functional_update(self, state: Dict[str, Any], *args: Any, **kwargs: Any) -> Dict[str, Any]:
        return self.metric.functional_update(state, *args, **kwargs)

    def functional_compute(self, state: Dict[str, Any], axis_name: Any = None, backend: Any = None) -> Dict[str, Array]:
        return self._convert(self.metric.functional_compute(state, axis_name=axis_name, backend=backend))

    def _sync_state_collect(self, state: Dict[str, Any], backend: Any, reducer: Any, group: Any = None) -> Any:
        return self.metric._sync_state_collect(state, backend, reducer, group)

    # generic implementations work once the pieces above exist
    functional_forward = Metric.functional_forward
    sync_state = Metric.sync_state
