"""BootStrapper (counterpart of reference ``wrappers/bootstrapping.py:54``)."""

from __future__ import annotations

from copy import deepcopy
from typing import Any, Dict, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from tpumetrics.metric import Metric
from tpumetrics.wrappers.abstract import WrapperMetric

Array = jax.Array


def _bootstrap_sampler(size: int, sampling_strategy: str = "poisson", rng: Optional[np.random.Generator] = None) -> np.ndarray:
    """Resample indices 0..size-1 with replacement (reference bootstrapping.py:31-51)."""
    rng = rng or np.random.default_rng()
    if sampling_strategy == "poisson":
        n = rng.poisson(1.0, size=size)
        return np.repeat(np.arange(size), n)
    if sampling_strategy == "multinomial":
        return rng.integers(0, size, size=size)
    raise ValueError("Unknown sampling strategy")


class BootStrapper(WrapperMetric):
    """Bootstrapped confidence statistics of any metric: ``num_bootstraps``
    copies each fed an index-resampled view of every update batch
    (reference bootstrapping.py:54-212).

    Args:
        base_metric: metric instance to bootstrap.
        num_bootstraps: number of resampled copies.
        mean/std/quantile/raw: which statistics ``compute`` returns.
        sampling_strategy: ``multinomial`` (default — exact batch-level
            bootstrap with fixed-size index arrays, so each inner metric's
            jitted update compiles once) or ``poisson`` (the reference's
            default; its resample length varies per draw, forcing an XLA
            recompile of the inner update on almost every call — use it only
            for strict reference parity or eager metrics).
        seed: optional seed for the resampling generator (TPU extension —
            the reference draws from the global torch RNG).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.wrappers import BootStrapper
        >>> from tpumetrics.classification import MulticlassAccuracy
        >>> metric = BootStrapper(MulticlassAccuracy(num_classes=5), num_bootstraps=20, seed=42)
        >>> preds = jnp.asarray([0, 1, 2, 3, 4, 0, 1, 2, 3, 4])
        >>> target = jnp.asarray([0, 1, 2, 3, 4, 0, 0, 0, 0, 0])
        >>> metric.update(preds, target)
        >>> out = metric.compute()
        >>> sorted(out.keys())
        ['mean', 'std']
    """

    full_state_update: Optional[bool] = True

    def __init__(
        self,
        base_metric: Metric,
        num_bootstraps: int = 10,
        mean: bool = True,
        std: bool = True,
        quantile: Optional[Union[float, Sequence[float]]] = None,
        raw: bool = False,
        sampling_strategy: str = "multinomial",
        seed: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected base metric to be an instance of tpumetrics.Metric but received {base_metric}"
            )
        self.metrics = [deepcopy(base_metric) for _ in range(num_bootstraps)]
        self.num_bootstraps = num_bootstraps
        self.mean = mean
        self.std = std
        self.quantile = quantile
        self.raw = raw
        allowed_sampling = ("poisson", "multinomial")
        if sampling_strategy not in allowed_sampling:
            raise ValueError(
                f"Expected argument ``sampling_strategy`` to be one of {allowed_sampling}"
                f" but received {sampling_strategy}"
            )
        self.sampling_strategy = sampling_strategy
        self._rng = np.random.default_rng(seed)

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Resample every array input along dim 0, once per bootstrap copy."""
        sizes = [len(a) for a in args if isinstance(a, (jax.Array, jnp.ndarray))]
        sizes += [len(v) for v in kwargs.values() if isinstance(v, (jax.Array, jnp.ndarray))]
        if not sizes:
            raise ValueError("None of the input contained tensors, so could not determine the sampling size")
        size = sizes[0]

        def _select(x: Any, idx: Array) -> Any:
            return jnp.take(x, idx, axis=0) if isinstance(x, (jax.Array, jnp.ndarray)) else x

        for idx in range(self.num_bootstraps):
            sample_idx = _bootstrap_sampler(size, self.sampling_strategy, self._rng)
            if sample_idx.size == 0:
                continue
            sample = jnp.asarray(sample_idx)
            new_args = tuple(_select(a, sample) for a in args)
            new_kwargs = {k: _select(v, sample) for k, v in kwargs.items()}
            self.metrics[idx].update(*new_args, **new_kwargs)

    def compute(self) -> Dict[str, Array]:
        """mean/std/quantile/raw over the bootstrap copies (reference :162-181)."""
        computed_vals = jnp.stack([m.compute() for m in self.metrics], axis=0)
        output_dict: Dict[str, Array] = {}
        if self.mean:
            output_dict["mean"] = computed_vals.mean(axis=0)
        if self.std:
            output_dict["std"] = computed_vals.std(axis=0, ddof=1)
        if self.quantile is not None:
            output_dict["quantile"] = jnp.quantile(computed_vals, jnp.asarray(self.quantile), axis=0)
        if self.raw:
            output_dict["raw"] = computed_vals
        return output_dict

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Update with resampling and return the current statistics."""
        self.update(*args, **kwargs)
        return self.compute()

    def reset(self) -> None:
        for m in self.metrics:
            m.reset()
        super().reset()
