"""MultioutputWrapper (counterpart of reference ``wrappers/multioutput.py:43``)."""

from __future__ import annotations

from copy import deepcopy
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp

from tpumetrics.metric import Metric
from tpumetrics.wrappers.abstract import WrapperMetric

Array = jax.Array


def _get_nan_indices(*tensors: Array) -> Array:
    """Rows where any tensor has a NaN (reference multioutput.py:26-40)."""
    if len(tensors) == 0:
        raise ValueError("Must pass at least one tensor as argument")
    nan_idxs = jnp.zeros(len(tensors[0]), dtype=bool)
    for tensor in tensors:
        permuted = tensor.reshape(len(tensor), -1)
        nan_idxs = nan_idxs | jnp.isnan(permuted).any(axis=1)
    return nan_idxs


class MultioutputWrapper(WrapperMetric):
    """One inner metric clone per output column (e.g. multi-target R2).

    ``remove_nans`` drops rows containing NaN before each inner update —
    data-dependent shapes, so the wrapper is eager-only by design (the inner
    metrics may still jit their own math).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.wrappers import MultioutputWrapper
        >>> from tpumetrics.regression import R2Score
        >>> target = jnp.asarray([[0.5, 1.0], [-1.0, 1.0], [7.0, -6.0]])
        >>> preds = jnp.asarray([[0.25, 0.5], [-1.0, 1.0], [8.0, -5.0]])
        >>> r2 = MultioutputWrapper(R2Score(), num_outputs=2)
        >>> r2.update(preds, target)
        >>> [round(float(x), 4) for x in r2.compute()]
        [0.9706, 0.9617]
    """

    is_differentiable = False

    def __init__(
        self,
        base_metric: Metric,
        num_outputs: int,
        output_dim: int = -1,
        remove_nans: bool = True,
        squeeze_outputs: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.metrics = [deepcopy(base_metric) for _ in range(num_outputs)]
        self.output_dim = output_dim
        self.remove_nans = remove_nans
        self.squeeze_outputs = squeeze_outputs

    def _get_args_kwargs_by_output(self, *args: Array, **kwargs: Array) -> List[Tuple]:
        """Slice every array input down to one output column (reference :100-124)."""
        args_kwargs_by_output = []
        for i in range(len(self.metrics)):
            def _select(x: Any) -> Any:
                if isinstance(x, (jax.Array, jnp.ndarray)):
                    return jnp.take(x, jnp.asarray([i]), axis=self.output_dim)
                return x

            selected_args = [_select(a) for a in args]
            selected_kwargs = {k: _select(v) for k, v in kwargs.items()}
            if self.remove_nans:
                args_kwargs = tuple(selected_args) + tuple(selected_kwargs.values())
                nan_idxs = _get_nan_indices(*args_kwargs)
                selected_args = [arg[~nan_idxs] for arg in selected_args]
                selected_kwargs = {k: v[~nan_idxs] for k, v in selected_kwargs.items()}
            if self.squeeze_outputs:
                selected_args = [jnp.squeeze(arg, axis=self.output_dim) for arg in selected_args]
                selected_kwargs = {k: jnp.squeeze(v, axis=self.output_dim) for k, v in selected_kwargs.items()}
            args_kwargs_by_output.append((selected_args, selected_kwargs))
        return args_kwargs_by_output

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Route each output column into its inner clone."""
        reshaped_args_kwargs = self._get_args_kwargs_by_output(*args, **kwargs)
        for metric, (selected_args, selected_kwargs) in zip(self.metrics, reshaped_args_kwargs):
            metric.update(*selected_args, **selected_kwargs)

    def compute(self) -> Array:
        """Stacked per-output results."""
        return jnp.stack([m.compute() for m in self.metrics], 0)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Per-output forwards, stacked (accumulates inner state like update)."""
        reshaped_args_kwargs = self._get_args_kwargs_by_output(*args, **kwargs)
        results = [
            metric(*selected_args, **selected_kwargs)
            for metric, (selected_args, selected_kwargs) in zip(self.metrics, reshaped_args_kwargs)
        ]
        if results[0] is None:
            return None
        return jnp.stack(results, 0)

    def reset(self) -> None:
        for metric in self.metrics:
            metric.reset()
        super().reset()

    def _filter_kwargs(self, **kwargs: Any) -> Dict[str, Any]:
        return self.metrics[0]._filter_kwargs(**kwargs)

    # ------------------------------------------------------ functional bridge
    # a list of per-output child states; requires remove_nans=False (NaN-row
    # removal is data-dependent boolean indexing — untraceable)

    def _require_traceable(self) -> None:
        if self.remove_nans:
            from tpumetrics.metric import TPUMetricsUserError

            raise TPUMetricsUserError(
                "MultioutputWrapper's functional/jit bridge requires remove_nans=False:"
                " NaN-row removal selects a data-dependent number of rows, which cannot"
                " be traced. Construct with remove_nans=False (and pre-filter NaNs"
                " outside the compiled step if needed)."
            )

    def init_state(self) -> List[Any]:
        self._require_traceable()
        return [m.init_state() for m in self.metrics]

    def functional_update(self, state: List[Any], *args: Any, **kwargs: Any) -> List[Any]:
        self._require_traceable()
        reshaped = self._get_args_kwargs_by_output(*args, **kwargs)
        return [
            m.functional_update(st, *sel_args, **sel_kwargs)
            for m, st, (sel_args, sel_kwargs) in zip(self.metrics, state, reshaped)
        ]

    def functional_compute(self, state: List[Any], axis_name: Any = None, backend: Any = None) -> Array:
        return jnp.stack(
            [
                m.functional_compute(st, axis_name=axis_name, backend=backend)
                for m, st in zip(self.metrics, state)
            ],
            0,
        )

    def _sync_state_collect(self, state: List[Any], backend: Any, reducer: Any, group: Any = None) -> Any:
        finalizers = [
            m._sync_state_collect(st, backend, reducer, group) for m, st in zip(self.metrics, state)
        ]
        return lambda: [fin() for fin in finalizers]

    # generic implementations work once the pieces above exist
    functional_forward = Metric.functional_forward
    sync_state = Metric.sync_state
