__version__ = "0.1.0dev"
__author__ = "tpumetrics contributors"
__license__ = "Apache-2.0"
