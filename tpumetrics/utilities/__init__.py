"""Migration alias: ``tpumetrics.utilities`` == :mod:`tpumetrics.utils`.

The reference exposes its utility surface at ``torchmetrics.utilities``
(reference ``src/torchmetrics/utilities/__init__.py:1-40``); this package
mirrors that import path so code migrating from the reference keeps working
unchanged::

    >>> from tpumetrics.utilities.data import METRIC_EPS, dim_zero_cat
    >>> from tpumetrics.utilities import rank_zero_warn, class_reduce

Every submodule here *is* the corresponding :mod:`tpumetrics.utils` module
(identical object, registered in ``sys.modules``), so there is exactly one
implementation and no drift between the two names.  The top-level package
itself is a namespace mirror: it re-exports ``tpumetrics.utils.__all__``.
"""

import importlib as _importlib
import importlib.abc as _importlib_abc
import importlib.util as _importlib_util
import pkgutil as _pkgutil
import sys as _sys

import tpumetrics.utils as _utils
from tpumetrics.utils import *  # noqa: F401,F403
from tpumetrics.utils import __all__ as __all__  # noqa: PLC0414

_SUBMODULES = tuple(
    info.name for info in _pkgutil.iter_modules(_utils.__path__) if not info.ispkg
)

for _name in _SUBMODULES:
    _mod = _importlib.import_module(f"tpumetrics.utils.{_name}")
    _sys.modules[f"{__name__}.{_name}"] = _mod
    globals()[_name] = _mod
del _name, _mod


class _UtilitiesAliasFinder(_importlib_abc.MetaPathFinder):
    """Resolve ``find_spec('tpumetrics.utilities.<sub>')`` probes.

    ``importlib.util.find_spec`` checks ``sys.modules`` *before* importing the
    parent package, so availability probes in a fresh process would otherwise
    see ``None`` (no ``<sub>.py`` exists on disk under ``utilities/``).  This
    finder answers with the real :mod:`tpumetrics.utils` submodule's spec.
    """

    _prefix = __name__ + "."

    def find_spec(self, fullname, path=None, target=None):
        if not fullname.startswith(self._prefix):
            return None
        sub = fullname[len(self._prefix) :]
        if sub not in _SUBMODULES:
            return None
        spec = _importlib_util.find_spec(f"tpumetrics.utils.{sub}")
        if spec is None:
            return None
        # Serve a spec whose identity matches the REQUESTED name: returning
        # the tpumetrics.utils spec unchanged breaks the identical-object
        # guarantee on any path that actually executes the spec (e.g.
        # importlib.reload of an alias module after sys.modules surgery),
        # producing a module whose __name__/__spec__.name disagree with its
        # sys.modules key.  File loaders also name-check exec_module, so the
        # loader is re-instantiated under the alias name.
        import copy as _copy

        alias_spec = _copy.copy(spec)
        alias_spec.name = fullname
        loader = getattr(spec, "loader", None)
        loader_path = getattr(loader, "path", None) or spec.origin
        if loader is not None and loader_path:
            try:
                alias_spec.loader = type(loader)(fullname, loader_path)
            except TypeError:
                pass  # exotic loader signature: keep the original loader
        return alias_spec


if not any(isinstance(f, _UtilitiesAliasFinder) for f in _sys.meta_path):
    _sys.meta_path.append(_UtilitiesAliasFinder())
