"""MeanSquaredLogError (counterpart of reference ``regression/log_mse.py``)."""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from tpumetrics.functional.regression.log_mse import (
    _mean_squared_log_error_compute,
    _mean_squared_log_error_update,
)
from tpumetrics.metric import Metric

Array = jax.Array


class MeanSquaredLogError(Metric):
    """MSLE (reference regression/log_mse.py:26).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.regression import MeanSquaredLogError
        >>> metric = MeanSquaredLogError()
        >>> metric.update(jnp.asarray([0., 1, 2, 3]), jnp.asarray([0., 1, 2, 2]))
        >>> round(float(metric.compute()), 4)
        0.0207
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0

    sum_squared_log_error: Array
    total: Array

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_squared_log_error", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros((), dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_squared_log_error, num_obs = _mean_squared_log_error_update(preds, target)
        self.sum_squared_log_error = self.sum_squared_log_error + sum_squared_log_error
        self.total = self.total + num_obs

    def compute(self) -> Array:
        return _mean_squared_log_error_compute(self.sum_squared_log_error, self.total)

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        return self._plot(val, ax)
