"""MinkowskiDistance (counterpart of reference ``regression/minkowski.py``)."""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from tpumetrics.functional.regression.minkowski import (
    _minkowski_distance_compute,
    _minkowski_distance_update,
)
from tpumetrics.metric import Metric
from tpumetrics.utils.exceptions import TPUMetricsUserError

Array = jax.Array


class MinkowskiDistance(Metric):
    """Minkowski distance of order p (reference regression/minkowski.py:25).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.regression import MinkowskiDistance
        >>> metric = MinkowskiDistance(p=3)
        >>> metric.update(jnp.asarray([0., 1, 2, 3]), jnp.asarray([0., 2, 3, 1]))
        >>> round(float(metric.compute()), 4)
        2.1544
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0

    minkowski_dist_sum: Array

    def __init__(self, p: float, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(p, (float, int)) and p >= 1):
            raise TPUMetricsUserError(f"Argument ``p`` must be a float or int greater than 1, but got {p}")
        self.p = p
        self.add_state("minkowski_dist_sum", jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, preds: Array, targets: Array) -> None:
        self.minkowski_dist_sum = self.minkowski_dist_sum + _minkowski_distance_update(preds, targets, self.p)

    def compute(self) -> Array:
        return _minkowski_distance_compute(self.minkowski_dist_sum, self.p)

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        return self._plot(val, ax)
