"""KendallRankCorrCoef (counterpart of reference ``regression/kendall.py``)."""

from __future__ import annotations

from typing import Any, List, Optional

import jax

from tpumetrics.functional.regression.kendall import (
    _ALLOWED_ALTERNATIVES,
    _ALLOWED_VARIANTS,
    kendall_rank_corrcoef,
)
from tpumetrics.metric import Metric
from tpumetrics.utils.data import dim_zero_cat

Array = jax.Array


class KendallRankCorrCoef(Metric):
    """Kendall's tau (reference regression/kendall.py:30).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.regression import KendallRankCorrCoef
        >>> metric = KendallRankCorrCoef()
        >>> metric.update(jnp.asarray([2.5, 1.0, 4.0, 3.0]), jnp.asarray([3.0, 2.0, 1.0, 4.0]))
        >>> round(float(metric.compute()), 4)
        0.0
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update = True
    plot_lower_bound: float = -1.0
    plot_upper_bound: float = 1.0

    preds: List[Array]
    target: List[Array]

    def __init__(
        self,
        variant: str = "b",
        t_test: bool = False,
        alternative: Optional[str] = "two-sided",
        num_outputs: int = 1,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if variant not in _ALLOWED_VARIANTS:
            raise ValueError(f"Argument `variant` is expected to be one of {_ALLOWED_VARIANTS}, but got {variant!r}")
        if not isinstance(t_test, bool):
            raise ValueError(f"Argument `t_test` is expected to be of a type `bool`, but got {t_test}.")
        if t_test and alternative is None:
            raise ValueError("Argument `alternative` is required if `t_test=True` but got `None`.")
        if alternative not in _ALLOWED_ALTERNATIVES:
            raise ValueError(
                f"Argument `alternative` is expected to be one of {_ALLOWED_ALTERNATIVES},"
                f" but got {alternative!r}"
            )
        self.variant = variant
        self.t_test = t_test
        self.alternative = alternative
        self.num_outputs = num_outputs
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        self.preds.append(preds)
        self.target.append(target)

    def compute(self):
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return kendall_rank_corrcoef(preds, target, self.variant, self.t_test, self.alternative)

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        return self._plot(val, ax)
