"""RelativeSquaredError (counterpart of reference ``regression/rse.py``)."""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from tpumetrics.functional.regression.r2 import _r2_score_update
from tpumetrics.functional.regression.rse import _relative_squared_error_compute
from tpumetrics.metric import Metric

Array = jax.Array


class RelativeSquaredError(Metric):
    """RSE (reference regression/rse.py:25).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.regression import RelativeSquaredError
        >>> metric = RelativeSquaredError()
        >>> metric.update(jnp.asarray([2.5, 0.0, 2, 8]), jnp.asarray([3., -0.5, 2, 7]))
        >>> round(float(metric.compute()), 4)
        0.0514
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    sum_squared_obs: Array
    sum_obs: Array
    sum_squared_error: Array
    total: Array

    def __init__(self, num_outputs: int = 1, squared: bool = True, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.num_outputs = num_outputs
        self.squared = squared
        self.add_state("sum_squared_obs", jnp.zeros(num_outputs), dist_reduce_fx="sum")
        self.add_state("sum_obs", jnp.zeros(num_outputs), dist_reduce_fx="sum")
        self.add_state("sum_squared_error", jnp.zeros(num_outputs), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros((), dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_squared_obs, sum_obs, rss, num_obs = _r2_score_update(preds, target)
        self.sum_squared_obs = self.sum_squared_obs + sum_squared_obs
        self.sum_obs = self.sum_obs + sum_obs
        self.sum_squared_error = self.sum_squared_error + rss
        self.total = self.total + num_obs

    def compute(self) -> Array:
        return _relative_squared_error_compute(
            self.sum_squared_obs, self.sum_obs, self.sum_squared_error, self.total, self.squared
        )

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        return self._plot(val, ax)
