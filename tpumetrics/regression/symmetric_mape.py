"""SymmetricMeanAbsolutePercentageError (counterpart of reference
``regression/symmetric_mape.py``)."""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from tpumetrics.functional.regression.mape import _symmetric_mean_absolute_percentage_error_update
from tpumetrics.metric import Metric

Array = jax.Array


class SymmetricMeanAbsolutePercentageError(Metric):
    """SMAPE (reference regression/symmetric_mape.py:26).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.regression import SymmetricMeanAbsolutePercentageError
        >>> metric = SymmetricMeanAbsolutePercentageError()
        >>> metric.update(jnp.asarray([0.9, 15., 1.2e6]), jnp.asarray([1., 10, 1e6]))
        >>> round(float(metric.compute()), 4)
        0.229
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 2.0

    sum_abs_per_error: Array
    total: Array

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_abs_per_error", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros((), dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_abs_per_error, num_obs = _symmetric_mean_absolute_percentage_error_update(preds, target)
        self.sum_abs_per_error = self.sum_abs_per_error + sum_abs_per_error
        self.total = self.total + num_obs

    def compute(self) -> Array:
        return self.sum_abs_per_error / self.total

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        return self._plot(val, ax)
