"""ConcordanceCorrCoef (counterpart of reference ``regression/concordance.py``)."""

from __future__ import annotations

from typing import Any

import jax

from tpumetrics.functional.regression.concordance import _concordance_corrcoef_compute
from tpumetrics.regression.pearson import PearsonCorrCoef

Array = jax.Array


class ConcordanceCorrCoef(PearsonCorrCoef):
    """Concordance correlation (reference regression/concordance.py:26 —
    shares the Pearson moment states).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.regression import ConcordanceCorrCoef
        >>> metric = ConcordanceCorrCoef()
        >>> metric.update(jnp.asarray([2.5, 0.0, 2, 8]), jnp.asarray([3., -0.5, 2, 7]))
        >>> round(float(metric.compute()), 4)
        0.9777
    """

    def compute(self) -> Array:
        mean_x, mean_y, var_x, var_y, corr_xy, n_total = self._aggregated()
        return _concordance_corrcoef_compute(mean_x, mean_y, var_x, var_y, corr_xy, n_total).squeeze()

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        return self._plot(val, ax)
