"""Modular regression metrics (counterpart of reference
``torchmetrics/regression/__init__.py``)."""

from tpumetrics.regression.concordance import ConcordanceCorrCoef
from tpumetrics.regression.cosine_similarity import CosineSimilarity
from tpumetrics.regression.explained_variance import ExplainedVariance
from tpumetrics.regression.kendall import KendallRankCorrCoef
from tpumetrics.regression.kl_divergence import KLDivergence
from tpumetrics.regression.log_cosh import LogCoshError
from tpumetrics.regression.log_mse import MeanSquaredLogError
from tpumetrics.regression.mae import MeanAbsoluteError
from tpumetrics.regression.mape import MeanAbsolutePercentageError
from tpumetrics.regression.minkowski import MinkowskiDistance
from tpumetrics.regression.mse import MeanSquaredError
from tpumetrics.regression.pearson import PearsonCorrCoef
from tpumetrics.regression.r2 import R2Score
from tpumetrics.regression.rse import RelativeSquaredError
from tpumetrics.regression.spearman import SpearmanCorrCoef
from tpumetrics.regression.symmetric_mape import SymmetricMeanAbsolutePercentageError
from tpumetrics.regression.tweedie_deviance import TweedieDevianceScore
from tpumetrics.regression.wmape import WeightedMeanAbsolutePercentageError

__all__ = [
    "ConcordanceCorrCoef",
    "CosineSimilarity",
    "ExplainedVariance",
    "KLDivergence",
    "KendallRankCorrCoef",
    "LogCoshError",
    "MeanAbsoluteError",
    "MeanAbsolutePercentageError",
    "MeanSquaredError",
    "MeanSquaredLogError",
    "MinkowskiDistance",
    "PearsonCorrCoef",
    "R2Score",
    "RelativeSquaredError",
    "SpearmanCorrCoef",
    "SymmetricMeanAbsolutePercentageError",
    "TweedieDevianceScore",
    "WeightedMeanAbsolutePercentageError",
]
