"""PearsonCorrCoef (counterpart of reference ``regression/pearson.py``).

The state is per-device streaming moments with ``dist_reduce_fx=None``
(rank-stack), merged at compute with the Chan parallel-moment aggregation —
the template for metrics whose state is not a plain sum (reference
regression/pearson.py:28-70,137-142).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from tpumetrics.functional.regression.pearson import (
    _final_aggregation,
    _pearson_corrcoef_compute,
    _pearson_corrcoef_update,
)
from tpumetrics.metric import Metric

Array = jax.Array


class PearsonCorrCoef(Metric):
    """Pearson correlation (reference regression/pearson.py:73).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.regression import PearsonCorrCoef
        >>> metric = PearsonCorrCoef()
        >>> metric.update(jnp.asarray([2.5, 0.0, 2, 8]), jnp.asarray([3., -0.5, 2, 7]))
        >>> round(float(metric.compute()), 4)
        0.9849
    """

    is_differentiable = True
    higher_is_better = None
    full_state_update = True
    plot_lower_bound: float = -1.0
    plot_upper_bound: float = 1.0

    mean_x: Array
    mean_y: Array
    var_x: Array
    var_y: Array
    corr_xy: Array
    n_total: Array

    def __init__(self, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(num_outputs, int) and num_outputs > 0):
            raise ValueError(f"Expected argument `num_outputs` to be an int larger than 0, but got {num_outputs}")
        self.num_outputs = num_outputs
        for name in ("mean_x", "mean_y", "var_x", "var_y", "corr_xy", "n_total"):
            # tpulint: disable-next=TPL303 -- per-rank stacks are folded by the reference's _final_aggregation in compute(); documented not elastic-reshardable (merge.py raises typed)
            self.add_state(name, jnp.zeros(self.num_outputs), dist_reduce_fx=None)

    def update(self, preds: Array, target: Array) -> None:
        self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total = _pearson_corrcoef_update(
            preds, target, self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total,
            self.num_outputs,
        )

    def _aggregated(self) -> tuple:
        if self.mean_x.ndim > 1:  # rank-stacked states from sync
            return _final_aggregation(self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total)
        return self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total

    def compute(self) -> Array:
        _, _, var_x, var_y, corr_xy, n_total = self._aggregated()
        return _pearson_corrcoef_compute(var_x, var_y, corr_xy, n_total)

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        return self._plot(val, ax)
