"""WeightedMeanAbsolutePercentageError (counterpart of reference
``regression/wmape.py``)."""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from tpumetrics.functional.regression.mape import (
    _weighted_mean_absolute_percentage_error_compute,
    _weighted_mean_absolute_percentage_error_update,
)
from tpumetrics.metric import Metric

Array = jax.Array


class WeightedMeanAbsolutePercentageError(Metric):
    """WMAPE (reference regression/wmape.py:26).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.regression import WeightedMeanAbsolutePercentageError
        >>> metric = WeightedMeanAbsolutePercentageError()
        >>> metric.update(jnp.asarray([0.9, 15., 1.2e6]), jnp.asarray([1., 10, 1e6]))
        >>> round(float(metric.compute()), 4)
        0.2
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0

    sum_abs_error: Array
    sum_scale: Array

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_abs_error", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("sum_scale", jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_abs_error, sum_scale = _weighted_mean_absolute_percentage_error_update(preds, target)
        self.sum_abs_error = self.sum_abs_error + sum_abs_error
        self.sum_scale = self.sum_scale + sum_scale

    def compute(self) -> Array:
        return _weighted_mean_absolute_percentage_error_compute(self.sum_abs_error, self.sum_scale)

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        return self._plot(val, ax)
