"""MeanAbsoluteError (counterpart of reference ``regression/mae.py``)."""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from tpumetrics.functional.regression.mae import _mean_absolute_error_compute, _mean_absolute_error_update
from tpumetrics.metric import Metric

Array = jax.Array


class MeanAbsoluteError(Metric):
    """MAE (reference regression/mae.py:26).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.regression import MeanAbsoluteError
        >>> metric = MeanAbsoluteError()
        >>> metric.update(jnp.asarray([0., 1, 2, 3]), jnp.asarray([0., 1, 2, 1]))
        >>> round(float(metric.compute()), 4)
        0.5
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0

    sum_abs_error: Array
    total: Array

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_abs_error", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros((), dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_abs_error, num_obs = _mean_absolute_error_update(preds, target)
        self.sum_abs_error = self.sum_abs_error + sum_abs_error
        self.total = self.total + num_obs

    def compute(self) -> Array:
        return _mean_absolute_error_compute(self.sum_abs_error, self.total)

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        return self._plot(val, ax)
