"""SpearmanCorrCoef (counterpart of reference ``regression/spearman.py``)."""

from __future__ import annotations

from typing import Any, List

import jax

from tpumetrics.functional.regression.spearman import (
    _spearman_corrcoef_compute,
    _spearman_corrcoef_update,
)
from tpumetrics.metric import Metric
from tpumetrics.utils.data import dim_zero_cat

Array = jax.Array


class SpearmanCorrCoef(Metric):
    """Spearman rank correlation (reference regression/spearman.py:25).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.regression import SpearmanCorrCoef
        >>> metric = SpearmanCorrCoef()
        >>> metric.update(jnp.asarray([2.5, 0.0, 2, 8]), jnp.asarray([3., -0.5, 2, 7]))
        >>> round(float(metric.compute()), 4)
        1.0
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound: float = -1.0
    plot_upper_bound: float = 1.0

    preds: List[Array]
    target: List[Array]

    def __init__(self, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(num_outputs, int) and num_outputs > 0):
            raise ValueError(f"Expected argument `num_outputs` to be an int larger than 0, but got {num_outputs}")
        self.num_outputs = num_outputs
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        preds, target = _spearman_corrcoef_update(preds, target, self.num_outputs)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _spearman_corrcoef_compute(preds, target)

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        return self._plot(val, ax)
