"""CLIPImageQualityAssessment metric (counterpart of reference
``multimodal/clip_iqa.py``)."""

from __future__ import annotations

from typing import Any, Dict, Tuple, Union

import jax
import jax.numpy as jnp

from tpumetrics.functional.multimodal.clip_iqa import (
    _clip_iqa_format_prompts,
    _clip_iqa_text_features,
    clip_image_quality_assessment,
)
from tpumetrics.functional.multimodal.clip_score import _get_clip_model_and_processor
from tpumetrics.metric import Metric

Array = jax.Array


class CLIPImageQualityAssessment(Metric):
    """CLIP-IQA accumulated over batches: per-prompt probability sums.

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from tpumetrics.multimodal import CLIPImageQualityAssessment
        >>> metric = CLIPImageQualityAssessment()  # doctest: +SKIP
        >>> imgs = jax.random.uniform(jax.random.PRNGKey(0), (1, 3, 224, 224))
        >>> metric.update(imgs)  # doctest: +SKIP
        >>> metric.compute().shape  # doctest: +SKIP
        (1,)
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = True
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        model_name_or_path: Union[str, Tuple[Any, Any]] = "clip_iqa",
        data_range: float = 1.0,
        prompts: Tuple[Union[str, Tuple[str, str]], ...] = ("quality",),
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.prompts_names, prompts_list = _clip_iqa_format_prompts(prompts)
        self.prompts = prompts
        self.model, self.processor = _get_clip_model_and_processor(model_name_or_path)
        self.model_name_or_path = (self.model, self.processor)
        self.data_range = data_range
        # prompt anchors depend only on `prompts`: encode once, reuse every update
        self._text_features = _clip_iqa_text_features(self.model, self.processor, prompts_list)
        n = len(self.prompts_names)
        self.add_state("score_sums", jnp.zeros(n), dist_reduce_fx="sum")
        self.add_state("n_samples", jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, images: Array) -> None:
        """Accumulate per-prompt probability sums."""
        out = clip_image_quality_assessment(
            images, self.model_name_or_path, self.data_range, self.prompts,
            text_features=self._text_features,
        )
        if isinstance(out, dict):
            sums = jnp.stack([out[name].sum() for name in self.prompts_names])
        else:
            sums = jnp.asarray([out.sum()])
        self.score_sums = self.score_sums + sums
        self.n_samples = self.n_samples + jnp.asarray(images.shape[0], jnp.float32)

    def compute(self) -> Union[Array, Dict[str, Array]]:
        means = self.score_sums / self.n_samples
        if len(self.prompts_names) == 1:
            return means[0]
        return {name: means[i] for i, name in enumerate(self.prompts_names)}
