"""Multimodal metric domain (counterpart of reference ``multimodal/__init__.py``)."""

from tpumetrics.multimodal.clip_iqa import CLIPImageQualityAssessment
from tpumetrics.multimodal.clip_score import CLIPScore

__all__ = [
    "CLIPImageQualityAssessment",
    "CLIPScore",
]
