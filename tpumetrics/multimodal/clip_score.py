"""CLIPScore metric (counterpart of reference ``multimodal/clip_score.py:43``)."""

from __future__ import annotations

from typing import Any, List, Tuple, Union

import jax
import jax.numpy as jnp

from tpumetrics.functional.multimodal.clip_score import _clip_score_update, _get_clip_model_and_processor
from tpumetrics.metric import Metric

Array = jax.Array


class CLIPScore(Metric):
    """CLIPScore accumulated over batches: scalar sum + count states
    (reference multimodal/clip_score.py:115-116).

    Args:
        model_name_or_path: HF hub id of a CLIP checkpoint, or an explicit
            ``(model, processor)`` pair for offline/custom models.

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from tpumetrics.multimodal import CLIPScore
        >>> metric = CLIPScore(model_name_or_path='openai/clip-vit-base-patch16')  # doctest: +SKIP
        >>> imgs = jax.random.randint(jax.random.PRNGKey(0), (1, 3, 224, 224), 0, 255)
        >>> metric.update(imgs, ['a photo of a cat'])  # doctest: +SKIP
        >>> round(float(metric.compute()), 1)  # doctest: +SKIP
        19.1
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = True
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 100.0

    def __init__(
        self,
        model_name_or_path: Union[str, Tuple[Any, Any]] = "openai/clip-vit-large-patch14",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.model, self.processor = _get_clip_model_and_processor(model_name_or_path)
        self.add_state("score", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("n_samples", jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, images: Union[Array, List[Array]], text: Union[str, List[str]]) -> None:
        """Accumulate similarity sums (reference multimodal/clip_score.py:118-129)."""
        score, n_samples = _clip_score_update(images, text, self.model, self.processor)
        self.score = self.score + score.sum()
        self.n_samples = self.n_samples + n_samples

    def compute(self) -> Array:
        return jnp.maximum(self.score / self.n_samples, jnp.zeros(()))
