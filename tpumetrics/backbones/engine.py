"""The shared backbone forward engine: jitted, bucketed, donated, policied.

One :class:`BackboneEngine` per resident :class:`~tpumetrics.backbones.
registry.BackboneHandle` owns the compiled forward every metric instance and
service tenant sharing that backbone dispatches through:

- **bucketed**: eager inputs are padded to the next power of two along the
  batch (and optionally sequence) axes via ``runtime/bucketing.py``'s
  ``pow2_at_least``, so the trace universe is bounded — log2(max batch)
  compiles, not one per shape.  Padded rows are zeros; the forward must be
  row-independent (every built-in backbone is), and the engine slices the
  pad rows back off, which is the ``valid``-mask contract in output form.
- **donated**: the engine always materializes its own padded staging buffer
  (a fresh XLA-owned copy even when no padding is needed), so the activation
  arguments are donated to the forward — XLA reuses them for intermediates
  instead of holding input + activations live together.
- **dtype policy**: params arrive already cast by
  :func:`~tpumetrics.backbones.placement.place_backbone`; the engine casts
  floating inputs to the policy dtype in-trace and casts floating outputs
  back to fp32, so downstream accumulators (Fréchet moments, cosine scores)
  keep fp32 state regardless of the forward precision.  fp32 is the default
  and the oracle; bf16 is opt-in behind the error-bound gate
  (``tests/test_backbones.py``).
- **trace-transparent**: called under an outer trace (a metric's fused update
  step, the service megabatch vmap), the engine inlines the forward into the
  caller's program instead of nesting a ``jit`` — the outer program compiles
  once and the engine's own compile counter stays untouched, which is what
  lets the 3-tenant sharing test assert "the embed compiled ONCE".

Every compiled (bucket, signature) registers a ``backbones/<key>`` program
profile (``telemetry/device.py``), so MFU and HBM for the shared forward are
readable from XLA's ``cost_analysis`` exactly like the detection matcher's.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from tpumetrics.runtime.bucketing import pow2_at_least
from tpumetrics.telemetry import device as _device
from tpumetrics.utils.data import _is_tracer

Array = jax.Array

__all__ = ["BackboneEngine"]


def _floating(arr: Any) -> bool:
    return jnp.issubdtype(jnp.asarray(arr).dtype, jnp.floating)


class BackboneEngine:
    """Compiled forward dispatch for one resident backbone.

    Args:
        forward: pure function ``(params, *arrays) -> pytree`` whose array
            leaves carry the batch on dim 0.
        label: the program-profile label (``backbones/<key>``).
        dtype_policy: ``"float32"`` (default, the oracle) or ``"bfloat16"``.
        mesh / data_axis: when set, activations are pinned batch-sharded
            along ``data_axis`` inside the trace (a sharding constraint), so
            the forward runs as one GSPMD program over the mesh.
        pad_axes: input axes padded to the next power of two (dim 0 = batch;
            add dim 1 for token-id/mask sequence axes).
    """

    def __init__(
        self,
        forward: Callable[..., Any],
        *,
        label: str,
        dtype_policy: str = "float32",
        mesh: Optional[Any] = None,
        data_axis: str = "dp",
        pad_axes: Sequence[int] = (0,),
    ) -> None:
        self.forward = forward
        self.label = label
        self.dtype_policy = dtype_policy
        self.mesh = mesh
        self.data_axis = data_axis
        self.pad_axes = tuple(sorted(set(int(a) for a in pad_axes)))
        self.compile_count = 0  # incremented at trace time, once per compile
        self.dispatch_count = 0
        self._lock = threading.Lock()
        self._jits: Dict[int, Any] = {}  # arg count -> jitted wrapper
        self._signatures: set = set()

    # ----------------------------------------------------------- trace body

    def _cast_in(self, arr: Array) -> Array:
        if self.dtype_policy != "float32" and _floating(arr):
            return arr.astype(jnp.dtype(self.dtype_policy))
        return arr

    def _cast_out(self, arr: Any) -> Any:
        if _floating(arr) and jnp.asarray(arr).dtype != jnp.float32:
            return jnp.asarray(arr, jnp.float32)
        return arr

    def _constrain_batch(self, arr: Array) -> Array:
        if self.mesh is None:
            return arr
        shape = getattr(arr, "shape", ())
        world = int(self.mesh.shape[self.data_axis])
        if not shape or shape[0] % world != 0:
            return arr
        from jax.sharding import NamedSharding, PartitionSpec

        spec = PartitionSpec(self.data_axis)
        return jax.lax.with_sharding_constraint(arr, NamedSharding(self.mesh, spec))

    def _apply(self, params: Any, *args: Array) -> Any:
        args = tuple(self._constrain_batch(self._cast_in(a)) for a in args)
        out = self.forward(params, *args)
        return jax.tree_util.tree_map(self._cast_out, out)

    def _traced(self, params: Any, *args: Array) -> Any:
        self.compile_count += 1  # python side effect: runs once per trace
        return self._apply(params, *args)

    # ------------------------------------------------------------- dispatch

    def _pad(self, arr: Array) -> Array:
        """Pad every bucketed axis up to the next power of two with zeros and
        materialize a fresh XLA-owned buffer either way — the staging copy
        that makes donating this argument safe."""
        arr = jnp.asarray(arr)
        pads = [(0, 0)] * arr.ndim
        padded = False
        for axis in self.pad_axes:
            if axis >= arr.ndim:
                continue
            n = arr.shape[axis]
            bucket = pow2_at_least(max(1, n))
            if bucket != n:
                pads[axis] = (0, bucket - n)
                padded = True
        if padded:
            return jnp.pad(arr, pads)
        return arr.copy()

    def _jit_for(self, n_args: int) -> Any:
        jitted = self._jits.get(n_args)
        if jitted is None:
            with self._lock:
                jitted = self._jits.get(n_args)
                if jitted is None:
                    jitted = jax.jit(
                        self._traced, donate_argnums=tuple(range(1, 1 + n_args))
                    )
                    self._jits[n_args] = jitted
        return jitted

    def __call__(self, params: Any, *args: Any) -> Any:
        """Run the forward.  Under an outer trace: inline (the caller's
        program owns bucketing and compile accounting).  Eagerly: pad to the
        bucket, dispatch the donated jitted program, slice the pad rows off.
        """
        if any(_is_tracer(a) for a in args) or _is_tracer(
            next(iter(jax.tree_util.tree_leaves(params)), None)
        ):
            return self._apply(params, *args)

        n = int(jnp.asarray(args[0]).shape[0]) if args else 0
        padded = tuple(self._pad(a) for a in args)
        jitted = self._jit_for(len(padded))
        sig = tuple((tuple(a.shape), str(a.dtype)) for a in padded)
        if sig not in self._signatures:
            with self._lock:
                if sig not in self._signatures:
                    self._signatures.add(sig)
                    # profile registration wants live args; donation consumes
                    # them on dispatch, so register against the abstract
                    # signature BEFORE the call
                    _device.register_program(
                        self.label, jitted, (params,) + padded, tenant=self.label
                    )
        import warnings

        with warnings.catch_warnings():
            # XLA reuses whichever donated staging buffers it can; the ones it
            # can't (shape-mismatched on this backend) are simply not reused —
            # not actionable for the metric user
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable"
            )
            out = jitted(params, *padded)
        self.dispatch_count += 1

        def trim(leaf: Any) -> Any:
            shape = getattr(leaf, "shape", ())
            if shape and args and shape[0] != n and shape[0] == padded[0].shape[0]:
                return leaf[:n]
            return leaf

        return jax.tree_util.tree_map(trim, out)
