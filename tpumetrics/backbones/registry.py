"""The process-global backbone registry: ONE resident weight set per
(architecture, weights-digest, mesh, dtype policy).

Every pretrained forward the metric families use — the LPIPS conv stacks,
the FID InceptionV3, BERT-style encoders — used to be loaded, cast, and
placed privately per metric instance: two FID instances on one stream held
two copies of a ~95 MB weight tree and compiled two identical programs.
:func:`get_backbone` collapses that to one :class:`BackboneHandle` per
registry key, refcounted across metric instances AND service tenants:

- weights are ``device_put`` once, sharded per
  :mod:`~tpumetrics.backbones.placement` (meshless fallback bit-identical
  to the old private path);
- the compiled forward lives in the handle's
  :class:`~tpumetrics.backbones.engine.BackboneEngine` — N instances share
  one program cache, so the embed compiles once no matter how many tenants
  dispatch it;
- HBM is attributed through the program-profile registry: each handle owns
  a ``backbones/<key>`` label whose profiles release on last close, and
  :func:`resident_bytes` feeds the ``backbone_bytes`` key of
  ``stats()["device"]["hbm"]``.

Handles are acquired in metric ``__init__`` and closed in ``close()`` —
never construct weights in ``update()``-reachable code (tpulint TPL107).
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpumetrics.backbones.engine import BackboneEngine
from tpumetrics.backbones.placement import (
    DTYPE_POLICIES,
    backbone_partition_rules,
    place_backbone,
)
from tpumetrics.parallel.sharding import StatePartitionRules, state_paths
from tpumetrics.telemetry import device as _device
from tpumetrics.utils.exceptions import TPUMetricsUserError

Array = jax.Array

__all__ = ["BackboneHandle", "get_backbone", "resident_bytes", "registry_stats"]


def _weights_digest(params: Any) -> str:
    """Content digest of a parameter pytree: path + shape + dtype + bytes per
    leaf.  Two metrics constructed from the same converted checkpoint hash
    identically even through separate ``np.load`` calls."""
    h = hashlib.sha1()
    for path, leaf in state_paths(params):
        arr = np.asarray(leaf)
        h.update(path.encode())
        h.update(str(arr.shape).encode())
        h.update(str(arr.dtype).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def _builtin_forward(arch: str) -> Callable[..., Any]:
    """The forward for a built-in arch key (``lpips:<net>`` /
    ``inception:<tap>``); raises for unknown keys so custom architectures
    must pass ``forward=`` explicitly."""
    family, _, variant = arch.partition(":")
    if family == "lpips":
        from tpumetrics.image._backbones import _BACKBONE_BUILDERS

        if variant not in _BACKBONE_BUILDERS:
            raise TPUMetricsUserError(
                f"Unknown LPIPS backbone arch {arch!r}; expected lpips:alex/vgg/squeeze."
            )

        def forward(params: Any, x: Array) -> Any:
            return _BACKBONE_BUILDERS[variant](params)(x)

        return forward
    if family == "inception":
        from tpumetrics.image._inception import inception_v3_features

        def forward(params: Any, x: Array) -> Array:
            return inception_v3_features(params, (variant,))(x)[0]

        return forward
    raise TPUMetricsUserError(
        f"Unknown backbone arch {arch!r} and no `forward=` given; built-in families"
        " are 'lpips:<alex|vgg|squeeze>' and 'inception:<tap>'."
    )


class BackboneHandle:
    """One resident backbone: placed params + shared engine + refcount.

    Instances come from :func:`get_backbone` only.  ``close()`` drops one
    reference; the last close evicts the handle from the registry, frees the
    weight tree, and releases the ``backbones/<key>`` program profiles."""

    def __init__(
        self,
        reg_key: Tuple,
        key: str,
        arch: str,
        params: Any,
        engine: BackboneEngine,
        mesh: Optional[Any],
        dtype_policy: str,
        placement: Optional[Dict[str, Any]] = None,
    ) -> None:
        self._reg_key = reg_key
        self.key = key
        self.arch = arch
        self.params = params
        self.engine = engine
        self.mesh = mesh
        self.dtype_policy = dtype_policy
        self.label = f"backbones/{key}"
        self.refs = 0
        self.closed = False
        # tenant-lifecycle parking: refs that moved resident -> parked (a
        # hibernated tenant still owns its reference, it just does not pin
        # HBM); when the LAST resident ref parks, the device tree is staged
        # to a host stash and freed — reacquire() re-places it from there
        # using the placement inputs recorded at first acquisition
        self.parked = 0
        self._host_params: Any = None
        self._placement: Dict[str, Any] = dict(placement or {})

    def __call__(self, *args: Any) -> Any:
        """Dispatch the shared forward (see :class:`BackboneEngine`)."""
        if self.closed:
            raise TPUMetricsUserError(
                f"Backbone handle {self.key!r} is closed; re-acquire it via get_backbone()."
            )
        return self.engine(self.params, *args)

    def acquire(self) -> "BackboneHandle":
        """Take one more reference (e.g. a metric adopting a caller-supplied
        handle) and return self.  Pair with :meth:`close`."""
        with _LOCK:
            if self.closed:
                raise TPUMetricsUserError(
                    f"Backbone handle {self.key!r} is closed; re-acquire it via get_backbone()."
                )
            self.refs += 1
        return self

    def resident_bytes(self) -> int:
        """On-device bytes held by this handle's weight tree."""
        if self.params is None:
            return 0
        return sum(
            int(getattr(leaf, "nbytes", 0) or 0)
            for leaf in jax.tree_util.tree_leaves(self.params)
        )

    def release_resident(self) -> bool:
        """Tenant hibernation: move one reference from resident to parked.

        The reference is still owned (the hibernated tenant will
        :meth:`reacquire` on revival, or :meth:`discard_parked` if closed
        for good while hibernated) — only HBM residency changes hands.
        When the last RESIDENT reference parks, the device tree is fetched
        to a host stash and freed, and the handle's program profiles are
        released; another resident holder keeps the weights exactly where
        they are (``resident_bytes()`` stays flat).  Returns ``True`` iff
        THIS call released the device tree."""
        with _LOCK:
            if self.closed:
                raise TPUMetricsUserError(
                    f"Backbone handle {self.key!r} is closed; re-acquire it via get_backbone()."
                )
            self.refs -= 1
            self.parked += 1
            if self.refs > 0 or self.params is None:
                return False
            # the fetch runs under the registry lock: parking is a rare
            # control-plane transition, and serializing it against
            # reacquire() keeps stash-vs-placed states impossible to race
            # tpulint: disable-next=TPL123 -- deliberate (comment above): parking is a rare control-plane transition, and fetching under the registry lock is what makes stash-vs-placed states impossible to race with reacquire()
            self._host_params = jax.device_get(self.params)
            self.params = None
        _device.release_profiles(self.label)
        return True

    def reacquire(self) -> "BackboneHandle":
        """Tenant revival: move one parked reference back to resident,
        re-placing the weight tree from the host stash when this is the
        first resident holder since the park.  Pair with
        :meth:`release_resident`."""
        with _LOCK:
            if self.closed:
                raise TPUMetricsUserError(
                    f"Backbone handle {self.key!r} is closed; re-acquire it via get_backbone()."
                )
            if self.parked > 0:
                self.parked -= 1
            self.refs += 1
            self._ensure_placed_locked()
        return self

    def _ensure_placed_locked(self) -> None:
        """Re-place a parked handle's weights from the host stash
        (registry lock held)."""
        if self.params is not None:
            return
        host, self._host_params = self._host_params, None
        if host is None:
            raise TPUMetricsUserError(
                f"Backbone handle {self.key!r} has neither resident nor parked "
                "weights; it was corrupted or reset mid-lifecycle."
            )
        self.params = place_backbone(
            self.arch, host, mesh=self.mesh, dtype_policy=self.dtype_policy,
            **self._placement,
        )

    def discard_parked(self) -> None:
        """Drop one PARKED reference without reviving — a hibernated
        tenant's metric being released for good.  The last reference
        (resident or parked) frees the handle entirely."""
        with _LOCK:
            if self.closed or self.parked <= 0:
                return
            self.parked -= 1
            if self.refs > 0 or self.parked > 0:
                return
            self.closed = True
            _HANDLES.pop(self._reg_key, None)
            self._host_params = None
        self.params = None
        _device.release_profiles(self.label)

    def close(self) -> None:
        """Drop one reference; the last reference frees the weights.  A
        parked reference (a hibernated tenant's claim) keeps the handle
        registered: its host stash must survive for the revival."""
        with _LOCK:
            if self.closed:
                return
            self.refs -= 1
            if self.refs > 0 or self.parked > 0:
                return
            self.closed = True
            _HANDLES.pop(self._reg_key, None)
            self._host_params = None
        self.params = None
        _device.release_profiles(self.label)

    def __deepcopy__(self, memo: Dict) -> "BackboneHandle":
        """Handles are shared by reference: a cloned metric dispatches the
        same resident backbone and owns one more reference on it."""
        # memo ourselves: deepcopy only records y when y is not x, so without
        # this every encounter within one clone would bump the refcount again
        memo[id(self)] = self
        with _LOCK:
            if not self.closed:
                self.refs += 1
        return self

    def __repr__(self) -> str:
        return (
            f"BackboneHandle({self.key!r}, refs={self.refs},"
            f" bytes={self.resident_bytes()})"
        )


_LOCK = threading.Lock()
_HANDLES: Dict[Tuple, BackboneHandle] = {}


def _mesh_key(mesh: Optional[Any]) -> Any:
    if mesh is None:
        return None
    return (
        tuple(mesh.axis_names),
        tuple(int(mesh.shape[a]) for a in mesh.axis_names),
        tuple(id(d) for d in mesh.devices.flat),
    )


def get_backbone(
    arch: str,
    params: Any,
    *,
    mesh: Optional[Any] = None,
    data_axis: str = "dp",
    model_axis: Optional[str] = None,
    dtype_policy: str = "float32",
    forward: Optional[Callable[..., Any]] = None,
    rules: Optional[StatePartitionRules] = None,
    pad_axes: Sequence[int] = (0,),
    key: Optional[str] = None,
    acquire: bool = True,
) -> BackboneHandle:
    """Acquire the resident :class:`BackboneHandle` for (arch, weights,
    mesh, dtype policy) — placing the weights on first acquisition, bumping
    the refcount on every later one.

    Args:
        arch: built-in key (``"lpips:alex"``, ``"inception:2048"``) or any
            caller-chosen name for a custom ``forward=``.
        params: the weight pytree (host numpy or device arrays).
        mesh / data_axis / model_axis / rules: placement inputs — see
            :func:`~tpumetrics.backbones.placement.place_backbone`.
        dtype_policy: ``"float32"`` (default/oracle) or ``"bfloat16"``
            (opt-in; gate with the per-metric error-bound suite).
        forward: ``(params, *arrays) -> pytree`` for custom architectures.
        pad_axes: engine bucketing axes (dim 0 batch; add dim 1 for
            token-id sequence axes).
        key: explicit weights identity, skipping the content digest — for
            callers that acquire per step and cannot afford the hash.
        acquire: ``True`` (default) bumps the refcount — the caller owns a
            reference and must ``close()`` it.  ``False`` is the functional
            idiom: an existing handle is returned without a ref bump, and a
            freshly placed one keeps a single registry-owned reference (a
            process-lifetime cache), so one-shot functional calls neither
            leak refs nor thrash placement.
    """
    if dtype_policy not in DTYPE_POLICIES:
        raise TPUMetricsUserError(
            f"Backbone dtype policy must be one of {DTYPE_POLICIES}, got {dtype_policy!r}."
        )
    digest = key if key is not None else _weights_digest(params)
    reg_key = (arch, digest, _mesh_key(mesh), dtype_policy)
    with _LOCK:
        handle = _HANDLES.get(reg_key)
        if handle is not None:
            if acquire:
                handle.refs += 1
            # a parked handle (every holder hibernated) re-places from its
            # host stash before being handed out — the caller expects a
            # dispatchable backbone
            handle._ensure_placed_locked()
            return handle
    # placement (a device_put of the whole tree) runs OUTSIDE the lock; the
    # setdefault below resolves the rare duplicate-placement race in favor
    # of the first publisher
    fwd = forward if forward is not None else _builtin_forward(arch)
    placed = place_backbone(
        arch, params, mesh=mesh, rules=rules,
        data_axis=data_axis, model_axis=model_axis, dtype_policy=dtype_policy,
    )
    public = f"{arch}:{digest[:12]}:{dtype_policy}" + ("" if mesh is None else ":mesh")
    engine = BackboneEngine(
        fwd, label=f"backbones/{public}", dtype_policy=dtype_policy,
        mesh=mesh, data_axis=data_axis, pad_axes=pad_axes,
    )
    fresh = BackboneHandle(
        reg_key, public, arch, placed, engine, mesh, dtype_policy,
        placement=dict(rules=rules, data_axis=data_axis, model_axis=model_axis),
    )
    with _LOCK:
        handle = _HANDLES.setdefault(reg_key, fresh)
        if acquire or handle.refs == 0:
            handle.refs += 1
    return handle


def resident_bytes() -> int:
    """Total on-device bytes held by every resident backbone — the
    ``backbone_bytes`` number ``stats()["device"]["hbm"]`` reports."""
    with _LOCK:
        handles = list(_HANDLES.values())
    return sum(h.resident_bytes() for h in handles)


def registry_stats() -> Dict[str, Dict[str, Any]]:
    """Per-handle registry snapshot: refs, resident bytes, engine counters."""
    with _LOCK:
        handles = list(_HANDLES.values())
    return {
        h.key: {
            "arch": h.arch,
            "refs": h.refs,
            "parked": h.parked,
            "bytes": h.resident_bytes(),
            "compiles": h.engine.compile_count,
            "dispatches": h.engine.dispatch_count,
            "dtype_policy": h.dtype_policy,
        }
        for h in handles
    }


def _reset_backbones() -> None:
    """Drop every resident handle (tests only)."""
    with _LOCK:
        handles = list(_HANDLES.values())
        _HANDLES.clear()
    for h in handles:
        h.closed = True
        h.params = None
        h._host_params = None
        _device.release_profiles(h.label)
