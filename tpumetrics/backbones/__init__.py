"""Shared sharded backbone runtime for the model-bound metric families.

The model-bound metrics (BERTScore/InfoLM, FID/KID/MiFID/IS, LPIPS/PPL) are
small inference services wearing a metric API; this package gives them ONE
process-global runtime instead of a private backbone per instance:

- :mod:`~tpumetrics.backbones.registry` — :func:`get_backbone` returns one
  refcounted resident :class:`BackboneHandle` per (architecture,
  weights-digest, mesh, dtype policy).
- :mod:`~tpumetrics.backbones.placement` — regex→``PartitionSpec`` weight
  rules per architecture over the ``parallel/sharding.py`` plumbing, with a
  bit-identical meshless fallback and the one-time dtype-policy cast.
- :mod:`~tpumetrics.backbones.engine` — the jitted, bucketed, donated
  forward every sharing instance and tenant dispatches through.

See ``docs/backbones.md`` for lifecycle, rule syntax, the bf16 gate, and
tenancy sharing semantics.
"""

from tpumetrics.backbones.engine import BackboneEngine
from tpumetrics.backbones.placement import (
    DTYPE_POLICIES,
    backbone_partition_rules,
    cast_params,
    place_backbone,
)
from tpumetrics.backbones.registry import (
    BackboneHandle,
    get_backbone,
    registry_stats,
    resident_bytes,
)

__all__ = [
    "BackboneEngine",
    "BackboneHandle",
    "DTYPE_POLICIES",
    "backbone_partition_rules",
    "cast_params",
    "get_backbone",
    "place_backbone",
    "registry_stats",
    "resident_bytes",
]
