"""Placement layer: pretrained backbone weights as long-lived sharded arrays.

Backbone parameter pytrees get the same treatment metric STATE pytrees got in
``parallel/sharding.py``: an ordered regex→``PartitionSpec`` rule list over
slash-joined paths (the ``match_partition_rules`` idiom), resolved against the
metric's mesh, with uneven shards demoted to replicated and a meshless
single-device fallback that is bit-identical to the private per-metric
placement it replaces.

Two things are deliberately different from state placement:

- **dtype policy is applied here, once.**  The forwards in
  ``image/_backbones.py`` / ``image/_inception.py`` used to re-cast every
  weight *inside the trace* (``jnp.asarray(w, x.dtype)`` per conv), so a bf16
  run still carried the fp32 constants in the program.  Placement casts every
  floating leaf to the policy dtype before the ``device_put``, and the
  forwards consume parameters as-is.
- **weights shard along non-contraction dims only.**  The built-in rules
  shard conv kernels along their output-channel dim and matmul kernels along
  their output-feature dim, so GSPMD never splits a reduction — no
  partial-sum collectives enter the math (pinned bit-identical by the mesh8
  test in ``tests/test_backbones.py``; per-shard re-vectorization can still
  reorder same-value FMA chains at large channel counts, ≈1e-6 relative).

See ``docs/backbones.md`` for the rule syntax and the worked example.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

from tpumetrics.parallel.sharding import StatePartitionRules, _map_state
from tpumetrics.utils.exceptions import TPUMetricsUserError

Array = jax.Array
P = PartitionSpec

__all__ = [
    "DTYPE_POLICIES",
    "backbone_partition_rules",
    "cast_params",
    "place_backbone",
]

# the two supported forward precisions: fp32 is the default AND the oracle;
# bf16 is opt-in behind the per-metric error-bound gate (docs/backbones.md)
DTYPE_POLICIES = ("float32", "bfloat16")


def _check_policy(dtype_policy: str) -> jnp.dtype:
    if dtype_policy not in DTYPE_POLICIES:
        raise TPUMetricsUserError(
            f"Backbone dtype policy must be one of {DTYPE_POLICIES}, got {dtype_policy!r}."
        )
    return jnp.dtype(dtype_policy)


# per-architecture-family weight rules; "O" shards dim 0 (conv output
# channels, OIHW layout), "LAST" shards dim 1 (matmul output features).
# Everything unmatched — biases, BN leaves, embeddings — replicates.
_FAMILY_RULES = {
    # LPIPS params are a flat list of (weight, bias) pairs: paths "i/0", "i/1"
    "lpips": [(r"(^|/)\d+/0$", "O")],
    # InceptionV3 params are a flat torch-state-dict mapping (dotted keys)
    "inception": [(r"conv\.weight$", "O"), (r"^fc\.weight$", "O")],
    # BERT-style encoders: dense kernels are (in, out) — shard the out dim;
    # 1-D / uneven leaves demote to replicated automatically
    "encoder": [(r"(kernel|weight)$", "LAST")],
}


def backbone_partition_rules(
    arch: str,
    *,
    data_axis: str = "dp",
    model_axis: Optional[str] = None,
    extra_rules: Sequence[Tuple[str, PartitionSpec]] = (),
) -> StatePartitionRules:
    """The regex→spec rules for one backbone architecture.

    ``arch`` is a registry key like ``"lpips:alex"`` or ``"inception:2048"``;
    its family (the part before ``":"``) selects the built-in rule set.
    Unknown families replicate everything (always safe).  ``model_axis``
    names the mesh axis big weight leaves shard along — the 1-D metric
    meshes from :func:`~tpumetrics.parallel.sharding.make_mesh` have only
    ``data_axis``, so it defaults to that; uneven leaves demote to
    replicated per :class:`StatePartitionRules` semantics.  ``extra_rules``
    prepend caller rules (first match wins), which is how a custom
    architecture plugs its own specs into the same plumbing
    :meth:`StatePartitionRules.for_metric` uses for state.
    """
    axis = model_axis if model_axis is not None else data_axis
    family = arch.split(":", 1)[0]
    rules: List[Tuple[str, PartitionSpec]] = list(extra_rules)
    for pattern, kind in _FAMILY_RULES.get(family, ()):
        rules.append((pattern, P(axis) if kind == "O" else P(None, axis)))
    return StatePartitionRules(rules, data_axis=data_axis)


def cast_params(params: Any, dtype_policy: str = "float32") -> Any:
    """Cast every floating leaf of a parameter pytree to the policy dtype —
    ONCE, at placement, so no forward re-materializes fp32 constants inside
    its trace.  Integer/bool leaves pass through untouched."""
    dtype = _check_policy(dtype_policy)

    def one(_path: str, leaf: Any) -> Any:
        arr = jnp.asarray(leaf)
        if jnp.issubdtype(arr.dtype, jnp.floating):
            return arr.astype(dtype)
        return arr

    return _map_state(one, params)


def place_backbone(
    arch: str,
    params: Any,
    *,
    mesh: Optional[Mesh] = None,
    rules: Optional[StatePartitionRules] = None,
    data_axis: str = "dp",
    model_axis: Optional[str] = None,
    dtype_policy: str = "float32",
) -> Any:
    """Cast + place a backbone parameter pytree.

    With a mesh, every leaf is ``device_put`` under its resolved
    ``NamedSharding`` (one resident sharded copy, the registry's contract);
    with ``mesh=None`` it degrades to the donation-safe on-device
    materialization state placement uses — bit-identical to the private
    ``jnp.asarray`` path each metric used to run."""
    if rules is None:
        rules = backbone_partition_rules(arch, data_axis=data_axis, model_axis=model_axis)
    return rules.place(mesh, cast_params(params, dtype_policy))
