"""tpumetrics — a TPU-native metrics framework on JAX/XLA.

Brand-new implementation of the capabilities of the reference TorchMetrics
fork (/root/reference, v1.3.0dev): a core ``Metric`` engine with declared
accumulator states and automatic cross-device synchronization, rebuilt
idiomatically for TPU — state as ``jax.Array`` pytrees, updates that can run
inside jitted/pjit-ed step functions, and sync lowered to XLA collectives
over ICI/DCN instead of ``torch.distributed``.
"""

from tpumetrics.__about__ import __version__
from tpumetrics.aggregation import (
    CatMetric,
    MaxMetric,
    MeanMetric,
    MinMetric,
    RunningMean,
    RunningSum,
    SumMetric,
)
from tpumetrics.collections import MetricCollection
from tpumetrics.metric import CompositionalMetric, Metric

__all__ = [
    "CatMetric",
    "CompositionalMetric",
    "MaxMetric",
    "MeanMetric",
    "Metric",
    "MetricCollection",
    "MinMetric",
    "RunningMean",
    "RunningSum",
    "SumMetric",
    "__version__",
]
