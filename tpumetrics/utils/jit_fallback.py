"""Shared jit-with-eager-fallback wrapper for metrics that jit a
user-supplied callable (FID's extractor, LPIPS's backbone, ...).

The jitted path is the remote-accelerator fast path (one dispatch per
update instead of dozens); a user callable that leaves jax (host/numpy
code) cannot be traced, so the first trace failure falls back to eager —
but only *latches* eager mode after the eager run succeeds, so a transient
data error (bad shapes for one batch) doesn't permanently downgrade the
metric with a misleading diagnosis.
"""

from __future__ import annotations

from typing import Any, Callable

import jax


class JitWithEagerFallback:
    """Callable wrapping ``jax.jit(fn)`` with a one-time eager fallback.

    Not picklable (holds a compiled function); owners drop it in
    ``__getstate__`` and rebuild lazily.
    """

    def __init__(self, fn: Callable, what: str) -> None:
        self._fn = fn
        self._jitted = jax.jit(fn)
        self._what = what
        self.eager_mode = False

    def __call__(self, *args: Any) -> Any:
        if self.eager_mode:
            return self._fn(*args)
        try:
            return self._jitted(*args)
        except Exception as err:
            # broad on purpose: exotic user callables raise arbitrary types
            # when handed a tracer.  The eager re-run below keeps this safe —
            # a genuine data error raises there too and propagates, and the
            # eager latch only flips after an eager SUCCESS, so transient
            # failures never permanently downgrade dispatch.
            out = self._fn(*args)
            self.eager_mode = True
            from tpumetrics.utils.prints import rank_zero_warn

            rank_zero_warn(
                f"{self._what} is not jit-traceable ({type(err).__name__}); falling back to"
                " eager evaluation for all further updates."
            )
            return out
