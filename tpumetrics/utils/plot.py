"""Plotting helpers (matplotlib optional).

Counterpart of reference ``utilities/plot.py``
(/root/reference/src/torchmetrics/utilities/plot.py:62-328):
``plot_single_or_multi_val``, ``plot_confusion_matrix``, ``plot_curve``.
"""

from __future__ import annotations

from itertools import product
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from tpumetrics.utils.imports import _MATPLOTLIB_AVAILABLE

if _MATPLOTLIB_AVAILABLE:
    import matplotlib
    import matplotlib.axes
    import matplotlib.pyplot as plt

    _AX_TYPE = "matplotlib.axes.Axes"
    _PLOT_OUT_TYPE = Tuple["plt.Figure", Union["matplotlib.axes.Axes", np.ndarray]]
else:
    _AX_TYPE = Any  # type: ignore[misc,assignment]
    _PLOT_OUT_TYPE = Tuple[object, object]  # type: ignore[misc,assignment]


def _error_on_missing_matplotlib() -> None:
    if not _MATPLOTLIB_AVAILABLE:
        raise ModuleNotFoundError(
            "Plot function expects `matplotlib` to be installed, which is not available in this environment."
        )


def _to_numpy(x: Any) -> np.ndarray:
    return np.asarray(x)


def plot_single_or_multi_val(
    val: Any,
    ax: Optional[Any] = None,
    higher_is_better: Optional[bool] = None,
    lower_bound: Optional[float] = None,
    upper_bound: Optional[float] = None,
    legend_name: Optional[str] = None,
    name: Optional[str] = None,
) -> "_PLOT_OUT_TYPE":
    """Plot a single scalar/array value or a sequence of them over steps
    (reference plot.py:62-196)."""
    _error_on_missing_matplotlib()
    fig, ax = (None, ax) if ax is not None else plt.subplots(1, 1)

    if isinstance(val, Sequence) and not isinstance(val, (str, bytes)):
        vals = [_to_numpy(v) for v in val]
        if vals and vals[0].ndim == 0:
            ax.plot(range(len(vals)), [float(v) for v in vals], marker="o")
        else:
            arr = np.stack(vals)
            for i in range(arr.shape[-1]):
                label = f"{legend_name or 'class'} {i}"
                ax.plot(range(arr.shape[0]), arr[..., i], marker="o", label=label)
            ax.legend()
        ax.set_xlabel("Step")
    elif isinstance(val, dict):
        for i, (k, v) in enumerate(val.items()):
            v = _to_numpy(v)
            if v.ndim == 0:
                ax.bar(i, float(v), label=k)
            else:
                ax.plot(v, label=k)
        ax.legend()
    else:
        v = _to_numpy(val)
        if v.ndim == 0:
            ax.bar(0, float(v))
        else:
            ax.bar(np.arange(v.size), v.ravel())
    if lower_bound is not None or upper_bound is not None:
        ax.set_ylim(lower_bound, upper_bound)
    if name:
        ax.set_title(name)
    return fig, ax


def plot_confusion_matrix(
    confmat: Any,
    ax: Optional[Any] = None,
    add_text: bool = True,
    labels: Optional[List[str]] = None,
    cmap: Optional[str] = None,
) -> "_PLOT_OUT_TYPE":
    """Heatmap plot of a (num_classes, num_classes) or (N, C, C) confusion matrix
    (reference plot.py:199-265)."""
    _error_on_missing_matplotlib()
    confmat = _to_numpy(confmat)
    if confmat.ndim == 3:  # multilabel
        nb, n_classes = confmat.shape[0], confmat.shape[1]
        rows, cols = 1, nb
    else:
        nb, n_classes = 1, confmat.shape[0]
        rows, cols = 1, 1
        confmat = confmat[None]

    if labels is not None and len(labels) != n_classes:
        raise ValueError(
            "Expected number of elements in arg `labels` to match number of labels in confmat got "
            f"{len(labels)} and {n_classes}"
        )
    labels = labels or [str(i) for i in range(n_classes)]

    if ax is not None:
        if nb > 1:
            raise ValueError(
                f"Cannot plot a multilabel confusion matrix ({nb} panels) onto a single provided axis."
            )
        fig = None
        axs = np.asarray([ax])
    else:
        fig, axs = plt.subplots(rows, cols, squeeze=False)
        axs = axs.ravel()
    for b in range(nb):
        a = axs[b]
        im = a.imshow(confmat[b], cmap=cmap or "viridis")
        a.set_xlabel("Predicted class")
        a.set_ylabel("True class")
        a.set_xticks(range(n_classes))
        a.set_yticks(range(n_classes))
        a.set_xticklabels(labels)
        a.set_yticklabels(labels)
        if add_text:
            for i, j in product(range(n_classes), range(n_classes)):
                a.text(j, i, str(round(float(confmat[b, i, j]), 2)), ha="center", va="center")
    return fig, (axs[0] if nb == 1 else axs)


def plot_curve(
    curve: Tuple[Any, ...],
    score: Optional[Any] = None,
    ax: Optional[Any] = None,
    label_names: Optional[Tuple[str, str]] = None,
    legend_name: Optional[str] = None,
    name: Optional[str] = None,
) -> "_PLOT_OUT_TYPE":
    """Plot a (x, y[, thresholds]) curve, e.g. ROC / PR (reference plot.py:268-328)."""
    _error_on_missing_matplotlib()
    if len(curve) < 2:
        raise ValueError("Expected 2 or more elements in curve object")
    x, y = _to_numpy(curve[0]), _to_numpy(curve[1])
    fig, ax = (None, ax) if ax is not None else plt.subplots(1, 1)
    if x.ndim == 1:
        label = f"AUC={float(_to_numpy(score)):0.3f}" if score is not None else None
        ax.plot(x, y, linestyle="-", linewidth=2, label=label)
        if label:
            ax.legend()
    else:
        for i in range(x.shape[0]):
            label = f"{legend_name or 'class'} {i}"
            if score is not None:
                label += f" AUC={float(_to_numpy(score)[i]):0.3f}"
            ax.plot(x[i], y[i], linestyle="-", linewidth=2, label=label)
        ax.legend()
    ax.grid(True)
    if label_names is not None:
        ax.set_xlabel(label_names[0])
        ax.set_ylabel(label_names[1])
    if name is not None:
        ax.set_title(name)
    return fig, ax
