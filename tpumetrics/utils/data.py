"""Array helpers for metric state handling.

Counterpart of the reference's ``utilities/data.py``
(/root/reference/src/torchmetrics/utilities/data.py:28-237), rebuilt on
``jax.numpy``. Notably the reference carries an explicit XLA *fallback loop*
for ``_bincount`` (data.py:169-199) because ``torch.bincount`` is unsupported
on XLA/deterministic backends — here bincount is implemented with a static
``length`` argument, which lowers to a one-hot sum natively on TPU, so no
fallback is needed.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from tpumetrics.utils.prints import rank_zero_warn  # noqa: F401  (re-export, reference utilities/data.py)

Array = jax.Array

# drop-in compatibility with ``torchmetrics.utilities.data``
METRIC_EPS = 1e-6


def apply_to_collection(
    data: Any,
    dtype: Any,
    function: Any,
    *args: Any,
    wrong_dtype: Any = None,
    include_none: bool = True,
    **kwargs: Any,
) -> Any:
    """Apply ``function`` to every element of ``dtype`` inside a nested
    collection (the lightning-utilities helper the reference re-exports from
    ``utilities.data``).  Faithful recursion: preserves dict insertion order
    and container types (incl. namedtuples, sets, defaultdicts), honors
    ``wrong_dtype`` exclusions and ``include_none`` dropping, recurses into
    dataclass instances (rebuilding via field-wise setattr like the
    lightning-utilities helper, raising on frozen ones) and frozensets —
    jax pytrees would sort dict keys and skip sets."""
    import copy
    import dataclasses
    from collections import OrderedDict, defaultdict

    if isinstance(data, dtype) and (wrong_dtype is None or not isinstance(data, wrong_dtype)):
        return function(data, *args, **kwargs)

    elem_type = type(data)
    if isinstance(data, (defaultdict, OrderedDict, dict)):
        out = []
        for k, v in data.items():
            v = apply_to_collection(
                v, dtype, function, *args, wrong_dtype=wrong_dtype, include_none=include_none, **kwargs
            )
            if include_none or v is not None:
                out.append((k, v))
        if isinstance(data, defaultdict):
            return defaultdict(data.default_factory, OrderedDict(out))
        return elem_type(OrderedDict(out))

    if dataclasses.is_dataclass(data) and not isinstance(data, type):
        result = copy.copy(data)
        for field in dataclasses.fields(data):
            if not field.init:
                continue
            v = apply_to_collection(
                getattr(data, field.name),
                dtype,
                function,
                *args,
                wrong_dtype=wrong_dtype,
                include_none=include_none,
                **kwargs,
            )
            if not include_none and v is None:
                v = getattr(data, field.name)
            try:
                setattr(result, field.name, v)
            except dataclasses.FrozenInstanceError as err:
                raise ValueError(
                    "A frozen dataclass was passed to `apply_to_collection` but this is not"
                    " allowed."
                ) from err
        return result

    is_namedtuple = isinstance(data, tuple) and hasattr(data, "_fields")
    if isinstance(data, (list, tuple, set, frozenset)):
        out = []
        for d in data:
            v = apply_to_collection(
                d, dtype, function, *args, wrong_dtype=wrong_dtype, include_none=include_none, **kwargs
            )
            if include_none or v is not None:
                out.append(v)
        return elem_type(*out) if is_namedtuple else elem_type(out)

    return data


def _is_tracer(x: Any) -> bool:
    return isinstance(x, jax.core.Tracer)


def dim_zero_cat(x: Union[Array, List[Array]]) -> Array:
    """Concatenate (a possibly-listed) state along dim 0.

    MaskedBuffer states materialize to their exact valid rows (off-trace
    only — under jit use mask-aware math via ``buffers.masked_values``).
    """
    from tpumetrics.buffers import MaskedBuffer, _BufferList, materialize

    if isinstance(x, _BufferList):
        x = x.buffer
    if isinstance(x, MaskedBuffer):
        return materialize(x)
    if isinstance(x, (jnp.ndarray, jax.Array)) and not isinstance(x, (list, tuple)):
        return x
    if not x:  # empty list
        raise ValueError("No samples to concatenate")
    x = [y.buffer if isinstance(y, _BufferList) else y for y in x]
    x = [materialize(y) if isinstance(y, MaskedBuffer) else y for y in x]
    x = [y[None] if jnp.ndim(y) == 0 else y for y in x]
    return jnp.concatenate(x, axis=0)


def dim_zero_sum(x: Array) -> Array:
    return jnp.sum(x, axis=0)


def dim_zero_mean(x: Array) -> Array:
    return jnp.mean(x, axis=0)


def dim_zero_max(x: Array) -> Array:
    return jnp.max(x, axis=0)


def dim_zero_min(x: Array) -> Array:
    return jnp.min(x, axis=0)


def _flatten(x: Sequence) -> list:
    """Flatten list of lists into one list."""
    return [item for sublist in x for item in sublist]


def _flatten_dict(x: Dict) -> tuple[Dict, bool]:
    """Flatten dict of dicts into one level; returns (flat_dict, all_values_were_dicts)."""
    new_dict = {}
    duplicates = False
    for key, value in x.items():
        if isinstance(value, dict):
            for k, v in value.items():
                if k in new_dict:
                    duplicates = True
                new_dict[k] = v
        else:
            if key in new_dict:
                duplicates = True
            new_dict[key] = value
    return new_dict, duplicates


def to_onehot(label_tensor: Array, num_classes: Optional[int] = None) -> Array:
    """Convert dense label array ``(N, d1, ...)`` to one-hot ``(N, C, d1, ...)``.

    Matches the reference layout (class axis inserted at position 1,
    utilities/data.py:80-112); ``jax.nn.one_hot`` puts the class axis last so
    we move it.
    """
    if num_classes is None:
        num_classes = int(jnp.max(label_tensor)) + 1
    onehot = jax.nn.one_hot(label_tensor, num_classes, dtype=jnp.int32)
    return jnp.moveaxis(onehot, -1, 1)


def select_topk(prob_tensor: Array, topk: int = 1, dim: int = 1) -> Array:
    """Binary mask of the top-k entries along ``dim`` (reference data.py:115-139).

    Uses ``jax.lax.top_k`` (static k) so it lowers cleanly on TPU.
    """
    if topk == 1:  # fast path: argmax one-hot
        idx = jnp.argmax(prob_tensor, axis=dim)
        return jnp.moveaxis(jax.nn.one_hot(idx, prob_tensor.shape[dim], dtype=jnp.int32), -1, dim)
    moved = jnp.moveaxis(prob_tensor, dim, -1)
    _, idx = jax.lax.top_k(moved, topk)
    mask = jnp.sum(jax.nn.one_hot(idx, moved.shape[-1], dtype=jnp.int32), axis=-2)
    return jnp.moveaxis(mask, -1, dim)


def to_categorical(x: Array, argmax_dim: int = 1) -> Array:
    """Probabilities/logits to dense labels via argmax (reference data.py:142-166)."""
    return jnp.argmax(x, axis=argmax_dim)


def _count_dtype() -> Any:
    """Integer dtype for long-running count accumulators.

    int64 when jax x64 is enabled; otherwise int32, which silently wraps past
    ~2.1B accumulated samples — enable ``jax.config.update("jax_enable_x64",
    True)`` for longer accumulation runs (the reference uses torch.long
    unconditionally).
    """
    import jax as _jax

    return jnp.int64 if _jax.config.jax_enable_x64 else jnp.int32


def _bincount(x: Array, minlength: Optional[int] = None) -> Array:
    """Count occurrences of ints in ``x``.

    The static ``length`` makes this jit-safe; XLA lowers it to a scatter-add /
    one-hot sum on TPU (no host fallback needed, unlike reference data.py:169-199).
    """
    if minlength is None:
        if _is_tracer(x):
            raise ValueError("_bincount under jit requires a static `minlength`.")
        minlength = int(jnp.max(x)) + 1 if x.size else 0
    x = jnp.ravel(x)
    # negative and >= minlength values are DROPPED on both paths below
    # (jnp.bincount alone would clip negatives into bin 0)
    x = jnp.where(x < 0, minlength, x)
    if 0 < x.size * minlength <= (1 << 27):
        # TPU scatter-adds serialize; when the fused compare-and-reduce sweep
        # is small enough, one vectorized VPU pass beats the scatter by ~3x
        # (out-of-range / sentinel values find no matching bin)
        return jnp.sum(
            (x[:, None] == jnp.arange(minlength, dtype=x.dtype)[None, :]).astype(jnp.int32),
            axis=0,
        )
    return jnp.bincount(x, length=minlength)


def _cumsum(x: Array, dim: Optional[int] = 0, dtype: Optional[Any] = None) -> Array:
    """Cumulative sum (deterministic on TPU, unlike CUDA — reference data.py:202-211)."""
    return jnp.cumsum(x, axis=dim, dtype=dtype)


def _flexible_bincount(x: Array) -> Array:
    """Counts of each *unique* value (dynamic output — eager/host only)."""
    # remap values to contiguous ids, then dense bincount
    _, inverse = jnp.unique(x, return_inverse=True)
    return _bincount(inverse, minlength=int(jnp.max(inverse)) + 1 if x.size else 0)


def allclose(tensor1: Array, tensor2: Array, rtol: float = 1e-5, atol: float = 1e-8) -> bool:
    """dtype-safe allclose (reference data.py:233-237)."""
    if tensor1.dtype != tensor2.dtype:
        tensor2 = tensor2.astype(tensor1.dtype)
    return bool(jnp.allclose(tensor1, tensor2, rtol=rtol, atol=atol))
