"""Availability flags for optional dependencies.

Counterpart of the reference's ``utilities/imports.py``
(/root/reference/src/torchmetrics/utilities/imports.py:1-67). On TPU the
roles are inverted: JAX/Flax are the core stack, torch & friends are the
optional extras used mainly as test references.
"""

from __future__ import annotations

import importlib.util
import shutil
import sys


def package_available(name: str) -> bool:
    try:
        return importlib.util.find_spec(name) is not None
    except (ImportError, ModuleNotFoundError, ValueError):
        return False


_PYTHON_GREATER_EQUAL_3_10 = sys.version_info >= (3, 10)

# Core stack (required — flags exist for symmetry / graceful degradation in docs builds).
_JAX_AVAILABLE = package_available("jax")
_FLAX_AVAILABLE = package_available("flax")

# Optional scientific stack.
_SCIPY_AVAILABLE = package_available("scipy")
_SKLEARN_AVAILABLE = package_available("sklearn")
_MATPLOTLIB_AVAILABLE = package_available("matplotlib")
_SCIENCEPLOT_AVAILABLE = package_available("scienceplots")
_PANDAS_AVAILABLE = package_available("pandas")

# Text / multimodal extras.
_TRANSFORMERS_AVAILABLE = package_available("transformers")
_TRANSFORMERS_GREATER_EQUAL_4_4 = _TRANSFORMERS_AVAILABLE
_NLTK_AVAILABLE = package_available("nltk")
_REGEX_AVAILABLE = package_available("regex")
_TQDM_AVAILABLE = package_available("tqdm")
_MECAB_AVAILABLE = package_available("MeCab")
_IPADIC_AVAILABLE = package_available("ipadic")
_SENTENCEPIECE_AVAILABLE = package_available("sentencepiece")

# Image / detection extras.
_TORCH_AVAILABLE = package_available("torch")
_TORCHVISION_AVAILABLE = package_available("torchvision")
_TORCH_FIDELITY_AVAILABLE = package_available("torch_fidelity")
_PYCOCOTOOLS_AVAILABLE = package_available("pycocotools")
_FASTER_COCO_EVAL_AVAILABLE = package_available("faster_coco_eval")
_PIQ_GREATER_EQUAL_0_8 = package_available("piq")

# Audio extras (all host-side C/NumPy packages).
_PESQ_AVAILABLE = package_available("pesq")
_PYSTOI_AVAILABLE = package_available("pystoi")
_GAMMATONE_AVAILABLE = package_available("gammatone")
_TORCHAUDIO_AVAILABLE = package_available("torchaudio")
_SACREBLEU_AVAILABLE = package_available("sacrebleu")

# Multi-host launch helpers.
_MULTIPROCESSING_AVAILABLE = True

# Latex rendering for plots.
_LATEX_AVAILABLE = shutil.which("latex") is not None
