"""Exception types for tpumetrics.

TPU-native counterpart of the reference's ``utilities/exceptions.py``
(/root/reference/src/torchmetrics/utilities/exceptions.py:1-21).
"""


class TPUMetricsUserError(Exception):
    """Error raised when a misuse of the metric API is detected (e.g. double sync)."""


class TPUMetricsUserWarning(UserWarning):
    """Warning raised for non-fatal metric API misuse or degraded behavior."""


# Aliases matching the reference naming so users migrating from torchmetrics
# can except the familiar names.
TorchMetricsUserError = TPUMetricsUserError
TorchMetricsUserWarning = TPUMetricsUserWarning
