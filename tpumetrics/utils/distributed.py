"""Distributed gather/reduce helpers.

Counterpart of the reference's ``utilities/distributed.py``
(/root/reference/src/torchmetrics/utilities/distributed.py:22-147), with the
wire ops delegated to the pluggable backend in
:mod:`tpumetrics.parallel.backend`.
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import jax.numpy as jnp

from tpumetrics.parallel.backend import get_default_backend
from tpumetrics.utils.compute import _safe_divide

Array = jax.Array


def reduce(x: Array, reduction: str) -> Array:
    """Reduce a tensor: 'elementwise_mean' | 'sum' | 'none' (reference distributed.py:22-42)."""
    if reduction == "elementwise_mean":
        return jnp.mean(x)
    if reduction == "sum":
        return jnp.sum(x)
    if reduction is None or reduction == "none":
        return x
    raise ValueError("Reduction parameter unknown.")


def class_reduce(num: Array, denom: Array, weights: Array, class_reduction: str = "none") -> Array:
    """Per-class fraction reduction: micro/macro/weighted/none (reference distributed.py:45-88)."""
    valid_reduction = ("micro", "macro", "weighted", "none", None)
    fraction = _safe_divide(jnp.sum(num), jnp.sum(denom)) if class_reduction == "micro" else _safe_divide(num, denom)
    if class_reduction == "micro":
        return fraction
    if class_reduction == "macro":
        return jnp.mean(fraction)
    if class_reduction == "weighted":
        return jnp.sum(fraction * (weights.astype(fraction.dtype) / jnp.sum(weights)))
    if class_reduction == "none" or class_reduction is None:
        return fraction
    raise ValueError(f"Reduction parameter {class_reduction} unknown. Choose between one of these: {valid_reduction}")


def gather_all_tensors(result: Array, group: Optional[Any] = None) -> List[Array]:
    """Gather a tensor from all ranks, handling uneven dim-0 sizes.

    THE sync primitive, equivalent of reference distributed.py:97-147;
    delegates to the ambient backend (ICI AxisBackend in-trace, DCN
    MultiHostBackend eagerly, NoOp single-replica).
    """
    backend = get_default_backend()
    return backend.all_gather(jnp.asarray(result), group=group)
