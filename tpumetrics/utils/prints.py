"""Rank-zero-only printing / warning helpers.

Counterpart of the reference's ``utilities/prints.py``
(/root/reference/src/torchmetrics/utilities/prints.py:22-73), rebuilt on
``jax.process_index`` instead of env-var ranks: on a multi-host TPU pod each
host is one JAX process and only process 0 emits warnings/info.
"""

from __future__ import annotations

import warnings
from functools import partial, wraps
from typing import Any, Callable


def _get_rank() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def rank_zero_only(fn: Callable) -> Callable:
    """Decorate ``fn`` so it only runs on JAX process 0."""

    @wraps(fn)
    def wrapped_fn(*args: Any, **kwargs: Any) -> Any:
        if _get_rank() == 0:
            return fn(*args, **kwargs)
        return None

    return wrapped_fn


@rank_zero_only
def rank_zero_warn(message: str, *args: Any, **kwargs: Any) -> None:
    kwargs.setdefault("stacklevel", 5)
    warnings.warn(message, *args, **kwargs)


@rank_zero_only
def rank_zero_info(message: str, *args: Any, **kwargs: Any) -> None:
    print(message, *args, **kwargs)


rank_zero_debug = rank_zero_info

_future_warning = partial(warnings.warn, category=FutureWarning)


def _deprecated_root_import_class(name: str, domain: str) -> None:
    """Warn that a root-level class import is deprecated (reference parity)."""
    _future_warning(
        f"`tpumetrics.{name}` was deprecated and will be removed in a future version."
        f" Import `tpumetrics.{domain}.{name}` instead."
    )


def _deprecated_root_import_func(name: str, domain: str) -> None:
    """Warn that a root-level functional import is deprecated (reference parity)."""
    _future_warning(
        f"`tpumetrics.functional.{name}` was deprecated and will be removed in a future version."
        f" Import `tpumetrics.functional.{domain}.{name}` instead."
    )
