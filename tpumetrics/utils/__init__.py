"""Utility subpackage (counterpart of reference ``torchmetrics/utilities``)."""

from tpumetrics.utils.checks import check_forward_full_state_property
from tpumetrics.utils.data import (
    dim_zero_cat,
    dim_zero_max,
    dim_zero_mean,
    dim_zero_min,
    dim_zero_sum,
    select_topk,
    to_categorical,
    to_onehot,
)
from tpumetrics.utils.distributed import class_reduce, gather_all_tensors, reduce
from tpumetrics.utils.exceptions import TPUMetricsUserError, TPUMetricsUserWarning
from tpumetrics.utils.prints import rank_zero_debug, rank_zero_info, rank_zero_warn

__all__ = [
    "check_forward_full_state_property",
    "class_reduce",
    "dim_zero_cat",
    "dim_zero_max",
    "dim_zero_mean",
    "dim_zero_min",
    "dim_zero_sum",
    "gather_all_tensors",
    "rank_zero_debug",
    "rank_zero_info",
    "rank_zero_warn",
    "reduce",
    "select_topk",
    "to_categorical",
    "to_onehot",
    "TPUMetricsUserError",
    "TPUMetricsUserWarning",
]
