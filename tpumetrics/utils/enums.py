"""Enums driving task-string dispatch.

Counterpart of the reference's ``utilities/enums.py``
(/root/reference/src/torchmetrics/utilities/enums.py:20-154). Implemented
standalone (no lightning_utilities dependency).
"""

from __future__ import annotations

from enum import Enum
from typing import Optional


class EnumStr(str, Enum):
    """Base class: case/sep-insensitive string enum with a helpful error message."""

    @staticmethod
    def _name() -> str:
        return "Task"

    @staticmethod
    def _normalize(value: str) -> str:
        return value.lower().replace("-", "_").replace(" ", "_")

    @classmethod
    def from_str(cls, value: str, source: str = "key") -> "EnumStr":
        norm = cls._normalize(value)
        for member in cls:
            if cls._normalize(str(member.value)) == norm:
                return member
        valid = [str(m.value) for m in cls]
        raise ValueError(f"Invalid {cls._name()}: expected one of {valid}, but got {value}.")

    @classmethod
    def from_str_or_none(cls, value: Optional[str]) -> Optional["EnumStr"]:
        if value is None:
            return None
        return cls.from_str(value)

    def __str__(self) -> str:
        return str(self.value)

    def __eq__(self, other: object) -> bool:
        if isinstance(other, str):
            return self._normalize(str(self.value)) == self._normalize(other)
        return Enum.__eq__(self, other)

    def __hash__(self) -> int:
        return hash(str(self.value))


class DataType(EnumStr):
    """Type of an input (legacy input-format classifier vocabulary)."""

    @staticmethod
    def _name() -> str:
        return "Data type"

    BINARY = "binary"
    MULTILABEL = "multi-label"
    MULTICLASS = "multi-class"
    MULTIDIM_MULTICLASS = "multi-dim multi-class"


class AverageMethod(EnumStr):
    """Reduction over classes: micro / macro / weighted / none / samples."""

    @staticmethod
    def _name() -> str:
        return "Average method"

    MICRO = "micro"
    MACRO = "macro"
    WEIGHTED = "weighted"
    NONE = "none"
    SAMPLES = "samples"


class MDMCAverageMethod(EnumStr):
    """Reduction over the extra multidim dimension."""

    GLOBAL = "global"
    SAMPLEWISE = "samplewise"


class ClassificationTask(EnumStr):
    """Task vocabulary for the task-string classification wrappers."""

    @staticmethod
    def _name() -> str:
        return "Classification"

    BINARY = "binary"
    MULTICLASS = "multiclass"
    MULTILABEL = "multilabel"


class ClassificationTaskNoBinary(EnumStr):
    @staticmethod
    def _name() -> str:
        return "Classification"

    MULTICLASS = "multiclass"
    MULTILABEL = "multilabel"


class ClassificationTaskNoMultilabel(EnumStr):
    @staticmethod
    def _name() -> str:
        return "Classification"

    BINARY = "binary"
    MULTICLASS = "multiclass"
