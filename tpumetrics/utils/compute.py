"""Numerically-safe compute helpers.

Counterpart of the reference's ``utilities/compute.py``
(/root/reference/src/torchmetrics/utilities/compute.py:20-157). All helpers
are pure jnp and jit-safe; where the reference branches on data-dependent
conditions (e.g. ``auc`` reorder) we use ``where``-style masking instead so
everything lowers to a single XLA program.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

Array = jax.Array


def _safe_matmul(x: Array, y: Array) -> Array:
    """Matmul at HIGHEST precision: on TPU the MXU's default fast path
    truncates fp32 operands to bf16, which is visibly lossy for metric values;
    HIGHEST selects the fp32-accurate (multi-pass) MXU mode."""
    return jnp.matmul(x, y.T, precision=jax.lax.Precision.HIGHEST)


def _safe_xlogy(x: Array, y: Array) -> Array:
    """x * log(y) with 0*log(0) := 0 (reference compute.py:31-42)."""
    res = jnp.where(x == 0.0, 0.0, x * jnp.log(jnp.where(x == 0.0, 1.0, y)))
    return res


def _safe_divide(num: Array, denom: Array, zero_division: float = 0.0) -> Array:
    """Division with 0/0 := zero_division (reference compute.py:45-54)."""
    num = num if jnp.issubdtype(jnp.asarray(num).dtype, jnp.floating) else jnp.asarray(num, jnp.float32)
    denom = denom if jnp.issubdtype(jnp.asarray(denom).dtype, jnp.floating) else jnp.asarray(denom, jnp.float32)
    zero_mask = denom == 0
    return jnp.where(zero_mask, zero_division, num / jnp.where(zero_mask, 1.0, denom))


def _adjust_weights_safe_divide(
    score: Array, average: Optional[str], multilabel: bool, tp: Array, fp: Array, fn: Array
) -> Array:
    """Apply micro/macro/weighted/none weighting to per-class scores
    (reference compute.py:57-86)."""
    if average is None or average == "none":
        return score
    if average == "weighted":
        weights = (tp + fn).astype(jnp.float32)
    else:
        weights = jnp.ones_like(score)
        if not multilabel:
            # macro: classes absent from both preds & target are excluded
            weights = jnp.where((tp + fp + fn) == 0, 0.0, weights)
    return jnp.sum(_safe_divide(weights, jnp.sum(weights, axis=-1, keepdims=True)) * score, axis=-1)


def _auc_compute_without_check(x: Array, y: Array, direction: float, axis: int = -1) -> Array:
    """Trapezoidal area under (x, y) assuming sorted x (reference compute.py:89-104)."""
    dx = jnp.diff(x, axis=axis)
    mean_y = (jax.lax.slice_in_dim(y, 1, None, axis=axis) + jax.lax.slice_in_dim(y, 0, -1, axis=axis)) / 2.0
    return jnp.sum(mean_y * dx, axis=axis) * direction


def _auc_compute(x: Array, y: Array, reorder: bool = False) -> Array:
    """AUC with optional reorder and direction detection (reference compute.py:107-127).

    jit-safe: direction is computed with ``where`` instead of a data-dependent
    python branch.
    """
    if reorder:
        order = jnp.argsort(x)
        x, y = x[order], y[order]
    dx = jnp.diff(x)
    any_neg = jnp.any(dx < 0)
    all_nonpos = jnp.all(dx <= 0)
    direction = jnp.where(any_neg, jnp.where(all_nonpos, -1.0, jnp.nan), 1.0)
    return _auc_compute_without_check(x, y, direction)


def auc(x: Array, y: Array, reorder: bool = False) -> Array:
    """Area under curve (trapezoidal), public helper (reference compute.py:130-132)."""
    if x.ndim != 1 or y.ndim != 1 or x.shape[0] != y.shape[0]:
        raise ValueError(
            f"Expected both `x` and `y` to be 1d arrays of the same size, got {x.shape} and {y.shape}"
        )
    return _auc_compute(x, y, reorder=reorder)


def interp(x: Array, xp: Array, fp: Array) -> Array:
    """1-D linear interpolation replicating the reference's ``interp``
    (reference compute.py:134-157) — NOT ``np.interp``: out-of-range points
    extrapolate along the edge segments, segment lookup is the count of
    ``xp`` values <= x (which the macro curve-averaging paths rely on, where
    ``xp`` is a precision/fpr curve that need not be monotonic), and
    zero-width segments get slope 0 via the safe divide."""
    scalar = jnp.ndim(x) == 0
    x1 = jnp.atleast_1d(x)
    m = _safe_divide(fp[1:] - fp[:-1], xp[1:] - xp[:-1])
    b = fp[:-1] - m * xp[:-1]
    # the (x, xp) comparison counts are evaluated in bounded chunks: one
    # dense (len(x), len(xp)) bool matrix is quadratic on the macro paths
    # (x is the concatenated per-class grid), while per-chunk matrices stay
    # constant-size; the chunk count is shape-derived, so this stays
    # jit-compatible
    chunk = 4096
    if x1.shape[0] == 0:
        return x1.astype(jnp.result_type(fp.dtype, x1.dtype))
    idx_parts = []
    for lo in range(0, x1.shape[0], chunk):
        part = x1[lo : lo + chunk]
        idx_parts.append(jnp.sum(part[:, None] >= xp[None, :], axis=1) - 1)
    indices = jnp.clip(jnp.concatenate(idx_parts) if len(idx_parts) > 1 else idx_parts[0], 0, m.shape[0] - 1)
    out = m[indices] * x1 + b[indices]
    return out[0] if scalar else out


def normalize_logits_if_needed(tensor: Array, normalization: str) -> Array:
    """Apply sigmoid/softmax only when input looks like logits (outside [0,1]).

    jit-safe rewrite of the reference's data-dependent branch
    (functional/classification helpers): uses ``where`` on a global predicate.
    """
    is_prob = jnp.logical_and(jnp.min(tensor) >= 0, jnp.max(tensor) <= 1)
    if normalization == "sigmoid":
        return jnp.where(is_prob, tensor, jax.nn.sigmoid(tensor))
    if normalization == "softmax":
        return jnp.where(is_prob, tensor, jax.nn.softmax(tensor, axis=1))
    return tensor


# ---- scatter-free counting contractions -----------------------------------
#
# TPU scatter-adds serialize, so count-shaped reductions (confusion matrices,
# contingency tables, histograms) are computed as one-hot MXU matmuls where
# the operands fit. The two gates below are shared by every such path:
#
# EXACT_F32_COUNT: largest sample count whose partial sums stay exactly
#   representable in the MXU's f32 accumulator (0/1 operands are exact in
#   bf16, so exactness is bounded only by the accumulator).
# ONEHOT_HBM_ELEMS: largest one-hot / comparison operand (in elements) we are
#   willing to materialize in HBM before falling back to an O(N) scatter.
EXACT_F32_COUNT = 1 << 24
ONEHOT_HBM_ELEMS = 1 << 27


def masked_onehot_count_matmul(
    row_labels: Array,
    col_labels: Array,
    num_rows: int,
    num_cols: int,
    valid: Optional[Array] = None,
) -> Optional[Array]:
    """(num_rows, num_cols) co-occurrence counts as a one-hot MXU matmul.

    ``counts[i, j] = Σ_n valid · (row==i) · (col==j)`` — exact (f32 integer
    counts, see :data:`EXACT_F32_COUNT`); out-of-range labels one-hot to a
    zero row and drop out, matching sentinel-bucket scatter semantics.
    Returns ``None`` when the inputs exceed the exactness or HBM gates — the
    caller falls back to its O(N)-memory scatter path.
    """
    n = row_labels.shape[0]
    if n >= EXACT_F32_COUNT or n * max(num_rows, num_cols) > ONEHOT_HBM_ELEMS:
        return None
    rows = jax.nn.one_hot(row_labels, num_rows, dtype=jnp.float32)
    if valid is not None:
        rows = rows * valid.astype(jnp.float32)[:, None]
    cols = jax.nn.one_hot(col_labels, num_cols, dtype=jnp.float32)
    return rows.T @ cols
