"""Input checking utilities.

Counterpart of the reference's ``utilities/checks.py``
(/root/reference/src/torchmetrics/utilities/checks.py). Validation runs
host-side in eager mode and is automatically skipped for traced (jit) inputs
— shape checks remain (shapes are static under jit), value checks that would
force a device sync are bypassed.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


def _is_tracer(*xs: Any) -> bool:
    return any(isinstance(x, jax.core.Tracer) for x in xs)


def _check_same_shape(preds: Array, target: Array) -> None:
    """Check that predictions and target have the same shape (reference checks.py:39-46)."""
    if preds.shape != target.shape:
        raise RuntimeError(
            f"Predictions and targets are expected to have the same shape, "
            f"but got {preds.shape} and {target.shape}."
        )


def is_overridden(method_name: str, instance: object, parent: type) -> bool:
    """Whether ``instance`` overrides ``parent.method_name`` (reference checks.py:741-752)."""
    instance_attr = getattr(type(instance), method_name, None)
    parent_attr = getattr(parent, method_name, None)
    return instance_attr is not None and instance_attr is not parent_attr


def check_forward_full_state_property(
    metric_class: type,
    init_args: Optional[Dict[str, Any]] = None,
    input_args: Optional[Dict[str, Any]] = None,
    num_update_to_compare: Sequence[int] = (10, 100, 1000),
    reps: int = 5,
) -> None:
    """Empirically time ``forward`` with ``full_state_update=True`` vs ``False``.

    Port of the reference's developer profiling tool (checks.py:636-740): runs
    both variants for each update count, prints the timings and a
    recommendation for the class's ``full_state_update`` flag.
    """
    init_args = init_args or {}
    input_args = input_args or {}

    class FullState(metric_class):  # type: ignore[misc,valid-type]
        full_state_update = True

    class PartState(metric_class):  # type: ignore[misc,valid-type]
        full_state_update = False

    fullstate = FullState(**init_args)
    partstate = PartState(**init_args)

    equal = True
    try:
        for _ in range(num_update_to_compare[0]):
            out1 = fullstate(**input_args)
            out2 = partstate(**input_args)
        equal = equal & bool(jnp.allclose(jnp.asarray(out1), jnp.asarray(out2)))
    except Exception:
        equal = False

    res = jnp.zeros((2, len(num_update_to_compare), reps))
    for i, metric in enumerate([fullstate, partstate]):
        for j, t in enumerate(num_update_to_compare):
            for r in range(reps):
                metric.reset()
                start = time.perf_counter()
                for _ in range(t):
                    _ = metric(**input_args)
                jax.block_until_ready(metric.compute())
                end = time.perf_counter()
                res = res.at[i, j, r].set(end - start)

    mean = jnp.mean(res, axis=-1)
    std = jnp.std(res, axis=-1)
    print("Timings using full_state_update=True / False:")
    for j, t in enumerate(num_update_to_compare):
        print(
            f"  {t} updates: full={float(mean[0, j]):.4f}s±{float(std[0, j]):.4f} "
            f"partial={float(mean[1, j]):.4f}s±{float(std[1, j]):.4f}"
        )
    faster = bool((mean[1, -1] < mean[0, -1]).item())
    if not equal:
        print(
            "Output of the metric differs between full_state_update=True and False; "
            "the recommendation is to set the flag to True."
        )
    else:
        print(f"Recommended setting: `full_state_update={not faster}`")


def _try_proceed_with_timeout(fn: Callable, timeout: int = 15) -> bool:
    """Run ``fn`` guarding against hangs (download guard, reference checks.py:766-795)."""
    import multiprocessing

    proc = multiprocessing.Process(target=fn)
    proc.start()
    proc.join(timeout)
    if not proc.is_alive():
        return proc.exitcode == 0
    proc.terminate()
    proc.join()
    return False
