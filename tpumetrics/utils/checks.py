"""Input checking utilities.

Counterpart of the reference's ``utilities/checks.py``
(/root/reference/src/torchmetrics/utilities/checks.py). Validation runs
host-side in eager mode and is automatically skipped for traced (jit) inputs
— shape checks remain (shapes are static under jit), value checks that would
force a device sync are bypassed.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


def _is_tracer(*xs: Any) -> bool:
    return any(isinstance(x, jax.core.Tracer) for x in xs)


def _check_same_shape(preds: Array, target: Array) -> None:
    """Check that predictions and target have the same shape (reference checks.py:39-46)."""
    if preds.shape != target.shape:
        raise RuntimeError(
            f"Predictions and targets are expected to have the same shape, "
            f"but got {preds.shape} and {target.shape}."
        )


def is_overridden(method_name: str, instance: object, parent: type) -> bool:
    """Whether ``instance`` overrides ``parent.method_name`` (reference checks.py:741-752)."""
    instance_attr = getattr(type(instance), method_name, None)
    parent_attr = getattr(parent, method_name, None)
    return instance_attr is not None and instance_attr is not parent_attr


def check_forward_full_state_property(
    metric_class: type,
    init_args: Optional[Dict[str, Any]] = None,
    input_args: Optional[Dict[str, Any]] = None,
    num_update_to_compare: Sequence[int] = (10, 100, 1000),
    reps: int = 5,
) -> None:
    """Empirically time ``forward`` with ``full_state_update=True`` vs ``False``.

    Port of the reference's developer profiling tool (checks.py:636-740): runs
    both variants for each update count, prints the timings and a
    recommendation for the class's ``full_state_update`` flag.
    """
    init_args = init_args or {}
    input_args = input_args or {}

    class FullState(metric_class):  # type: ignore[misc,valid-type]
        full_state_update = True

    class PartState(metric_class):  # type: ignore[misc,valid-type]
        full_state_update = False

    fullstate = FullState(**init_args)
    partstate = PartState(**init_args)

    equal = True
    try:
        for _ in range(num_update_to_compare[0]):
            out1 = fullstate(**input_args)
            out2 = partstate(**input_args)
        equal = equal & bool(jnp.allclose(jnp.asarray(out1), jnp.asarray(out2)))
    except Exception:
        equal = False

    res = jnp.zeros((2, len(num_update_to_compare), reps))
    for i, metric in enumerate([fullstate, partstate]):
        for j, t in enumerate(num_update_to_compare):
            for r in range(reps):
                metric.reset()
                start = time.perf_counter()
                for _ in range(t):
                    _ = metric(**input_args)
                jax.block_until_ready(metric.compute())
                end = time.perf_counter()
                res = res.at[i, j, r].set(end - start)

    mean = jnp.mean(res, axis=-1)
    std = jnp.std(res, axis=-1)
    print("Timings using full_state_update=True / False:")
    for j, t in enumerate(num_update_to_compare):
        print(
            f"  {t} updates: full={float(mean[0, j]):.4f}s±{float(std[0, j]):.4f} "
            f"partial={float(mean[1, j]):.4f}s±{float(std[1, j]):.4f}"
        )
    faster = bool((mean[1, -1] < mean[0, -1]).item())
    if not equal:
        print(
            "Output of the metric differs between full_state_update=True and False; "
            "the recommendation is to set the flag to True."
        )
    else:
        print(f"Recommended setting: `full_state_update={not faster}`")


def _try_proceed_with_timeout(fn: Callable, timeout: int = 15) -> bool:
    """Run ``fn`` guarding against hangs (download guard, reference checks.py:766-795)."""
    import multiprocessing

    proc = multiprocessing.Process(target=fn)
    proc.start()
    proc.join(timeout)
    if not proc.is_alive():
        return proc.exitcode == 0
    proc.terminate()
    proc.join()
    return False

# ------------------------------------------------------- retrieval inputs


def _check_retrieval_target_and_prediction_types(
    preds: Array, target: Array, allow_non_binary_target: bool = False
) -> Tuple[Array, Array]:
    """Dtype checks + flatten for retrieval inputs (reference checks.py:598-630)."""
    if jnp.issubdtype(target.dtype, jnp.complexfloating):
        raise ValueError("`target` must be a tensor of booleans, integers or floats")
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError("`preds` must be a tensor of floats")
    if not allow_non_binary_target and not _is_tracer(target):
        if bool((target.max() > 1) | (target.min() < 0)):
            raise ValueError("`target` must contain `binary` values")
    target = target.astype(jnp.float32)
    preds = preds.astype(jnp.float32)
    return preds.ravel(), target.ravel()


def _check_retrieval_functional_inputs(
    preds: Array, target: Array, allow_non_binary_target: bool = False
) -> Tuple[Array, Array]:
    """Shape/dtype validation for single-query retrieval functions
    (reference checks.py:509-538)."""
    if preds.shape != target.shape:
        raise ValueError("`preds` and `target` must be of the same shape")
    if preds.size == 0 or preds.ndim == 0:
        raise ValueError("`preds` and `target` must be non-empty and non-scalar tensors")
    return _check_retrieval_target_and_prediction_types(preds, target, allow_non_binary_target)


def _check_retrieval_inputs(
    indexes: Array,
    preds: Array,
    target: Array,
    allow_non_binary_target: bool = False,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array, Optional[Array]]:
    """Shape/dtype validation for batched retrieval updates (reference
    checks.py:541-595).

    Where the reference physically drops rows whose target equals
    ``ignore_index`` (a shape change), this returns a keep-mask as a fourth
    value — jit-safe, and exact on the eager path too (masked rows are
    dropped by the list-state append, routed to the dump slot by buffers).
    """
    if indexes.shape != preds.shape or preds.shape != target.shape:
        raise ValueError("`indexes`, `preds` and `target` must be of the same shape")
    if not jnp.issubdtype(indexes.dtype, jnp.integer):
        raise ValueError("`indexes` must be a tensor of long integers")
    if indexes.size == 0 or indexes.ndim == 0:
        raise ValueError("`indexes`, `preds` and `target` must be non-empty and non-scalar tensors")

    keep = None
    if ignore_index is not None:
        keep = (target != ignore_index).ravel()
        # the binary-values check must only see kept rows (ignore_index
        # itself may be outside [0, 1], reference drops those rows first)
        target = jnp.where(target == ignore_index, jnp.zeros_like(target), target)

    preds, target = _check_retrieval_target_and_prediction_types(
        preds, target, allow_non_binary_target=allow_non_binary_target
    )
    return indexes.ravel().astype(jnp.int32), preds, target, keep
