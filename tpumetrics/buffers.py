"""Fixed-capacity masked buffers — jit-safe "cat"/ragged list states.

The reference accumulates variable-length state in Python lists of tensors
and syncs them with a pad-gather-trim collective (reference
``utilities/distributed.py:135-147``) or pickled object gather for truly
ragged state (reference ``detection/mean_ap.py:994-1024``). Neither shape
dance exists under XLA: compiled programs need static shapes. The TPU-native
redesign is a **fixed-capacity buffer + valid count**:

- ``values``: a preallocated ``(capacity, *feature)`` array,
- ``count``: how many leading rows are real data.

``append`` writes a batch at offset ``count`` with one scatter (optionally
masked, so a batch can contribute an *uneven, data-dependent* number of
rows while shapes stay static). Cross-device sync is one ``all_gather`` of
values+counts followed by a static-shape compaction scatter — the
pad-gather-trim of the reference becomes pad-gather-*mask*, fully inside the
compiled program, riding ICI. Off-trace, :func:`materialize` recovers the
exact variable-length array, so eager code paths behave exactly like the
reference's list states.

Overflow policy: rows beyond ``capacity`` are silently dropped (the dump-row
scatter). Size ``capacity`` to the worst-case number of accumulated samples;
:func:`buffer_overflowed` exposes the would-be count for host-side checks.
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


class MaskedBuffer(NamedTuple):
    """Fixed-capacity masked accumulation buffer (a pytree of two arrays)."""

    values: Array  # (capacity, *feature)
    count: Array  # () int32 — number of valid leading rows
    requested: Array  # () int32 — rows ever requested (== count unless overflowed)

    @property
    def capacity(self) -> int:
        return self.values.shape[0]

    def valid_mask(self) -> Array:
        """Boolean ``(capacity,)`` mask of rows holding real data."""
        return jnp.arange(self.capacity) < self.count


def create_buffer(capacity: int, feature_shape: Tuple[int, ...] = (), dtype: Any = jnp.float32) -> MaskedBuffer:
    """Fresh empty buffer of static shape ``(capacity, *feature_shape)``."""
    return MaskedBuffer(
        values=jnp.zeros((capacity,) + tuple(feature_shape), dtype=dtype),
        count=jnp.zeros((), dtype=jnp.int32),
        requested=jnp.zeros((), dtype=jnp.int32),
    )


def buffer_append(buf: MaskedBuffer, batch: Array, valid: Optional[Array] = None) -> MaskedBuffer:
    """Append ``batch`` rows (optionally only where ``valid``) at the write
    offset — one static-shape scatter, traceable under jit.

    ``valid`` enables data-dependent contribution counts with static shapes:
    invalid rows are routed to a dump slot past the end of the buffer, which
    is then trimmed away. Rows past capacity are dropped (see module note).
    """
    batch = jnp.asarray(batch)
    if batch.ndim == buf.values.ndim - 1:
        batch = batch[None]  # single row
    b = batch.shape[0]
    cap = buf.values.shape[0]
    if valid is None:
        valid = jnp.ones((b,), dtype=bool)
    valid = valid.astype(bool)
    pos = jnp.where(valid, buf.count + jnp.cumsum(valid.astype(jnp.int32)) - 1, cap)
    # invalid/overflow rows get out-of-bounds indices; scatter mode="drop"
    # discards them with no extra buffer copy
    new_values = buf.values.at[pos].set(batch.astype(buf.values.dtype), mode="drop")
    n_new = jnp.sum(valid.astype(jnp.int32))
    return MaskedBuffer(
        values=new_values,
        count=jnp.minimum(buf.count + n_new, cap),
        requested=buf.requested + n_new,
    )


def buffer_append_bucketed(buf: MaskedBuffer, padded: Array, n_valid: Array) -> MaskedBuffer:
    """Append the first ``n_valid`` rows of a bucket-padded batch.

    The runtime's shape-bucketed ingestion (``tpumetrics/runtime/bucketing``)
    pads ragged batches to a fixed set of edge sizes; this is the
    buffer-side half of that convention — the pad rows are routed to the
    dump slot by the derived mask, so a buffer-backed ("cat"-style) state
    absorbs a padded batch with static shapes and exact contents.
    """
    padded = jnp.asarray(padded)
    valid = jnp.arange(padded.shape[0]) < jnp.asarray(n_valid)
    return buffer_append(buf, padded, valid=valid)


def buffer_extend(buf: MaskedBuffer, other: MaskedBuffer) -> MaskedBuffer:
    """Append another buffer's valid rows (used when merging a batch state
    into a global state, e.g. ``forward``'s reduce-state merge).

    Overflow accounting carries over: rows the *source* buffer already
    dropped stay visible in the merged ``requested``, so
    :func:`buffer_overflowed` cannot be laundered away by a merge."""
    merged = buffer_append(buf, other.values, valid=other.valid_mask())
    return merged._replace(requested=buf.requested + other.requested)


def buffer_compact(stacked_values: Array, counts: Array) -> MaskedBuffer:
    """Compact per-rank buffers ``(W, cap, *f)`` with valid ``counts`` ``(W,)``
    into one ``(W*cap, *f)`` buffer — the static-shape replacement for the
    reference's gather-then-trim (utilities/distributed.py:141-147)."""
    w, cap = stacked_values.shape[0], stacked_values.shape[1]
    counts = counts.astype(jnp.int32)
    offsets = jnp.cumsum(counts) - counts
    idx = jnp.arange(cap)
    pos = offsets[:, None] + idx[None, :]  # (W, cap) global positions
    valid = idx[None, :] < counts[:, None]
    total = w * cap
    pos = jnp.where(valid, pos, total)  # invalid rows -> out of bounds, dropped
    flat = stacked_values.reshape((total,) + stacked_values.shape[2:])
    out = jnp.zeros((total,) + stacked_values.shape[2:], stacked_values.dtype)
    out = out.at[pos.reshape(-1)].set(flat, mode="drop")
    return MaskedBuffer(values=out, count=jnp.sum(counts), requested=jnp.sum(counts))


def buffer_all_gather(buf: MaskedBuffer, backend: Any, group: Optional[Any] = None) -> MaskedBuffer:
    """Gather + compact a buffer across ranks through a sync backend
    (in-trace: one XLA all_gather over ICI; eager: DCN process gather).

    Two wire ops per buffer: the values gather and one packed (count,
    requested) scalar gather.  Both are reported to the telemetry ledger
    here as logical ``"buffer_gather"`` records (``source="reducer"``, like
    a :class:`~tpumetrics.parallel.fuse.FusedReducer` flush reports its
    fused classes) so buffer-backed metrics keep wire-byte attribution even
    through a custom/uninstrumented backend; instrumented backends
    additionally record the actual wire calls (``source="backend"``) —
    aggregation never double counts because only backend-source records add
    to the wire totals.
    """
    from tpumetrics.telemetry import ledger as _telemetry

    packed = jnp.stack([buf.count, buf.requested]).astype(jnp.int32)
    if _telemetry.recording():  # static metadata only — trace-safe
        try:
            world = int(backend.world_size())
        except Exception:
            world = 1
        in_trace = bool(getattr(backend, "in_trace", False))
        for arr in (buf.values, packed):
            _telemetry.record_collective(
                backend, "buffer_gather", "gather", tuple(jnp.shape(arr)),
                arr.dtype, np.dtype(arr.dtype).itemsize,
                world, in_trace=in_trace, source="reducer", capacity=buf.capacity,
            )
    vals = backend.all_gather(buf.values, group)  # list of (cap, *f)
    meta = backend.all_gather(packed, group)
    stacked = jnp.stack(list(vals))
    meta_arr = jnp.stack([jnp.reshape(m, (2,)) for m in meta])  # (W, 2)
    merged = buffer_compact(stacked, meta_arr[:, 0])
    return MaskedBuffer(values=merged.values, count=merged.count, requested=jnp.sum(meta_arr[:, 1]))


def buffer_merge(bufs: Sequence[MaskedBuffer]) -> MaskedBuffer:
    """Merge same-capacity per-rank buffers eagerly (DCN/emulated-rank path)."""
    stacked = jnp.stack([b.values for b in bufs])
    counts = jnp.stack([jnp.reshape(b.count, ()) for b in bufs])
    merged = buffer_compact(stacked, counts)
    requested = sum((jnp.reshape(b.requested, ()) for b in bufs), start=jnp.zeros((), jnp.int32))
    return MaskedBuffer(values=merged.values, count=merged.count, requested=requested)


def buffer_overflowed(buf: MaskedBuffer) -> Array:
    """True when rows were dropped because capacity was exceeded."""
    return buf.requested > buf.count


def materialize(buf: MaskedBuffer) -> Array:
    """Exact variable-length contents ``values[:count]`` — **off-trace only**
    (the result shape is data-dependent)."""
    from tpumetrics.utils.data import _is_tracer

    if _is_tracer(buf.count) or _is_tracer(buf.values):
        raise ValueError(
            "materialize() of a MaskedBuffer is data-dependent and cannot run under jit;"
            " use masked_values() and mask-aware math inside compiled code."
        )
    return buf.values[: int(buf.count)]


def masked_values(
    state: Any, feature_shape: Tuple[int, ...] = (), dtype: Any = jnp.float32
) -> Tuple[Array, Array]:
    """Uniform (values, valid_mask) view of a cat-style state: a Python list
    of arrays (eager path — all rows valid) or a MaskedBuffer (jit path).

    ``feature_shape``/``dtype`` shape the zero-row result for an *empty* eager
    list (an empty list carries no shape information of its own); pass the
    state's declared spec so empty and non-empty states produce consistent
    downstream shapes and trace signatures.
    """
    from tpumetrics.utils.data import dim_zero_cat

    if isinstance(state, MaskedBuffer):
        return state.values, state.valid_mask()
    if isinstance(state, list):
        if not state:  # empty eager state mirrors an empty buffer, not an error
            return jnp.zeros((0,) + tuple(feature_shape), dtype=dtype), jnp.zeros((0,), dtype=bool)
        cat = dim_zero_cat(state)
        return cat, jnp.ones((cat.shape[0],), dtype=bool)
    if isinstance(state, (jnp.ndarray, jax.Array)):
        return state, jnp.ones((state.shape[0],), dtype=bool)
    raise TypeError(f"Unsupported cat-state type {type(state)}")


class _BufferList:
    """List-like adapter so subclass ``update`` code written for list states
    (``self.preds.append(x)``) transparently drives a MaskedBuffer when the
    metric runs through the functional/jit bridge."""

    __slots__ = ("buffer",)

    def __init__(self, buffer: MaskedBuffer) -> None:
        self.buffer = buffer

    def append(self, x: Array, valid: Optional[Array] = None) -> None:
        self.buffer = buffer_append(self.buffer, x, valid=valid)

    def __iter__(self):
        return iter([materialize(self.buffer)])

    def __len__(self) -> int:
        return 1
