"""ROUGEScore (counterpart of reference ``text/rouge.py``)."""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from tpumetrics.functional.text.rouge import (
    ALLOWED_ACCUMULATE_VALUES,
    ALLOWED_ROUGE_KEYS,
    _rouge_score_update,
)
from tpumetrics.metric import Metric
from tpumetrics.utils.data import dim_zero_cat
from tpumetrics.utils.imports import _NLTK_AVAILABLE

Array = jax.Array


class ROUGEScore(Metric):
    """ROUGE-N/L/Lsum accumulated over batches: per-key per-sentence score
    lists as cat states (reference text/rouge.py:143).

    Example:
        >>> from tpumetrics.text import ROUGEScore
        >>> rouge = ROUGEScore(rouge_keys="rouge1")
        >>> result = rouge(["My name is John"], ["Is your name John"])
        >>> round(float(result["rouge1_fmeasure"]), 4)
        0.75
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = True
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        use_stemmer: bool = False,
        normalizer: Optional[Callable[[str], str]] = None,
        tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
        accumulate: str = "best",
        rouge_keys: Union[str, Tuple[str, ...]] = ("rouge1", "rouge2", "rougeL", "rougeLsum"),
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if use_stemmer and not _NLTK_AVAILABLE:
            raise ModuleNotFoundError("Stemmer requires that `nltk` is installed.")
        if not isinstance(rouge_keys, tuple):
            rouge_keys = (rouge_keys,)
        for key in rouge_keys:
            if key not in ALLOWED_ROUGE_KEYS:
                raise ValueError(f"Got unknown rouge key {key}. Expected to be one of {list(ALLOWED_ROUGE_KEYS)}")
        if accumulate not in ALLOWED_ACCUMULATE_VALUES:
            raise ValueError(
                f"Got unknown accumulate value {accumulate}. Expected to be one of {ALLOWED_ACCUMULATE_VALUES}"
            )

        self.rouge_keys = rouge_keys
        self.rouge_keys_values = [ALLOWED_ROUGE_KEYS[key] for key in rouge_keys]
        self.use_stemmer = use_stemmer
        self.normalizer = normalizer
        self.tokenizer = tokenizer
        self.accumulate = accumulate

        if use_stemmer:
            import nltk

            self.stemmer = nltk.stem.porter.PorterStemmer()
        else:
            self.stemmer = None

        for rouge_key in self.rouge_keys:
            for score_type in ("fmeasure", "precision", "recall"):
                self.add_state(f"{rouge_key}_{score_type}", default=[], dist_reduce_fx="cat")

    def update(
        self,
        preds: Union[str, Sequence[str]],
        target: Union[str, Sequence[str], Sequence[Sequence[str]]],
    ) -> None:
        """Accumulate per-sentence rouge scores."""
        if isinstance(target, list) and all(isinstance(tgt, str) for tgt in target):
            target = [target] if isinstance(preds, str) else [[tgt] for tgt in target]
        if isinstance(preds, str):
            preds = [preds]
        if isinstance(target, str):
            target = [[target]]

        output = _rouge_score_update(
            preds,
            target,
            self.rouge_keys_values,
            accumulate=self.accumulate,
            stemmer=self.stemmer,
            normalizer=self.normalizer,
            tokenizer=self.tokenizer,
        )
        for rouge_key, metrics in output.items():
            suffix = rouge_key if isinstance(rouge_key, str) else str(rouge_key)
            for metric in metrics:
                for score_type, score in metric.items():
                    getattr(self, f"rouge{suffix}_{score_type}").append(
                        jnp.asarray([score], jnp.float32)
                    )

    def compute(self) -> Dict[str, Array]:
        """Mean per rouge key/score type (reference text/rouge.py compute)."""
        update_output = {}
        for rouge_key in self.rouge_keys:
            for score_type in ("fmeasure", "precision", "recall"):
                vals = getattr(self, f"{rouge_key}_{score_type}")
                update_output[f"{rouge_key}_{score_type}"] = (
                    jnp.mean(dim_zero_cat(vals)) if vals else jnp.zeros(())
                )
        return update_output

    def __hash__(self) -> int:
        # cat list states of variable length: hash over names + lengths
        hash_vals = [self.__class__.__name__]
        for key in self._defaults:
            val = getattr(self, key)
            hash_vals.append(len(val) if isinstance(val, list) else val)
        return hash(tuple(hash_vals))
