"""MatchErrorRate (counterpart of reference ``text/mer.py``)."""

from __future__ import annotations

from typing import Any, List, Union

import jax
import jax.numpy as jnp

from tpumetrics.functional.text.mer import _mer_compute, _mer_update
from tpumetrics.metric import Metric

Array = jax.Array


class MatchErrorRate(Metric):
    """Match error rate accumulated over batches.

    Example:
        >>> from tpumetrics.text import MatchErrorRate
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> mer = MatchErrorRate()
        >>> round(float(mer(preds, target)), 4)
        0.4444
    """

    is_differentiable: bool = False
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", default=jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        """Accumulate edit distances and max-length counts."""
        errors, total = _mer_update(preds, target)
        self.errors = self.errors + errors
        self.total = self.total + total

    def compute(self) -> Array:
        return _mer_compute(self.errors, self.total)
