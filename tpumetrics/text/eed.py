"""ExtendedEditDistance (counterpart of reference ``text/eed.py``)."""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from tpumetrics.functional.text.eed import _eed_compute, _eed_update
from tpumetrics.metric import Metric
from tpumetrics.utils.data import dim_zero_cat

Array = jax.Array


class ExtendedEditDistance(Metric):
    """EED accumulated over batches (sentence scores as a cat state).

    Example:
        >>> from tpumetrics.text import ExtendedEditDistance
        >>> preds = ["this is the prediction", "here is an other sample"]
        >>> target = ["this is the reference", "here is another one"]
        >>> eed = ExtendedEditDistance()
        >>> round(float(eed(preds, target)), 4)
        0.3078
    """

    is_differentiable: bool = False
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        language: str = "en",
        return_sentence_level_score: bool = False,
        alpha: float = 2.0,
        rho: float = 0.3,
        deletion: float = 0.2,
        insertion: float = 1.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if language not in ("en", "ja"):
            raise ValueError(f"Expected argument `language` to either be `en` or `ja` but got {language}")
        self.language = language
        self.return_sentence_level_score = return_sentence_level_score
        for param_name, param in (("alpha", alpha), ("rho", rho), ("deletion", deletion), ("insertion", insertion)):
            if not isinstance(param, float) or param < 0:
                raise ValueError(f"Parameter `{param_name}` is expected to be a non-negative float.")
        self.alpha = alpha
        self.rho = rho
        self.deletion = deletion
        self.insertion = insertion

        self.add_state("sentence_eed", default=[], dist_reduce_fx="cat")

    def update(self, preds: Union[str, Sequence[str]], target: Sequence[Union[str, Sequence[str]]]) -> None:
        """Accumulate sentence scores."""
        scores = _eed_update(
            preds, target, self.language, self.alpha, self.rho, self.deletion, self.insertion
        )
        if scores:
            self.sentence_eed.append(jnp.asarray(scores, jnp.float32))

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        if not self.sentence_eed:
            average = jnp.zeros(())
            sentence_scores = jnp.zeros((0,), jnp.float32)
        else:
            sentence_scores = dim_zero_cat(self.sentence_eed)
            average = sentence_scores.mean()
        if self.return_sentence_level_score:
            return average, sentence_scores
        return average
