"""Shared sync policy for metrics whose state includes raw Python sentences
(BERTScore, InfoLM): strings live outside the array sync path.  Across
processes (DCN) they travel through the backend's ``all_gather_object``
host-object wire — the analogue of the reference running its tokenized cat
states through ``all_gather`` (reference text/bert.py:191-194).  Inside a
trace there is no host channel, so an in-trace sync is refused unless the
caller declares the corpus replicated on every rank."""

from __future__ import annotations


class HostSentenceStateMixin:
    """Mixin syncing host-side sentence buffers via object-gather.

    Subclasses set ``self.sentences_replicated`` in ``__init__`` and keep
    their sentence buffers in ``self._preds`` / ``self._target``.
    """

    sentences_replicated: bool = False
    _sentence_cache = None

    @property
    def sentence_state(self):
        """The accumulated (predictions, references) sentence lists — the
        public handle for a manual multi-host object-gather: gather both
        lists from every rank (e.g. over DCN), feed the union into one
        metric, compute once.  Returns copies; mutating them does not touch
        the metric."""
        return list(self._preds), list(self._target)

    def _sync_dist(self, dist_sync_fn=None, process_group=None, _reducer=None):
        from tpumetrics.metric import TPUMetricsUserError

        if self.sentences_replicated:
            # array states sync normally; sentence lists are identical by
            # declaration. A custom dist_sync_fn alone is NOT enough — it
            # only sees the array states, never the strings.
            return super()._sync_dist(
                dist_sync_fn=dist_sync_fn, process_group=process_group, _reducer=_reducer
            )

        if getattr(self, "dist_sync_on_step", False):
            # forward()'s in-step sync saves/restores *registered* states only
            # (metric.py:346-362); the unregistered sentence lists would be
            # merged but never restored — silent corpus corruption. The
            # pre-object-gather behavior (always raise) is kept for this flag.
            raise TPUMetricsUserError(
                f"{type(self).__name__} keeps raw sentences as host-side state and does"
                " not support dist_sync_on_step=True (forward's per-step sync cannot"
                " restore host-side sentence buffers). Sync once at compute() instead,"
                " or replicate sentences on every rank with sentences_replicated=True."
            )
        if dist_sync_fn is not None:
            # a custom gather fn only ever sees the array states; letting it
            # run would merge arrays while silently keeping one rank's
            # sentence shard
            raise TPUMetricsUserError(
                f"{type(self).__name__} keeps raw sentences as host-side state; a custom"
                " dist_sync_fn cannot move them (it only sees array states). Either"
                " drop dist_sync_fn (the backend's host-object channel syncs sentences),"
                " compute per process and aggregate the returned scores, or replicate"
                " the sentences to every rank and construct with"
                " sentences_replicated=True."
            )

        backend = self._active_backend()
        group = process_group or self.process_group
        try:
            gathered = backend.all_gather_object(
                (list(self._preds), list(self._target)), group=group
            )
        except NotImplementedError:
            raise TPUMetricsUserError(
                f"{type(self).__name__} keeps raw sentences as host-side state, and the"
                f" active backend ({type(backend).__name__}) has no host-object channel"
                " to sync them (in-trace collectives move arrays only). Either compute"
                " per process and aggregate the returned scores, or replicate the"
                " sentences to every rank before update() and construct with"
                " sentences_replicated=True (or sync_on_compute=False)."
            ) from None
        # merge the array states first: if that fails, the sentence buffers
        # are still untouched and a retried sync re-gathers the local shard
        # (under a shared reducer the array apply defers to the returned
        # finalize; the sentence swap below stays immediate)
        finalize = super()._sync_dist(
            dist_sync_fn=dist_sync_fn, process_group=process_group, _reducer=_reducer
        )
        self._sentence_cache = (self._preds, self._target)
        self._preds = [p for rank_preds, _ in gathered for p in rank_preds]
        self._target = [t for _, rank_target in gathered for t in rank_target]
        return finalize

    def unsync(self, should_unsync: bool = True) -> None:
        super().unsync(should_unsync)
        if should_unsync and self._sentence_cache is not None:
            self._preds, self._target = self._sentence_cache
            self._sentence_cache = None

    def reset(self) -> None:
        super().reset()
        self._sentence_cache = None
