"""Shared sync policy for metrics whose state includes raw Python sentences
(BERTScore, InfoLM): strings live outside the array sync path, so a
cross-process sync is refused unless the caller declares the corpus
replicated on every rank."""

from __future__ import annotations


class HostSentenceStateMixin:
    """Mixin refusing dist-sync of host-side sentence buffers.

    Subclasses set ``self.sentences_replicated`` in ``__init__`` and keep
    their sentence buffers in ``self._preds`` / ``self._target``.
    """

    sentences_replicated: bool = False

    @property
    def sentence_state(self):
        """The accumulated (predictions, references) sentence lists — the
        public handle for a multi-host object-gather: gather both lists from
        every rank (e.g. over DCN), feed the union into one metric, compute
        once.  Returns copies; mutating them does not touch the metric."""
        return list(self._preds), list(self._target)

    def _sync_dist(self, dist_sync_fn=None, process_group=None) -> None:
        from tpumetrics.metric import TPUMetricsUserError

        if self.sentences_replicated:
            # array states sync normally; sentence lists are identical by
            # declaration. A custom dist_sync_fn alone is NOT enough — it
            # only sees the array states, never the strings.
            return super()._sync_dist(dist_sync_fn=dist_sync_fn, process_group=process_group)
        raise TPUMetricsUserError(
            f"{type(self).__name__} keeps raw sentences as host-side state and cannot"
            " dist-sync them. Either compute per process and aggregate the returned"
            " scores, or replicate the sentences to every rank before update() and"
            " construct with sentences_replicated=True (or sync_on_compute=False)."
        )
