"""EditDistance (counterpart of reference ``text/edit.py``)."""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from tpumetrics.functional.text.edit import _edit_distance_compute, _edit_distance_update
from tpumetrics.metric import Metric
from tpumetrics.utils.data import dim_zero_cat

Array = jax.Array


class EditDistance(Metric):
    """Character-level Levenshtein distance accumulated over batches.

    Args:
        substitution_cost: cost of a substitution operation.
        reduction: ``mean``/``sum``/``none`` over accumulated pair distances.

    Example:
        >>> from tpumetrics.text import EditDistance
        >>> metric = EditDistance()
        >>> float(metric(["rain"], ["shine"]))
        3.0
    """

    is_differentiable: bool = False
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(self, substitution_cost: int = 1, reduction: Optional[str] = "mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(substitution_cost, int) and substitution_cost >= 0):
            raise ValueError(
                f"Expected argument `substitution_cost` to be a positive integer, but got {substitution_cost}"
            )
        self.substitution_cost = substitution_cost
        allowed_reduction = (None, "mean", "sum", "none")
        if reduction not in allowed_reduction:
            raise ValueError(f"Expected argument `reduction` to be one of {allowed_reduction}, but got {reduction}")
        self.reduction = reduction

        if self.reduction == "none" or self.reduction is None:
            self.add_state("edit_scores_list", default=[], dist_reduce_fx="cat", feature_dtype=jnp.int32)
        else:
            self.add_state("edit_scores", default=jnp.zeros((), jnp.int32), dist_reduce_fx="sum")
            self.add_state("num_elements", default=jnp.zeros((), jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Union[str, Sequence[str]], target: Union[str, Sequence[str]]) -> None:
        """Accumulate per-pair edit distances."""
        distances = _edit_distance_update(preds, target, self.substitution_cost)
        if self.reduction == "none" or self.reduction is None:
            self.edit_scores_list.append(distances)
        else:
            self.edit_scores = self.edit_scores + distances.sum()
            self.num_elements = self.num_elements + distances.shape[0]

    def compute(self) -> Array:
        if self.reduction == "none" or self.reduction is None:
            return dim_zero_cat(self.edit_scores_list)
        return _edit_distance_compute(
            jnp.atleast_1d(self.edit_scores), self.num_elements, self.reduction
        )
