"""InfoLM metric (counterpart of reference ``text/infolm.py:41``)."""

from __future__ import annotations

from typing import Any, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from tpumetrics.functional.text.infolm import _InformationMeasure, infolm
from tpumetrics.metric import Metric
from tpumetrics.text._sentence_state import HostSentenceStateMixin

Array = jax.Array


class InfoLM(HostSentenceStateMixin, Metric):
    """InfoLM accumulated over batches (sentences stored, embedded at compute
    like :class:`~tpumetrics.text.bert.BERTScore`).

    Example:
        >>> from tpumetrics.text import InfoLM
        >>> metric = InfoLM(model_name_or_path='google/bert_uncased_L-2_H-128_A-2')  # doctest: +SKIP
        >>> metric.update(['the cat sat'], ['a cat sat'])  # doctest: +SKIP
        >>> float(metric.compute())  # doctest: +SKIP
        -0.1784
    """

    is_differentiable: bool = False
    higher_is_better: bool = False
    full_state_update: bool = False

    def __init__(
        self,
        model_name_or_path: str = "bert-base-uncased",
        temperature: float = 0.25,
        information_measure: str = "kl_divergence",
        idf: bool = True,
        alpha: Optional[float] = None,
        beta: Optional[float] = None,
        device: Optional[Any] = None,
        max_length: Optional[int] = None,
        batch_size: int = 64,
        num_threads: int = 0,
        verbose: bool = True,
        return_sentence_level_score: bool = False,
        model: Optional[Any] = None,
        user_tokenizer: Optional[Any] = None,
        sentences_replicated: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.sentences_replicated = sentences_replicated
        _InformationMeasure(information_measure, alpha, beta)  # validate early
        self.model_name_or_path = model_name_or_path
        self.temperature = temperature
        self.information_measure = information_measure
        self.idf = idf
        self.alpha = alpha
        self.beta = beta
        self.max_length = max_length
        self.batch_size = batch_size
        self.return_sentence_level_score = return_sentence_level_score
        self.model = model
        self.user_tokenizer = user_tokenizer

        self._preds: List[str] = []
        self._target: List[str] = []
        self.add_state("dummy", jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        """Store sentences for the compute-time model pass."""
        if isinstance(preds, str):
            preds = [preds]
        if isinstance(target, str):
            target = [target]
        if len(preds) != len(target):
            raise ValueError(
                f"Expected argument `preds` and `target` to have same length, but got {len(preds)} and {len(target)}"
            )
        self._preds.extend(preds)
        self._target.extend(target)

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        return infolm(
            self._preds,
            self._target,
            model_name_or_path=self.model_name_or_path,
            temperature=self.temperature,
            information_measure=self.information_measure,
            idf=self.idf,
            alpha=self.alpha,
            beta=self.beta,
            max_length=self.max_length,
            batch_size=self.batch_size,
            return_sentence_level_score=self.return_sentence_level_score,
            model=self.model,
            user_tokenizer=self.user_tokenizer,
        )

    def reset(self) -> None:
        super().reset()
        self._preds = []
        self._target = []

