"""InfoLM metric (counterpart of reference ``text/infolm.py:41``)."""

from __future__ import annotations

from typing import Any, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from tpumetrics.functional.text.infolm import _InformationMeasure, infolm
from tpumetrics.metric import Metric
from tpumetrics.text._sentence_state import HostSentenceStateMixin

Array = jax.Array


class _BackboneMLM:
    """Adapter presenting a shared backbone handle as InfoLM's masked-LM
    model protocol (``model(input_ids=, attention_mask=).logits``).

    The handle's forward is ``(params, input_ids, attention_mask) ->
    (B, S, V) logits``; dispatching through the handle gives InfoLM's
    per-chunk model pass the shared engine's jit + pow-2 bucketing + donated
    staging buffers — the raw model call in
    ``functional/text/infolm.py::_sentence_distribution`` is eager.
    """

    def __init__(self, handle: Any) -> None:
        self.handle = handle

    def __call__(self, input_ids: Any = None, attention_mask: Any = None, **_: Any):
        from types import SimpleNamespace

        return SimpleNamespace(logits=self.handle(input_ids, attention_mask))


class InfoLM(HostSentenceStateMixin, Metric):
    """InfoLM accumulated over batches (sentences stored, embedded at compute
    like :class:`~tpumetrics.text.bert.BERTScore`).

    Example:
        >>> from tpumetrics.text import InfoLM
        >>> metric = InfoLM(model_name_or_path='google/bert_uncased_L-2_H-128_A-2')  # doctest: +SKIP
        >>> metric.update(['the cat sat'], ['a cat sat'])  # doctest: +SKIP
        >>> float(metric.compute())  # doctest: +SKIP
        -0.1784
    """

    is_differentiable: bool = False
    higher_is_better: bool = False
    full_state_update: bool = False

    def __init__(
        self,
        model_name_or_path: str = "bert-base-uncased",
        temperature: float = 0.25,
        information_measure: str = "kl_divergence",
        idf: bool = True,
        alpha: Optional[float] = None,
        beta: Optional[float] = None,
        device: Optional[Any] = None,
        max_length: Optional[int] = None,
        batch_size: int = 64,
        num_threads: int = 0,
        verbose: bool = True,
        return_sentence_level_score: bool = False,
        model: Optional[Any] = None,
        user_tokenizer: Optional[Any] = None,
        sentences_replicated: bool = False,
        backbone: Optional[Any] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.sentences_replicated = sentences_replicated
        _InformationMeasure(information_measure, alpha, beta)  # validate early
        if backbone is not None:
            if user_tokenizer is None:
                raise ValueError("`user_tokenizer` must be provided together with a `backbone`")
            if model is not None:
                raise ValueError("Pass either `model` or `backbone`, not both")
            # the metric owns one registry reference (release_backbones());
            # the adapter routes the masked-LM forward through the shared
            # engine (jit + bucketing + donation) instead of the eager call
            self._backbone_handles = (backbone.acquire(),)
            self.backbone_key = backbone.key
            model = _BackboneMLM(backbone)
        self.model_name_or_path = model_name_or_path
        self.temperature = temperature
        self.information_measure = information_measure
        self.idf = idf
        self.alpha = alpha
        self.beta = beta
        self.max_length = max_length
        self.batch_size = batch_size
        self.return_sentence_level_score = return_sentence_level_score
        self.model = model
        self.user_tokenizer = user_tokenizer

        self._preds: List[str] = []
        self._target: List[str] = []
        self.add_state("dummy", jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        """Store sentences for the compute-time model pass."""
        if isinstance(preds, str):
            preds = [preds]
        if isinstance(target, str):
            target = [target]
        if len(preds) != len(target):
            raise ValueError(
                f"Expected argument `preds` and `target` to have same length, but got {len(preds)} and {len(target)}"
            )
        self._preds.extend(preds)
        self._target.extend(target)

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        return infolm(
            self._preds,
            self._target,
            model_name_or_path=self.model_name_or_path,
            temperature=self.temperature,
            information_measure=self.information_measure,
            idf=self.idf,
            alpha=self.alpha,
            beta=self.beta,
            max_length=self.max_length,
            batch_size=self.batch_size,
            return_sentence_level_score=self.return_sentence_level_score,
            model=self.model,
            user_tokenizer=self.user_tokenizer,
        )

    def reset(self) -> None:
        super().reset()
        self._preds = []
        self._target = []

