"""CHRFScore (counterpart of reference ``text/chrf.py``)."""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from tpumetrics.functional.text.chrf import _chrf_score_compute, _chrf_score_update
from tpumetrics.metric import Metric
from tpumetrics.utils.data import dim_zero_cat

Array = jax.Array


class CHRFScore(Metric):
    """chrF/chrF++ accumulated over batches. Where the reference keeps six
    dicts of scalar states (reference text/chrf.py class), the totals here
    are a single (6, max_order) sum state — one psum on sync.

    Example:
        >>> from tpumetrics.text import CHRFScore
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat']]
        >>> chrf = CHRFScore()
        >>> round(float(chrf(preds, target)), 4)
        0.4942
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = True
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        n_char_order: int = 6,
        n_word_order: int = 2,
        beta: float = 2.0,
        lowercase: bool = False,
        whitespace: bool = False,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(n_char_order, int) or n_char_order < 1:
            raise ValueError("Expected argument `n_char_order` to be an integer greater than or equal to 1.")
        if not isinstance(n_word_order, int) or n_word_order < 0:
            raise ValueError("Expected argument `n_word_order` to be an integer greater than or equal to 0.")
        if beta < 0:
            raise ValueError("Expected argument `beta` to be greater than 0.")
        self.n_char_order = n_char_order
        self.n_word_order = n_word_order
        self.beta = beta
        self.lowercase = lowercase
        self.whitespace = whitespace
        self.return_sentence_level_score = return_sentence_level_score

        max_order = max(n_char_order, n_word_order, 1)
        self.add_state("totals", default=jnp.zeros((6, max_order)), dist_reduce_fx="sum")
        if return_sentence_level_score:
            self.add_state("sentence_chrf_score", default=[], dist_reduce_fx="cat")

    def update(self, preds: Union[str, Sequence[str]], target: Union[Sequence[str], Sequence[Sequence[str]]]) -> None:
        """Accumulate corpus n-gram totals."""
        sentence_scores: Optional[List[float]] = [] if self.return_sentence_level_score else None
        totals = np.asarray(self.totals, np.float64).copy()  # tpulint: disable=TPL101 -- text metrics consume host strings; n-gram counting is eager by contract and float64 for parity
        totals = _chrf_score_update(
            preds,
            target,
            totals,
            self.n_char_order,
            self.n_word_order,
            self.beta,
            self.lowercase,
            self.whitespace,
            sentence_scores,
        )
        self.totals = jnp.asarray(totals, jnp.float32)
        if sentence_scores is not None:
            self.sentence_chrf_score.append(jnp.asarray(sentence_scores, jnp.float32))

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        score = _chrf_score_compute(self.totals, self.n_char_order, self.n_word_order, self.beta)
        if self.return_sentence_level_score:
            return score, dim_zero_cat(self.sentence_chrf_score)
        return score
