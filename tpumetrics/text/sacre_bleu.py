"""SacreBLEUScore (counterpart of reference ``text/sacre_bleu.py``)."""

from __future__ import annotations

from typing import Any, Optional, Sequence

from tpumetrics.functional.text.sacre_bleu import _SacreBLEUTokenizer
from tpumetrics.text.bleu import BLEUScore


class SacreBLEUScore(BLEUScore):
    """BLEU with sacrebleu-compatible tokenization (reference sacre_bleu.py
    class). Shares all count states with :class:`BLEUScore`.

    Args:
        n_gram: maximum n-gram order.
        smooth: apply add-one smoothing.
        tokenize: one of ``none``/``13a``/``zh``/``intl``/``char``.
        lowercase: case-insensitive scoring.
        weights: per-order weights (default uniform).

    Example:
        >>> from tpumetrics.text import SacreBLEUScore
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> sacre_bleu = SacreBLEUScore()
        >>> round(float(sacre_bleu(preds, target)), 4)
        0.7598
    """

    def __init__(
        self,
        n_gram: int = 4,
        smooth: bool = False,
        tokenize: str = "13a",
        lowercase: bool = False,
        weights: Optional[Sequence[float]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(n_gram=n_gram, smooth=smooth, weights=weights, **kwargs)
        self.tokenizer = _SacreBLEUTokenizer(tokenize, lowercase)
