"""TranslationEditRate (counterpart of reference ``text/ter.py``)."""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from tpumetrics.functional.text.ter import _TercomTokenizer, _ter_compute, _ter_update
from tpumetrics.metric import Metric
from tpumetrics.utils.data import dim_zero_cat

Array = jax.Array


class TranslationEditRate(Metric):
    """TER accumulated over batches.

    Example:
        >>> from tpumetrics.text import TranslationEditRate
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> ter = TranslationEditRate()
        >>> round(float(ter(preds, target)), 4)
        0.1538
    """

    is_differentiable: bool = False
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(
        self,
        normalize: bool = False,
        no_punctuation: bool = False,
        lowercase: bool = True,
        asian_support: bool = False,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(normalize, bool):
            raise ValueError(f"Expected argument `normalize` to be of type boolean but got {normalize}.")
        if not isinstance(no_punctuation, bool):
            raise ValueError(f"Expected argument `no_punctuation` to be of type boolean but got {no_punctuation}.")
        if not isinstance(lowercase, bool):
            raise ValueError(f"Expected argument `lowercase` to be of type boolean but got {lowercase}.")
        if not isinstance(asian_support, bool):
            raise ValueError(f"Expected argument `asian_support` to be of type boolean but got {asian_support}.")
        self.tokenizer = _TercomTokenizer(normalize, no_punctuation, lowercase, asian_support)
        self.return_sentence_level_score = return_sentence_level_score

        self.add_state("total_num_edits", default=jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total_tgt_length", default=jnp.zeros(()), dist_reduce_fx="sum")
        if return_sentence_level_score:
            self.add_state("sentence_ter", default=[], dist_reduce_fx="cat")

    def update(self, preds: Union[str, Sequence[str]], target: Union[Sequence[str], Sequence[Sequence[str]]]) -> None:
        """Accumulate edit counts and reference lengths."""
        sentence_scores: Optional[List[float]] = [] if self.return_sentence_level_score else None
        num_edits, tgt_length = _ter_update(preds, target, self.tokenizer, 0.0, 0.0, sentence_scores)
        self.total_num_edits = self.total_num_edits + num_edits
        self.total_tgt_length = self.total_tgt_length + tgt_length
        if sentence_scores is not None:
            self.sentence_ter.append(jnp.asarray(sentence_scores, jnp.float32))

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        score = _ter_compute(self.total_num_edits, self.total_tgt_length)
        if self.return_sentence_level_score:
            return score, dim_zero_cat(self.sentence_ter)
        return score
