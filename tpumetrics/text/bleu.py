"""BLEUScore (counterpart of reference ``text/bleu.py``)."""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from tpumetrics.functional.text.bleu import _bleu_score_compute, _bleu_score_update, _tokenize_fn
from tpumetrics.metric import Metric

Array = jax.Array


class BLEUScore(Metric):
    """BLEU accumulated over batches; the four n-gram count vectors are
    device sum states.

    Args:
        n_gram: maximum n-gram order.
        smooth: apply Lin & Och (2004) add-one smoothing.
        weights: per-order weights (default uniform).

    Example:
        >>> from tpumetrics.text import BLEUScore
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> bleu = BLEUScore()
        >>> round(float(bleu(preds, target)), 4)
        0.7598
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = True
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        n_gram: int = 4,
        smooth: bool = False,
        weights: Optional[Sequence[float]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.n_gram = n_gram
        self.smooth = smooth
        if weights is not None and len(weights) != n_gram:
            raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
        self.weights = weights if weights is not None else [1.0 / n_gram] * n_gram
        self.tokenizer = _tokenize_fn

        self.add_state("preds_len", default=jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("target_len", default=jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("numerator", default=jnp.zeros(n_gram), dist_reduce_fx="sum")
        self.add_state("denominator", default=jnp.zeros(n_gram), dist_reduce_fx="sum")

    def update(self, preds: Union[str, Sequence[str]], target: Union[Sequence[str], Sequence[Sequence[str]]]) -> None:
        """Accumulate clipped n-gram matches."""
        preds_ = [preds] if isinstance(preds, str) else preds
        target_ = [[tgt] if isinstance(tgt, str) else tgt for tgt in target]
        if len(preds_) != len(target_):
            raise ValueError(f"Corpus has different size {len(preds_)} != {len(target_)}")

        numerator = np.zeros(self.n_gram)
        denominator = np.zeros(self.n_gram)
        preds_len, target_len = _bleu_score_update(
            preds_, target_, numerator, denominator, 0.0, 0.0, self.n_gram, self.tokenizer
        )
        self.preds_len = self.preds_len + preds_len
        self.target_len = self.target_len + target_len
        self.numerator = self.numerator + jnp.asarray(numerator, jnp.float32)
        self.denominator = self.denominator + jnp.asarray(denominator, jnp.float32)

    def compute(self) -> Array:
        return _bleu_score_compute(
            self.preds_len, self.target_len, self.numerator, self.denominator, self.n_gram, self.weights, self.smooth
        )
