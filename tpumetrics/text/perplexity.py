"""Perplexity (counterpart of reference ``text/perplexity.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from tpumetrics.functional.text.perplexity import _perplexity_compute, _perplexity_update
from tpumetrics.metric import Metric

Array = jax.Array


class Perplexity(Metric):
    """Perplexity accumulated over batches — pure device math, fully
    jit/shard_map safe through the functional bridge.

    Example:
        >>> import jax
        >>> from tpumetrics.text import Perplexity
        >>> preds = jax.random.uniform(jax.random.PRNGKey(22), (2, 8, 5))
        >>> target = jax.random.randint(jax.random.PRNGKey(89), (2, 8), 0, 5)
        >>> perp = Perplexity()
        >>> 4.0 < float(perp(preds, target)) < 6.0
        True
    """

    is_differentiable: bool = True
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(self, ignore_index: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError(f"Argument `ignore_index` expected to either be `None` or an `int` but got {ignore_index}")
        self.ignore_index = ignore_index
        self.add_state("total_log_probs", default=jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("count", default=jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate negative log probabilities."""
        total_log_probs, count = _perplexity_update(preds, target, self.ignore_index)
        self.total_log_probs = self.total_log_probs + total_log_probs
        self.count = self.count + count

    def compute(self) -> Array:
        return _perplexity_compute(self.total_log_probs, self.count)
