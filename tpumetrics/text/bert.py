"""BERTScore metric (counterpart of reference ``text/bert.py:54``)."""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Union

import jax
import jax.numpy as jnp

from tpumetrics.functional.text.bert import bert_score
from tpumetrics.metric import Metric
from tpumetrics.text._sentence_state import HostSentenceStateMixin
from tpumetrics.utils.data import dim_zero_cat

Array = jax.Array


class BERTScore(HostSentenceStateMixin, Metric):
    """BERTScore accumulated over batches.

    Where the reference stores tokenized input-id/attention-mask cat states
    and runs the model inside ``compute`` (reference text/bert.py:191-194),
    the raw sentences are stored here and embedded at compute — strings
    cannot live in device states, and this keeps update cheap while the
    heavy model forward batches once at the end.

    Args:
        model_name_or_path: transformers hub id (gated when not downloadable).
        model / user_tokenizer / user_forward_fn: custom embedding stack.
        idf: inverse-document-frequency weighting over the reference corpus.

    Example:
        >>> from tpumetrics.text import BERTScore
        >>> metric = BERTScore(model_name_or_path='roberta-large')  # doctest: +SKIP
        >>> metric.update(['the cat sat'], ['a cat sat'])  # doctest: +SKIP
        >>> {k: round(float(v[0]), 3) for k, v in metric.compute().items()}  # doctest: +SKIP
        {'precision': 0.998, 'recall': 0.998, 'f1': 0.998}
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        model_name_or_path: Optional[str] = None,
        num_layers: Optional[int] = None,
        all_layers: bool = False,
        model: Optional[Any] = None,
        user_tokenizer: Optional[Any] = None,
        user_forward_fn: Optional[Callable] = None,
        verbose: bool = False,
        idf: bool = False,
        device: Optional[Any] = None,
        max_length: int = 512,
        batch_size: int = 64,
        num_threads: int = 0,
        return_hash: bool = False,
        lang: str = "en",
        rescale_with_baseline: bool = False,
        baseline_path: Optional[str] = None,
        baseline_url: Optional[str] = None,
        sentences_replicated: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.sentences_replicated = sentences_replicated
        self.model_name_or_path = model_name_or_path
        self.num_layers = num_layers
        self.all_layers = all_layers
        self.model = model
        self.user_tokenizer = user_tokenizer
        self.user_forward_fn = user_forward_fn
        self.verbose = verbose
        self.idf = idf
        self.max_length = max_length
        self.batch_size = batch_size
        self.return_hash = return_hash
        self.lang = lang
        if rescale_with_baseline and not baseline_path:
            # fail at construction, not after a full epoch of updates: without
            # a local file the baseline would need a download (reference
            # bert.py:202-222); with `baseline_path=` rescaling is supported
            raise NotImplementedError(
                "Baseline rescaling without a local file requires downloading the bert-score"
                " baseline, which is not supported here. Save the baseline CSV locally and pass"
                " it via `baseline_path=`."
            )
        self.rescale_with_baseline = rescale_with_baseline
        self.baseline_path = baseline_path
        self.baseline_url = baseline_url

        self._preds: List[str] = []
        self._target: List[str] = []
        self.add_state("dummy", jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        """Store sentences for the compute-time embedding pass."""
        if isinstance(preds, str):
            preds = [preds]
        if isinstance(target, str):
            target = [target]
        if len(preds) != len(target):
            raise ValueError(
                f"Expected argument `preds` and `target` to have same length, but got {len(preds)} and {len(target)}"
            )
        self._preds.extend(preds)
        self._target.extend(target)

    def compute(self) -> Dict[str, Array]:
        """Embed everything and score (reference text/bert.py compute)."""
        return bert_score(
            self._preds,
            self._target,
            model_name_or_path=self.model_name_or_path,
            num_layers=self.num_layers,
            all_layers=self.all_layers,
            model=self.model,
            user_tokenizer=self.user_tokenizer,
            user_forward_fn=self.user_forward_fn,
            verbose=self.verbose,
            idf=self.idf,
            max_length=self.max_length,
            batch_size=self.batch_size,
            return_hash=self.return_hash,
            lang=self.lang,
            rescale_with_baseline=self.rescale_with_baseline,
            baseline_path=self.baseline_path,
            baseline_url=self.baseline_url,
        )

    def reset(self) -> None:
        super().reset()
        self._preds = []
        self._target = []

