"""BERTScore metric (counterpart of reference ``text/bert.py:54``)."""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Union

import jax
import jax.numpy as jnp

from tpumetrics.functional.text.bert import bert_score
from tpumetrics.metric import Metric
from tpumetrics.text._sentence_state import HostSentenceStateMixin
from tpumetrics.utils.data import dim_zero_cat

Array = jax.Array


class BERTScore(HostSentenceStateMixin, Metric):
    """BERTScore accumulated over batches.

    Where the reference stores tokenized input-id/attention-mask cat states
    and runs the model inside ``compute`` (reference text/bert.py:191-194),
    the raw sentences are stored here and embedded at compute — strings
    cannot live in device states, and this keeps update cheap while the
    heavy model forward batches once at the end.

    With a ``backbone`` (a handle from
    :func:`tpumetrics.backbones.get_backbone` over an encoder forward
    ``(params, input_ids, attention_mask) -> (B, S, D)`` or ``(B, L, S, D)``,
    plus ``user_tokenizer``) the metric instead embeds at STREAM TIME: each
    ``update`` batch runs through the shared compiled embed immediately and
    only the (much smaller) embeddings wait for ``compute``, which just
    scores them.  The encoder forward requires mask-respecting, per-row
    independent embeddings (any standard masked transformer qualifies) since
    batches are embedded at their own padded length.  The host sentence
    lists are still kept, so snapshots restore exactly as before — a
    restored metric falls back to the compute-time embedding pass.

    **Migration note (backbone runtime):** pretrained forwards now live in
    the process-global backbone registry (:mod:`tpumetrics.backbones`).
    Passing ``model=``/``user_forward_fn=`` keeps the historical
    compute-time behavior, bit for bit; passing ``backbone=`` opts into the
    shared resident weight set, the stream-time embed, and cross-tenant
    sharing in the evaluation service.  Call ``release_backbones()`` (or let
    the service ``close()`` do it) when done.

    Args:
        model_name_or_path: transformers hub id (gated when not downloadable).
        model / user_tokenizer / user_forward_fn: custom embedding stack.
        idf: inverse-document-frequency weighting over the reference corpus.
        backbone: shared registry handle over the encoder (see above).

    Example:
        >>> from tpumetrics.text import BERTScore
        >>> metric = BERTScore(model_name_or_path='roberta-large')  # doctest: +SKIP
        >>> metric.update(['the cat sat'], ['a cat sat'])  # doctest: +SKIP
        >>> {k: round(float(v[0]), 3) for k, v in metric.compute().items()}  # doctest: +SKIP
        {'precision': 0.998, 'recall': 0.998, 'f1': 0.998}
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        model_name_or_path: Optional[str] = None,
        num_layers: Optional[int] = None,
        all_layers: bool = False,
        model: Optional[Any] = None,
        user_tokenizer: Optional[Any] = None,
        user_forward_fn: Optional[Callable] = None,
        verbose: bool = False,
        idf: bool = False,
        device: Optional[Any] = None,
        max_length: int = 512,
        batch_size: int = 64,
        num_threads: int = 0,
        return_hash: bool = False,
        lang: str = "en",
        rescale_with_baseline: bool = False,
        baseline_path: Optional[str] = None,
        baseline_url: Optional[str] = None,
        sentences_replicated: bool = False,
        backbone: Optional[Any] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.backbone = backbone
        if backbone is not None:
            if user_tokenizer is None:
                raise ValueError("`user_tokenizer` must be provided together with a `backbone`")
            # own one registry reference (released by release_backbones())
            self._backbone_handles = (backbone.acquire(),)
            self.backbone_key = backbone.key
        self.sentences_replicated = sentences_replicated
        self.model_name_or_path = model_name_or_path
        self.num_layers = num_layers
        self.all_layers = all_layers
        self.model = model
        self.user_tokenizer = user_tokenizer
        self.user_forward_fn = user_forward_fn
        self.verbose = verbose
        self.idf = idf
        self.max_length = max_length
        self.batch_size = batch_size
        self.return_hash = return_hash
        self.lang = lang
        if rescale_with_baseline and not baseline_path:
            # fail at construction, not after a full epoch of updates: without
            # a local file the baseline would need a download (reference
            # bert.py:202-222); with `baseline_path=` rescaling is supported
            raise NotImplementedError(
                "Baseline rescaling without a local file requires downloading the bert-score"
                " baseline, which is not supported here. Save the baseline CSV locally and pass"
                " it via `baseline_path=`."
            )
        self.rescale_with_baseline = rescale_with_baseline
        self.baseline_path = baseline_path
        self.baseline_url = baseline_url

        self._preds: List[str] = []
        self._target: List[str] = []
        # stream-time embedding buffers (backbone mode): per-update-batch
        # (embeddings, token-weight scale) pairs, NOT part of snapshots — a
        # restored metric re-embeds from the sentence lists at compute
        self._streamed: List[Any] = []
        self.add_state("dummy", jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        """Store sentences; with a ``backbone`` also embed the batch now
        through the shared compiled encoder (stream-time embedding)."""
        if isinstance(preds, str):
            preds = [preds]
        if isinstance(target, str):
            target = [target]
        if len(preds) != len(target):
            raise ValueError(
                f"Expected argument `preds` and `target` to have same length, but got {len(preds)} and {len(target)}"
            )
        self._preds.extend(preds)
        self._target.extend(target)
        # idf weights need the full reference corpus, so idf mode keeps the
        # historical embed-at-compute path
        if self.backbone is not None and not self.idf and preds:
            from tpumetrics.functional.text.bert import _embed

            pe, ps, _ = _embed(
                list(preds), None, self.user_tokenizer, None, self.all_layers,
                self.max_length, False, None, self.num_layers, self.batch_size,
                self.backbone,
            )
            te, ts, _ = _embed(
                list(target), None, self.user_tokenizer, None, self.all_layers,
                self.max_length, False, None, self.num_layers, self.batch_size,
                self.backbone,
            )
            self._streamed.append(((pe, ps, len(preds)), (te, ts, len(target))))

    @staticmethod
    def _cat_streamed(parts: List[Any]) -> Any:
        """Concatenate per-batch (emb, scale, n) triples: pad the sequence
        axis to the common max (padded positions carry zero embeddings and
        zero weight, exactly like in-batch padding) and stack rows."""
        import numpy as np

        seq = max(p[0].shape[2] for p in parts)
        embs, scales = [], []
        for emb, scale, n in parts:
            pad_s = seq - emb.shape[2]
            if pad_s:
                emb = jnp.pad(emb, [(0, 0), (0, 0), (0, pad_s), (0, 0)])
                scale = jnp.pad(scale, [(0, 0), (0, pad_s)])
            embs.append(emb[:n])
            scales.append(scale[:n])
        return jnp.concatenate(embs, axis=0), jnp.concatenate(scales, axis=0)

    def compute(self) -> Dict[str, Array]:
        """Score (reference text/bert.py compute): streamed embeddings when
        complete, otherwise embed everything now."""
        streamed_rows = sum(p[0][2] for p in self._streamed)
        if self.backbone is not None and self._streamed and streamed_rows == len(self._preds):
            from tpumetrics.functional.text.bert import _read_baseline_csv, _score_embeddings

            preds_emb, preds_scale = self._cat_streamed([p[0] for p in self._streamed])
            target_emb, target_scale = self._cat_streamed([p[1] for p in self._streamed])
            baseline = _read_baseline_csv(self.baseline_path) if self.rescale_with_baseline else None
            precision, recall, f1 = _score_embeddings(
                preds_emb, target_emb, preds_scale, target_scale,
                self.batch_size, baseline, self.num_layers, self.all_layers,
            )
            output: Dict[str, Array] = {"precision": precision, "recall": recall, "f1": f1}
            if self.return_hash:
                output["hash"] = f"tpumetrics-bert_score-idf:{self.idf}"  # type: ignore[assignment]
            return output
        return bert_score(
            self._preds,
            self._target,
            model_name_or_path=self.model_name_or_path,
            num_layers=self.num_layers,
            all_layers=self.all_layers,
            model=self.model,
            user_tokenizer=self.user_tokenizer,
            user_forward_fn=self.user_forward_fn,
            verbose=self.verbose,
            idf=self.idf,
            max_length=self.max_length,
            batch_size=self.batch_size,
            return_hash=self.return_hash,
            lang=self.lang,
            rescale_with_baseline=self.rescale_with_baseline,
            baseline_path=self.baseline_path,
            baseline_url=self.baseline_url,
            backbone=self.backbone,
        )

    def reset(self) -> None:
        super().reset()
        self._preds = []
        self._target = []
        self._streamed = []

    def __getstate__(self):
        state = super().__getstate__()
        # device-resident embed buffers don't snapshot; restore re-embeds
        # from the sentence lists (same scores, one extra forward pass)
        state["_streamed"] = []
        return state

