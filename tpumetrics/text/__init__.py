"""Text metric domain (counterpart of reference ``text/__init__.py``)."""

from tpumetrics.text.bert import BERTScore
from tpumetrics.text.bleu import BLEUScore
from tpumetrics.text.cer import CharErrorRate
from tpumetrics.text.chrf import CHRFScore
from tpumetrics.text.edit import EditDistance
from tpumetrics.text.eed import ExtendedEditDistance
from tpumetrics.text.infolm import InfoLM
from tpumetrics.text.mer import MatchErrorRate
from tpumetrics.text.perplexity import Perplexity
from tpumetrics.text.rouge import ROUGEScore
from tpumetrics.text.sacre_bleu import SacreBLEUScore
from tpumetrics.text.squad import SQuAD
from tpumetrics.text.ter import TranslationEditRate
from tpumetrics.text.wer import WordErrorRate
from tpumetrics.text.wil import WordInfoLost
from tpumetrics.text.wip import WordInfoPreserved

__all__ = [
    "BERTScore",
    "BLEUScore",
    "CHRFScore",
    "CharErrorRate",
    "EditDistance",
    "ExtendedEditDistance",
    "InfoLM",
    "MatchErrorRate",
    "Perplexity",
    "ROUGEScore",
    "SQuAD",
    "SacreBLEUScore",
    "TranslationEditRate",
    "WordErrorRate",
    "WordInfoLost",
    "WordInfoPreserved",
]
