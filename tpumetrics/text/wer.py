"""WordErrorRate (counterpart of reference ``text/wer.py``)."""

from __future__ import annotations

from typing import Any, List, Union

import jax
import jax.numpy as jnp

from tpumetrics.functional.text.wer import _wer_compute, _wer_update
from tpumetrics.metric import Metric

Array = jax.Array


class WordErrorRate(Metric):
    """Word error rate accumulated over batches.

    Example:
        >>> from tpumetrics.text import WordErrorRate
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> wer = WordErrorRate()
        >>> round(float(wer(preds, target)), 4)
        0.5
    """

    is_differentiable: bool = False
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", default=jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        """Accumulate edit distances and reference word counts."""
        errors, total = _wer_update(preds, target)
        self.errors = self.errors + errors
        self.total = self.total + total

    def compute(self) -> Array:
        return _wer_compute(self.errors, self.total)
