"""WordInfoPreserved (counterpart of reference ``text/wip.py``)."""

from __future__ import annotations

from typing import Any, List, Union

import jax
import jax.numpy as jnp

from tpumetrics.functional.text.wip import _wip_compute, _wip_update
from tpumetrics.metric import Metric

Array = jax.Array


class WordInfoPreserved(Metric):
    """Word Information Preserved accumulated over batches.

    Example:
        >>> from tpumetrics.text import WordInfoPreserved
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> wip = WordInfoPreserved()
        >>> round(float(wip(preds, target)), 4)
        0.3472
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", default=jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("target_total", default=jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("preds_total", default=jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        """Accumulate word-hit statistics."""
        errors, target_total, preds_total = _wip_update(preds, target)
        self.errors = self.errors + errors
        self.target_total = self.target_total + target_total
        self.preds_total = self.preds_total + preds_total

    def compute(self) -> Array:
        return _wip_compute(self.errors, self.target_total, self.preds_total)
