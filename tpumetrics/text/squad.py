"""SQuAD (counterpart of reference ``text/squad.py``)."""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from tpumetrics.functional.text.squad import _squad_compute, _squad_input_check, _squad_update
from tpumetrics.metric import Metric

Array = jax.Array


class SQuAD(Metric):
    """SQuAD v1.1 exact-match/F1 accumulated over batches.

    Example:
        >>> from tpumetrics.text import SQuAD
        >>> preds = [{"prediction_text": "1976", "id": "56e10a3be3433e1400422b22"}]
        >>> target = [{"answers": {"answer_start": [97], "text": ["1976"]}, "id": "56e10a3be3433e1400422b22"}]
        >>> squad = SQuAD()
        >>> {k: float(v) for k, v in squad(preds, target).items()}
        {'exact_match': 100.0, 'f1': 100.0}
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 100.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("f1_score", default=jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("exact_match", default=jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, preds: Any, target: Any) -> None:
        """Accumulate EM/F1 sums."""
        preds_dict, target_dict = _squad_input_check(preds, target)
        f1, exact_match, total = _squad_update(preds_dict, target_dict)
        self.f1_score = self.f1_score + f1
        self.exact_match = self.exact_match + exact_match
        self.total = self.total + total

    def compute(self) -> Dict[str, Array]:
        return _squad_compute(self.f1_score, self.exact_match, self.total)
