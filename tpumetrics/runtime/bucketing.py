"""Shape-bucketed padding: compile once per bucket, not once per batch shape.

Serving traffic is ragged — every request batch has a different leading
dimension, and a jitted update path would recompile for each novel shape
(XLA caches executables by input shape).  The runtime's answer is the same
static-shape idea as :class:`~tpumetrics.buffers.MaskedBuffer`: pad every
batch up to a small fixed set of **bucket edges** and carry the true row
count beside the data, so the compiled-program universe is bounded by
``len(edges)`` regardless of how many distinct raw shapes the stream
produces.

Padding convention (load-bearing): pad rows are **copies of row 0** of the
batch, never zeros.  Row 0 is always real data, so metrics whose reduce
states are row-wise ``max``/``min`` see a no-op contribution from padding,
and the ``sum`` correction below needs only one extra single-row update.

Masked update semantics — how padded rows are kept out of the state:

1. **Native mask path.**  A metric whose ``update`` signature declares a
   ``valid`` keyword receives the boolean mask directly
   (``arange(bucket) < n_valid``) and owns exact masking itself — the
   :meth:`~tpumetrics.metric.Metric._append_state` convention routes it into
   :class:`~tpumetrics.buffers.MaskedBuffer` appends in-trace.
2. **Delta-correction fallback** (any metric with only
   ``sum``/``max``/``min`` tensor states).  One padded-batch update and one
   single-row (row 0) update, both from the default state, reconstruct the
   exact valid-only transition::

       contrib_all = U(init, padded)[s] - init[s]          # k valid + (B-k) pad rows
       contrib_pad = (B - k) * (U(init, row0)[s] - init[s])  # the pad rows exactly
       sum:      new[s] = state[s] + contrib_all - contrib_pad
       max/min:  new[s] = op(state[s], U(init, padded)[s])   # row-0 dups are neutral

   Exactness requires the update to be **row-separable** (each row's
   contribution independent of the others — true of counting/statscores/
   moment-style metrics); integer sum states stay exact because the pad
   correction is a product, never a division.  Metrics with ``mean``/
   ``cat``/custom/list states and no native ``valid`` parameter are
   rejected at construction with :class:`NotBucketableError` — silent
   approximation is worse than a loud error.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from tpumetrics.metric import Metric, _reduce_fn_to_op
from tpumetrics.utils.exceptions import TPUMetricsUserError

Array = jax.Array

_FALLBACK_OPS = ("sum", "max", "min")


class NotBucketableError(TPUMetricsUserError):
    """The metric cannot take padded (bucketed) updates exactly."""


def pow2_at_least(n: int, floor: int = 1) -> int:
    """Smallest power of two >= max(n, floor) — the single-size counterpart
    of :func:`pow2_bucket_edges`, shared by every pow-2 shape-bucketing site
    (detection packing, the jitted matcher's cell grids)."""
    e = max(int(floor), 1)
    while e < n:
        e *= 2
    return e


def pow2_bucket_edges(max_size: int, min_size: int = 1) -> Tuple[int, ...]:
    """Power-of-two bucket edges ``min_size..>=max_size`` (each edge doubles)."""
    if min_size <= 0 or max_size < min_size:
        raise ValueError(f"Need 0 < min_size <= max_size, got {min_size}, {max_size}")
    edges: List[int] = []
    e = 1
    while e < min_size:
        e *= 2
    while True:
        edges.append(e)
        if e >= max_size:
            break
        e *= 2
    return tuple(edges)


class ShapeBucketer:
    """Maps ragged leading dimensions onto a fixed set of padded sizes.

    Args:
        edges: strictly increasing bucket sizes.  A batch of ``n`` rows pads
            to the smallest edge ``>= n``; batches larger than the top edge
            are split into top-edge chunks first (:meth:`chunk_sizes`).
    """

    def __init__(self, edges: Sequence[int]) -> None:
        edges = tuple(int(e) for e in edges)
        if not edges:
            raise ValueError("Need at least one bucket edge")
        if any(e <= 0 for e in edges) or list(edges) != sorted(set(edges)):
            raise ValueError(f"Bucket edges must be strictly increasing positives, got {edges}")
        self.edges = edges

    def bucket_for(self, n: int) -> int:
        """Smallest edge >= n (n must fit the top edge; see chunk_sizes)."""
        if n <= 0:
            raise ValueError(f"Batch must be non-empty, got {n} rows")
        for e in self.edges:
            if n <= e:
                return e
        raise ValueError(
            f"Batch of {n} rows exceeds the largest bucket edge {self.edges[-1]}; "
            "split it first (chunk_sizes) or widen the edges."
        )

    def chunk_sizes(self, n: int) -> List[int]:
        """Split an arbitrary row count into bucketable chunk sizes."""
        top = self.edges[-1]
        sizes = [top] * (n // top)
        if n % top:
            sizes.append(n % top)
        return sizes

    def pad_args(self, args: Sequence[Any], n: int) -> Tuple[Tuple[Any, ...], int]:
        """Pad every per-row array in ``args`` (leading dim == n) to the
        bucket edge with row-0 copies; returns (padded_args, bucket)."""
        bucket = self.bucket_for(n)
        return pad_args_to(args, n, bucket), bucket


def pad_args_to(args: Sequence[Any], n: int, bucket: int) -> Tuple[Any, ...]:
    """Pad per-row arrays (leading dim == n) to an EXPLICIT bucket size with
    row-0 copies.  The megabatch path pads each group member to the GROUP's
    bucket — taken from the member's own signature probe, never re-derived
    from another tenant's bucket edges (same-config tenants may bucket the
    same row count differently)."""
    if bucket == n:
        return tuple(args)
    return tuple(_pad_one(a, n, bucket) for a in args)


def _pad_one(a: Any, n: int, bucket: int) -> Any:
    if isinstance(a, dict):
        return {k: _pad_one(v, n, bucket) for k, v in a.items()}
    if not _is_per_row(a, n):
        return a
    a = jnp.asarray(a)
    pad = jnp.broadcast_to(a[0:1], (bucket - n,) + a.shape[1:])
    return jnp.concatenate([a, pad], axis=0)


def _is_per_row(a: Any, n: int) -> bool:
    """A per-row argument: an array with leading dim ``n``, or a **dict of
    per-row arrays** (the packed detection layout — every leaf shares the
    batch's image axis, so the whole dict pads/slices as one unit)."""
    if isinstance(a, dict):
        return bool(a) and all(_is_per_row(v, n) for v in a.values())
    return hasattr(a, "shape") and getattr(a, "ndim", 0) >= 1 and a.shape[0] == n


def _slice_rows(a: Any, n: int, lo: int, hi: int) -> Any:
    """Row-slice one argument (dict leaves slice together)."""
    if isinstance(a, dict):
        return {k: _slice_rows(v, n, lo, hi) for k, v in a.items()}
    return a[lo:hi] if _is_per_row(a, n) else a


def _args_signature(args: Sequence[Any]) -> Tuple[Any, ...]:
    """The (shape, dtype) tuple mirroring the jit cache key; python scalars
    key by their weak result type, dict args by their sorted item specs."""
    out = []
    for a in args:
        if isinstance(a, dict):
            out.append(
                ("dict",)
                + tuple(
                    (k, tuple(jnp.shape(v)), str(jnp.result_type(v)))
                    for k, v in sorted(a.items())
                )
            )
        else:
            try:
                out.append((tuple(jnp.shape(a)), str(jnp.result_type(a))))
            except (TypeError, ValueError):
                # not array-able (e.g. a list of per-image dicts): key by
                # structure so the metric's own update can reject the layout
                # with ITS typed, instructive error instead of an opaque
                # dtype failure here
                out.append(("opaque", type(a).__name__))
    return tuple(out)


def leading_rows(args: Sequence[Any]) -> int:
    """The batch's row count: leading dim of the first per-row array (dicts:
    their first array leaf), or 1 for scalar-only updates (aggregation
    metrics fed floats)."""
    for a in args:
        if isinstance(a, dict):
            for _k, v in sorted(a.items()):
                if hasattr(v, "shape") and getattr(v, "ndim", 0) >= 1:
                    return int(v.shape[0])
            continue
        if hasattr(a, "shape") and getattr(a, "ndim", 0) >= 1:
            return int(a.shape[0])
    return 1


def plan_bucketed_update(bucketer: "ShapeBucketer", args: Sequence[Any]):
    """Split one submitted batch into the bucketed step calls it will run as.

    Returns ``(n_rows, chunks)`` where each chunk is one device dispatch:

    - ``("scalar", args, sig)`` — a scalar-only submit (no per-row array):
      nothing to pad, so bucketing (and the fallback's pad correction) must
      NOT apply; the caller runs the plain fused update over the raw args.
    - ``("masked", padded_args, bucket, size, sig)`` — ``size`` valid rows
      padded to ``bucket``; the caller runs the masked fused update.

    ``sig`` mirrors the jit cache key (bucket + shapes/dtypes), so the set
    of distinct sigs == the XLA compile count of the stream.  Shared by the
    single-stream :class:`~tpumetrics.runtime.evaluator.StreamingEvaluator`
    and the multi-tenant :class:`~tpumetrics.runtime.service.
    EvaluationService` (which additionally groups same-sig chunks from
    different tenants into one vmapped megabatch program).
    """
    n = leading_rows(args)
    if n == 0:
        raise ValueError("submit() got arguments with no per-row array (or zero rows)")
    if not any(_is_per_row(a, n) for a in args):
        return n, [("scalar", tuple(args), ("scalar",) + _args_signature(args))]
    chunks = []
    offset = 0
    for size in bucketer.chunk_sizes(n):
        chunk = tuple(_slice_rows(a, n, offset, offset + size) for a in args)
        padded, bucket = bucketer.pad_args(chunk, size)
        chunks.append(("masked", padded, bucket, size, (bucket,) + _args_signature(padded)))
        offset += size
    return n, chunks


def single_chunk_signature(
    bucketer: "ShapeBucketer", args: Sequence[Any]
) -> Optional[Tuple[int, int, Tuple[Any, ...]]]:
    """``(bucket, n_rows, sig)`` when the batch would bucketize to exactly ONE
    masked chunk, else ``None`` — WITHOUT materializing the padding.

    The multi-tenant service's megabatch probe: it must compare head-of-queue
    signatures across tenants under a lock, so the signature is derived from
    shapes alone (a per-row array pads to ``(bucket,) + shape[1:]``, same
    dtype).  Produces bit-identical signatures to :func:`plan_bucketed_update`
    for the same batch (pinned by a test) — the two MUST agree, or the
    compile accounting drifts between the megabatch and single-tenant paths.
    """
    n = leading_rows(args)
    if n <= 0 or not any(_is_per_row(a, n) for a in args):
        return None
    if len(bucketer.chunk_sizes(n)) != 1:
        return None  # splits past the top edge: megabatch handles heads only
    bucket = bucketer.bucket_for(n)

    def padded_spec_leaf(a: Any):
        shape = tuple(jnp.shape(a))
        if _is_per_row(a, n):
            shape = (bucket,) + shape[1:]
        return (shape, str(jnp.result_type(a)))

    parts = []
    for a in args:
        if isinstance(a, dict):
            parts.append(
                ("dict",)
                + tuple((k, *padded_spec_leaf(v)) for k, v in sorted(a.items()))
            )
        else:
            parts.append(padded_spec_leaf(a))
    return bucket, n, (bucket,) + tuple(parts)


def _has_native_valid(metric: Metric) -> bool:
    return "valid" in metric._update_signature.parameters


def _check_metric_bucketable(metric: Metric, label: str) -> None:
    if _has_native_valid(metric):
        return
    bad = {
        attr: (_reduce_fn_to_op(fn) or ("list" if isinstance(metric._defaults[attr], list) else "custom"))
        for attr, fn in metric._reductions.items()
        if isinstance(metric._defaults[attr], list) or _reduce_fn_to_op(fn) not in _FALLBACK_OPS
    }
    if bad:
        raise NotBucketableError(
            f"Metric {label} cannot take padded (bucketed) updates: state(s) "
            f"{bad} are outside the exact delta-correction fallback "
            f"(supported: tensor states with {_FALLBACK_OPS} reduce). "
            "HINT: add a `valid` mask parameter to update() (the "
            "MaskedBuffer convention), or run the evaluator with buckets=None."
        )


def check_bucketable(obj: Any) -> None:
    """Validate that a Metric / MetricCollection supports exact bucketed
    updates; raises :class:`NotBucketableError` naming the offending state."""
    from tpumetrics.collections import MetricCollection

    if isinstance(obj, MetricCollection):
        for cg in obj._groups.values():
            _check_metric_bucketable(obj._modules[cg[0]], label=repr(cg[0]))
        return
    if isinstance(obj, Metric):
        _check_metric_bucketable(obj, label=type(obj).__name__)
        return
    raise TypeError(f"Expected Metric or MetricCollection, got {type(obj)}")


# ------------------------------------------------------------- masked update


def _masked_metric_update(
    metric: Metric,
    state: Dict[str, Any],
    padded: Tuple[Any, ...],
    n_valid: Array,
    bucket: int,
    kwargs: Dict[str, Any],
) -> Dict[str, Any]:
    """One exact bucketed state transition for a single Metric (traceable)."""
    if _has_native_valid(metric):
        mask = jnp.arange(bucket) < n_valid
        return metric.functional_update(state, *padded, valid=mask, **kwargs)

    init = metric.init_state()
    after_all = metric.functional_update(metric.init_state(), *padded, **kwargs)
    row0 = tuple(_slice_rows(a, bucket, 0, 1) for a in padded)
    after_one = metric.functional_update(metric.init_state(), *row0, **kwargs)

    n_pad = jnp.asarray(bucket) - n_valid
    out: Dict[str, Any] = {}
    for attr, fn in metric._reductions.items():
        op = _reduce_fn_to_op(fn)
        if op == "sum":
            contrib_all = after_all[attr] - init[attr]
            contrib_one = after_one[attr] - init[attr]
            out[attr] = state[attr] + contrib_all - n_pad.astype(contrib_one.dtype) * contrib_one
        elif op == "max":
            out[attr] = jnp.maximum(state[attr], after_all[attr])
        elif op == "min":
            out[attr] = jnp.minimum(state[attr], after_all[attr])
        else:  # unreachable after check_bucketable
            raise NotBucketableError(f"State {attr!r} ({op}) has no exact masked update")
    return out


def masked_functional_update(
    obj: Any,
    state: Dict[str, Any],
    padded: Tuple[Any, ...],
    n_valid: Array,
    bucket: int,
    kwargs: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Exact bucketed state transition for a Metric or MetricCollection.

    ``state`` is the functional state pytree (collection: per-group-leader
    dict), ``padded`` the bucket-padded positional args, ``n_valid`` the true
    row count (traced scalar), ``bucket`` the static padded size.
    """
    from tpumetrics.collections import MetricCollection

    kwargs = kwargs or {}
    if isinstance(obj, MetricCollection):
        out = {}
        for cg in obj._groups.values():
            m0 = obj._modules[cg[0]]
            out[cg[0]] = _masked_metric_update(
                m0, state[cg[0]], padded, n_valid, bucket, m0._filter_kwargs(**kwargs)
            )
        return out
    return _masked_metric_update(obj, state, padded, n_valid, bucket, kwargs)
