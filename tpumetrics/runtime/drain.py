"""Graceful drain on preemption notice: stop intake, flush, cut, exit typed.

Cloud TPU fleets deliver two kinds of death: the abrupt SIGKILL (handled by
snapshots + :meth:`~tpumetrics.runtime.evaluator.StreamingEvaluator.
restore_elastic`) and the *polite* preemption — a SIGTERM (or maintenance
notice) with a grace window.  A polite preemption should lose NOTHING: every
batch already submitted must reach the state, one final coordinated snapshot
cut must cover exactly that position, and late submitters must get a typed
error instead of silently feeding a dying process.  This module is that
contract:

- :class:`DrainingError` — the typed refusal every ``submit`` raises once a
  drain began (on :class:`~tpumetrics.runtime.evaluator.StreamingEvaluator`
  and on :class:`~tpumetrics.runtime.service.EvaluationService` /
  :class:`~tpumetrics.runtime.service.TenantHandle` alike).
- ``request_drain()`` / ``drain()`` on the evaluator and the service — the
  programmatic half: mark draining (intake off), flush the queues, write the
  final cut, close, and return a :class:`DrainReport` describing exactly
  what the cut covers.
- :func:`install_preemption_handler` — the signal half: registers a SIGTERM
  (configurable) handler that records the notice (``preemption_notice``
  ledger event + flight-ring incident mark) and either just flags a
  :class:`PreemptionGuard` (``mode="notify"``) or interrupts the main thread
  with :class:`PreemptionInterrupt` (``mode="raise"``) so a blocked main
  loop reacts within the grace window.  The handler itself does NO heavy
  work (async-signal discipline): draining runs wherever the caller calls
  :meth:`PreemptionGuard.drain_now`.

Why the final cut is safe under SIGTERM: a coordinated (elastic) cut runs a
barrier over the host-object wire.  A *polite* preemption preempts the whole
job, so every rank receives the notice and every rank reaches its final
``snapshot()`` — the barrier completes.  A rank that dies instead of
draining turns the final cut partial, which the restore side refuses or
quorum-degrades explicitly (:mod:`tpumetrics.resilience.elastic`) — never
silently.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from tpumetrics.telemetry import export as _export
from tpumetrics.telemetry import ledger as _telemetry
from tpumetrics.utils.exceptions import TPUMetricsUserError

__all__ = [
    "DrainReport",
    "DrainingError",
    "PreemptionGuard",
    "PreemptionInterrupt",
    "install_preemption_handler",
]


class DrainingError(TPUMetricsUserError):
    """Submit refused: this evaluator/service is draining for shutdown.

    Raised by every ``submit`` after ``request_drain()`` (or a preemption
    notice) — the typed signal for load balancers/callers to re-route the
    stream instead of feeding a process that is about to exit."""


class PreemptionInterrupt(BaseException):
    """Raised IN THE MAIN THREAD by a ``mode="raise"`` preemption handler.

    Derives from :class:`BaseException` (like ``KeyboardInterrupt``) so
    ordinary ``except Exception`` recovery paths cannot swallow the notice;
    catch it explicitly at the serving loop's top level and drain."""

    def __init__(self, signum: int) -> None:
        super().__init__(f"preemption notice (signal {signum})")
        self.signum = signum


@dataclass
class DrainReport:
    """What one target's graceful drain covered (returned by ``drain()``).

    ``batches``/``items`` are the stream position the final state covers
    (everything submitted before intake stopped — nothing in flight was
    lost); ``cut_path``/``cut_step`` identify the final snapshot when one
    was written (``final_cut=True`` and a snapshot dir configured);
    ``drain_ms`` is the flush+final-cut wall time (also stamped into the
    ``drain_complete`` ledger event — the durable copy, since ``close``
    releases the per-stream histogram series as part of its own
    contract).

    ``partial`` marks a drain whose FINAL CUT failed with a storage error
    that survived the retry budget (degraded durability at shutdown): the
    state that was drained is NOT fully covered by any snapshot.  The
    report then names the uncovered tail — ``uncovered_batches`` /
    ``uncovered_items`` (stream positions past the last durable cut) and
    ``reason`` (the typed storage error) — so the caller can re-route or
    replay that tail explicitly instead of discovering the loss at the
    next restore."""

    target: str
    batches: int
    items: int
    cut_path: Optional[str] = None
    cut_step: Optional[int] = None
    drain_ms: Optional[float] = None
    tenants: Dict[str, "DrainReport"] = field(default_factory=dict)
    partial: bool = False
    uncovered_batches: int = 0
    uncovered_items: int = 0
    reason: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "target": self.target,
            "batches": self.batches,
            "items": self.items,
            "cut_path": self.cut_path,
            "cut_step": self.cut_step,
            "drain_ms": self.drain_ms,
        }
        if self.partial:
            out["partial"] = True
            out["uncovered_batches"] = self.uncovered_batches
            out["uncovered_items"] = self.uncovered_items
            out["reason"] = self.reason
        if self.tenants:
            out["tenants"] = {k: v.to_dict() for k, v in self.tenants.items()}
        return out


class PreemptionGuard:
    """The main-loop side of an installed preemption handler.

    ``requested`` flips (and :meth:`wait` unblocks) when the signal lands;
    :meth:`drain_now` runs the graceful sequence over every registered
    target — stop intake, flush, final cut, close — and returns the per-
    target :class:`DrainReport` list.  Idempotent: a second signal or a
    second ``drain_now`` call does not double-drain."""

    def __init__(
        self,
        targets: Sequence[Any],
        *,
        final_cut: bool = True,
        on_drained: Optional[Callable[[List[DrainReport]], None]] = None,
    ) -> None:
        self._targets = list(targets)
        self._final_cut = bool(final_cut)
        self._on_drained = on_drained
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._signum: Optional[int] = None
        self._notified_at: Optional[float] = None
        self._reports: Optional[List[DrainReport]] = None
        self._previous: Dict[int, Any] = {}
        # the notice runner is PRE-SPAWNED and parked: the signal handler
        # may not allocate or start threads (Thread.start takes threading's
        # internal non-reentrant lock — a signal landing while the main
        # thread is itself inside Thread.start would self-deadlock), so the
        # handler only flips the wake event of a thread that already exists
        self._wake = threading.Event()
        self._closed = False
        self._runner = threading.Thread(
            target=self._notice_runner, name="tpumetrics-preemption-notice",
            daemon=True,
        )
        self._runner.start()

    # ------------------------------------------------------------- observe

    @property
    def requested(self) -> bool:
        return self._event.is_set()

    @property
    def signum(self) -> Optional[int]:
        return self._signum

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until a preemption notice arrives (or ``timeout``)."""
        return self._event.wait(timeout)

    # -------------------------------------------------------------- notice

    def _notice(self, signum: int) -> bool:
        """Signal-handler body; returns True only for the FIRST notice.
        MUST stay lock-free against anything the interrupted main thread
        could hold: the handler runs in the main thread between bytecodes,
        so taking the service lock (mid-submit), the ledger lock (mid-emit)
        or threading's thread-startup lock would self-deadlock.  It
        therefore only records the signum and wakes the PRE-SPAWNED runner
        (the wake event's internal lock is touched by no other main-thread
        code path); everything that locks — telemetry records,
        ``request_drain`` on the targets — runs on the runner, which sets
        the guard's public event AFTER intake is off, so ``wait()``/
        ``requested`` returning true implies late submits already fail
        typed."""
        if self._signum is not None:
            return False  # repeated signal: the first notice is in flight
        self._signum = signum
        self._notified_at = time.monotonic()
        self._wake.set()
        return True

    def _notice_runner(self) -> None:
        self._wake.wait()
        if self._closed:
            return
        signum = self._signum
        _telemetry.record_event(
            None, "preemption_notice", signum=int(signum), pid=os.getpid()
        )
        _export.note_incident("preemption_notice", signum=int(signum))
        for t in self._targets:
            request = getattr(t, "request_drain", None)
            if request is not None:
                request()  # intake off: late submits get typed errors
        self._event.set()

    # --------------------------------------------------------------- drain

    def drain_now(self, timeout: Optional[float] = None) -> List[DrainReport]:
        """Run the graceful sequence on every target (idempotent)."""
        with self._lock:
            if self._reports is not None:
                return self._reports
            reports: List[DrainReport] = []
            for t in self._targets:
                reports.append(t.drain(final_cut=self._final_cut, timeout=timeout))
            self._reports = reports
        if self._on_drained is not None:
            self._on_drained(reports)
        return reports

    def uninstall(self) -> None:
        """Restore the previously-installed signal handlers and release the
        parked notice runner."""
        for signum, prev in self._previous.items():
            try:
                signal.signal(signum, prev)
            except (ValueError, OSError):  # not main thread / signal gone
                pass
        self._previous.clear()
        if self._signum is None:  # never signaled: let the runner exit
            self._closed = True
            self._wake.set()


def install_preemption_handler(
    *targets: Any,
    signals: Tuple[int, ...] = (signal.SIGTERM,),
    mode: str = "notify",
    final_cut: bool = True,
    on_drained: Optional[Callable[[List[DrainReport]], None]] = None,
) -> PreemptionGuard:
    """Register a preemption-notice handler for ``targets`` (evaluators/
    services — anything with ``request_drain``/``drain``).

    ``mode="notify"`` sets the returned guard's flag (poll ``requested`` or
    block in ``wait()``); ``mode="raise"`` additionally raises
    :class:`PreemptionInterrupt` in the main thread, interrupting a blocked
    main loop — the right choice for command-loop workers whose grace
    window is short.  Either way the handler marks intake off on every
    target immediately, so submits racing the notice fail typed instead of
    landing in a queue that is about to be drained for the last time.

    Must be called from the main thread (CPython restricts
    ``signal.signal``); returns the :class:`PreemptionGuard`.  Call
    :meth:`PreemptionGuard.uninstall` to restore previous handlers (tests).
    """
    if mode not in ("notify", "raise"):
        raise ValueError(f"mode must be 'notify' or 'raise', got {mode!r}")
    guard = PreemptionGuard(targets, final_cut=final_cut, on_drained=on_drained)

    def _handler(signum: int, _frame: Any) -> None:
        first = guard._notice(signum)
        if mode == "raise" and first:
            # only the FIRST notice interrupts: a fleet re-sending SIGTERM
            # during the grace window must not abort the drain the first
            # signal already started (the guard's documented idempotency)
            raise PreemptionInterrupt(signum)

    for signum in signals:
        guard._previous[signum] = signal.signal(signum, _handler)
    return guard
