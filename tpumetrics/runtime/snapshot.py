"""Preemption-safe metric-state snapshots: atomic write-rename + replay tags.

A preempted host must not lose hours of accumulated metric state, and a
restored host must not double-count or skip stream items.  Guarantees:

- **Atomicity.**  A snapshot is one ``.npz`` file written to a temp name in
  the same directory, fsynced, then ``os.replace``-d into place — readers
  never observe a torn file.  A CRC32 over every leaf's bytes is stored in
  the metadata and re-verified on load, so even exotic partial-write modes
  surface as :class:`SnapshotIntegrityError`, not silent corruption.
- **Monotonic step tagging.**  :class:`SnapshotManager` refuses to save a
  step <= the latest step already on disk (a restarted process that forgot
  to restore cannot silently rewind history); filenames embed the step and
  ``restore_latest`` picks the highest valid one, skipping corrupt files.
- **Replay contract.**  The snapshot stores caller metadata (the evaluator
  records how many batches/items were drained *before* the save, with the
  ingestion queue flushed), so a restored consumer knows exactly which
  stream position the state covers and replays from there — see the
  crash-consistency test in ``tests/test_runtime.py``.

State travels as a flattened pytree (``jax.tree_util`` paths -> host numpy
leaves), which covers metric attribute states, functional state pytrees, and
:class:`~tpumetrics.buffers.MaskedBuffer` leaves alike.  ``restore`` needs a
**template** pytree (e.g. ``metric.init_state()``) and validates the stored
spec — leaf paths, shapes, dtypes — against it, raising
:class:`SnapshotSpecError` naming every mismatch.

Built on the same serialization contract as
:meth:`tpumetrics.metric.Metric.state_dict` /
:meth:`~tpumetrics.metric.Metric.load_state_dict`: the eager OO hooks
(:meth:`~tpumetrics.metric.Metric.snapshot_state` /
:meth:`~tpumetrics.metric.Metric.load_snapshot_state`) produce exactly the
pytrees saved here.
"""

from __future__ import annotations

import json
import os
import re
import zipfile
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from tpumetrics.resilience import storage as _storage
from tpumetrics.utils.exceptions import TPUMetricsUserError

FORMAT = "tpumetrics-snapshot"
VERSION = 1
_FILE_RE = re.compile(r"^snapshot-(\d+)\.npz$")


class SnapshotError(TPUMetricsUserError):
    """Base class for snapshot failures."""


class SnapshotSpecError(SnapshotError):
    """Stored state spec is incompatible with the restore template."""


class SnapshotIntegrityError(SnapshotError):
    """Snapshot file failed checksum/format validation."""


def _flatten(tree: Any) -> List[Tuple[str, Any]]:
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [(jax.tree_util.keystr(path), leaf) for path, leaf in leaves_with_paths]


def _crc(arrays: List[np.ndarray]) -> int:
    crc = 0
    for a in arrays:
        crc = zlib.crc32(np.ascontiguousarray(a).tobytes(), crc)
    return crc


def save_snapshot(
    directory: str,
    step: int,
    state: Any,
    meta: Optional[Dict[str, Any]] = None,
    guard_non_finite: str = "off",
    *,
    seam: str = "snapshot",
    retry_policy: Optional[_storage.RetryPolicy] = None,
) -> str:
    """Atomically write ``state`` (any pytree of arrays) as snapshot ``step``.

    Returns the final path.  The file only appears under its final name once
    fully written (write temp -> fsync -> rename), via the
    :mod:`~tpumetrics.resilience.storage` shim: transient I/O errors retry
    under ``retry_policy`` (labelled ``seam`` in the ledger/instruments),
    permanent ones raise a typed
    :class:`~tpumetrics.resilience.storage.StorageError`.

    ``guard_non_finite`` (``"off"``/``"warn"``/``"error"``) screens every
    float leaf for NaN/Inf before it is persisted: a poisoned state written
    to disk would otherwise survive a crash-restore cycle and re-poison the
    stream — ``"error"`` raises :class:`~tpumetrics.resilience.policy.
    NonFiniteStateError` naming the offending leaf path instead.
    """
    os.makedirs(directory, exist_ok=True)
    flat = _flatten(state)
    host: List[np.ndarray] = [np.asarray(jax.device_get(leaf)) for _, leaf in flat]
    if guard_non_finite != "off":
        from tpumetrics.resilience.policy import screen_non_finite

        for (path, _), arr in zip(flat, host):
            screen_non_finite(arr, where=f"snapshot leaf {path!r}", mode=guard_non_finite)
    spec = [
        {"path": path, "shape": list(a.shape), "dtype": str(a.dtype)}
        for (path, _), a in zip(flat, host)
    ]
    header = {
        "format": FORMAT,
        "version": VERSION,
        "step": int(step),
        "spec": spec,
        "crc32": _crc(host),
        "meta": dict(meta or {}),
    }
    # for plain dict/list pytrees (e.g. Metric.snapshot_state payloads, which
    # may hold variable-length eager list states) store a leaf-index skeleton
    # so the tree is reconstructible WITHOUT a template of identical list
    # lengths; non-JSON structures (NamedTuple leaves etc.) use template
    # restore instead
    try:
        counter = iter(range(len(host)))
        skeleton = jax.tree_util.tree_map(lambda _leaf: next(counter), state)
        encoded = json.dumps(skeleton)
        if json.loads(encoded) == skeleton:  # round-trips exactly (no tuples)
            header["skeleton"] = skeleton
    except (TypeError, ValueError):
        pass
    payload = {f"leaf_{i}": a for i, a in enumerate(host)}
    payload["__header__"] = np.frombuffer(json.dumps(header).encode(), dtype=np.uint8)

    final = os.path.join(directory, f"snapshot-{int(step)}.npz")
    # the shim owns the temp-write -> fsync -> rename -> directory-fsync
    # sequence (the directory fsync matters: the file's bytes are durable,
    # but the rename itself lives in the directory inode — without it a host
    # power-loss can leave a directory entry pointing at nothing) and retries
    # the WHOLE sequence on transient I/O errors
    return _storage.atomic_write(
        directory,
        final,
        lambda fh: np.savez(fh, **payload),
        seam=seam,
        prefix=".snapshot-",
        suffix=".tmp",
        policy=retry_policy,
    )


def _fsync_dir(directory: str) -> None:
    _storage.fsync_directory(directory)


def list_snapshots(directory: str) -> List[Tuple[int, str]]:
    """``(step, path)`` of every snapshot file, ascending by step."""
    if not os.path.isdir(directory):
        return []
    out = []
    for name in os.listdir(directory):
        m = _FILE_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    return sorted(out)


def _header_of(z: Any, path: str) -> Dict[str, Any]:
    if "__header__" not in z.files:
        raise SnapshotIntegrityError(f"{path}: not a tpumetrics snapshot (no header)")
    header = json.loads(bytes(z["__header__"].tobytes()).decode())
    if header.get("format") != FORMAT:
        raise SnapshotIntegrityError(f"{path}: unknown format {header.get('format')!r}")
    if header.get("version") != VERSION:
        raise SnapshotIntegrityError(
            f"{path}: snapshot version {header.get('version')} != supported {VERSION}"
        )
    return header


def read_header(path: str, *, seam: str = "snapshot") -> Dict[str, Any]:
    """Header (step/spec/meta) WITHOUT loading or checksumming the leaves —
    the cheap scan primitive the elastic cut discovery uses to group rank
    snapshots before committing to a full CRC-verified load.  Transient read
    errors retry through the storage shim."""

    def _read() -> Dict[str, Any]:
        with np.load(path) as z:
            return _header_of(z, path)

    try:
        return _storage.read_with_retry(_read, seam=seam, path=path)
    except (OSError, ValueError, KeyError, json.JSONDecodeError, zipfile.BadZipFile) as err:
        raise SnapshotIntegrityError(f"{path}: unreadable snapshot ({err})") from err


def load_snapshot(path: str, *, seam: str = "snapshot") -> Tuple[Dict[str, Any], List[np.ndarray]]:
    """Read + integrity-check one snapshot file -> (header, leaves)."""

    def _read() -> Tuple[Dict[str, Any], List[np.ndarray]]:
        with np.load(path) as z:
            header = _header_of(z, path)
            leaves = [z[f"leaf_{i}"] for i in range(len(header["spec"]))]
        return header, leaves

    try:
        header, leaves = _storage.read_with_retry(_read, seam=seam, path=path)
    except (OSError, ValueError, KeyError, json.JSONDecodeError, zipfile.BadZipFile) as err:
        raise SnapshotIntegrityError(f"{path}: unreadable snapshot ({err})") from err
    if _crc(leaves) != header["crc32"]:
        raise SnapshotIntegrityError(f"{path}: checksum mismatch (torn or corrupted write)")
    return header, leaves


def validate_spec(
    header: Dict[str, Any],
    template: Any,
    context: str = "",
    annotations: Optional[Dict[str, str]] = None,
) -> None:
    """Compare a snapshot's stored spec against a template pytree; raise
    :class:`SnapshotSpecError` listing every path/shape/dtype mismatch.

    ``annotations`` maps leaf-path *suffixes* (e.g. ``"['sketch']"``) to
    human notes appended to that path's mismatch lines — how merge-kind
    (sketch) states get their declared capacity/levels named in the error,
    the way ``_config_fingerprint`` names classification configs."""
    flat = _flatten(template)
    want = [
        {"path": p, "shape": list(np.shape(leaf)), "dtype": str(np.asarray(jax.device_get(leaf)).dtype)}
        for p, leaf in flat
    ]
    got = header["spec"]
    problems = []
    got_by_path = {e["path"]: e for e in got}
    want_by_path = {e["path"]: e for e in want}

    def _note(path: str) -> str:
        for suffix, text in (annotations or {}).items():
            if path.endswith(suffix):
                return f" [{text}]"
        return ""

    for p in want_by_path:
        if p not in got_by_path:
            problems.append(f"missing state {p}{_note(p)}")
    for p in got_by_path:
        if p not in want_by_path:
            problems.append(f"unexpected state {p}{_note(p)}")
    for p, w in want_by_path.items():
        g = got_by_path.get(p)
        if g and (g["shape"] != w["shape"] or g["dtype"] != w["dtype"]):
            problems.append(
                f"{p}: stored {g['dtype']}{g['shape']} != expected {w['dtype']}{w['shape']}{_note(p)}"
            )
    if problems:
        raise SnapshotSpecError(
            f"Snapshot state spec incompatible with {context or 'the restore template'}: "
            + "; ".join(problems)
            + ". HINT: the metric configuration (classes/thresholds/capacity/dtype) "
            "must match the one that wrote the snapshot."
        )


def restore(
    path: str, template: Any, annotations: Optional[Dict[str, str]] = None
) -> Tuple[Any, Dict[str, Any]]:
    """Load one snapshot into the template's pytree structure -> (state, header)."""
    header, leaves = load_snapshot(path)
    validate_spec(header, template, context=f"template for {path}", annotations=annotations)
    treedef = jax.tree_util.tree_structure(template)
    ordered = [jax.numpy.asarray(a) for a in leaves]
    return jax.tree_util.tree_unflatten(treedef, ordered), header


def state_annotations(metric: Any) -> Dict[str, str]:
    """Leaf-path-suffix annotations for ``metric``'s functional state
    template: one entry per merge-kind (:class:`~tpumetrics.parallel.merge.
    AssociativeMerge`) state, naming its declared parameters — threaded into
    :func:`validate_spec` by the runtime so a sketch-geometry mismatch reads
    ``sketch: stored f32[1, 5379] != expected f32[1, 2051] [merge state
    'sketch' (merge:sketch(capacity=16, levels=16, ...))]`` instead of bare
    shapes."""
    from tpumetrics.collections import MetricCollection
    from tpumetrics.parallel.merge import AssociativeMerge

    if isinstance(metric, MetricCollection):
        members = list(metric._modules.items())
    else:
        members = [(None, metric)]
    out: Dict[str, str] = {}
    for key, m in members:
        for name, fn in getattr(m, "_reductions", {}).items():
            if isinstance(fn, AssociativeMerge) and fn.params:
                # collection leaf paths are leader-qualified — key each
                # annotation by the member too, so two members with a
                # same-named sketch state of DIFFERENT geometry never
                # collide onto one entry (the suffix match would then name
                # the wrong parameters)
                suffix = f"['{key}']['{name}']" if key is not None else f"['{name}']"
                out[suffix] = f"merge state {name!r} ({fn.describe()})"
    return out


def restore_latest(
    directory: str,
    template: Any,
    annotations: Optional[Dict[str, str]] = None,
    *,
    quarantine_corrupt: bool = True,
) -> Optional[Tuple[Any, Dict[str, Any]]]:
    """Restore the highest-step valid snapshot in ``directory``.

    Corrupt/torn files (e.g. a crash mid-write that still left a temp file,
    or disk-level damage) are skipped with the next-newest tried, so a bad
    latest snapshot degrades to the previous one instead of failing the
    restore — and (by default) moved into the directory's bounded
    ``.quarantine/`` so the fallback walk is paid once, not on every later
    restore.  Spec mismatches are NOT skipped — they mean the caller's
    configuration changed, which must surface.  Returns ``None`` when the
    directory holds no snapshot.
    """
    for _step, path in reversed(list_snapshots(directory)):
        try:
            return restore(path, template, annotations=annotations)
        except SnapshotIntegrityError as err:
            if quarantine_corrupt:
                _storage.quarantine(path, reason=str(err))
            continue
    return None


def reconstruct(header: Dict[str, Any], leaves: List[np.ndarray]) -> Any:
    """Rebuild a dict/list pytree from the stored leaf-index skeleton
    (template-free restore — the path for :meth:`Metric.snapshot_state`
    payloads whose eager list states may differ in length from any fresh
    template).  Raises :class:`SnapshotIntegrityError` when the snapshot was
    written without a skeleton (use :func:`restore` with a template)."""
    skeleton = header.get("skeleton")
    if skeleton is None:
        raise SnapshotIntegrityError(
            "Snapshot has no structure skeleton; restore it with a template "
            "pytree (snapshot.restore/restore_latest)."
        )

    def build(node: Any) -> Any:
        if isinstance(node, dict):
            return {k: build(v) for k, v in node.items()}
        if isinstance(node, list):
            return [build(v) for v in node]
        if isinstance(node, int) and not isinstance(node, bool):
            return leaves[node]
        return node  # None and other JSON scalars pass through

    return build(skeleton)


def restore_latest_reconstruct(
    directory: str, *, quarantine_corrupt: bool = True
) -> Optional[Tuple[Any, Dict[str, Any]]]:
    """Template-free :func:`restore_latest` for skeleton-bearing snapshots."""
    for _step, path in reversed(list_snapshots(directory)):
        try:
            header, leaves = load_snapshot(path)
        except SnapshotIntegrityError as err:
            if quarantine_corrupt:
                _storage.quarantine(path, reason=str(err))
            continue
        try:
            return reconstruct(header, leaves), header
        except SnapshotIntegrityError:
            # a skeleton-less snapshot is HEALTHY (it just needs a template
            # restore) — skip it, but never quarantine it
            continue
    return None


class SnapshotManager:
    """Directory-level snapshot policy: monotonic steps + bounded retention.

    Args:
        directory: snapshot directory (created on first save).
        keep: how many most-recent snapshots to retain (older ones are
            pruned after a successful save); ``None`` keeps everything.
        seam: the durability-seam label saves carry through the storage
            shim (``io_retry`` events, ``tpumetrics_io_retries_total``).
    """

    def __init__(
        self, directory: str, keep: Optional[int] = 3, *, seam: str = "snapshot"
    ) -> None:
        if keep is not None and keep < 1:
            raise ValueError(f"keep must be >= 1 or None, got {keep}")
        self.directory = directory
        self.keep = keep
        self.seam = seam
        existing = list_snapshots(directory)
        self._last_step: Optional[int] = existing[-1][0] if existing else None

    @property
    def last_step(self) -> Optional[int]:
        return self._last_step

    def save(
        self,
        step: int,
        state: Any,
        meta: Optional[Dict[str, Any]] = None,
        guard_non_finite: str = "off",
    ) -> str:
        step = int(step)
        if self._last_step is not None and step <= self._last_step:
            raise SnapshotError(
                f"Non-monotonic snapshot step {step} (latest on disk: {self._last_step}). "
                "HINT: restore_latest() first, or point the manager at a fresh directory."
            )
        path = save_snapshot(
            self.directory, step, state, meta=meta, guard_non_finite=guard_non_finite,
            seam=self.seam,
        )
        self._last_step = step
        if self.keep is not None:
            for _, old in list_snapshots(self.directory)[: -self.keep]:
                try:
                    os.unlink(old)
                except OSError:
                    pass
        return path

    def restore_latest(
        self, template: Any, annotations: Optional[Dict[str, str]] = None
    ) -> Optional[Tuple[Any, Dict[str, Any]]]:
        return restore_latest(self.directory, template, annotations=annotations)
