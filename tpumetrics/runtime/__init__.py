"""``tpumetrics.runtime`` — the streaming evaluation runtime.

The layer between the L1 metric core (state pytrees, functional updates,
fused sync) and a serving system: it owns **ingestion** (async dispatch off
the request path), **shape discipline** (bucketed padding so ragged traffic
compiles once per bucket), and **recovery** (preemption-safe snapshots with
replay positions).  See ``docs/runtime.md`` for the guide.

- :mod:`~tpumetrics.runtime.dispatch` — bounded async queue + backpressure
  (block / drop-oldest / error) + worker draining micro-batches, with queue
  depth and drop counts reported into the telemetry ledger.
- :mod:`~tpumetrics.runtime.bucketing` — pow-2 or user-supplied bucket
  edges, row-0 padding, and the exact masked-update semantics (native
  ``valid`` mask or delta-correction fallback).
- :mod:`~tpumetrics.runtime.snapshot` — atomic write-rename snapshots,
  CRC-verified, monotonically step-tagged, restored against a validated
  state spec.
- :mod:`~tpumetrics.runtime.evaluator` — :class:`StreamingEvaluator`, the
  facade tying the three together with ``compute_every(n)``
  bounded-staleness results and clean queue-flushing shutdown.  Bucketed
  updates run ONE fused, buffer-donating XLA program per (bucket,
  signature) for the whole collection
  (:class:`~tpumetrics.parallel.fuse_update.FusedCollectionStep`).
- :mod:`~tpumetrics.runtime.compile_cache` — JAX's persistent compilation
  cache as a one-call option, so cold starts / preemption restarts /
  elastic resizes reuse on-disk executables instead of re-compiling
  (``docs/performance.md``).
- :mod:`~tpumetrics.runtime.scheduler` — deficit-round-robin fairness and
  the LRU-bounded trace-signature registry, the primitives under the
  multi-tenant service.
- :mod:`~tpumetrics.runtime.service` — :class:`EvaluationService`:
  thousands of tenant streams multiplexed onto ONE dispatcher, with
  cross-tenant compile dedupe (same-config tenants share one fused step),
  a vmapped megabatch fast path, DRR fairness + per-tenant backpressure
  and quotas, and per-tenant quarantine/snapshots/telemetry
  (``docs/service.md``).

Multi-host: with ``snapshot_rank``/``snapshot_world_size`` set, snapshots
become COORDINATED cuts (barrier-stamped, per-rank directories) and
:meth:`StreamingEvaluator.restore_elastic` restores them onto a different
world size after preemption — see :mod:`tpumetrics.resilience.elastic`.
"""

from tpumetrics.runtime.bucketing import (
    NotBucketableError,
    ShapeBucketer,
    check_bucketable,
    masked_functional_update,
    pow2_bucket_edges,
)
from tpumetrics.runtime.compile_cache import (
    compilation_cache_info,
    count_cache_hits,
    enable_persistent_compilation_cache,
)
from tpumetrics.runtime.dispatch import AsyncDispatcher, DispatcherClosedError, QueueFullError
from tpumetrics.runtime.drain import (
    DrainReport,
    DrainingError,
    PreemptionGuard,
    PreemptionInterrupt,
    install_preemption_handler,
)
from tpumetrics.runtime.evaluator import CrashLoopError, StreamingEvaluator
from tpumetrics.runtime.scheduler import DeficitRoundRobin, SignatureRegistry
from tpumetrics.runtime.service import (
    EvaluationService,
    TenantHandle,
    TenantQuarantinedError,
)
from tpumetrics.runtime.snapshot import (
    SnapshotError,
    SnapshotIntegrityError,
    SnapshotManager,
    SnapshotSpecError,
    list_snapshots,
    load_snapshot,
    restore,
    restore_latest,
    save_snapshot,
)

__all__ = [
    "AsyncDispatcher",
    "CrashLoopError",
    "DeficitRoundRobin",
    "DispatcherClosedError",
    "DrainReport",
    "DrainingError",
    "EvaluationService",
    "PreemptionGuard",
    "PreemptionInterrupt",
    "install_preemption_handler",
    "NotBucketableError",
    "QueueFullError",
    "ShapeBucketer",
    "SignatureRegistry",
    "TenantHandle",
    "TenantQuarantinedError",
    "SnapshotError",
    "SnapshotIntegrityError",
    "SnapshotManager",
    "SnapshotSpecError",
    "StreamingEvaluator",
    "check_bucketable",
    "compilation_cache_info",
    "count_cache_hits",
    "enable_persistent_compilation_cache",
    "list_snapshots",
    "load_snapshot",
    "masked_functional_update",
    "pow2_bucket_edges",
    "restore",
    "restore_latest",
    "save_snapshot",
]
