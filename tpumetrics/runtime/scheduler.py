"""Fairness and compile-bookkeeping primitives for multiplexed streams.

Two small, lock-free (caller-synchronized) data structures the
multi-tenant :class:`~tpumetrics.runtime.service.EvaluationService` is
built from — kept separate so their invariants are unit-testable without
threads or devices:

- :class:`DeficitRoundRobin` — the classic DRR scheduler over tenant ids.
  Each tenant carries a *quantum* (its fair share per scheduling round, in
  whatever cost unit the caller charges — the service charges batch rows)
  and a *deficit counter*; a tenant may be served while its deficit covers
  the head-of-queue cost, and earns one quantum per round otherwise.  DRR
  is O(1) per decision and starvation-free: a backlogged tenant is visited
  every round regardless of how hot its neighbors run, and a tenant whose
  cost exceeds its quantum accumulates deficit across rounds until it can
  be served (never skipped forever).

- :class:`SignatureRegistry` — an LRU-bounded replacement for the
  unbounded ``set`` the single-stream evaluator used to track trace
  signatures.  A shape-churning (or adversarial) stream produces unbounded
  distinct signatures; the registry caps the tracked set and counts
  evictions instead of leaking.  Eviction only costs accounting accuracy
  (a re-seen evicted signature is conservatively treated as new again —
  jit's own executable cache is unaffected), never correctness.
"""

from __future__ import annotations

from collections import OrderedDict, deque
from typing import Any, Callable, Dict, Hashable, Optional

__all__ = ["DeficitRoundRobin", "SignatureRegistry"]


class SignatureRegistry:
    """LRU-bounded set of trace signatures with insert/eviction accounting.

    Args:
        capacity: maximum number of signatures tracked; ``None`` = unbounded
            (the pre-LRU behavior).

    :meth:`observe` returns ``True`` when the signature is *new* (not
    currently tracked) — the caller's cue to pre-compile — and refreshes
    recency otherwise.  ``inserts`` counts every new-at-observation
    signature (== the number of distinct signatures when nothing was ever
    evicted, which keeps the evaluator's ``xla_compiles`` stat identical on
    non-adversarial streams); ``evictions`` counts LRU evictions.
    """

    def __init__(self, capacity: Optional[int] = None) -> None:
        if capacity is not None and int(capacity) <= 0:
            raise ValueError(f"capacity must be positive or None, got {capacity}")
        self._capacity = int(capacity) if capacity is not None else None
        self._seen: "OrderedDict[Hashable, None]" = OrderedDict()
        self.inserts = 0
        self.evictions = 0

    def observe(self, sig: Hashable) -> bool:
        """Record one signature; ``True`` iff it was not currently tracked."""
        if sig in self._seen:
            self._seen.move_to_end(sig)
            return False
        self._seen[sig] = None
        self.inserts += 1
        if self._capacity is not None:
            while len(self._seen) > self._capacity:
                self._seen.popitem(last=False)
                self.evictions += 1
        return True

    def __len__(self) -> int:
        return len(self._seen)

    def __contains__(self, sig: Hashable) -> bool:
        return sig in self._seen


class DeficitRoundRobin:
    """Deficit round-robin over tenant ids (caller holds the lock).

    The caller owns the actual work queues; the scheduler only tracks the
    *active ring* (tenants with queued work), per-tenant quanta, and deficit
    counters.  Protocol per decision::

        tid = drr.select(head_cost)   # head_cost(tid) -> cost or None
        ... pop + run that tenant's head item ...

    ``head_cost`` returns the cost of a tenant's head-of-queue item, or
    ``None`` when the tenant has no work (it is then dropped from the ring
    and its deficit reset — the DRR rule that keeps an idle tenant from
    hoarding credit).  ``select`` charges the returned tenant's deficit for
    the head cost; :meth:`charge` lets the caller bill extra cost to a
    tenant served out of turn (the megabatch path serves several tenants'
    heads in one device program — fairness must still account for them).
    """

    def __init__(self) -> None:
        self._quantum: Dict[Any, float] = {}
        self._deficit: Dict[Any, float] = {}
        self._ring: deque = deque()  # active tenants, head = next to visit
        self._in_ring: set = set()

    # ------------------------------------------------------------ membership

    def add(self, tid: Any, quantum: float) -> None:
        if tid in self._quantum:
            raise ValueError(f"tenant {tid!r} already scheduled")
        if not quantum > 0:
            raise ValueError(f"quantum must be positive, got {quantum}")
        self._quantum[tid] = float(quantum)
        self._deficit[tid] = 0.0

    def remove(self, tid: Any) -> None:
        self._quantum.pop(tid, None)
        self._deficit.pop(tid, None)
        if tid in self._in_ring:
            self._in_ring.discard(tid)
            self._ring.remove(tid)

    def activate(self, tid: Any) -> None:
        """Mark a tenant as having queued work (idempotent)."""
        if tid not in self._quantum:
            raise KeyError(f"unknown tenant {tid!r}")
        if tid not in self._in_ring:
            self._ring.append(tid)
            self._in_ring.add(tid)

    @property
    def active(self) -> int:
        return len(self._ring)

    def __len__(self) -> int:
        """Scheduled tenants (resident membership, active or not) — the
        lifecycle manager's O(active) census: hibernated tenants are
        removed entirely, so this tracks residents, never the registered
        total."""
        return len(self._quantum)

    def __contains__(self, tid: Any) -> bool:
        return tid in self._quantum

    # ------------------------------------------------------------ scheduling

    def select(self, head_cost: Callable[[Any], Optional[float]]) -> Optional[Any]:
        """Pick the next tenant to serve one head item from, or ``None``
        when no tenant has work.  Charges the winner's deficit."""
        while self._ring:
            # one pass over the ring; tenants whose deficit cannot cover
            # their head cost earn a quantum and rotate to the tail.  If a
            # full pass serves nobody, the loop re-enters and everyone earns
            # again — the bounded "fast-forward" of DRR rounds for a head
            # item costing more than one quantum.
            served_possible = False
            for _ in range(len(self._ring)):
                tid = self._ring[0]
                cost = head_cost(tid)
                if cost is None:
                    # no work: leave the ring, forfeit accumulated deficit
                    self._ring.popleft()
                    self._in_ring.discard(tid)
                    self._deficit[tid] = 0.0
                    if not self._ring:
                        return None
                    continue
                if self._deficit[tid] >= cost:
                    self._deficit[tid] -= cost
                    return tid
                self._deficit[tid] += self._quantum[tid]
                if self._deficit[tid] >= cost:
                    served_possible = True
                self._ring.rotate(-1)
            if not self._ring:
                return None
            if not served_possible:
                # every active tenant still short after earning this round's
                # quantum — keep earning (equivalent to idling real rounds)
                continue
        return None

    def charge(self, tid: Any, cost: float) -> None:
        """Bill extra served cost to a tenant (megabatch co-service); the
        deficit may go negative, deferring its next solo turn."""
        if tid in self._deficit:
            self._deficit[tid] -= float(cost)

    def deficit(self, tid: Any) -> float:
        return self._deficit.get(tid, 0.0)
