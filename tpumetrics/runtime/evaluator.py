"""StreamingEvaluator — the runtime facade: async, bucketed, restartable.

Ties the three runtime pieces together around any ``Metric`` /
``MetricCollection``:

- ingestion rides an :class:`~tpumetrics.runtime.dispatch.AsyncDispatcher`
  (bounded queue, backpressure policy, worker thread) so ``submit`` never
  runs a device step on the caller's thread;
- with ``buckets`` set, updates run through per-bucket **jitted** step
  functions over :class:`~tpumetrics.runtime.bucketing.ShapeBucketer`-padded
  batches — the XLA compile count is bounded by the bucket set, not by the
  number of distinct batch shapes the stream produces;
- with ``buckets=None``, updates run the eager OO path (``metric.update``)
  — still async, and the only mode for metrics with ragged eager list
  states (mAP-style) that cannot take padded updates;
- snapshots (:mod:`tpumetrics.runtime.snapshot`) are taken at drained-batch
  boundaries, tagged with the stream position, and written atomically;
  :meth:`restore_latest` validates spec compatibility and returns the
  position to replay from.

Determinism contract (load-bearing for preemption recovery): every
submitted batch is applied to the state **individually, in submission
order** — the worker never concatenates queued batches — so the sequence of
state transitions is a pure function of the submitted stream.  A restored
evaluator that replays the stream from the snapshot's ``batches`` position
therefore reaches **bit-identical** ``compute()`` results to an
uninterrupted run (verified in ``tests/test_runtime.py``).

Bounded staleness: with ``compute_every=n`` the worker refreshes
:meth:`latest_result` after every ``n`` drained batches — serving handlers
read a result at most ``n`` batches stale without ever blocking on a
flush + compute.

Self-healing (``tpumetrics.resilience``): with ``crash_policy="restore"``
the evaluator keeps an in-memory **journal** of the batches applied since
the last snapshot; when a batch crashes the worker, it restores the latest
good snapshot, replays the journal plus the crashed micro-batch, and keeps
serving — bounded by ``max_restores`` (the crash-loop budget: a
deterministically-poisonous batch re-crashes every replay, and exhaustion
raises :class:`CrashLoopError` through the dispatcher's poison path instead
of looping forever).  Degraded results — a sync failure swallowed by the
active :class:`~tpumetrics.resilience.policy.SyncPolicy` (``on_failure=
"local"``/``"last_good"``) — are marked in :meth:`stats` and
:meth:`latest_result` and stamped into snapshot metadata, so the flag
round-trips across preemption.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from tpumetrics.metric import Metric
from tpumetrics.parallel.fuse_update import FusedCollectionStep
from tpumetrics.runtime.bucketing import (
    ShapeBucketer,
    check_bucketable,
    leading_rows,
    plan_bucketed_update,
    pow2_bucket_edges,
)
from tpumetrics.runtime.compile_cache import (
    ENV_CACHE_DIR,
    attribute_compiles,
    enable_persistent_compilation_cache,
    recompile_count,
)
from tpumetrics.runtime.dispatch import _DEPTH_GAUGE, AsyncDispatcher
from tpumetrics.runtime.scheduler import SignatureRegistry
from tpumetrics.runtime import snapshot as _snapshot
from tpumetrics.resilience import storage as _storage
from tpumetrics.telemetry import device as _device
from tpumetrics.telemetry import export as _export
from tpumetrics.telemetry import health as _health
from tpumetrics.telemetry import instruments as _instruments
from tpumetrics.telemetry import ledger as _telemetry
from tpumetrics.telemetry import spans as _spans
from tpumetrics.utils.exceptions import TPUMetricsUserError

Array = jax.Array

#: distinguishes two evaluators over the same metric class in the shared
#: process-global instrument registry (label cardinality: one per evaluator)
_STREAM_IDS = itertools.count(1)

# sketch=True: the runtime's latency quantiles carry the sketch's
# <= 1/capacity relative-error bound (and federate across ranks) instead of
# fixed-bucket interpolation — the SLO engine's p99 objectives compare
# against these
_SUBMIT_HIST = _instruments.histogram(
    _instruments.SUBMIT_LATENCY_MS, help="submit() call latency", labels=("stream",),
    sketch=True,
)
_DISPATCH_HIST = _instruments.histogram(
    _instruments.DISPATCH_LATENCY_MS, help="device dispatch latency", labels=("stream",),
    sketch=True,
)
_JOURNAL_GAUGE = _instruments.gauge(
    _instruments.JOURNAL_LEN, help="crash-replay journal length", labels=("stream",)
)
_RESTORE_HIST = _instruments.histogram(
    _instruments.RESTORE_LATENCY_MS,
    help="elastic restore (cut discovery + fold + reshard + place) latency",
    labels=("stream",),
    sketch=True,
)
_DRAIN_HIST = _instruments.histogram(
    _instruments.DRAIN_LATENCY_MS,
    help="graceful drain (flush + final cut) latency",
    labels=("stream",),
    sketch=True,
)
_STATE_HBM_GAUGE = _instruments.gauge(
    _instruments.STATE_HBM_BYTES,
    help="live metric-state buffer bytes held on device for the stream",
    labels=("stream",),
)
_DURABILITY_GAUGE = _instruments.gauge(
    _instruments.DURABILITY_DEGRADED,
    help="1 while cut durability is suspended behind the heal probe",
    labels=("stream",),
)

# heal-probe backoff while durability is degraded: the first re-attempt
# comes quickly (a transient ENOSPC window clears fast), later ones back
# off so a genuinely full disk is probed, not hammered
_HEAL_BACKOFF_BASE_S = 0.5
_HEAL_BACKOFF_MAX_S = 30.0


class CrashLoopError(TPUMetricsUserError):
    """The crash-loop budget (``max_restores``) is spent: the same (or a new)
    batch kept crashing the worker after every snapshot-restore-replay cycle.
    Poisons the dispatcher; the final underlying crash is ``__cause__``."""


#: how long a stats()-path reader may wait for the state lock before
#: serving its cached snapshot.  A donating dispatch holds the lock for the
#: host-side dispatch — normally microseconds, but a backend that
#: synchronizes on a pending donated input (the CPU client does) can hold
#: it for a whole device step; the never-blocking stats() contract (and the
#: admin plane's scrape-under-load pin) bounds the reader instead of the
#: backend.
_STATS_LOCK_TIMEOUT_S = 0.02


class _bounded_lock:
    """``with _bounded_lock(lock) as got:`` — acquire with a small timeout;
    ``got`` is False when the owner kept it (serve the cached snapshot)."""

    __slots__ = ("_lock", "_got")

    def __init__(self, lock: threading.Lock, timeout: float = _STATS_LOCK_TIMEOUT_S):
        self._lock = lock
        self._got = lock.acquire(timeout=timeout)

    def __enter__(self) -> bool:
        return self._got

    def __exit__(self, *exc: Any) -> None:
        if self._got:
            self._lock.release()


class StreamingEvaluator:
    """Streaming evaluation runtime around a Metric / MetricCollection.

    Args:
        metric: any :class:`~tpumetrics.metric.Metric` or
            :class:`~tpumetrics.collections.MetricCollection`.  For a
            collection on the bucketed path, call
            ``establish_compute_groups`` first if you want group dedup.
        buckets: bucket edges for shape-bucketed jitted updates — a sequence
            of sizes, an int (pow-2 edges up to it), or ``None`` for the
            eager (unbucketed, uncompiled) update path.
        backpressure: ``"block"`` | ``"drop_oldest"`` | ``"error"`` —
            :mod:`tpumetrics.runtime.dispatch`.
        max_queue: ingestion queue capacity (batches).
        micro_batch: max queued batches drained per worker cycle.
        compute_every: refresh :meth:`latest_result` every n drained batches.
        snapshot_dir: enable snapshots into this directory.
        snapshot_every: auto-snapshot every n drained batches (requires
            ``snapshot_dir``); manual :meth:`snapshot` works regardless.
        keep_snapshots: retention for :class:`SnapshotManager` (per rank
            directory in elastic mode).
        keep_cuts: CUT-level retention for elastic mode (requires
            ``snapshot_rank``/``snapshot_world_size``): keep the newest N
            complete coordinated cuts and garbage-collect superseded
            partial cuts + stale rank dirs, auto-run on rank 0's saves
            (:func:`tpumetrics.resilience.elastic.gc_cuts`) — the policy a
            days-long soak needs so the snapshot root stays O(N) instead
            of O(history).  Overrides ``keep_snapshots``.
        update_kwargs: static keyword arguments forwarded to every update
            (e.g. ``real=True``); per-batch data is positional.
        crash_policy: ``"raise"`` (default — a crashing batch poisons the
            dispatcher, the pre-resilience behavior) or ``"restore"`` —
            auto-restore the latest good snapshot and replay the journal
            (module docstring).  Without ``snapshot_dir`` the restore target
            is a fresh state and the journal spans the whole stream (bounded
            memory requires ``snapshot_every``).
        max_restores: crash-loop budget for ``crash_policy="restore"``.
        guard_non_finite: ``"off"``/``"warn"``/``"error"`` NaN/Inf screen on
            the state at every snapshot save (a poisoned state written to
            disk would survive restore and re-poison the stream).
        donate_state: donate the state pytree to every jitted step (default
            True) so XLA reuses the state buffers in place instead of
            allocating fresh ones per batch.  The evaluator is the sole
            owner of its functional state between steps, which is exactly
            the donation contract (``docs/performance.md``); disable only
            when external code holds references into ``_state``.
        mesh: a :class:`jax.sharding.Mesh` enabling **sharded execution
            mode** (requires ``buckets``): the state pytree lives as
            ``NamedSharding``-ed ``jax.Array``s placed per
            ``partition_rules``, batches shard along ``data_axis``, and
            every collection step runs as ONE global SPMD program whose
            ``dist_reduce_fx`` folds lower to in-trace collectives — zero
            host round trips from ``update()`` to ``compute()``, and
            :meth:`restore_elastic` becomes "re-place the same pytree on
            this (possibly different) mesh".
        partition_rules: optional
            :class:`~tpumetrics.parallel.sharding.StatePartitionRules`
            override (default: derived from the metric's state registry).
        data_axis: mesh axis batches shard along (default: first mesh axis).
        compile_cache_dir: enable JAX's persistent compilation cache rooted
            here (:func:`tpumetrics.runtime.enable_persistent_compilation_cache`)
            so cold starts, preemption restarts, and elastic resizes reuse
            on-disk executables instead of recompiling every bucket step.
            ``None`` falls back to ``$TPUMETRICS_COMPILE_CACHE`` if set and
            is otherwise a no-op — in particular a deployment-level
            ``$JAX_COMPILATION_CACHE_DIR`` is left entirely to jax (native
            thresholds), never rewritten by this constructor.
        snapshot_rank / snapshot_world_size: enable COORDINATED multi-host
            snapshots (:mod:`tpumetrics.resilience.elastic`): this rank
            writes into ``snapshot_dir/rank-<NNNNN>/`` and every
            :meth:`snapshot` runs the cut barrier first, stamping the file
            with the agreed step + cut digest.  :meth:`restore_elastic`
            then folds a consistent cut from ALL rank directories and
            re-shards it for this (possibly different-size) world.
        barrier_backend: backend carrying the barrier's host-object
            exchange; defaults to the ambient
            :func:`~tpumetrics.parallel.backend.get_default_backend` when
            ``snapshot_world_size > 1``.
        signature_cache_size: LRU capacity of the trace-signature registry
            backing ``stats()["xla_compiles"]`` (``None`` = unbounded).  A
            shape-churning stream beyond the capacity costs only eviction
            accounting (``stats()["signature_evictions"]``) and redundant
            cold-signature pre-compiles — never correctness or a leak.
        health_probe: arm the in-trace state health probe (requires
            ``buckets``): every step program additionally emits per-state
            NaN/inf/saturation counters (:mod:`tpumetrics.telemetry.health`)
            that stay ON DEVICE and ride down on the host fetches
            ``compute()``/``stats()`` already make — zero extra transfers,
            bit-identical state.  First corruption of a state latches one
            ``state_health`` ledger event and the
            ``tpumetrics_state_nonfinite_total{stream,state}`` series, so a
            poisoned stream is visible BEFORE the compute-time non-finite
            guard trips.
        admin_port: start the embedded admin server
            (:mod:`tpumetrics.telemetry.serve`) on this port (``0`` = an
            ephemeral port, read back from ``evaluator.admin.port``):
            ``/metrics``, ``/healthz``, ``/statusz``, ``/spanz``,
            ``/flightz`` served from a daemon thread, scoped to this
            evaluator and stopped by ``close()``.
    """

    def __init__(
        self,
        metric: Any,
        *,
        buckets: Union[None, int, Sequence[int]] = None,
        backpressure: str = "block",
        max_queue: int = 256,
        micro_batch: Optional[int] = None,
        compute_every: Optional[int] = None,
        snapshot_dir: Optional[str] = None,
        snapshot_every: Optional[int] = None,
        keep_snapshots: Optional[int] = 3,
        keep_cuts: Optional[int] = None,
        update_kwargs: Optional[Dict[str, Any]] = None,
        crash_policy: str = "raise",
        max_restores: int = 3,
        guard_non_finite: str = "off",
        donate_state: bool = True,
        compile_cache_dir: Optional[str] = None,
        snapshot_rank: Optional[int] = None,
        snapshot_world_size: Optional[int] = None,
        barrier_backend: Optional[Any] = None,
        mesh: Optional[Any] = None,
        partition_rules: Optional[Any] = None,
        data_axis: Optional[str] = None,
        signature_cache_size: Optional[int] = 4096,
        health_probe: bool = False,
        admin_port: Optional[int] = None,
    ) -> None:
        from tpumetrics.collections import MetricCollection

        if not isinstance(metric, (Metric, MetricCollection)):
            raise TypeError(f"Expected Metric or MetricCollection, got {type(metric)}")
        if compute_every is not None and compute_every < 1:
            raise ValueError(f"compute_every must be >= 1, got {compute_every}")
        if snapshot_every is not None and snapshot_dir is None:
            raise ValueError("snapshot_every requires snapshot_dir")
        if crash_policy not in ("raise", "restore"):
            raise ValueError(f"crash_policy must be 'raise' or 'restore', got {crash_policy!r}")
        if max_restores < 0:
            raise ValueError(f"max_restores must be >= 0, got {max_restores}")
        if guard_non_finite not in ("off", "warn", "error"):
            raise ValueError(
                f"guard_non_finite must be 'off', 'warn' or 'error', got {guard_non_finite!r}"
            )
        self._metric = metric
        self._update_kwargs = dict(update_kwargs or {})
        self._compute_every = compute_every
        self._snapshot_every = snapshot_every
        self._crash_policy = crash_policy
        self._max_restores = int(max_restores)
        self._guard_non_finite = guard_non_finite

        # persistent compile cache first: every jit below benefits.  Only an
        # explicit argument or tpumetrics' own env var opts in — a deployment
        # that sets bare $JAX_COMPILATION_CACHE_DIR gets jax's native cache
        # with jax's own thresholds, which this constructor must not rewrite
        if compile_cache_dir is not None or os.environ.get(ENV_CACHE_DIR):
            enable_persistent_compilation_cache(compile_cache_dir)

        if mesh is not None and buckets is None:
            raise ValueError(
                "mesh (sharded execution mode) requires buckets: sharded steps "
                "ride the functional/jitted path."
            )
        self._mesh = mesh
        if health_probe and buckets is None:
            raise ValueError(
                "health_probe rides the functional/jitted step path and "
                "therefore requires buckets."
            )
        if buckets is None:
            self._bucketer: Optional[ShapeBucketer] = None
            self._state: Optional[Dict[str, Any]] = None
            self._step: Optional[FusedCollectionStep] = None
        else:
            edges = pow2_bucket_edges(int(buckets)) if isinstance(buckets, int) else tuple(buckets)
            self._bucketer = ShapeBucketer(edges)
            check_bucketable(metric)
            # ONE jitted program per (bucket, trace signature) covers the
            # WHOLE collection, with the state pytree donated so XLA reuses
            # its buffers in place — the evaluator owns the state between
            # steps, so nothing else can observe the deleted inputs.  With a
            # mesh, that one program is a global SPMD program over all mesh
            # devices and the state lives as NamedSharding-ed arrays.
            self._step = FusedCollectionStep(
                metric, update_kwargs=self._update_kwargs, donate=bool(donate_state),
                mesh=mesh, partition_rules=partition_rules, data_axis=data_axis,
                health_probe=bool(health_probe),
            )
            self._state = self._step.init_state()

        self._lock = threading.Lock()  # guards state/counters/latest across threads
        self._batches = 0  # submitted batches fully applied to the state
        self._items = 0  # rows applied
        self._latest: Optional[Dict[str, Any]] = None
        self._last_compute_at = 0
        # (bucket, arg shapes/dtypes) signatures seen — LRU-bounded so an
        # adversarial shape-churning stream degrades to extra pre-compile
        # accounting (signature_evictions in stats()) instead of leaking an
        # unbounded set; jit's own executable cache is unaffected
        self._trace_signatures = SignatureRegistry(signature_cache_size)

        # resilience bookkeeping: batches applied since the last snapshot
        # (the crash-replay journal), its stream base position, crash/restore
        # counters, and whether the latest served result was degraded.
        # journal/base/inflight are worker-thread-only; counters+flag take
        # the lock.
        self._journal: list = []
        self._journal_base = 0
        self._inflight_pos = 0
        self._crashes = 0
        self._restores = 0
        self._degraded = False
        # device-side observability: the latest on-device health counter
        # tree (probed steps only; fetched host-side at stats()/compute()),
        # the per-state first-corruption latch (doubles as the minted
        # instrument-label ledger close() releases), and the live-state HBM
        # watermark
        self._device_health: Optional[Any] = None
        self._health_summary: Optional[Dict[str, Any]] = None  # last fetched
        self._health_alerted: set = set()
        self._health_lock = threading.Lock()  # one state_health event per corruption
        self._hbm_watermark = 0
        self._closed = False  # stats() after close must not re-mint released series
        # bounded-staleness snapshots served when a donating dispatch owns
        # the state lock (the never-blocking stats() contract; guarded by
        # _health_lock, which is never held across a dispatch)
        self._stats_cache: Dict[str, Any] = {}
        self._hbm_cache: Dict[str, int] = {"state_bytes": 0, "watermark_bytes": 0, "backbone_bytes": 0}
        # graceful-drain state: flag read lock-free on the submit hot path
        # (a single store-release is enough — late submits only need to fail
        # EVENTUALLY-before-close, and drain() flushes after setting it)
        self._drain_requested = False
        self._drain_report: Optional[Any] = None
        self._drain_lock = threading.Lock()  # serializes concurrent drain()s
        # durability degradation: a cut save whose StorageError survived the
        # shim's retry budget must not kill serving — the state is intact in
        # HBM.  Saves are suspended behind a backoff heal probe instead, the
        # window is latched observably (durability_degraded ledger event +
        # gauge, stats()["storage"], /healthz reason), and the first healed
        # probe IS the resume cut.  Mutated under _lock on the save paths;
        # read lock-free (GIL-atomic scalars) by the never-blocking stats().
        self._storage_degraded = False
        self._storage_reason: Optional[str] = None
        self._storage_degraded_at: Optional[float] = None
        self._suspended_cuts = 0  # auto-cadence saves skipped while degraded
        self._heal_backoff_s = _HEAL_BACKOFF_BASE_S
        self._next_heal_at = 0.0  # monotonic deadline for the next probe
        self._durable_items = 0  # items covered by the last durable cut
        self._restore_fallback_depth: Optional[int] = None  # restore_elastic

        if (snapshot_rank is None) != (snapshot_world_size is None):
            raise ValueError("snapshot_rank and snapshot_world_size must be set together")
        if snapshot_every is not None and snapshot_world_size is not None and snapshot_world_size > 1:
            # the auto cadence triggers on the LOCAL batch count; ranks
            # draining uneven stream shards would reach the trigger a
            # different number of times and the unmatched cut barrier would
            # hang (inert policy) or crash-loop (armed).  Coordinated cuts
            # need an agreed trigger: call snapshot() at application-level
            # coordinated points instead.
            raise ValueError(
                "snapshot_every cannot drive coordinated (multi-rank elastic) "
                "snapshots: the per-rank batch cadence is not provably lockstep "
                "across ranks. Call snapshot() at coordinated stream points."
            )
        self._elastic = snapshot_rank is not None
        self._rank = int(snapshot_rank) if self._elastic else 0
        self._world = int(snapshot_world_size) if self._elastic else 1
        self._barrier_backend = barrier_backend
        self._elastic_config: Optional[str] = None
        self._elastic_base_batches = 0  # global stream position adopted by the
        self._elastic_base_items = 0  # last elastic restore (0 = fresh world)
        if self._elastic:
            if snapshot_dir is None:
                raise ValueError("snapshot_rank/snapshot_world_size require snapshot_dir")
            from tpumetrics.resilience.elastic import (
                DistributedSnapshotManager,
                config_digest,
            )

            self._elastic_config = config_digest(metric)
            self._snapshots: Optional[Any] = DistributedSnapshotManager(
                snapshot_dir, self._rank, self._world, keep=keep_snapshots,
                keep_cuts=keep_cuts,
            )
        else:
            if keep_cuts is not None:
                raise ValueError(
                    "keep_cuts is cut-level retention and needs the elastic "
                    "constructor arguments (snapshot_rank/snapshot_world_size); "
                    "use keep_snapshots for rank-local retention."
                )
            self._snapshots = (
                _snapshot.SnapshotManager(snapshot_dir, keep=keep_snapshots)
                if snapshot_dir
                else None
            )

        name = type(metric).__name__
        self._stream = f"{name}#{next(_STREAM_IDS)}"
        self._dispatcher = AsyncDispatcher(
            self._drain,
            max_queue=max_queue,
            policy=backpressure,
            max_batch=micro_batch,
            name=name,
            instrument_label=self._stream,  # gauges are last-write-wins per label
            crash_handler=self._handle_crash if crash_policy == "restore" else None,
        )
        # the embedded admin plane (telemetry/serve.py): a strict host-side
        # reader over this evaluator — /metrics, /healthz, /statusz, /spanz,
        # /flightz on a daemon thread.  Owned here, stopped by close().
        self._admin = None
        if admin_port is not None:
            from tpumetrics.telemetry.serve import start_admin_server

            self._admin = start_admin_server(
                int(admin_port), targets={self._stream: self}, name=self._stream
            )

    @property
    def admin(self):
        """The embedded :class:`~tpumetrics.telemetry.serve.AdminServer`
        (``admin_port=``), or ``None``."""
        return self._admin

    # -------------------------------------------------------------- ingestion

    def submit(self, *args: Any) -> None:
        """Enqueue one batch (positional update args); applies backpressure.

        Never runs the update on the calling thread — cost is one bounded
        enqueue (plus the policy's wait when the queue is full).  With span
        tracing on, the batch roots a fresh trace here ("one batch = one
        trace"); with instruments on, the call duration lands in the shared
        ``tpumetrics_submit_latency_ms{stream=…}`` histogram.
        """
        if not args:
            raise ValueError("submit() needs at least one positional batch argument")
        if self._drain_requested:
            from tpumetrics.runtime.drain import DrainingError

            raise DrainingError(
                f"StreamingEvaluator {self._stream!r} is draining (preemption notice "
                "or request_drain()): intake is closed. Re-route the stream; batches "
                "submitted before the drain began are being applied and will be "
                "covered by the final snapshot cut."
            )
        timed = _instruments.enabled()
        t0 = time.perf_counter() if timed else 0.0
        root = _spans.start_trace("batch", stream=self._stream)
        try:
            self._dispatcher.submit((args, root), trace_ctx=root)
            # successful submits only: a failed one (closed dispatcher, full
            # queue) must not pollute the distribution — or re-mint the
            # series close() just released
            if timed:
                _SUBMIT_HIST.observe((time.perf_counter() - t0) * 1e3, self._stream)
        except BaseException as err:
            _spans.end_span(root, error=repr(err))
            raise

    def flush(self, timeout: Optional[float] = None) -> None:
        """Block until every submitted batch has been applied to the state."""
        self._dispatcher.flush(timeout=timeout)

    def close(self, drain: bool = True, timeout: Optional[float] = None) -> None:
        """Flush (unless ``drain=False``) and stop the worker.  Idempotent.

        Releases this evaluator's auto-minted ``stream`` label series from
        the process-global instruments — each construction mints a fresh
        label, so without the release a construct-per-job process would
        grow dead histogram series forever.  ``stats()`` after ``close``
        therefore reports an empty ``latency`` section.  The release runs
        even when ``close`` raises (poisoned worker, join timeout): a
        replaced-after-crash evaluator must not leak its series."""
        try:
            self._dispatcher.close(drain=drain, timeout=timeout)
        finally:
            if self._admin is not None:
                self._admin.close()
            for inst in (
                _SUBMIT_HIST, _DISPATCH_HIST, _JOURNAL_GAUGE, _RESTORE_HIST, _DRAIN_HIST,
                _DURABILITY_GAUGE,
            ):
                inst.remove(self._stream)
            _DEPTH_GAUGE.remove(self._stream)
            # device-side series (the health latch's minted labels, the
            # state-HBM gauge, the program-profile records + gauges): latch
            # _closed and release UNDER the health lock, which the stats()-
            # side gauge writes also take — a concurrent stats() either
            # lands before the release (its series is removed below) or
            # observes _closed and writes nothing; without the shared lock
            # it could re-mint a series between the remove and the flag
            with self._health_lock:
                self._closed = True
                _STATE_HBM_GAUGE.remove(self._stream)
                _health.release_health(self._stream, self._health_alerted)
                _device.release_profiles(self._stream)
            # drift monitors: per-stream latch state + the
            # drift_score/drift_alerts label series under this stream
            from tpumetrics.monitoring.drift import release_stream

            release_stream(self._metric, self._stream)
            # the XLA attribution side of the same contract: compile-seconds
            # / recompile series and the retrace keys under this token
            from tpumetrics.telemetry.xla import release_attribution

            release_attribution(self._stream, tokens=(self._stream,))

    def __enter__(self) -> "StreamingEvaluator":
        return self

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> None:
        try:
            self.close(drain=exc_type is None)
        except Exception:
            if exc_type is None:
                raise

    # --------------------------------------------------------- graceful drain

    def request_drain(self) -> None:
        """Close intake NOW (``submit`` raises a typed
        :class:`~tpumetrics.runtime.drain.DrainingError`) without touching
        the queue — already-submitted batches keep applying.  The first half
        of the graceful-preemption contract; :meth:`drain` is the rest."""
        if not self._drain_requested:
            self._drain_requested = True
            _telemetry.record_event(None, "drain_requested", stream=self._stream)

    @property
    def draining(self) -> bool:
        return self._drain_requested

    def drain(self, final_cut: bool = True, timeout: Optional[float] = None) -> Any:
        """Graceful shutdown: stop intake, apply every queued batch, write
        one final snapshot cut (when ``final_cut`` and snapshots are
        configured — a COORDINATED cut in elastic mode, so a politely
        preempted world loses zero in-flight batches), close the worker,
        and return a :class:`~tpumetrics.runtime.drain.DrainReport` naming
        exactly the stream position the final state covers.  Idempotent AND
        serialized: concurrent callers (the preemption guard racing an
        application shutdown path) get ONE drain — a duplicate final cut
        would re-enter the elastic barrier after the peers already exited
        and burn the grace window on its timeout."""
        from tpumetrics.runtime.drain import DrainReport

        with self._drain_lock:
            if self._drain_report is not None:
                return self._drain_report
            self.request_drain()
            timed = _instruments.enabled()
            t0 = time.perf_counter()
            self.flush(timeout=timeout)
            cut_path: Optional[str] = None
            cut_step: Optional[int] = None
            cut_error: Optional[str] = None
            if final_cut and self._snapshots is not None:
                # degraded storage must not turn a polite preemption into a
                # hang or a lie: the final cut is attempted regardless of
                # the heal-probe schedule (last chance before exit), and a
                # surviving StorageError yields a PARTIAL report naming the
                # uncovered tail instead of an exception mid-grace-window
                try:
                    cut_path = self.snapshot()
                    cut_step = self._snapshots.last_step
                except _storage.StorageError as err:
                    cut_error = f"{type(err).__name__}: {err}"
            with self._lock:
                batches, items = self._batches, self._items
                durable_batches, durable_items = self._journal_base, self._durable_items
            drain_ms = (time.perf_counter() - t0) * 1e3
            if timed:
                _DRAIN_HIST.observe(drain_ms, self._stream)
            # the ledger event is the DURABLE latency record: close() below
            # releases this stream's histogram series per its own contract
            _telemetry.record_event(
                None, "drain_complete", stream=self._stream, batches=batches,
                items=items, cut_step=cut_step, drain_ms=round(drain_ms, 3),
                partial=cut_error is not None,
            )
            report = DrainReport(
                target=self._stream, batches=batches, items=items,
                cut_path=cut_path, cut_step=cut_step, drain_ms=drain_ms,
            )
            if cut_error is not None:
                report.partial = True
                report.reason = cut_error
                report.uncovered_batches = batches - durable_batches
                report.uncovered_items = items - durable_items
            self.close(drain=True, timeout=timeout)
            self._drain_report = report  # cached only once the close succeeded
            return report

    # ---------------------------------------------------------------- results

    def compute(self) -> Any:
        """Exact result over everything submitted so far (flushes first).

        On the eager path the metric's own sync (and the active
        :class:`~tpumetrics.resilience.policy.SyncPolicy`) applies: a
        swallowed sync failure serves a degraded value, reflected in
        ``stats()["degraded"]``.
        """
        from tpumetrics.monitoring.drift import stream_scope

        self.flush()
        # health first: a poisoned state must page (state_health event +
        # nonzero nonfinite series) BEFORE any value is computed or the
        # non-finite guard turns the corruption into an exception
        self._refresh_health(block=True)
        with self._lock, stream_scope(self._stream):
            # drift monitors alert at compute time under this stream's label
            # (gauge + drift_alert ledger event; stats()["monitoring"])
            if self._bucketer is None:
                value = self._metric.compute()
                self._degraded = bool(getattr(self._metric, "degraded", False))
                return value
            return self._metric.functional_compute(self._state)

    def latest_result(self) -> Optional[Dict[str, Any]]:
        """The bounded-staleness result maintained by ``compute_every=n``:
        ``{"value", "batches", "items", "degraded"}`` — at most ``n`` batches
        stale — or ``None`` before the first refresh.  ``degraded`` marks a
        value served from unsynced-local or last-good state after a swallowed
        sync failure.  Never blocks on the queue."""
        with self._lock:
            return dict(self._latest) if self._latest is not None else None

    def stats(self) -> Dict[str, Any]:
        """Dispatcher counters + stream position + compile accounting +
        resilience status (``degraded``, ``crashes``, ``restores``) +
        observability (``latency`` — submit/dispatch p50/p99 from the shared
        instrument histograms — and ``recompiles``, the attributed-retrace
        count for this stream).  Existing keys are a stable contract; the
        new sections only ever ADD keys.

        Never-blocking, now by construction: the state lock is taken with a
        bounded acquire — when a donating dispatch owns it (a backend may
        hold it for a whole device step while synchronizing a pending
        donated input), the last successful snapshot is served instead
        (``stale=True``), so a ``/statusz`` scrape never waits on the
        device."""
        out = self._dispatcher.stats()
        with _bounded_lock(self._lock) as got:
            if got:
                core = dict(
                    batches=self._batches,
                    items=self._items,
                    xla_compiles=self._trace_signatures.inserts,
                    signature_evictions=self._trace_signatures.evictions,
                    buckets=list(self._bucketer.edges) if self._bucketer else None,
                    mesh=(
                        {str(k): int(v) for k, v in self._mesh.shape.items()}
                        if self._mesh is not None
                        else None
                    ),
                    degraded=self._degraded,
                    crashes=self._crashes,
                    restores=self._restores,
                )
                with self._health_lock:
                    self._stats_cache = core
        if not got:
            with self._health_lock:
                core = dict(self._stats_cache) or dict(
                    batches=0, items=0, xla_compiles=0, signature_evictions=0,
                    buckets=None, mesh=None, degraded=False, crashes=0, restores=0,
                )
        out.update(core)
        out["stale"] = not got
        out["latency"] = _instruments.latency_section(self._stream)
        out["recompiles"] = recompile_count(self._stream)
        out["device"] = self._device_section()
        out["storage"] = self._storage_section()
        from tpumetrics.monitoring.drift import monitoring_stats

        monitoring = monitoring_stats(self._metric, self._stream)
        if monitoring:
            out["monitoring"] = monitoring
        return out

    # ---------------------------------------------------- storage observability

    def _storage_section(self) -> Dict[str, Any]:
        """The ``stats()["storage"]`` payload: the durability-degradation
        latch, the shim's per-seam retry counts, the fallback depth of the
        last elastic restore, and the quarantine census under the snapshot
        root.  Reads GIL-atomic scalars lock-free (the never-blocking
        ``stats()`` contract); the census is one bounded directory walk."""
        section: Dict[str, Any] = {
            "degraded": self._storage_degraded,
            "reason": self._storage_reason,
            "suspended_cuts": self._suspended_cuts,
            "heal_backoff_s": self._heal_backoff_s if self._storage_degraded else 0.0,
            "retries": _storage.retry_counts(),
            "fallback_depth": self._restore_fallback_depth,
        }
        t0 = self._storage_degraded_at
        if self._storage_degraded and t0 is not None:
            section["degraded_s"] = round(time.monotonic() - t0, 3)
        if self._snapshots is not None:
            root = getattr(self._snapshots, "root", None) or self._snapshots.directory
            section["quarantine"] = _storage.quarantine_census(root)
        return section

    # ----------------------------------------------------- device observability

    def _device_section(self) -> Dict[str, Any]:
        """The ``stats()["device"]`` payload: program-profile aggregate for
        this stream (registered/resolved counts + flops/bytes of already-
        resolved profiles — ``stats()`` never blocks on an XLA compile, so
        lazy resolution is left to explicit readers), the live-state HBM
        footprint + watermark, and the health summary (probed steps only —
        one ``device_get`` of a few int32 vectors, the fetch ``stats()``
        piggybacks the counters on)."""
        with self._health_lock:  # serializes the gauge writes with close()
            programs = _device.profile_summary(self._stream)
        return {
            "programs": programs,
            "hbm": self._hbm_section(),
            "health": self._refresh_health(),
        }

    def _hbm_section(self) -> Dict[str, Any]:
        with _bounded_lock(self._lock) as got:
            if got:
                if self._bucketer is not None:
                    leaves = jax.tree_util.tree_leaves(self._state)
                else:
                    leaves = _eager_state_leaves(self._metric)
                current = sum(int(getattr(l, "nbytes", 0) or 0) for l in leaves)
                if current > self._hbm_watermark:
                    self._hbm_watermark = current
                watermark = self._hbm_watermark
        from tpumetrics.backbones.registry import resident_bytes as _backbone_bytes

        with self._health_lock:
            if not got:
                # a donating dispatch owns the state: bounded-stale footprint
                return dict(self._hbm_cache)
            self._hbm_cache = {
                "state_bytes": current,
                "watermark_bytes": watermark,
                "backbone_bytes": _backbone_bytes(),
            }
            if not self._closed:  # close() released the series; don't re-mint
                _STATE_HBM_GAUGE.set(current, self._stream)
            return dict(self._hbm_cache)

    def _refresh_health(self, block: bool = False) -> Optional[Dict[str, Any]]:
        """Fetch + publish the latest on-device health counters (None when
        the probe is not armed).  First corruption per state latches ONE
        ``state_health`` ledger event and the per-(stream, state) non-finite
        series — this runs on the stats()/compute() read path, never per
        step.

        ``stats()`` is documented never-blocking, and a ``device_get`` of
        counters produced by an in-flight async dispatch would wait for the
        whole step program: with ``block=False`` a not-yet-ready probe
        output serves the LAST fetched summary instead (all-zero before the
        first fetch); ``compute()`` passes ``block=True`` — it synchronizes
        with the device anyway, and corruption must page before a value is
        served."""
        if self._step is None or not self._step.health_probe:
            return None
        if block:
            with self._lock:
                health = self._device_health
                paths = _health.state_paths(self._state) if health is not None else None
        else:
            with _bounded_lock(self._lock) as got:
                if got:
                    health = self._device_health
                    paths = _health.state_paths(self._state) if health is not None else None
            if not got:
                # the lock owner is mid-dispatch: the cached summary is the
                # never-blocking answer (all-zero before the first fetch)
                with self._health_lock:
                    cached = self._health_summary
                return cached if cached is not None else _health.summarize(None)
        if not block and health is not None:
            is_ready = getattr(health, "is_ready", None)
            if is_ready is not None and not is_ready():
                with self._health_lock:
                    cached = self._health_summary
                return cached if cached is not None else _health.summarize(None)
        summary = _health.summarize(health, paths)
        with self._health_lock:
            if not self._closed:  # post-close reads must not re-mint/re-page
                _health.publish_health(self._stream, summary, self._health_alerted)
            self._health_summary = summary
        return summary

    # -------------------------------------------------------------- snapshots

    def snapshot(self) -> str:
        """Flush, then atomically persist the state tagged with the stream
        position (step = batches drained).  The saved state covers exactly
        the submitted prefix of the stream — the crash-consistency anchor.

        While durability is degraded an explicit call still attempts the
        write (it doubles as a heal probe — an explicit request outranks the
        probe schedule): success resumes durability, failure re-raises the
        typed :class:`~tpumetrics.resilience.storage.StorageError`."""
        if self._snapshots is None:
            raise TPUMetricsUserError("StreamingEvaluator was built without snapshot_dir")
        self.flush()
        with self._lock:
            return self._durable_save_locked()

    def _durable_save_locked(self) -> str:
        """:meth:`_save_snapshot_locked` + the durability-degradation latch:
        a surviving :class:`~tpumetrics.resilience.storage.StorageError`
        (the shim's retry budget is already spent by the time it surfaces)
        enters/extends the degraded window before re-raising; a success
        heals it (the successful save IS the resume cut)."""
        try:
            path = self._save_snapshot_locked()
        except _storage.StorageError as err:
            self._note_storage_failure(err)
            raise
        self._note_storage_healed()
        return path

    def _autosave_locked(self) -> Optional[str]:
        """The auto-cadence (``snapshot_every``) save: while degraded, skip
        until the heal probe is due — serving continues from HBM and the
        skipped cut is counted (``stats()["storage"]["suspended_cuts"]``).
        A failure is fully latched by :meth:`_durable_save_locked`; it never
        propagates into the worker (a storage fault is not a crash — the
        state is intact and restore+replay would not fix the disk)."""
        if self._storage_degraded and time.monotonic() < self._next_heal_at:
            self._suspended_cuts += 1
            return None
        try:
            return self._durable_save_locked()
        except _storage.StorageError:
            return None

    def _note_storage_failure(self, err: BaseException) -> None:
        now = time.monotonic()
        self._storage_reason = f"{type(err).__name__}: {err}"
        if not self._storage_degraded:
            # entry: ONE durability_degraded event + gauge flip per window
            self._storage_degraded = True
            self._storage_degraded_at = now
            self._suspended_cuts = 0
            self._heal_backoff_s = _HEAL_BACKOFF_BASE_S
            if _instruments.enabled() and not self._closed:
                _DURABILITY_GAUGE.set(1.0, self._stream)
            _telemetry.record_event(
                None, "durability_degraded", stream=self._stream,
                error=self._storage_reason, seam=getattr(err, "seam", ""),
                batches=self._batches, durable_batches=self._journal_base,
            )
        else:
            # a failed heal probe: back off before the next one
            self._heal_backoff_s = min(self._heal_backoff_s * 2.0, _HEAL_BACKOFF_MAX_S)
        self._next_heal_at = now + self._heal_backoff_s

    def _note_storage_healed(self) -> None:
        if not self._storage_degraded:
            return
        t0 = self._storage_degraded_at
        degraded_s = time.monotonic() - t0 if t0 is not None else 0.0
        suspended, self._suspended_cuts = self._suspended_cuts, 0
        self._storage_degraded = False
        self._storage_reason = None
        self._storage_degraded_at = None
        self._heal_backoff_s = _HEAL_BACKOFF_BASE_S
        self._next_heal_at = 0.0
        if _instruments.enabled() and not self._closed:
            _DURABILITY_GAUGE.set(0.0, self._stream)
        _telemetry.record_event(
            None, "durability_resumed", stream=self._stream,
            suspended_cuts=suspended, degraded_s=round(degraded_s, 3),
            batches=self._batches,
        )

    def _barrier_proposal(self) -> int:
        """The logical step this rank proposes to the cut barrier: its
        stream position, floored to its own next free on-disk step.  After
        an elastic resize onto a reused snapshot root, a rank directory can
        hold steps from the OLD world that exceed the adopted global
        position (e.g. a quorum-degraded restore that lost a long rank);
        since the barrier agrees on the MAX proposal, flooring here keeps
        every rank's saves monotonic without any cross-rank special case."""
        last = self._snapshots.last_step
        return max(self._batches, (last + 1) if last is not None else 0)

    def _save_snapshot_locked(self) -> str:
        file_step = self._batches
        elastic_meta = None
        if self._elastic:
            # coordinated cut: agree on the logical step with every rank
            # (lockstep-style object exchange under the SyncPolicy deadline)
            # BEFORE writing, and stamp the snapshot as a cut member.  The
            # cut digest is deterministic in (step, world, config), so a
            # barrier re-run at the same position re-stamps identically.
            from tpumetrics.resilience.elastic import snapshot_barrier

            backend = self._barrier_backend
            if backend is None and self._world > 1:
                from tpumetrics.parallel.backend import get_default_backend

                backend = get_default_backend()
            file_step, digest = snapshot_barrier(
                backend,
                rank=self._rank,
                world_size=self._world,
                step=self._barrier_proposal(),
                config=self._elastic_config,
            )
            elastic_meta = self._snapshots.elastic_meta(
                file_step, digest, self._elastic_config
            )
        # the same-step reuse shortcut is NON-elastic only: an elastic save
        # must write its member of THIS cut (a step-equal file from a
        # previous world carries a different cut digest and would leave the
        # new cut permanently missing this rank); barrier proposals are
        # floored past last_step, so elastic saves never collide anyway
        if not self._elastic and self._snapshots.last_step == file_step:
            # a manual snapshot right after an auto-snapshot (or vice versa)
            # at the same stream position: the state is identical by the
            # determinism contract — reuse the file instead of failing the
            # monotonic-step check
            for step, path in _snapshot.list_snapshots(self._snapshots.directory):
                if step == file_step:
                    return path
        meta = {
            "batches": self._batches,
            "items": self._items,
            "metric": type(self._metric).__name__,
            "mode": "bucketed" if self._bucketer is not None else "eager",
            "degraded": self._degraded,  # survives preemption (restore re-flags)
            # global positions already covered before this world's ranks
            # started counting (set by restore_elastic; 0 on a fresh world) —
            # the next fold needs them to total positions without
            # re-counting the pre-resize prefix once per rank
            "base_batches": self._elastic_base_batches,
            "base_items": self._elastic_base_items,
        }
        if elastic_meta is not None:
            meta["elastic"] = elastic_meta
        if self._bucketer is not None:
            payload: Any = self._state
        else:
            payload = self._metric.snapshot_state()
        path = self._snapshots.save(
            file_step, payload, meta=meta, guard_non_finite=self._guard_non_finite
        )
        # the journal is "since the last snapshot": this save is the new base
        self._journal = []
        self._journal_base = self._batches
        self._durable_items = self._items
        if self._crash_policy == "restore":
            _JOURNAL_GAUGE.set(0, self._stream)  # cleared, not just appended
        return path

    def restore_latest(self) -> Optional[int]:
        """Restore the newest compatible snapshot; returns the stream
        position (batches) to replay from, or ``None`` when no snapshot
        exists.  Must run before any ``submit`` (a partially-fed evaluator
        cannot adopt older state without double counting)."""
        if self._snapshots is None:
            raise TPUMetricsUserError("StreamingEvaluator was built without snapshot_dir")
        with self._lock:
            if self._batches or self._dispatcher.stats()["enqueued"]:
                raise TPUMetricsUserError(
                    "restore_latest() after ingestion started would double-count; "
                    "restore on a fresh evaluator, then replay the stream from the "
                    "returned position."
                )
            got = self._load_latest_snapshot()
            if got is None:
                return None
            return self._adopt_snapshot_locked(got)

    def restore_elastic(
        self, quorum: Optional[Any] = None, cat_placement: str = "rank0"
    ) -> Optional[Dict[str, Any]]:
        """Adopt the newest consistent multi-host snapshot cut, folded into
        one canonical global state and re-sharded for THIS evaluator's
        ``(snapshot_rank, snapshot_world_size)`` — which may differ from the
        world that wrote the cut (shrink and grow both work).

        Requires the elastic constructor arguments and must run before any
        ``submit`` (like :meth:`restore_latest`).  Returns ``None`` when the
        shared root holds no elastic snapshots; otherwise a dict with the
        adopted global position (``batches``/``items`` — the stream prefix
        the folded state covers; replay the rest under the NEW sharding),
        the cut ``step``, ``from_world``, and ``degraded``.

        ``quorum`` (a :class:`~tpumetrics.resilience.elastic.QuorumPolicy`)
        admits INCOMPLETE cuts: the missing ranks' data is absent from the
        fold, ``degraded`` is flagged here and in :meth:`stats`, and an
        ``elastic_degraded`` ledger event names the missing ranks — an
        explicit trade of completeness for freshness, never a silent one.
        Without it, only complete cuts restore (older complete cuts win over
        a newer partial one); if nothing restorable exists a typed
        :class:`~tpumetrics.resilience.elastic.InconsistentCutError` raises.

        ``cat_placement`` (``"rank0"``/``"balanced"``) controls where
        restored cat/list/buffer rows land — see
        :func:`tpumetrics.parallel.merge.reshard_metric_states`.
        """
        if self._snapshots is None or not self._elastic:
            raise TPUMetricsUserError(
                "restore_elastic() needs snapshot_dir plus snapshot_rank/"
                "snapshot_world_size (the elastic constructor arguments)."
            )
        from tpumetrics.resilience.elastic import (
            ElasticRestoreError,
            InconsistentCutError,
            load_latest_cut,
        )

        t_restore = time.perf_counter()
        with self._lock:
            if self._batches or self._dispatcher.stats()["enqueued"]:
                raise TPUMetricsUserError(
                    "restore_elastic() after ingestion started would double-count; "
                    "restore on a fresh evaluator, then replay the stream (re-sharded "
                    "for the new world) from the returned position."
                )
            template = self._metric.init_state() if self._bucketer is not None else None
            cut = load_latest_cut(
                self._snapshots.root, template=template, quorum=quorum,
                backend=self._barrier_backend,
                mode="bucketed" if self._bucketer is not None else "eager",
            )
            if cut is None:
                return None
            if cut.config and self._elastic_config and cut.config != self._elastic_config:
                raise ElasticRestoreError(
                    f"The cut at step {cut.step} was written under config digest "
                    f"{cut.config[:12]}… but this evaluator's metric digests to "
                    f"{self._elastic_config[:12]}…: the metric configuration changed "
                    "across the resize, so the fold would be meaningless."
                )
            ranks = sorted(cut.payloads)
            # validate EVERYTHING that can reject the cut before any state is
            # touched: a typed failure below must leave the evaluator fresh,
            # not half-restored (the load_snapshot_state atomicity contract)
            metas = [cut.headers[r]["meta"] for r in ranks]
            bases_b = {int(m.get("base_batches", 0)) for m in metas}
            bases_i = {int(m.get("base_items", 0)) for m in metas}
            if len(bases_b) > 1 or len(bases_i) > 1:
                raise InconsistentCutError(
                    f"The cut at step {cut.step} mixes ranks restored from different "
                    f"elastic bases (batches {sorted(bases_b)}, items {sorted(bases_i)}): "
                    "the global position cannot be totaled."
                )
            base_b, base_i = bases_b.pop(), bases_i.pop()
            if self._bucketer is not None:
                # world-level fold/reshard first (rank shares of the stream),
                # then RE-PLACE the pytree on this evaluator's mesh — the
                # entire mesh-resize story for sharded states is this one
                # placement call; there is no sharded fold/reshard branch
                folded = self._metric.fold_state_dicts([cut.payloads[r] for r in ranks])
                self._state = self._place_state(
                    self._metric.reshard_state_dict(
                        folded, self._rank, self._world, cat_placement=cat_placement
                    )
                )
            else:
                folded = self._metric.fold_snapshot_states(
                    [_as_snapshot_payload(cut.payloads[r]) for r in ranks]
                )
                mine = self._metric.reshard_snapshot_state(
                    folded, self._rank, self._world, cat_placement=cat_placement
                )
                self._metric.load_snapshot_state(mine)
            total_batches = base_b + sum(int(m["batches"]) - base_b for m in metas)
            total_items = base_i + sum(int(m["items"]) - base_i for m in metas)
            degraded = bool(cut.degraded or any(m.get("degraded", False) for m in metas))
            self._batches = total_batches
            self._items = total_items
            self._last_compute_at = total_batches
            self._journal = []
            self._journal_base = total_batches
            self._durable_items = total_items
            self._degraded = degraded
            # how deep the CRC walk had to fall back past corrupt cuts to
            # find this one (0 = newest; the chaos soak gates <= keep_cuts)
            self._restore_fallback_depth = int(getattr(cut, "fallback_depth", 0))
            self._device_health = None  # counters describe the pre-restore pytree
            self._elastic_base_batches = total_batches
            self._elastic_base_items = total_items
            restore_ms = (time.perf_counter() - t_restore) * 1e3
            if _instruments.enabled():
                # the per-cycle number the chaos soak / bench series reads:
                # cut discovery + CRC loads + fold + reshard + placement
                _RESTORE_HIST.observe(restore_ms, self._stream)
            _telemetry.record_event(
                self._barrier_backend, "elastic_restore", step=cut.step,
                from_world=cut.world_size, world_size=self._world, rank=self._rank,
                batches=total_batches, degraded=degraded,
                missing=list(cut.missing), restore_ms=round(restore_ms, 3),
                fallback_depth=self._restore_fallback_depth,
            )
            return {
                "step": cut.step,
                "batches": total_batches,
                "items": total_items,
                "from_world": cut.world_size,
                "world_size": self._world,
                "rank": self._rank,
                "degraded": degraded,
                "missing_ranks": list(cut.missing),
                "restore_ms": restore_ms,
                "fallback_depth": self._restore_fallback_depth,
            }

    def _place_state(self, payload: Any) -> Any:
        """Adopted snapshot payloads carry host (numpy) leaves; the donated
        fused step must only ever receive XLA-OWNED device buffers, and in
        sharded mode the leaves must land under their partition rules.  Both
        are the same operation — place the pytree
        (:func:`tpumetrics.parallel.sharding.place_states`): on-device
        materialization without a mesh (a plain ``jnp.asarray`` can wrap
        host memory the device allocator does not own — donating such a
        buffer corrupted the heap on jaxlib 0.4.37), ``NamedSharding``
        placement with one.  Restoring a snapshot written under a DIFFERENT
        mesh shape needs nothing more: the pytree is mesh-shape-independent
        and this call is the entire re-placement."""
        return self._step.place(payload)

    def _load_latest_snapshot(self) -> Optional[Tuple[Any, Dict[str, Any]]]:
        """(payload, header) of the newest valid snapshot, or ``None``."""
        if self._snapshots is None:
            return None
        if self._bucketer is not None:
            # annotations name merge-kind (sketch) declaration parameters in
            # any SnapshotSpecError this raises (capacity/levels, not just
            # opaque flat shapes)
            return self._snapshots.restore_latest(
                self._metric.init_state(),
                annotations=_snapshot.state_annotations(self._metric),
            )
        return _snapshot.restore_latest_reconstruct(self._snapshots.directory)

    def _adopt_snapshot_locked(self, got: Optional[Tuple[Any, Dict[str, Any]]]) -> int:
        """Apply a loaded snapshot (or a fresh state when ``None``) to the
        evaluator under the held lock: state, stream counters, journal base,
        and the degraded flag from snapshot meta.  The single restore path —
        shared by :meth:`restore_latest` and the crash handler so the meta
        contract cannot drift between them.  Returns the adopted position."""
        if got is None:
            if self._bucketer is not None:
                self._state = self._step.init_state()
            else:
                self._metric.reset()
            restored, items, degraded = 0, 0, False
            self._elastic_base_batches = 0
            self._elastic_base_items = 0
        else:
            payload, header = got
            if self._bucketer is not None:
                self._state = self._place_state(payload)
            else:
                self._metric.load_snapshot_state(_as_snapshot_payload(payload))
            restored = int(header["meta"]["batches"])
            items = int(header["meta"]["items"])
            degraded = bool(header["meta"].get("degraded", False))
            self._elastic_base_batches = int(header["meta"].get("base_batches", 0))
            self._elastic_base_items = int(header["meta"].get("base_items", 0))
        self._batches = restored
        self._items = items
        self._last_compute_at = restored
        self._journal = []
        self._journal_base = restored
        self._durable_items = items
        self._degraded = degraded
        # the adopted state is a different pytree: stale health counters
        # describe buffers that no longer exist (the alert latch stays — a
        # past corruption event remains true of the stream's history)
        self._device_health = None
        if self._crash_policy == "restore":
            _JOURNAL_GAUGE.set(0, self._stream)
        return restored

    # ----------------------------------------------------------------- worker

    def _drain(self, batch: list) -> None:
        """Worker-side: apply each submitted batch individually, in order.
        Queue items are ``(args, root_span_or_None)`` pairs — the span rides
        next to the batch so the worker's child spans join its trace.  A
        crash completes the undrained tail's roots too (their batches are
        applied — if at all — via span-less replay or discarded by poison;
        an open root would orphan its recorded queue_wait child)."""
        for pos, (args, ctx) in enumerate(batch):
            self._inflight_pos = pos  # lets the crash handler find the tail
            try:
                self._apply_one(args, ctx)
            except BaseException as err:
                for _t_args, t_ctx in batch[pos + 1 :]:
                    _spans.end_span(t_ctx, error=f"drain interrupted: {err!r}")
                raise

    def _apply_one(self, args: Tuple[Any, ...], ctx: Any = None) -> None:
        """Apply ONE submitted batch: journal (under a restore policy), state
        transition, counters, and the compute/snapshot cadences.  ``ctx`` is
        the batch's root span (``None`` on crash replays and with tracing
        off): the worker adopts it so plan/dispatch/write-back children nest
        under the submit-side trace, and ends it when the batch — cadences
        included — is fully applied."""
        if self._crash_policy == "restore":
            # journaled BEFORE applying so a crashed batch is replayable
            self._journal.append(args)
            _JOURNAL_GAUGE.set(len(self._journal), self._stream)
        try:
            # outer attribution (signature None): eager helper ops (padding,
            # casts) outside the per-chunk program contexts still charge
            # their compiles to this stream
            with attribute_compiles(self._stream, None, token=self._stream), _spans.activate(ctx):
                if self._bucketer is None:
                    with _spans.span("dispatch", mode="eager"):
                        self._metric.update(*args, **self._update_kwargs)
                    n_rows = leading_rows(args)
                else:
                    n_rows = self._bucketed_update(args)
                with self._lock:
                    self._batches += 1
                    self._items += n_rows
                    batches = self._batches
                if self._compute_every and batches - self._last_compute_at >= self._compute_every:
                    self._refresh_latest()
                if (
                    self._snapshot_every
                    and self._snapshots is not None
                    and batches % self._snapshot_every == 0
                ):
                    with self._lock:
                        self._autosave_locked()
        except BaseException as err:
            # end the root NOW so the poisoned batch's trace is complete
            # (and in the flight ring) before crash handling dumps/raises
            _spans.end_span(ctx, error=repr(err))
            raise
        _spans.end_span(ctx, batches=batches)

    # ------------------------------------------------------------ self-healing

    def _handle_crash(self, err: BaseException, batch: list) -> bool:
        """Dispatcher crash hook (worker thread): restore + replay, bounded.

        ``pending`` is everything the restored state is missing: the journal
        (applied-since-snapshot batches, crashed one included — it was
        journaled before applying) plus the not-yet-reached tail of the
        dispatcher micro-batch.  A replay that crashes again rebuilds
        ``pending`` from the fresh journal and keeps trying until the budget
        is spent, then raises :class:`CrashLoopError` (which poisons the
        dispatcher — the handler's exception becomes the poison cause).

        The budget bounds CONSECUTIVE crashes at the SAME stream position (a
        deterministically-poisonous batch re-crashing every replay); any
        forward progress — a later batch crashing, or a successful recovery —
        resets it, so independent transient crashes never accumulate into a
        spurious exhaustion.  ``stats()`` still reports lifetime totals.
        """
        # dispatcher items are (args, root_span) pairs; the journal holds raw
        # args — replays run span-less (their traces ended at the crash)
        pending = list(self._journal) + [item[0] for item in batch[self._inflight_pos + 1 :]]
        attempts = 0  # consecutive same-position crashes (lifetime: _crashes)
        last_pos = -1
        while True:
            with self._lock:
                pos = self._batches  # stream position of the item that crashed
                self._crashes += 1
                crashes = self._crashes
            attempts = attempts + 1 if pos <= last_pos else 1
            last_pos = max(last_pos, pos)
            _telemetry.record_event(
                self, "runtime_crash", error=repr(err), crashes=crashes, attempt=attempts
            )
            if attempts > self._max_restores:
                flight = _export.flight_dump("crash_loop", err, stream=self._stream)
                note = f" Flight record: {flight}" if flight else ""
                loop_err = CrashLoopError(
                    f"StreamingEvaluator worker crashed {attempts} consecutive time(s) "
                    f"without progress; crash-loop budget (max_restores="
                    f"{self._max_restores}) is spent. Last crash: "
                    f"{type(err).__name__}: {err}.{note}"
                )
                if flight:
                    # the dispatcher's poison path reuses this dump instead
                    # of writing a second one for the same incident
                    loop_err._tpumetrics_flight_path = flight
                raise loop_err from err
            idx = -1  # nothing replayed yet (restore itself may fail)
            try:
                # span-less: the replayed batches' traces ended at the crash;
                # child spans fired here would root fresh fragment traces
                with _spans.suppress():
                    self._restore_for_crash()
                    idx = 0
                    while idx < len(pending):
                        self._apply_one(pending[idx])
                        idx += 1
            except TPUMetricsUserError:
                raise  # config/snapshot-level problems are not crash-loopable
            except BaseException as replay_err:  # noqa: BLE001 — bounded above
                err = replay_err
                if idx >= 0:
                    # journal now holds the replayed prefix (+ crashed item)
                    # since the last snapshot; the rest is still untried.
                    # (idx < 0 = restore itself failed: the journal was not
                    # cleared and pending already covers it — keep as is.)
                    pending = list(self._journal) + pending[idx + 1 :]
                continue
            with self._lock:
                self._restores += 1
                restores = self._restores
            _telemetry.record_event(
                self, "runtime_restore", restores=restores, replayed=len(pending)
            )
            return True

    def _restore_for_crash(self) -> None:
        """Rewind state + counters to the latest good snapshot (or a fresh
        state when snapshots are absent/never taken), clearing the journal.
        The restored position must equal the journal's base — if the latest
        snapshot was lost/corrupt and an older one is picked, the journal
        cannot bridge the gap and the crash is not recoverable."""
        got = self._load_latest_snapshot()
        with self._lock:
            expected = self._journal_base  # the position the journal covers from
            restored = self._adopt_snapshot_locked(got)
            if restored != expected:
                raise _snapshot.SnapshotError(
                    f"Crash restore landed on stream position {restored} but the replay "
                    f"journal starts at {expected} (latest snapshot lost or "
                    "corrupt?): the journal cannot bridge the gap."
                )

    def _bucketed_update(self, args: Tuple[Any, ...]) -> int:
        # the plan (chunking, padding, jit-cache-mirroring signatures) is
        # shared with the multi-tenant service; signatures feed the
        # LRU-bounded registry whose insert count == XLA compile count, per
        # (bucket, signature) for the WHOLE collection, never per member.
        # The device tenant scope names this stream as the owner of any
        # program profile the dispatches below register (no-op singleton
        # with profiling off).
        with _spans.span("plan"):
            n, chunks = plan_bucketed_update(self._bucketer, args)
        with _device.tenant_scope(self._stream):
            return self._run_chunks(chunks, n)

    def _run_chunks(self, chunks: Any, n: int) -> int:
        for chunk in chunks:
            if chunk[0] == "scalar":
                # scalar-only submit (e.g. an aggregation metric fed floats):
                # nothing to pad, so bucketing — and in particular the
                # fallback's pad correction — must NOT apply; run the fused
                # whole-collection step (donated state) over the raw args
                _, cargs, sig = chunk
                new_sig = self._trace_signatures.observe(sig)
                with attribute_compiles(self._stream, sig, token=self._stream):
                    self._apply_step(new_sig, lambda s, a=cargs: self._step.update(s, *a))
                continue
            _, padded, bucket, size, sig = chunk
            new_sig = self._trace_signatures.observe(sig)
            n_valid = jnp.asarray(size, jnp.int32)
            with attribute_compiles(self._stream, sig, token=self._stream):
                self._apply_step(
                    new_sig,
                    lambda s, p=padded, b=bucket, nv=n_valid: self._step.masked_update(s, p, nv, b),
                )
        return n

    def _apply_step(self, new_sig: bool, run: Callable[[Any], Any]) -> None:
        """Run one fused step over the current state and publish the result.

        A donating dispatch DELETES the input buffers, so it must hold the
        lock — a concurrently locked ``snapshot()``/``compute()`` must never
        observe a state mid-donation.  But jit compiles at first dispatch,
        and holding the lock through XLA would stall ``latest_result()``/
        ``stats()`` (documented never-blocking) for the whole compile: a
        cold trace signature is therefore pre-compiled OUTSIDE the lock on
        a throwaway on-device copy of the state, making the locked dispatch
        a cached one.  (The worker is the only thread that rebinds or
        donates ``_state`` while streaming, so the unlocked copy is safe.)
        Non-donating steps delete nothing and stay outside the lock
        entirely, as before donation existed."""
        timed = _instruments.enabled()
        probed = self._step.health_probe
        if not self._step.donate:
            t0 = time.perf_counter() if timed else 0.0
            with _spans.span("dispatch", cold=new_sig):
                new_state = run(self._state)
            if timed:
                _DISPATCH_HIST.observe((time.perf_counter() - t0) * 1e3, self._stream)
            with self._lock:
                with _spans.span("write_back"):
                    if probed:
                        # probed programs return (state, on-device health)
                        self._state, self._device_health = new_state
                    else:
                        self._state = new_state
            return
        if new_sig:
            with _spans.span("compile"):
                run(jax.tree_util.tree_map(lambda leaf: leaf.copy(), self._state))
        with self._lock:
            t0 = time.perf_counter() if timed else 0.0
            with _spans.span("dispatch", cold=new_sig):
                new_state = run(self._state)
            if timed:
                _DISPATCH_HIST.observe((time.perf_counter() - t0) * 1e3, self._stream)
            with _spans.span("write_back"):
                if probed:
                    self._state, self._device_health = new_state
                else:
                    self._state = new_state

    def _refresh_latest(self) -> None:
        from tpumetrics.monitoring.drift import stream_scope

        with self._lock:
            state = self._state
            batches, items = self._batches, self._items
        if self._bucketer is None:
            with stream_scope(self._stream):
                value = self._metric.compute()
            self._metric._computed = None  # the stream moves on; don't pin the cache
            degraded = bool(getattr(self._metric, "degraded", False))
        else:
            with stream_scope(self._stream):
                value = self._metric.functional_compute(state)
            with self._lock:
                degraded = self._degraded  # bucketed updates never sync eagerly
        with self._lock:
            if self._bucketer is None:
                self._degraded = degraded
            self._latest = {
                "value": value, "batches": batches, "items": items, "degraded": degraded,
            }
            self._last_compute_at = batches


def _eager_state_leaves(metric: Any) -> list:
    """Array leaves of an eager-path metric's LIVE attribute state —
    ``metric_state()`` per metric (a collection contributes every member's).
    Shared by the evaluator's and the service's HBM accounting."""
    from tpumetrics.collections import MetricCollection

    if isinstance(metric, MetricCollection):
        return jax.tree_util.tree_leaves(
            {name: m.metric_state() for name, m in metric._modules.items()}
        )
    return jax.tree_util.tree_leaves(metric.metric_state())


def _as_snapshot_payload(payload: Any) -> Dict[str, Any]:
    """Normalize a reconstructed eager snapshot payload: numpy scalar leaves
    back to ints where the hooks expect them."""
    out = dict(payload)
    if "update_count" in out:
        out["update_count"] = int(out["update_count"])
    return out
