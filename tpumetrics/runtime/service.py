"""EvaluationService — thousands of tenant streams, one dispatcher.

The single-stream :class:`~tpumetrics.runtime.evaluator.StreamingEvaluator`
owns a whole worker thread and a private compile universe.  Production
traffic is many models / tenants / experiments evaluated *concurrently* on
shared hardware, where the wins come from sharing — the device, the
compile cache, and the batch:

- **One dispatcher.**  N tenants multiplex onto ONE
  :class:`~tpumetrics.runtime.dispatch.AsyncDispatcher` / worker thread /
  device owner.  Each tenant registers a Metric / MetricCollection and gets
  a lightweight :class:`TenantHandle` with the familiar
  ``submit/flush/compute/snapshot/stats`` surface backed by a per-tenant
  **bounded queue** (own backpressure policy: ``block`` / ``drop_oldest`` /
  ``error``) — one hot tenant fills its own queue, never the neighbors'.

- **Cross-tenant fairness.**  The worker drains a deficit-round-robin
  schedule (:class:`~tpumetrics.runtime.scheduler.DeficitRoundRobin`) over
  the tenant queues; each tenant's ``quota`` is its DRR quantum in batch
  rows per round.  DRR is starvation-free: a backlogged tenant is served
  every round no matter how hot its neighbors run.

- **Global trace-signature dedupe.**  Tenants whose metric configuration
  digests identically (same
  :func:`~tpumetrics.resilience.elastic.config_digest`, update kwargs, and
  donation mode) SHARE one
  :class:`~tpumetrics.parallel.fuse_update.FusedCollectionStep` — and with
  it one jit program cache: K tenants running the same model eval compile
  once, not K times (and once per *process set* with the PR 6 persistent
  compile cache).  The per-evaluator trace-signature set becomes one
  service-wide LRU :class:`~tpumetrics.runtime.scheduler.SignatureRegistry`
  keyed by (step identity, bucket, signature).

- **Megabatch fast path.**  Same-step, same-bucket, same-signature head
  batches from *different* tenants are driven through ONE vmapped device
  program per drain decision
  (:meth:`~tpumetrics.parallel.fuse_update.FusedCollectionStep.
  megabatch_update`): the per-tenant states ride a leading tenant axis
  inside the trace and come back unstacked, so K small dispatches become
  one.  Groups pad to power-of-two sizes with fresh-state dummies to bound
  the K-specialization universe.  Eligibility: bucketed tenant, no mesh,
  megabatch enabled, and the head batch fits one bucket chunk — everything
  else takes the same single-tenant path the evaluator runs.

- **Per-tenant failure domains.**  A batch that crashes the worker is
  handled inside the tenant that submitted it: ``crash_policy="restore"``
  replays the tenant's journal from its latest snapshot (bounded by
  ``max_restores``), and exhaustion — or ``crash_policy="raise"`` — puts
  THAT tenant into **quarantine** (its queue dropped, its handle raising
  :class:`TenantQuarantinedError`) while every other tenant keeps
  computing, bit-identically.  The dispatcher itself is never poisoned by
  tenant work.

- **Per-tenant telemetry.**  Every ledger event the service emits runs
  under an attribution tag naming the tenant, the dispatcher splits its
  drop/drain counters per tag, and snapshots live in per-tenant
  directories (per-tenant ``snapshot_dir``; restores validate the spec per
  tenant and never cross-contaminate).

See ``docs/service.md`` for the tenancy model and megabatch eligibility
rules; ``bench.py``'s ``multitenant_scaling`` scenario gates the
16-tenants-through-one-service throughput ratio and the 1000-stream soak's
p99 submit latency.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from tpumetrics.metric import Metric
from tpumetrics.parallel.fuse_update import FusedCollectionStep
from tpumetrics.runtime.bucketing import (
    ShapeBucketer,
    check_bucketable,
    leading_rows,
    pad_args_to,
    plan_bucketed_update,
    pow2_bucket_edges,
    single_chunk_signature,
)
from tpumetrics.runtime.compile_cache import (
    ENV_CACHE_DIR,
    attribute_compiles,
    enable_persistent_compilation_cache,
    recompile_count,
)
from tpumetrics.runtime.dispatch import _DEPTH_GAUGE, AsyncDispatcher
from tpumetrics.runtime.evaluator import CrashLoopError, _bounded_lock
from tpumetrics.runtime.scheduler import DeficitRoundRobin, SignatureRegistry
from tpumetrics.runtime import snapshot as _snapshot
from tpumetrics.telemetry import device as _device
from tpumetrics.telemetry import export as _export
from tpumetrics.telemetry import health as _health
from tpumetrics.telemetry import instruments as _instruments
from tpumetrics.telemetry import ledger as _telemetry
from tpumetrics.telemetry import spans as _spans
from tpumetrics.utils.exceptions import TPUMetricsUserError

_POLICIES = ("block", "drop_oldest", "error")

# shared-with-the-evaluator instrument families (get-or-create): the service
# labels them by tenant id — 1000-stream-scale cardinality is a documented
# budget (docs/observability.md), ~20 numbers per series
_SUBMIT_HIST = _instruments.histogram(
    _instruments.SUBMIT_LATENCY_MS, help="submit() call latency", labels=("stream",),
    sketch=True,
)
_DISPATCH_HIST = _instruments.histogram(
    _instruments.DISPATCH_LATENCY_MS, help="device dispatch latency", labels=("stream",),
    sketch=True,
)
_TENANTS_GAUGE = _instruments.gauge(
    _instruments.TENANTS_LIVE, help="registered, non-quarantined tenants", labels=("service",)
)
_STATE_HBM_GAUGE = _instruments.gauge(
    _instruments.STATE_HBM_BYTES,
    help="live metric-state buffer bytes held on device for the stream",
    labels=("stream",),
)
#: gauges are last-write-wins per label: two default-named services must not
#: share one series, so each instance mints a unique instrument label
_SERVICE_IDS = itertools.count(1)


def _state_alive(state: Any) -> bool:
    """Whether every array leaf of a state pytree is still usable — a
    donating dispatch that failed mid-execution leaves deleted buffers."""
    for leaf in jax.tree_util.tree_leaves(state):
        deleted = getattr(leaf, "is_deleted", None)
        if deleted is not None and deleted():
            return False
    return True


class TenantQuarantinedError(TPUMetricsUserError):
    """The tenant's stream is fenced off after a crash (or a spent
    crash-loop budget); the underlying failure is ``__cause__``.  Other
    tenants are unaffected — quarantine is the service's unit of blast
    radius."""


class _Tenant:
    """Internal per-tenant record; every field is guarded by the service
    lock except ``journal``/``journal_base``/``crash bookkeeping``, which
    only the worker thread touches (the evaluator's convention)."""

    def __init__(
        self,
        tid: str,
        metric: Any,
        bucketer: Optional[ShapeBucketer],
        step: Optional[FusedCollectionStep],
        step_token: Any,
        state: Optional[Dict[str, Any]],
        *,
        max_queue: int,
        policy: str,
        quota: float,
        update_kwargs: Dict[str, Any],
        compute_every: Optional[int],
        snapshots: Optional[_snapshot.SnapshotManager],
        snapshot_every: Optional[int],
        crash_policy: str,
        max_restores: int,
        guard_non_finite: str,
        megabatch: bool,
    ) -> None:
        self.tid = tid
        self.metric = metric
        self.bucketer = bucketer
        self.step = step
        self.step_token = step_token  # signature-registry namespace + share key
        self.state = state
        self.max_queue = int(max_queue)
        self.policy = policy
        self.quota = float(quota)
        self.update_kwargs = update_kwargs
        self.compute_every = compute_every
        self.snapshots = snapshots
        self.snapshot_every = snapshot_every
        self.crash_policy = crash_policy
        self.max_restores = int(max_restores)
        self.guard_non_finite = guard_non_finite
        self.megabatch = bool(megabatch)

        # queue entries: (args, n_rows, single_chunk_sig_or_None) — the row
        # count and megabatch probe are computed at submit time (caller
        # thread) so the worker's locked scheduling pass stays O(heads)
        self.queue: deque = deque()
        self.pending = 0  # queued + in-flight batches (flush waits on 0)
        self.error: Optional[BaseException] = None  # quarantine cause

        self.batches = 0
        self.items = 0
        self.enqueued = 0
        self.dropped = 0
        self.megabatched = 0  # batches applied via the megabatch path
        self.latest: Optional[Dict[str, Any]] = None
        self.last_compute_at = 0
        self.degraded = False

        self.journal: list = []
        self.journal_base = 0
        self.crashes = 0
        self.restores = 0
        self.flight_path: Optional[str] = None  # quarantine's flight dump

        # tenant lifecycle (lifecycle/manager.py): residency state machine
        # + the last-dispatch recency hibernation and LRU eviction key off.
        # Both guarded by the service lock (== the manager's residency
        # lock); without a lifecycle manager the tenant stays "resident"
        # forever and only the timestamp is maintained.
        self.residency = "resident"
        self.last_dispatch = time.monotonic()
        # a failed revival latches its error here for the waiters blocked on
        # that attempt (typed TenantRevivalError); the next attempt clears it
        self.revival_error: Optional[BaseException] = None

        # live migration (fleet/migrate.py): ``migrating`` opens the
        # final-cut window — intake gated by the tenant's own backpressure
        # policy; ``migrated_to`` is stamped at commit as
        # ``(target_rank, routing_epoch)`` so woken waiters learn the new
        # owner.  Both guarded by the service lock.
        self.migrating = False
        self.migrated_to: Optional[Tuple[Any, Any]] = None

        # device-side observability (health probe + HBM watermark); the
        # alerted set doubles as the minted health-label ledger close()
        # releases, guarded by health_lock (one state_health per corruption)
        self.device_health: Optional[Any] = None
        self.health_summary: Optional[Dict[str, Any]] = None  # last fetched
        self.health_alerted: set = set()
        self.health_lock = threading.Lock()
        self.hbm_watermark = 0
        self.released = False  # stats() after close must not re-mint series
        # bounded-staleness snapshots served when a donating dispatch owns
        # the service lock (the never-blocking stats() contract; guarded by
        # health_lock, which is never held across a dispatch)
        self.stats_cache: Dict[str, Any] = {}
        self.hbm_cache: Dict[str, int] = {"state_bytes": 0, "watermark_bytes": 0}


class TenantHandle:
    """A tenant's view of the service: the familiar single-stream surface
    (``submit``/``flush``/``compute``/``snapshot``/``restore_latest``/
    ``latest_result``/``stats``) routed through the shared dispatcher.
    Lightweight — holding a thousand of these costs a thousand queue
    objects, not a thousand worker threads."""

    def __init__(self, service: "EvaluationService", tid: str) -> None:
        self._service = service
        self._tid = tid

    @property
    def tenant_id(self) -> str:
        return self._tid

    def submit(self, *args: Any) -> None:
        self._service.submit(self._tid, *args)

    def flush(self, timeout: Optional[float] = None) -> None:
        self._service.flush(self._tid, timeout=timeout)

    def compute(self) -> Any:
        return self._service.compute(self._tid)

    def latest_result(self) -> Optional[Dict[str, Any]]:
        return self._service.latest_result(self._tid)

    def snapshot(self) -> str:
        return self._service.snapshot(self._tid)

    def restore_latest(self) -> Optional[int]:
        return self._service.restore_latest(self._tid)

    def stats(self) -> Dict[str, Any]:
        return self._service.tenant_stats(self._tid)

    @property
    def quarantined(self) -> bool:
        return self._service.tenant_error(self._tid) is not None

    @property
    def quarantine_cause(self) -> Optional[BaseException]:
        return self._service.tenant_error(self._tid)


class EvaluationService:
    """Multi-tenant streaming evaluation: N metric streams, one dispatcher.

    Args:
        max_tokens: capacity of the shared dispatcher's wake-token queue.
            Tokens are tiny (one per submitted batch); real backpressure is
            per-tenant, so this only bounds total queued batches across all
            tenants.
        signature_cache_size: LRU capacity of the service-wide trace-
            signature registry (``None`` = unbounded) — the global analog
            of the evaluator's ``signature_cache_size``.
        megabatch_max_group: cap on tenants stacked into one megabatch
            program (default 16).  Bounds both the vmapped program's
            parameter count (a thousand-tenant group would compile a
            thousand-input XLA program) and — with power-of-two group
            padding — the K-specialization universe to
            ``log2(megabatch_max_group)`` programs per bucket.
        compile_cache_dir: enable JAX's persistent compilation cache
            (:func:`~tpumetrics.runtime.compile_cache.
            enable_persistent_compilation_cache`) so the deduped compiles
            also amortize across processes/restarts.
        name: dispatcher thread / telemetry name.
        admin_port: start the embedded admin server
            (:mod:`tpumetrics.telemetry.serve`) on this port (``0`` = an
            ephemeral port, read back from ``service.admin.port``) — the
            live ``/metrics`` / ``/healthz`` / ``/statusz`` plane over
            every tenant, stopped by ``close()``.
        lifecycle: a :class:`~tpumetrics.lifecycle.policy.LifecyclePolicy`
            enabling the tenant lifecycle manager: cold tenants hibernate
            to a per-service spill store (releasing device buffers,
            instrument series, and last-holder backbone references) and
            revive bit-identically on their next submit.  See
            ``docs/lifecycle.md``.
        hbm_budget_bytes: shorthand for a lifecycle policy with a budget —
            proactive LRU eviction keeps resident tenant-state + backbone
            bytes under this ceiling no matter how many tenants register.
            Combines with ``lifecycle=`` (the explicit budget wins).
        spill_dir: spill-store root for hibernation cuts (enables the
            lifecycle manager); default is a private temporary directory
            removed by ``close()``.

    Register tenants with :meth:`register`; each returns a
    :class:`TenantHandle`.  The module docstring describes the sharing
    layers (step dedupe, megabatch) and the isolation contract.
    """

    def __init__(
        self,
        *,
        max_tokens: int = 65536,
        signature_cache_size: Optional[int] = 8192,
        megabatch_max_group: int = 16,
        compile_cache_dir: Optional[str] = None,
        name: str = "EvaluationService",
        admin_port: Optional[int] = None,
        lifecycle: Optional[Any] = None,
        hbm_budget_bytes: Optional[int] = None,
        spill_dir: Optional[str] = None,
    ) -> None:
        if int(megabatch_max_group) < 2:
            raise ValueError(
                f"megabatch_max_group must be >= 2, got {megabatch_max_group}"
            )
        if compile_cache_dir is not None or os.environ.get(ENV_CACHE_DIR):
            enable_persistent_compilation_cache(compile_cache_dir)
        self._lock = threading.Lock()
        self._space = threading.Condition(self._lock)  # per-tenant queue space
        self._done = threading.Condition(self._lock)  # per-tenant pending -> 0
        self._tenants: Dict[str, _Tenant] = {}
        self._drr = DeficitRoundRobin()
        self._signatures = SignatureRegistry(signature_cache_size)
        # step dedupe: share key -> FusedCollectionStep (single-device,
        # hashable-kwargs steps only; mesh'd / unhashable-kwargs tenants get
        # private steps)
        self._steps: Dict[Any, FusedCollectionStep] = {}
        # megabatch readiness: share key -> tenant ids with queued work
        self._ready: Dict[Any, set] = {}
        self._megabatch_max = int(megabatch_max_group)
        self._megabatch_steps = 0
        self._megabatch_tenants = 0
        self._mega_group_meta = (0, 0, 0)  # worker-thread-only scratch
        self._quarantines = 0
        # migration tombstones: tenant id -> (target_rank, routing_epoch).
        # A submit/compute against a migrated-away id gets a typed
        # TenantMigratingError naming the new owner instead of a bare
        # KeyError; re-registration clears the tombstone.
        self._migrated: Dict[str, Tuple[Any, Any]] = {}
        self._draining = False  # graceful drain: intake refused service-wide
        self._drain_report: Optional[Any] = None
        self._drain_lock = threading.Lock()  # serializes concurrent drain()s
        self._name = name
        self._stats_cache: Dict[str, Any] = {}  # never-blocking stats() fallback
        self._tenant_ids_cache: List[str] = []  # never-blocking census fallback
        self._label = f"{name}#{next(_SERVICE_IDS)}"
        # tenant lifecycle: any of the three knobs arms the manager (the
        # import is lazy — services without lifecycle pay nothing)
        self._lifecycle = None
        if lifecycle is not None or hbm_budget_bytes is not None or spill_dir is not None:
            import dataclasses

            from tpumetrics.lifecycle import LifecycleManager, LifecyclePolicy

            policy = lifecycle if lifecycle is not None else LifecyclePolicy()
            if not isinstance(policy, LifecyclePolicy):
                raise TypeError(
                    f"lifecycle must be a LifecyclePolicy, got {type(policy)}"
                )
            if hbm_budget_bytes is not None:
                policy = dataclasses.replace(
                    policy, hbm_budget_bytes=int(hbm_budget_bytes)
                )
            self._lifecycle = LifecycleManager(self, policy, spill_dir=spill_dir)
        self._dispatcher = AsyncDispatcher(
            self._drain, max_queue=max_tokens, policy="block", name=name,
            instrument_label=self._label,
        )
        # the embedded admin plane (telemetry/serve.py): /metrics, /healthz
        # (per-tenant degraded/quarantine/state-health), /statusz (per-tenant
        # stats incl. device section, DRR shares, signature-cache occupancy),
        # /spanz, /flightz.  Owned here, stopped by close().
        self._admin = None
        if admin_port is not None:
            from tpumetrics.telemetry.serve import start_admin_server

            self._admin = start_admin_server(
                int(admin_port), targets={self._label: self}, name=self._label
            )

    @property
    def admin(self):
        """The embedded :class:`~tpumetrics.telemetry.serve.AdminServer`
        (``admin_port=``), or ``None``."""
        return self._admin

    def tenant_ids(self) -> List[str]:
        """Registered tenant ids (quarantined included — their stats still
        report, which is exactly what ``/healthz`` needs to see).  Bounded
        like every stats-path reader: when a donating dispatch owns the
        lock, the last census is served (registration is rare; the census
        is as fresh as the last unowned read)."""
        with _bounded_lock(self._lock) as locked:
            if locked:
                ids = sorted(self._tenants)
                self._tenant_ids_cache = ids
                return ids
        return list(self._tenant_ids_cache)

    # ------------------------------------------------------------ registration

    def register(
        self,
        tenant_id: str,
        metric: Any,
        *,
        buckets: Union[None, int, Sequence[int]] = None,
        update_kwargs: Optional[Dict[str, Any]] = None,
        quota: float = 64.0,
        max_queue: int = 256,
        backpressure: str = "block",
        compute_every: Optional[int] = None,
        snapshot_dir: Optional[str] = None,
        snapshot_every: Optional[int] = None,
        keep_snapshots: Optional[int] = 3,
        crash_policy: str = "raise",
        max_restores: int = 3,
        guard_non_finite: str = "off",
        donate_state: bool = True,
        megabatch: bool = True,
        mesh: Optional[Any] = None,
        partition_rules: Optional[Any] = None,
        data_axis: Optional[str] = None,
        health_probe: bool = False,
        _start_hibernated: bool = False,
    ) -> TenantHandle:
        """Register one tenant stream; returns its :class:`TenantHandle`.

        The per-tenant arguments mirror :class:`StreamingEvaluator`:
        ``buckets`` (``None`` = the eager update path — no sharing, no
        megabatch), ``backpressure``/``max_queue`` (this tenant's bounded
        queue), ``snapshot_dir`` (this tenant's private snapshot root),
        ``crash_policy``/``max_restores`` (quarantine is the budget-spent
        outcome), ``mesh``/``partition_rules``/``data_axis`` (sharded
        execution — a private step, megabatch-excluded).  ``quota`` is the
        DRR quantum in batch rows per scheduling round — a tenant with
        twice the quota gets twice the share of a contended worker.
        ``megabatch=False`` opts this tenant out of cross-tenant stacking
        (it still shares the step's compile cache).  ``health_probe=True``
        (requires ``buckets``) arms the in-trace state health probe — the
        tenant's step programs also emit on-device NaN/inf/saturation
        counters, surfaced via ``stats()["device"]`` and latched into one
        ``state_health`` ledger event per corrupted state BEFORE compute;
        probed tenants are excluded from megabatch grouping and share steps
        only with other probed tenants (the probe is part of the program
        shape)."""
        from tpumetrics.collections import MetricCollection

        if not isinstance(metric, (Metric, MetricCollection)):
            raise TypeError(f"Expected Metric or MetricCollection, got {type(metric)}")
        if backpressure not in _POLICIES:
            raise ValueError(
                f"Unknown backpressure policy {backpressure!r}; expected one of {_POLICIES}"
            )
        if int(max_queue) <= 0:
            raise ValueError(f"max_queue must be positive, got {max_queue}")
        if crash_policy not in ("raise", "restore"):
            raise ValueError(f"crash_policy must be 'raise' or 'restore', got {crash_policy!r}")
        if guard_non_finite not in ("off", "warn", "error"):
            raise ValueError(
                f"guard_non_finite must be 'off', 'warn' or 'error', got {guard_non_finite!r}"
            )
        if not quota > 0:
            raise ValueError(f"quota must be positive, got {quota}")
        if snapshot_every is not None and snapshot_dir is None:
            raise ValueError("snapshot_every requires snapshot_dir")
        kwargs = dict(update_kwargs or {})
        if _start_hibernated and self._lifecycle is None:
            raise TPUMetricsUserError(
                "_start_hibernated registration (a migrated hibernated tenant) "
                "requires a lifecycle manager (lifecycle=/spill_dir=)."
            )

        if buckets is None:
            if mesh is not None:
                raise ValueError("mesh (sharded execution mode) requires buckets")
            if health_probe:
                raise ValueError(
                    "health_probe rides the functional/jitted step path and "
                    "therefore requires buckets"
                )
            bucketer = step = None
            state = None
            step_token: Any = ("eager", tenant_id)
            start_hibernated = bool(_start_hibernated)
        else:
            edges = pow2_bucket_edges(int(buckets)) if isinstance(buckets, int) else tuple(buckets)
            bucketer = ShapeBucketer(edges)
            check_bucketable(metric)
            step, step_token = self._resolve_step(
                metric, kwargs, donate=bool(donate_state), mesh=mesh,
                partition_rules=partition_rules, data_axis=data_axis,
                tenant_id=tenant_id, health_probe=bool(health_probe),
            )
            # pristine hibernated start: once the HBM budget is saturated
            # and the step's state size is known, a new same-config tenant
            # is created with NO device allocation and NO scheduler entry —
            # registration of a mostly-idle fleet is O(1) per tenant, and
            # its first submit revives it (a fresh init_state) lazily
            start_hibernated = bool(_start_hibernated) or (
                self._lifecycle is not None
                and self._lifecycle.starts_hibernated(step_token)
            )
            state = None if start_hibernated else step.init_state()

        snapshots = (
            _snapshot.SnapshotManager(snapshot_dir, keep=keep_snapshots)
            if snapshot_dir
            else None
        )
        tenant = _Tenant(
            tenant_id, metric, bucketer, step, step_token, state,
            max_queue=max_queue, policy=backpressure, quota=quota,
            update_kwargs=kwargs, compute_every=compute_every,
            snapshots=snapshots, snapshot_every=snapshot_every,
            crash_policy=crash_policy, max_restores=max_restores,
            guard_non_finite=guard_non_finite,
            # probed tenants are megabatch-excluded: the group path does not
            # unstack per-tenant probe results (fuse_update refuses)
            megabatch=megabatch and step is not None and mesh is None
            and not health_probe,
        )
        with self._lock:
            if self._draining:
                from tpumetrics.runtime.drain import DrainingError

                raise DrainingError(
                    f"EvaluationService {self._label!r} is draining: no new tenants."
                )
            if tenant_id in self._tenants:
                raise ValueError(f"tenant {tenant_id!r} is already registered")
            # a re-registered (or migrated-back) id is a fresh stream: the
            # migration tombstone no longer describes it
            self._migrated.pop(tenant_id, None)
            if not start_hibernated:
                # the scheduler joins FIRST: a failure here must not publish
                # a half-registered zombie tenant (a hibernated start joins
                # the scheduler on revival instead)
                self._drr.add(tenant_id, quota)
            self._tenants[tenant_id] = tenant
            if self._lifecycle is not None:
                self._lifecycle.on_register_locked(tenant, hibernated=start_hibernated)
            _TENANTS_GAUGE.set(len(self._tenants) - self._quarantines, self._label)
        if start_hibernated:
            with _telemetry.attribution(tenant_id):
                _telemetry.record_event(
                    self, "tenant_hibernated",
                    reason="migrate_in" if _start_hibernated else "register_budget",
                    pristine=True, batches=0, spill_bytes=0,
                )
        elif self._lifecycle is not None:
            # a materialized registration can push the watermark over the
            # budget: evict LRU idle tenants back under it proactively
            self._lifecycle.enforce_budget()
        return TenantHandle(self, tenant_id)

    def _resolve_step(
        self,
        metric: Any,
        kwargs: Dict[str, Any],
        *,
        donate: bool,
        mesh: Optional[Any],
        partition_rules: Optional[Any],
        data_axis: Optional[str],
        tenant_id: str,
        health_probe: bool = False,
    ) -> Tuple[FusedCollectionStep, Any]:
        """The global dedupe layer: same (config digest, static kwargs,
        donation, health probe) tenants share ONE step — one program cache,
        one compile per (bucket, signature) no matter how many tenants run
        the eval.  The probe flag is part of the share key because it is
        part of the program SHAPE (probed programs return a counter tree).
        Mesh'd tenants and unhashable kwargs fall back to a private step
        (still persistent-cache-backed), keyed per tenant."""
        from tpumetrics.resilience.elastic import config_digest

        share_key: Any = None
        if mesh is None:
            try:
                share_key = (
                    config_digest(metric), tuple(sorted(kwargs.items())), donate,
                    health_probe,
                    # resident-backbone identity: config digests of two metrics
                    # built from different WEIGHT SETS can coincide (weights are
                    # not config), and megabatching them would silently score
                    # through one tenant's backbone — the registry keys keep
                    # sharing to same-backbone tenants only
                    tuple(getattr(metric, "_backbone_share_ids", ())),
                )
                hash(share_key)
            except TypeError:
                share_key = None
        if share_key is not None:
            with self._lock:
                step = self._steps.get(share_key)
            if step is not None:
                return step, share_key
        step = FusedCollectionStep(
            metric, update_kwargs=kwargs, donate=donate,
            mesh=mesh, partition_rules=partition_rules, data_axis=data_axis,
            health_probe=health_probe,
        )
        if share_key is not None:
            with self._lock:
                step = self._steps.setdefault(share_key, step)
            return step, share_key
        return step, ("private", tenant_id)

    # -------------------------------------------------------------- ingestion

    def submit(self, tenant_id: str, *args: Any) -> None:
        """Enqueue one batch for a tenant; applies THAT tenant's
        backpressure policy.  Never runs a device step on the caller's
        thread — cost is one signature probe + one bounded enqueue (and,
        with observability on, one histogram observation + a batch root
        span: "one batch = one trace" is anchored here)."""
        if not args:
            raise ValueError("submit() needs at least one positional batch argument")
        if self._draining:
            from tpumetrics.runtime.drain import DrainingError

            raise DrainingError(
                f"EvaluationService {self._label!r} is draining (preemption notice "
                f"or request_drain()): intake is closed for tenant {tenant_id!r}. "
                "Batches submitted before the drain began are being applied and "
                "will be covered by each tenant's final snapshot."
            )
        tenant = self._get(tenant_id)
        timed = _instruments.enabled()
        t0 = time.perf_counter() if timed else 0.0
        # probe computed outside the lock: row count for DRR cost, and the
        # single-chunk signature for the worker's megabatch grouping.  A
        # probe failure (pathological args) is NOT the caller's crash — the
        # batch takes the single-tenant worker path, whose crash fence owns
        # the failure and quarantines only this tenant.
        n = leading_rows(args)
        probe = None
        if tenant.bucketer is not None:
            try:
                probe = single_chunk_signature(tenant.bucketer, args)
            except Exception:
                probe = None
        root = _spans.start_trace("batch", stream=tenant_id)
        qspan = _spans.start_span("queue_wait", parent=root) if root is not None else None
        entry = (tuple(args), max(int(n), 1), probe, (root, qspan))
        try:
            while True:
                # the migration gate comes FIRST: a hibernated tenant whose
                # spill file is mid-handoff must not be revived here (the
                # file is the thing being shipped)
                self._gate_migration(tenant)
                if self._lifecycle is not None and tenant.residency != "resident":
                    # the FIRST submit over a hibernated tenant revives it
                    # (restore -> re-place -> re-enter the scheduler);
                    # concurrent submitters wait on the residency condition
                    # or get a typed refusal per the tenant's policy
                    self._lifecycle.ensure_resident(tenant)
                with self._lock:
                    if self._lifecycle is not None and tenant.residency != "resident":
                        # an idle sweep won the race between revival and
                        # enqueue: revive again before enqueueing
                        continue
                    self._submit_locked(tenant, entry)
                break
            self._dispatcher.submit(tenant_id, tag=tenant_id)
            # successful submits only: a quarantined/full-queue failure must
            # not pollute the distribution or re-mint a released series
            if timed:
                _SUBMIT_HIST.observe((time.perf_counter() - t0) * 1e3, tenant_id)
        except BaseException as err:
            _spans.end_span(qspan, error=repr(err))
            _spans.end_span(root, error=repr(err))
            raise

    def _submit_locked(self, tenant: _Tenant, entry: Tuple[Any, ...]) -> None:
        """The enqueue body of :meth:`submit` (service lock held): the
        tenant's backpressure policy, then queue + scheduler bookkeeping."""
        tenant_id = tenant.tid
        while True:
            self._raise_if_quarantined(tenant)
            if tenant.migrating or tenant.migrated_to is not None:
                # the final-cut window opened (or closed as a commit) while
                # this submitter held — or waited for — the lock: gate by the
                # tenant's own policy, then re-check EVERYTHING.  Without
                # this re-check a block-policy submitter woken for queue
                # space could enqueue a batch the final cut already missed —
                # a silently lost update at commit.
                self._gate_migration_locked(tenant)
                continue
            if len(tenant.queue) < tenant.max_queue:
                break
            if tenant.policy == "error":
                from tpumetrics.runtime.dispatch import QueueFullError

                raise QueueFullError(
                    f"Tenant {tenant_id!r} queue full ({tenant.max_queue} batches) "
                    "under policy='error'."
                )
            if tenant.policy == "drop_oldest":
                _, _, _, (d_root, d_qspan) = tenant.queue.popleft()
                _spans.end_span(d_qspan, dropped=True)
                _spans.end_span(d_root, error="dropped (drop_oldest)")
                tenant.pending -= 1
                tenant.dropped += 1
                with _telemetry.attribution(tenant_id):
                    _telemetry.record_event(
                        self, "runtime_drop", dropped_total=tenant.dropped
                    )
                break
            # block
            if self._draining:
                from tpumetrics.runtime.drain import DrainingError

                raise DrainingError(
                    f"EvaluationService {self._label!r} began draining "
                    f"while tenant {tenant_id!r} waited for queue "
                    "space: intake is closed."
                )
            self._space.wait()
        tenant.queue.append(entry)
        tenant.pending += 1
        tenant.enqueued += 1
        tenant.last_dispatch = time.monotonic()
        self._drr.activate(tenant_id)
        self._mark_ready(tenant)

    def flush(self, tenant_id: Optional[str] = None, timeout: Optional[float] = None) -> None:
        """Block until the tenant's queue is fully applied (``tenant_id=None``
        = every tenant).  Raises :class:`TenantQuarantinedError` when the
        awaited tenant was quarantined (its queue was discarded)."""
        if tenant_id is None:
            self._dispatcher.flush(timeout=timeout)
            return
        tenant = self._get(tenant_id)
        with self._lock:
            while tenant.pending > 0 and tenant.error is None:
                if not self._done.wait(timeout=timeout):
                    raise TimeoutError(
                        f"Tenant {tenant_id!r} did not drain within {timeout}s "
                        f"(pending={tenant.pending})."
                    )
            self._raise_if_quarantined(tenant)

    # -------------------------------------------------------- tenant lifecycle

    @property
    def lifecycle(self):
        """The :class:`~tpumetrics.lifecycle.manager.LifecycleManager`
        owning tenant residency (``None`` when the service was built
        without ``lifecycle=``/``hbm_budget_bytes=``/``spill_dir=``)."""
        return self._lifecycle

    def _require_lifecycle(self):
        if self._lifecycle is None:
            raise TPUMetricsUserError(
                f"EvaluationService {self._label!r} has no lifecycle manager; "
                "construct it with lifecycle=LifecyclePolicy(...), "
                "hbm_budget_bytes=, or spill_dir= to enable hibernation."
            )
        return self._lifecycle

    def hibernate(self, tenant_id: str) -> bool:
        """Explicitly demote one tenant: flush its queue, cut its state to
        the spill store, release its device buffers / instrument series /
        last-holder backbone references, and remove it from the scheduler.
        Returns ``False`` when the tenant cannot hibernate right now (new
        work raced the flush, quarantine, a draining service).  Its next
        ``submit()``/``compute()`` revives it bit-identically."""
        manager = self._require_lifecycle()
        self.flush(tenant_id)
        return manager.hibernate(tenant_id, reason="manual")

    def sweep_lifecycle(self, idle_for: Optional[float] = None) -> List[str]:
        """Hibernate every tenant idle past the policy threshold
        (``idle_for`` overrides ``LifecyclePolicy.idle_hibernate_after``);
        returns the demoted tenant ids.  Run it from a maintenance cadence
        — the sweep itself is O(registered) in bookkeeping but performs
        I/O only for the tenants it demotes."""
        return self._require_lifecycle().sweep(idle_for=idle_for)

    # ---------------------------------------------------------- live migration

    def _gate_migration(self, tenant: _Tenant) -> None:
        """Hold the caller at the migration gate when the tenant's final-cut
        window is open (lock-free fast path; the locked recheck in
        :meth:`_submit_locked` / the residency loops is authoritative)."""
        if not tenant.migrating and tenant.migrated_to is None:
            return
        with self._lock:
            self._gate_migration_locked(tenant)

    def _gate_migration_locked(self, tenant: _Tenant) -> None:
        """The final-cut window gate (service lock held): ``block`` and
        ``drop_oldest`` tenants wait out the window on the queue-space
        condition (commit/abort notify it); ``error`` tenants get the typed
        refusal immediately.  A committed migration wakes waiters with
        ``migrated_to`` stamped — they are refused toward the new owner."""
        from tpumetrics.fleet.migrate import TenantMigratingError

        while tenant.migrating:
            if tenant.policy == "error":
                raise TenantMigratingError(
                    f"Tenant {tenant.tid!r} is mid-migration (final-cut window) "
                    "under policy='error'; retry once the window closes."
                )
            self._space.wait()
        if tenant.migrated_to is not None:
            rank, epoch = tenant.migrated_to
            raise TenantMigratingError(
                f"Tenant {tenant.tid!r} migrated to rank {rank} at routing "
                f"epoch {epoch}: resubmit to the new owner.",
                target_rank=rank, routing_epoch=epoch,
            )

    def begin_migration(self, tenant_id: str) -> Tuple[str, Any, Dict[str, Any]]:
        """Open the final-cut window on this (source) service and produce
        the cut: gate intake by the tenant's backpressure policy, flush its
        pending batches, and return ``(mode, cut, meta)`` where ``mode`` is

        - ``"live"`` — a resident tenant: ``cut`` is the state payload
          (bucketed pytree or eager ``snapshot_state()``), exactly the
          atomic-snapshot shape with the batch count stamped in ``meta``;
        - ``"spill"`` — a hibernated tenant: ``cut`` is the PATH of its
          newest spill file, shipped verbatim — O(1) in state size, no
          revival;
        - ``"pristine"`` — a hibernated tenant that never applied a batch:
          ``cut`` is ``None`` (the target registers it pre-hibernated).

        The window stays open (intake gated) until :meth:`commit_migration`
        or :meth:`abort_migration` closes it."""
        mgr = self._lifecycle
        with self._lock:
            while True:
                tenant = self._tenants.get(tenant_id)
                if tenant is None:
                    raise KeyError(f"unknown tenant {tenant_id!r}")
                self._raise_if_quarantined(tenant)
                if self._draining:
                    from tpumetrics.runtime.drain import DrainingError

                    raise DrainingError(
                        f"EvaluationService {self._label!r} is draining: "
                        f"tenant {tenant_id!r} cannot migrate out now."
                    )
                if tenant.migrating:
                    raise TPUMetricsUserError(
                        f"Tenant {tenant_id!r} already has an open migration window."
                    )
                if mgr is None or tenant.residency == "resident":
                    mode = "live"
                    break
                if tenant.residency == "hibernated":
                    mode = "pristine" if tenant.batches == 0 else "spill"
                    break
                # hibernating / reviving: the transition owner notifies the
                # residency condition when it completes — wait it out
                # tpulint: disable-next=TPL123 -- mgr._cond wraps THIS service's _lock (Condition(service._lock), manager.py), so wait() releases the held lock while parked; the cross-object alias is beyond the static resolver
                mgr._cond.wait()
            tenant.migrating = True
        if mode != "live":
            with self._lock:
                meta = self._cut_meta_locked(tenant)
            path = mgr.store.newest_path(tenant_id) if mode == "spill" else None
            if mode == "spill" and path is None:
                # the spill store lost the cut: the stream cannot move
                self.abort_migration(tenant_id)
                raise _snapshot.SnapshotIntegrityError(
                    f"Tenant {tenant_id!r} hibernated at stream position "
                    f"{meta['batches']} but its spill store holds no cut: "
                    "the migration cannot be loss-free."
                )
            return mode, path, meta
        try:
            # with the window open no NEW batch can be enqueued (the gate in
            # _submit_locked re-checks after every wake), so after this
            # flush the tenant's stream position is final
            self.flush(tenant_id)
        except BaseException:
            self.abort_migration(tenant_id)
            raise
        with self._lock:
            meta = self._cut_meta_locked(tenant)
            payload: Any = (
                tenant.state
                if tenant.bucketer is not None
                else tenant.metric.snapshot_state()
            )
        return "live", payload, meta

    def _cut_meta_locked(self, tenant: _Tenant) -> Dict[str, Any]:
        """The migration cut's header meta — the exact shape the snapshot /
        spill formats stamp, so restore-side integrity checks apply as-is."""
        return {
            "batches": tenant.batches,
            "items": tenant.items,
            "metric": type(tenant.metric).__name__,
            "mode": "bucketed" if tenant.bucketer is not None else "eager",
            "degraded": tenant.degraded,
            "tenant": tenant.tid,
        }

    def abort_migration(self, tenant_id: str) -> bool:
        """Close an open final-cut window WITHOUT moving the tenant: it
        stays (or re-becomes) the live resident stream here, gated waiters
        resume, and nothing was lost (the window admitted no batches).
        Idempotent; returns whether a window was actually open."""
        with self._lock:
            tenant = self._tenants.get(tenant_id)
            if tenant is None or not tenant.migrating:
                return False
            tenant.migrating = False
            self._space.notify_all()
            self._done.notify_all()
            if self._lifecycle is not None:
                self._lifecycle._cond.notify_all()
            return True

    def commit_migration(
        self, tenant_id: str, *, target_rank: Any = None, routing_epoch: Any = None
    ) -> None:
        """Finalize an outbound migration: deregister the tenant here,
        tombstone its id toward ``(target_rank, routing_epoch)``, release
        its series/buffers (or discard its spill — the target adopted the
        file), and wake gated waiters into the typed moved-refusal."""
        with self._lock:
            tenant = self._tenants.get(tenant_id)
            if tenant is None or not tenant.migrating:
                raise TPUMetricsUserError(
                    f"Tenant {tenant_id!r} has no open migration window to commit."
                )
            was_hibernated = self._deregister_locked(
                tenant, (target_rank, routing_epoch)
            )
        self._deregister_finish(tenant, was_hibernated)

    def withdraw_adoption(self, tenant_id: str) -> None:
        """Roll back an adoption on this (target) service: deregister the
        just-adopted tenant WITHOUT a tombstone (it still lives on the
        source).  Refused once the tenant accepted work here — at that
        point the adoption is the live stream and rollback would lose it."""
        with self._lock:
            tenant = self._tenants.get(tenant_id)
            if tenant is None:
                raise KeyError(f"unknown tenant {tenant_id!r}")
            if tenant.queue or tenant.pending or tenant.enqueued:
                raise TPUMetricsUserError(
                    f"Tenant {tenant_id!r} accepted work since adoption; "
                    "withdrawing now would lose updates."
                )
            was_hibernated = self._deregister_locked(tenant, None)
        self._deregister_finish(tenant, was_hibernated)

    def _deregister_locked(
        self, tenant: _Tenant, moved_to: Optional[Tuple[Any, Any]]
    ) -> bool:
        """Remove one tenant from every locked structure (service lock
        held); ``moved_to`` non-None stamps the migration tombstone.
        Returns whether the tenant left in the hibernated state (the caller
        finishes the matching release path outside the lock)."""
        tid = tenant.tid
        tenant.migrating = False
        tenant.migrated_to = moved_to
        del self._tenants[tid]
        if moved_to is not None:
            self._migrated[tid] = moved_to
        was_hibernated = tenant.residency == "hibernated"
        if not was_hibernated:
            self._drr.remove(tid)
        self._unmark_ready(tenant)
        if self._lifecycle is not None:
            self._lifecycle.on_migrate_out_locked(tenant)
        if tenant.error is not None:
            self._quarantines -= 1
        _TENANTS_GAUGE.set(len(self._tenants) - self._quarantines, self._label)
        self._space.notify_all()
        self._done.notify_all()
        if self._lifecycle is not None:
            self._lifecycle._cond.notify_all()
        return was_hibernated

    def _deregister_finish(self, tenant: _Tenant, was_hibernated: bool) -> None:
        """The out-of-lock deregistration tail: series release and state
        drop for a resident leaver, spill discard for a hibernated one
        (the file moved with it), backbone references either way."""
        if was_hibernated:
            if self._lifecycle is not None:
                self._lifecycle.store.discard(tenant.tid)
        else:
            self._release_tenant_series(tenant)
            tenant.state = None
            if tenant.bucketer is None:
                tenant.metric.reset()
        release = getattr(tenant.metric, "release_backbones", None)
        if callable(release):
            release()

    def adopt_migrated(
        self,
        tenant_id: str,
        metric: Any,
        payload: Any,
        meta: Dict[str, Any],
        **register_kw: Any,
    ) -> TenantHandle:
        """Adopt a live-migrated tenant on this (target) service: register
        it fresh, then place the final cut — batch count, items, and
        degraded flag stamped from the cut's meta.  Registration's own
        duplicate check IS the exactly-once guard: a second adoption of the
        same id raises before any state moves."""
        handle = self.register(tenant_id, metric, **register_kw)
        tenant = self._get(tenant_id)
        if self._lifecycle is not None and tenant.residency != "resident":
            # a saturated budget started the registration hibernated —
            # adoption needs a resident target (pristine revival: fresh state)
            self._lifecycle.ensure_resident(tenant)
        with self._lock:
            self._adopt_snapshot_locked(tenant, (payload, {"meta": dict(meta)}))
            if self._lifecycle is not None:
                self._lifecycle._account_resident_locked(tenant)
        return handle

    def adopt_hibernated(
        self,
        tenant_id: str,
        metric: Any,
        meta: Dict[str, Any],
        spill_path: Optional[str] = None,
        **register_kw: Any,
    ) -> TenantHandle:
        """Adopt a hibernated tenant on this (target) service at O(1):
        register it directly in the hibernated state, adopt its spill file
        verbatim (``None`` = a pristine tenant with nothing to ship), and
        stamp its stream position — no revival, no device allocation.  Its
        next submit/compute revives it here bit-identically."""
        mgr = self._require_lifecycle()
        handle = self.register(
            tenant_id, metric, _start_hibernated=True, **register_kw
        )
        if spill_path is not None:
            mgr.store.adopt_file(tenant_id, spill_path)
        tenant = self._get(tenant_id)
        with self._lock:
            tenant.batches = int(meta.get("batches", 0))
            tenant.items = int(meta.get("items", 0))
            tenant.last_compute_at = tenant.batches
            tenant.journal = []
            tenant.journal_base = tenant.batches
            tenant.degraded = bool(meta.get("degraded", False))
            mgr._publish_gauges_locked()
        return handle

    # --------------------------------------------------------- graceful drain

    def request_drain(self) -> None:
        """Close intake service-wide: every tenant's ``submit`` (and
        :meth:`TenantHandle.submit`) raises a typed
        :class:`~tpumetrics.runtime.drain.DrainingError` from now on, while
        already-queued batches keep applying.  Blocked ``"block"``-policy
        submitters are woken so they observe the drain instead of waiting
        on queue space forever."""
        notify = False
        with self._lock:
            if not self._draining:
                self._draining = True
                notify = True
            self._space.notify_all()
        if notify:
            _telemetry.record_event(None, "drain_requested", stream=self._label)

    @property
    def draining(self) -> bool:
        return self._draining

    def drain(self, final_cut: bool = True, timeout: Optional[float] = None) -> Any:
        """Graceful shutdown of the whole service: stop intake, apply every
        tenant's queued batches, write one final snapshot per tenant that
        has a snapshot dir (when ``final_cut``), close the shared worker,
        and return a :class:`~tpumetrics.runtime.drain.DrainReport` whose
        ``tenants`` section names each tenant's covered position.
        Quarantined tenants are skipped (their queues were already
        discarded; the report omits them).  Idempotent AND serialized:
        concurrent callers get ONE drain (a duplicate per-tenant final cut
        is wasted work at best, a barrier hang in elastic setups)."""
        from tpumetrics.runtime.drain import DrainReport

        with self._drain_lock:
            if self._drain_report is not None:
                return self._drain_report
            self.request_drain()
            t0 = time.perf_counter()
            self._dispatcher.flush(timeout=timeout)
            with self._lock:
                tenants = [t for t in self._tenants.values() if t.error is None]
            reports: Dict[str, DrainReport] = {}
            total_b = total_i = 0
            for tenant in tenants:
                cut_path = cut_step = None
                if final_cut and tenant.snapshots is not None:
                    cut_path = self.snapshot(tenant.tid)
                    cut_step = tenant.snapshots.last_step
                with self._lock:
                    b, i = tenant.batches, tenant.items
                reports[tenant.tid] = DrainReport(
                    target=tenant.tid, batches=b, items=i,
                    cut_path=cut_path, cut_step=cut_step,
                )
                total_b += b
                total_i += i
            drain_ms = (time.perf_counter() - t0) * 1e3
            _telemetry.record_event(
                None, "drain_complete", stream=self._label, batches=total_b,
                items=total_i, tenants=len(reports), drain_ms=round(drain_ms, 3),
            )
            report = DrainReport(
                target=self._label, batches=total_b, items=total_i,
                drain_ms=drain_ms, tenants=reports,
            )
            self.close(drain=True, timeout=timeout)
            self._drain_report = report  # cached only once the close succeeded
            return report

    def close(self, drain: bool = True, timeout: Optional[float] = None) -> None:
        """Flush every tenant (unless ``drain=False``) and stop the worker.

        Releases this service's instrument series — the per-instance gauge
        labels and every tenant's submit/dispatch histogram series — from
        the process-global registry, so a construct-per-job process does
        not grow dead series (the evaluator's ``close`` contract).  Note a
        tenant id reused by ANOTHER live service shares (and here loses)
        its series — ids are aggregation keys, use unique ones.  The
        release (and the abandoned-batch span completion) runs even when
        ``close`` raises — a poisoned dispatcher or a drain timeout is
        exactly when batches are left behind."""
        try:
            self._dispatcher.close(drain=drain, timeout=timeout)
        finally:
            if self._admin is not None:
                self._admin.close()
            with self._lock:
                tenants = list(self._tenants.values())
                # any batch still in a tenant queue will never be drained
                # (drain=False, a poisoned dispatcher, a timed-out drain):
                # complete its spans like the dispatcher's discard paths do,
                # or recorded queue_wait children stay orphaned.  After a
                # clean drain the queues are empty and this is a no-op.
                for tenant in tenants:
                    for _args, _n, _probe, (d_root, d_qspan) in tenant.queue:
                        _spans.end_span(d_qspan, discarded=True)
                        _spans.end_span(d_root, error="discarded (service close)")
            for tenant in tenants:
                self._release_tenant_series(tenant)
                # shared-backbone protocol: drop the metric's registry
                # references (the LAST tenant over a weight set frees it);
                # outside the health lock — handle close can release device
                # buffers and program profiles of its own label.  Parked
                # references (hibernated tenants) are discarded too.
                release = getattr(tenant.metric, "release_backbones", None)
                if callable(release):
                    release()
            _TENANTS_GAUGE.remove(self._label)
            _DEPTH_GAUGE.remove(self._label)
            if self._lifecycle is not None:
                self._lifecycle.close()

    def _release_tenant_series(self, tenant: _Tenant) -> None:
        """Release one tenant's per-tenant instrument series from the
        process-global registry — shared by :meth:`close` (permanent) and
        the lifecycle manager's hibernation path (the tenant re-mints its
        series on revival).  Idempotent."""
        from tpumetrics.monitoring.drift import release_stream
        from tpumetrics.telemetry.xla import release_attribution

        _SUBMIT_HIST.remove(tenant.tid)
        _DISPATCH_HIST.remove(tenant.tid)
        release_stream(self._stats_metric(tenant), tenant.tid)
        release_attribution(tenant.tid, tokens=(tenant.step_token,))
        # device-side series: latch + release UNDER the health lock
        # the stats()-side gauge writes also take, so a concurrent
        # tenant_stats() cannot re-mint what is being released (the
        # evaluator's close() ordering, per tenant)
        with tenant.health_lock:
            tenant.released = True
            _STATE_HBM_GAUGE.remove(tenant.tid)
            _health.release_health(tenant.tid, tenant.health_alerted)
            _device.release_profiles(tenant.tid)

    def __enter__(self) -> "EvaluationService":
        return self

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> None:
        try:
            self.close(drain=exc_type is None)
        except Exception:
            if exc_type is None:
                raise

    # ---------------------------------------------------------------- results

    def compute(self, tenant_id: str) -> Any:
        """Exact result over everything the tenant submitted (flushes it
        first)."""
        from tpumetrics.monitoring.drift import stream_scope

        tenant = self._get(tenant_id)
        self.flush(tenant_id)
        while True:
            # compute during the final-cut window gates like submit: block /
            # drop_oldest wait the window out, error gets the typed refusal
            self._gate_migration(tenant)
            if self._lifecycle is not None and tenant.residency != "resident":
                # a hibernated tenant's result is served by reviving it:
                # restore -> re-place -> the SAME functional compute an
                # uninterrupted stream would run (the bit-identity contract)
                self._lifecycle.ensure_resident(tenant)
            # health first: a poisoned tenant must page (state_health event +
            # nonzero nonfinite series) BEFORE any value is computed or the
            # non-finite guard turns the corruption into an exception
            self._refresh_health(tenant)
            with self._lock, stream_scope(tenant.tid):
                if tenant.migrating or tenant.migrated_to is not None:
                    self._gate_migration_locked(tenant)
                    continue  # the window closed as an abort: state is live
                if self._lifecycle is not None and tenant.residency != "resident":
                    continue  # an idle sweep raced the revival: revive again
                return self._compute_locked(tenant)

    def _compute_locked(self, tenant: _Tenant) -> Any:
        """The compute body (service lock held, drift stream scope
        active).  Drift monitors alert under THIS tenant's label — latches
        are per-stream on the (possibly shared) metric instance, so one
        shared-step monitor pages each tenant independently."""
        self._raise_if_quarantined(tenant)
        if tenant.bucketer is None:
            value = tenant.metric.compute()
            tenant.degraded = bool(getattr(tenant.metric, "degraded", False))
            return value
        # the step's metric runs ALL functional ops for shared-step
        # tenants (init/update/compute from one config-identical object),
        # so state structure and compute can never drift between sharers.
        # Compile attribution: signature None = attribute, but exempt
        # from retrace detection (eager computes re-fire per new shape)
        with attribute_compiles(tenant.tid, None, token=tenant.step_token):
            return tenant.step._metric.functional_compute(tenant.state)

    def latest_result(self, tenant_id: str) -> Optional[Dict[str, Any]]:
        """The tenant's bounded-staleness result (``compute_every=n``);
        never blocks on the queue."""
        tenant = self._get(tenant_id)
        with self._lock:
            return dict(tenant.latest) if tenant.latest is not None else None

    def tenant_error(self, tenant_id: str) -> Optional[BaseException]:
        tenant = self._get(tenant_id)
        with self._lock:
            return tenant.error

    def tenant_stats(self, tenant_id: str) -> Dict[str, Any]:
        """Never-blocking by construction: ONE bounded acquire of the
        service lock grabs everything the lock guards (counters, HBM, the
        health probe handle) — when a donating dispatch owns it, the
        tenant's last successful snapshot is served (``stale=True``) so a
        scrape never waits on the device (the admin plane's contract)."""
        tenant = self._get(tenant_id)
        with _bounded_lock(self._lock) as locked:
            grab = self._grab_locked(tenant) if locked else None
        return self._assemble_tenant_stats(tenant, grab)

    def all_tenant_stats(self) -> Dict[str, Dict[str, Any]]:
        """Every tenant's stats under ONE bounded lock acquire — the admin
        plane's census read: a ``/statusz`` scrape of a 1000-tenant service
        pays at most one bounded wait, never one per tenant (per-tenant
        bounded acquires would stack N timeouts under a continuously
        contended lock)."""
        with _bounded_lock(self._lock) as locked:
            if locked:
                tenants = [self._tenants[tid] for tid in sorted(self._tenants)]
                self._tenant_ids_cache = [t.tid for t in tenants]
                grabs: List[Any] = [self._grab_locked(t) for t in tenants]
            else:
                tenants = [
                    self._tenants[tid]
                    for tid in self._tenant_ids_cache
                    if tid in self._tenants
                ]
                grabs = [None] * len(tenants)
        return {
            t.tid: self._assemble_tenant_stats(t, g) for t, g in zip(tenants, grabs)
        }

    # ----------------------------------------------------- device observability

    def _grab_locked(self, tenant: _Tenant) -> Tuple[Any, ...]:
        """Everything ``tenant_stats`` needs from under the service lock,
        grabbed quickly (host-side counter/shape reads only): the core
        counters, the live-state HBM numbers, and the health probe's device
        handle.  Assembly — instrument reads, summaries — happens OUTSIDE
        the lock (:meth:`_assemble_tenant_stats`)."""
        from tpumetrics.runtime.evaluator import _eager_state_leaves

        core = {
            "batches": tenant.batches,
            "items": tenant.items,
            "enqueued": tenant.enqueued,
            "depth": len(tenant.queue),
            "pending": tenant.pending,
            "dropped": tenant.dropped,
            "megabatched": tenant.megabatched,
            "quarantined": tenant.error is not None,
            "degraded": tenant.degraded,
            "crashes": tenant.crashes,
            "restores": tenant.restores,
            "buckets": list(tenant.bucketer.edges) if tenant.bucketer else None,
            # the tenant's DRR quantum (its fair share of a contended
            # worker, in batch rows per round) — /statusz surfaces it
            "quota": tenant.quota,
            # lifecycle census: resident / hibernating / hibernated /
            # reviving (always "resident" without a lifecycle manager)
            "residency": tenant.residency,
            # live-migration census: True while the final-cut window is open
            "migrating": tenant.migrating,
        }
        if tenant.bucketer is not None:
            leaves = jax.tree_util.tree_leaves(tenant.state)
        else:
            leaves = _eager_state_leaves(tenant.metric)
        current = sum(int(getattr(l, "nbytes", 0) or 0) for l in leaves)
        if current > tenant.hbm_watermark:
            tenant.hbm_watermark = current
        from tpumetrics.backbones.registry import resident_bytes as _backbone_bytes

        hbm = {
            "state_bytes": current,
            "watermark_bytes": tenant.hbm_watermark,
            # process-wide resident backbone weights (shared across tenants,
            # reported flat — NOT multiplied per tenant); section contract:
            # keys only ever get added
            "backbone_bytes": _backbone_bytes(),
        }
        probed = tenant.step is not None and tenant.step.health_probe
        health = tenant.device_health if probed else None
        paths = _health.state_paths(tenant.state) if health is not None else None
        return core, hbm, health, paths

    def _assemble_tenant_stats(
        self, tenant: _Tenant, grab: Optional[Tuple[Any, ...]]
    ) -> Dict[str, Any]:
        """Build the ``TenantHandle.stats()`` payload from a lock grab
        (``None`` = the lock was contended: serve the cached snapshot with
        ``stale=True``).  Runs entirely outside the service lock; existing
        keys are a stable contract — sections only ever ADD keys."""
        locked = grab is not None
        if locked:
            core, hbm, health_dev, paths = grab
            with tenant.health_lock:
                tenant.stats_cache = dict(core)
                tenant.hbm_cache = dict(hbm)
                if not tenant.released:  # close() released; don't re-mint
                    _STATE_HBM_GAUGE.set(hbm["state_bytes"], tenant.tid)
        else:
            with tenant.health_lock:
                core = dict(tenant.stats_cache) or {
                    "batches": 0, "items": 0, "enqueued": 0, "depth": 0,
                    "pending": 0, "dropped": 0, "megabatched": 0,
                    "quarantined": False, "degraded": False, "crashes": 0,
                    "restores": 0, "buckets": None, "quota": tenant.quota,
                    "residency": tenant.residency,
                    "migrating": tenant.migrating,
                }
                hbm = dict(tenant.hbm_cache)
            health_dev = paths = None
        out = dict(core)
        out["stale"] = not locked
        out["latency"] = _instruments.latency_section(tenant.tid)
        out["recompiles"] = recompile_count(tenant.tid)
        with tenant.health_lock:  # serializes the gauge writes with close()
            programs = _device.profile_summary(tenant.tid)
        out["device"] = {
            "programs": programs,
            "hbm": hbm,
            "health": self._health_section(tenant, health_dev, paths, locked),
        }
        from tpumetrics.monitoring.drift import monitoring_stats

        monitoring = monitoring_stats(self._stats_metric(tenant), tenant.tid)
        if monitoring:
            out["monitoring"] = monitoring
        return out

    def _health_section(
        self, tenant: _Tenant, health: Any, paths: Any, locked: bool
    ) -> Optional[Dict[str, Any]]:
        """The never-blocking stats()-side health summary: a contended lock
        or a not-yet-ready probe output serves the LAST fetched summary
        (all-zero before the first fetch); a ready one is summarized and
        latched (first corruption per state pages ONE ``state_health``
        event)."""
        if tenant.step is None or not tenant.step.health_probe:
            return None
        ready = locked and (
            health is None or getattr(health, "is_ready", lambda: True)()
        )
        if not ready:
            with tenant.health_lock:
                cached = tenant.health_summary
            return cached if cached is not None else _health.summarize(None)
        summary = _health.summarize(health, paths)
        with tenant.health_lock:
            if not tenant.released:  # post-close reads must not re-mint/re-page
                _health.publish_health(tenant.tid, summary, tenant.health_alerted)
            tenant.health_summary = summary
        return summary

    def _refresh_health(self, tenant: _Tenant) -> Optional[Dict[str, Any]]:
        """The compute()-side BLOCKING health fetch (None when unprobed):
        compute() synchronizes with the device anyway, and corruption must
        page — one ``state_health`` ledger event per (stream, state) —
        BEFORE a value is served."""
        if tenant.step is None or not tenant.step.health_probe:
            return None
        with self._lock:
            health = tenant.device_health
            paths = _health.state_paths(tenant.state) if health is not None else None
        summary = _health.summarize(health, paths)
        with tenant.health_lock:
            if not tenant.released:
                _health.publish_health(tenant.tid, summary, tenant.health_alerted)
            tenant.health_summary = summary
        return summary

    @staticmethod
    def _stats_metric(tenant: "_Tenant") -> Any:
        """The metric instance whose compute path serves this tenant — the
        SHARED step metric on the bucketed path (drift latches there are
        keyed per tenant id), the tenant's own on the eager path."""
        return tenant.step._metric if tenant.bucketer is not None else tenant.metric

    def stats(self) -> Dict[str, Any]:
        """Service-wide counters: the shared dispatcher's (with the per-tag
        split), compile dedupe accounting, and megabatch totals.  The
        service lock is taken with a bounded acquire (``tenant_stats``'s
        never-blocking contract); ``stale=True`` marks a snapshot served
        while a donating dispatch owned the lock."""
        out = self._dispatcher.stats()
        with _bounded_lock(self._lock) as locked:
            if locked:
                core = dict(
                    tenants=len(self._tenants),
                    shared_steps=len(self._steps),
                    xla_compiles=self._signatures.inserts,
                    signatures_tracked=len(self._signatures),
                    signature_evictions=self._signatures.evictions,
                    megabatch_steps=self._megabatch_steps,
                    megabatch_tenants=self._megabatch_tenants,
                    quarantined_tenants=self._quarantines,
                )
                if self._lifecycle is not None:
                    core["lifecycle"] = self._lifecycle.stats_locked()
                self._stats_cache = core
        if not locked:
            core = dict(self._stats_cache) or dict(
                tenants=0, shared_steps=0, xla_compiles=0, signatures_tracked=0,
                signature_evictions=0, megabatch_steps=0, megabatch_tenants=0,
                quarantined_tenants=0,
            )
            if self._lifecycle is not None and "lifecycle" not in core:
                core["lifecycle"] = self._lifecycle.stats_default()
        out.update(core)
        out["stale"] = not locked
        return out

    # -------------------------------------------------------------- snapshots

    def snapshot(self, tenant_id: str) -> str:
        """Flush the tenant, then persist its state into its own snapshot
        directory, tagged with its stream position."""
        tenant = self._get(tenant_id)
        if tenant.snapshots is None:
            raise TPUMetricsUserError(
                f"Tenant {tenant_id!r} was registered without snapshot_dir"
            )
        self.flush(tenant_id)
        while True:
            self._gate_migration(tenant)
            if self._lifecycle is not None and tenant.residency != "resident":
                self._lifecycle.ensure_resident(tenant)
            with self._lock:
                if tenant.migrating or tenant.migrated_to is not None:
                    self._gate_migration_locked(tenant)
                    continue
                if self._lifecycle is not None and tenant.residency != "resident":
                    continue  # an idle sweep raced the revival
                self._raise_if_quarantined(tenant)
                return self._save_snapshot_locked(tenant)

    def _save_snapshot_locked(self, tenant: _Tenant) -> str:
        if tenant.snapshots.last_step == tenant.batches:
            # a manual snapshot right after an auto-snapshot at the same
            # stream position: identical state by the determinism contract
            for step, path in _snapshot.list_snapshots(tenant.snapshots.directory):
                if step == tenant.batches:
                    return path
        meta = {
            "batches": tenant.batches,
            "items": tenant.items,
            "metric": type(tenant.metric).__name__,
            "mode": "bucketed" if tenant.bucketer is not None else "eager",
            "degraded": tenant.degraded,
            "tenant": tenant.tid,
        }
        payload: Any = (
            tenant.state if tenant.bucketer is not None else tenant.metric.snapshot_state()
        )
        path = tenant.snapshots.save(
            tenant.batches, payload, meta=meta, guard_non_finite=tenant.guard_non_finite
        )
        self._trim_journal(tenant)
        return path

    @staticmethod
    def _trim_journal(tenant: _Tenant) -> None:
        """Discard exactly the journal entries the just-saved snapshot
        covers.  The worker journals a batch BEFORE applying it (lock-free),
        so a batch drained between a user snapshot()'s flush and its lock
        acquisition may already sit in the journal without being counted in
        ``batches`` — rebinding ``journal = []`` would silently drop it from
        crash replay.  Entries covered by the snapshot number exactly
        ``batches - journal_base``; deleting that prefix keeps any in-flight
        tail (del/append interleave safely under the GIL)."""
        covered = tenant.batches - tenant.journal_base
        del tenant.journal[:covered]
        tenant.journal_base = tenant.batches

    def restore_latest(self, tenant_id: str) -> Optional[int]:
        """Restore the tenant's newest compatible snapshot; returns the
        stream position to replay from (``None`` = no snapshot).  Must run
        before the tenant's first ``submit``."""
        tenant = self._get(tenant_id)
        if tenant.snapshots is None:
            raise TPUMetricsUserError(
                f"Tenant {tenant_id!r} was registered without snapshot_dir"
            )
        while True:
            self._gate_migration(tenant)
            if self._lifecycle is not None and tenant.residency != "resident":
                # a pristine hibernated tenant may restore_latest: revival
                # is a fresh state, which is exactly what restore expects
                self._lifecycle.ensure_resident(tenant)
            with self._lock:
                if tenant.migrating or tenant.migrated_to is not None:
                    self._gate_migration_locked(tenant)
                    continue
                if self._lifecycle is not None and tenant.residency != "resident":
                    continue  # an idle sweep raced the revival
                self._raise_if_quarantined(tenant)
                if tenant.batches or tenant.pending:
                    raise TPUMetricsUserError(
                        "restore_latest() after ingestion started would double-count; "
                        "restore on a fresh tenant, then replay from the returned position."
                    )
                got = self._load_latest_snapshot(tenant)
                if got is None:
                    return None
                return self._adopt_snapshot_locked(tenant, got)

    def _load_latest_snapshot(self, tenant: _Tenant) -> Optional[Tuple[Any, Dict[str, Any]]]:
        if tenant.snapshots is None:
            return None
        if tenant.bucketer is not None:
            return tenant.snapshots.restore_latest(
                tenant.step._metric.init_state(),
                annotations=_snapshot.state_annotations(tenant.step._metric),
            )
        return _snapshot.restore_latest_reconstruct(tenant.snapshots.directory)

    def _adopt_snapshot_locked(
        self, tenant: _Tenant, got: Optional[Tuple[Any, Dict[str, Any]]]
    ) -> int:
        if got is None:
            if tenant.bucketer is not None:
                tenant.state = tenant.step.init_state()
            else:
                tenant.metric.reset()
            restored, items, degraded = 0, 0, False
        else:
            payload, header = got
            if tenant.bucketer is not None:
                # donation-safe on-device placement (host-backed leaves must
                # never be donated — see StreamingEvaluator._place_state)
                tenant.state = tenant.step.place(payload)
            else:
                from tpumetrics.runtime.evaluator import _as_snapshot_payload

                tenant.metric.load_snapshot_state(_as_snapshot_payload(payload))
            restored = int(header["meta"]["batches"])
            items = int(header["meta"]["items"])
            degraded = bool(header["meta"].get("degraded", False))
        tenant.batches = restored
        tenant.items = items
        tenant.last_compute_at = restored
        tenant.journal = []
        tenant.journal_base = restored
        tenant.degraded = degraded
        # stale health counters describe the pre-restore pytree; the alert
        # latch stays (a past corruption remains true of the stream history)
        tenant.device_health = None
        return restored

    # ----------------------------------------------------------------- worker

    def _get(self, tenant_id: str) -> _Tenant:
        tenant = self._tenants.get(tenant_id)
        if tenant is None:
            moved = self._migrated.get(tenant_id)
            if moved is not None:
                from tpumetrics.fleet.migrate import TenantMigratingError

                raise TenantMigratingError(
                    f"Tenant {tenant_id!r} migrated to rank {moved[0]} at "
                    f"routing epoch {moved[1]}: re-read the routing ring and "
                    "resubmit to the new owner.",
                    target_rank=moved[0], routing_epoch=moved[1],
                )
            raise KeyError(f"unknown tenant {tenant_id!r}")
        return tenant

    def _raise_if_quarantined(self, tenant: _Tenant) -> None:
        if tenant.error is not None:
            flight = f" Flight record: {tenant.flight_path}" if tenant.flight_path else ""
            raise TenantQuarantinedError(
                f"Tenant {tenant.tid!r} is quarantined after "
                f"{type(tenant.error).__name__}: {tenant.error}.{flight}"
            ) from tenant.error

    def _mark_ready(self, tenant: _Tenant) -> None:
        if tenant.megabatch and tenant.queue:
            self._ready.setdefault(tenant.step_token, set()).add(tenant.tid)

    def _unmark_ready(self, tenant: _Tenant) -> None:
        if not tenant.queue:
            ready = self._ready.get(tenant.step_token)
            if ready is not None:
                ready.discard(tenant.tid)

    def _drain(self, tokens: List[Any]) -> None:
        """Worker-side: serve the DRR schedule until every tenant queue is
        empty.  Tokens only wake the worker — one is enqueued per submitted
        batch, so the dispatcher's flush/idle semantics hold (the queues
        are provably empty whenever the token queue is); a token whose
        batch was already co-served (megabatch) or dropped drains as a
        no-op."""
        while True:
            group = self._take_group()
            if group is None:
                return
            self._run_group(*group)

    def _take_group(self):
        """Pick the next fair unit of work under the lock: the DRR winner's
        head batch, plus — when it is megabatch-eligible — every other
        ready tenant's head with the SAME (step, bucket, signature), each
        co-served tenant's deficit charged for its rows.  Each popped
        batch's ``queue_wait`` span ends here, and the selection window is
        recorded as a ``schedule`` child span (the DRR scheduling delay)
        under every member's trace."""
        sched_t0 = _spans._now_ns() if _spans.enabled() else 0
        with self._lock:
            tid = self._drr.select(self._head_cost)
            if tid is None:
                return None
            tenant = self._tenants[tid]
            args, n, probe, (root, qspan) = tenant.queue.popleft()
            _spans.end_span(qspan)
            self._unmark_ready(tenant)
            self._space.notify_all()
            if not (tenant.megabatch and probe is not None):
                members = [(tenant, args, n, probe, root)]
                self._record_schedule(members, sched_t0)
                return ("single", members)
            bucket, _, sig = probe
            members = [(tenant, args, n, probe, root)]
            ready = self._ready.get(tenant.step_token)
            if ready:
                for other_id in list(ready):
                    if len(members) >= self._megabatch_max:
                        break
                    if other_id == tid:
                        continue
                    other = self._tenants[other_id]
                    if other.error is not None or not other.queue:
                        continue
                    o_args, o_n, o_probe, (o_root, o_qspan) = other.queue[0]
                    if o_probe is None or o_probe[0] != bucket or o_probe[2] != sig:
                        continue
                    other.queue.popleft()
                    _spans.end_span(o_qspan, co_served=True)
                    self._unmark_ready(other)
                    self._drr.charge(other_id, o_n)
                    members.append((other, o_args, o_n, o_probe, o_root))
                self._space.notify_all()
            self._record_schedule(members, sched_t0)
            if len(members) == 1:
                return ("single", members)
            return ("mega", members)

    @staticmethod
    def _record_schedule(members: list, sched_t0: int) -> None:
        if not _spans.enabled():
            return
        end = _spans._now_ns()
        start = sched_t0 or end  # tracing flipped on mid-selection: zero-width
        for _tenant, _args, _n, _probe, root in members:
            if root is not None:
                _spans.record_span("schedule", start, end, parent=root)

    def _head_cost(self, tid: str) -> Optional[float]:
        tenant = self._tenants[tid]
        if tenant.error is not None or not tenant.queue:
            return None
        return float(tenant.queue[0][1])

    def _run_group(self, kind: str, members: list) -> None:
        if kind == "mega" and len(members) > 1:
            try:
                # outer attribution for the group's helper ops (padding,
                # dummy init states); the program dispatch inside carries
                # its own signature-bearing context
                tenant0 = members[0][0]
                with attribute_compiles(tenant0.tid, None, token=tenant0.step_token):
                    self._megabatch_dispatch(members)
            except BaseException as err:  # noqa: BLE001 — fenced per member
                # a megabatch failure cannot be attributed to one tenant and
                # nothing was written back — re-run members individually and
                # let each tenant's own crash path fence the actual culprit
                self._megabatch_fallback(members, err)
                return
            self._megabatch_finish(members)
            return
        for tenant, args, _n, _probe, root in members:
            self._run_single(tenant, args, root)

    # ------------------------------------------------------------- single path

    def _run_single(self, tenant: _Tenant, args: Tuple[Any, ...], root: Any = None) -> None:
        try:
            with _telemetry.attribution(tenant.tid):
                # outer attribution (signature None): the small eager helper
                # ops a batch fires outside the per-chunk program contexts
                # (padding, casts) still charge their compiles to THIS tenant
                with attribute_compiles(tenant.tid, None, token=tenant.step_token):
                    with _spans.activate(root):
                        self._apply_batch(tenant, args)
        except BaseException as err:  # noqa: BLE001 — fenced per tenant
            # complete the poisoned batch's trace BEFORE crash handling, so
            # a quarantine's flight dump carries its spans in the ring tail
            _spans.end_span(root, error=repr(err))
            self._handle_tenant_crash(tenant, err)
        else:
            _spans.end_span(root, batches=tenant.batches)
        finally:
            self._finish_one(tenant)

    def _finish_one(self, tenant: _Tenant) -> None:
        over = False
        with self._lock:
            tenant.pending -= 1
            if (
                self._lifecycle is not None
                and tenant.pending == 0
                and tenant.residency == "resident"
            ):
                # the batch that just completed may have pushed the watermark
                # over the budget while this tenant still counted as busy
                # (pending > 0 excludes it from eviction candidacy) — now
                # idle, it is a candidate itself
                over = self._lifecycle._over_budget_locked()
            self._done.notify_all()
        if over:
            self._lifecycle.enforce_budget()

    def _apply_batch(self, tenant: _Tenant, args: Tuple[Any, ...]) -> None:
        """Apply ONE batch to one tenant (journal, transition, counters,
        cadences) — the evaluator's ``_apply_one``, scoped to a tenant."""
        if tenant.crash_policy == "restore":
            tenant.journal.append(args)
        if tenant.bucketer is None:
            with _spans.span("dispatch", mode="eager"):
                tenant.metric.update(*args, **tenant.update_kwargs)
            n_rows = leading_rows(args)
        else:
            n_rows = self._bucketed_update(tenant, args)
        self._count_applied(tenant, args, n_rows)

    def _count_applied(self, tenant: _Tenant, args: Tuple[Any, ...], n_rows: int) -> None:
        with self._lock:
            tenant.batches += 1
            tenant.items += n_rows
            tenant.last_dispatch = time.monotonic()
            batches = tenant.batches
        if self._lifecycle is not None:
            # refresh the tenant's resident-byte count and evict LRU idle
            # tenants if this batch pushed the watermark over the budget
            # (worker-side — never in a submit path)
            self._lifecycle.after_batch(tenant)
        if (
            tenant.compute_every
            and batches - tenant.last_compute_at >= tenant.compute_every
        ):
            self._refresh_latest(tenant)
        if (
            tenant.snapshot_every
            and tenant.snapshots is not None
            and batches % tenant.snapshot_every == 0
        ):
            self._auto_snapshot(tenant)

    def _auto_snapshot(self, tenant: _Tenant) -> None:
        """The worker-side snapshot cadence serializes OUTSIDE the service
        lock: the worker is the only thread that mutates (or donates) this
        tenant's state and journal, so a reference captured under the lock
        stays valid for the whole file write — and one tenant's disk write
        never sits in every other tenant's submit path (the 1000-stream
        soak's p99 gate).  The user-facing :meth:`snapshot` keeps the full
        lock instead: it must exclude concurrent worker donation, which the
        worker itself never has to."""
        with self._lock:
            if tenant.snapshots.last_step == tenant.batches:
                # a crash-restore replay re-fires the cadence at an
                # already-saved position: the state is identical by the
                # determinism contract — reuse, like the evaluator does
                return
            payload: Any = (
                tenant.state if tenant.bucketer is not None
                else tenant.metric.snapshot_state()
            )
            meta = {
                "batches": tenant.batches,
                "items": tenant.items,
                "metric": type(tenant.metric).__name__,
                "mode": "bucketed" if tenant.bucketer is not None else "eager",
                "degraded": tenant.degraded,
                "tenant": tenant.tid,
            }
            batches = tenant.batches
        tenant.snapshots.save(
            batches, payload, meta=meta, guard_non_finite=tenant.guard_non_finite
        )
        # worker-side: nothing can be appended meanwhile, but the covered-
        # prefix trim is the one correct formula on both paths
        with self._lock:
            self._trim_journal(tenant)

    def _bucketed_update(self, tenant: _Tenant, args: Tuple[Any, ...]) -> int:
        with _spans.span("plan"):
            n, chunks = plan_bucketed_update(tenant.bucketer, args)
        # the device tenant scope names this tenant as the owner of any
        # program profile the dispatches register (no-op with profiling off)
        with _device.tenant_scope(tenant.tid):
            return self._run_chunks(tenant, chunks, n)

    def _run_chunks(self, tenant: _Tenant, chunks: Any, n: int) -> int:
        for chunk in chunks:
            if chunk[0] == "scalar":
                _, cargs, sig = chunk
                new_sig = self._observe(tenant, sig)
                with attribute_compiles(tenant.tid, sig, token=tenant.step_token):
                    self._apply_step(
                        tenant, new_sig, lambda s, a=cargs: tenant.step.update(s, *a)
                    )
                continue
            _, padded, bucket, size, sig = chunk
            new_sig = self._observe(tenant, sig)
            n_valid = jnp.asarray(size, jnp.int32)
            with attribute_compiles(tenant.tid, sig, token=tenant.step_token):
                self._apply_step(
                    tenant,
                    new_sig,
                    lambda s, p=padded, b=bucket, nv=n_valid: tenant.step.masked_update(s, p, nv, b),
                )
        return n

    def _observe(self, tenant: _Tenant, sig: Any) -> bool:
        """One service-WIDE signature observation: namespaced by the shared
        step's identity, so K tenants on one step count ONE compile."""
        with self._lock:
            return self._signatures.observe((tenant.step_token, sig))

    def _apply_step(self, tenant: _Tenant, new_sig: bool, run: Callable[[Any], Any]) -> None:
        """The evaluator's donation discipline, per tenant: a donating
        dispatch deletes the input buffers, so it holds the lock (a
        concurrent snapshot()/compute() must never see a state
        mid-donation); cold signatures pre-compile OUTSIDE the lock on a
        throwaway copy so ``latest_result``/``stats`` never block on XLA."""
        timed = _instruments.enabled()
        probed = tenant.step.health_probe
        if not tenant.step.donate:
            t0 = time.perf_counter() if timed else 0.0
            with _spans.span("dispatch", cold=new_sig):
                new_state = run(tenant.state)
            if timed:
                _DISPATCH_HIST.observe((time.perf_counter() - t0) * 1e3, tenant.tid)
            with self._lock:
                with _spans.span("write_back"):
                    if probed:
                        # probed programs return (state, on-device health)
                        tenant.state, tenant.device_health = new_state
                    else:
                        tenant.state = new_state
            return
        if new_sig:
            with _spans.span("compile"):
                run(jax.tree_util.tree_map(lambda leaf: leaf.copy(), tenant.state))
        with self._lock:
            t0 = time.perf_counter() if timed else 0.0
            with _spans.span("dispatch", cold=new_sig):
                new_state = run(tenant.state)
            if timed:
                _DISPATCH_HIST.observe((time.perf_counter() - t0) * 1e3, tenant.tid)
            with _spans.span("write_back"):
                if probed:
                    tenant.state, tenant.device_health = new_state
                else:
                    tenant.state = new_state

    # ---------------------------------------------------------- megabatch path

    def _megabatch_dispatch(self, members: list) -> None:
        """Drive K tenants' same-signature head batches through ONE vmapped
        device program; unstacked states write back under the lock.  May
        raise ONLY with no state written back (the caller then falls back
        per member); after a successful return, every member's state is the
        stepped one and only :meth:`_megabatch_finish` may run."""
        tenant0 = members[0][0]
        step = tenant0.step
        bucket, _, sig = members[0][3]
        k = len(members)
        # pad the group to a power of two with fresh-state dummies so the
        # K-specialization universe stays logarithmic in the tenant count
        k_padded = 1
        while k_padded < k:
            k_padded *= 2
        padded_list, n_list = [], []
        for _tenant, args, n, _probe, _root in members:
            # pad to the GROUP's bucket (from the member's own signature
            # probe — signature equality guarantees identical padded
            # shapes), never through another tenant's bucket edges: two
            # same-config tenants may bucket the same row count differently
            padded_list.append(pad_args_to(args, n, bucket))
            n_list.append(n)
        for _ in range(k_padded - k):
            padded_list.append(padded_list[0])  # args are not donated: alias ok
            n_list.append(n_list[0])
        mega_sig = (tenant0.step_token, ("mega", bucket, k_padded, sig))
        with self._lock:
            new_sig = self._signatures.observe(mega_sig)
        # group programs attribute to the DRR winner, like the compile does
        mega_scope = _device.tenant_scope(tenant0.tid)
        # the group program is attributed to the DRR winner that formed the
        # group (one label, bounded cardinality); attrs carry the group size
        attrib = attribute_compiles(tenant0.tid, mega_sig[1], token=tenant0.step_token)
        if new_sig:
            # cold compile outside the lock on throwaway copies (+ fresh
            # dummies — a donating program consumes every state-list leaf,
            # and even a non-donating one must not trace + XLA-compile
            # inside the lock, where it would stall every tenant's submit)
            states = [
                jax.tree_util.tree_map(lambda leaf: leaf.copy(), m[0].state)
                for m in members
            ] + [step.init_state() for _ in range(k_padded - k)]
            with mega_scope, attrib:
                step.megabatch_update(states, padded_list, n_list, bucket)
        dummies = [step.init_state() for _ in range(k_padded - k)]
        timed_spans = _spans.enabled()
        with self._lock:
            states = [m[0].state for m in members] + dummies
            t0 = _spans._now_ns() if timed_spans else 0
            with mega_scope, attrib:
                outs = step.megabatch_update(states, padded_list, n_list, bucket)
            t1 = _spans._now_ns() if timed_spans else 0
            for i, (tenant, args, n, _probe, root) in enumerate(members):
                tenant.state = outs[i]
                tenant.megabatched += 1
                if tenant.crash_policy == "restore":
                    tenant.journal.append(args)
            t2 = _spans._now_ns() if timed_spans else 0
            if timed_spans:
                # the shared device program + the GROUP's write-back loop,
                # recorded under every co-served member's own trace with the
                # SAME window (a per-iteration end time would charge member
                # i for members 0..i-1's bookkeeping)
                for _tenant, _args, _n, _probe, root in members:
                    if root is not None:
                        _spans.record_span(
                            "dispatch", t0, t1, parent=root, megabatch=True, tenants=k
                        )
                        _spans.record_span(
                            "write_back", t1, t2, parent=root, megabatch=True
                        )
            self._megabatch_steps += 1
            self._megabatch_tenants += k
            self._mega_group_meta = (k, k_padded, bucket)

    def _megabatch_finish(self, members: list) -> None:
        """Post-write-back tail: the event record and each member's counter
        and cadence bookkeeping.  NOTHING here may escape to the caller — a
        re-raise would trigger the individual fallback and double-apply the
        already-written states."""
        k, k_padded, bucket = self._mega_group_meta
        try:
            _telemetry.record_event(
                self, "megabatch_step", tenants=k, padded_to=k_padded, bucket=bucket
            )
        except Exception:  # noqa: BLE001 — a raising user sink must not
            pass  # cascade into re-applied batches; the step already ran
        for tenant, args, n, _probe, root in members:
            try:
                with _telemetry.attribution(tenant.tid):
                    self._count_applied(tenant, args, n)
            except BaseException as err:  # noqa: BLE001 — cadence failure
                # the batch IS applied and journaled; a failing cadence
                # (snapshot guard, compute refresh) takes the tenant's own
                # crash path like the single-tenant route would
                _spans.end_span(root, error=repr(err))
                self._handle_tenant_crash(tenant, err)
            else:
                _spans.end_span(root, batches=tenant.batches, megabatch=True)
            finally:
                self._finish_one(tenant)

    def _megabatch_fallback(self, members: list, err: BaseException) -> None:
        """A failed group dispatch re-runs members individually — but a
        raise DURING a donating execution may already have consumed some
        members' state buffers.  A member whose state is intact re-runs in
        place; one whose buffers were deleted cannot, and takes its crash
        path instead (restore + journal replay rebuilds the state — the
        crashed batch is journaled first, exactly as the single path would
        have), so co-batched tenants are never quarantined for a neighbor's
        poison when their own buffers survived."""
        for tenant, args, _n, _probe, root in members:
            if _state_alive(tenant.state):
                self._run_single(tenant, args, root)
                continue
            try:
                if tenant.crash_policy == "restore":
                    tenant.journal.append(args)
                _spans.end_span(root, error=repr(err))
                with _telemetry.attribution(tenant.tid):
                    self._handle_tenant_crash(tenant, err)
            finally:
                self._finish_one(tenant)

    # ------------------------------------------------------------ self-healing

    def _handle_tenant_crash(self, tenant: _Tenant, err: BaseException) -> None:
        """Per-tenant crash fence (worker thread): restore + replay under a
        consecutive-crash budget when the tenant opted into
        ``crash_policy="restore"``, quarantine otherwise — the service
        itself NEVER poisons on tenant work."""
        if tenant.crash_policy != "restore":
            self._quarantine(tenant, err)
            return
        pending = list(tenant.journal)
        # the budget bounds CONSECUTIVE crashes at the SAME stream position
        # within this incident (the evaluator's semantics); attempts stay
        # local so a successful later incident starts its own budget
        attempts = 0
        last_pos = -1
        while True:
            with self._lock:
                pos = tenant.batches
                tenant.crashes += 1
                crashes = tenant.crashes
            attempts = attempts + 1 if pos <= last_pos else 1
            last_pos = max(last_pos, pos)
            with _telemetry.attribution(tenant.tid):
                _telemetry.record_event(
                    self, "runtime_crash", error=repr(err), crashes=crashes,
                    attempt=attempts,
                )
            if attempts > tenant.max_restores:
                self._quarantine(
                    tenant,
                    CrashLoopError(
                        f"Tenant {tenant.tid!r} crashed {attempts} "
                        f"consecutive time(s) without progress; crash-loop budget "
                        f"(max_restores={tenant.max_restores}) is spent. Last crash: "
                        f"{type(err).__name__}: {err}"
                    ),
                )
                return
            idx = -1
            try:
                # span-less replay: these batches' traces ended at the crash
                with _spans.suppress():
                    self._restore_for_crash(tenant)
                    idx = 0
                    while idx < len(pending):
                        self._apply_batch(tenant, pending[idx])
                        idx += 1
            except TPUMetricsUserError as user_err:
                # config/snapshot-level problems are not crash-loopable
                self._quarantine(tenant, user_err)
                return
            except BaseException as replay_err:  # noqa: BLE001 — bounded above
                err = replay_err
                if idx >= 0:
                    pending = list(tenant.journal) + pending[idx + 1 :]
                continue
            with self._lock:
                tenant.restores += 1
                restores = tenant.restores
            with _telemetry.attribution(tenant.tid):
                _telemetry.record_event(
                    self, "runtime_restore", restores=restores, replayed=len(pending)
                )
            return

    def _restore_for_crash(self, tenant: _Tenant) -> None:
        got = self._load_latest_snapshot(tenant)
        with self._lock:
            expected = tenant.journal_base
            restored = self._adopt_snapshot_locked(tenant, got)
            if restored != expected:
                raise _snapshot.SnapshotError(
                    f"Tenant {tenant.tid!r} crash restore landed on stream position "
                    f"{restored} but the replay journal starts at {expected} (latest "
                    "snapshot lost or corrupt?): the journal cannot bridge the gap."
                )

    def _quarantine(self, tenant: _Tenant, err: BaseException) -> None:
        """Fence one tenant: record the cause, discard its queue, release
        its producers and waiters.  Every other tenant is untouched — this
        is the isolation contract the tests pin bit-identically."""
        with self._lock:
            tenant.error = err
            discarded = len(tenant.queue)
            for _args, _n, _probe, (d_root, d_qspan) in tenant.queue:
                _spans.end_span(d_qspan, quarantined=True)
                _spans.end_span(d_root, error="discarded (tenant quarantined)")
            tenant.queue.clear()
            # discarded queued batches release their pending counts here; the
            # in-flight batch that crashed is finished by its own _finish_one
            tenant.pending -= discarded
            self._unmark_ready(tenant)
            self._quarantines += 1
            _TENANTS_GAUGE.set(len(self._tenants) - self._quarantines, self._label)
            self._space.notify_all()
            self._done.notify_all()
        with _telemetry.attribution(tenant.tid):
            _telemetry.record_event(
                self, "tenant_quarantined", error=repr(err), discarded=discarded
            )
        # the quarantine fences this stream for good: dump the flight ring
        # (when a recorder is installed) — its tail holds the poisoned
        # batch's spans and the crash/quarantine events just recorded — and
        # name the file in every TenantQuarantinedError this tenant raises
        tenant.flight_path = _export.flight_dump(
            "tenant_quarantined", err, tenant=tenant.tid, discarded=discarded
        )

    # ------------------------------------------------------------ cadences

    def _refresh_latest(self, tenant: _Tenant) -> None:
        from tpumetrics.monitoring.drift import stream_scope

        with self._lock:
            state = tenant.state
            batches, items = tenant.batches, tenant.items
        if tenant.bucketer is None:
            with stream_scope(tenant.tid):
                value = tenant.metric.compute()
            tenant.metric._computed = None  # the stream moves on
            degraded = bool(getattr(tenant.metric, "degraded", False))
        else:
            with attribute_compiles(tenant.tid, None, token=tenant.step_token), stream_scope(
                tenant.tid
            ):
                value = tenant.step._metric.functional_compute(state)
            with self._lock:
                degraded = tenant.degraded
        with self._lock:
            if tenant.bucketer is None:
                tenant.degraded = degraded
            tenant.latest = {
                "value": value, "batches": batches, "items": items, "degraded": degraded,
            }
            tenant.last_compute_at = batches
