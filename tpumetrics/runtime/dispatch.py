"""Async ingestion: a bounded queue + worker thread feeding metric updates.

The serving-path contract of the runtime (see ``docs/runtime.md``): request
threads call :meth:`AsyncDispatcher.submit` — O(enqueue), never a device
step — and a single worker thread drains **micro-batches** into the drain
callback (a :class:`~tpumetrics.runtime.evaluator.StreamingEvaluator` step,
or any callable taking a list of items).  JAX dispatch, padding, and the
jitted update therefore never block the request path; the queue is the only
coupling, and it is bounded.

Backpressure policy when the queue is full (``max_queue`` items):

- ``"block"``   — ``submit`` waits until the worker frees a slot (lossless;
  the request path absorbs the latency).
- ``"drop_oldest"`` — evict the oldest queued item and enqueue the new one
  (bounded-staleness lossy ingestion; drops are counted and reported).
- ``"error"``   — raise :class:`QueueFullError` immediately (the caller owns
  the retry/shed decision).

Observability: drops and drain cycles report into the telemetry ledger
(:mod:`tpumetrics.telemetry`) as payload-free events — ``runtime_drop``
per eviction burst and ``runtime_drain`` per worker cycle (carrying queue
depth and batch size) — and :meth:`AsyncDispatcher.stats` exposes cheap
process-local counters (enqueued / drained / dropped / max depth) without
requiring a ledger.  Items submitted with an attribution ``tag`` (the
multi-tenant service passes the tenant id) are additionally counted per
tag — ``stats()["by_tag"]`` splits enqueued/drained/dropped so a
``runtime_drop`` burst can be blamed on the tenant that overflowed, not
just observed globally — and the ``runtime_drop`` ledger event carries the
evicted item's tag.

Span tracing (:mod:`tpumetrics.telemetry.spans`): a submit that carries a
``trace_ctx`` gets a ``queue_wait`` child span — started at enqueue, ended
when the worker pops the item — so a batch's trace shows exactly how long
it sat in this queue; the worker refreshes the
``tpumetrics_queue_depth{dispatcher=…}`` gauge each drain cycle.  Both are
inert (``None`` span, flag-test gauge) when observability is off.

A worker-side exception poisons the dispatcher: it is captured, the worker
stops, and the exception re-raises (wrapped, original as ``__cause__``) from
the next ``submit``/``flush``/``close`` so ingestion errors cannot vanish
silently on a daemon thread.  If a flight recorder is installed
(:func:`tpumetrics.telemetry.export.enable_flight_recorder`), the poison
path dumps the recent-activity ring to a JSONL file first and every later
``DispatcherClosedError`` names the dump path.

Self-healing (``tpumetrics.resilience``): an optional ``crash_handler`` is
consulted before poisoning.  It runs on the worker thread with the exception
and the micro-batch that was being drained; returning ``True`` means the
handler fully recovered (including applying or discarding the batch) and the
worker keeps draining — a ``runtime_restart`` ledger event and the
``restarts`` counter record it.  Returning ``False`` — or raising (e.g. a
:class:`~tpumetrics.runtime.evaluator.CrashLoopError` once the restore
budget is exhausted) — poisons the dispatcher as before, with the handler's
exception taking over as the poison cause when it raised one.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from tpumetrics.telemetry import export as _export
from tpumetrics.telemetry import instruments as _instruments
from tpumetrics.telemetry import ledger as _telemetry
from tpumetrics.telemetry import spans as _spans
from tpumetrics.utils.exceptions import TPUMetricsUserError

_POLICIES = ("block", "drop_oldest", "error")

# queue depth per dispatcher, refreshed each worker cycle (cheap: one gauge
# store per drain, not per item)
_DEPTH_GAUGE = _instruments.gauge(
    _instruments.QUEUE_DEPTH, help="dispatch queue depth", labels=("dispatcher",)
)


def _end_root(trace_ctx: Any, **attrs: Any) -> None:
    """Complete a queued batch's ROOT span when the batch will never be
    drained (evicted, discarded by poison/close) — an orphaned open root
    would leave its already-recorded queue_wait child parentless in the
    ring.  Only Span handles can be ended; a bare (trace_id, span_id)
    context belongs to its submitter."""
    if isinstance(trace_ctx, _spans.Span):
        _spans.end_span(trace_ctx, **attrs)


class QueueFullError(TPUMetricsUserError):
    """Raised by ``submit`` under the ``"error"`` backpressure policy."""


class DispatcherClosedError(TPUMetricsUserError):
    """Raised when submitting to a closed (or poisoned) dispatcher."""


class AsyncDispatcher:
    """Bounded async queue draining micro-batches into a callback off-thread.

    Args:
        drain_fn: called from the worker thread with a non-empty ``list`` of
            queued items (at most ``max_batch`` per call).
        max_queue: queue capacity in items (> 0).
        policy: backpressure policy — ``"block"`` | ``"drop_oldest"`` |
            ``"error"`` (module docstring).
        max_batch: micro-batch ceiling per drain call; ``None`` drains
            everything currently queued in one call.
        name: attribution tag for telemetry events (e.g. the evaluator's
            metric class name).
        instrument_label: label for the queue-depth gauge (defaults to
            ``name``).  Pass a process-unique label (the evaluator's stream
            label) when several same-named dispatchers may coexist — gauges
            are last-write-wins per label.
        crash_handler: optional ``(exc, batch) -> bool`` recovery hook run on
            the worker thread when ``drain_fn`` raises (module docstring);
            ``True`` = recovered, keep draining; ``False``/raise = poison.

    Thread safety: ``submit`` may be called from many threads; ``flush`` /
    ``close`` from any thread.  ``drain_fn`` only ever runs on the single
    worker thread, so a non-thread-safe consumer (a Metric) is safe.
    """

    def __init__(
        self,
        drain_fn: Callable[[List[Any]], None],
        *,
        max_queue: int = 64,
        policy: str = "block",
        max_batch: Optional[int] = None,
        name: str = "",
        instrument_label: Optional[str] = None,
        crash_handler: Optional[Callable[[BaseException, List[Any]], bool]] = None,
    ) -> None:
        if policy not in _POLICIES:
            raise ValueError(f"Unknown backpressure policy {policy!r}; expected one of {_POLICIES}")
        if int(max_queue) <= 0:
            raise ValueError(f"max_queue must be positive, got {max_queue}")
        if max_batch is not None and int(max_batch) <= 0:
            raise ValueError(f"max_batch must be positive or None, got {max_batch}")
        self._drain_fn = drain_fn
        self._max_queue = int(max_queue)
        self._policy = policy
        self._max_batch = int(max_batch) if max_batch is not None else None
        self._name = name or type(self).__name__
        self._instr_label = instrument_label or self._name
        self._crash_handler = crash_handler

        self._q: deque = deque()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self._idle = threading.Condition(self._lock)  # queue empty AND worker not draining
        self._draining = False
        self._closed = False
        self._error: Optional[BaseException] = None
        self._flight_path: Optional[str] = None  # flight dump of the poison

        # counters (read under lock by stats())
        self._enqueued = 0
        self._drained_items = 0
        self._drain_cycles = 0
        self._dropped = 0
        self._max_depth = 0
        self._restarts = 0
        # per-attribution-tag split of the three blameable counters; only
        # tagged submits pay for it (the single-stream evaluator passes no
        # tag and keeps the zero-cost path)
        self._by_tag: Dict[str, Dict[str, int]] = {}

        self._worker = threading.Thread(
            target=self._run, name=f"tpumetrics-dispatch[{self._name}]", daemon=True
        )
        self._worker.start()

    # ------------------------------------------------------------- producers

    def _tag_counters(self, tag: str) -> Dict[str, int]:
        got = self._by_tag.get(tag)
        if got is None:
            got = self._by_tag[tag] = {"enqueued": 0, "drained": 0, "dropped": 0}
        return got

    def submit(
        self,
        item: Any,
        timeout: Optional[float] = None,
        tag: Optional[str] = None,
        trace_ctx: Any = None,
    ) -> None:
        """Enqueue one item, applying the backpressure policy when full.

        ``tag`` attributes the item for the per-tag counter split (and for
        the ``runtime_drop`` event should it later be evicted).
        ``trace_ctx`` (a :class:`~tpumetrics.telemetry.spans.Span` or
        ``(trace_id, span_id)``) parents a ``queue_wait`` child span under
        the submitter's batch trace, started here and ended when the worker
        pops the item — the batch's time in THIS queue."""
        qspan = (
            _spans.start_span("queue_wait", parent=trace_ctx)
            if trace_ctx is not None
            else None
        )
        try:
            self._submit_locked(item, timeout, tag, trace_ctx, qspan)
        except BaseException as err:
            # never enqueued: complete the wait span (the submitter owns —
            # and on failure ends — the root itself)
            _spans.end_span(qspan, error=repr(err))
            raise

    def _submit_locked(
        self,
        item: Any,
        timeout: Optional[float],
        tag: Optional[str],
        trace_ctx: Any,
        qspan: Any,
    ) -> None:
        with self._lock:
            self._check_alive()
            if len(self._q) >= self._max_queue:
                if self._policy == "error":
                    raise QueueFullError(
                        f"Dispatch queue full ({self._max_queue} items) under policy='error'. "
                        "HINT: raise max_queue, slow the producer, or use 'block'/'drop_oldest'."
                    )
                if self._policy == "drop_oldest":
                    _, dropped_tag, dropped_span, dropped_ctx = self._q.popleft()
                    _spans.end_span(dropped_span, dropped=True)
                    _end_root(dropped_ctx, error="dropped (drop_oldest)")
                    self._dropped += 1
                    if dropped_tag is not None:
                        self._tag_counters(dropped_tag)["dropped"] += 1
                    # the event blames the EVICTED item's tenant — the drop is
                    # charged to whoever overflowed the queue, per satellite
                    with _telemetry.attribution(dropped_tag):
                        _telemetry.record_event(self, "runtime_drop", dropped_total=self._dropped)
                else:  # block
                    while len(self._q) >= self._max_queue:
                        self._check_alive()
                        if not self._not_full.wait(timeout=timeout):
                            raise QueueFullError(
                                f"Timed out after {timeout}s waiting for queue space "
                                f"({self._max_queue} items, policy='block')."
                            )
            self._q.append((item, tag, qspan, trace_ctx))
            self._enqueued += 1
            if tag is not None:
                self._tag_counters(tag)["enqueued"] += 1
            self._max_depth = max(self._max_depth, len(self._q))
            self._not_empty.notify()

    def flush(self, timeout: Optional[float] = None) -> None:
        """Block until every queued item has been drained (worker idle)."""
        with self._lock:
            while (self._q or self._draining) and self._error is None:
                if not self._idle.wait(timeout=timeout):
                    raise TimeoutError(
                        f"Dispatch queue did not drain within {timeout}s "
                        f"(depth={len(self._q)}, draining={self._draining})."
                    )
            self._check_alive(allow_closed=True)

    def close(self, drain: bool = True, timeout: Optional[float] = None) -> None:
        """Stop the worker; by default drain the queue first.  Idempotent."""
        with self._lock:
            if self._closed and not self._worker.is_alive():
                self._check_alive(allow_closed=True)
                return
            if not drain:
                for _, _, qspan, ctx in self._q:
                    _spans.end_span(qspan, discarded=True)
                    _end_root(ctx, error="discarded (close(drain=False))")
                self._q.clear()
            self._closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()
        self._worker.join(timeout=timeout)
        if self._worker.is_alive():
            raise TimeoutError(f"Dispatch worker did not stop within {timeout}s.")
        with self._lock:
            self._check_alive(allow_closed=True)

    def __enter__(self) -> "AsyncDispatcher":
        return self

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> None:
        # on an exception in the with-body, don't mask it with a drain error
        try:
            self.close(drain=exc_type is None)
        except Exception:
            if exc_type is None:
                raise

    # --------------------------------------------------------------- observe

    def stats(self) -> Dict[str, int]:
        """Cheap process-local counters (no ledger required)."""
        with self._lock:
            return {
                "depth": len(self._q),
                "max_depth": self._max_depth,
                "enqueued": self._enqueued,
                "drained_items": self._drained_items,
                "drain_cycles": self._drain_cycles,
                "dropped": self._dropped,
                "restarts": self._restarts,
                "by_tag": {tag: dict(c) for tag, c in self._by_tag.items()},
            }

    @property
    def closed(self) -> bool:
        return self._closed

    # ---------------------------------------------------------------- worker

    def _check_alive(self, allow_closed: bool = False) -> None:
        if self._error is not None:
            flight = f" Flight record: {self._flight_path}" if self._flight_path else ""
            raise DispatcherClosedError(
                f"Dispatch worker died: {type(self._error).__name__}: {self._error}.{flight}"
            ) from self._error
        if self._closed and not allow_closed:
            raise DispatcherClosedError("Dispatcher is closed.")

    def _run(self) -> None:
        while True:
            with self._lock:
                while not self._q and not self._closed:
                    self._not_empty.wait()
                if not self._q and self._closed:
                    self._idle.notify_all()
                    return
                n = len(self._q) if self._max_batch is None else min(len(self._q), self._max_batch)
                pairs = [self._q.popleft() for _ in range(n)]
                batch = [item for item, _, _, _ in pairs]
                tags = [t for _, t, _, _ in pairs if t is not None]
                depth_after = len(self._q)
                self._draining = True
                self._not_full.notify_all()
            _DEPTH_GAUGE.set(depth_after, self._instr_label)
            for _, _, qspan, _ in pairs:
                _spans.end_span(qspan, depth_after=depth_after)
            try:
                self._drain_fn(batch)
            except BaseException as err:  # noqa: BLE001 — poison, don't lose it
                recovered = False
                if self._crash_handler is not None:
                    try:
                        recovered = bool(self._crash_handler(err, batch))
                    except BaseException as handler_err:  # noqa: BLE001
                        err = handler_err  # e.g. CrashLoopError: budget spent
                if recovered:
                    with self._lock:
                        self._restarts += 1
                        self._drained_items += n  # the handler applied them
                        for t in tags:
                            self._tag_counters(t)["drained"] += 1
                        self._drain_cycles += 1
                        self._draining = False
                        _telemetry.record_event(
                            self, "runtime_restart", items=n, restarts=self._restarts
                        )
                        self._not_full.notify_all()
                        if not self._q:
                            self._idle.notify_all()
                    continue
                # the dispatcher is about to die un-drainable: dump the
                # flight ring (when a recorder is installed) so the last
                # spans/events before the poison are on disk, and name the
                # file in every later DispatcherClosedError.  An error that
                # already carries a dump (CrashLoopError: the crash handler
                # dumped at budget exhaustion) is the SAME incident — reuse
                # its file instead of writing a near-duplicate
                flight_path = getattr(err, "_tpumetrics_flight_path", None)
                if flight_path is None:
                    flight_path = _export.flight_dump(
                        "dispatcher_poisoned", err, dispatcher=self._name
                    )
                with self._lock:
                    self._error = err
                    self._flight_path = flight_path
                    self._draining = False
                    for _, _, qspan, ctx in self._q:
                        _spans.end_span(qspan, poisoned=True)
                        _end_root(ctx, error="discarded (dispatcher poisoned)")
                    self._q.clear()
                    self._not_full.notify_all()
                    self._idle.notify_all()
                return
            with self._lock:
                self._drained_items += n
                for t in tags:
                    self._tag_counters(t)["drained"] += 1
                self._drain_cycles += 1
                self._draining = False
                _telemetry.record_event(
                    self, "runtime_drain", items=n, depth=depth_after,
                    drained_total=self._drained_items,
                )
                if not self._q:
                    self._idle.notify_all()
